#!/usr/bin/env python3
"""Scaling benchmark: VGG16 DP throughput per chip across mesh widths.

The BASELINE.json headline includes "scaling efficiency 8->256 chips"; this
script measures it on whatever devices the session has: for each power-of-two
width w <= n_devices it trains VGG16 (gradient_allreduce) on a w-device DP
mesh and reports img/s/chip, then emits the efficiency of the widest mesh
relative to width 1 as the authoritative last line.  On the current
single-chip tunnel it degenerates to a width-1 measurement (efficiency 1.0);
on a pod slice it produces the scaling curve.

Emission protocol shared with bench.py (`_bench_common`).  CPU smoke:
``BENCH_FORCE_CPU=1 BENCH_BATCH_PER_CHIP=4 BENCH_IMAGE_SIZE=64
XLA_FLAGS=--xla_force_host_platform_device_count=8 python bench_scaling.py``.

Dead-tunnel salvage: on the ``accepted-then-dropped`` relay signature the
harness emits this metric's modeled 1→8 efficiency from the committed
BENCH_MODELED.json (``"mode": "modeled"`` rows, provenance tagged) before
the CPU-sim fallback; the structured error record still lands last.
"""

import os
import time

from _bench_common import BenchHarness

HARNESS = BenchHarness("vgg16_dp_scaling_efficiency", "ratio")

import jax
import jax.numpy as jnp
import numpy as np
import optax


def measure(width, params, model_cfg, deadline, max_iters=8):
    import bagua_tpu
    from bagua_tpu.algorithms import build_algorithm
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.models.vgg import vgg_loss_fn

    model, per_chip_batch, image_size = model_cfg
    group = bagua_tpu.init_process_group(devices=jax.devices()[:width])
    ddp = DistributedDataParallel(
        vgg_loss_fn(model), optax.sgd(0.01, momentum=0.9),
        build_algorithm("gradient_allreduce"), process_group=group,
    )
    state = ddp.init(params)
    rng = np.random.RandomState(0)
    gb = per_chip_batch * width
    x = jnp.asarray(rng.rand(gb, image_size, image_size, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, size=(gb,)).astype(np.int32))
    state, losses = ddp.train_step(state, (x, y))  # compile + settle
    jax.block_until_ready(losses)
    # second warmup step compiles the steady-state executable (committed
    # sharding + XLA layouts signature) — see the bench.py warmup note
    state, losses = ddp.train_step(state, (x, y))
    jax.block_until_ready(losses)
    n_iters = 0
    t0 = time.perf_counter()
    while n_iters < max_iters and (n_iters == 0 or time.perf_counter() < deadline):
        state, losses = ddp.train_step(state, (x, y))
        n_iters += 1
    jax.block_until_ready(losses)
    elapsed = time.perf_counter() - t0
    ddp.shutdown()
    return gb * n_iters / elapsed / width


def main():
    from bagua_tpu.models.vgg import init_vgg16

    deadline = HARNESS.t0 + float(os.environ.get("BENCH_DEADLINE_SEC", "420"))
    n = len(jax.devices())
    HARNESS.note(f"{n} {jax.devices()[0].platform} device(s)")

    per_chip_batch = int(os.environ.get("BENCH_BATCH_PER_CHIP", "32"))
    image_size = int(os.environ.get("BENCH_IMAGE_SIZE", "224"))
    smoke = (per_chip_batch, image_size) != (32, 224)

    model, params = init_vgg16(
        jax.random.PRNGKey(0), image_size=image_size, num_classes=1000,
        compute_dtype=jnp.bfloat16,
    )
    cfg = (model, per_chip_batch, image_size)

    widths = []
    w = 1
    while w <= n:
        widths.append(w)
        w *= 2
    if widths[-1] != n:
        widths.append(n)

    def emit_efficiency(per_chip, provisional):
        widest = max(per_chip)
        eff = per_chip[widest] / per_chip[widths[0]]
        extra = {"widths": {str(k): round(v, 2) for k, v in per_chip.items()}}
        if smoke:
            extra["config"] = "SMOKE (non-reference shapes)"
        HARNESS.emit(round(eff, 4), provisional=provisional, extra=extra)

    per_chip = {}
    for w in widths:
        # A new width costs a fresh compile (~1-2 min cold); don't start one
        # the watchdog would cut short of its efficiency line.
        if w != widths[0] and time.perf_counter() > deadline - 150:
            HARNESS.note(f"skipping width {w}: <150s budget left")
            break
        rate = measure(w, params, cfg, deadline)
        per_chip[w] = rate
        line = {"metric": "vgg16_img_per_sec_per_chip", "unit": "img/s/chip", "width": w}
        if smoke:
            line["config"] = "SMOKE (non-reference shapes)"
        HARNESS.note(f"width {w}: {rate:.2f} img/s/chip")
        HARNESS.emit(rate, provisional=True, extra=line)
        # Keep the last-emitted line an efficiency line at every point: the
        # watchdog may end the process mid-sweep.
        emit_efficiency(per_chip, provisional=True)

    emit_efficiency(per_chip, provisional=False)


if __name__ == "__main__":
    HARNESS.guard(main)
