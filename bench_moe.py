#!/usr/bin/env python3
"""Benchmark: MoE transformer training throughput per chip.

The reference CI gates MoE end-to-end but pins only a final loss
(``/root/reference/.buildkite/scripts/benchmark_master.sh:109-144`` — MNIST,
2 local experts/GPU); it publishes no MoE throughput number.  This bench
puts a *measurable* MoE line on the board (VERDICT r3 next #7): a GPT-small
-shaped encoder whose FFNs are top-2 MoE blocks (8 experts, the reference's
2-local-experts-per-GPU density at ep_size=1 on a single chip), bf16
compute, synthetic LM-style data.

Emission protocol: see ``_bench_common`` (JSON lines, last authoritative).
``vs_baseline`` is null — the reference has no MoE throughput floor; the
committed artifact IS the baseline for future rounds.
"""

import os
import time

from _bench_common import BenchHarness

HARNESS = BenchHarness(
    "moe_samples_per_sec_per_chip", "samples/s/chip",
    recorded_artifact="BENCH_MOE_TPU.json",
)

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

# GPT-small-ish MoE encoder: 8 layers x hidden 512, seq 128, 8 experts top-2.
HIDDEN, LAYERS, SEQ, EXPERTS, TOP_K = 512, 8, 128, 8, 2
VOCAB = 8192


def fused_a2a_row(hidden: int, deadline: float):
    """Fused-collective-matmul row: step time of the ep-sharded MoE block
    with the chunked (overlapped) all-to-all schedule vs the monolithic one,
    over every local device.  Emitted as its own JSON line BEFORE the
    authoritative throughput line (last-line protocol); a single-device
    session skips it — there is no all-to-all to overlap."""
    import json as _json

    from jax.sharding import Mesh, PartitionSpec as P

    from bagua_tpu.parallel.moe import MoE

    devs = jax.devices()
    n_dev = len(devs)
    if n_dev < 2 or time.perf_counter() > deadline - 60.0:
        HARNESS.note("fused-a2a row skipped (single device or out of budget)")
        return
    mesh = Mesh(np.array(devs), ("ep",))
    num_experts = n_dev * max(1, EXPERTS // n_dev)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(512 * n_dev, hidden).astype(np.float32))

    def step_ms(chunks):
        moe = MoE(
            hidden_size=hidden, num_experts=num_experts, k=TOP_K,
            capacity_factor=1.25, ep_size=n_dev, ep_axis="ep",
            a2a_chunks=chunks,
        )
        params = moe.init(jax.random.PRNGKey(0), x[: 512])["params"]
        fn = jax.jit(
            jax.shard_map(
                lambda xx: moe.apply({"params": params}, xx)[0],
                mesh=mesh, in_specs=P("ep", None), out_specs=P("ep", None),
                check_vma=False,
            )
        )
        fn(x).block_until_ready()  # compile outside the timed loop
        iters = 10
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(x)
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters * 1e3

    mono, chunked = step_ms(1), step_ms(4)
    print(_json.dumps({
        "metric": "moe_fused_a2a_step_ms",
        "value": round(chunked, 3),
        "unit": "ms/step (ep-sharded MoE forward)",
        "a2a_chunks": 4,
        "unchunked_ms": round(mono, 3),
        "speedup": round(mono / chunked, 3) if chunked else None,
        "ep_size": n_dev,
        "provisional": True,  # never the authoritative last line
    }), flush=True)


def main():
    import bagua_tpu
    from bagua_tpu.algorithms import build_algorithm
    from bagua_tpu.communication import ALL_AXES
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.parallel.moe import MoE

    deadline = HARNESS.t0 + float(os.environ.get("BENCH_DEADLINE_SEC", "420"))
    HARNESS.note(f"jax ready: {len(jax.devices())} {jax.devices()[0].platform} device(s)")

    group = bagua_tpu.init_process_group()
    n = group.size
    per_chip_batch = int(os.environ.get("BENCH_BATCH_PER_CHIP", "32"))
    hidden = int(os.environ.get("BENCH_MOE_HIDDEN", str(HIDDEN)))
    layers = int(os.environ.get("BENCH_MOE_LAYERS", str(LAYERS)))
    smoke = (per_chip_batch, hidden, layers) != (32, HIDDEN, LAYERS)
    compute_dtype = jnp.bfloat16

    class Block(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.LayerNorm(dtype=compute_dtype)(x)
            att = nn.SelfAttention(
                num_heads=8, dtype=compute_dtype, deterministic=True
            )(h)
            x = x + att
            h = nn.LayerNorm(dtype=compute_dtype)(x)
            # ep_size=1: all experts local (single-chip bench); the layer is
            # the same one the 8-dev dryrun shards with ep_size=n.
            # expert compute dtype follows the (bf16) activations
            moe_out, l_aux = MoE(
                hidden_size=hidden, num_experts=EXPERTS, k=TOP_K,
                capacity_factor=1.25, ep_size=1, ep_axis=ALL_AXES,
            )(h)
            return x + moe_out, l_aux

    class Model(nn.Module):
        @nn.compact
        def __call__(self, tokens):
            x = nn.Embed(VOCAB, hidden, dtype=compute_dtype)(tokens)
            aux = 0.0
            for _ in range(layers):
                x, l_aux = Block()(x)
                aux = aux + l_aux
            logits = nn.Dense(VOCAB, dtype=compute_dtype)(nn.LayerNorm(dtype=compute_dtype)(x))
            return logits.astype(jnp.float32), aux / layers

    model = Model()

    def loss_fn(params, batch):
        tokens, targets = batch
        logits, l_aux = model.apply({"params": params}, tokens)
        ce = -jnp.mean(
            jnp.take_along_axis(
                jax.nn.log_softmax(logits), targets[..., None], axis=-1
            )
        )
        return ce + 0.01 * l_aux

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, VOCAB, (per_chip_batch * n, SEQ)), jnp.int32)
    targets = jnp.asarray(rng.randint(0, VOCAB, (per_chip_batch * n, SEQ)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:1])["params"]
    HARNESS.note("model initialized")

    ddp = DistributedDataParallel(
        loss_fn, optax.adam(1e-3), build_algorithm("gradient_allreduce"),
        process_group=group,
    )
    try:
        state = ddp.init(params)
        for _ in range(2):  # two warmups: fresh-array + steady-state compiles
            state, losses = ddp.train_step(state, (tokens, targets))
            jax.block_until_ready(losses)
        HARNESS.note("compile + warmup done (2 steps)")
        ddp.host_overhead_snapshot(reset=True)  # timed window only
        t0 = time.perf_counter()
        n_iters = 0
        while n_iters < 12 and (n_iters < 2 or time.perf_counter() < deadline):
            state, losses = ddp.train_step(state, (tokens, targets))
            n_iters += 1
        jax.block_until_ready(losses)
        elapsed = time.perf_counter() - t0
        HARNESS.note(f"{n_iters} steps in {elapsed:.2f}s; "
                     f"host overhead {ddp.host_overhead_snapshot()}")
        value = tokens.shape[0] * n_iters / elapsed / n
        extra = {
            "config": f"hidden{hidden} L{layers} seq{SEQ} {EXPERTS}experts top{TOP_K}",
            "vs_baseline": None,
        }
        if smoke:
            extra["config"] = "SMOKE " + extra["config"]
        fused_a2a_row(hidden, deadline)
        HARNESS.emit(value, extra=extra)
    finally:
        ddp.shutdown()


if __name__ == "__main__":
    HARNESS.guard(main)
