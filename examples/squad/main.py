#!/usr/bin/env python3
"""SQuAD BERT finetune example (analog of the reference's
``examples/squad/main.py``): BERT + span-prediction head, ByteGrad
compression (the BASELINE.json config "BERT-Large SQuAD finetune with
ByteGrad 8-bit compression").

Two data paths:

* ``--data train-v1.1.json`` — REAL SQuAD: parses the official JSON, trains
  a WordPiece tokenizer from the corpus itself (zero-egress: no pretrained
  vocab download), and maps character answer spans to token spans via the
  tokenizer's offsets.
* default — synthetic QA batches with the same feature shape (CI path).

    python examples/squad/main.py --steps 20           # BERT-mini, CPU-able
    python examples/squad/main.py --large --steps 100  # BERT-Large
    python examples/squad/main.py --data /data/squad/train-v1.1.json
"""

import argparse
import json

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms import Algorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.bert import BertConfig, BertModel, bert_large_config


class BertForQuestionAnswering(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None):
        h = BertModel(self.cfg, name="bert")(input_ids, attention_mask=attention_mask)
        logits = nn.Dense(2, name="qa_outputs")(h)  # (B, T, 2)
        return logits[..., 0], logits[..., 1]  # start, end


def qa_loss_fn(model):
    def loss_fn(params, batch):
        ids, mask, starts, ends = batch
        s_logits, e_logits = model.apply({"params": params}, ids, attention_mask=mask)
        s_logits = jnp.where(mask, s_logits, -1e9)
        e_logits = jnp.where(mask, e_logits, -1e9)

        def ce(logits, pos):
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(jnp.take_along_axis(logp, pos[:, None], axis=1))

        return 0.5 * (ce(s_logits, starts) + ce(e_logits, ends))

    return loss_fn


def load_real_squad(path, seq, vocab_size=8000, max_examples=20000):
    """Official SQuAD JSON -> (ids, mask, starts, ends) arrays.

    The WordPiece vocabulary is trained from the corpus itself with the
    ``tokenizers`` library (reference uses a downloaded pretrained vocab,
    ``examples/squad/run_squad.py``; this environment is zero-egress).
    Character answer spans map to token spans through the fast tokenizer's
    byte offsets; examples whose answer falls outside the truncated window
    are dropped, as in the reference feature builder."""
    from tokenizers import BertWordPieceTokenizer

    raw = json.load(open(path))["data"]
    examples = []
    for article in raw:
        for para in article["paragraphs"]:
            ctx = para["context"]
            for qa in para["qas"]:
                if len(examples) >= max_examples:
                    break
                if qa.get("answers"):
                    a = qa["answers"][0]
                    examples.append(
                        (qa["question"], ctx, a["answer_start"],
                         a["answer_start"] + len(a["text"]))
                    )
            if len(examples) >= max_examples:
                break
        if len(examples) >= max_examples:
            break
    tok = BertWordPieceTokenizer(lowercase=True)
    tok.train_from_iterator(
        [t for q, c, _, _ in examples for t in (q, c)],
        vocab_size=vocab_size,
        special_tokens=["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"],
    )
    tok.enable_truncation(max_length=seq)
    tok.enable_padding(length=seq, pad_token="[PAD]")

    ids_l, mask_l, s_l, e_l = [], [], [], []
    for q, ctx, cs, ce in examples:
        enc = tok.encode(q, ctx)
        ts = te = None
        for i, (sid, (o0, o1)) in enumerate(zip(enc.sequence_ids, enc.offsets)):
            if sid != 1 or o0 == o1:
                continue
            if o0 <= cs < o1:
                ts = i
            if o0 < ce <= o1:
                te = i
        if ts is None or te is None:
            continue  # answer truncated away
        ids_l.append(enc.ids)
        mask_l.append(enc.attention_mask)
        s_l.append(ts)
        e_l.append(te)
    return (
        np.array(ids_l, np.int32),
        np.array(mask_l, bool),
        np.array(s_l, np.int32),
        np.array(e_l, np.int32),
        tok.get_vocab_size(),
    )


def synthetic_squad(rng, n, seq, vocab):
    ids = rng.randint(5, vocab, (n, seq)).astype(np.int32)
    lengths = rng.randint(seq // 2, seq, n)
    mask = np.arange(seq)[None, :] < lengths[:, None]
    starts = (rng.rand(n) * (lengths - 2)).astype(np.int32)
    spans = rng.randint(1, 5, n)
    ends = np.minimum(starts + spans, lengths - 1).astype(np.int32)
    # plant a weak signal: answer tokens get a marker id
    for i in range(n):
        ids[i, starts[i]] = 2
        ids[i, ends[i]] = 3
    return ids, mask, starts, ends


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--large", action="store_true")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--data", default=None,
                   help="path to SQuAD train-v1.1.json; synthetic when omitted")
    args = p.parse_args()

    group = bagua_tpu.init_process_group()
    real = None
    if args.data:
        ids, mask, starts, ends, vocab = load_real_squad(args.data, args.seq)
        real = (ids, mask, starts, ends)
        print(f"{len(ids)} SQuAD features, vocab {vocab}")
    if args.large:
        cfg = bert_large_config(
            compute_dtype=jnp.bfloat16, max_position_embeddings=args.seq,
            **({"vocab_size": vocab} if real else {}),
        )
    else:
        cfg = BertConfig(
            vocab_size=vocab if real else 1000, hidden_size=64, num_layers=2,
            num_heads=4, intermediate_size=128, max_position_embeddings=args.seq,
        )
    model = BertForQuestionAnswering(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, args.seq), jnp.int32)
    )["params"]

    ddp = DistributedDataParallel(
        qa_loss_fn(model), optax.adam(3e-4), Algorithm.init("bytegrad"),
        process_group=group,
    )
    state = ddp.init(params)

    rng = np.random.RandomState(0)
    bs = args.batch_size * group.size
    for step in range(args.steps):
        if real is not None:
            idx = rng.randint(0, len(real[0]), bs)
            ids, mask, starts, ends = (a[idx] for a in real)
        else:
            ids, mask, starts, ends = synthetic_squad(rng, bs, args.seq, cfg.vocab_size)
        state, losses = ddp.train_step(
            state,
            (jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(starts), jnp.asarray(ends)),
        )
        if step % 10 == 0:
            print(f"step {step}: loss {float(losses.mean()):.4f}")
    print(f"final loss {float(losses.mean()):.6f}")


if __name__ == "__main__":
    main()
