#!/usr/bin/env python3
"""SQuAD-style BERT finetune example (analog of the reference's
``examples/squad``): BERT + span-prediction head, ByteGrad compression (the
BASELINE.json config "BERT-Large SQuAD finetune with ByteGrad 8-bit
compression").  QA data is synthetic (zero-egress) but the model/loss shape
is the real finetune task: predict answer start/end positions.

    python examples/squad/main.py --steps 20           # BERT-mini, CPU-able
    python examples/squad/main.py --large --steps 100  # BERT-Large
"""

import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms import Algorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.bert import BertConfig, BertModel, bert_large_config


class BertForQuestionAnswering(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None):
        h = BertModel(self.cfg, name="bert")(input_ids, attention_mask=attention_mask)
        logits = nn.Dense(2, name="qa_outputs")(h)  # (B, T, 2)
        return logits[..., 0], logits[..., 1]  # start, end


def qa_loss_fn(model):
    def loss_fn(params, batch):
        ids, mask, starts, ends = batch
        s_logits, e_logits = model.apply({"params": params}, ids, attention_mask=mask)
        s_logits = jnp.where(mask, s_logits, -1e9)
        e_logits = jnp.where(mask, e_logits, -1e9)

        def ce(logits, pos):
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(jnp.take_along_axis(logp, pos[:, None], axis=1))

        return 0.5 * (ce(s_logits, starts) + ce(e_logits, ends))

    return loss_fn


def synthetic_squad(rng, n, seq, vocab):
    ids = rng.randint(5, vocab, (n, seq)).astype(np.int32)
    lengths = rng.randint(seq // 2, seq, n)
    mask = np.arange(seq)[None, :] < lengths[:, None]
    starts = (rng.rand(n) * (lengths - 2)).astype(np.int32)
    spans = rng.randint(1, 5, n)
    ends = np.minimum(starts + spans, lengths - 1).astype(np.int32)
    # plant a weak signal: answer tokens get a marker id
    for i in range(n):
        ids[i, starts[i]] = 2
        ids[i, ends[i]] = 3
    return ids, mask, starts, ends


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--large", action="store_true")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    args = p.parse_args()

    group = bagua_tpu.init_process_group()
    if args.large:
        cfg = bert_large_config(
            compute_dtype=jnp.bfloat16, max_position_embeddings=args.seq
        )
    else:
        cfg = BertConfig(
            vocab_size=1000, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position_embeddings=args.seq,
        )
    model = BertForQuestionAnswering(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, args.seq), jnp.int32)
    )["params"]

    ddp = DistributedDataParallel(
        qa_loss_fn(model), optax.adam(3e-4), Algorithm.init("bytegrad"),
        process_group=group,
    )
    state = ddp.init(params)

    rng = np.random.RandomState(0)
    bs = args.batch_size * group.size
    for step in range(args.steps):
        ids, mask, starts, ends = synthetic_squad(rng, bs, args.seq, cfg.vocab_size)
        state, losses = ddp.train_step(
            state,
            (jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(starts), jnp.asarray(ends)),
        )
        if step % 10 == 0:
            print(f"step {step}: loss {float(losses.mean()):.4f}")
    print(f"final loss {float(losses.mean()):.6f}")


if __name__ == "__main__":
    main()
