#!/usr/bin/env python3
"""ImageNet training example (analog of the reference's
``examples/imagenet/main.py``): ResNet-50 or VGG16 with any algorithm,
demonstrating the contrib data tier.

Two data paths:

* ``--data-dir DIR`` — REAL ImageFolder data (``DIR/<class>/<img>.jpeg``,
  the torchvision/reference layout): bytes are read by the native GIL-free
  IO prefetcher (C++ thread pool, ``contrib/native/io_prefetcher.cpp``),
  decoded with PIL, random-cropped + flipped, normalized.
* default — synthetic data through the cached-dataset + load-balancing
  sampler pipeline (zero-egress CI path; the pipeline is the real one).

    python examples/imagenet/main.py --arch resnet50 --algorithm decentralized
    python examples/imagenet/main.py --data-dir /data/imagenet/train
"""

import argparse
import io
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms import Algorithm
from bagua_tpu.contrib import CachedDataset, LoadBalancingDistributedSampler
from bagua_tpu.ddp import DistributedDataParallel


class SyntheticImageNet:
    """Map-style dataset with an expensive-looking __getitem__ (the cache
    tier's reason to exist)."""

    def __init__(self, n=512, image_size=64, classes=100, seed=0):
        self.n, self.image_size, self.classes = n, image_size, classes
        self.rng = np.random.RandomState(seed)
        self.labels = self.rng.randint(0, classes, n)
        self.protos = self.rng.rand(classes, image_size, image_size, 3).astype(np.float32)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        y = self.labels[i]
        x = self.protos[y] + 0.1 * np.random.RandomState(i).randn(
            self.image_size, self.image_size, 3
        ).astype(np.float32)
        return x, np.int32(y)


class FolderImageNet:
    """ImageFolder-layout dataset (reference loader:
    ``examples/imagenet/main.py`` torchvision ``ImageFolder``): class
    subdirectories of image files.  ``read_batches`` streams decoded,
    augmented batches with file IO overlapped by the native prefetcher."""

    MEAN = np.array([0.485, 0.456, 0.406], np.float32)
    STD = np.array([0.229, 0.224, 0.225], np.float32)

    def __init__(self, root, image_size=64, seed=0):
        self.root = root
        self.image_size = image_size
        self.classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        if not self.classes:
            raise FileNotFoundError(f"no class subdirectories under {root}")
        exts = (".jpg", ".jpeg", ".png", ".bmp", ".webp", ".ppm")
        self.samples = []
        for ci, cname in enumerate(self.classes):
            cdir = os.path.join(root, cname)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(exts):  # skip READMEs/checksums
                    self.samples.append((os.path.join(cdir, fname), ci))
        if not self.samples:
            raise FileNotFoundError(f"no image files under {root}")
        self.rng = np.random.RandomState(seed)

    def __len__(self):
        return len(self.samples)

    def _decode(self, raw, train=True):
        from PIL import Image

        img = Image.open(io.BytesIO(raw)).convert("RGB")
        s = self.image_size
        # resize shorter side to 1.15*s, then random (train) / center crop
        w, h = img.size
        scale = int(s * 1.15) / min(w, h)
        img = img.resize((max(s, round(w * scale)), max(s, round(h * scale))))
        w, h = img.size
        if train:
            x0 = self.rng.randint(0, w - s + 1)
            y0 = self.rng.randint(0, h - s + 1)
        else:
            x0, y0 = (w - s) // 2, (h - s) // 2
        img = img.crop((x0, y0, x0 + s, y0 + s))
        x = np.asarray(img, np.float32) / 255.0
        if train and self.rng.rand() < 0.5:
            x = x[:, ::-1]
        return (x - self.MEAN) / self.STD

    def read_batches(self, batch_size, steps, prefetch_threads=4):
        """Yield ``(x, y)`` batches; file reads ride the C++ IO prefetcher
        so decode/augment overlaps disk latency."""
        from bagua_tpu.contrib.io_prefetcher import IOPrefetcher

        order = self.rng.permutation(len(self.samples))
        needed = [
            self.samples[order[k % len(order)]] for k in range(batch_size * steps)
        ]
        pf = IOPrefetcher(n_threads=prefetch_threads)
        try:
            it = pf.read_ordered([p for p, _ in needed])
            k = 0
            for _ in range(steps):
                xs, ys = [], []
                for _ in range(batch_size):
                    path, raw = next(it)
                    if raw is None:
                        raise IOError(f"prefetcher failed to read {path}")
                    xs.append(self._decode(raw))
                    ys.append(needed[k][1])
                    k += 1
                yield np.stack(xs), np.array(ys, np.int32)
        finally:
            pf.close()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--arch", default="resnet50", choices=["resnet50", "vgg16"])
    p.add_argument("--algorithm", default="gradient_allreduce")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--data-dir", default=None,
                   help="ImageFolder root (class subdirs of images); "
                        "synthetic data when omitted")
    args = p.parse_args()

    group = bagua_tpu.init_process_group()
    folder = FolderImageNet(args.data_dir, args.image_size) if args.data_dir else None
    classes = len(folder.classes) if folder else 100

    if args.arch == "resnet50":
        from bagua_tpu.models.resnet import init_resnet50, resnet_loss_fn

        model, variables = init_resnet50(
            jax.random.PRNGKey(0), args.image_size, classes, compute_dtype=jnp.bfloat16
        )
        params = {"params": variables["params"], "batch_stats": variables["batch_stats"]}
        loss_fn = resnet_loss_fn(model)
        dp_filter = lambda name: "batch_stats" not in name
    else:
        from bagua_tpu.models.vgg import init_vgg16, vgg_loss_fn

        model, params = init_vgg16(
            jax.random.PRNGKey(0), args.image_size, classes, compute_dtype=jnp.bfloat16
        )
        loss_fn = vgg_loss_fn(model)
        dp_filter = None

    ddp = DistributedDataParallel(
        loss_fn, optax.sgd(0.005, momentum=0.9), Algorithm.init(args.algorithm),
        process_group=group, dp_filter=dp_filter,
    )
    state = ddp.init(params)

    bs = args.batch_size * group.size
    if folder is not None:
        print(f"{len(folder)} images, {classes} classes from {args.data_dir}")
        for step, (x, y) in enumerate(folder.read_batches(bs, args.steps)):
            state, losses = ddp.train_step(state, (jnp.asarray(x), jnp.asarray(y)))
            if step % 10 == 0:
                print(f"step {step}: loss {float(losses.mean()):.4f}")
    else:
        dataset = CachedDataset(
            SyntheticImageNet(image_size=args.image_size), backend="memory"
        )
        # Sampling over the CACHED dataset warms the cache during the
        # complexity pass, so the training loop is served entirely from cache.
        sampler = LoadBalancingDistributedSampler(
            dataset, complexity_fn=lambda s: int(s[1]),  # class id as fake complexity
            num_replicas=1, rank=0,
        )
        order = list(iter(sampler))
        for step in range(args.steps):
            idx = [order[(step * bs + j) % len(order)] for j in range(bs)]
            samples = [dataset[i] for i in idx]
            x = jnp.asarray(np.stack([s[0] for s in samples]))
            y = jnp.asarray(np.array([s[1] for s in samples], np.int32))
            state, losses = ddp.train_step(state, (x, y))
            if step % 10 == 0:
                print(f"step {step}: loss {float(losses.mean()):.4f} "
                      f"(cache hit rate {dataset.cache_loader.hit_rate:.2f})")
    print(f"final loss {float(losses.mean()):.6f}")


if __name__ == "__main__":
    main()
