#!/usr/bin/env python3
"""ImageNet-style training example (analog of the reference's
``examples/imagenet``): ResNet-50 or VGG16 with any algorithm, demonstrating
the contrib data tier — cached dataset over the shared-memory store and the
load-balancing sampler.  Data is synthetic (zero-egress environment) but the
pipeline is the real one.

    python examples/imagenet/main.py --arch resnet50 --algorithm decentralized
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms import Algorithm
from bagua_tpu.contrib import CachedDataset, LoadBalancingDistributedSampler
from bagua_tpu.ddp import DistributedDataParallel


class SyntheticImageNet:
    """Map-style dataset with an expensive-looking __getitem__ (the cache
    tier's reason to exist)."""

    def __init__(self, n=512, image_size=64, classes=100, seed=0):
        self.n, self.image_size, self.classes = n, image_size, classes
        self.rng = np.random.RandomState(seed)
        self.labels = self.rng.randint(0, classes, n)
        self.protos = self.rng.rand(classes, image_size, image_size, 3).astype(np.float32)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        y = self.labels[i]
        x = self.protos[y] + 0.1 * np.random.RandomState(i).randn(
            self.image_size, self.image_size, 3
        ).astype(np.float32)
        return x, np.int32(y)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--arch", default="resnet50", choices=["resnet50", "vgg16"])
    p.add_argument("--algorithm", default="gradient_allreduce")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=64)
    args = p.parse_args()

    group = bagua_tpu.init_process_group()
    classes = 100

    if args.arch == "resnet50":
        from bagua_tpu.models.resnet import init_resnet50, resnet_loss_fn

        model, variables = init_resnet50(
            jax.random.PRNGKey(0), args.image_size, classes, compute_dtype=jnp.bfloat16
        )
        params = {"params": variables["params"], "batch_stats": variables["batch_stats"]}
        loss_fn = resnet_loss_fn(model)
        dp_filter = lambda name: "batch_stats" not in name
    else:
        from bagua_tpu.models.vgg import init_vgg16, vgg_loss_fn

        model, params = init_vgg16(
            jax.random.PRNGKey(0), args.image_size, classes, compute_dtype=jnp.bfloat16
        )
        loss_fn = vgg_loss_fn(model)
        dp_filter = None

    ddp = DistributedDataParallel(
        loss_fn, optax.sgd(0.005, momentum=0.9), Algorithm.init(args.algorithm),
        process_group=group, dp_filter=dp_filter,
    )
    state = ddp.init(params)

    dataset = CachedDataset(SyntheticImageNet(image_size=args.image_size), backend="memory")
    # Sampling over the CACHED dataset warms the cache during the complexity
    # pass, so the training loop below is served entirely from cache.
    sampler = LoadBalancingDistributedSampler(
        dataset, complexity_fn=lambda s: int(s[1]),  # class id as fake complexity
        num_replicas=1, rank=0,
    )

    order = list(iter(sampler))
    bs = args.batch_size * group.size
    for step in range(args.steps):
        idx = [order[(step * bs + j) % len(order)] for j in range(bs)]
        samples = [dataset[i] for i in idx]
        x = jnp.asarray(np.stack([s[0] for s in samples]))
        y = jnp.asarray(np.array([s[1] for s in samples], np.int32))
        state, losses = ddp.train_step(state, (x, y))
        if step % 10 == 0:
            print(f"step {step}: loss {float(losses.mean()):.4f} "
                  f"(cache hit rate {dataset.cache_loader.hit_rate:.2f})")
    print(f"final loss {float(losses.mean()):.6f}")


if __name__ == "__main__":
    main()
