#!/usr/bin/env python3
"""Synthetic throughput benchmark (analog of the reference's
``examples/benchmark/synthetic_benchmark.py``, the workload behind the CI
thresholds in ``benchmark_master.sh``).

    python examples/benchmark/synthetic_benchmark.py --model vgg16 \
        --algorithm gradient_allreduce --num-iters 30
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.ddp import DistributedDataParallel


def build(model_name: str, dtype):
    if model_name == "vgg16":
        from bagua_tpu.models.vgg import init_vgg16, vgg_loss_fn

        model, params = init_vgg16(jax.random.PRNGKey(0), 224, 1000, compute_dtype=dtype)
        def batch_fn(rng, bs):
            return (
                jnp.asarray(rng.rand(bs, 224, 224, 3).astype(np.float32)),
                jnp.asarray(rng.randint(0, 1000, (bs,)).astype(np.int32)),
            )
        return vgg_loss_fn(model), params, batch_fn
    if model_name == "bert-large":
        from bagua_tpu.models.bert import BertForPreTraining, bert_large_config, mlm_loss_fn

        cfg = bert_large_config(compute_dtype=dtype, max_position_embeddings=128)
        model = BertForPreTraining(cfg)
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 128), jnp.int32))["params"]
        def batch_fn(rng, bs):
            return (
                jnp.asarray(rng.randint(0, cfg.vocab_size, (bs, 128)).astype(np.int32)),
                jnp.asarray(rng.randint(0, cfg.vocab_size, (bs, 128)).astype(np.int32)),
            )
        return mlm_loss_fn(model), params, batch_fn
    raise ValueError(model_name)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="vgg16", choices=["vgg16", "bert-large"])
    p.add_argument("--algorithm", default="gradient_allreduce")
    p.add_argument("--batch-size", type=int, default=32, help="per chip")
    p.add_argument("--num-iters", type=int, default=30)
    p.add_argument("--num-warmup", type=int, default=3)
    p.add_argument("--fp32", action="store_true")
    args = p.parse_args()

    group = bagua_tpu.init_process_group()
    dtype = jnp.float32 if args.fp32 else jnp.bfloat16
    loss_fn, params, batch_fn = build(args.model, dtype)

    algo = build_algorithm(args.algorithm, lr=1e-3, qadam_warmup_steps=10)
    opt = None if args.algorithm == "qadam" else optax.sgd(0.01, momentum=0.9)

    ddp = DistributedDataParallel(loss_fn, opt, algo, process_group=group)
    state = ddp.init(params)
    rng = np.random.RandomState(0)
    batch = batch_fn(rng, args.batch_size * group.size)

    for _ in range(args.num_warmup):
        state, losses = ddp.train_step(state, batch)
    jax.block_until_ready(losses)

    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        state, losses = ddp.train_step(state, batch)
    jax.block_until_ready(losses)
    dt = time.perf_counter() - t0

    sps = args.batch_size * group.size * args.num_iters / dt / group.size
    print(
        f"model={args.model} algorithm={args.algorithm} "
        f"batch={args.batch_size}/chip chips={group.size}: "
        f"{sps:.1f} samples/sec/chip, final loss {float(losses.mean()):.6f}"
    )


if __name__ == "__main__":
    main()
