#!/usr/bin/env python3
"""Elastic training example (analog of the reference's
``examples/elastic_training/main.py``): checkpoint every epoch, resume from
the latest checkpoint on (re)start.  Run under the elastic launcher:

    python -m bagua_tpu.distributed.run --nproc_per_node 1 --max_restarts 3 \
        examples/elastic_training/main.py --ckpt-dir /tmp/elastic_ckpt
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms import Algorithm
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.trainer import Trainer


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", default="/tmp/bagua_tpu_elastic")
    p.add_argument("--steps", type=int, default=200)
    args = p.parse_args()

    from bagua_tpu.distributed import init_from_env

    init_from_env()  # launcher-exported env (multi-host ready); local fallback
    with Trainer(
        mse_loss,
        optax.adam(1e-3),
        Algorithm.init("gradient_allreduce"),
        ckpt_dir=args.ckpt_dir,
        ckpt_interval=50,
        watchdog_timeout_s=120.0,
    ) as trainer:
        params = init_mlp(jax.random.PRNGKey(0), [32, 64, 8])
        state = trainer.init_state(params)
        start = int(state.step[0])
        print(f"starting at step {start}")

        rng = np.random.RandomState(0)
        n = bagua_tpu.get_default_group().size

        def batches():
            for _ in range(args.steps - start):
                yield (
                    jnp.asarray(rng.randn(16 * n, 32), jnp.float32),
                    jnp.asarray(rng.randn(16 * n, 8), jnp.float32),
                )

        state = trainer.fit(state, batches(), log_every=50)
        print(f"done at step {int(state.step[0])}")


if __name__ == "__main__":
    main()
