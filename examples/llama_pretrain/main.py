#!/usr/bin/env python3
"""Llama-style character-LM pretraining: dp x tp x sp with zigzag ring
attention and grouped-query attention.

The modern-decoder companion to ``examples/gpt_pretrain`` (which showcases
the 4D pp composition): a Llama model (RMSNorm, RoPE on global SP positions,
SwiGLU, GQA with unrepeated K/V on the ring) trains on real text — any UTF-8
file via ``--data`` (tiny-shakespeare-style char LM) — or on a built-in
synthetic corpus, over a ``(dp, tp, sp)`` mesh:

* **dp** — batch sharded, gradients averaged over (dp, sp).
* **tp** — Megatron column/row sharding inside attention and the SwiGLU MLP.
* **sp** — zigzag causal ring attention; each rank holds two globally
  non-adjacent half-blocks of every sequence, balancing the causal triangle.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/llama_pretrain/main.py --dp 2 --tp 2 --sp 2 --steps 5

    # real text
    ... main.py --data path/to/corpus.txt --steps 20

    # engine mode: the bagua DDP engine owns the step (bucketed gradient
    # exchange with backward overlap, confined to the dp/fsdp axes of a
    # named MeshSpec mesh) while the model's Megatron tp collectives ride
    # the tp axis untouched
    ... main.py --engine --dp 4 --tp 2 --sp 1 --steps 5
    ... main.py --engine --dp 4 --fsdp 2 --tp 1 --sp 1 --algo zero --steps 5
"""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from bagua_tpu.models.llama import LlamaConfig, LlamaModel, llama_loss_fn
from bagua_tpu.parallel.ring_attention import zigzag_order


def load_corpus(path, rng):
    """Char-level corpus: (token array, vocab size).  Synthetic fallback is a
    Markov-ish byte stream so the loss has real structure to learn."""
    if path:
        text = open(path, "r", encoding="utf-8", errors="replace").read()
        chars = sorted(set(text))
        lut = {c: i for i, c in enumerate(chars)}
        return np.array([lut[c] for c in text], dtype=np.int32), len(chars)
    n, vocab = 65536, 64
    toks = np.zeros(n, dtype=np.int32)
    for i in range(1, n):
        # next char depends on the previous one: learnable bigram structure
        toks[i] = (toks[i - 1] * 7 + rng.randint(0, 8)) % vocab
    return toks, vocab


def batches(toks, rng, batch, seq, steps):
    for _ in range(steps):
        idx = rng.randint(0, len(toks) - seq - 1, size=batch)
        yield np.stack([toks[i : i + seq] for i in idx])


def run_engine(args):
    """Engine-driven mesh mode: a named ``MeshSpec`` threads the axes through
    ``DistributedDataParallel`` — the bucketed gradient exchange (with
    backward overlap, or ZeRO's rs+ag under ``--algo zero``) rides the
    dp/fsdp data axes only while the Llama model's explicit tp collectives
    keep their own axis.  sp stays with the hand-scheduled mode above."""
    assert args.sp == 1, "--engine covers dp x tp / dp x fsdp; drop --sp"
    import bagua_tpu
    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.sharded.algorithm import ZeroAlgorithm

    axes = {"dp": args.dp}
    if args.fsdp > 1:
        axes["fsdp"] = args.fsdp
    if args.tp > 1:
        axes["tp"] = args.tp
    group = bagua_tpu.init_process_group(mesh_spec=bagua_tpu.MeshSpec(axes))

    rng = np.random.RandomState(0)
    toks, vocab = load_corpus(args.data, rng)
    heads = max(2, 2 * args.tp)
    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=args.hidden, num_layers=args.layers,
        num_heads=heads, num_kv_heads=heads // 2, intermediate_size=2 * args.hidden,
        max_position_embeddings=args.seq, tp_size=args.tp, tp_axis="tp",
    )
    model = LlamaModel(cfg)
    loss_fn = llama_loss_fn(model)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((2, args.seq), jnp.int32))["params"]

    algo = ZeroAlgorithm() if args.algo == "zero" else GradientAllReduceAlgorithm()
    ddp = DistributedDataParallel(
        loss_fn, optax.adamw(args.lr), algo, process_group=group,
        bucket_size_bytes=1 << 14, overlap=True,
        dp_axis="dp",
        fsdp_axis="fsdp" if args.fsdp > 1 else None,
        tp_axis="tp" if args.tp > 1 else None,
    )
    state = ddp.init(params=params)
    first = last = None
    for i, ids in enumerate(batches(toks, rng, args.batch, args.seq, args.steps)):
        state, losses = ddp.train_step(state, ddp.shard_batch(jnp.asarray(ids)))
        last = float(np.asarray(losses).ravel()[0])
        first = first if first is not None else last
        print(f"step {i}: loss {last:.4f}", flush=True)
    state = ddp.finalize_pending_updates(state)
    ddp.shutdown()
    print(
        f"final: engine mesh={axes} algo={args.algo} vocab={vocab} "
        f"loss {first:.4f} -> {last:.4f}",
        flush=True,
    )
    assert np.isfinite(last)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="UTF-8 text file (char LM); synthetic if unset")
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--sp", type=int, default=2)
    p.add_argument("--fsdp", type=int, default=1, help="engine mode only: fsdp axis size")
    p.add_argument(
        "--engine", action="store_true",
        help="drive the step through the bagua DDP engine over a named "
        "MeshSpec mesh (dp x tp / dp x fsdp) instead of the raw shard_map",
    )
    p.add_argument(
        "--algo", choices=("gradient_allreduce", "zero"),
        default="gradient_allreduce", help="engine mode: exchange algorithm",
    )
    p.add_argument("--seq", type=int, default=64, help="global sequence length")
    p.add_argument("--batch", type=int, default=8, help="global batch size")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--lr", type=float, default=3e-3)
    args = p.parse_args()

    if args.engine:
        return run_engine(args)

    n_dev = args.dp * args.tp * args.sp
    devs = jax.devices()
    assert len(devs) >= n_dev, f"need {n_dev} devices, have {len(devs)}"
    mesh = Mesh(np.array(devs[:n_dev]).reshape(args.dp, args.tp, args.sp), ("dp", "tp", "sp"))

    rng = np.random.RandomState(0)
    toks, vocab = load_corpus(args.data, rng)
    heads = max(2, 2 * args.tp)
    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=args.hidden, num_layers=args.layers,
        num_heads=heads, num_kv_heads=heads // 2, intermediate_size=2 * args.hidden,
        max_position_embeddings=args.seq, tp_size=args.tp, tp_axis="tp",
        sp_axis="sp" if args.sp > 1 else None,
        sp_layout="zigzag" if args.sp > 1 else "contiguous",
    )
    model = LlamaModel(cfg)
    loss_fn = llama_loss_fn(model)
    seq_local = args.seq // args.sp
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((2, seq_local), jnp.int32))["params"]
    opt = optax.adamw(args.lr)
    opt_state = opt.init(params)

    def local_step(params, opt_state, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, ("dp", "sp")), grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, jax.lax.pmean(loss, ("dp", "sp"))

    step = jax.jit(
        jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(), P("dp", "sp")),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )

    zz = np.asarray(zigzag_order(args.seq, args.sp)) if args.sp > 1 else None
    first = last = None
    for i, ids in enumerate(batches(toks, rng, args.batch, args.seq, args.steps)):
        if zz is not None:
            ids = ids[:, zz]  # physical zigzag layout; the model assigns
            # matching global RoPE positions per rank
        params, opt_state, loss = step(params, opt_state, jnp.asarray(ids))
        last = float(loss)
        first = first if first is not None else last
        print(f"step {i}: loss {last:.4f}", flush=True)
    print(f"final: vocab={vocab} loss {first:.4f} -> {last:.4f}", flush=True)
    assert np.isfinite(last)


if __name__ == "__main__":
    main()
