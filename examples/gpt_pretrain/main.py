#!/usr/bin/env python3
"""4D-parallel GPT pretraining: dp x pp x tp x sp in one training step.

The composition showcase for the mesh substrate (each dimension is tested
separately in the suite; this wires all four together the way a real LLM
pretrain would):

* **dp** — data parallelism: batch sharded, gradients averaged.
* **pp** — GPipe pipeline over uniform transformer stages
  (``parallel.pipeline.pipeline_apply``; autodiff runs the backward
  schedule).  Embedding/head stay replicated outside the pipeline.
* **tp** — Megatron column/row tensor parallelism inside every block
  (``parallel.tensor_parallel``).
* **sp** — ring attention over the sequence axis
  (``parallel.ring_attention``; context length scales with sp).

Gradient sync rules (the interesting part — see ``sync_grads``):
embedding grads flow only into pipeline stage 0, so they **psum** over pp;
stage params are pp-local; everything replicated averages over (dp, sp).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python examples/gpt_pretrain/main.py \
        --dp 1 --pp 2 --tp 2 --sp 2 --steps 5

    # engine mode: the bagua DDP engine owns the step over a named MeshSpec
    # mesh — bucketed gradient exchange (backward-overlapped, or ZeRO under
    # --algo zero) on the dp/fsdp axes, Megatron tp inside the blocks
    ... main.py --engine --dp 4 --tp 2 --pp 1 --sp 1 --steps 5
    ... main.py --engine --dp 4 --fsdp 2 --tp 1 --pp 1 --sp 1 --algo zero
"""

import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from bagua_tpu.models.gpt import GPTBlock, GPTConfig
from bagua_tpu.parallel.pipeline import pipeline_apply, pipeline_train_1f1b


class GPTStage(nn.Module):
    """A uniform chunk of GPT blocks — one pipeline stage."""

    cfg: GPTConfig
    n_blocks: int

    @nn.compact
    def __call__(self, x):
        for i in range(self.n_blocks):
            x = GPTBlock(self.cfg, name=f"block{i}")(x)
        return x


def build(args):
    cfg = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.blocks_per_stage, num_heads=args.heads,
        max_position_embeddings=args.seq,
        tp_size=args.tp, tp_axis="tp",
        sp_axis="sp" if args.sp > 1 else None,
    )
    stage = GPTStage(cfg, n_blocks=args.blocks_per_stage)
    embed = nn.Embed(args.vocab, args.hidden, name="embed")
    head = nn.Dense(args.vocab, use_bias=False, name="head")
    return cfg, stage, embed, head


def run_engine(args):
    """Engine-driven mesh mode: embed + blocks + head as one parameter tree
    trained through ``DistributedDataParallel`` over a named ``MeshSpec``
    mesh — the engine's bucketed exchange rides the dp/fsdp data axes only,
    the blocks' Megatron tp collectives keep the tp axis.  The pipeline
    (pp) and ring-attention (sp) compositions stay with the hand-scheduled
    mode above."""
    assert args.pp == 1 and args.sp == 1, (
        "--engine covers dp x tp / dp x fsdp; use the default mode for pp/sp"
    )
    import bagua_tpu
    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.sharded.algorithm import ZeroAlgorithm

    axes = {"dp": args.dp}
    if args.fsdp > 1:
        axes["fsdp"] = args.fsdp
    if args.tp > 1:
        axes["tp"] = args.tp
    group = bagua_tpu.init_process_group(mesh_spec=bagua_tpu.MeshSpec(axes))
    cfg, stage, embed, head = build(args)

    rng0 = jax.random.PRNGKey(0)
    x0 = jnp.zeros((2, args.seq, args.hidden), jnp.float32)
    ids0 = jnp.zeros((2, args.seq), jnp.int32)
    params = {
        "embed": embed.init(rng0, ids0)["params"],
        "stage": stage.init(jax.random.PRNGKey(100), x0)["params"],
        "head": head.init(jax.random.PRNGKey(1), x0)["params"],
    }

    def loss_fn(p, batch):
        ids, labels = batch
        x = embed.apply({"params": p["embed"]}, ids)
        x = stage.apply({"params": p["stage"]}, x)
        logits = head.apply({"params": p["head"]}, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], axis=-1))

    algo = ZeroAlgorithm() if args.algo == "zero" else GradientAllReduceAlgorithm()
    ddp = DistributedDataParallel(
        loss_fn, optax.adam(1e-3), algo, process_group=group,
        bucket_size_bytes=1 << 14, overlap=True,
        dp_axis="dp",
        fsdp_axis="fsdp" if args.fsdp > 1 else None,
        tp_axis="tp" if args.tp > 1 else None,
    )
    state = ddp.init(params=params)
    rng = np.random.RandomState(0)
    data = rng.randint(0, args.vocab, size=(args.steps, args.batch, args.seq + 1))
    losses = []
    for i in range(args.steps):
        batch = (
            jnp.asarray(data[i, :, :-1], jnp.int32),
            jnp.asarray(data[i, :, 1:], jnp.int32),
        )
        state, step_losses = ddp.train_step(state, ddp.shard_batch(batch))
        losses.append(float(np.asarray(step_losses).ravel()[0]))
        print(f"step {i}: loss {losses[-1]:.4f}", flush=True)
    state = ddp.finalize_pending_updates(state)
    ddp.shutdown()
    print(f"final: engine mesh={axes} algo={args.algo}", flush=True)
    return losses


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--pp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--sp", type=int, default=2)
    p.add_argument("--fsdp", type=int, default=1, help="engine mode only: fsdp axis size")
    p.add_argument(
        "--engine", action="store_true",
        help="drive the step through the bagua DDP engine over a named "
        "MeshSpec mesh (dp x tp / dp x fsdp) instead of the raw shard_map",
    )
    p.add_argument(
        "--algo", choices=("gradient_allreduce", "zero"),
        default="gradient_allreduce", help="engine mode: exchange algorithm",
    )
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--seq", type=int, default=32, help="GLOBAL sequence length")
    p.add_argument("--blocks-per-stage", type=int, default=1)
    p.add_argument("--batch", type=int, default=8, help="global batch")
    p.add_argument("--microbatches", type=int, default=2)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument(
        "--schedule", choices=["1f1b", "gpipe"], default="1f1b",
        help="pipeline schedule: 1F1B (bounded-memory, remat) or GPipe",
    )
    args = p.parse_args(argv)

    if args.engine:
        return run_engine(args)

    n = args.dp * args.pp * args.tp * args.sp
    devices = np.array(jax.devices()[:n]).reshape(args.dp, args.pp, args.tp, args.sp)
    mesh = Mesh(devices, ("dp", "pp", "tp", "sp"))
    cfg, stage, embed, head = build(args)

    t_local = args.seq // args.sp
    b_local = args.batch // args.dp
    rng0 = jax.random.PRNGKey(0)
    x0 = jnp.zeros((2, t_local, args.hidden), jnp.float32)
    ids0 = jnp.zeros((2, t_local), jnp.int32)

    # one stage's params per pp rank (same structure; stacked for sharding)
    stage_params = [
        stage.init(jax.random.PRNGKey(100 + s), x0)["params"] for s in range(args.pp)
    ]
    stacked_stage = jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)
    embed_params = embed.init(rng0, ids0)["params"]
    head_params = head.init(jax.random.PRNGKey(1), x0)["params"]

    # Separate optimizers per component: stage moments are pp-LOCAL state
    # (each rank's Adam moments describe its own stage's gradients), so they
    # live stacked over pp exactly like the stage params — declaring them
    # replicated would clobber every stage's moments with one rank's.
    opt = optax.adam(1e-3)
    embed_opt_state = opt.init(embed_params)
    stage_opt_state = jax.vmap(opt.init)(stacked_stage)  # leading pp axis
    head_opt_state = opt.init(head_params)

    def local_step(embed_p, stage_stacked, head_p, e_opt, s_opt_stacked, h_opt, ids, labels):
        my_stage = jax.tree.map(lambda x: x[0], stage_stacked)  # this rank's slice
        my_s_opt = jax.tree.map(lambda x: x[0], s_opt_stacked)

        mb_rows = b_local // args.microbatches

        def head_loss(h_p, y, lbl):
            # the LM head + cross entropy, evaluated on the LAST pipeline
            # stage's output only (1F1B's loss_params surface)
            logits = head.apply({"params": h_p}, y)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, lbl[..., None], axis=-1))

        if args.schedule == "1f1b":
            # Hand-scheduled 1F1B: stage inputs stashed in a bounded ring
            # buffer, backward recomputes (remat); only the scalar loss is
            # psum'd across stages.  Embedding backward is fed by the input
            # cotangents the schedule returns on pp rank 0.
            x, embed_vjp = jax.vjp(
                lambda e_p: embed.apply({"params": e_p}, ids), embed_p
            )
            micro = x.reshape(args.microbatches, mb_rows, t_local, args.hidden)
            labels_m = labels.reshape(args.microbatches, mb_rows, t_local)
            loss, grads = pipeline_train_1f1b(
                lambda sp_, u: stage.apply({"params": sp_}, u), my_stage,
                micro, labels_m, head_loss, axis_name="pp",
                loss_params=head_p, with_input_grads=True,
            )
            g_stage = grads.stage
            # input cotangents are real on pp rank 0 (zeros elsewhere): psum
            # over pp, then pull back through the embedding
            dx = jax.lax.psum(grads.inputs, "pp")
            (g_embed,) = embed_vjp(dx.reshape(b_local, t_local, args.hidden))
            g_embed = jax.tree.map(lambda g: jax.lax.pmean(g, ("dp", "sp")), g_embed)
            # head grads live on the LAST pp rank (zeros elsewhere): psum
            # over pp recovers, then average the data axes.
            g_head = jax.tree.map(
                lambda g: jax.lax.pmean(jax.lax.psum(g, "pp"), ("dp", "sp")),
                grads.loss_params,
            )
            g_stage = jax.tree.map(lambda g: jax.lax.pmean(g, ("dp", "sp")), g_stage)
        else:
            def loss_fn(triple):
                e_p, s_p, h_p = triple
                x = embed.apply({"params": e_p}, ids)  # (b_local, t_local, hidden)
                micro = x.reshape(args.microbatches, mb_rows, t_local, args.hidden)
                y = pipeline_apply(
                    lambda sp_, u: stage.apply({"params": sp_}, u), s_p, micro,
                    axis_name="pp",
                )
                h = y.reshape(b_local, t_local, args.hidden)
                return head_loss(h_p, h, labels)

            loss, grads = jax.value_and_grad(loss_fn)((embed_p, my_stage, head_p))
            g_embed, g_stage, g_head = grads

            # -- gradient sync rules --------------------------------------
            # embedding: grads enter the pipeline only on pp rank 0 -> psum
            # over pp recovers the full gradient; then average over (dp, sp).
            g_embed = jax.tree.map(
                lambda g: jax.lax.pmean(jax.lax.psum(g, "pp"), ("dp", "sp")), g_embed
            )
            # stage params: pp-local (each rank owns its stage); average (dp, sp).
            g_stage = jax.tree.map(lambda g: jax.lax.pmean(g, ("dp", "sp")), g_stage)
            # head: computed identically on every pp rank (pipeline output is
            # broadcast); average everywhere it is replicated.
            g_head = jax.tree.map(lambda g: jax.lax.pmean(g, ("dp", "pp", "sp")), g_head)

        e_upd, e_opt = opt.update(g_embed, e_opt, embed_p)
        s_upd, my_s_opt = opt.update(g_stage, my_s_opt, my_stage)
        h_upd, h_opt = opt.update(g_head, h_opt, head_p)
        embed_p = optax.apply_updates(embed_p, e_upd)
        my_stage = optax.apply_updates(my_stage, s_upd)
        head_p = optax.apply_updates(head_p, h_upd)
        # the global training loss: local losses vary over (dp, sp) shards
        loss = jax.lax.pmean(loss, ("dp", "sp"))
        return (
            embed_p,
            jax.tree.map(lambda x: x[None], my_stage),
            head_p,
            e_opt,
            jax.tree.map(lambda x: x[None], my_s_opt),
            h_opt,
            loss,
        )

    step = jax.jit(
        jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), P("pp"), P(), P(), P("pp"), P(), P("dp", "sp"), P("dp", "sp")),
            out_specs=(P(), P("pp"), P(), P(), P("pp"), P(), P()),
            check_vma=False,
        )
    )

    rng = np.random.RandomState(0)
    data = rng.randint(0, args.vocab, size=(args.steps, args.batch, args.seq + 1))
    losses = []
    for i in range(args.steps):
        ids = jnp.asarray(data[i, :, :-1], jnp.int32)
        labels = jnp.asarray(data[i, :, 1:], jnp.int32)
        (
            embed_params, stacked_stage, head_params,
            embed_opt_state, stage_opt_state, head_opt_state, loss,
        ) = step(
            embed_params, stacked_stage, head_params,
            embed_opt_state, stage_opt_state, head_opt_state, ids, labels,
        )
        losses.append(float(loss))
        print(f"step {i}: loss {losses[-1]:.4f}", flush=True)
    return losses


if __name__ == "__main__":
    main()
