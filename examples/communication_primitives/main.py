#!/usr/bin/env python3
"""Exercise every eager collective (analog of the reference's
``examples/communication_primitives/main.py``, the 2-node CI smoke test)."""

import jax.numpy as jnp
import numpy as np

import bagua_tpu
from bagua_tpu import ReduceOp


def main():
    group = bagua_tpu.init_process_group()
    n = group.size
    x = jnp.asarray(np.arange(n * 8, dtype=np.float32).reshape(n, 8))

    print("group:", group)
    print("allreduce SUM :", np.asarray(bagua_tpu.allreduce(x, op=ReduceOp.SUM))[0][:4])
    print("allreduce AVG :", np.asarray(bagua_tpu.allreduce(x, op=ReduceOp.AVG))[0][:4])
    print("allgather     :", bagua_tpu.allgather(x).shape)
    print("reducescatter :", bagua_tpu.reducescatter(x).shape)
    print("broadcast     :", np.asarray(bagua_tpu.broadcast(x, src=0))[-1][:4])
    print("alltoall      :", bagua_tpu.alltoall(x).shape)
    print("reduce(dst=0) :", np.asarray(bagua_tpu.reduce(x, dst=0))[0][:4])
    print("scatter(src=0):", bagua_tpu.scatter(x, src=0).shape)
    print("gather(dst=0) :", bagua_tpu.gather(x, dst=0).shape)
    bagua_tpu.barrier()
    print("barrier OK")


if __name__ == "__main__":
    main()
