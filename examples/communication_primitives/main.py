#!/usr/bin/env python3
"""Exercise every eager collective (analog of the reference's
``examples/communication_primitives/main.py``, the 2-node CI smoke test).

Single process: each collective takes/returns the full ``(group.size, ...)``
stack.  Multi-host (launch via ``bagua_tpu.distributed.run`` with
``WORLD_SIZE > 1``): each process passes its *local view* — a stack of its
own ranks' send values (``bagua_tpu.local_ranks``) — and receives its own
ranks' results, exactly like the reference's per-process explicit
collectives (reference ``communication.py:573-1401``)."""

import numpy as np

import bagua_tpu
from bagua_tpu import ReduceOp


def main():
    from bagua_tpu.distributed import init_from_env

    group = init_from_env()
    n = group.size
    mine = bagua_tpu.local_ranks(group) if group.spans_processes else range(n)
    # every rank's send value: rows of an (n, 8) arange, rank r holds row r
    x = np.stack(
        [np.arange(r * 8, (r + 1) * 8, dtype=np.float32) for r in mine]
    )

    print("group:", group, "local ranks:", list(mine))
    print("allreduce SUM :", np.asarray(bagua_tpu.allreduce(x, op=ReduceOp.SUM))[0][:4])
    print("allreduce AVG :", np.asarray(bagua_tpu.allreduce(x, op=ReduceOp.AVG))[0][:4])
    print("allgather     :", np.asarray(bagua_tpu.allgather(x)).shape)
    print("reducescatter :", np.asarray(bagua_tpu.reducescatter(x)).shape)
    print("broadcast     :", np.asarray(bagua_tpu.broadcast(x, src=0))[-1][:4])
    print("alltoall      :", np.asarray(bagua_tpu.alltoall(x)).shape)
    print("reduce(dst=0) :", np.asarray(bagua_tpu.reduce(x, dst=0))[0][:4])
    print("scatter(src=0):", np.asarray(bagua_tpu.scatter(x, src=0)).shape)
    print("gather(dst=0) :", np.asarray(bagua_tpu.gather(x, dst=0)).shape)
    bagua_tpu.barrier(comm=group)
    print("barrier OK")


if __name__ == "__main__":
    main()
