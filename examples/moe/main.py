#!/usr/bin/env python3
"""MoE example (analog of the reference's ``examples/moe/main.py``): a small
classifier with an expert-parallel MoE block, experts excluded from DP sync.

    python examples/moe/main.py --num-experts 8
"""

import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms import Algorithm
from bagua_tpu.communication import ALL_AXES
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.parallel.moe import MoE


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-experts", type=int, default=0, help="0 = one per chip")
    p.add_argument("--steps", type=int, default=50)
    args = p.parse_args()

    group = bagua_tpu.init_process_group()
    n = group.size
    num_experts = args.num_experts or n

    class Model(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = jax.nn.relu(nn.Dense(64)(x))
            h, l_aux = MoE(
                hidden_size=128, num_experts=num_experts, k=1, capacity_factor=2.0,
                ep_size=n, ep_axis=ALL_AXES,
            )(h)
            return nn.Dense(10)(h), l_aux

    model = Model()

    def loss_fn(params, batch):
        x, y = batch
        logits, l_aux = model.apply({"params": params}, x)
        ce = -jnp.mean(jnp.take_along_axis(jax.nn.log_softmax(logits), y[:, None], axis=1))
        return ce + 0.01 * l_aux

    x0 = jnp.zeros((4, 32))
    # per-rank independent expert initialization
    per_rank = [model.init(jax.random.PRNGKey(r), x0)["params"] for r in range(n)]
    base = per_rank[0]
    merged = [
        jax.tree_util.tree_map_with_path(
            lambda path, b, pr: pr if "experts" in jax.tree_util.keystr(path) else b,
            base, per_rank[r],
        )
        for r in range(n)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *merged)

    ddp = DistributedDataParallel(
        loss_fn, optax.adam(1e-3), Algorithm.init("gradient_allreduce"),
        process_group=group, dp_filter=lambda name: "experts" not in name,
    )
    state = ddp.init(stacked_params=stacked)

    rng = np.random.RandomState(0)
    protos = rng.rand(10, 32).astype(np.float32)
    for i in range(args.steps):
        y = rng.randint(0, 10, size=64 * n)
        x = protos[y] + 0.2 * rng.randn(64 * n, 32).astype(np.float32)
        state, losses = ddp.train_step(state, (jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.int32)))
        if i % 10 == 0:
            print(f"step {i}: loss {float(losses.mean()):.4f}")
    print(f"final loss {float(losses.mean()):.6f}")


if __name__ == "__main__":
    main()
