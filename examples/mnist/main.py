#!/usr/bin/env python3
"""MNIST-style example (analog of the reference's ``examples/mnist/main.py``).

Uses a synthetic MNIST-shaped classification task (zero-egress environment),
a small ConvNet, and any registered algorithm:

    python examples/mnist/main.py --algorithm gradient_allreduce --epochs 2
"""

import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.ddp import DistributedDataParallel


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Conv(32, (3, 3))(x)
        x = jax.nn.relu(nn.max_pool(x, (2, 2), strides=(2, 2)))
        x = nn.Conv(64, (3, 3))(x)
        x = jax.nn.relu(nn.max_pool(x, (2, 2), strides=(2, 2)))
        x = x.reshape((x.shape[0], -1))
        x = jax.nn.relu(nn.Dense(128)(x))
        return nn.Dense(10)(x)


def synthetic_mnist(n=4096, seed=0):
    """Separable synthetic digits: class-dependent blob patterns."""
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, 10, size=n)
    protos = rng.rand(10, 28, 28, 1).astype(np.float32)
    xs = protos[ys] + 0.3 * rng.randn(n, 28, 28, 1).astype(np.float32)
    return xs.astype(np.float32), ys.astype(np.int32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--algorithm", default="gradient_allreduce")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args()

    group = bagua_tpu.init_process_group()
    model = Net()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))["params"]

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return -jnp.mean(
            jnp.take_along_axis(jax.nn.log_softmax(logits), y[:, None], axis=1)
        )

    algo = build_algorithm(args.algorithm, lr=args.lr, qadam_warmup_steps=20)
    opt = None if args.algorithm == "qadam" else optax.adam(args.lr)

    ddp = DistributedDataParallel(loss_fn, opt, algo, process_group=group)
    state = ddp.init(params)

    xs, ys = synthetic_mnist()
    n_batches = len(xs) // args.batch_size
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(len(xs))
        for b in range(n_batches):
            idx = perm[b * args.batch_size : (b + 1) * args.batch_size]
            state, losses = ddp.train_step(state, (jnp.asarray(xs[idx]), jnp.asarray(ys[idx])))
        print(f"epoch {epoch}: loss {float(losses.mean()):.4f}")

    # eval accuracy on the training distribution
    logits = model.apply({"params": ddp.params_unstacked(state)}, jnp.asarray(xs[:1024]))
    acc = float((jnp.argmax(logits, -1) == jnp.asarray(ys[:1024])).mean())
    print(f"final train-accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
