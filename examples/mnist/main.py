#!/usr/bin/env python3
"""MNIST example (analog of the reference's ``examples/mnist/main.py``).

A small ConvNet with any registered algorithm.  With ``--data-dir`` pointing
at the official IDX files (``train-images-idx3-ubyte[.gz]`` +
``train-labels-idx1-ubyte[.gz]``, the format torchvision downloads), the run
uses REAL MNIST; otherwise a synthetic MNIST-shaped task (zero-egress CI
path):

    python examples/mnist/main.py --algorithm gradient_allreduce --epochs 2
    python examples/mnist/main.py --data-dir /data/mnist
"""

import argparse
import gzip
import os
import struct

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.ddp import DistributedDataParallel


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Conv(32, (3, 3))(x)
        x = jax.nn.relu(nn.max_pool(x, (2, 2), strides=(2, 2)))
        x = nn.Conv(64, (3, 3))(x)
        x = jax.nn.relu(nn.max_pool(x, (2, 2), strides=(2, 2)))
        x = x.reshape((x.shape[0], -1))
        x = jax.nn.relu(nn.Dense(128)(x))
        return nn.Dense(10)(x)


def _read_idx(path):
    """Official IDX format (http://yann.lecun.com/exdb/mnist/): big-endian
    magic (2 type bytes + ndim), then per-dim sizes, then raw u8 data."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0 or dtype != 0x08:
            raise ValueError(f"{path}: not a u8 IDX file")
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(shape)


def real_mnist(data_dir):
    """Load the official train split from IDX files (plain or .gz)."""
    def find(stem):
        for suffix in ("", ".gz"):
            p = os.path.join(data_dir, stem + suffix)
            if os.path.exists(p):
                return p
        raise FileNotFoundError(f"{stem}[.gz] not found under {data_dir}")

    xs = _read_idx(find("train-images-idx3-ubyte")).astype(np.float32)
    xs = (xs / 255.0 - 0.1307) / 0.3081  # torchvision normalization
    ys = _read_idx(find("train-labels-idx1-ubyte")).astype(np.int32)
    return xs[..., None], ys


def synthetic_mnist(n=4096, seed=0):
    """Separable synthetic digits: class-dependent blob patterns."""
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, 10, size=n)
    protos = rng.rand(10, 28, 28, 1).astype(np.float32)
    xs = protos[ys] + 0.3 * rng.randn(n, 28, 28, 1).astype(np.float32)
    return xs.astype(np.float32), ys.astype(np.int32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--algorithm", default="gradient_allreduce")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--data-dir", default=None,
                   help="directory with the official MNIST IDX files; "
                        "synthetic data when omitted")
    args = p.parse_args()

    group = bagua_tpu.init_process_group()
    model = Net()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))["params"]

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return -jnp.mean(
            jnp.take_along_axis(jax.nn.log_softmax(logits), y[:, None], axis=1)
        )

    algo = build_algorithm(args.algorithm, lr=args.lr, qadam_warmup_steps=20)
    opt = None if args.algorithm == "qadam" else optax.adam(args.lr)

    ddp = DistributedDataParallel(loss_fn, opt, algo, process_group=group)
    state = ddp.init(params)

    xs, ys = real_mnist(args.data_dir) if args.data_dir else synthetic_mnist()
    print(f"{len(xs)} samples ({'real' if args.data_dir else 'synthetic'})")
    n_batches = len(xs) // args.batch_size
    if n_batches == 0:
        raise SystemExit(
            f"dataset ({len(xs)} samples) smaller than --batch-size "
            f"{args.batch_size}; lower the batch size"
        )
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(len(xs))
        for b in range(n_batches):
            idx = perm[b * args.batch_size : (b + 1) * args.batch_size]
            state, losses = ddp.train_step(state, (jnp.asarray(xs[idx]), jnp.asarray(ys[idx])))
        print(f"epoch {epoch}: loss {float(losses.mean()):.4f}")

    # eval accuracy on the training distribution
    logits = model.apply({"params": ddp.params_unstacked(state)}, jnp.asarray(xs[:1024]))
    acc = float((jnp.argmax(logits, -1) == jnp.asarray(ys[:1024])).mean())
    print(f"final train-accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
