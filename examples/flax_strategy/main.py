#!/usr/bin/env python3
"""Adopting bagua_tpu from an existing Flax training loop.

The analog of the reference's pytorch-lightning integration
(``strategy=BaguaStrategy(...)``, docs at
``/root/reference/docs/tutorials/bagua_lightning.rst``-era examples): you
already have a ``flax.training.train_state.TrainState`` loop; switch its
data parallelism onto any bagua algorithm with three calls —

    strategy = FlaxBaguaStrategy(loss_fn, algorithm="bytegrad")
    bstate   = strategy.init_from_flax(fstate)     # enter the engine
    bstate,_ = strategy.train_step(bstate, batch)  # your loop, unchanged shape
    fstate   = strategy.to_flax(bstate, fstate)    # checkpoint/eval boundary

Everything else in your stack (orbax checkpoints keyed on the flax state,
eval code calling ``state.apply_fn``) keeps working on the ``to_flax``
output.

    python examples/flax_strategy/main.py --algorithm bytegrad --steps 30
"""

import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state

import bagua_tpu
from bagua_tpu.integrations.flax import FlaxBaguaStrategy


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(128)(x))
        x = nn.relu(nn.Dense(64)(x))
        return nn.Dense(10)(x)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--algorithm", default="gradient_allreduce")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64, help="global batch")
    args = ap.parse_args(argv)

    group = bagua_tpu.init_process_group()
    model = Net()

    # --- the user's pre-existing flax setup, unchanged -----------------
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32)))["params"]
    fstate = train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adamw(1e-3)
    )

    def loss_fn(p, batch):
        x, y = batch
        logits = model.apply({"params": p}, x)
        return -jnp.mean(jnp.take_along_axis(
            jax.nn.log_softmax(logits), y[:, None], axis=1))

    # --- three-call adoption ------------------------------------------
    strategy = FlaxBaguaStrategy(loss_fn, args.algorithm, process_group=group)
    bstate = strategy.init_from_flax(fstate)

    rng = np.random.RandomState(0)
    w = rng.randn(32, 10).astype(np.float32)  # a learnable synthetic task
    for step in range(args.steps):
        x = rng.randn(args.batch, 32).astype(np.float32)
        y = (x @ w).argmax(axis=1).astype(np.int32)
        bstate, losses = strategy.train_step(bstate, (jnp.asarray(x), jnp.asarray(y)))
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:3d}  loss {float(jnp.mean(losses)):.4f}")

    fstate = strategy.to_flax(bstate, fstate)
    strategy.shutdown()
    # flax-ecosystem exit: the returned state drives apply_fn / checkpoints
    acc_x = rng.randn(512, 32).astype(np.float32)
    acc_y = (acc_x @ w).argmax(axis=1)
    preds = fstate.apply_fn({"params": fstate.params}, jnp.asarray(acc_x)).argmax(axis=1)
    print(f"final step {int(fstate.step)}  synthetic accuracy "
          f"{float((np.asarray(preds) == acc_y).mean()):.2%}")


if __name__ == "__main__":
    main()
