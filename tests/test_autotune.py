"""Autotune service tests (CPU-only tier, like reference ``tests/service``).

The main test mirrors the reference's ``MockBaguaProcess`` pattern
(``tests/service/test_autotune_service.py:29-102``): register fake tensor
declarations, report a synthetic concave score peaking at 20 MB buckets, and
assert the optimizer converges near the peak.
"""

import time

import numpy as np
import pytest

from bagua_tpu.defs import BaguaHyperparameter, TensorDeclaration
from bagua_tpu.service.autotune_client import AutotuneClient
from bagua_tpu.service.autotune_service import AutotuneService, start_autotune_server
from bagua_tpu.service.bayesian_optimizer import BayesianOptimizer, BoolParam, IntParam


def synthetic_score(bucket_size_bytes: int, hierarchical: bool) -> float:
    """Concave in log2(bucket size), peak at 2^21 * 10 ≈ 20 MB; hierarchy
    adds a small bonus (reference test peaks near 20MB too)."""
    p = np.log2(bucket_size_bytes)
    return float(100.0 - (p - np.log2(20 * 1024 ** 2)) ** 2 + (1.0 if hierarchical else 0.0))


def test_bayesian_optimizer_converges():
    opt = BayesianOptimizer(
        [IntParam("bucket_size_2p", 10, 31), BoolParam("is_hierarchical_reduce")],
        n_initial_points=5,
        seed=1,
    )
    for _ in range(40):
        params = opt.ask()
        score = synthetic_score(1 << params["bucket_size_2p"], bool(params["is_hierarchical_reduce"]))
        opt.tell(params, score)
    best, best_score = opt.best()
    # peak at log2(20 MiB) = 24.32
    assert abs(best["bucket_size_2p"] - 24.32) <= 1.5, best
    assert best["is_hierarchical_reduce"] == 1


def test_bayesian_optimizer_initial_walk_is_deterministic_and_duplicate_free():
    """The initial phase walks a seeded permutation: two optimizers with the
    same seed propose the same sequence, and no point is proposed twice —
    every duplicate would cost the client a re-jit it already paid for."""
    space = [IntParam("bucket_size_2p", 10, 31), BoolParam("is_hierarchical_reduce")]

    def walk(seed, n=8):
        opt = BayesianOptimizer(space, n_initial_points=n, seed=seed)
        seen = []
        for _ in range(n):
            p = opt.ask()
            seen.append(tuple(sorted(p.items())))
            opt.tell(p, 1.0)  # flat score: EI adds no signal
        return seen

    a, b = walk(seed=7), walk(seed=7)
    assert a == b, "same seed must give the same initial proposals"
    assert len(set(a)) == len(a), "initial walk re-proposed a point"
    assert walk(seed=8) != a, "different seeds should explore differently"


def test_bayesian_optimizer_ei_never_reproposes_explored_points():
    opt = BayesianOptimizer([IntParam("x", 0, 7)], n_initial_points=2, seed=0)
    seen = set()
    for _ in range(8):  # exhaust the whole 8-point grid
        p = opt.ask()
        assert p["x"] not in seen, "explored point re-proposed"
        seen.add(p["x"])
        opt.tell(p, float(p["x"]))
    assert seen == set(range(8))
    # everything explored: ask() must still answer (best-EI fallback)
    assert 0 <= opt.ask()["x"] <= 7


def test_bayesian_optimizer_warm_start_served_first():
    opt = BayesianOptimizer(
        [IntParam("bucket_size_2p", 10, 31), BoolParam("is_hierarchical_reduce")],
        n_initial_points=4, seed=0,
    )
    warm = [
        {"bucket_size_2p": 24, "is_hierarchical_reduce": 1},
        {"bucket_size_2p": 25, "is_hierarchical_reduce": 0},
    ]
    opt.warm_start(warm)
    first = opt.ask()
    assert first == warm[0]
    opt.tell(first, 5.0)
    # the already-told head is skipped if re-queued; the next pending serves
    opt.warm_start([warm[0]])
    assert opt.ask() == warm[1]


def fake_decls(n=6):
    return [
        TensorDeclaration(name=f"t{i}", num_elements=1 << 18, dtype="f32")
        for i in range(n)
    ]


@pytest.fixture()
def server():
    service = AutotuneService(
        world_size=1,
        autotune_level=1,
        max_samples=30,
        sampling_confidence_time_s=0.0,
        warmup_time_s=0.0,
    )
    srv = start_autotune_server(service, port=0)
    client = AutotuneClient(port=srv.server_address[1])
    yield service, client
    srv.shutdown()


def test_service_end_to_end_converges(server):
    service, client = server
    assert client.wait_until_ready(5.0)
    hp = client.register_tensors("mock_model", fake_decls())
    assert hp.buckets, "initial bucket assignment expected"

    for it in range(35):
        score = synthetic_score(hp.bucket_size, hp.is_hierarchical_reduce)
        client.report_metrics("mock_model", 0, it, score)
        hp, completed = client.ask_hyperparameters("mock_model", 0, it)
        if completed:
            break
    assert completed
    # locked to the best seen: near the 20 MiB peak (log2 = 24.32)
    assert abs(np.log2(hp.bucket_size) - 24.32) <= 2.5


def test_warmup_gating():
    service = AutotuneService(
        world_size=1, autotune_level=1, max_samples=10,
        sampling_confidence_time_s=0.0, warmup_time_s=3600.0,
    )
    srv = start_autotune_server(service, port=0)
    try:
        client = AutotuneClient(port=srv.server_address[1])
        assert client.wait_until_ready(5.0)
        hp0 = client.register_tensors("m", fake_decls())
        client.report_metrics("m", 0, 1, 10.0)
        hp1, completed = client.ask_hyperparameters("m", 0, 1)
        # still in warmup: nothing sampled, hyperparameters unchanged
        assert not completed
        assert hp1.bucket_size == hp0.bucket_size
        assert service._managers["m"].sampling_counter == 0
    finally:
        srv.shutdown()


def test_execution_order_reorders_buckets(server):
    service, client = server
    client.register_tensors("om", fake_decls(3))
    spans = [
        {"action": "tensor_ready", "tensor_name": "t2", "start_time": 1},
        {"action": "tensor_ready", "tensor_name": "t0", "start_time": 2},
        {"action": "tensor_ready", "tensor_name": "t1", "start_time": 3},
    ]
    client.report_tensor_execution_order("om", spans)
    mgr = service._managers["om"]
    ordered = [td.name for td in mgr.ordered_tensor_list()]
    assert ordered == ["t2", "t0", "t1"]


@pytest.mark.slow
def test_autotune_session_rebuckets(group):
    """End-to-end: DDP + AutotuneSession against a live service re-buckets."""
    import jax
    import jax.numpy as jnp
    import optax

    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.ddp import AutotuneSession, DistributedDataParallel
    from bagua_tpu.models.mlp import init_mlp, mse_loss

    service = AutotuneService(
        world_size=1, autotune_level=1, max_samples=5,
        sampling_confidence_time_s=0.0, warmup_time_s=0.0,
    )
    srv = start_autotune_server(service, port=0)
    try:
        client = AutotuneClient(port=srv.server_address[1])
        params = init_mlp(jax.random.PRNGKey(0), [16, 64, 64, 4])
        ddp = DistributedDataParallel(
            mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(), process_group=group,
            bucket_size_bytes=1 << 10,  # tiny start: several buckets
        )
        state = ddp.init(params)
        session = AutotuneSession(ddp, "ddp_model", client=client, interval=2)
        n0 = ddp.plan.num_buckets
        rng = np.random.RandomState(0)
        for i in range(8):
            batch = (
                jnp.asarray(rng.randn(16, 16), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            )
            state, _ = ddp.train_step(state, batch)
            session.tick(16)
        # service proposes >=1MB buckets -> single bucket; plan must change
        assert ddp.plan.num_buckets != n0
        # training still works after re-bucketing
        state, losses = ddp.train_step(
            state,
            (jnp.asarray(rng.randn(16, 16), np.float32), jnp.asarray(rng.randn(16, 4), np.float32)),
        )
        assert np.isfinite(np.asarray(losses)).all()
    finally:
        srv.shutdown()


@pytest.mark.slow
def test_profile_bucket_order_measures_backward_depth(group):
    """Measured bucket costs reflect real backward depth: the first layer's
    gradients (deepest in backprop) cost more than the last layer's — the
    measurement the circular plan-order report could never make."""
    import jax
    import jax.numpy as jnp
    import optax

    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.models.mlp import init_mlp, mse_loss

    params = init_mlp(jax.random.PRNGKey(0), [64, 768, 768, 768, 768, 8])
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(), process_group=group,
        bucket_size_bytes=1,  # one leaf per bucket
    )
    state = ddp.init(params)
    rng = np.random.RandomState(0)
    batch = (
        jnp.asarray(rng.randn(64, 64), np.float32),
        jnp.asarray(rng.randn(64, 8), np.float32),
    )
    t1 = ddp.profile_bucket_order(state, batch)
    t2 = ddp.profile_bucket_order(state, batch)
    times = [min(a, b) for a, b in zip(t1, t2)]  # noise floor

    def bucket_of(fragment):
        for i, spec in enumerate(ddp.plan.specs):
            if any(fragment in slot.name and "'w'" in slot.name for slot in spec.slots):
                return i
        raise AssertionError(fragment)

    assert times[bucket_of("layer0")] > times[bucket_of("layer4")], times


def test_profile_single_probe_machinery(group):
    """The one-compile probe's label join works on any backend: every bucket
    gets a ``bagua_probe/bucket=<i>`` scope that survives XLA fusion into the
    device trace, and arrivals come back attributed per bucket.  (Whether the
    timestamps reflect readiness is a scheduler property — only the TPU
    latency-hiding scheduler guarantees it, hence ``method="auto"`` picks the
    pruned probe on hosts; see ``profile_bucket_order``.)"""
    import jax
    import jax.numpy as jnp
    import optax

    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.models.mlp import init_mlp, mse_loss

    params = init_mlp(jax.random.PRNGKey(0), [16, 64, 64, 4])
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(), process_group=group,
        bucket_size_bytes=1 << 10,
    )
    state = ddp.init(params)
    rng = np.random.RandomState(0)
    batch = (
        jnp.asarray(rng.randn(16, 16), np.float32),
        jnp.asarray(rng.randn(16, 4), np.float32),
    )
    times, capture = ddp.profile_bucket_order(
        state, batch, return_capture=True, method="single_probe"
    )
    assert len(times) == ddp.plan.num_buckets
    assert all(t >= 0.0 for t in times)
    assert capture["method"] == "single_probe"
    assert capture["labeled_buckets"] == ddp.plan.num_buckets
    assert "bagua_probe/bucket=0" in capture["hlo_text"]
    # auto on a host backend routes to the pruned probe
    t2, cap2 = ddp.profile_bucket_order(state, batch, return_capture=True)
    assert cap2["method"] == "pruned_per_bucket" and len(t2) == len(times)


@pytest.mark.slow
def test_session_profile_reports_measured_order(group):
    """profile_and_report ships measured spans; the service's learned partial
    order puts early-ready (late-layer) tensors first even though they were
    declared last."""
    import jax
    import jax.numpy as jnp
    import optax

    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.ddp import AutotuneSession, DistributedDataParallel
    from bagua_tpu.models.mlp import init_mlp, mse_loss

    service = AutotuneService(world_size=1, autotune_level=1)
    srv = start_autotune_server(service, port=0)
    try:
        client = AutotuneClient(port=srv.server_address[1])
        params = init_mlp(jax.random.PRNGKey(0), [64, 768, 768, 768, 768, 8])
        ddp = DistributedDataParallel(
            mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(),
            process_group=group, bucket_size_bytes=1,
        )
        state = ddp.init(params)
        session = AutotuneSession(ddp, "prof_model", client=client)
        rng = np.random.RandomState(0)
        batch = (
            jnp.asarray(rng.randn(64, 64), np.float32),
            jnp.asarray(rng.randn(64, 8), np.float32),
        )
        session.profile_and_report(state, batch)
        assert session.profiled
        order = service._managers["prof_model"].tensor_partial_order
        assert order, "no measured order arrived at the service"
        w0 = next(k for k in order if "layer0" in k and "'w'" in k)
        w4 = next(k for k in order if "layer4" in k and "'w'" in k)
        assert order[w4] < order[w0]  # late layer ready earlier
    finally:
        srv.shutdown()


def test_plan_changes_are_step_agreed_under_drift():
    """Ranks must adopt each sampled plan at the same train_iter even when
    one rank's host loop runs rounds ahead (async dispatch drift) — the
    effective-from history guarantees identical answers per iter."""
    from bagua_tpu.defs import TensorDeclaration

    svc = AutotuneService(
        world_size=2, autotune_level=1, warmup_time_s=0,
        sampling_confidence_time_s=0, max_samples=4,
    )
    srv = start_autotune_server(svc, port=0)
    try:
        c = AutotuneClient(port=srv.server_address[1])
        decls = [
            TensorDeclaration(name=f"t{i}", num_elements=256, dtype="f32")
            for i in range(6)
        ]
        c.register_tensors("drift", decls)
        seen = {0: {}, 1: {}}

        def ask(rank, it):
            c.report_metrics("drift", rank, it, 100.0)
            hp, done = c.ask_hyperparameters("drift", rank, it)
            seen[rank][it] = (len(hp.buckets), hp.bucket_size, done)

        for it in range(1, 10):  # rank 0 races two rounds ahead
            ask(0, it)
            if it >= 3:
                ask(1, it - 2)
        for it in range(8, 10):
            ask(1, it)

        common = sorted(set(seen[0]) & set(seen[1]))
        assert len(common) >= 9
        for it in common:
            assert seen[0][it] == seen[1][it], (it, seen[0][it], seen[1][it])
        # sampling really happened and eventually locked
        assert svc._managers["drift"].sampling_counter == 4
        assert any(done for (_, _, done) in seen[0].values())
    finally:
        srv.shutdown()


def test_wire_dtype_knob_opt_in():
    """With tune_wire_dtype the optimizer explores wire_bf16 and the service
    reports it in proposals; without it the field stays at its False default."""
    service = AutotuneService(
        world_size=1, autotune_level=1, max_samples=25,
        sampling_confidence_time_s=0.0, warmup_time_s=0.0, tune_wire_dtype=True,
    )
    srv = start_autotune_server(service, port=0)
    try:
        client = AutotuneClient(port=srv.server_address[1])
        assert client.wait_until_ready(5.0)
        hp = client.register_tensors("wm", fake_decls())
        seen_bf16 = set()
        for it in range(30):
            # synthetic score: bf16 wire is strictly better
            score = synthetic_score(hp.bucket_size, hp.is_hierarchical_reduce)
            score += 25.0 if hp.wire_bf16 else 0.0
            client.report_metrics("wm", 0, it, score)
            hp, completed = client.ask_hyperparameters("wm", 0, it)
            seen_bf16.add(hp.wire_bf16)
            if completed:
                break
        assert completed
        assert seen_bf16 == {False, True}, "knob was never explored"
        assert hp.wire_bf16 is True, "locked hyperparameters missed the bf16 win"
    finally:
        srv.shutdown()


def test_wire_dtype_disabled_by_default(server):
    service, client = server
    hp = client.register_tensors("wd", fake_decls())
    for it in range(12):
        client.report_metrics("wd", 0, it, 1.0)
        hp, _ = client.ask_hyperparameters("wd", 0, it)
        assert hp.wire_bf16 is None  # dimension not tuned
    assert "wire_bf16" not in service._managers["wd"].optimizer.ask()


def test_untuned_service_preserves_user_wire_dtype(group):
    """Autotune without tune_wire_dtype must not clobber an explicitly
    configured wire_dtype on the algorithm."""
    import jax
    import jax.numpy as jnp
    import optax

    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.ddp import AutotuneSession, DistributedDataParallel
    from bagua_tpu.models.mlp import init_mlp, mse_loss

    service = AutotuneService(
        world_size=1, autotune_level=1, max_samples=3,
        sampling_confidence_time_s=0.0, warmup_time_s=0.0,
    )
    srv = start_autotune_server(service, port=0)
    try:
        client = AutotuneClient(port=srv.server_address[1])
        params = init_mlp(jax.random.PRNGKey(0), [16, 32, 4])
        ddp = DistributedDataParallel(
            mse_loss, optax.sgd(0.05),
            GradientAllReduceAlgorithm(wire_dtype=jnp.bfloat16), process_group=group,
        )
        state = ddp.init(params)
        session = AutotuneSession(ddp, "keep_model", client=client, interval=1)
        rng = np.random.RandomState(0)
        for i in range(6):
            batch = (
                jnp.asarray(rng.randn(16, 16), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            )
            state, _ = ddp.train_step(state, batch)
            session.tick(16)
            assert ddp.impl.wire_dtype == jnp.dtype(jnp.bfloat16), (
                "user wire_dtype clobbered by an untuned dimension"
            )
    finally:
        srv.shutdown()


def test_autotune_session_applies_wire_dtype(group):
    """A wire_bf16 proposal flips the gradient_allreduce impl's wire_dtype
    (re-jitting the step) and training continues finite."""
    import jax
    import jax.numpy as jnp
    import optax

    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.ddp import AutotuneSession, DistributedDataParallel
    from bagua_tpu.models.mlp import init_mlp, mse_loss

    service = AutotuneService(
        world_size=1, autotune_level=1, max_samples=40,
        sampling_confidence_time_s=0.0, warmup_time_s=0.0, tune_wire_dtype=True,
    )
    srv = start_autotune_server(service, port=0)
    try:
        client = AutotuneClient(port=srv.server_address[1])
        params = init_mlp(jax.random.PRNGKey(0), [16, 32, 4])
        ddp = DistributedDataParallel(
            mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(), process_group=group,
        )
        state = ddp.init(params)
        session = AutotuneSession(ddp, "wire_model", client=client, interval=1)
        rng = np.random.RandomState(0)
        saw_bf16 = False
        for i in range(25):
            batch = (
                jnp.asarray(rng.randn(16, 16), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            )
            state, losses = ddp.train_step(state, batch)
            assert np.isfinite(np.asarray(losses)).all()
            session.tick(16)
            saw_bf16 = saw_bf16 or ddp.impl.wire_dtype is not None
            if saw_bf16:
                break
        assert saw_bf16, "the optimizer never proposed (or _apply never set) bf16 wire"
        # step still runs with the bf16 wire in force
        state, losses = ddp.train_step(
            state,
            (jnp.asarray(rng.randn(16, 16), np.float32), jnp.asarray(rng.randn(16, 4), np.float32)),
        )
        assert np.isfinite(np.asarray(losses)).all()
    finally:
        srv.shutdown()


def test_first_sample_labeled_with_preconfigured_wire_dtype():
    """A client that starts with bf16 on the wire must have its first score
    credited to wire_bf16=1, not the f32 default."""
    service = AutotuneService(
        world_size=1, autotune_level=1, max_samples=10,
        sampling_confidence_time_s=0.0, warmup_time_s=0.0, tune_wire_dtype=True,
    )
    srv = start_autotune_server(service, port=0)
    try:
        client = AutotuneClient(port=srv.server_address[1])
        assert client.wait_until_ready(5.0)
        hp = client.register_tensors("pre", fake_decls(), current_wire_bf16=True)
        assert hp.wire_bf16 is True
        client.report_metrics("pre", 0, 0, 50.0)
        client.ask_hyperparameters("pre", 0, 0)
        opt = service._managers["pre"].optimizer
        wire_idx = [p.name for p in opt.params].index("wire_bf16")
        assert opt.xs[0][wire_idx] == 1.0
        assert opt.ys[0] == 50.0
    finally:
        srv.shutdown()
