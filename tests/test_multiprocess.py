"""Real multi-process distributed bootstrap.

Spawns two OS processes that rendezvous through
``bagua_tpu.init_process_group(coordinator_address=...)`` (the analog of the
reference's torch-store NCCL-unique-id exchange) on the CPU backend, then
exercise ``broadcast_object`` across processes — the reference test strategy
of simulating multi-node with real processes on one host
(``tests/internal/multi_process.py``).
"""

import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    coordinator, proc_id = sys.argv[1], int(sys.argv[2])
    import bagua_tpu

    group = bagua_tpu.init_process_group(
        coordinator_address=coordinator, num_processes=2, process_id=proc_id
    )
    assert jax.process_count() == 2, jax.process_count()
    assert jax.process_index() == proc_id

    # broadcast a picklable object from process 1 (non-default src)
    obj = {"payload": [proc_id * 10, "hello"], "src": 1} if proc_id == 1 else None
    got = bagua_tpu.broadcast_object(obj, src=1)
    assert got == {"payload": [10, "hello"], "src": 1}, got

    # group spans both processes' devices
    assert group.size == jax.device_count()
    print(f"proc {proc_id} OK size={group.size}")
    """
)


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_rendezvous_and_broadcast_object(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    coordinator = f"127.0.0.1:{free_port()}"
    import os

    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # one device per process
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=150)
        outs.append((p.returncode, out, err))
    for code, out, err in outs:
        assert code == 0, f"worker failed:\n{out}\n{err}"
        assert "OK size=2" in out
