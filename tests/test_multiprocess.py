"""Real multi-process distributed bootstrap.

Spawns two OS processes that rendezvous through
``bagua_tpu.init_process_group(coordinator_address=...)`` (the analog of the
reference's torch-store NCCL-unique-id exchange) on the CPU backend, then
exercise ``broadcast_object`` across processes — the reference test strategy
of simulating multi-node with real processes on one host
(``tests/internal/multi_process.py``).
"""

import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow  # spawns OS-process gangs per test

from helpers import free_port, spawn_and_collect, worker_env

WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    coordinator, proc_id = sys.argv[1], int(sys.argv[2])
    import bagua_tpu

    group = bagua_tpu.init_process_group(
        coordinator_address=coordinator, num_processes=2, process_id=proc_id
    )
    assert jax.process_count() == 2, jax.process_count()
    assert jax.process_index() == proc_id

    # broadcast a picklable object from process 1 (non-default src)
    obj = {"payload": [proc_id * 10, "hello"], "src": 1} if proc_id == 1 else None
    got = bagua_tpu.broadcast_object(obj, src=1)
    assert got == {"payload": [10, "hello"], "src": 1}, got

    # group spans both processes' devices
    assert group.size == jax.device_count()
    print(f"proc {proc_id} OK size={group.size}")
    """
)


def test_two_process_rendezvous_and_broadcast_object(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    coordinator = f"127.0.0.1:{free_port()}"
    outs = spawn_and_collect(
        [[sys.executable, str(script), coordinator, str(i)] for i in range(2)],
        worker_env(), timeout=150,
    )
    for code, out, err in outs:
        assert code == 0, f"worker failed:\n{out}\n{err}"
        assert "OK size=2" in out


DDP_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    coordinator, proc_id = sys.argv[1], int(sys.argv[2])
    import numpy as np
    import optax
    import bagua_tpu
    from bagua_tpu.algorithms import Algorithm
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.models.mlp import init_mlp, mse_loss

    group = bagua_tpu.init_process_group(
        coordinator_address=coordinator, num_processes=2, process_id=proc_id
    )
    assert group.size == 8 and group.spans_processes, group
    assert group.inter_size == 2 and group.intra_size == 4, group

    params = init_mlp(jax.random.PRNGKey(0), [12, 16, 4])  # same seed everywhere
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05),
        Algorithm.init("gradient_allreduce", hierarchical=True),
        process_group=group,
    )
    state = ddp.init(params)

    # each process feeds a DIFFERENT local half of the global batch
    rng = np.random.RandomState(100 + proc_id)
    losses_seen = []
    for step in range(3):
        local = (
            rng.randn(16, 12).astype(np.float32),  # 4 ranks x 4 rows
            rng.randn(16, 4).astype(np.float32),
        )
        state, losses = ddp.train_step(state, ddp.shard_batch(local))
        local_losses = [float(s.data.reshape(-1)[0]) for s in losses.addressable_shards]
        losses_seen.append(local_losses)
    assert all(np.isfinite(l) for ls in losses_seen for l in ls), losses_seen

    # cross-process weight equality: every rank's copy must be identical after
    # hierarchical allreduce -- hash each local shard and allgather the hashes
    from jax.experimental import multihost_utils

    sums = np.array(
        [float(np.asarray(s.data).sum()) for l in jax.tree.leaves(state.params)
         for s in l.addressable_shards],
        dtype=np.float64,
    )
    all_sums = multihost_utils.process_allgather(sums)
    assert all_sums.shape[0] == 2, all_sums.shape
    np.testing.assert_allclose(all_sums[0], all_sums[1], rtol=0, atol=0)
    bagua_tpu.barrier()  # multi-host barrier path (cross-process device sync)
    print(f"proc {proc_id} DDP OK losses={losses_seen[-1]}")
    """
)


def test_two_process_ddp_train_step(tmp_path):
    """Full DDP training across 2 processes x 4 CPU devices: hierarchical
    gradient allreduce rides the inter (cross-process) axis, batches are fed
    per-process via shard_batch, and weights stay bitwise equal across
    processes (the reference bar: 2-node CI training,
    ``benchmark_master.sh:13-21``)."""
    script = tmp_path / "ddp_worker.py"
    script.write_text(DDP_WORKER)
    coordinator = f"127.0.0.1:{free_port()}"
    outs = spawn_and_collect(
        [[sys.executable, str(script), coordinator, str(i)] for i in range(2)],
        worker_env(XLA_FLAGS="--xla_force_host_platform_device_count=4"),
        timeout=240,
    )
    for code, out, err in outs:
        assert code == 0, f"worker failed:\n{out}\n{err}"
        assert "DDP OK" in out


BAGUARUN_WORKER = textwrap.dedent(
    """
    import os
    import jax
    jax.config.update("jax_platforms", "cpu")
    import bagua_tpu
    from bagua_tpu.distributed import init_from_env

    group = init_from_env()
    assert group.size == 2 and jax.process_count() == 2
    got = bagua_tpu.broadcast_object(
        {"from": 0} if jax.process_index() == 0 else None, src=0
    )
    assert got == {"from": 0}
    marker = os.path.join(os.environ["BAGUARUN_WORK"], f"node{os.environ['NODE_RANK']}")
    open(marker, "w").write("ok")
    """
)


def test_baguarun_subprocess_fanout(tmp_path):
    """baguarun analog (reference ``script/baguarun.py:36-113``): fan out one
    ``bagua_tpu.distributed.run`` per host with the right --node_rank.  The
    subprocess launcher simulates two hosts locally; the two single-worker
    gangs rendezvous into one jax.distributed world."""
    script = tmp_path / "worker.py"
    script.write_text(BAGUARUN_WORKER)
    env = worker_env(BAGUARUN_WORK=str(tmp_path))
    r = subprocess.run(
        [
            sys.executable, "-m", "bagua_tpu.distributed.baguarun",
            "--launcher", "subprocess", "--hosts", "hostA hostB",
            "--nproc_per_node", "1", "--master_port", str(free_port()),
            str(script),
        ],
        env=env, capture_output=True, text=True, timeout=180,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert (tmp_path / "node0").exists() and (tmp_path / "node1").exists()


AUTOTUNE_WORKER = textwrap.dedent(
    """
    import os, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax
    import bagua_tpu
    from bagua_tpu.algorithms import Algorithm
    from bagua_tpu.ddp import AutotuneSession, DistributedDataParallel
    from bagua_tpu.distributed import init_from_env
    from bagua_tpu.models.mlp import init_mlp, mse_loss
    from bagua_tpu.service.autotune_client import get_hyperparameters_service_client

    group = init_from_env()
    assert group.size == 2, group
    # the client must resolve the service from launcher-exported env
    client = get_hyperparameters_service_client()
    assert client.wait_until_ready(30), "autotune service unreachable via AUTO_TUNE_SERVER_ADDR"

    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05), Algorithm.init("gradient_allreduce"),
        process_group=group, bucket_size_bytes=1 << 10,
    )
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), [16, 64, 64, 4]))
    n0 = ddp.plan.num_buckets
    session = AutotuneSession(ddp, "mh_model", client=client, interval=1)
    rng = np.random.RandomState(int(os.environ["RANK"]))
    changed = False
    for i in range(80):
        local = (rng.randn(8, 16).astype(np.float32), rng.randn(8, 4).astype(np.float32))
        state, _ = ddp.train_step(state, ddp.shard_batch(local))
        session.tick(16)
        if session.completed or ddp.plan.num_buckets != n0:
            changed = True
            break
        time.sleep(0.02)
    assert changed, "autotune never tuned: the per-rank check board never filled"
    marker = os.path.join(os.environ["AT_WORK"], f"tuned_{os.environ['RANK']}")
    open(marker, "w").write(str(ddp.plan.num_buckets))
    """
)


def test_multiprocess_autotune_tunes(tmp_path):
    """Launcher-hosted autotune service + 2 worker processes: the service's
    per-rank check board only fills because each process reports its own
    jax.process_index() (ADVICE fix), the client resolves the service from
    AUTO_TUNE_SERVER_ADDR, and both workers adopt a re-bucketed plan."""
    script = tmp_path / "worker.py"
    script.write_text(AUTOTUNE_WORKER)
    env = worker_env(AT_WORK=str(tmp_path))  # 1 device per process
    r = subprocess.run(
        [
            sys.executable, "-m", "bagua_tpu.distributed.run",
            "--nproc_per_node", "2", "--autotune_level", "1",
            "--autotune_warmup_time_s", "0", "--autotune_sampling_confidence_time_s", "0",
            "--autotune_max_samples", "3",
            "--master_port", str(free_port()), "--bagua_service_port", str(free_port()),
            "--monitor_interval", "0.2", str(script),
        ],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert (tmp_path / "tuned_0").exists() and (tmp_path / "tuned_1").exists()


EAGER_COLLECTIVES_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import bagua_tpu
    from bagua_tpu import ReduceOp

    coordinator, proc_id = sys.argv[1], int(sys.argv[2])
    group = bagua_tpu.init_process_group(
        coordinator_address=coordinator, num_processes=2, process_id=proc_id
    )
    assert group.size == 8 and group.spans_processes
    mine = bagua_tpu.local_ranks(group)
    assert len(mine) == 4 and all(r // 4 == proc_id for r in mine), mine

    # rank r sends row r of the global (8, 8) arange matrix
    full = np.arange(64, dtype=np.float32).reshape(8, 8)
    x = full[mine]

    out = bagua_tpu.allreduce(x, op=ReduceOp.SUM)
    assert out.shape == (4, 8), out.shape
    np.testing.assert_allclose(out, np.tile(full.sum(0), (4, 1)))

    out = bagua_tpu.allgather(x)
    np.testing.assert_allclose(out, np.tile(full.reshape(-1), (4, 1)))

    out = bagua_tpu.reducescatter(x, op=ReduceOp.SUM)
    # rank r gets chunk r (rows of length 1) of the summed vector
    expect = np.stack([full.sum(0)[r:r + 1] for r in mine])
    np.testing.assert_allclose(out, expect)

    out = bagua_tpu.broadcast(x, src=3)
    np.testing.assert_allclose(out, np.tile(full[3], (4, 1)))

    out = bagua_tpu.alltoall(x)
    # rank r receives element r of every rank's row
    np.testing.assert_allclose(out, full.T[mine])

    out = bagua_tpu.reduce(x, dst=5, op=ReduceOp.SUM)
    for i, r in enumerate(mine):
        np.testing.assert_allclose(out[i], full.sum(0) if r == 5 else full[r])

    out = bagua_tpu.scatter(x, src=2)
    np.testing.assert_allclose(out, full[2].reshape(8, 1)[mine])

    out = bagua_tpu.gather(x, dst=1)
    for i, r in enumerate(mine):
        np.testing.assert_allclose(
            out[i], full.reshape(-1) if r == 1 else np.zeros(64))

    bagua_tpu.barrier()
    print(f"proc {proc_id} eager collectives OK", flush=True)
    """
)


def test_two_process_eager_collectives(tmp_path):
    """VERDICT r2 #6: the user-facing explicit collective set works across
    processes — each process passes its local-view stack and receives its own
    ranks' results, value-checked against the single-controller semantics."""
    script = tmp_path / "worker.py"
    script.write_text(EAGER_COLLECTIVES_WORKER)
    coordinator = f"127.0.0.1:{free_port()}"
    outs = spawn_and_collect(
        [[sys.executable, str(script), coordinator, str(i)] for i in range(2)],
        worker_env(XLA_FLAGS="--xla_force_host_platform_device_count=4"),
        timeout=240,
    )
    for code, out, err in outs:
        assert code == 0, f"worker failed:\n{out}\n{err}"
        assert "eager collectives OK" in out


def test_communication_primitives_example_two_process(tmp_path):
    """The communication_primitives example (reference 2-node CI smoke) runs
    under a real 2-process launch."""
    import os

    from helpers import REPO_ROOT

    env = worker_env(JAX_PLATFORMS="cpu")  # 1 device per process
    # The example is backend-agnostic (no jax.config override of its own), so
    # pin the workers to CPU: drop the axon sitecustomize dir from PYTHONPATH
    # (it force-registers the TPU plugin) and set JAX_PLATFORMS.
    env["PYTHONPATH"] = REPO_ROOT
    r = subprocess.run(
        [
            sys.executable, "-m", "bagua_tpu.distributed.run",
            "--nproc_per_node", "2", "--master_port", str(free_port()),
            "--monitor_interval", "0.2",
            os.path.join(REPO_ROOT, "examples", "communication_primitives", "main.py"),
        ],
        env=env, capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"


SUBGROUP_BARRIER_WORKER = textwrap.dedent(
    """
    import sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import bagua_tpu
    from bagua_tpu.communication import new_group

    coordinator, proc_id = sys.argv[1], int(sys.argv[2])
    bagua_tpu.init_process_group(
        coordinator_address=coordinator, num_processes=3, process_id=proc_id
    )
    if proc_id == 2:
        # outside the subgroup: never calls barrier; a process-global sync
        # here would deadlock the others against this sleep
        time.sleep(8)
        print("proc 2 done (never joined the barrier)", flush=True)
        sys.exit(0)
    sub = new_group(ranks=[0, 1])
    assert sub.spans_processes and sub.size == 2
    t0 = time.monotonic()
    bagua_tpu.barrier(comm=sub)
    dt = time.monotonic() - t0
    assert dt < 6.0, f"barrier waited on the out-of-group process ({dt:.1f}s)"
    print(f"proc {proc_id} subgroup barrier OK in {dt:.2f}s", flush=True)
    """
)


def test_subgroup_barrier_excludes_outside_processes(tmp_path):
    """barrier() on a group spanning a strict subset of processes must
    synchronize only that subset — a process-global sync would deadlock
    against the third process, which never calls it."""
    script = tmp_path / "worker.py"
    script.write_text(SUBGROUP_BARRIER_WORKER)
    coordinator = f"127.0.0.1:{free_port()}"
    outs = spawn_and_collect(
        [[sys.executable, str(script), coordinator, str(i)] for i in range(3)],
        worker_env(),
    )
    for code, out, err in outs:
        assert code == 0, f"worker failed:\n{out}\n{err}"
    assert "proc 0 subgroup barrier OK" in outs[0][1]
    assert "proc 1 subgroup barrier OK" in outs[1][1]
