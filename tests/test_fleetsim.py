"""Fleet-simulator gates: determinism under a fixed seed, exact straggler
attribution, KV-flap absorption by the breaker, preemption staleness, and
the no-exceptions-into-the-step-loop contract — all against a live loopback
rendezvous service driving the real GangAggregator/flight-digest paths."""

import pytest

from bagua_tpu.perflab.fleetsim import (
    BandwidthCollapse,
    FleetConfig,
    FlakyClient,
    KVFlap,
    Preemption,
    Straggler,
    run_fleet,
)


def _cfg(**kw):
    base = dict(n_gangs=2, ranks_per_gang=4, windows=3, seed=11)
    base.update(kw)
    return FleetConfig(**base)


def test_fleet_deterministic_under_fixed_seed():
    cfg = _cfg(faults=(Straggler(gang=0, rank=1, factor=3.0),))
    a = run_fleet(cfg)
    b = run_fleet(cfg)  # fresh server, different real port — same report
    assert a == b
    # and a different seed genuinely changes the modeled clocks
    c = run_fleet(_cfg(seed=12, faults=(Straggler(gang=0, rank=1, factor=3.0),)))
    assert c != a


def test_straggler_attributed_to_exact_injected_rank():
    cfg = _cfg(faults=(Straggler(gang=1, rank=3, factor=3.0, phase="wire"),))
    report = run_fleet(cfg)
    clean, faulty = report["gangs"][0], report["gangs"][1]
    assert clean["straggler_detections"] == []
    dets = faulty["straggler_detections"]
    assert len(dets) == cfg.windows  # flagged in every window
    for d in dets:
        assert d["rank"] == 3
        assert d["phase"] == "wire"
        assert d["score"] >= cfg.straggler_factor
    assert clean["healthy"] and faulty["healthy"]


def test_compute_straggler_attributed_to_compute_phase():
    cfg = _cfg(faults=(Straggler(gang=0, rank=2, factor=3.0, phase="compute"),))
    dets = run_fleet(cfg)["gangs"][0]["straggler_detections"]
    assert dets and all(
        d["rank"] == 2 and d["phase"] == "compute" for d in dets
    )


def test_kv_flap_absorbed_by_breaker_no_training_error():
    cfg = _cfg(faults=(KVFlap(gang=0, start_window=2, end_window=3),))
    report = run_fleet(cfg)
    flapped = report["gangs"][0]
    # the flap reached the transport...
    assert flapped["kv_injected_failures"] > 0
    # ...opened the breaker, which re-closed on the first post-flap probe...
    assert flapped["breaker"]["times_opened"] >= 1
    assert flapped["breaker"]["final_state"] == "closed"
    # ...degraded exactly the flapped window to a local-only view...
    assert flapped["degraded_windows"] == [2]
    # ...and not one exception reached the simulated step loop
    assert flapped["errors"] == []
    assert flapped["healthy"]
    # the untouched gang saw nothing
    assert report["gangs"][1]["degraded_windows"] == []
    assert report["gangs"][1]["breaker"]["times_opened"] == 0


def test_preempted_rank_surfaces_as_stale():
    cfg = _cfg(faults=(Preemption(gang=0, rank=1, window=2),))
    report = run_fleet(cfg)
    windows = report["gangs"][0]["windows"]
    assert windows[0]["stale_ranks"] == []  # pushed normally in window 1
    for w in windows[1:]:  # ghost summary from window 1 must read stale
        assert 1 in w["stale_ranks"], w


def test_bandwidth_collapse_slows_gang_without_straggler_flag():
    """A whole-gang brownout inflates every rank together: the gang median
    moves, the skew doesn't — no false straggler attribution."""
    cfg = _cfg(faults=(BandwidthCollapse(gang=0, factor=4.0),))
    report = run_fleet(cfg)
    collapsed, clean = report["gangs"][0], report["gangs"][1]
    assert collapsed["straggler_detections"] == []
    for w_slow, w_ok in zip(collapsed["windows"], clean["windows"]):
        assert w_slow["p50_skew"] < cfg.straggler_factor
        assert w_ok["p50_skew"] < cfg.straggler_factor
    assert collapsed["healthy"]


def test_flaky_client_contains_injection():
    class Dead:  # the wrapped client is never reached while failing
        def kv_set(self, k, v):
            raise AssertionError("inner client reached during flap")

    fc = FlakyClient(Dead())
    fc.failing = True
    with pytest.raises(ConnectionError):
        fc.kv_set("k", "v")
    assert fc.injected_failures == 1


def test_axis_scoped_collapse_surfaces_in_gang_axis_medians():
    """With per-axis wire spans configured, an axis-scoped collapse inflates
    ONLY that axis's gang median — the signature a per-axis regression
    sentinel attributes — while the whole-gang inflation still never reads
    as a straggler."""
    cfg = _cfg(
        windows=4,
        axis_wire_ms={"dp": 3.0, "tp": 1.0},
        faults=(BandwidthCollapse(gang=0, factor=8.0, axis="tp",
                                  start_window=3, end_window=5),),
    )
    report = run_fleet(cfg)
    collapsed, clean = report["gangs"][0], report["gangs"][1]
    for w in clean["windows"]:
        meas = w["gang_wire_axis_ms"]
        assert set(meas) == {"dp", "tp"}
        assert meas["dp"] == pytest.approx(3.0, rel=0.1)
        assert meas["tp"] == pytest.approx(1.0, rel=0.1)
    for w in collapsed["windows"][:2]:  # pre-fault: nominal on both axes
        assert w["gang_wire_axis_ms"]["tp"] == pytest.approx(1.0, rel=0.1)
    for w in collapsed["windows"][2:]:  # fault: tp x8, dp untouched
        meas = w["gang_wire_axis_ms"]
        assert meas["tp"] == pytest.approx(8.0, rel=0.1)
        assert meas["dp"] == pytest.approx(3.0, rel=0.1)
    assert collapsed["straggler_detections"] == []
    assert collapsed["healthy"]
    # deterministic like every other fleetsim report
    assert run_fleet(cfg) == report


def test_axis_blind_collapse_inflates_every_axis_span():
    cfg = _cfg(
        axis_wire_ms={"dp": 3.0, "tp": 1.0},
        faults=(BandwidthCollapse(gang=0, factor=4.0),),
    )
    report = run_fleet(cfg)
    for w in report["gangs"][0]["windows"]:
        meas = w["gang_wire_axis_ms"]
        assert meas["dp"] == pytest.approx(12.0, rel=0.1)
        assert meas["tp"] == pytest.approx(4.0, rel=0.1)


def test_legacy_scalar_wire_reports_no_axis_medians():
    report = run_fleet(_cfg())
    for gang in report["gangs"]:
        for w in gang["windows"]:
            assert "gang_wire_axis_ms" not in w


def test_transient_straggler_ramps_plateaus_and_heals():
    """The transient profile: onset below the detection threshold (one ramp
    window at half the excess), indictment only at the plateau, and clean
    windows after ``end_window`` — the arc the straggler-tolerance lane's
    degradation ladder rides."""
    fault = Straggler(
        gang=0, rank=1, factor=1.5, phase="compute",
        start_window=2, end_window=5, ramp_windows=1,
    )
    cfg = _cfg(
        n_gangs=1, windows=6, compute_ms=8.0, wire_ms=2.0,
        straggler_factor=1.25, faults=(fault,),
    )
    # the shape of the injected clock: 1.25x compute on the ramp window
    # (1.2 whole-step, below threshold), 1.5x at the plateau (1.4, above)
    assert fault.effective_factor(1) == 1.0
    assert fault.effective_factor(2) == pytest.approx(1.25)
    assert fault.effective_factor(3) == fault.effective_factor(4) == 1.5
    assert fault.effective_factor(5) == 1.0  # healed at end_window

    report = run_fleet(cfg)
    gang = report["gangs"][0]
    dets = gang["straggler_detections"]
    assert [d["window"] for d in dets] == [3, 4], dets
    for d in dets:
        assert d["rank"] == 1 and d["phase"] == "compute"
        assert d["score"] >= cfg.straggler_factor
    # healthy: detections match the expectation derived from the profile
    assert gang["expected_stragglers"] == [[1, "compute"]]
    assert gang["healthy"]


def test_transient_straggler_that_never_plateaus_is_not_expected():
    """A ramp longer than the active span peaks below the detection
    threshold: the verdict must expect (and get) zero detections."""
    fault = Straggler(
        gang=0, rank=1, factor=1.5, phase="compute",
        start_window=2, end_window=4, ramp_windows=8,
    )
    cfg = _cfg(
        n_gangs=1, windows=4, compute_ms=8.0, wire_ms=2.0,
        straggler_factor=1.25, faults=(fault,),
    )
    assert max(fault.effective_factor(w) for w in range(1, 5)) < 1.25
    report = run_fleet(cfg)
    gang = report["gangs"][0]
    assert gang["straggler_detections"] == []
    assert gang["expected_stragglers"] == []
    assert gang["healthy"]
