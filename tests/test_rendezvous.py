"""Cross-host elastic membership: rendezvous store unit tests + the
two-launcher (fake two-host) scale 2 -> 1 -> 2 e2e.

Reference contract: ``bagua/distributed/run.py:116-148`` — on any membership
change ALL workers everywhere are stopped and restarted with fresh
``RANK``/``WORLD_SIZE``; workers checkpoint and resume."""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from helpers import free_port
from bagua_tpu.distributed.rendezvous import (
    RendezvousClient,
    RendezvousState,
    rotated_master_port,
    start_rendezvous_server,
)


# ---------------- state machine ----------------------------------------------


def test_settle_batches_joins_and_assigns_offsets():
    st = RendezvousState(min_nodes=2, settle_s=0.1)
    assert st.join(1, nslots=2, incarnation=5)["accepted"]
    assert not st.assignment()["settled"]  # below min_nodes
    st.join(0, nslots=3, incarnation=9)
    assert not st.assignment()["settled"]  # settle window still open
    time.sleep(0.15)
    asn = st.assignment()
    assert asn["settled"] and asn["generation"] == 1
    assert asn["world_size"] == 5
    # sorted by node_rank, offsets by prefix sum
    assert [(m["node_rank"], m["rank_offset"]) for m in asn["members"]] == [(0, 0), (1, 3)]


def test_reannounce_is_idempotent_but_shrink_bumps_generation():
    st = RendezvousState(min_nodes=1, settle_s=0.05)
    st.join(0, 2, 1)
    time.sleep(0.08)
    g1 = st.assignment()["generation"]
    st.join(0, 2, 1)  # same nslots+incarnation: no membership change
    time.sleep(0.08)
    assert st.assignment()["generation"] == g1
    st.join(0, 1, 1)  # slot benched on the node: membership change
    time.sleep(0.08)
    asn = st.assignment()
    assert asn["generation"] == g1 + 1 and asn["world_size"] == 1


def test_restart_bumps_epoch_only_and_stale_requests_coalesce():
    st = RendezvousState(min_nodes=1, settle_s=0.01)
    st.join(0, 1, 1)
    time.sleep(0.05)
    asn = st.assignment()
    e = asn["epoch"]
    assert st.request_restart(e)["epoch"] == e + 1
    # a second node observed the same pre-restart epoch: no double restart
    assert st.request_restart(e)["epoch"] == e + 1
    assert st.assignment()["generation"] == asn["generation"]  # membership same


def test_crash_origin_first_reporter_wins():
    st = RendezvousState(min_nodes=1, settle_s=0.01)
    st.join(0, 1, 1)
    st.join(1, 1, 1)
    time.sleep(0.05)
    e = st.assignment()["epoch"]
    # node 1's worker crashed first; node 0's died of collateral
    assert st.report_crash(1, e)["origin"] is True
    assert st.report_crash(0, e)["origin"] is False
    assert st.report_crash(1, e)["origin"] is True  # idempotent for the origin
    # stale report after the world moved: nobody new takes blame
    st.request_restart(e)
    assert st.report_crash(0, e)["origin"] is False


def test_completed_leave_does_not_reform_but_crash_leave_does():
    st = RendezvousState(min_nodes=1, settle_s=0.01)
    st.join(0, 1, 1)
    st.join(1, 1, 1)
    time.sleep(0.05)
    g = st.assignment()["generation"]
    st.leave(1, completed=True)
    time.sleep(0.05)
    assert st.assignment()["generation"] == g  # no churn for a finished node
    st.join(1, 1, 2)  # rejoin (new incarnation)
    time.sleep(0.05)
    g2 = st.assignment()["generation"]
    assert g2 > g
    st.leave(1, completed=False)
    time.sleep(0.05)
    assert st.assignment()["generation"] > g2


def test_restart_after_completed_leave_resettles_live_membership():
    """A restart request must not revive a gang that includes a node that
    already left with completed=True (its ranks would never rejoin)."""
    st = RendezvousState(min_nodes=1, settle_s=0.01)
    st.join(0, 1, 1)
    st.join(1, 1, 1)
    time.sleep(0.05)
    asn = st.assignment()
    assert asn["world_size"] == 2
    st.leave(0, completed=True)
    st.request_restart(asn["epoch"])  # node 1 crashed on the final step
    time.sleep(0.05)
    asn2 = st.assignment()
    assert asn2["settled"] and asn2["world_size"] == 1
    assert [m["node_rank"] for m in asn2["members"]] == [1]


def test_ttl_reaps_silent_node():
    st = RendezvousState(min_nodes=1, settle_s=0.01, ttl_s=0.2)
    st.join(0, 1, 1)
    st.join(1, 1, 1)
    time.sleep(0.05)
    assert st.assignment()["world_size"] == 2
    t0 = time.time()
    while time.time() - t0 < 2.0:
        st.heartbeat(0)  # node 1 went silent
        time.sleep(0.05)
        asn = st.assignment()
        if asn.get("settled") and asn["world_size"] == 1:
            break
    asn = st.assignment()
    assert asn["settled"] and asn["world_size"] == 1
    assert [m["node_rank"] for m in asn["members"]] == [0]


def test_max_nodes_rejects_extra_join():
    st = RendezvousState(min_nodes=1, max_nodes=2, settle_s=0.01)
    assert st.join(0, 1, 1)["accepted"]
    assert st.join(1, 1, 1)["accepted"]
    assert not st.join(2, 1, 1)["accepted"]


def test_rotated_master_port_skips_reserved():
    reserved = [29501, 29400]
    base = 29501 - 5  # epoch 5 would land exactly on a reserved port
    assert rotated_master_port(base, 5, reserved) not in reserved
    # all hosts at the same epoch compute the same port
    assert rotated_master_port(29500, 7, reserved) == rotated_master_port(29500, 7, reserved)


# ---------------- HTTP server + client ---------------------------------------


def test_client_server_roundtrip():
    st = RendezvousState(min_nodes=2, settle_s=0.05)
    port = free_port()
    server = start_rendezvous_server(st, port, host="127.0.0.1")
    try:
        c0 = RendezvousClient(f"127.0.0.1:{port}", node_rank=0, timeout_s=10)
        c1 = RendezvousClient(f"127.0.0.1:{port}", node_rank=1, timeout_s=10)
        c1.announce(nslots=2, incarnation=7)
        asn = c0.wait_assignment(nslots=1, incarnation=3)
        assert asn["world_size"] == 3
        assert not c0.epoch_changed(asn["epoch"])
        c1.request_restart(asn["epoch"])
        assert c0.epoch_changed(asn["epoch"])
        c0.kv_set("ckpt", {"iter": 4})
        assert c1.kv_get("ckpt") == {"iter": 4}
        assert c1.kv_get("missing") is None
        c0.kv_set("job name/with space?&#", [1, 2])  # keys are URL-encoded
        assert c1.kv_get("job name/with space?&#") == [1, 2]
    finally:
        server.shutdown()


def test_wait_assignment_retries_until_server_appears():
    port = free_port()
    client = RendezvousClient(f"127.0.0.1:{port}", node_rank=0, timeout_s=15)
    st = RendezvousState(min_nodes=1, settle_s=0.05)
    import threading

    started = {}

    def late_start():
        time.sleep(0.6)
        started["server"] = start_rendezvous_server(st, port, host="127.0.0.1")

    threading.Thread(target=late_start, daemon=True).start()
    asn = client.wait_assignment(nslots=1)
    assert asn["settled"] and asn["world_size"] == 1
    started["server"].shutdown()


# ---------------- two-launcher e2e: scale 2 -> 1 -> 2 -------------------------

# One worker slot per fake host.  Node 1's first worker crashes permanently
# (tolerance 1 -> slot benched -> node below its floor -> node LEAVES); node 0
# re-forms alone at world size 1 from the checkpoint; when a fresh node-1
# launcher joins, the store re-forms the gang at world size 2 and training
# resumes from the checkpoint with the state remapped to the new world size.
CROSS_HOST_WORKER = """
import json, os, sys

work = os.environ["ELASTIC_WORK_DIR"]
rank, ws = os.environ["RANK"], int(os.environ["WORLD_SIZE"])
node = os.environ["NODE_RANK"]
crash_flag = os.path.join(work, "crashed")

import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax
from bagua_tpu.algorithms import Algorithm
from bagua_tpu.checkpoint import (
    get_latest_iteration, load_checkpoint, remap_world_size, save_checkpoint,
)
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.distributed import init_from_env
from bagua_tpu.models.mlp import init_mlp, mse_loss

group = init_from_env()
assert group.size == ws, (group, ws)
ddp = DistributedDataParallel(
    mse_loss, optax.sgd(0.1),
    Algorithm.init("gradient_allreduce"), process_group=group,
)
ckpt_dir = os.path.join(work, "ckpt")
start = get_latest_iteration(ckpt_dir) or 0
if start:
    loaded, start = load_checkpoint(ckpt_dir, to_host=True)
    stacked = remap_world_size(loaded, ws, expert_filter=lambda p: False)
    state = ddp.init(stacked_params=jax.tree.map(jnp.asarray, stacked))
else:
    state = ddp.init(params=init_mlp(jax.random.PRNGKey(0), [8, 8, 2]))

rng = np.random.RandomState(7)  # same stream everywhere; slice per process
X = rng.randn(8, 8, 8).astype(np.float32)
Y = rng.randn(8, 8, 2).astype(np.float32)
loss_log = os.path.join(work, "losses.jsonl")
for i in range(start, 8):
    per = 8 // ws
    local = (
        X[i][int(rank) * per:(int(rank) + 1) * per],
        Y[i][int(rank) * per:(int(rank) + 1) * per],
    )
    state, losses = ddp.train_step(state, ddp.shard_batch(local))
    my_loss = float(np.asarray(losses.addressable_shards[0].data).reshape(-1)[0])
    save_checkpoint(i + 1, ckpt_dir, state.params, moe_split=False)
    if rank == "0":
        with open(loss_log, "a") as f:
            f.write(json.dumps({"iter": i + 1, "ws": ws, "loss": my_loss}) + chr(10))
    if ws == 1:
        # Pace the solo phase so the test's fresh node-1 launcher has time to
        # join and trigger the scale-up re-form before training completes.
        # 10s/iter x ~6 solo iters ~= 60s of window: a fresh launcher boots a
        # whole jax process (tens of seconds on a loaded single-core box —
        # 1s/iter was observed losing the race under a concurrent full-suite
        # run).  Passing runs don't pay the full window: the re-form restarts
        # this worker mid-sleep, so the remaining solo iterations never run.
        import time as _t
        _t.sleep(10.0)
    if node == "1" and i >= 1 and not os.path.exists(crash_flag):
        open(crash_flag, "w").write("gone")
        os._exit(7)  # hard node death: no atexit handshakes
open(os.path.join(work, f"finished_node{node}_ws{ws}"), "w").write("ok")
"""


def _launch_node(tmp_path, script, node_rank, ports):
    from helpers import worker_env

    env = worker_env(ELASTIC_WORK_DIR=str(tmp_path))  # 1 device per worker
    return subprocess.Popen(
        [
            sys.executable, "-m", "bagua_tpu.distributed.run",
            "--nnodes", "1:2", "--node_rank", str(node_rank),
            "--nproc_per_node", "1",
            "--slot_failure_tolerance", "1", "--max_restarts", "4",
            "--monitor_interval", "0.2",
            "--rdzv_settle_s", "0.4", "--rdzv_timeout_s", "90",
            "--master_port", str(ports["master"]),
            "--rdzv_port", str(ports["rdzv"]),
            str(script),
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


@pytest.mark.slow
def test_cross_host_elastic_scale_down_then_up(tmp_path):
    """VERDICT r2 #3: two launcher processes (fake hosts) scale 2 -> 1 -> 2
    with checkpointed state carried across every membership change."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(CROSS_HOST_WORKER))
    ports = {"master": free_port(), "rdzv": free_port()}
    node0 = _launch_node(tmp_path, script, 0, ports)
    node1 = _launch_node(tmp_path, script, 1, ports)
    node1b = None
    loss_log = tmp_path / "losses.jsonl"

    def records():
        if not loss_log.exists():
            return []
        return [json.loads(l) for l in loss_log.read_text().splitlines()]

    try:
        # Phase 1+2: gang forms at ws=2, node 1 dies, node 0 continues at ws=1.
        # Generous deadlines: on a loaded single-core box the 4+ processes
        # (2 launchers + workers) serialize their jax inits and recompiles —
        # observed >240s under a concurrent full-suite run; normal pass ~70s.
        deadline = time.time() + 480
        while time.time() < deadline:
            if any(r["ws"] == 1 for r in records()):
                break
            assert node0.poll() is None, node0.communicate()[0]
            time.sleep(0.3)
        assert any(r["ws"] == 1 for r in records()), (
            f"node0 never continued alone; log={records()}\n"
            f"node1 out:\n{node1.communicate()[0] if node1.poll() is not None else '(running)'}"
        )
        assert node1.wait(timeout=60) == 1  # node below its floor: leaves

        # Phase 3: a fresh node-1 launcher joins; gang re-forms at ws=2.
        node1b = _launch_node(tmp_path, script, 1, ports)
        assert node0.wait(timeout=480) == 0, node0.communicate()[0]
        assert node1b.wait(timeout=480) == 0, node1b.communicate()[0]
    finally:
        for p in (node0, node1, node1b):
            if p is not None and p.poll() is None:
                p.kill()

    recs = records()
    ws_seq = [r["ws"] for r in recs]
    assert ws_seq[0] == 2 and 1 in ws_seq and ws_seq[-1] == 2, ws_seq
    assert recs[-1]["iter"] == 8
    # scale-down then scale-up actually happened in that order
    first_ws1 = ws_seq.index(1)
    assert 2 in ws_seq[first_ws1:], ws_seq
    assert (tmp_path / "finished_node0_ws2").exists()
    assert (tmp_path / "finished_node1_ws2").exists()
    # training kept converging across both membership changes
    assert min(r["loss"] for r in recs[-3:]) < recs[0]["loss"]
