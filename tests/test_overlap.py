"""Backward-overlapped execution mode: parity, wire pattern, guard rails.

The overlap mode anchors each bucket's collective inside the backward pass
via a per-bucket ``custom_vjp`` identity (``bucket.wrap_params_for_overlap``).
These tests pin its contract on the 8-device CPU sim:

* numerics match the monolithic ``transform_gradients`` path to float
  tolerance for every fuse × wire-dtype combination;
* the compiled step carries exactly one ``all-reduce`` per bucket;
* ``rebucket()`` re-wraps against the new plan;
* algorithms without ``overlap_exchange`` (or with per-bucket state) reject
  explicit ``overlap=True`` and resolve ``"auto"`` to the monolithic path.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.bucket import BucketPlan
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

N_STEPS = 3
GLOBAL_BATCH = 32
DIM_IN, DIM_OUT = 12, 4
LAYERS = [DIM_IN, 16, 16, DIM_OUT]


def make_data(seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(N_STEPS, GLOBAL_BATCH, DIM_IN).astype(np.float32)
    ys = rng.randn(N_STEPS, GLOBAL_BATCH, DIM_OUT).astype(np.float32)
    return xs, ys


def make_ddp(group, overlap, fuse="tuple", wire=None, bucket_size=1 << 9):
    return DistributedDataParallel(
        mse_loss,
        optax.sgd(0.1),
        GradientAllReduceAlgorithm(fuse=fuse, wire_dtype=wire),
        process_group=group,
        bucket_size_bytes=bucket_size,  # small: forces several buckets
        overlap=overlap,
    )


def run_steps(ddp, params, xs, ys):
    state = ddp.init(params)
    for i in range(len(xs)):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
    return state


def count_allreduces(text):
    return sum(
        1
        for line in text.splitlines()
        if re.search(r"\ball-reduce(-start)?\(", line)
    )


@pytest.mark.parametrize("wire", [None, jnp.bfloat16], ids=["f32", "bf16"])
@pytest.mark.parametrize("fuse", ["tuple", "flat"])
def test_overlap_matches_monolithic(group, fuse, wire):
    """Acceptance: overlap == monolithic to float tolerance for all four
    fuse × wire-dtype combos.  Both paths run the same per-bucket cast →
    reduce → cast-back, just anchored at different program points, so even
    the bf16 wire pairs stay within a few ulps of each other."""
    params = init_mlp(jax.random.PRNGKey(11), LAYERS)
    xs, ys = make_data(seed=11)
    finals = {}
    for overlap in (False, True):
        ddp = make_ddp(group, overlap, fuse=fuse, wire=wire)
        state = run_steps(ddp, params, xs, ys)
        assert ddp.plan.num_buckets > 1
        assert ddp.overlap_enabled is overlap
        finals[overlap] = ddp.params_unstacked(state)
    tol = dict(rtol=1e-5, atol=1e-6) if wire is None else dict(rtol=1e-2, atol=1e-3)
    for a, b in zip(jax.tree.leaves(finals[False]), jax.tree.leaves(finals[True])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **tol)


@pytest.mark.parametrize("fuse", ["tuple", "flat"])
def test_census_one_allreduce_per_bucket(group, fuse):
    """The overlap wire pattern: one collective per bucket, none merged into
    a monolithic tail exchange (ci/perf_audit.py asserts the same on VGG16).
    The flat fuse materializes each bucket buffer, so the count is exactly
    ``len(plan.specs)`` on every backend.  The tuple fuse issues one
    *variadic* psum per bucket; backends without variadic all-reduce
    (XLA:CPU) legalize it to one all-reduce per operand — per-slot — so for
    tuple we accept either form and additionally pin the overlap census to
    the monolithic one (same wire ops, only their anchor moves)."""

    def compiled_text(overlap):
        ddp = make_ddp(group, overlap, fuse=fuse)
        state = ddp.init(params)
        fn = ddp._build_step(ddp.impl.step_variant(0))
        lowered = fn.lower(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
        return ddp.plan, lowered.compile().as_text()

    params = init_mlp(jax.random.PRNGKey(12), LAYERS)
    xs, ys = make_data(seed=12)
    plan, text = compiled_text(True)
    assert plan.num_buckets > 1
    n = count_allreduces(text)
    if fuse == "flat":
        assert n == len(plan.specs)
    else:
        n_slots = sum(len(s.slots) for s in plan.specs)
        assert n in (len(plan.specs), n_slots)
    _, mono_text = compiled_text(False)
    assert n == count_allreduces(mono_text)


def test_backward_order_is_reverse_topological(group):
    params = init_mlp(jax.random.PRNGKey(13), LAYERS)
    plan = BucketPlan.from_tree(params, 1 << 9, align_elems=group.size)
    assert plan.num_buckets > 1
    order = plan.backward_order()
    assert sorted(order) == list(range(plan.num_buckets))
    # Leaf positions in treedef order; buckets must come out latest-first.
    dummy = plan._treedef.unflatten(range(plan._treedef.num_leaves))
    pos = {
        jax.tree_util.keystr(p): i
        for i, (p, _) in enumerate(jax.tree_util.tree_flatten_with_path(dummy)[0])
    }
    latest = [max(pos[s.name] for s in plan.specs[bi].slots) for bi in order]
    assert latest == sorted(latest, reverse=True)


def test_rebucket_rewraps_under_overlap(group):
    """rebucket() under overlap mode must re-derive the custom_vjp wrappers
    from the new plan: the recompiled step carries the new bucket count's
    all-reduces and numerics still match the monolithic path."""
    params = init_mlp(jax.random.PRNGKey(14), LAYERS)
    xs, ys = make_data(seed=14)
    ddp = make_ddp(group, True, fuse="flat")  # flat: exact per-bucket census
    state = ddp.init(params)
    state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
    old_n = ddp.plan.num_buckets

    new_plan = BucketPlan.from_tree(params, 1 << 20, align_elems=group.size)
    ddp.rebucket(new_plan)
    assert ddp.plan.num_buckets != old_n
    fn = ddp._build_step(ddp.impl.step_variant(1))
    text = fn.lower(state, (jnp.asarray(xs[1]), jnp.asarray(ys[1]))).compile().as_text()
    assert count_allreduces(text) == len(new_plan.specs)

    for i in range(1, N_STEPS):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))

    # Bucket layout never changes allreduce numerics, so the monolithic run
    # without any rebucket is the oracle.
    mono = make_ddp(group, False)
    mono_state = run_steps(mono, params, xs, ys)
    got, expect = ddp.params_unstacked(state), mono.params_unstacked(mono_state)
    for a, b in zip(jax.tree.leaves(expect), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_overlap_rejected_without_support(group):
    """Guard rails: explicit overlap=True needs overlap_exchange, and the
    rejection names the algorithm class and the concrete reason; 'auto'
    degrades to monolithic for unsupported or non-numerics-preserving
    algorithms."""
    # No overlap_exchange hook at all → named rejection.
    with pytest.raises(ValueError, match="AlgorithmImpl.*overlap_exchange"):
        DistributedDataParallel(
            mse_loss, optax.sgd(0.1), build_algorithm("none"),
            process_group=group, overlap=True,
        )
    with pytest.raises(ValueError, match="overlap must be"):
        make_ddp(group, "yes")

    # Decentralized now reports weight-mode overlap: explicit True accepted,
    # auto on (elementwise exchange — bucket split never changes numerics).
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.1), build_algorithm("decentralized"),
        process_group=group, overlap="auto",
    )
    assert ddp.overlap_enabled is True
    assert ddp.impl.overlap_capability().mode == "weight"

    # Low-precision decentralized: supported (post_step granularity switch)
    # but NOT numerics-preserving — auto must stay monolithic.
    lp = DistributedDataParallel(
        mse_loss, optax.sgd(0.1),
        build_algorithm("low_precision_decentralized"),
        process_group=group, overlap="auto",
    )
    assert lp.overlap_enabled is False
    cap = lp.impl.overlap_capability()
    assert cap.supported and not cap.auto and cap.mode == "post_step"

    assert make_ddp(group, "auto").overlap_enabled is True


def test_auto_never_enables_overlap_for_unstable_step_variant(group):
    """Regression (satellite): an algorithm whose compiled step variant
    changes across steps must never get overlap from 'auto', and explicit
    overlap=True must be rejected with a reason naming the class and the
    step_variant cause — per-bucket backward anchors would be re-traced
    inconsistently across variants."""
    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithmImpl

    class VariantSwitching(GradientAllReduceAlgorithmImpl):
        stable_step_variant = False

        def step_variant(self, step):
            return "even" if step % 2 == 0 else "odd"

    impl = VariantSwitching(group)
    cap = impl.overlap_capability()
    assert not cap.supported
    assert "VariantSwitching" in cap.reason and "step variant" in cap.reason

    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.1), impl, process_group=group, overlap="auto",
    )
    assert ddp.overlap_enabled is False

    with pytest.raises(ValueError, match="VariantSwitching.*step variant"):
        DistributedDataParallel(
            mse_loss, optax.sgd(0.1), VariantSwitching(group),
            process_group=group, overlap=True,
        )
