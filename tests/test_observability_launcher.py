"""Observability (spans, timer, watchdog) and elastic launcher tests."""

import os
import subprocess
import sys
import textwrap
import time

import pytest

from bagua_tpu.observability import SpanRecorder, StepTimer, Watchdog
from bagua_tpu.utils import SpeedMeter


def test_span_recorder_plan_order():
    import jax.numpy as jnp

    from bagua_tpu.bucket import BucketPlan

    tree = {"a": jnp.zeros((4,)), "b": jnp.zeros((4,)), "c": jnp.zeros((4,))}
    plan = BucketPlan.from_tree(tree, bucket_size_bytes=1)
    rec = SpanRecorder()
    rec.record_plan_order(plan)
    spans = rec.drain()
    assert len(spans) == 3
    assert [s["action"] for s in spans] == ["tensor_ready"] * 3
    starts = [s["start_time"] for s in spans]
    assert starts == sorted(starts)
    assert rec.drain() == []


def test_step_timer():
    timer = StepTimer(speed_meter=SpeedMeter())
    with timer.step(n_samples=32):
        time.sleep(0.01)
    assert timer.n_steps == 1
    assert timer.last_step_time >= 0.01
    assert timer.mean_step_time > 0


def test_watchdog_fires_and_disarms():
    fired = []
    wd = Watchdog(timeout_s=0.2, check_interval_s=0.05, on_timeout=lambda s: fired.append(s)).start()
    wd.beat()
    time.sleep(0.6)
    assert fired, "watchdog should have fired"
    wd.stop()


def test_watchdog_quiet_while_beating():
    fired = []
    wd = Watchdog(timeout_s=0.5, check_interval_s=0.05, on_timeout=lambda s: fired.append(s)).start()
    for _ in range(8):
        wd.beat()
        time.sleep(0.05)
    assert not fired
    wd.stop()


def test_watchdog_not_armed_before_first_beat():
    fired = []
    wd = Watchdog(timeout_s=0.1, check_interval_s=0.05, on_timeout=lambda s: fired.append(s)).start()
    time.sleep(0.3)
    assert not fired  # never armed
    wd.stop()


# ---------------- launcher ----------------------------------------------------


def run_launcher(tmp_path, script_body: str, extra_args=None, max_restarts=1):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(script_body))
    cmd = [
        sys.executable, "-m", "bagua_tpu.distributed.run",
        "--nproc_per_node", "2", "--max_restarts", str(max_restarts),
        "--monitor_interval", "0.2",
    ] + (extra_args or []) + [str(script)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(cmd, env=env, capture_output=True, text=True, timeout=120)


def test_launcher_success(tmp_path):
    marker = tmp_path / "ok"
    r = run_launcher(
        tmp_path,
        f"""
        import os
        rank = os.environ["RANK"]; ws = os.environ["WORLD_SIZE"]
        assert ws == "2"
        assert os.environ["LOCAL_WORLD_SIZE"] == "2"
        open(r"{marker}" + rank, "w").write("done")
        """,
    )
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "ok0").exists() and (tmp_path / "ok1").exists()


def test_launcher_restart_then_success(tmp_path):
    """First attempt fails (rank 1 exits 1); restart succeeds — the
    checkpoint-restart elastic pattern."""
    flag = tmp_path / "attempted"
    r = run_launcher(
        tmp_path,
        f"""
        import os, sys
        flag = r"{flag}" + os.environ["RANK"]
        if not os.path.exists(flag):
            open(flag, "w").write("x")
            if os.environ["RANK"] == "1":
                sys.exit(1)
        """,
        max_restarts=2,
    )
    assert r.returncode == 0, r.stderr


def test_launcher_exceeds_max_restarts(tmp_path):
    r = run_launcher(tmp_path, "import sys; sys.exit(3)", max_restarts=1)
    assert r.returncode == 1
    assert "exceeded max_restarts" in r.stderr + r.stdout
