"""Observability (spans, timer, watchdog) and elastic launcher tests."""

import os
import subprocess
import sys
import textwrap
import time

import pytest

pytestmark = pytest.mark.slow  # elastic launcher gangs (subprocess)

from bagua_tpu.observability import SpanRecorder, StepTimer, Watchdog
from bagua_tpu.utils import SpeedMeter


def test_span_recorder_measured_order():
    """Measured per-bucket costs become tensor_ready spans whose start times
    sort tensors into the measured readiness order (cheap buckets first)."""
    import jax.numpy as jnp

    from bagua_tpu.bucket import BucketPlan

    tree = {"a": jnp.zeros((4,)), "b": jnp.zeros((4,)), "c": jnp.zeros((4,))}
    plan = BucketPlan.from_tree(tree, bucket_size_bytes=1)
    assert plan.num_buckets == 3
    rec = SpanRecorder()
    rec.record_measured_order(plan, [0.03, 0.01, 0.02])  # bucket 1 is cheapest
    spans = rec.drain()
    assert len(spans) == 3
    assert [s["action"] for s in spans] == ["tensor_ready"] * 3
    by_start = [s["tensor_name"] for s in sorted(spans, key=lambda s: s["start_time"])]
    slot_names = [spec.slots[0].name for spec in plan.specs]
    assert by_start == [slot_names[1], slot_names[2], slot_names[0]]
    assert rec.drain() == []


def test_step_timer():
    timer = StepTimer(speed_meter=SpeedMeter())
    with timer.step(n_samples=32):
        time.sleep(0.01)
    assert timer.n_steps == 1
    assert timer.last_step_time >= 0.01
    assert timer.mean_step_time > 0


def test_watchdog_fires_and_disarms(tmp_path):
    fired = []
    wd = Watchdog(timeout_s=0.2, check_interval_s=0.05, on_timeout=lambda s: fired.append(s))
    wd.dump_dir = str(tmp_path)  # the timeout path now leaves evidence files
    wd.start()
    wd.beat()
    time.sleep(0.6)
    assert fired, "watchdog should have fired"
    wd.stop()


def test_watchdog_quiet_while_beating():
    fired = []
    wd = Watchdog(timeout_s=0.5, check_interval_s=0.05, on_timeout=lambda s: fired.append(s)).start()
    for _ in range(8):
        wd.beat()
        time.sleep(0.05)
    assert not fired
    wd.stop()


def test_watchdog_not_armed_before_first_beat():
    fired = []
    wd = Watchdog(timeout_s=0.1, check_interval_s=0.05, on_timeout=lambda s: fired.append(s)).start()
    time.sleep(0.3)
    assert not fired  # never armed
    wd.stop()


# ---------------- launcher ----------------------------------------------------


def run_launcher(tmp_path, script_body: str, extra_args=None, max_restarts=1):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(script_body))
    cmd = [
        sys.executable, "-m", "bagua_tpu.distributed.run",
        "--nproc_per_node", "2", "--max_restarts", str(max_restarts),
        "--monitor_interval", "0.2",
    ] + (extra_args or []) + [str(script)]
    from helpers import worker_env

    env = worker_env(JAX_PLATFORMS="cpu")
    return subprocess.run(cmd, env=env, capture_output=True, text=True, timeout=120)


def test_launcher_success(tmp_path):
    marker = tmp_path / "ok"
    r = run_launcher(
        tmp_path,
        f"""
        import os
        rank = os.environ["RANK"]; ws = os.environ["WORLD_SIZE"]
        assert ws == "2"
        assert os.environ["LOCAL_WORLD_SIZE"] == "2"
        open(r"{marker}" + rank, "w").write("done")
        """,
    )
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "ok0").exists() and (tmp_path / "ok1").exists()


def test_launcher_restart_then_success(tmp_path):
    """First attempt fails (rank 1 exits 1); restart succeeds — the
    checkpoint-restart elastic pattern."""
    flag = tmp_path / "attempted"
    r = run_launcher(
        tmp_path,
        f"""
        import os, sys
        flag = r"{flag}" + os.environ["RANK"]
        if not os.path.exists(flag):
            open(flag, "w").write("x")
            if os.environ["RANK"] == "1":
                sys.exit(1)
        """,
        max_restarts=2,
    )
    assert r.returncode == 0, r.stderr


def test_launcher_exceeds_max_restarts(tmp_path):
    r = run_launcher(tmp_path, "import sys; sys.exit(3)", max_restarts=1)
    assert r.returncode == 1
    assert "exceeded max_restarts" in r.stderr + r.stdout


ELASTIC_WORKER = """
import json, os, sys

work = os.environ["ELASTIC_WORK_DIR"]
rank, ws = os.environ["RANK"], int(os.environ["WORLD_SIZE"])
crash_flag = os.path.join(work, "crashed")
if rank == "1" and os.path.exists(crash_flag):
    sys.exit(7)  # this slot's capacity is permanently gone

import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax
import bagua_tpu
from bagua_tpu.algorithms import Algorithm
from bagua_tpu.checkpoint import (
    get_latest_iteration, load_checkpoint, remap_world_size, save_checkpoint,
)
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.distributed import init_from_env
from bagua_tpu.models.mlp import init_mlp, mse_loss

group = init_from_env()
assert group.size == ws, (group, ws)
ddp = DistributedDataParallel(
    mse_loss, optax.sgd(0.1),
    Algorithm.init("gradient_allreduce"), process_group=group,
)
ckpt_dir = os.path.join(work, "ckpt")
start = get_latest_iteration(ckpt_dir) or 0
if start:
    # Elastic resume: host-restore ignores the old topology, remap re-stacks
    # the replicated leaves for the new world size.
    loaded, start = load_checkpoint(ckpt_dir, to_host=True)
    stacked = remap_world_size(loaded, ws, expert_filter=lambda p: False)
    state = ddp.init(stacked_params=jax.tree.map(jnp.asarray, stacked))
else:
    state = ddp.init(params=init_mlp(jax.random.PRNGKey(0), [8, 8, 2]))

rng = np.random.RandomState(7)  # same stream everywhere; slice per process
X = rng.randn(8, 8, 8).astype(np.float32)
Y = rng.randn(8, 8, 2).astype(np.float32)
loss_log = os.path.join(work, "losses.jsonl")
for i in range(start, 6):
    per = 8 // ws
    local = (
        X[i][int(rank) * per:(int(rank) + 1) * per],
        Y[i][int(rank) * per:(int(rank) + 1) * per],
    )
    state, losses = ddp.train_step(state, ddp.shard_batch(local))
    my_loss = float(np.asarray(losses.addressable_shards[0].data).reshape(-1)[0])
    save_checkpoint(i + 1, ckpt_dir, state.params, moe_split=False)  # all ranks
    if rank == "0":
        with open(loss_log, "a") as f:
            f.write(json.dumps({"iter": i + 1, "ws": ws, "loss": my_loss}) + chr(10))
    if rank == "1" and i >= 1:
        open(crash_flag, "w").write("gone")
        os._exit(7)  # hard crash: a dying node runs no atexit handshakes
open(os.path.join(work, f"finished_ws{ws}"), "w").write("ok")
"""


def test_elastic_shrink_resumes_from_checkpoint(tmp_path):
    """VERDICT scenario: one of two workers dies permanently; the launcher
    benches its slot, re-forms the gang at world size 1 with a fresh
    rendezvous port, and training resumes from the checkpoint."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(ELASTIC_WORKER))
    from helpers import free_port, worker_env

    env = worker_env(ELASTIC_WORK_DIR=str(tmp_path))  # 1 device per process
    base_port = free_port()
    r = subprocess.run(
        [
            sys.executable, "-m", "bagua_tpu.distributed.run",
            "--nnodes", "1", "--nproc_per_node", "2", "--min_replicas", "1",
            "--max_restarts", "3", "--monitor_interval", "0.2",
            "--master_port", str(base_port), str(script),
        ],
        env=env, capture_output=True, text=True, timeout=420,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert (tmp_path / "finished_ws1").exists(), r.stderr  # shrunk gang finished
    assert "benched" in r.stderr + r.stdout
    import json

    recs = [json.loads(l) for l in (tmp_path / "losses.jsonl").read_text().splitlines()]
    assert recs[0]["ws"] == 2 and recs[-1]["ws"] == 1  # world size changed
    assert recs[-1]["iter"] == 6
    resumed = [r for r in recs if r["ws"] == 1]
    assert resumed[0]["iter"] == 3  # picked up right after the checkpoint
    assert min(r["loss"] for r in resumed) < recs[0]["loss"]  # kept converging


def test_profiler_session_captures_trace(tmp_path):
    """ProfilerSession writes an XLA profiler trace for the wrapped steps."""
    import glob

    import jax
    import jax.numpy as jnp

    from bagua_tpu.observability import ProfilerSession

    @jax.jit
    def f(x):
        return (x @ x).sum()

    x = jnp.ones((64, 64))
    prof = ProfilerSession(str(tmp_path))
    _, aux = prof.trace_steps(lambda s, b: (s, f(b)), x, [x, x])
    assert float(aux) == 64.0 * 64 * 64
    assert glob.glob(str(tmp_path) + "/**/*.xplane.pb", recursive=True)
