"""Collective flight recorder: ring semantics, dump atomicity, the engine's
trace-time capture / dispatch-time replay, and the cross-rank hang join.

The recorder's three contracts, each pinned here:

* **ring safety** — wraparound keeps the newest ``capacity`` records in
  sequence order, and a dump racing a concurrent ``record()`` (the
  watchdog thread vs the dispatch thread) never observes a torn record;
* **bitwise-inert** — a DDP engine with the recorder attached trains to
  *bit-identical* params + optimizer state vs recorder-off, for both
  gradient_allreduce and zero with overlap on (capture reads trace-time
  Python values only, replay happens on the host);
* **forensics** — per-rank dumps validate against ``bagua.flight_dump.v1``
  and :func:`build_hang_report` joins them into the documented verdict
  taxonomy (healthy / desync / straggler / host_wedge / no_data) with
  first-divergence and blocked-on attribution.
"""

import hashlib
import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.observability import Telemetry, Watchdog, validate_metrics_file
from bagua_tpu.observability.flight_recorder import (
    FLIGHT_DUMP_SCHEMA,
    FlightRecorder,
    build_hang_report,
    capture_program,
    flight_dump_path,
    notify_collective,
    notify_ring,
    push_flight_digest,
    validate_flight_dump,
    validate_flight_record,
    validate_hang_report,
)

LAYERS = [12, 16, 16, 4]


def make_record(seq_hint=0, bucket=0, phase="overlap", step=0, label=None):
    """A schema-complete record template (``record_program`` stamps seq/
    step/timestamps on replay; here we stamp them by hand)."""
    return {
        "step": step,
        "label": label or f"bagua_ex/algo=gradient_allreduce/bucket={bucket}/phase={phase}",
        "algo": "gradient_allreduce",
        "bucket": bucket,
        "phase": phase,
        "precision": "f32",
        "nbytes": 4096,
        "plan_version": 1,
        "t_enqueue": 100.0 + seq_hint,
        "t_retire": 100.5 + seq_hint,
    }


def fill(recorder, n_records, step=0, retired=True):
    program = [make_record(i, bucket=i % 3, step=step) for i in range(n_records)]
    for rec in program:
        if not retired:
            rec["t_retire"] = None
        recorder.record(rec)


# -- ring semantics -----------------------------------------------------------


def test_ring_wraparound_keeps_newest_in_order():
    fr = FlightRecorder(capacity=16)
    for i in range(16 + 5):
        fr.record(make_record(i))
    recs = fr.records()
    assert len(recs) == 16  # the oldest 5 evicted
    seqs = [r["seq"] for r in recs]
    assert seqs == list(range(5, 21))  # newest capacity records, in order
    assert fr.last_seq == 20


def test_retire_stamps_only_live_matching_records():
    fr = FlightRecorder(capacity=8)
    seqs = fr.record_program([make_record(0), make_record(1)], step=3)
    recs = fr.records()
    assert [r["t_retire"] for r in recs] == [None, None]
    assert [r["step"] for r in recs] == [3, 3]
    fr.retire(seqs)
    assert all(r["t_retire"] is not None for r in fr.records())
    # a seq the ring has since evicted is skipped, not resurrected
    for i in range(10):
        fr.record(make_record(i))
    fr.retire(seqs)  # stale: slots now hold newer seqs
    assert all(r["seq"] >= 4 for r in fr.records())


def test_concurrent_record_and_dump_never_torn(tmp_path):
    """The watchdog-thread dump racing the dispatch-thread append: every
    record the dump sees must be complete and schema-valid, with strictly
    increasing seqs — a torn (half-built) record would fail validation."""
    fr = FlightRecorder(capacity=64, rank=0, world_size=1)
    stop = threading.Event()
    errors = []

    def writer():
        step = 0
        while not stop.is_set():
            seqs = fr.record_program(
                [make_record(i, bucket=i) for i in range(4)], step=step
            )
            fr.retire(seqs)
            step += 1

    def reader():
        while not stop.is_set():
            recs = fr.records()
            seqs = [r["seq"] for r in recs]
            if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
                errors.append(f"non-monotonic snapshot: {seqs}")
                return
            for r in recs:
                problems = validate_flight_record(r)
                if problems:
                    errors.append(f"torn record: {problems}")
                    return
            dump = fr.dump(str(tmp_path / "flight_0.json"), reason="race")
            problems = validate_flight_dump(dump)
            # the in-memory payload must always validate; last_seq advances
            # between records() and the payload build, so only tears count
            problems = [p for p in problems if "last_seq" not in p]
            if problems:
                errors.append(f"torn dump: {problems}")
                return

    w = threading.Thread(target=writer)
    r = threading.Thread(target=reader)
    w.start(), r.start()
    import time as _time

    _time.sleep(0.5)
    stop.set()
    w.join(5.0), r.join(5.0)
    assert not errors, errors
    assert fr.last_seq > 100  # the race actually exercised wraparound


def test_dump_roundtrip_validates(tmp_path):
    fr = FlightRecorder(capacity=32, rank=2, world_size=4)
    fill(fr, 10)
    path = flight_dump_path(str(tmp_path), fr.rank)
    assert path.endswith("flight_2.json")
    fr.dump(path, reason="manual", telemetry={"step": 9, "phase": "wait"},
            plan_version=1)
    with open(path) as f:
        dump = json.load(f)
    assert validate_flight_dump(dump) == []
    assert dump["schema"] == FLIGHT_DUMP_SCHEMA
    assert dump["rank"] == 2 and dump["world_size"] == 4
    assert dump["reason"] == "manual"
    assert len(dump["records"]) == 10 and dump["last_seq"] == 9
    assert dump["threads"]  # every live thread's stack rides along
    assert dump["telemetry"]["phase"] == "wait"
    # no temp file left behind (write-temp + os.replace)
    assert [p.name for p in tmp_path.iterdir()] == ["flight_2.json"]


def test_validators_reject_malformed(tmp_path):
    fr = FlightRecorder(capacity=8)
    fill(fr, 3)
    dump = fr.dump(str(tmp_path / "d.json"), reason="x")
    assert validate_flight_dump(dump) == []
    bad = dict(dump, schema="bogus")
    assert any("schema" in p for p in validate_flight_dump(bad))
    bad = dict(dump)
    bad["records"] = [dict(dump["records"][0])]
    del bad["records"][0]["bucket"]
    assert any("bucket" in p for p in validate_flight_dump(bad))
    report = build_hang_report([dump])
    assert validate_hang_report(report) == []
    assert any("verdict" in p
               for p in validate_hang_report(dict(report, verdict="nope")))


# -- trace-time capture -------------------------------------------------------


def test_capture_program_collects_and_restores():
    notify_collective("gradient_allreduce", 0, "mono")  # no capture: no-op
    with capture_program() as events:
        notify_collective("gradient_allreduce", 0, "overlap")
        notify_ring(kind="rs", bits=8, hops=7, wire_bytes=1024)
        with capture_program() as inner:  # reentrant
            notify_collective("zero", 1, "rs")
        notify_collective("gradient_allreduce", 1, "overlap")
    notify_collective("gradient_allreduce", 9, "mono")  # capture over: no-op
    assert [e["phase"] for e in events] == ["overlap", "hop", "overlap"]
    assert inner == [{"algo": "zero", "bucket": 1, "phase": "rs"}]
    hop = events[1]
    # the ring hop inherits the enclosing collective's attribution and
    # carries the hop count in-record
    assert hop["algo"] == "gradient_allreduce" and hop["bucket"] == 0
    assert hop["hops"] == 7 and hop["precision"] == "int8"
    assert hop["nbytes"] == 1024


# -- the cross-rank join ------------------------------------------------------


def rank_dump(tmp_path, rank, n_records, *, drop_idx=None, unretired_from=None,
              phase="wait", world_size=4, axes=None):
    fr = FlightRecorder(capacity=64, rank=rank, world_size=world_size)
    program = [make_record(i, bucket=i % 3, step=i // 3) for i in range(n_records)]
    if axes is not None:  # named-mesh engines stamp the exchange axes
        program = [dict(rec, axes=list(axes)) for rec in program]
    if drop_idx is not None:
        program = program[:drop_idx] + program[drop_idx + 1:]
    for i, rec in enumerate(program):
        if unretired_from is not None and i >= unretired_from:
            rec = dict(rec, t_retire=None)
        fr.record(rec)
    return fr.dump(flight_dump_path(str(tmp_path), rank),
                   reason="watchdog_timeout",
                   telemetry={"step": n_records // 3, "phase": phase})


def test_hang_report_healthy_and_no_data(tmp_path):
    report = build_hang_report([])
    assert report["verdict"] == "no_data"
    dumps = [rank_dump(tmp_path, r, 12) for r in range(4)]
    report = build_hang_report(dumps)
    assert validate_hang_report(report) == []
    assert report["verdict"] == "healthy"
    assert report["lagging_ranks"] == [] and report["divergent_ranks"] == []


def test_hang_report_first_desync_attribution(tmp_path):
    """One rank skipped a collective mid-stream: the join must name the
    first divergent seq, the minority rank, and the majority's record as
    the collective the gang desynced at."""
    dumps = [rank_dump(tmp_path, r, 12, drop_idx=7 if r == 2 else None)
             for r in range(4)]
    report = build_hang_report(dumps)
    assert validate_hang_report(report) == []
    assert report["verdict"] == "desync"
    assert report["first_divergence_seq"] == 7
    assert report["divergent_ranks"] == [2]
    blocked = report["blocked_on"]
    assert blocked["seq"] == 7 and blocked["bucket"] == 7 % 3
    assert blocked["label"].endswith(f"bucket={7 % 3}/phase=overlap")
    assert blocked["plan_version"] == 1


def test_hang_report_straggler_vs_host_wedge(tmp_path):
    # identical programs, rank 1 stopped 3 records early with everything
    # retired and the host parked in "wait": a device-side straggler
    dumps = [rank_dump(tmp_path, r, 9 if r == 1 else 12) for r in range(4)]
    report = build_hang_report(dumps)
    assert report["verdict"] == "straggler"
    assert report["lagging_ranks"] == [1]
    # blocked_on = the first collective rank 1 never issued (seq 9), read
    # from an advanced rank's ring
    assert report["blocked_on"]["seq"] == 9
    assert report["per_rank"]["1"]["unretired"] == 0

    # same lag, but the laggard never came back from its last dispatch
    # (unretired records) => the host is wedged, not the device
    dumps = [rank_dump(tmp_path, r, 9 if r == 1 else 12,
                       unretired_from=8 if r == 1 else None,
                       phase="dispatch" if r == 1 else "wait")
             for r in range(4)]
    report = build_hang_report(dumps)
    assert report["verdict"] == "host_wedge"
    assert report["per_rank"]["1"]["unretired"] == 1
    assert report["blocked_on"]["seq"] == 9


def test_hang_report_blocked_on_carries_axes(tmp_path):
    """On a named mesh the records carry the exchange axes; the straggler
    verdict's ``blocked_on`` must surface them (which link a wedged gang is
    stuck behind), and the diagnose_hang summary must print them alongside
    any nearby axis-scoped sentinel incident."""
    import importlib.util
    import os

    dumps = [rank_dump(tmp_path, r, 9 if r == 1 else 12, axes=["dp", "fsdp"])
             for r in range(4)]
    report = build_hang_report(dumps)
    assert validate_hang_report(report) == []
    assert report["verdict"] == "straggler"
    assert report["blocked_on"]["axes"] == ["dp", "fsdp"]
    # axis-blind dumps keep the legacy shape: no axes key at all
    (tmp_path / "legacy").mkdir()
    legacy = build_hang_report(
        [rank_dump(tmp_path / "legacy", r, 9 if r == 1 else 12)
         for r in range(4)])
    assert "axes" not in legacy["blocked_on"]

    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ci", "diagnose_hang.py")
    spec = importlib.util.spec_from_file_location("_diagnose_hang", script)
    dh = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(dh)
    incident = {
        "event": "perf_regression", "ts": 1.0, "step": 30,
        "stream": "wire_axis:fsdp", "dominant": "wire_slowdown",
        "residual_ms": 9.0, "axis": "fsdp", "link_class": "dcn",
    }
    dh.fold_incidents(report, [incident])
    assert report["incidents"][-1]["axis"] == "fsdp"
    assert report["incidents"][-1]["link_class"] == "dcn"
    text = dh.summarize(report)
    assert "axes dpxfsdp" in text
    assert "axis fsdp [dcn]" in text


# -- the engine integration ---------------------------------------------------


def make_batch(seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(32, LAYERS[0]).astype(np.float32))
    y = jnp.asarray(rng.randn(32, LAYERS[-1]).astype(np.float32))
    return x, y


def run_steps(group, algo_name, flight, steps=3, overlap=True):
    tel = Telemetry(flight=flight)
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.1, momentum=0.9), build_algorithm(algo_name),
        process_group=group, bucket_size_bytes=1 << 9, overlap=overlap,
        telemetry=tel,
    )
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    batch = make_batch()
    losses = None
    for _ in range(steps):
        state, losses = ddp.train_step(state, batch)
    jax.block_until_ready(losses)
    ddp.shutdown()
    return ddp, state


def state_sha(state):
    h = hashlib.sha256()
    for leaf in jax.tree.leaves((state.params, state.opt_state)):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def test_ddp_capture_replays_one_record_per_collective(group):
    fr = FlightRecorder(capacity=128, rank=0, world_size=1)
    ddp, _ = run_steps(group, "gradient_allreduce", fr, steps=3)
    assert ddp.plan.num_buckets > 1
    (program,) = ddp._flight_programs.values()
    # the captured program: one overlap collective per plan bucket, in the
    # named-scope grammar, carrying plan bytes + version
    assert len(program) == ddp.plan.num_buckets
    # capture preserves *issue* order (backward-pass bucket order under
    # overlap), covering every plan bucket exactly once
    assert sorted(r["bucket"] for r in program) == list(range(ddp.plan.num_buckets))
    for rec in program:
        assert rec["phase"] == "overlap"
        assert rec["label"] == (
            f"bagua_ex/algo=gradient_allreduce/bucket={rec['bucket']}"
            f"/phase=overlap"
        )
        assert rec["nbytes"] == ddp.plan.specs[rec["bucket"]].nbytes > 0
        assert rec["plan_version"] == ddp.plan_version
    # every dispatch (3 steps) replayed the program and retired its records
    recs = fr.records()
    assert len(recs) == 3 * len(program)
    assert all(r["t_retire"] is not None for r in recs)
    assert [r["step"] for r in recs[:len(program)]] == [0] * len(program)
    assert recs[-1]["step"] == 2


@pytest.mark.parametrize("algo_name", ["gradient_allreduce", "zero"])
def test_recorder_is_bitwise_inert(group, algo_name):
    """The acceptance criterion: recorder on vs off trains bit-identical
    state (params + optimizer), overlap on, for the all-reduce AND the
    sharded (zero) exchange paths."""
    _, state_off = run_steps(group, algo_name, None, steps=3)
    fr = FlightRecorder(capacity=128, rank=0, world_size=1)
    _, state_on = run_steps(group, algo_name, fr, steps=3)
    assert fr.last_seq >= 0  # the recorder actually recorded
    assert state_sha(state_on) == state_sha(state_off)


def test_quantized_ring_records_hops(group, monkeypatch):
    """The int8 wire path records one phase="hop" descriptor per ring leg
    with the hop count in-record, attributed to its bucket."""
    monkeypatch.setenv("BAGUA_QR_BLOCK", "128")
    fr = FlightRecorder(capacity=256, rank=0, world_size=1)
    tel = Telemetry(flight=fr)
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.1),
        build_algorithm("gradient_allreduce", wire_precision="int8"),
        process_group=group, bucket_size_bytes=1 << 9, telemetry=tel,
    )
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    state, losses = ddp.train_step(state, make_batch())
    jax.block_until_ready(losses)
    ddp.shutdown()
    (program,) = ddp._flight_programs.values()
    hops = [r for r in program if r["phase"] == "hop"]
    n = ddp.group.size
    assert hops, "quantized ring left no hop records"
    assert {r["ring"] for r in hops} == {"rs", "ag"}
    for rec in hops:
        assert rec["hops"] == n - 1
        assert rec["precision"] == "int8" and rec["nbytes"] > 0
        assert rec["bucket"] >= 0  # inherited from the enclosing collective


# -- the dying path -----------------------------------------------------------


def test_watchdog_timeout_leaves_evidence_and_hang_event(tmp_path):
    """Satellite 1 + the dump hooks: a watchdog timeout atomically writes
    watchdog_dump.json and flight_<rank>.json, pushes the digest, and emits
    a schema-valid ``hang`` JSONL event through the hub — all BEFORE
    on_timeout runs."""
    events_path = str(tmp_path / "metrics.jsonl")
    fr = FlightRecorder(capacity=32, rank=0, world_size=1)
    fill(fr, 5, step=7)
    tel = Telemetry(metrics_jsonl=events_path, flight=fr)
    tel.current_step, tel.current_phase = 7, "dispatch"
    order = []
    pushed = []
    wd = Watchdog(timeout_s=0.15, check_interval_s=0.05,
                  on_timeout=lambda s: order.append("on_timeout"))
    wd.dump_dir = str(tmp_path)
    wd.digest_pusher = lambda: pushed.append(True)
    tel.bind_watchdog(wd)
    assert wd.flight_recorder is fr and wd.hang_hook == tel.on_hang
    wd.start()
    wd.beat(phase="dispatch")
    import time as _time

    deadline = _time.time() + 3.0
    while not order and _time.time() < deadline:
        _time.sleep(0.05)
    wd.stop()
    tel.close()
    assert order == ["on_timeout"]
    assert pushed  # digest pusher ran on the dying path

    with open(tmp_path / "watchdog_dump.json") as f:
        wdump = json.load(f)
    assert wdump["reason"] == "watchdog_timeout"
    assert wdump["last_phase"] == "dispatch"
    assert wdump["telemetry"]["step"] == 7
    with open(tmp_path / "flight_0.json") as f:
        fdump = json.load(f)
    assert validate_flight_dump(fdump) == []
    assert fdump["reason"] == "watchdog_timeout" and len(fdump["records"]) == 5

    assert validate_metrics_file(events_path) == []
    with open(events_path) as f:
        events = [json.loads(line) for line in f if line.strip()]
    hang = [e for e in events if e["event"] == "hang"]
    assert len(hang) == 1
    assert hang[0]["reason"] == "watchdog_timeout"
    assert hang[0]["last_phase"] == "dispatch"
    assert hang[0]["flight_last_seq"] == 4
    assert "watchdog_dump" in hang[0]["dumps"] and "flight_dump" in hang[0]["dumps"]


def test_push_flight_digest_best_effort():
    fr = FlightRecorder(capacity=8, rank=3, world_size=4)
    fill(fr, 4)

    class KV:
        def __init__(self):
            self.store = {}

        def kv_set(self, key, value):
            self.store[key] = value

    class Breaker:
        def before_call(self):
            pass

        def record_success(self):
            pass

        def record_failure(self):
            pass

    kv = KV()
    assert push_flight_digest(kv, fr, attempt="a1", breaker=Breaker())
    digest = kv.store["bagua/flight/a1/rank3"]
    assert digest["rank"] == 3 and digest["last_seq"] == 3
    assert digest["unretired"] == 0
    assert digest["last"]["seq"] == 3

    class DeadKV:
        def kv_set(self, key, value):
            raise OSError("kv down")

    # outage: degrade to local-only, never raise
    assert push_flight_digest(DeadKV(), fr, attempt="a1", breaker=Breaker()) is False
    assert push_flight_digest(None, fr) is False
    assert push_flight_digest(kv, None) is False
