"""Trace-driven bucket planner: cost model, DP optimality, service integration.

The planner (``bagua_tpu/service/planner.py``) is pure numpy-free Python, so
most of this file runs instantly with no devices.  The DP solver is pinned
against brute-force enumeration of every feasible contiguous partition — the
strongest statement the unit tier can make about "optimal".  The recorded
VGG16 fixture test mirrors the CI gate (``ci/perf_audit.py`` planner lane):
on the committed measured spans the DP partition must be *strictly* cheaper
than the seed greedy 10 MiB plan.  The tail of the file exercises the
service-side integration (``AutotuneTaskManager``): spans → fitted cost model
→ BO warm-start → decision trail, under each ``BAGUA_AUTOTUNE_PLANNER`` mode,
and the end-to-end bitwise-parity guarantee of a mid-training re-bucket.
"""

import itertools
import json
import os

import numpy as np

import pytest

from bagua_tpu.defs import TensorDeclaration, dtype_itemsize
from bagua_tpu.service.planner import (
    DEFAULT_FLAT,
    AlphaBeta,
    BucketPlanner,
    CostModel,
    WireSample,
    fit_alpha_beta,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "ci", "fixtures", "vgg16_bucket_spans.json")


def decls(sizes, dtype="f32", prefix="t"):
    return [
        TensorDeclaration(name=f"{prefix}{i}", num_elements=n, dtype=dtype)
        for i, n in enumerate(sizes)
    ]


# -- α–β fitting ------------------------------------------------------------


def test_fit_alpha_beta_recovers_linear_model():
    true = AlphaBeta(alpha=120e-6, beta=35e9)
    samples = [
        WireSample(nbytes=n, seconds=true.predict(n))
        for n in (1 << 16, 1 << 20, 1 << 22, 1 << 24, 1 << 25)
    ]
    fit = fit_alpha_beta(samples)
    assert fit.n_samples == 5
    assert fit.alpha == pytest.approx(true.alpha, rel=1e-6)
    assert fit.beta == pytest.approx(true.beta, rel=1e-6)


def test_fit_alpha_beta_no_samples_returns_prior():
    assert fit_alpha_beta([]) is DEFAULT_FLAT
    # zero-duration samples are noise, not measurements
    assert fit_alpha_beta([WireSample(nbytes=1 << 20, seconds=0.0)]) is DEFAULT_FLAT


def test_fit_alpha_beta_single_operating_point():
    """One size: keep the prior's latency share, solve bandwidth from the
    remainder — and never predict more than the measurement at that size."""
    fit = fit_alpha_beta([WireSample(nbytes=1 << 24, seconds=2e-3)])
    assert fit.alpha <= DEFAULT_FLAT.alpha
    assert fit.predict(1 << 24) == pytest.approx(2e-3, rel=1e-6)


def test_fit_alpha_beta_negative_intercept_clamped():
    # these two points extrapolate to a negative latency; the fit must
    # re-solve through the origin instead of predicting time travel
    fit = fit_alpha_beta(
        [WireSample(nbytes=1e6, seconds=1e-3), WireSample(nbytes=2e6, seconds=3e-3)]
    )
    assert fit.alpha == 0.0
    assert fit.predict(0) == 0.0
    assert fit.predict(1.5e6) == pytest.approx(2e-3, rel=1e-6)


def test_fit_alpha_beta_nonpositive_slope_degrades_to_latency():
    # time *decreasing* with bytes: bandwidth is unidentifiable, keep a
    # pure-latency model at the mean with the prior's bandwidth
    fit = fit_alpha_beta(
        [WireSample(nbytes=1e6, seconds=3e-3), WireSample(nbytes=4e6, seconds=1e-3)]
    )
    assert fit.alpha == pytest.approx(2e-3)
    assert fit.beta == DEFAULT_FLAT.beta


def test_cost_model_from_samples_fits_legs_independently():
    intra = AlphaBeta(alpha=20e-6, beta=90e9)
    inter = AlphaBeta(alpha=300e-6, beta=20e9)
    samples = [
        WireSample(nbytes=n, seconds=intra.predict(n), leg="intra")
        for n in (1 << 20, 1 << 23, 1 << 25)
    ] + [
        WireSample(nbytes=n, seconds=inter.predict(n), leg="inter")
        for n in (1 << 18, 1 << 21, 1 << 23)
    ]
    cm = CostModel.from_samples(samples, intra_size=4)
    # flat leg untouched (no samples -> prior)
    assert cm.flat is DEFAULT_FLAT
    assert cm.intra.alpha == pytest.approx(intra.alpha, rel=1e-6)
    assert cm.inter.beta == pytest.approx(inter.beta, rel=1e-6)
    # hierarchical = intra over full payload + inter over payload/intra_size
    n = 1 << 24
    assert cm.bucket_wire_time(n, hierarchical=True) == pytest.approx(
        intra.predict(n) + inter.predict(n / 4), rel=1e-6
    )
    assert cm.bucket_wire_time(n, hierarchical=False) == DEFAULT_FLAT.predict(n)


# -- DP solver vs brute force -----------------------------------------------


def brute_force(planner, items, max_bucket_bytes=None, hierarchical=False):
    """Minimum predicted exposed time over ALL feasible contiguous partitions
    of the timeline (2^(n-1) cut masks, filtered for dtype homogeneity and
    the size cap with singletons always feasible)."""
    n = len(items)
    best = None
    for mask in range(1 << (n - 1)):
        cuts, start = [], 0
        for i in range(n - 1):
            if mask & (1 << i):
                cuts.append((start, i + 1))
                start = i + 1
        cuts.append((start, n))
        buckets = [items[a:b] for a, b in cuts]
        ok = True
        for b in buckets:
            if len({td.dtype for td in b}) > 1:
                ok = False
                break
            size = sum(td.num_elements * dtype_itemsize(td.dtype) for td in b)
            if max_bucket_bytes and size > max_bucket_bytes and len(b) > 1:
                ok = False
                break
        if not ok:
            continue
        res = planner.evaluate(buckets, hierarchical)
        if best is None or res.predicted_exposed_s < best - 1e-15:
            best = res.predicted_exposed_s
    return best


@pytest.mark.parametrize("eta", [0.0, 0.4, 1.0])
@pytest.mark.parametrize("cap", [None, 6 * 4096 * 4])
def test_dp_matches_brute_force(eta, cap):
    sizes = [4096, 65536, 4096, 32768, 8192, 131072, 4096, 16384]
    ds = decls(sizes)
    arrivals = {f"t{i}": t for i, t in enumerate([0.0, 0.1, 0.4, 0.5, 0.9, 1.3, 1.4, 2.0])}
    cm = CostModel(flat=AlphaBeta(alpha=200e-6, beta=1e6))  # wire time matters
    planner = BucketPlanner(ds, arrivals, cost_model=cm, overlap_efficiency=eta)
    dp = planner.plan(max_bucket_bytes=cap)
    bf = brute_force(planner, planner.timeline, max_bucket_bytes=cap)
    assert dp.predicted_exposed_s == pytest.approx(bf, rel=1e-9, abs=1e-12)


def test_dp_matches_brute_force_with_dtype_boundary():
    ds = decls([4096, 8192, 4096], dtype="f32") + decls(
        [16384, 4096], dtype="bf16", prefix="q"
    )
    arrivals = {"t0": 0.0, "t1": 0.2, "t2": 0.5, "q0": 0.3, "q1": 0.6}
    cm = CostModel(flat=AlphaBeta(alpha=150e-6, beta=1e6))
    planner = BucketPlanner(ds, arrivals, cost_model=cm, overlap_efficiency=0.7)
    dp = planner.plan()
    bf = brute_force(planner, planner.timeline)
    assert dp.predicted_exposed_s == pytest.approx(bf, rel=1e-9, abs=1e-12)
    for bucket in dp.buckets:
        assert len({td.dtype for td in bucket}) == 1


def test_dp_cap_bounds_fusion_not_tensors():
    itemsz = dtype_itemsize("f32")
    ds = decls([1024, 1024, 1 << 22, 1024])  # t2 alone exceeds any small cap
    arrivals = {f"t{i}": 0.1 * i for i in range(4)}
    planner = BucketPlanner(ds, arrivals)
    cap = 4096 * itemsz
    res = planner.plan(max_bucket_bytes=cap)
    names = [[td.name for td in b] for b in res.buckets]
    assert ["t2"] in names  # oversized tensor still got its own bucket
    for bucket in res.buckets:
        size = sum(td.num_elements * itemsz for td in bucket)
        assert len(bucket) == 1 or size <= cap


def test_eta_extremes_select_different_partitions():
    """η=0 minimizes total wire (prefers fewer launches); η=1 minimizes the
    tail (prefers overlapping early arrivals) — the calibration must actually
    steer the solver, not just scale the reported number."""
    ds = decls([1 << 18] * 6)
    arrivals = {f"t{i}": 0.5 * i for i in range(6)}
    cm = CostModel(flat=AlphaBeta(alpha=5e-3, beta=1e9))  # launches are costly
    serial = BucketPlanner(ds, arrivals, cost_model=cm, overlap_efficiency=0.0)
    hidden = BucketPlanner(ds, arrivals, cost_model=cm, overlap_efficiency=1.0)
    assert serial.plan().n_buckets == 1  # one launch = least total wire
    assert hidden.plan().n_buckets > 1  # spread over the backward = least tail


def test_evaluate_handles_non_contiguous_partitions():
    """The greedy seed plan is declaration-ordered, not arrival-ordered; the
    simulator must still serialize its buckets on the measured clock."""
    ds = decls([4096, 4096])
    # declared t0 before t1, but t1's cotangent arrives first
    planner = BucketPlanner(ds, {"t0": 1.0, "t1": 0.0})
    res = planner.evaluate([[ds[0]], [ds[1]]])
    rows = sorted(res.per_bucket, key=lambda r: r["start_s"])
    assert rows[0]["ready_s"] == 0.0 and rows[1]["ready_s"] == 1.0
    assert rows[1]["start_s"] >= rows[0]["finish_s"]  # wire serialization


def test_unmeasured_tensors_placed_at_latest_arrival():
    ds = decls([4096, 4096, 4096])
    planner = BucketPlanner(ds, {"t0": 0.2, "t1": 0.9})  # t2 never measured
    assert planner.arrivals["t2"] == 0.9
    assert planner.timeline[-1].name in ("t1", "t2")


def test_rank_caps_sorted_and_complete():
    ds = decls([1 << 16] * 4)
    arrivals = {f"t{i}": 0.05 * i for i in range(4)}
    planner = BucketPlanner(ds, arrivals)
    ranked = planner.rank_caps(range(18, 22))
    assert len(ranked) == 4 * 2  # caps × {flat, hierarchical}
    costs = [c["predicted_exposed_ms"] for c in ranked]
    assert costs == sorted(costs)
    assert {c["is_hierarchical_reduce"] for c in ranked} == {0, 1}


def test_empty_planner_is_harmless():
    planner = BucketPlanner([], {})
    res = planner.plan()
    assert res.n_buckets == 0 and res.predicted_exposed_s == 0.0


# -- the recorded VGG16 fixture (the CI acceptance gate, in-suite) -----------


def test_fixture_planner_strictly_beats_seed_greedy():
    """On the committed measured spans, the DP partition's predicted exposed
    communication is strictly lower than the seed greedy 10 MiB plan's —
    the same assertion ``ci/perf_audit.py``'s planner lane gates on."""
    from bagua_tpu.bucket import split_declarations

    fx = json.load(open(FIXTURE))
    ds = [TensorDeclaration(**d) for d in fx["declarations"]]
    samples = [WireSample(**s) for s in fx["wire_samples"]]
    cm = CostModel.from_samples(samples)
    num = sum(s.hidden_frac * s.seconds for s in samples if s.hidden_frac is not None)
    den = sum(s.seconds for s in samples if s.hidden_frac is not None)
    eta = num / den if den else 1.0
    planner = BucketPlanner(ds, fx["arrivals"], cost_model=cm, overlap_efficiency=eta)
    shapes = {td.name: (td.num_elements,) for td in ds}
    greedy_specs = split_declarations(ds, shapes, fx["seed_bucket_size_bytes"])
    greedy = planner.evaluate([s.declarations() for s in greedy_specs])
    dp = planner.plan()
    assert dp.predicted_exposed_s < greedy.predicted_exposed_s
    # every declared tensor is in exactly one planned bucket
    planned = sorted(td.name for b in dp.buckets for td in b)
    assert planned == sorted(td.name for td in ds)


# -- service integration: AutotuneTaskManager -------------------------------


def wire_span(nbytes=1 << 24, seconds=2e-3, hidden_frac=0.5, intra_size=1):
    return {
        "action": "bucket_wire",
        "tensor_name": "bucket0",
        "start_time": 0.0,
        "end_time": seconds,
        "nbytes": nbytes,
        "seconds": seconds,
        "leg": "flat",
        "hidden_frac": hidden_frac,
        "intra_size": intra_size,
    }


def ready_spans(names_and_times):
    return [
        {"action": "tensor_ready", "tensor_name": n, "start_time": t}
        for n, t in names_and_times
    ]


def make_manager(mode, n=6):
    from bagua_tpu.service.autotune_task_manager import AutotuneTaskManager

    mgr = AutotuneTaskManager("m", planner_mode=mode)
    mgr.tensor_list = decls([1 << 18] * n)
    return mgr


def test_manager_warmstart_builds_planner_and_trail():
    mgr = make_manager("warmstart")
    spans = ready_spans((f"t{i}", 0.01 * i) for i in range(6))
    spans.append(wire_span(hidden_frac=0.25))
    mgr.report_spans(spans)
    assert mgr.planner is not None
    trail = mgr.decision_trail
    assert trail["spans_reported"] is True
    assert trail["overlap_efficiency"] == pytest.approx(0.25)
    assert trail["cost_model"]["flat"]["n_samples"] == 1
    assert trail["dp_plan"] and trail["greedy_plan"]
    assert trail["candidates"] and trail["warm_start"]
    # the warm-start queue feeds the optimizer's next asks, best first
    assert mgr.optimizer._pending
    first = mgr.optimizer.ask()
    assert first == trail["warm_start"][0]
    # proposals flow through the planner: predicted cost attached + recorded
    hp = mgr.tell_and_ask(score=10.0, train_iter=1)
    assert hp.predicted_exposed_ms is not None
    assert trail["chosen"]["predicted_exposed_ms"] == hp.predicted_exposed_ms
    assert trail["proposals"][-1] is trail["chosen"]


def test_manager_mode_off_never_activates_planner():
    mgr = make_manager("off")
    spans = ready_spans((f"t{i}", 0.01 * i) for i in range(6))
    spans.append(wire_span())
    mgr.report_spans(spans)
    assert mgr.planner is None
    assert mgr.decision_trail["mode"] == "off"
    assert mgr.decision_trail["spans_reported"] is False
    assert not mgr.optimizer._pending
    hp = mgr.tell_and_ask(score=10.0, train_iter=1)
    assert hp.predicted_exposed_ms is None  # pure BO, seed behavior


def test_manager_mode_on_uses_dp_partition():
    mgr = make_manager("on")
    # early tensors bunch at t~0, last one arrives late: the DP under a
    # permissive cap fuses the early group — a split greedy can't reproduce
    mgr.report_spans(
        ready_spans([("t0", 0.0), ("t1", 0.001), ("t2", 0.002),
                     ("t3", 0.003), ("t4", 0.004), ("t5", 0.5)])
        + [wire_span(hidden_frac=0.0)]
    )
    cap_2p = 24  # 16 MiB >= all six tensors together
    hp = mgr.recommended_from_param_dict(
        {"bucket_size_2p": cap_2p, "is_hierarchical_reduce": 0}
    )
    assert hp.predicted_exposed_ms is not None
    dp_direct = mgr.planner.plan(max_bucket_bytes=1 << cap_2p)
    assert [[td.name for td in b] for b in hp.buckets] == [
        [td.name for td in b] for b in dp_direct.buckets
    ]
    for bucket in hp.buckets:  # cap respected (no tensor here exceeds it)
        assert sum(td.num_elements * 4 for td in bucket) <= 1 << cap_2p


def test_manager_no_spans_is_pure_bo():
    """Measured signal is an upgrade, never a requirement: with nothing
    reported the optimizer runs its cold deterministic walk unchanged."""
    mgr = make_manager("warmstart")
    assert mgr.planner is None and not mgr.optimizer._pending
    hp = mgr.tell_and_ask(score=1.0, train_iter=0)
    assert hp.buckets and hp.predicted_exposed_ms is None


def test_manager_malformed_wire_span_ignored():
    mgr = make_manager("warmstart")
    bad = wire_span()
    del bad["seconds"]
    mgr.report_spans(ready_spans([("t0", 0.0), ("t1", 0.1)]) + [bad])
    assert mgr.wire_samples == []  # dropped, not crashed
    assert mgr.planner is not None  # arrivals alone still build a planner


def test_planner_mode_env_knob(monkeypatch):
    from bagua_tpu.env import get_autotune_planner_mode

    monkeypatch.delenv("BAGUA_AUTOTUNE_PLANNER", raising=False)
    assert get_autotune_planner_mode() == "warmstart"
    monkeypatch.setenv("BAGUA_AUTOTUNE_PLANNER", "ON")
    assert get_autotune_planner_mode() == "on"
    monkeypatch.setenv("BAGUA_AUTOTUNE_PLANNER", "off")
    assert get_autotune_planner_mode() == "off"
    monkeypatch.setenv("BAGUA_AUTOTUNE_PLANNER", "bogus")
    assert get_autotune_planner_mode() == "warmstart"
    # the manager default follows the env knob
    from bagua_tpu.service.autotune_task_manager import AutotuneTaskManager

    monkeypatch.setenv("BAGUA_AUTOTUNE_PLANNER", "off")
    assert AutotuneTaskManager("envm").planner_mode == "off"


# -- mid-training re-bucket: bitwise parity (the adoption-safety gate) -------


def test_midtrain_planner_rebucket_bitwise_parity(group):
    """Adopting a planner-proposed plan mid-training must be numerically
    invisible: engine A trains k steps, re-buckets onto the planner's DP
    partition, trains m more; engine B starts fresh on that plan and runs the
    same m steps from A's pre-rebucket state.  Bitwise-identical params —
    re-bucketing changes the wire schedule, never the math."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.bucket import BucketPlan
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.models.mlp import init_mlp, mse_loss

    params = init_mlp(jax.random.PRNGKey(0), [16, 64, 64, 4])

    def make_engine():
        return DistributedDataParallel(
            mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(),
            process_group=group, bucket_size_bytes=1 << 10, overlap=True,
        )

    def batches(n, seed):
        rng = np.random.RandomState(seed)
        return [
            (jnp.asarray(rng.randn(16, 16), np.float32),
             jnp.asarray(rng.randn(16, 4), np.float32))
            for _ in range(n)
        ]

    ddp_a = make_engine()
    state = ddp_a.init(params)
    for batch in batches(3, seed=1):
        state, _ = ddp_a.train_step(state, batch)
    # steps donate their input buffers: keep a live copy for engine B
    saved = jax.tree.map(jnp.copy, state)

    # planner plan over the engine's own declarations (synthetic arrivals in
    # declaration order stand in for a trace on this tiny model)
    flat_decls = [td for b in ddp_a.plan.declarations() for td in b]
    arrivals = {td.name: 0.001 * i for i, td in enumerate(flat_decls)}
    # η=0 models a serializing backend: the DP fuses the tiny seed buckets
    result = BucketPlanner(flat_decls, arrivals, overlap_efficiency=0.0).plan()
    assert result.n_buckets != ddp_a.plan.num_buckets  # genuinely a new plan
    new_plan = BucketPlan.from_declarations(
        result.buckets, ddp_a._tree_template, align_elems=group.size
    )

    ddp_a.rebucket(new_plan, predicted_exposed_ms=result.predicted_exposed_s * 1e3)
    assert ddp_a.plan_version == 1
    tail = batches(3, seed=2)
    state_a = state
    for batch in tail:
        state_a, _ = ddp_a.train_step(state_a, batch)

    # engine B: fresh build, adopts the same plan before compiling anything
    ddp_b = make_engine()
    ddp_b.init(params)  # binds the tree template
    ddp_b.rebucket(new_plan)
    state_b = saved
    for batch in tail:
        state_b, _ = ddp_b.train_step(state_b, batch)

    for pa, pb in zip(
        jax.tree_util.tree_leaves(state_a.params),
        jax.tree_util.tree_leaves(state_b.params),
    ):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


# -- sharded (zero) wire legs -------------------------------------------------


def test_cost_model_fits_rs_ag_legs_independently():
    """The sharded exchange reports its two legs separately; each must get
    its own α–β fit while the allreduce legs keep their priors."""
    from bagua_tpu.service.planner import DEFAULT_AG, DEFAULT_RS

    rs = AlphaBeta(alpha=80e-6, beta=70e9)
    ag = AlphaBeta(alpha=150e-6, beta=55e9)
    samples = [
        WireSample(nbytes=n, seconds=rs.predict(n), leg="rs")
        for n in (1 << 20, 1 << 23, 1 << 25)
    ] + [
        WireSample(nbytes=n, seconds=ag.predict(n), leg="ag")
        for n in (1 << 19, 1 << 22, 1 << 24)
    ]
    cm = CostModel.from_samples(samples)
    assert cm.flat is DEFAULT_FLAT
    assert cm.rs.alpha == pytest.approx(rs.alpha, rel=1e-6)
    assert cm.rs.beta == pytest.approx(rs.beta, rel=1e-6)
    assert cm.ag.alpha == pytest.approx(ag.alpha, rel=1e-6)
    # no samples on a leg -> its prior stays
    assert CostModel.from_samples([]).rs is DEFAULT_RS
    assert CostModel.from_samples([]).ag is DEFAULT_AG
    # the sharded pattern prices the RS leg; the deferred all-gather is
    # priced by ag_time (next step's forward), never the backward tail
    n = 1 << 24
    assert cm.bucket_wire_time(n, wire_pattern="sharded") == pytest.approx(
        rs.predict(n), rel=1e-6
    )
    assert cm.ag_time(n) == pytest.approx(ag.predict(n), rel=1e-6)


def test_planner_sharded_wire_pattern_prices_rs_leg():
    """A ``wire_pattern="sharded"`` planner sees cheaper per-bucket wire time
    (RS moves half an allreduce's bytes), so with costly flat bandwidth the
    sharded plan's predicted exposed tail must be strictly below the
    allreduce plan's for the identical partition."""
    ds = decls([1 << 18, 1 << 18, 1 << 18, 1 << 18])
    arrivals = {td.name: 0.0005 * i for i, td in enumerate(ds)}
    cm = CostModel(
        flat=AlphaBeta(alpha=100e-6, beta=1e9),
        rs=AlphaBeta(alpha=100e-6, beta=2e9),  # half the bytes on the wire
    )
    ar = BucketPlanner(ds, arrivals, cost_model=cm, wire_pattern="allreduce")
    sh = BucketPlanner(ds, arrivals, cost_model=cm, wire_pattern="sharded")
    part = [[ds[0], ds[1]], [ds[2], ds[3]]]
    assert (
        sh.evaluate(part).predicted_exposed_s
        < ar.evaluate(part).predicted_exposed_s
    )
    # and the DP search itself runs under the sharded pattern
    res = sh.plan()
    assert res.n_buckets >= 1
    assert res.total_wire_s < ar.plan().total_wire_s


# -- ppermute (collective-matmul ring) leg ----------------------------------


def test_cost_model_fits_pp_leg_from_samples():
    from bagua_tpu.service.planner import DEFAULT_PP

    pp = AlphaBeta(alpha=15e-6, beta=120e9)
    samples = [
        WireSample(nbytes=n, seconds=pp.predict(n), leg="pp")
        for n in (1 << 20, 1 << 22, 1 << 24)
    ]
    cm = CostModel.from_samples(samples)
    np.testing.assert_allclose(cm.pp.alpha, pp.alpha, rtol=1e-6)
    np.testing.assert_allclose(cm.pp.beta, pp.beta, rtol=1e-6)
    # other legs untouched by pp samples; no pp samples -> the prior
    assert CostModel.from_samples([]).pp is DEFAULT_PP
    assert cm.flat is not None


def test_ring_matmul_wire_time():
    pp = AlphaBeta(alpha=10e-6, beta=100e9)
    cm = CostModel(pp=pp)
    nbytes, ring = 64 << 20, 8
    # ring_size - 1 neighbor hops, each carrying the per-rank shard
    expect = (ring - 1) * pp.predict(nbytes / ring)
    np.testing.assert_allclose(cm.ring_matmul_wire_time(nbytes, ring), expect)
    # degenerate rings cost nothing
    assert cm.ring_matmul_wire_time(nbytes, 1) == 0.0
    assert cm.ring_matmul_wire_time(nbytes, 0) == 0.0


def test_describe_includes_pp_row():
    rows = CostModel().describe()
    assert "pp" in rows
    assert set(rows["pp"]) == {"alpha_us", "beta_gbps", "n_samples"}


# -- quantized-ring (qr8/qr4) legs and the per-bucket precision chooser ------


def test_cost_model_fits_qr_legs_from_samples():
    from bagua_tpu.service.planner import DEFAULT_QR4, DEFAULT_QR8

    qr8 = AlphaBeta(alpha=25e-6, beta=110e9)
    qr4 = AlphaBeta(alpha=45e-6, beta=70e9)
    samples = [
        WireSample(nbytes=n, seconds=qr8.predict(n), leg="qr8")
        for n in (1 << 18, 1 << 20, 1 << 22)
    ] + [
        WireSample(nbytes=n, seconds=qr4.predict(n), leg="qr4")
        for n in (1 << 17, 1 << 19, 1 << 21)
    ]
    cm = CostModel.from_samples(samples)
    assert cm.qr8.alpha == pytest.approx(qr8.alpha, rel=1e-6)
    assert cm.qr8.beta == pytest.approx(qr8.beta, rel=1e-6)
    assert cm.qr4.alpha == pytest.approx(qr4.alpha, rel=1e-6)
    # no samples on a leg -> its prior; describe carries both rows
    assert CostModel.from_samples([]).qr8 is DEFAULT_QR8
    assert CostModel.from_samples([]).qr4 is DEFAULT_QR4
    assert {"qr8", "qr4"} <= set(CostModel().describe())


def test_quantized_hop_bytes_matches_kernel_accounting():
    """The planner's jax-free hop-byte mirror must agree exactly with the
    kernel module's ``ring_wire_bytes`` (2(n-1) hops per ring allreduce) —
    the drift guard for the deliberately duplicated formula."""
    from bagua_tpu.kernels.quantized_ring import ring_wire_bytes
    from bagua_tpu.service.planner import quantized_hop_bytes

    for numel in (1, 244, 4096, 12345678, 16 << 20):
        for n in (2, 4, 8, 32):
            for bits in (8, 4):
                assert (
                    quantized_hop_bytes(numel, n, bits) * 2 * (n - 1)
                    == ring_wire_bytes(numel, n, bits)
                ), (numel, n, bits)
    assert quantized_hop_bytes(1 << 20, 1, 8) == 0


def test_quantized_ring_wire_time_formula():
    from bagua_tpu.service.planner import quantized_hop_bytes

    qr8 = AlphaBeta(alpha=30e-6, beta=90e9)
    cm = CostModel(qr8=qr8)
    numel, n = 16 << 20, 8
    hop = quantized_hop_bytes(numel, n, 8)
    expect = 2 * (n - 1) * qr8.predict(hop)
    assert cm.quantized_ring_wire_time(numel, n, "int8") == pytest.approx(expect)
    # leg aliases and degenerate rings
    assert cm.quantized_ring_wire_time(numel, n, "qr8") == pytest.approx(expect)
    assert cm.quantized_ring_wire_time(numel, 1, "int4") == 0.0


def test_plan_precision_guardrail_allowlist():
    """The allow-list is the convergence guardrail: a quantized precision
    that would win on predicted wire time is only *chosen* once certified;
    until then it shows up as ``blocked`` in the record."""
    ds = decls([16 << 20])  # 64 MiB bucket: quantization clearly pays
    planner = BucketPlanner(ds, {"t0": 0.0})
    buckets = [[ds[0]]]
    rec = planner.plan_precision(buckets, n_ranks=8)  # default allow: f32 only
    assert rec["precisions"] == ["f32"]
    assert rec["allow_list"] == ["f32"]
    assert set(rec["per_bucket"][0]["blocked"]) == {"int8", "int4"}
    assert rec["saved_frac"] == 0.0
    # certify int8 only: it gets chosen, int4 (cheaper still at this size)
    # stays blocked
    rec8 = planner.plan_precision(buckets, n_ranks=8, allowed=("f32", "int8"))
    assert rec8["precisions"] == ["int8"]
    assert rec8["per_bucket"][0]["candidate_us"]["int8"] < rec8["per_bucket"][0][
        "candidate_us"
    ]["f32"]
    assert rec8["total_wire_ms"] < rec8["total_wire_ms_f32"]
    with pytest.raises(ValueError, match="unknown wire precisions"):
        planner.plan_precision(buckets, n_ranks=8, allowed=("bf16",))


def test_plan_precision_latency_floor_keeps_small_buckets_f32():
    """2(n-1) quantized hops carry a real latency floor: a tiny bucket is
    cheaper as one f32 collective even with everything certified, while a
    huge one flips to the quantized ring — the mixed plan emerges from the
    cost model, not from a hand-set threshold."""
    small, big = decls([64]), decls([64 << 20], prefix="b")
    planner = BucketPlanner(small + big, {"t0": 0.0, "b0": 0.1})
    rec = planner.plan_precision(
        [[small[0]], [big[0]]], n_ranks=8, allowed=("f32", "int8", "int4")
    )
    assert rec["precisions"][0] == "f32"
    assert rec["precisions"][1] in ("int8", "int4")
    assert rec["per_bucket"][0]["blocked"] == []  # f32 genuinely won


def test_plan_precision_nonfloat_and_degenerate_ring_stay_f32():
    ds = decls([1 << 22], dtype="i32")
    planner = BucketPlanner(ds, {"t0": 0.0})
    rec = planner.plan_precision([[ds[0]]], n_ranks=8, allowed=("f32", "int8"))
    assert rec["precisions"] == ["f32"]
    assert "int8" not in rec["per_bucket"][0]["candidate_us"]
    fds = decls([1 << 22])
    solo = BucketPlanner(fds, {"t0": 0.0})
    rec1 = solo.plan_precision([[fds[0]]], n_ranks=1, allowed=("f32", "int8"))
    assert rec1["precisions"] == ["f32"]


def test_plan_precision_sharded_prices_half_ring():
    """zero's gradient leg is only the reduce-scatter half of the quantized
    ring (the deferred param all-gather stays f32), so the sharded pattern's
    quantized candidate is exactly half the allreduce pattern's."""
    ds = decls([16 << 20])
    ar = BucketPlanner(ds, {"t0": 0.0}, wire_pattern="allreduce")
    sh = BucketPlanner(ds, {"t0": 0.0}, wire_pattern="sharded")
    a = ar.plan_precision([[ds[0]]], n_ranks=8, allowed=("f32", "int8"))
    s = sh.plan_precision([[ds[0]]], n_ranks=8, allowed=("f32", "int8"))
    assert s["per_bucket"][0]["candidate_us"]["int8"] == pytest.approx(
        a["per_bucket"][0]["candidate_us"]["int8"] / 2, rel=1e-3
    )


def test_fixture_precision_plan_is_mixed():
    """The acceptance operating point: on the recorded VGG16 spans, under the
    seed 10 MiB cap and an 8-rank ring with every precision certified, the
    chooser lands a genuinely mixed plan — small/late buckets stay f32 (hop
    latency floor), mid buckets ride int8, the big dense bucket int4 — and
    the record carries the allow-list the guardrail applied."""
    fx = json.load(open(FIXTURE))
    ds = [TensorDeclaration(**d) for d in fx["declarations"]]
    cm = CostModel.from_samples([WireSample(**s) for s in fx["wire_samples"]])
    planner = BucketPlanner(ds, fx["arrivals"], cost_model=cm, overlap_efficiency=0.0)
    dp = planner.plan(max_bucket_bytes=fx["seed_bucket_size_bytes"])
    rec = planner.plan_precision(
        dp.buckets, n_ranks=8, allowed=("f32", "int8", "int4")
    )
    chosen = set(rec["precisions"])
    assert "f32" in chosen and chosen & {"int8", "int4"}, rec["precisions"]
    assert len(chosen) >= 2
    assert rec["allow_list"] == ["f32", "int4", "int8"]
    assert rec["total_wire_ms"] < rec["total_wire_ms_f32"]
    assert 0.0 < rec["saved_frac"] < 1.0
    assert len(rec["precisions"]) == dp.n_buckets == len(rec["per_bucket"])


def test_manager_precision_allowlist_feeds_decision_trail():
    """Service side: bucket_wire spans carry world_size; the default trail
    shows the guardrail blocking quantization, and installing a certified
    allow-list re-chooses precisions in place."""
    from bagua_tpu.service.autotune_task_manager import AutotuneTaskManager

    mgr = AutotuneTaskManager("m", planner_mode="warmstart")
    mgr.tensor_list = decls([1 << 22] * 4)  # 16 MiB each: quantization pays
    span = wire_span(nbytes=1 << 24, seconds=2e-3, hidden_frac=0.0)
    span["world_size"] = 8
    mgr.report_spans(ready_spans((f"t{i}", 0.01 * i) for i in range(4)) + [span])
    trail = mgr.decision_trail["precision_plan"]
    assert trail is not None
    assert trail["allow_list"] == ["f32"] and trail["n_ranks"] == 8
    assert set(trail["precisions"]) == {"f32"}
    assert any(row["blocked"] for row in trail["per_bucket"])
    mgr.set_precision_allow_list(["int8", "int4"])
    trail = mgr.decision_trail["precision_plan"]
    assert trail["allow_list"] == ["f32", "int4", "int8"]
    assert set(trail["precisions"]) & {"int8", "int4"}
    with pytest.raises(ValueError, match="unknown wire precisions"):
        mgr.set_precision_allow_list(["fp8"])
