"""Backward-overlapped compressed & hierarchical exchange: bitwise parity.

The overlap engine anchors each bucket's wire program (ByteGrad's compress →
all-to-all → fused reduce → all-gather → decompress pipeline, QAdam's
phase-switched exchange, decentralized peer averaging) inside the backward
pass.  Because ``flatten_bucket_leaves``/``split_bucket_flat`` reproduce the
monolithic path's padded bucket layout exactly, every chunk boundary — and
therefore every quantization decision — is identical, so overlap vs.
monolithic must be **bitwise** equal for ByteGrad/QAdam/decentralized (the
acceptance criterion in ISSUE.md).  Low-precision decentralized is the
deliberate exception: its per-bucket min/max granularity changes with the
plan, so it is close-but-not-bitwise and ``"auto"`` must never enable it.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.algorithms.bytegrad import ByteGradAlgorithm
from bagua_tpu.algorithms.decentralized import (
    DecentralizedAlgorithm,
    LowPrecisionDecentralizedAlgorithm,
)
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

N_STEPS = 4
GLOBAL_BATCH = 32
DIM_IN, DIM_OUT = 12, 4
LAYERS = [DIM_IN, 16, 16, DIM_OUT]
BUCKET = 1 << 9  # small: forces several buckets on the tiny MLP


def make_problem(seed=0):
    params = init_mlp(jax.random.PRNGKey(seed), LAYERS)
    rng = np.random.RandomState(seed)
    xs = rng.randn(N_STEPS, GLOBAL_BATCH, DIM_IN).astype(np.float32)
    ys = rng.randn(N_STEPS, GLOBAL_BATCH, DIM_OUT).astype(np.float32)
    return params, xs, ys


def run_final(group, algo, overlap, params, xs, ys, optimizer="sgd",
              bucket=BUCKET, steps=N_STEPS):
    """Train ``steps`` steps; return (ddp, stacked-final-params leaves)."""
    opt = optax.sgd(0.1) if optimizer == "sgd" else optimizer
    ddp = DistributedDataParallel(
        mse_loss, opt, algo, process_group=group,
        bucket_size_bytes=bucket, overlap=overlap,
    )
    state = ddp.init(params)
    for i in range(steps):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
    return ddp, jax.tree.leaves(state.params)


def assert_bitwise(a_leaves, b_leaves):
    for a, b in zip(a_leaves, b_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("hierarchical", [True, False], ids=["hier", "flat"])
def test_bytegrad_overlap_bitwise(group, hierarchical):
    """Per-bucket overlap exchange runs the same compress → exchange →
    fused-reduce → decompress program on the same padded flat buffers as the
    monolithic loop, so final params match bit for bit on every rank."""
    params, xs, ys = make_problem(seed=21)
    finals = {}
    for overlap in (False, True):
        ddp, leaves = run_final(
            group, ByteGradAlgorithm(hierarchical=hierarchical),
            overlap, params, xs, ys,
        )
        assert ddp.plan.num_buckets > 1
        assert ddp.overlap_enabled is overlap
        finals[overlap] = leaves
    assert_bitwise(finals[False], finals[True])


def test_qadam_overlap_bitwise_across_phase_switch(group):
    """QAdam's overlap_exchange threads both phases through one traced
    ``lax.cond`` on the step counter, so a run crossing the warmup boundary
    (warmup_steps=2, 4 steps) must stay bitwise identical to the monolithic
    path in BOTH phases — full-precision averaging and quantized momentum."""
    params, xs, ys = make_problem(seed=22)
    finals = {}
    for overlap in (False, True):
        algo = build_algorithm("qadam", lr=0.1, qadam_warmup_steps=2)
        ddp, leaves = run_final(
            group, algo, overlap, params, xs, ys, optimizer=None,
        )
        assert ddp.plan.num_buckets > 1
        assert ddp.overlap_enabled is overlap
        finals[overlap] = leaves
    assert_bitwise(finals[False], finals[True])


@pytest.mark.parametrize(
    "mode,hierarchical", [("all", True), ("shift_one", False)],
    ids=["all-hier", "shift_one"],
)
def test_decentralized_overlap_bitwise(group, mode, hierarchical):
    """Weight-mode overlap: peer averaging is elementwise, so splitting the
    mega-bucket into per-bucket exchanges issued in backward order cannot
    change a single bit.  The monolithic path keeps its 1-bucket plan; the
    overlap path switches to a multi-bucket plan via overlap_hint."""
    params, xs, ys = make_problem(seed=23)
    algo_kw = dict(hierarchical=hierarchical, peer_selection_mode=mode)
    mono, mono_leaves = run_final(
        group, DecentralizedAlgorithm(**algo_kw), False, params, xs, ys,
    )
    ov, ov_leaves = run_final(
        group, DecentralizedAlgorithm(**algo_kw), True, params, xs, ys,
    )
    assert mono.plan.num_buckets == 1  # mega-bucket without overlap
    assert ov.plan.num_buckets > 1
    assert ov.impl.overlap_capability().mode == "weight"
    assert_bitwise(mono_leaves, ov_leaves)


def test_low_precision_decentralized_overlap_close_not_bitwise(group):
    """LP-decentralized overlap changes quantization granularity (per-bucket
    min/max instead of one global pair), so parity is close-but-not-bitwise:
    explicit opt-in converges to the same weights within quantization error,
    and 'auto' must resolve to the monolithic path (capability auto=False)."""
    params, xs, ys = make_problem(seed=24)
    auto = DistributedDataParallel(
        mse_loss, optax.sgd(0.1),
        build_algorithm("low_precision_decentralized"),
        process_group=group, overlap="auto",
    )
    assert auto.overlap_enabled is False
    cap = auto.impl.overlap_capability()
    assert cap.supported and not cap.auto
    assert "quantization granularity" in cap.reason

    mono, mono_leaves = run_final(
        group, LowPrecisionDecentralizedAlgorithm(), False, params, xs, ys,
    )
    ov, ov_leaves = run_final(
        group, LowPrecisionDecentralizedAlgorithm(), True, params, xs, ys,
    )
    assert mono.plan.num_buckets == 1 and ov.plan.num_buckets > 1
    for a, b in zip(mono_leaves, ov_leaves):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=2e-2
        )


def test_bytegrad_overlap_census_one_pipeline_per_bucket(group):
    """Wire-pattern acceptance at test scale (ci/perf_audit.py asserts the
    same on VGG16): the overlapped compiled step carries exactly one
    uint8-payload all-to-all and all-gather per bucket — each bucket's
    compressed pipeline anchored separately in the backward pass, none
    merged.  (Each pipeline also ships a small f32 min/max sidecar through
    its own collective, so we count by dtype, as ci/perf_audit.py does.)"""
    params, xs, ys = make_problem(seed=25)
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.1), ByteGradAlgorithm(hierarchical=False),
        process_group=group, bucket_size_bytes=BUCKET, overlap=True,
    )
    state = ddp.init(params)
    fn = ddp._build_step(ddp.impl.step_variant(0))
    text = fn.lower(
        state, (jnp.asarray(xs[0]), jnp.asarray(ys[0]))
    ).compile().as_text()
    n_buckets = ddp.plan.num_buckets
    assert n_buckets > 1

    def count_u8(op):
        return sum(
            1 for line in text.splitlines()
            if re.search(rf"\b{op}(-start)?\(", line) and "u8[" in line
        )

    assert count_u8("all-to-all") == n_buckets
    assert count_u8("all-gather") == n_buckets


def test_auto_enables_overlap_for_compressed_algorithms(group):
    """'auto' resolution now consults the per-algorithm capability report:
    bytegrad and qadam report gradient-mode, numerics-preserving overlap."""
    for algo in (
        ByteGradAlgorithm(),
        build_algorithm("qadam", lr=0.1, qadam_warmup_steps=2),
    ):
        opt = optax.sgd(0.1) if isinstance(algo, ByteGradAlgorithm) else None
        ddp = DistributedDataParallel(
            mse_loss, opt, algo, process_group=group,
            bucket_size_bytes=BUCKET, overlap="auto",
        )
        assert ddp.overlap_enabled is True
        cap = ddp.impl.overlap_capability()
        assert cap.supported and cap.auto and cap.mode == "gradient"
