"""Training-health guardrail: detector units, actions, engine integration.

Pins the acceptance criteria:

* the in-graph health scalars are **bitwise-inert**: training with the
  monitor on vs off produces bitwise-identical parameters, for both the
  allreduce and the ZeRO (sharded-optimizer) paths with overlap on;
* the detector raises ``loss_spike`` (EWMA z-score), ``grad_norm_explosion``
  (factor over its EWMA), and ``nonfinite`` (latched once), with warmup
  suppression and NaN-poisoning resistance;
* ``health_alert`` events validate against the JSONL schema;
* :class:`PrecisionDemotionAction` demotes a planner-chosen wire plan one
  rung (int8→f32) under ``wire_precision="auto"`` and refuses to touch a
  user-pinned precision; :class:`SnapshotOnAnomalyAction` fires exactly once;
* a raising action is contained — the step loop never sees it.
"""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.contrib.zero import zero_optimizer
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.observability import (
    HealthConfig,
    HealthMonitor,
    PrecisionDemotionAction,
    SnapshotOnAnomalyAction,
    Telemetry,
    health_scalars,
    validate_metrics_file,
)

LAYERS = [12, 16, 16, 4]
N = 8


def make_batch(seed=0, batch=32, scale=1.0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(batch, LAYERS[0]).astype(np.float32))
    y = jnp.asarray(scale * rng.randn(batch, LAYERS[-1]).astype(np.float32))
    return x, y


# -- in-graph scalars ---------------------------------------------------------


def test_health_scalars_values():
    grads = {"w": jnp.asarray([3.0, 4.0], jnp.float32),
             "b": jnp.asarray([[0.0]], jnp.float32),
             "n_steps": jnp.asarray(7, jnp.int32)}  # non-inexact leaf: ignored
    h = np.asarray(health_scalars(jnp.asarray(2.5), grads))
    assert h.shape == (3,) and h.dtype == np.float32
    assert h[0] == pytest.approx(2.5)
    assert h[1] == pytest.approx(5.0)  # sqrt(9+16+0)
    assert h[2] == 0.0

    grads["w"] = jnp.asarray([np.nan, np.inf], jnp.float32)
    h = np.asarray(health_scalars(jnp.asarray(1.0), grads))
    assert h[2] == 2.0 and not math.isfinite(float(h[1]))


# -- detector units -----------------------------------------------------------


def feed_steady(mon, n, loss=1.0, gn=1.0, start=0):
    for i in range(n):
        assert mon.observe(step=start + i, loss=loss, grad_norm=gn, nonfinite=0) == []


def test_warmup_suppresses_alerts():
    mon = HealthMonitor(config=HealthConfig(warmup_steps=5, loss_z_threshold=2.0))
    # wild values during warmup: no alerts while the EWMAs settle
    for i, loss in enumerate([1.0, 100.0, 0.01, 50.0, 2.0]):
        assert mon.observe(step=i, loss=loss, grad_norm=1.0, nonfinite=0) == []
    assert mon.report()["observed_steps"] == 5


def test_loss_spike_z_score():
    mon = HealthMonitor(config=HealthConfig(warmup_steps=3, loss_z_threshold=6.0))
    feed_steady(mon, 10, loss=1.0)
    alerts = mon.observe(step=10, loss=1000.0, grad_norm=1.0, nonfinite=0)
    assert [a["kind"] for a in alerts] == ["loss_spike"]
    a = alerts[0]
    assert a["value"] == 1000.0 and a["threshold"] == 6.0 and a["step"] == 10
    assert "z=" in a["detail"]
    # a flat loss cannot alert on numerical noise (min_std floor)
    mon2 = HealthMonitor(config=HealthConfig(warmup_steps=3))
    feed_steady(mon2, 10, loss=1.0)
    assert mon2.observe(step=10, loss=1.0 + 1e-9, grad_norm=1.0, nonfinite=0) == []


def test_grad_norm_explosion():
    mon = HealthMonitor(config=HealthConfig(warmup_steps=3, grad_norm_factor=10.0,
                                            loss_z_threshold=1e9))
    feed_steady(mon, 8, gn=2.0)
    alerts = mon.observe(step=8, loss=1.0, grad_norm=50.0, nonfinite=0)
    assert [a["kind"] for a in alerts] == ["grad_norm_explosion"]
    assert alerts[0]["threshold"] == pytest.approx(20.0)


def test_nan_latch_fires_once_and_does_not_poison_ewma():
    mon = HealthMonitor(config=HealthConfig(warmup_steps=3, loss_z_threshold=6.0))
    feed_steady(mon, 8, loss=1.0)
    mean_before = mon._loss_mean
    alerts = mon.observe(step=8, loss=float("nan"), grad_norm=1.0, nonfinite=3)
    assert [a["kind"] for a in alerts] == ["nonfinite"]
    assert mon.nan_latched
    # the NaN never entered the EWMA
    assert mon._loss_mean == pytest.approx(mean_before, rel=1e-6)
    # second nonfinite step: counted, not re-alerted
    assert mon.observe(step=9, loss=float("inf"), grad_norm=1.0, nonfinite=1) == []
    # a healthy step afterwards is still judged against clean statistics
    assert mon.observe(step=10, loss=1.0, grad_norm=1.0, nonfinite=0) == []


def test_actions_run_in_order_and_failures_are_contained():
    calls = []

    def ok(alert, state):
        calls.append("ok")
        return True

    def declined(alert, state):
        calls.append("declined")
        return False

    def boom(alert, state):
        calls.append("boom")
        raise RuntimeError("action blew up")

    ok.name = "ok_action"
    mon = HealthMonitor(config=HealthConfig(warmup_steps=1, loss_z_threshold=2.0),
                        actions=[ok, declined, boom])
    feed_steady(mon, 5, loss=1.0)
    alerts = mon.observe(step=5, loss=1e6, grad_norm=1.0, nonfinite=0)
    assert len(alerts) == 1
    # only the applier is recorded; the raiser was logged and skipped
    assert alerts[0]["actions"] == ["ok_action"]
    assert calls == ["ok", "declined", "boom"]


def test_alert_history_ring_is_bounded():
    mon = HealthMonitor(config=HealthConfig(warmup_steps=1, loss_z_threshold=2.0,
                                            max_alerts=4))
    feed_steady(mon, 3, loss=1.0)
    raised = []
    for i in range(10):
        # geometric spikes: each is far outside even the post-spike EWMA std
        raised += mon.observe(step=10 + i, loss=1e3 * 100.0 ** i, grad_norm=1.0,
                              nonfinite=0)
    assert len(raised) > 4  # enough alerts to overflow the ring...
    assert len(mon.alerts) <= 4  # ...which keeps only the most recent
    assert mon.alerts == raised[-len(mon.alerts):]


def test_health_alert_event_schema(tmp_path):
    path = str(tmp_path / "m.jsonl")
    tel = Telemetry(metrics_jsonl=path)
    mon = HealthMonitor(telemetry=tel,
                        config=HealthConfig(warmup_steps=1, loss_z_threshold=2.0))
    feed_steady(mon, 5, loss=1.0)
    mon.observe(step=5, loss=1e6, grad_norm=1.0, nonfinite=0)
    tel.close()
    assert validate_metrics_file(path) == []
    events = [json.loads(l) for l in open(path)]
    ha = [e for e in events if e["event"] == "health_alert"]
    assert len(ha) == 1
    assert ha[0]["kind"] == "loss_spike" and ha[0]["step"] == 5
    assert isinstance(ha[0]["value"], float) and isinstance(ha[0]["actions"], list)


# -- bitwise inertness (acceptance) -------------------------------------------


def _run_steps(group, opt_fn, monitor, n_steps=4):
    ddp = DistributedDataParallel(
        mse_loss, opt_fn(), GradientAllReduceAlgorithm(),
        process_group=group, bucket_size_bytes=1 << 9, overlap=True,
        health_monitor=monitor,
    )
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    for i in range(n_steps):
        state, _ = ddp.train_step(state, make_batch(seed=i))
    leaves = [np.asarray(l) for l in jax.tree.leaves(state.params)]
    ddp.shutdown()
    return leaves


@pytest.mark.parametrize("opt_fn", [
    pytest.param(lambda: optax.adam(1e-2), id="gradient_allreduce"),
    pytest.param(lambda: zero_optimizer(optax.adam(1e-2), n_shards=N), id="zero"),
])
def test_monitor_is_bitwise_inert(group, opt_fn):
    """Params after N overlapped steps are bitwise-identical with the
    monitor on vs off — the health scalars are pure reads."""
    mon = HealthMonitor(config=HealthConfig(warmup_steps=1))
    with_mon = _run_steps(group, opt_fn, mon)
    without = _run_steps(group, opt_fn, None)
    assert mon.report()["observed_steps"] > 0  # the monitor really observed
    for a, b in zip(with_mon, without):
        np.testing.assert_array_equal(a, b)
        assert a.tobytes() == b.tobytes()


# -- actions against the real engine ------------------------------------------


def test_precision_demotion_under_auto_plan(group):
    """The verified demotion recipe: wire_precision="auto" + a
    planner-adopted int8 plan; a loss spike demotes every bucket to f32."""
    mon = HealthMonitor(config=HealthConfig(warmup_steps=2, loss_z_threshold=4.0))
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05),
        build_algorithm("gradient_allreduce", wire_precision="auto"),
        # "auto" holds per-bucket EF state, so backward-overlap is fenced
        process_group=group, bucket_size_bytes=1 << 9, overlap="auto",
        health_monitor=mon,
    )
    mon.register_action(PrecisionDemotionAction(ddp))
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    state, _ = ddp.train_step(state, make_batch(0))  # plan exists after warmup
    assert ddp.apply_precision_plan(["int8"] * ddp.plan.num_buckets,
                                    reason="planner")
    for i in range(1, 6):
        state, _ = ddp.train_step(state, make_batch(i))
    assert mon.alerts == []
    assert list(ddp.impl.bucket_precisions(ddp.plan)) == ["int8"] * ddp.plan.num_buckets
    # synthetic divergence: targets scaled 1000x
    state, _ = ddp.train_step(state, make_batch(99, scale=1000.0))
    kinds = {a["kind"] for a in mon.alerts}
    assert "loss_spike" in kinds
    applied = [a for a in mon.alerts if "precision_demotion" in a["actions"]]
    assert applied, mon.alerts
    assert list(ddp.impl.bucket_precisions(ddp.plan)) == ["f32"] * ddp.plan.num_buckets
    # training continues on the demoted wire
    state, _ = ddp.train_step(state, make_batch(100))
    ddp.shutdown()


def test_precision_demotion_refuses_pinned_precision(group):
    """A uniform pinned wire_precision is an explicit operator choice —
    the action declines instead of overriding it."""
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05),
        build_algorithm("gradient_allreduce", wire_precision="int8"),
        process_group=group, bucket_size_bytes=1 << 9, overlap=False,
    )
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    state, _ = ddp.train_step(state, make_batch(0))
    action = PrecisionDemotionAction(ddp)
    assert action({"kind": "loss_spike"}, None) is False
    assert list(ddp.impl.bucket_precisions(ddp.plan)) == ["int8"] * ddp.plan.num_buckets
    ddp.shutdown()


def test_precision_demotion_noop_without_knob_or_at_f32(group):
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(),
        process_group=group, bucket_size_bytes=1 << 9, overlap=False,
    )
    action = PrecisionDemotionAction(ddp)
    assert action({"kind": "loss_spike"}, None) is False  # no plan yet
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    state, _ = ddp.train_step(state, make_batch(0))
    assert action({"kind": "loss_spike"}, None) is False  # plain f32 algorithm
    ddp.shutdown()


def test_snapshot_on_anomaly_fires_once():
    class Snap:
        def __init__(self):
            self.calls = []

        def snapshot(self, state, step, blocking=False, kind="async"):
            self.calls.append((step, blocking, kind))

    snap = Snap()
    action = SnapshotOnAnomalyAction(snap)
    assert action({"kind": "loss_spike", "step": 7}, state={"p": 1}) is True
    assert action({"kind": "nonfinite", "step": 8}, state={"p": 1}) is False
    assert snap.calls == [(7, True, "anomaly")]
    # no state (detector-only caller): declines without firing
    fresh = SnapshotOnAnomalyAction(snap)
    assert fresh({"kind": "loss_spike", "step": 1}, state=None) is False
    assert not fresh.fired
