"""Goodput / MFU accounting: analytic FLOPs, the wall-clock ledger, gauges.

Pins the acceptance criteria of the goodput meter:

* the analytic VGG16 estimator reproduces the perf-audit hand-math
  (``32 img × 46.5 GFLOP = 1.49 TF/step/chip``, compute floor 7.6 ms at
  100% MFU on a 197 TFLOP/s v5e) within 5%;
* the ledger's clocked buckets sum to the elapsed wall time — exactly under
  a fake clock, within 1% over a real engine run with a forced recompile
  and a blocking snapshot ride-along;
* compile wall lands in the ``compile_ms`` histogram, the recompile
  detector's ``compile_ms_total``, and the ledger's ``compile`` bucket;
* ``wire_efficiency`` divides the planner-predicted α–β wire time by the
  measured one.
"""

import numpy as np
import optax
import pytest

import bagua_tpu
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.observability import (
    GoodputLedger,
    GoodputMeter,
    MetricsRegistry,
    Telemetry,
    flops_from_cost_analysis,
    model_flops_per_sample,
    predicted_wire_time,
    register_model_flops,
)
from bagua_tpu.observability.goodput import (
    LEDGER_BUCKETS,
    PEAK_FLOPS_PER_CHIP,
    TRAIN_FLOPS_MULTIPLIER,
    mlp_fwd_flops,
    vgg16_fwd_flops,
)

# the perf-audit hand-math constants (ci/perf_audit.py render_md)
AUDIT_VGG16_TRAIN_GFLOP = 46.5e9
AUDIT_V5E_PEAK = 197e12


# -- analytic estimators ------------------------------------------------------


def test_vgg16_flops_match_audit_hand_math():
    train = model_flops_per_sample("vgg16")
    assert train == pytest.approx(AUDIT_VGG16_TRAIN_GFLOP, rel=0.05)
    fwd = vgg16_fwd_flops()
    assert fwd * TRAIN_FLOPS_MULTIPLIER == train
    assert fwd == pytest.approx(15.5e9, rel=0.05)


def test_mfu_matches_audit_compute_floor():
    # audit: 32 img × 46.5 GFLOP = 1.49 TF/step/chip; 1.49/197 = 7.6 ms at
    # 100% MFU.  A step taking exactly the compute floor must report MFU≈1.
    reg = MetricsRegistry()
    meter = GoodputMeter(model="vgg16", peak_flops_per_chip="v5e", n_chips=1,
                         registry=reg)
    floor_s = 32 * AUDIT_VGG16_TRAIN_GFLOP / AUDIT_V5E_PEAK
    mfu = meter.on_step(wall_s=floor_s, n_samples=32)
    assert mfu == pytest.approx(1.0, rel=0.05)
    assert reg.snapshot()["mfu"] == pytest.approx(mfu, rel=1e-6)
    assert reg.snapshot()["model_flops_per_step"] == pytest.approx(
        32 * AUDIT_VGG16_TRAIN_GFLOP, rel=0.05)
    # half the throughput -> half the MFU; spread over 8 chips -> 1/8 each
    assert meter.on_step(wall_s=2 * floor_s, n_samples=32) == pytest.approx(
        mfu / 2, rel=1e-6)
    meter8 = GoodputMeter(model="vgg16", peak_flops_per_chip="v5e", n_chips=8)
    assert meter8.on_step(wall_s=floor_s, n_samples=32) == pytest.approx(
        mfu / 8, rel=1e-6)


def test_mlp_flops_and_registry():
    assert mlp_fwd_flops([64, 128, 4]) == 64 * 128 + 128 * 4
    assert model_flops_per_sample("mlp", sizes=[64, 128, 4]) == pytest.approx(
        3.0 * (64 * 128 + 128 * 4))
    assert model_flops_per_sample("mlp", train=False, sizes=[64, 128, 4]) == (
        64 * 128 + 128 * 4)
    with pytest.raises(KeyError):
        model_flops_per_sample("resnet9000")
    register_model_flops("toy", lambda width=2: 10.0 * width)
    assert model_flops_per_sample("toy", width=3) == pytest.approx(90.0)
    assert "v5e" in PEAK_FLOPS_PER_CHIP and PEAK_FLOPS_PER_CHIP["v5e"] == AUDIT_V5E_PEAK


def test_flops_from_cost_analysis_shapes():
    class C:
        def __init__(self, ca):
            self._ca = ca

        def cost_analysis(self):
            if isinstance(self._ca, Exception):
                raise self._ca
            return self._ca

    assert flops_from_cost_analysis(C({"flops": 123.0})) == 123.0
    assert flops_from_cost_analysis(C([{"flops": 7}])) == 7.0
    assert flops_from_cost_analysis(C({})) is None
    assert flops_from_cost_analysis(C({"flops": -1.0})) is None
    assert flops_from_cost_analysis(C({"flops": "n/a"})) is None
    assert flops_from_cost_analysis(C([])) is None
    assert flops_from_cost_analysis(C(RuntimeError("no backend"))) is None


def test_calibrate_from_compiled_adopts_xla_count():
    meter = GoodputMeter(flops_per_sample=1.0)

    class C:
        def cost_analysis(self):
            return {"flops": 640.0}

    assert meter.calibrate_from_compiled(C(), n_samples=32) == pytest.approx(20.0)
    assert meter.flops_per_sample == pytest.approx(20.0)

    class N:
        def cost_analysis(self):
            return {}

    # nothing reported: keep the previous estimate
    assert meter.calibrate_from_compiled(N(), n_samples=32) is None
    assert meter.flops_per_sample == pytest.approx(20.0)


# -- the ledger ---------------------------------------------------------------


def test_ledger_partitions_wall_exactly_under_fake_clock():
    t = [100.0]
    led = GoodputLedger(clock=lambda: t[0])
    t[0] += 2.0          # 2 s startup
    led.enter("productive")
    t[0] += 5.0          # 5 s productive
    led.enter("data")
    t[0] += 1.0          # 1 s data
    led.enter("productive")
    t[0] += 4.0          # 4 s productive (1.5 of which was really a compile)
    led.reattribute("productive", "compile", 1.5)
    led.charge("lost_restart", 3.0)   # synthetic: previous incarnation's wall
    rep = led.report()
    b = rep["buckets"]
    assert b["startup"] == pytest.approx(2.0)
    assert b["productive"] == pytest.approx(7.5)
    assert b["data"] == pytest.approx(1.0)
    assert b["compile"] == pytest.approx(1.5)
    assert b["lost_restart"] == pytest.approx(3.0)
    assert rep["synthetic_s"] == pytest.approx(3.0)
    assert rep["wall_s"] == pytest.approx(12.0)
    # the identity: clocked buckets partition the wall exactly
    assert sum(b.values()) - rep["synthetic_s"] == pytest.approx(rep["wall_s"])
    assert rep["goodput_frac"] == pytest.approx(7.5 / 12.0)
    assert set(b) >= set(LEDGER_BUCKETS)


def test_ledger_reattribute_never_overdraws():
    t = [0.0]
    led = GoodputLedger(clock=lambda: t[0])
    led.enter("productive")
    t[0] += 1.0
    led.reattribute("productive", "compile", 99.0)  # capped at what's there
    rep = led.report()
    assert rep["buckets"]["productive"] == pytest.approx(0.0)
    assert rep["buckets"]["compile"] == pytest.approx(1.0)
    assert sum(rep["buckets"].values()) == pytest.approx(rep["wall_s"])


def test_on_restart_prices_lost_steps_at_p50():
    meter = GoodputMeter(flops_per_sample=1.0)
    for w in (0.1, 0.2, 0.3, 0.2, 0.2):
        meter.on_step(wall_s=w, n_samples=1)
    meter.on_restart(lost_steps=4)
    rep = meter.ledger.report()
    assert rep["buckets"]["lost_restart"] == pytest.approx(4 * 0.2)
    assert rep["synthetic_s"] == pytest.approx(4 * 0.2)


# -- wire efficiency ----------------------------------------------------------


class FakeCostModel:
    def bucket_wire_time(self, nbytes, hierarchical=False, wire_pattern="allreduce"):
        return 1e-6 + nbytes / 1e9  # alpha + beta * bytes


def test_predicted_wire_time_and_efficiency_gauge():
    cm = FakeCostModel()
    buckets = [1 << 20, 1 << 20, 1 << 18]
    predicted = predicted_wire_time(cm, buckets)
    assert predicted == pytest.approx(sum(1e-6 + b / 1e9 for b in buckets))

    reg = MetricsRegistry()
    meter = GoodputMeter(flops_per_sample=1.0, cost_model=cm,
                         bucket_bytes=buckets, registry=reg)
    assert meter.predicted_wire_s() == pytest.approx(predicted)
    eff = meter.observe_wire(measured_wire_s=2 * predicted)
    assert eff == pytest.approx(0.5)
    assert reg.snapshot()["wire_efficiency"] == pytest.approx(0.5, abs=1e-6)
    # no cost model -> no gauge, no crash
    bare = GoodputMeter(flops_per_sample=1.0)
    assert bare.predicted_wire_s() is None
    assert bare.observe_wire(1.0) is None


# -- end-to-end: ledger over a real engine run --------------------------------


def test_ledger_sums_to_wall_over_real_run(group, tmp_path):
    """Acceptance: buckets sum to wall time ±1% over a run with a forced
    recompile and a blocking snapshot ride-along."""
    meter = GoodputMeter(model="mlp", model_kwargs={"sizes": [12, 16, 16, 4]},
                         n_chips=8)
    tel = Telemetry(metrics_jsonl=str(tmp_path / "m.jsonl"), goodput=meter)
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.1), GradientAllReduceAlgorithm(),
        process_group=group, bucket_size_bytes=1 << 9, overlap=True,
        telemetry=tel,
    )
    rng = np.random.RandomState(0)
    params = init_mlp(__import__("jax").random.PRNGKey(0), [12, 16, 16, 4])
    state = ddp.init(params)
    x = rng.randn(32, 12).astype(np.float32)
    y = rng.randn(32, 4).astype(np.float32)
    for _ in range(4):
        state, _ = ddp.train_step(state, (x, y))
    # forced recompile: new batch shape -> new jit variant
    x2 = rng.randn(16, 12).astype(np.float32)
    y2 = rng.randn(16, 4).astype(np.float32)
    state, _ = ddp.train_step(state, (x2, y2))
    # a blocking snapshot stalls the loop; the hub re-attributes its wall
    tel.on_snapshot(step=5, wall_ms=25.0, n_bytes=1 << 10, kind="forced")
    rep = meter.report()["ledger"]
    clocked = sum(rep["buckets"].values()) - rep["synthetic_s"]
    assert clocked == pytest.approx(rep["wall_s"], rel=0.01)
    # both compiles were re-attributed out of productive
    assert rep["buckets"]["compile"] > 0
    assert rep["buckets"]["snapshot"] >= 25e-3 * 0.9
    assert 0 < rep["goodput_frac"] < 1
    assert meter.last_mfu is not None and meter.last_mfu > 0
    ddp.shutdown()
    tel.close()


def test_compile_wall_lands_in_histogram_and_detector(group, tmp_path):
    meter = GoodputMeter(flops_per_sample=1.0)
    tel = Telemetry(metrics_jsonl=str(tmp_path / "m.jsonl"), goodput=meter)
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.1), GradientAllReduceAlgorithm(),
        process_group=group, bucket_size_bytes=1 << 9, overlap=False,
        telemetry=tel,
    )
    params = init_mlp(__import__("jax").random.PRNGKey(0), [12, 16, 16, 4])
    state = ddp.init(params)
    rng = np.random.RandomState(0)
    batch = (rng.randn(32, 12).astype(np.float32),
             rng.randn(32, 4).astype(np.float32))
    for _ in range(3):
        state, _ = ddp.train_step(state, batch)
    snap = tel.registry.snapshot()
    assert snap["compile_ms"]["count"] == 1  # exactly the warmup compile
    rec = tel.recompile.report()
    assert rec["compile_ms_total"] > 0
    assert set(rec["compile_ms_by_variant"]) == set(rec["compiles_by_variant"])
    assert rec["compile_ms_total"] == pytest.approx(
        sum(rec["compile_ms_by_variant"].values()), rel=1e-6)
    ddp.shutdown()
    tel.close()
