"""Contrib tier tests: fused optimizer equivalence, stores, cache loader,
cached dataset, load-balancing samplers, sync batch norm, shm store
(reference ``tests/contrib/``)."""

import multiprocessing
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.contrib import (
    CacheLoader,
    CachedDataset,
    ClusterStore,
    FileStore,
    InMemoryStore,
    LoadBalancingDistributedBatchSampler,
    LoadBalancingDistributedSampler,
    SyncBatchNorm,
    fuse_optimizer,
)
from bagua_tpu.models.mlp import init_mlp, mse_loss


# ---------------- fused optimizer (reference test_fused_optimizer.py) -------


@pytest.mark.parametrize("make_opt", [lambda: optax.sgd(0.1, momentum=0.9), lambda: optax.adam(1e-2)])
def test_fused_optimizer_matches_unfused(make_opt):
    params = init_mlp(jax.random.PRNGKey(0), [8, 16, 4])
    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 0.1, params)

    plain, fused = make_opt(), fuse_optimizer(make_opt())
    ps, fs = plain.init(params), fused.init(params)
    p1, f1 = dict(params), dict(params)
    for _ in range(5):
        up, ps = plain.update(grads, ps, p1)
        p1 = optax.apply_updates(p1, up)
        uf, fs = fused.update(grads, fs, f1)
        f1 = optax.apply_updates(f1, uf)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(f1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_fused_optimizer_in_ddp(group):
    """fuse_optimizer composes with the DDP engine."""
    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.ddp import DistributedDataParallel

    params = init_mlp(jax.random.PRNGKey(1), [8, 16, 4])
    ddp = DistributedDataParallel(
        mse_loss, fuse_optimizer(optax.adam(1e-3)), GradientAllReduceAlgorithm(),
        process_group=group,
    )
    state = ddp.init(params)
    rng = np.random.RandomState(0)
    state, losses = ddp.train_step(
        state, (jnp.asarray(rng.randn(16, 8), np.float32), jnp.asarray(rng.randn(16, 4), np.float32))
    )
    assert np.isfinite(np.asarray(losses)).all()


# ---------------- stores ----------------------------------------------------


@pytest.mark.parametrize("make_store", [InMemoryStore, FileStore])
def test_store_basic(make_store):
    s = make_store()
    s.clear()
    assert s.get("a") is None
    s.set("a", {"x": 1})
    s.set("b", [1, 2, 3])
    assert s.get("a") == {"x": 1}
    assert s.get("b") == [1, 2, 3]
    assert s.num_keys() == 2
    s.mset({"c": 1, "d": 2})
    assert s.mget(["c", "d", "nope"]) == [1, 2, None]
    s.clear()
    assert s.num_keys() == 0


def test_cluster_store_routing():
    backends = [InMemoryStore() for _ in range(3)]
    cs = ClusterStore(backends)
    for i in range(50):
        cs.set(f"key{i}", i)
    assert cs.num_keys() == 50
    assert all(cs.get(f"key{i}") == i for i in range(50))
    # keys actually spread over backends
    assert sum(1 for b in backends if b.num_keys() > 0) >= 2
    cs.clear()
    assert cs.num_keys() == 0


# ---------------- cache loader / cached dataset ------------------------------


def test_cache_loader_batching_and_hits():
    loads = []

    def load(k):
        loads.append(k)
        return int(k) * 2

    cl = CacheLoader(backend="memory", dataset_name="d", writer_buffer_size=4)
    for i in range(8):
        assert cl.get(str(i), load) == i * 2
    assert len(loads) == 8
    for i in range(8):
        assert cl.get(str(i), load) == i * 2
    assert len(loads) == 8  # all hits
    assert cl.num_keys() == 8
    assert cl.hit_rate == 0.5


class SlowDataset:
    def __init__(self, n=10):
        self.n = n
        self.calls = 0

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        self.calls += 1
        return np.full((3,), i)


def test_cached_dataset():
    ds = SlowDataset()
    cds = CachedDataset(ds, backend="memory", dataset_name="sd")
    for _ in range(3):
        for i in range(len(cds)):
            np.testing.assert_array_equal(cds[i], np.full((3,), i))
    assert ds.calls == 10  # each sample materialized exactly once


# ---------------- load balancing sampler -------------------------------------


def test_lb_sampler_balances_complexity():
    data = list(np.random.RandomState(0).randint(1, 100, size=64))
    n_replicas = 4
    per_rank = []
    for rank in range(n_replicas):
        s = LoadBalancingDistributedSampler(
            data, complexity_fn=lambda x: int(x), num_replicas=n_replicas, rank=rank,
            shuffle=True, seed=7,
        )
        s.set_epoch(0)
        idx = list(iter(s))
        assert len(idx) == len(s) == 16
        per_rank.append(sum(data[i] for i in idx))
    # balanced: per-rank total complexity within 15% of each other
    assert (max(per_rank) - min(per_rank)) / max(per_rank) < 0.15

    # every chunk groups samples of similar complexity: disjoint coverage
    all_idx = set()
    for rank in range(n_replicas):
        s = LoadBalancingDistributedSampler(
            data, complexity_fn=lambda x: int(x), num_replicas=n_replicas, rank=rank,
            shuffle=False,
        )
        all_idx.update(iter(s))
    assert len(all_idx) == 64


def test_lb_sampler_epoch_changes_order():
    data = list(range(32))
    s = LoadBalancingDistributedSampler(
        data, complexity_fn=lambda x: x, num_replicas=2, rank=0, shuffle=True, seed=0,
        random_level=0.5,
    )
    s.set_epoch(0)
    a = list(iter(s))
    s.set_epoch(1)
    b = list(iter(s))
    assert a != b


def test_lb_sampler_invalid_args():
    with pytest.raises(ValueError):
        LoadBalancingDistributedSampler([1, 2], lambda x: x, num_replicas=2, rank=5)
    with pytest.raises(ValueError):
        LoadBalancingDistributedSampler(
            [1, 2], lambda x: x, num_replicas=2, rank=0, random_level=1.5
        )


def test_lb_batch_sampler():
    data = list(np.random.RandomState(1).randint(1, 50, size=40))
    sampler = LoadBalancingDistributedSampler(
        data, complexity_fn=lambda x: int(x), num_replicas=2, rank=0, shuffle=True, seed=3
    )

    def batch_fn(indices):
        # dynamic batches capped at total complexity 100
        batches, cur, total = [], [], 0
        for i in indices:
            if cur and total + data[i] > 100:
                batches.append(cur)
                cur, total = [], 0
            cur.append(i)
            total += data[i]
        if cur:
            batches.append(cur)
        return batches

    bs = LoadBalancingDistributedBatchSampler(sampler, batch_fn=batch_fn)
    batches = list(iter(bs))
    assert len(batches) == len(bs)
    assert all(isinstance(b, list) and b for b in batches)


# ---------------- sync batch norm -------------------------------------------


def test_sync_batchnorm_matches_global_bn(group):
    """Per-rank SyncBatchNorm under shard_map == ordinary BN on the global
    batch (the defining property; reference tests/contrib sync BN)."""
    from jax.sharding import PartitionSpec as P
    from bagua_tpu.communication import ALL_AXES

    rng = np.random.RandomState(0)
    x = rng.randn(32, 6).astype(np.float32) * 3 + 1.5

    bn = SyncBatchNorm(axis_name=ALL_AXES, use_running_average=False)
    variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:4]))

    def local_apply(xl):
        y, _ = bn.apply(variables, xl, mutable=["batch_stats"])
        return y

    fn = jax.jit(group.shard_map(local_apply, in_specs=P(ALL_AXES), out_specs=P(ALL_AXES)))
    y_sync = np.asarray(fn(jnp.asarray(x)))

    mean = x.mean(0)
    var = x.var(0)
    y_ref = (x - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(y_sync, y_ref, rtol=2e-3, atol=2e-4)


def test_sync_batchnorm_single_device_fallback():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, 4).astype(np.float32))
    bn = SyncBatchNorm(axis_name="nonexistent_axis")
    variables = bn.init(jax.random.PRNGKey(0), x)
    y, _ = bn.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y).mean(0), np.zeros(4), atol=1e-5)


# ---------------- shm store (C++ native) ------------------------------------


def _shm_child(name, q):
    try:
        from bagua_tpu.contrib.shm_store import ShmStore

        s = ShmStore(name=name, capacity_bytes=1 << 20, create=False)
        q.put(("ok", s.get("hello")))
        s.set("from_child", [4, 5, 6])
        s.shutdown()
    except Exception as e:  # pragma: no cover
        q.put(("err", repr(e)))


@pytest.mark.slow
def test_shm_store_cross_process():
    pytest.importorskip("ctypes")
    from bagua_tpu.contrib.shm_store import ShmStore

    name = f"/bagua_test_{os.getpid()}"
    s = ShmStore(name=name, capacity_bytes=1 << 20)
    try:
        s.clear()
        s.set("hello", {"a": np.arange(3)})
        got = s.get("hello")
        np.testing.assert_array_equal(got["a"], np.arange(3))
        assert s.get("missing") is None
        assert s.num_keys() == 1

        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_shm_child, args=(name, q))
        p.start()
        status, value = q.get(timeout=60)
        p.join(timeout=30)
        assert status == "ok", value
        np.testing.assert_array_equal(value["a"], np.arange(3))
        assert s.get("from_child") == [4, 5, 6]
    finally:
        s.shutdown()
        ShmStore(name=name, capacity_bytes=1 << 20).unlink()


def test_cache_loader_degrades_when_store_full():
    """A bounded backend filling up disables caching instead of crashing."""

    class TinyStore(InMemoryStore):
        def mset(self, mapping):
            raise MemoryError("full")

    cl = CacheLoader(store=TinyStore(), writer_buffer_size=1)
    assert cl.get("a", lambda k: 1) == 1  # triggers a failing flush
    assert cl._cache_full
    assert cl.get("b", lambda k: 2) == 2  # still serves, no crash


def test_shm_store_overwrite_and_clear():
    from bagua_tpu.contrib.shm_store import ShmStore

    name = f"/bagua_test2_{os.getpid()}"
    s = ShmStore(name=name, capacity_bytes=1 << 20)
    try:
        s.clear()
        s.set("k", 1)
        s.set("k", 2)
        assert s.get("k") == 2
        assert s.num_keys() == 1
        s.clear()
        assert s.num_keys() == 0
        assert s.get("k") is None
    finally:
        s.shutdown()
        ShmStore(name=name, capacity_bytes=1 << 20).unlink()


def test_file_store_hash_collision(tmp_path, monkeypatch):
    """Two distinct keys whose 64-bit hashes collide must both survive: the
    store linear-probes suffixed slots instead of silently evicting."""
    import bagua_tpu.contrib.store as store_mod

    monkeypatch.setattr(store_mod, "_hash", lambda b: 42)  # force collisions
    s = store_mod.FileStore(path=str(tmp_path))
    s.set("alpha", 1)
    s.set("beta", 2)
    s.set("alpha", 11)  # overwrite must hit alpha's probed slot, not beta's
    assert s.get("alpha") == 11
    assert s.get("beta") == 2
    assert s.get("gamma") is None
    assert s.num_keys() == 2
