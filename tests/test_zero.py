"""ZeRO-1 optimizer-state sharding: exact equivalence with the unsharded
optimizer, state memory 1/n, and cross-rank weight equality under DDP."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.contrib.zero import zero_optimizer
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

N = 8


def test_zero_matches_unsharded_adam(group):
    params = init_mlp(jax.random.PRNGKey(0), [10, 16, 4])
    rng = np.random.RandomState(0)
    batches = [
        (
            jnp.asarray(rng.randn(16, 10), np.float32),
            jnp.asarray(rng.randn(16, 4), np.float32),
        )
        for _ in range(6)
    ]

    def run(opt):
        ddp = DistributedDataParallel(
            mse_loss, opt, GradientAllReduceAlgorithm(), process_group=group
        )
        state = ddp.init(params)
        for b in batches:
            state, _ = ddp.train_step(state, b)
        return ddp.params_unstacked(state), state

    ref_params, _ = run(optax.adam(1e-2))
    zero_params, zero_state = run(zero_optimizer(optax.adam(1e-2), n_shards=N))

    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(zero_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)

    # optimizer moment state is 1/N per rank (plus alignment padding)
    total = sum(l.size for l in jax.tree.leaves(params))
    mu_leaves = [
        l for l in jax.tree.leaves(zero_state.opt_state) if l.ndim == 2
    ]  # stacked (N, shard)
    assert mu_leaves, "expected sharded moment arrays"
    for l in mu_leaves:
        assert l.shape[1] <= total // N + N


def test_zero_cross_rank_equality(group):
    params = init_mlp(jax.random.PRNGKey(1), [10, 16, 4])
    ddp = DistributedDataParallel(
        mse_loss,
        zero_optimizer(optax.sgd(0.05, momentum=0.9), n_shards=N),
        GradientAllReduceAlgorithm(),
        process_group=group,
    )
    state = ddp.init(params)
    rng = np.random.RandomState(1)
    for _ in range(4):
        state, _ = ddp.train_step(
            state,
            (
                jnp.asarray(rng.randn(16, 10), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            ),
        )
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, state.params)):
        for r in range(1, N):
            np.testing.assert_array_equal(leaf[0], leaf[r])


def test_zero_wrong_shard_count(group):
    params = init_mlp(jax.random.PRNGKey(2), [10, 16, 4])
    ddp = DistributedDataParallel(
        mse_loss, zero_optimizer(optax.adam(1e-2), n_shards=4),
        GradientAllReduceAlgorithm(), process_group=group,
    )
    state = ddp.init(params)
    with pytest.raises(ValueError, match="built for 4 shards"):
        ddp.train_step(
            state, (jnp.zeros((16, 10)), jnp.zeros((16, 4)))
        )
