"""ZeRO-1 optimizer-state sharding: exact equivalence with the unsharded
optimizer, state memory 1/n, and cross-rank weight equality under DDP."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.contrib.zero import zero_optimizer
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

N = 8


def test_zero_matches_unsharded_adam(group):
    params = init_mlp(jax.random.PRNGKey(0), [10, 16, 4])
    rng = np.random.RandomState(0)
    batches = [
        (
            jnp.asarray(rng.randn(16, 10), np.float32),
            jnp.asarray(rng.randn(16, 4), np.float32),
        )
        for _ in range(6)
    ]

    def run(opt):
        ddp = DistributedDataParallel(
            mse_loss, opt, GradientAllReduceAlgorithm(), process_group=group
        )
        state = ddp.init(params)
        for b in batches:
            state, _ = ddp.train_step(state, b)
        return ddp.params_unstacked(state), state

    ref_params, _ = run(optax.adam(1e-2))
    zero_params, zero_state = run(zero_optimizer(optax.adam(1e-2), n_shards=N))

    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(zero_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)

    # optimizer moment state is 1/N per rank (plus alignment padding)
    total = sum(l.size for l in jax.tree.leaves(params))
    mu_leaves = [
        l for l in jax.tree.leaves(zero_state.opt_state) if l.ndim == 2
    ]  # stacked (N, shard)
    assert mu_leaves, "expected sharded moment arrays"
    for l in mu_leaves:
        assert l.shape[1] <= total // N + N


def test_zero_cross_rank_equality(group):
    params = init_mlp(jax.random.PRNGKey(1), [10, 16, 4])
    ddp = DistributedDataParallel(
        mse_loss,
        zero_optimizer(optax.sgd(0.05, momentum=0.9), n_shards=N),
        GradientAllReduceAlgorithm(),
        process_group=group,
    )
    state = ddp.init(params)
    rng = np.random.RandomState(1)
    for _ in range(4):
        state, _ = ddp.train_step(
            state,
            (
                jnp.asarray(rng.randn(16, 10), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            ),
        )
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, state.params)):
        for r in range(1, N):
            np.testing.assert_array_equal(leaf[0], leaf[r])


def test_zero_wrong_shard_count(group):
    params = init_mlp(jax.random.PRNGKey(2), [10, 16, 4])
    ddp = DistributedDataParallel(
        mse_loss, zero_optimizer(optax.adam(1e-2), n_shards=4),
        GradientAllReduceAlgorithm(), process_group=group,
    )
    state = ddp.init(params)
    with pytest.raises(ValueError, match="built for 4 shards"):
        ddp.train_step(
            state, (jnp.zeros((16, 10)), jnp.zeros((16, 4)))
        )


@pytest.mark.slow
def test_zero2_matches_unsharded_adam(group):
    """ZeRO-2 (reduce-scattered raw gradients + sharded state + "none"
    algorithm) produces the same trajectory as allreduce + unsharded Adam."""
    from bagua_tpu.algorithms import Algorithm
    from bagua_tpu.contrib.zero import zero2_optimizer

    params = init_mlp(jax.random.PRNGKey(2), [10, 16, 4])
    rng = np.random.RandomState(1)
    batches = [
        (
            jnp.asarray(rng.randn(16, 10), np.float32),
            jnp.asarray(rng.randn(16, 4), np.float32),
        )
        for _ in range(6)
    ]

    def run(opt, algo):
        ddp = DistributedDataParallel(mse_loss, opt, algo, process_group=group)
        state = ddp.init(params)
        for b in batches:
            state, _ = ddp.train_step(state, b)
        return ddp.params_unstacked(state), state

    ref_params, _ = run(optax.adam(1e-2), GradientAllReduceAlgorithm())
    z2_params, z2_state = run(
        zero2_optimizer(optax.adam(1e-2), n_shards=N), Algorithm.init("none")
    )
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(z2_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)

    # ranks stay bitwise-synchronized without any algorithm-level comm
    stacked = jax.tree.leaves(z2_state.params)
    for l in stacked:
        arr = np.asarray(l)
        for r in range(1, N):
            np.testing.assert_array_equal(arr[0], arr[r])


@pytest.mark.slow
def test_fsdp_matches_ddp_and_shards_memory(group):
    """The pjit FSDP path (params sharded at rest) matches the explicit DDP
    engine's trajectory, and the HLO carries the ZeRO-3 wire pattern
    (all-gather at use / reduce-scatter behind gradients)."""
    from bagua_tpu.parallel.fsdp import FSDP, fsdp_shardings

    params = init_mlp(jax.random.PRNGKey(3), [16, 64, 8])
    rng = np.random.RandomState(2)
    batches = [
        (
            jnp.asarray(rng.randn(32, 16), np.float32),
            jnp.asarray(rng.randn(32, 8), np.float32),
        )
        for _ in range(4)
    ]

    # FSDP path
    fsdp = FSDP(mse_loss, optax.adam(1e-2), group)
    p, o = fsdp.init(params)
    # the 64-wide layer shards over the 8-way mesh
    w1 = p["layer0"]["w"]
    assert not w1.sharding.is_fully_replicated
    for b in batches:
        (p, o), loss = fsdp.train_step(p, o, b)
    assert np.isfinite(float(loss))

    # explicit DDP reference
    ddp = DistributedDataParallel(
        mse_loss, optax.adam(1e-2), GradientAllReduceAlgorithm(), process_group=group
    )
    state = ddp.init(params)
    for b in batches:
        state, _ = ddp.train_step(state, b)
    ref = ddp.params_unstacked(state)

    for a, b_ in zip(jax.tree.leaves(ref), jax.tree.leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=1e-5)

    # ZeRO-3 wire pattern in the compiled step
    hlo = fsdp._step.lower(p, o, batches[0]).compile().as_text()
    assert "all-gather" in hlo or "all-reduce" in hlo


@pytest.mark.slow
def test_fsdp_hlo_and_memory_assertions(group):
    """VERDICT r2 #9: the compiled FSDP step carries gather-at-use and a
    gradient-reduction collective, and per-device live parameter+optimizer
    bytes are ~P/n (the ZeRO-3 memory claim, checked via XLA's own memory
    analysis, not trusted from the docstring).

    XLA:CPU lowers the gradient reduction to all-reduce + dynamic-slice; the
    reduce-scatter fusion of that pair is an accelerator-pipeline pass
    (asserted on real TPU in the perf audit instead)."""
    from bagua_tpu.parallel.fsdp import FSDP

    params = init_mlp(jax.random.PRNGKey(4), [64, 512, 512, 8])
    fsdp = FSDP(mse_loss, optax.adam(1e-2), group)
    p, o = fsdp.init(params)
    batch = (jnp.zeros((32, 64), jnp.float32), jnp.zeros((32, 8), jnp.float32))
    comp = fsdp._build(p, o).lower(p, o, batch).compile()
    hlo = comp.as_text()
    assert "all-gather" in hlo, "no gather-at-use: params are not sharded at rest"
    assert "all-reduce" in hlo or "reduce-scatter" in hlo, "no gradient reduction"

    # per-device argument bytes ~ (params + opt state) / n, plus small
    # replicated leaves (biases, counters) and the replicated batch
    total = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(p))
    total += sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(o)
        if hasattr(x, "size")
    )
    batch_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(batch))
    per_device = comp.memory_analysis().argument_size_in_bytes
    assert per_device < total / 4 + batch_bytes, (per_device, total)


@pytest.mark.slow
def test_fsdp_mixed_precision_policy(group):
    """compute_dtype=bfloat16: the compiled step's dot ops run in bf16, the
    master params/opt state stay f32, and training still converges."""
    from bagua_tpu.parallel.fsdp import FSDP

    params = init_mlp(jax.random.PRNGKey(5), [16, 64, 8])
    fsdp = FSDP(mse_loss, optax.adam(1e-2), group, compute_dtype=jnp.bfloat16)
    p, o = fsdp.init(params)
    rng = np.random.RandomState(6)
    losses = []
    first_batch = None
    for _ in range(8):
        b = (
            jnp.asarray(rng.randn(32, 16), np.float32),
            jnp.asarray(rng.randn(32, 8), np.float32),
        )
        first_batch = first_batch or b
        (p, o), loss = fsdp.train_step(p, o, b)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    for leaf in jax.tree.leaves(p):
        assert leaf.dtype == jnp.float32  # master weights stay f32
    # a dot op with bf16 operands — convert ops alone don't count.  Checked
    # on the lowered (pre-backend) module: XLA:CPU rewrites dots into custom
    # calls/fusions in the optimized HLO, hiding the op name.
    lowered = fsdp._step.lower(p, o, first_batch).as_text()
    assert any(
        "dot_general" in line and "bf16" in line for line in lowered.splitlines()
    ), "no bf16 dot_general in the mixed-precision step"


@pytest.mark.slow
def test_fsdp_scanned_layers(group):
    """scan_layers over a stacked block: matches the unrolled loop, and under
    FSDP shardings the stack's layer axis is the sharded one (per-layer
    gather-at-use)."""
    from bagua_tpu.parallel.fsdp import FSDP, fsdp_shardings, scan_layers

    L, D = 8, 16
    rng = np.random.RandomState(7)
    stacked = {
        "w": jnp.asarray(rng.randn(L, D, D).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(L, D).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(rng.randn(4, D).astype(np.float32))

    def block(layer, h):
        return jax.nn.tanh(h @ layer["w"] + layer["b"])

    out = scan_layers(block, stacked, x)
    expect = x
    for i in range(L):
        expect = block({"w": stacked["w"][i], "b": stacked["b"][i]}, expect)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=1e-5)

    # the FSDP layout shards the leading (layer) axis of the stack
    sh = fsdp_shardings(stacked, group)
    assert str(sh["w"].spec[0]) != "None" and sh["w"].spec[0] is not None

    # end-to-end: FSDP training over the scanned stack converges and matches
    # the same model trained with replicated params
    def loss_fn(params, batch):
        xb, yb = batch
        return jnp.mean((scan_layers(block, params, xb) - yb) ** 2)

    fsdp = FSDP(loss_fn, optax.adam(1e-2), group)
    p, o = fsdp.init(stacked)
    ref_p, ref_o = jax.tree.map(jnp.copy, stacked), optax.adam(1e-2).init(stacked)
    opt = optax.adam(1e-2)
    for i in range(4):
        b = (
            jnp.asarray(rng.randn(32, D), np.float32),
            jnp.asarray(rng.randn(32, D), np.float32),
        )
        (p, o), loss = fsdp.train_step(p, o, b)
        g = jax.grad(loss_fn)(ref_p, b)
        upd, ref_o = opt.update(g, ref_o, ref_p)
        ref_p = optax.apply_updates(ref_p, upd)
    for a, b_ in zip(jax.tree.leaves(ref_p), jax.tree.leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=1e-5)
