"""ZeRO-1 optimizer-state sharding: exact equivalence with the unsharded
optimizer, state memory 1/n, and cross-rank weight equality under DDP."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.contrib.zero import zero_optimizer
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

N = 8


def test_zero_matches_unsharded_adam(group):
    params = init_mlp(jax.random.PRNGKey(0), [10, 16, 4])
    rng = np.random.RandomState(0)
    batches = [
        (
            jnp.asarray(rng.randn(16, 10), np.float32),
            jnp.asarray(rng.randn(16, 4), np.float32),
        )
        for _ in range(6)
    ]

    def run(opt):
        ddp = DistributedDataParallel(
            mse_loss, opt, GradientAllReduceAlgorithm(), process_group=group
        )
        state = ddp.init(params)
        for b in batches:
            state, _ = ddp.train_step(state, b)
        return ddp.params_unstacked(state), state

    ref_params, _ = run(optax.adam(1e-2))
    zero_params, zero_state = run(zero_optimizer(optax.adam(1e-2), n_shards=N))

    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(zero_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)

    # optimizer moment state is 1/N per rank (plus alignment padding)
    total = sum(l.size for l in jax.tree.leaves(params))
    mu_leaves = [
        l for l in jax.tree.leaves(zero_state.opt_state) if l.ndim == 2
    ]  # stacked (N, shard)
    assert mu_leaves, "expected sharded moment arrays"
    for l in mu_leaves:
        assert l.shape[1] <= total // N + N


def test_zero_cross_rank_equality(group):
    params = init_mlp(jax.random.PRNGKey(1), [10, 16, 4])
    ddp = DistributedDataParallel(
        mse_loss,
        zero_optimizer(optax.sgd(0.05, momentum=0.9), n_shards=N),
        GradientAllReduceAlgorithm(),
        process_group=group,
    )
    state = ddp.init(params)
    rng = np.random.RandomState(1)
    for _ in range(4):
        state, _ = ddp.train_step(
            state,
            (
                jnp.asarray(rng.randn(16, 10), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            ),
        )
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, state.params)):
        for r in range(1, N):
            np.testing.assert_array_equal(leaf[0], leaf[r])


def test_zero_wrong_shard_count(group):
    params = init_mlp(jax.random.PRNGKey(2), [10, 16, 4])
    ddp = DistributedDataParallel(
        mse_loss, zero_optimizer(optax.adam(1e-2), n_shards=4),
        GradientAllReduceAlgorithm(), process_group=group,
    )
    state = ddp.init(params)
    with pytest.raises(ValueError, match="built for 4 shards"):
        ddp.train_step(
            state, (jnp.zeros((16, 10)), jnp.zeros((16, 4)))
        )


@pytest.mark.slow
def test_zero2_matches_unsharded_adam(group):
    """ZeRO-2 (reduce-scattered raw gradients + sharded state + "none"
    algorithm) produces the same trajectory as allreduce + unsharded Adam."""
    from bagua_tpu.algorithms import Algorithm
    from bagua_tpu.contrib.zero import zero2_optimizer

    params = init_mlp(jax.random.PRNGKey(2), [10, 16, 4])
    rng = np.random.RandomState(1)
    batches = [
        (
            jnp.asarray(rng.randn(16, 10), np.float32),
            jnp.asarray(rng.randn(16, 4), np.float32),
        )
        for _ in range(6)
    ]

    def run(opt, algo):
        ddp = DistributedDataParallel(mse_loss, opt, algo, process_group=group)
        state = ddp.init(params)
        for b in batches:
            state, _ = ddp.train_step(state, b)
        return ddp.params_unstacked(state), state

    ref_params, _ = run(optax.adam(1e-2), GradientAllReduceAlgorithm())
    z2_params, z2_state = run(
        zero2_optimizer(optax.adam(1e-2), n_shards=N), Algorithm.init("none")
    )
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(z2_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)

    # ranks stay bitwise-synchronized without any algorithm-level comm
    stacked = jax.tree.leaves(z2_state.params)
    for l in stacked:
        arr = np.asarray(l)
        for r in range(1, N):
            np.testing.assert_array_equal(arr[0], arr[r])


@pytest.mark.slow
def test_fsdp_matches_ddp_and_shards_memory(group):
    """The pjit FSDP path (params sharded at rest) matches the explicit DDP
    engine's trajectory, and the HLO carries the ZeRO-3 wire pattern
    (all-gather at use / reduce-scatter behind gradients)."""
    from bagua_tpu.parallel.fsdp import FSDP, fsdp_shardings

    params = init_mlp(jax.random.PRNGKey(3), [16, 64, 8])
    rng = np.random.RandomState(2)
    batches = [
        (
            jnp.asarray(rng.randn(32, 16), np.float32),
            jnp.asarray(rng.randn(32, 8), np.float32),
        )
        for _ in range(4)
    ]

    # FSDP path
    fsdp = FSDP(mse_loss, optax.adam(1e-2), group)
    p, o = fsdp.init(params)
    # the 64-wide layer shards over the 8-way mesh
    w1 = p["layer0"]["w"]
    assert not w1.sharding.is_fully_replicated
    for b in batches:
        (p, o), loss = fsdp.train_step(p, o, b)
    assert np.isfinite(float(loss))

    # explicit DDP reference
    ddp = DistributedDataParallel(
        mse_loss, optax.adam(1e-2), GradientAllReduceAlgorithm(), process_group=group
    )
    state = ddp.init(params)
    for b in batches:
        state, _ = ddp.train_step(state, b)
    ref = ddp.params_unstacked(state)

    for a, b_ in zip(jax.tree.leaves(ref), jax.tree.leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=1e-5)

    # ZeRO-3 wire pattern in the compiled step
    hlo = fsdp._step.lower(p, o, batches[0]).compile().as_text()
    assert "all-gather" in hlo or "all-reduce" in hlo


@pytest.mark.slow
def test_fsdp_hlo_and_memory_assertions(group):
    """VERDICT r2 #9: the compiled FSDP step carries gather-at-use and a
    gradient-reduction collective, and per-device live parameter+optimizer
    bytes are ~P/n (the ZeRO-3 memory claim, checked via XLA's own memory
    analysis, not trusted from the docstring).

    XLA:CPU lowers the gradient reduction to all-reduce + dynamic-slice; the
    reduce-scatter fusion of that pair is an accelerator-pipeline pass
    (asserted on real TPU in the perf audit instead)."""
    from bagua_tpu.parallel.fsdp import FSDP

    params = init_mlp(jax.random.PRNGKey(4), [64, 512, 512, 8])
    fsdp = FSDP(mse_loss, optax.adam(1e-2), group)
    p, o = fsdp.init(params)
    batch = (jnp.zeros((32, 64), jnp.float32), jnp.zeros((32, 8), jnp.float32))
    comp = fsdp._build(p, o).lower(p, o, batch).compile()
    hlo = comp.as_text()
    assert "all-gather" in hlo, "no gather-at-use: params are not sharded at rest"
    assert "all-reduce" in hlo or "reduce-scatter" in hlo, "no gradient reduction"

    # per-device argument bytes ~ (params + opt state) / n, plus small
    # replicated leaves (biases, counters) and the replicated batch
    total = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(p))
    total += sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(o)
        if hasattr(x, "size")
    )
    batch_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(batch))
    per_device = comp.memory_analysis().argument_size_in_bytes
    assert per_device < total / 4 + batch_bytes, (per_device, total)


@pytest.mark.slow
def test_fsdp_mixed_precision_policy(group):
    """compute_dtype=bfloat16: the compiled step's dot ops run in bf16, the
    master params/opt state stay f32, and training still converges."""
    from bagua_tpu.parallel.fsdp import FSDP

    params = init_mlp(jax.random.PRNGKey(5), [16, 64, 8])
    fsdp = FSDP(mse_loss, optax.adam(1e-2), group, compute_dtype=jnp.bfloat16)
    p, o = fsdp.init(params)
    rng = np.random.RandomState(6)
    losses = []
    first_batch = None
    for _ in range(8):
        b = (
            jnp.asarray(rng.randn(32, 16), np.float32),
            jnp.asarray(rng.randn(32, 8), np.float32),
        )
        first_batch = first_batch or b
        (p, o), loss = fsdp.train_step(p, o, b)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    for leaf in jax.tree.leaves(p):
        assert leaf.dtype == jnp.float32  # master weights stay f32
    # a dot op with bf16 operands — convert ops alone don't count.  Checked
    # on the lowered (pre-backend) module: XLA:CPU rewrites dots into custom
    # calls/fusions in the optimized HLO, hiding the op name.
    lowered = fsdp._step.lower(p, o, first_batch).as_text()
    assert any(
        "dot_general" in line and "bf16" in line for line in lowered.splitlines()
    ), "no bf16 dot_general in the mixed-precision step"


@pytest.mark.slow
def test_fsdp_scanned_layers(group):
    """scan_layers over a stacked block: matches the unrolled loop, and under
    FSDP shardings the stack's layer axis is the sharded one (per-layer
    gather-at-use)."""
    from bagua_tpu.parallel.fsdp import FSDP, fsdp_shardings, scan_layers

    L, D = 8, 16
    rng = np.random.RandomState(7)
    stacked = {
        "w": jnp.asarray(rng.randn(L, D, D).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(L, D).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(rng.randn(4, D).astype(np.float32))

    def block(layer, h):
        return jax.nn.tanh(h @ layer["w"] + layer["b"])

    out = scan_layers(block, stacked, x)
    expect = x
    for i in range(L):
        expect = block({"w": stacked["w"][i], "b": stacked["b"][i]}, expect)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=1e-5)

    # the FSDP layout shards the leading (layer) axis of the stack
    sh = fsdp_shardings(stacked, group)
    assert str(sh["w"].spec[0]) != "None" and sh["w"].spec[0] is not None

    # end-to-end: FSDP training over the scanned stack converges and matches
    # the same model trained with replicated params
    def loss_fn(params, batch):
        xb, yb = batch
        return jnp.mean((scan_layers(block, params, xb) - yb) ** 2)

    fsdp = FSDP(loss_fn, optax.adam(1e-2), group)
    p, o = fsdp.init(stacked)
    ref_p, ref_o = jax.tree.map(jnp.copy, stacked), optax.adam(1e-2).init(stacked)
    opt = optax.adam(1e-2)
    for i in range(4):
        b = (
            jnp.asarray(rng.randn(32, D), np.float32),
            jnp.asarray(rng.randn(32, D), np.float32),
        )
        (p, o), loss = fsdp.train_step(p, o, b)
        g = jax.grad(loss_fn)(ref_p, b)
        upd, ref_o = opt.update(g, ref_o, ref_p)
        ref_p = optax.apply_updates(ref_p, upd)
    for a, b_ in zip(jax.tree.leaves(ref_p), jax.tree.leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=1e-5)


# -- engine-native zero algorithm (bagua_tpu.sharded) -------------------------
# The tests above exercise the deprecated contrib wrappers; from here down is
# the engine-native three-leg exchange: per-bucket reduce-scatter, shard-only
# optimizer update, all-gather deferred into the next step's forward.

from jax.sharding import PartitionSpec as P  # noqa: E402

from bagua_tpu.bucket import BucketPlan  # noqa: E402
from bagua_tpu.algorithms.bytegrad import ByteGradAlgorithm  # noqa: E402
from bagua_tpu.communication import ALL_AXES, ReduceOp, allreduce_inplace  # noqa: E402
from bagua_tpu.sharded import ZeroAlgorithm  # noqa: E402

ZLAYERS = [10, 16, 4]  # 244 params; at 1<<9 bucket bytes: 3 f32 buckets,
# the last one ([layer1.b, layer1.w], 68 elems) padded to 72 — the
# non-divisible last-shard path rides every test below.
ZSTEPS = 5


def _zopt(name):
    return optax.adam(1e-2) if name == "adam" else optax.sgd(1e-2, momentum=0.9)


def _zbatches(steps=ZSTEPS, seed=1):
    rng = np.random.RandomState(seed)
    return [
        (jnp.asarray(rng.randn(16, ZLAYERS[0]), np.float32),
         jnp.asarray(rng.randn(16, ZLAYERS[-1]), np.float32))
        for _ in range(steps)
    ]


def _run_engine(group, algo, opt_name, overlap, steps=ZSTEPS, rebucket_at=None):
    ddp = DistributedDataParallel(
        mse_loss, _zopt(opt_name), algo, process_group=group,
        bucket_size_bytes=1 << 9, overlap=overlap,
    )
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), ZLAYERS))
    for i, b in enumerate(_zbatches(steps)):
        if i == rebucket_at:
            ddp.rebucket(BucketPlan.from_tree(
                init_mlp(jax.random.PRNGKey(0), ZLAYERS),
                bucket_size_bytes=1 << 22, align_elems=group.size,
            ))
        state, _ = ddp.train_step(state, b)
    state = ddp.finalize_pending_updates(state)
    return ddp, state


def _plain_optax_reference(group, opt_name, steps=ZSTEPS):
    """The unsharded reference trajectory: shard_map fwd/bwd + gradient
    all-reduce, then a textbook optax update in its own jit.  This is the
    trajectory the bitwise contract is against — the sharded path pins its
    optimizer math to standalone-optax codegen (see sharded/updater.py)."""
    opt = _zopt(opt_name)
    params = init_mlp(jax.random.PRNGKey(0), ZLAYERS)
    opt_state = opt.init(params)

    def local_g(p, batch):
        g = jax.grad(mse_loss)(p, batch)
        return jax.tree.map(lambda l: allreduce_inplace(l, op=ReduceOp.AVG), g)

    grad_fn = jax.jit(group.shard_map(
        local_g, in_specs=(P(), P(ALL_AXES)), out_specs=P(),
    ))

    @jax.jit
    def upd(p, g, s):
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    for b in _zbatches(steps):
        params, opt_state = upd(params, grad_fn(params, b), opt_state)
    return params


def _params_bitwise(state, expect):
    got = jax.tree.map(lambda l: np.asarray(l)[0], state.params)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(expect)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("overlap", [False, True], ids=["mono", "overlap"])
@pytest.mark.parametrize("opt_name", ["adam", "sgdm"])
def test_zero_engine_bitwise_matches_plain_optax(group, opt_name, overlap):
    """The tentpole numerics contract: the sharded three-leg trajectory
    (reduce-scatter → shard-only fused update → deferred all-gather) is
    bitwise-identical to the plain-optax unsharded reference, monolithic and
    overlapped, for elementwise optimizers — including the padded
    non-divisible last bucket."""
    ddp, state = _run_engine(group, ZeroAlgorithm(), opt_name, overlap)
    assert ddp.plan.num_buckets > 1  # multi-bucket: shard math is non-trivial
    # the last bucket is alignment-padded: 68 raw elems -> 72
    raw = [sum(s.numel for s in spec.slots) for spec in ddp.plan.specs]
    assert any(spec.numel > r for spec, r in zip(ddp.plan.specs, raw))
    _params_bitwise(state, _plain_optax_reference(group, opt_name))
    # ranks stay bitwise-synchronized (the all-gather is identical everywhere)
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, state.params)):
        for r in range(1, N):
            np.testing.assert_array_equal(leaf[0], leaf[r])


def test_zero_engine_vs_allreduce_engine(group):
    """Engine vs engine: the allreduce path's optimizer math fuses into the
    step program (per-op rounding), while the sharded path pins
    standalone-optax codegen (FMA-contracted) — the trajectories agree to
    1 ulp per step, not bitwise.  The bitwise contract lives in
    test_zero_engine_bitwise_matches_plain_optax."""
    _, z = _run_engine(group, ZeroAlgorithm(), "adam", overlap=True)
    _, r = _run_engine(group, GradientAllReduceAlgorithm(), "adam", overlap=False)
    for a, b in zip(jax.tree.leaves(z.params), jax.tree.leaves(r.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=1e-7
        )


@pytest.mark.parametrize("overlap", [False, True], ids=["mono", "overlap"])
def test_zero_bytegrad_bitwise_matches_monolithic(group, overlap):
    """ByteGrad composition: the compressed reduce-scatter (compress →
    all-to-all → fused reduce → LOCAL decompress, no gather of the gradient
    leg) lands on the exact trajectory of the monolithic flat ByteGrad
    engine — each rank's reduced chunk is bitwise row-me of the reference
    pipeline's output."""
    _, ref = _run_engine(group, ByteGradAlgorithm(hierarchical=False), "adam", False)
    _, got = _run_engine(
        group, ZeroAlgorithm(compression="bytegrad"), "adam", overlap
    )
    for a, b in zip(jax.tree.leaves(got.params), jax.tree.leaves(ref.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_opt_state_bytes_per_chip(group):
    """The ZeRO-1 memory claim: per-chip Adam moment bytes are ~1/n of the
    replicated engine's (alignment padding is the only slack)."""
    zd, zs = _run_engine(group, ZeroAlgorithm(), "adam", False, steps=1)
    rd, rs = _run_engine(group, GradientAllReduceAlgorithm(), "adam", False, steps=1)

    def per_chip(state):
        return sum(
            l.size * l.dtype.itemsize // N for l in jax.tree.leaves(state.opt_state)
        )

    ratio = per_chip(zs) / per_chip(rs)
    assert ratio <= 1 / N + 0.05, ratio


def test_zero_rebucket_midtraining_bitwise(group):
    """Satellite: a mid-training ``rebucket`` under the sharded algorithm
    (overlap on) migrates optimizer shards + pending update shards to the
    new layout element-value-preservingly — the continued trajectory is
    bitwise-identical to an uninterrupted run, which is itself bitwise vs
    the plain-optax reference."""
    ddp, state = _run_engine(
        group, ZeroAlgorithm(), "adam", overlap=True, rebucket_at=2
    )
    assert ddp.plan.num_buckets == 1  # the swap actually happened
    assert ddp._sharded_updater.layout.buckets[0].shard_numel * N >= 244
    _params_bitwise(state, _plain_optax_reference(group, "adam"))


def test_fuse_optimizer_contrib_shim_deprecated():
    """The contrib shim warns but stays bitwise-identical to the engine-native
    fused optimizer it delegates to."""
    from bagua_tpu.contrib import fuse_optimizer as shim_fn
    from bagua_tpu.sharded import fuse_optimizer as native

    params = init_mlp(jax.random.PRNGKey(7), [6, 8, 2])
    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 0.3, params)
    with pytest.warns(DeprecationWarning, match="deprecated"):
        fused = shim_fn(optax.adam(1e-2))
    ref = native(optax.adam(1e-2))
    fs, rs_ = fused.init(params), ref.init(params)
    uf, _ = fused.update(grads, fs, params)
    ur, _ = ref.update(grads, rs_, params)
    for a, b in zip(jax.tree.leaves(uf), jax.tree.leaves(ur)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
