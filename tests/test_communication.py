"""Collective correctness tests.

TPU analog of reference ``tests/comm/test_communicator.py:222-291``: every
collective is exercised on the simulated 8-device mesh; results are checked
against numpy oracles computed from the stacked per-rank inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bagua_tpu
from bagua_tpu import ReduceOp
from bagua_tpu import communication as C
from jax.sharding import PartitionSpec as P


def stacked_input(n=8, numel=16, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return rng.uniform(-1, 1, size=(n, numel)).astype(dtype)


def test_allreduce_sum_avg(group):
    x = stacked_input()
    out = np.asarray(bagua_tpu.allreduce(jnp.asarray(x), op=ReduceOp.SUM))
    expect = np.tile(x.sum(0, keepdims=True), (8, 1))
    np.testing.assert_allclose(out, expect, rtol=1e-5)

    out = np.asarray(bagua_tpu.allreduce(jnp.asarray(x), op=ReduceOp.AVG))
    np.testing.assert_allclose(out, expect / 8.0, rtol=1e-5)


def test_allreduce_min_max_prod(group):
    x = stacked_input(seed=1)
    for op, red in [(ReduceOp.MIN, np.min), (ReduceOp.MAX, np.max), (ReduceOp.PRODUCT, np.prod)]:
        out = np.asarray(bagua_tpu.allreduce(jnp.asarray(x), op=op))
        expect = np.tile(red(x, axis=0, keepdims=True), (8, 1))
        np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_allreduce_bitwise(group):
    x = (stacked_input(seed=2) * 100).astype(np.int32)
    for op, red in [
        (ReduceOp.BOR, np.bitwise_or.reduce),
        (ReduceOp.BAND, np.bitwise_and.reduce),
        (ReduceOp.BXOR, np.bitwise_xor.reduce),
    ]:
        out = np.asarray(bagua_tpu.allreduce(jnp.asarray(x), op=op))
        expect = np.tile(red(x, axis=0)[None], (8, 1))
        np.testing.assert_array_equal(out, expect)


def test_allgather(group):
    x = stacked_input()
    out = np.asarray(bagua_tpu.allgather(jnp.asarray(x)))
    expect = np.tile(x.reshape(1, -1), (8, 1))
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_reducescatter(group):
    x = stacked_input()
    out = np.asarray(bagua_tpu.reducescatter(jnp.asarray(x), op=ReduceOp.SUM))
    total = x.sum(0)  # (16,)
    expect = total.reshape(8, 2)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_broadcast(group):
    x = stacked_input()
    out = np.asarray(bagua_tpu.broadcast(jnp.asarray(x), src=3))
    expect = np.tile(x[3][None], (8, 1))
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_alltoall(group):
    x = stacked_input()
    out = np.asarray(bagua_tpu.alltoall(jnp.asarray(x)))
    # rank i's output chunk j == rank j's input chunk i
    chunks = x.reshape(8, 8, 2)
    expect = np.transpose(chunks, (1, 0, 2)).reshape(8, 16)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_reduce(group):
    x = stacked_input()
    out = np.asarray(bagua_tpu.reduce(jnp.asarray(x), dst=2, op=ReduceOp.SUM))
    np.testing.assert_allclose(out[2], x.sum(0), rtol=1e-5)
    for i in [0, 1, 3, 4, 5, 6, 7]:
        np.testing.assert_allclose(out[i], x[i], rtol=1e-6)


def test_scatter(group):
    x = stacked_input()
    out = np.asarray(bagua_tpu.scatter(jnp.asarray(x), src=1))
    expect = x[1].reshape(8, 2)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_gather(group):
    x = stacked_input()
    out = np.asarray(bagua_tpu.gather(jnp.asarray(x), dst=5))
    np.testing.assert_allclose(out[5], x.reshape(-1), rtol=1e-6)
    # non-dst ranks receive zeros, never fabricated data (the reference
    # leaves their recv buffers untouched)
    for r in range(8):
        if r != 5:
            assert not np.any(out[r])


def test_barrier(group):
    bagua_tpu.barrier()


def test_hierarchical_allreduce_matches_flat(group):
    x = stacked_input(seed=3)
    flat = bagua_tpu.allreduce(jnp.asarray(x), op=ReduceOp.AVG)

    fn = jax.jit(
        group.shard_map(
            lambda v: C.hierarchical_allreduce_inplace(v, op=ReduceOp.AVG),
            in_specs=P(C.ALL_AXES),
            out_specs=P(C.ALL_AXES),
        )
    )
    hier = fn(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(flat), np.asarray(hier), rtol=1e-5)


@pytest.mark.parametrize("shift", [1, -1, 3, 5, -5, 4, 7])
def test_ppermute_shift(group, shift):
    """Ring shifts over the combined (inter=2, intra=4) axes: both the
    two-stage point-to-point path (|shift| < intra) and the gather fallback."""
    x = stacked_input(seed=4)
    fn = jax.jit(
        group.shard_map(
            lambda v: C.ppermute_shift(v[0], shift=shift)[None],
            in_specs=P(C.ALL_AXES),
            out_specs=P(C.ALL_AXES),
        )
    )
    out = np.asarray(fn(jnp.asarray(x)))
    # rank i receives rank (i-shift) mod 8's value
    expect = np.roll(x, shift, axis=0)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_ppermute_apply_missing_dst_zero(group):
    """Destinations absent from the permutation receive zeros, matching
    lax.ppermute semantics, on the combined-axes fallback path too."""
    x = stacked_input(seed=6)
    fn = jax.jit(
        group.shard_map(
            lambda v: C.ppermute_apply(v[0], [(0, 1)])[None],
            in_specs=P(C.ALL_AXES),
            out_specs=P(C.ALL_AXES),
        )
    )
    out = np.asarray(fn(jnp.asarray(x)))
    np.testing.assert_allclose(out[1], x[0], rtol=1e-6)
    for r in [0, 2, 3, 4, 5, 6, 7]:
        np.testing.assert_array_equal(out[r], np.zeros_like(out[r]))


def test_new_group_subset(group):
    sub = bagua_tpu.new_group(ranks=[0, 1, 2, 3], intra_size=2)
    assert sub.size == 4
    x = stacked_input(n=4, seed=5)
    out = np.asarray(bagua_tpu.allreduce(jnp.asarray(x), op=ReduceOp.SUM, comm=sub))
    expect = np.tile(x.sum(0, keepdims=True), (4, 1))
    np.testing.assert_allclose(out, expect, rtol=1e-5)
