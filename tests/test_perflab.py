"""Perf-lab unit gates: α–β fit round-trip, modeled-bytes == census-bytes
on the live engines, FLOP census exactness, Pallas evidence gating, and the
one-topology-model unification with ci/scaling_projection.py."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.perflab import (
    DEFAULT_TOPOLOGY,
    flops_census,
    model_step_cell,
    modeled_bench_rows,
    pallas_kernel_basis,
    t_collective,
    torus_dims,
)
from bagua_tpu.service.planner import (
    AlphaBeta,
    CostModel,
    WireSample,
    fit_alpha_beta,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAYERS = [64, 128, 128, 64]


# ---------------------------------------------------------------------------
# α–β fit round-trip
# ---------------------------------------------------------------------------


def test_alpha_beta_fit_round_trip():
    """Samples synthesized from a known (α, β) fit back to it exactly —
    the cost model's seconds are then a faithful readback of the fixture."""
    truth = AlphaBeta(alpha=50e-6, beta=50e9)
    sizes = [1 << 16, 1 << 20, 1 << 24, 1 << 26]
    samples = [
        WireSample(nbytes=n, seconds=truth.predict(n), leg="flat")
        for n in sizes
    ]
    fit = fit_alpha_beta(samples, AlphaBeta(1e-3, 1e9))
    assert fit.alpha == pytest.approx(truth.alpha, rel=1e-6)
    assert fit.beta == pytest.approx(truth.beta, rel=1e-6)
    for n in sizes:
        assert fit.predict(n) == pytest.approx(truth.predict(n), rel=1e-9)


def test_cost_model_single_point_and_prior_degradation():
    """One operating point degrades gracefully (pure-bandwidth through the
    clamped α), and an unsampled leg falls back to its prior — both arms the
    BENCH_MODELED fit relies on with the single-sample vgg16 fixture."""
    one = [WireSample(nbytes=175_942_816, seconds=0.010842, leg="flat")]
    cm = CostModel.from_samples(one, intra_size=4)
    # the single-point fit must reproduce the observed point
    assert cm.flat.predict(one[0].nbytes) == pytest.approx(
        one[0].seconds, rel=1e-6
    )
    assert cm.flat.n_samples == 1
    # unsampled legs carry the planner priors (positive, finite)
    for leg in (cm.rs, cm.ag, cm.pp, cm.qr8, cm.qr4):
        assert leg.n_samples == 0
        assert leg.alpha > 0 and leg.beta > 0


# ---------------------------------------------------------------------------
# Modeled bytes == census bytes on the live engines
# ---------------------------------------------------------------------------


def _build(group, name, wire, overlap):
    kwargs = {} if wire == "f32" else {"wire_precision": wire}
    algo = build_algorithm(name, lr=0.1, **kwargs)
    return DistributedDataParallel(
        mse_loss, optax.sgd(0.1, momentum=0.9), algo,
        process_group=group, bucket_size_bytes=1 << 12, overlap=overlap,
    )


def _batch():
    rng = np.random.RandomState(0)
    return (
        jnp.asarray(rng.randn(32, LAYERS[0]).astype(np.float32)),
        jnp.asarray(rng.randn(32, LAYERS[-1]).astype(np.float32)),
    )


@pytest.mark.parametrize("name,wire", [
    ("gradient_allreduce", "f32"),
    ("gradient_allreduce", "int8"),
    ("gradient_allreduce", "int4"),
    ("zero", "f32"),
    ("zero", "int8"),
    ("zero", "int4"),
])
def test_modeled_bytes_equal_census_bytes(group, name, wire):
    """The tentpole's provenance invariant, on the real traced engines: the
    bytes the α–β pricing charges are exactly the CollectiveIR census bytes
    (both branch-deduped the verifier's way), the cell verifies, and the
    modeled step is nonzero."""
    cost_model = CostModel.from_samples([], intra_size=4)
    ddp = _build(group, name, wire, overlap=False)
    try:
        state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
        cell = model_step_cell(ddp, state, _batch(), cost_model, wire=wire)
    finally:
        ddp.shutdown()
    assert cell.verified, cell.findings
    assert cell.modeled_wire_bytes == cell.census_wire_bytes
    assert cell.modeled_wire_bytes > 0
    assert cell.modeled_step_ms > 0
    assert cell.wire_ms > 0
    assert 0 < cell.modeled_goodput_frac <= 1.0
    # every priced group maps to a real cost-model leg
    assert cell.legs_used
    assert set(cell.legs_used) <= {
        "flat", "intra", "inter", "rs", "ag", "pp", "qr8", "qr4",
    }
    # and the leg breakdown re-sums to the totals
    assert sum(
        leg["wire_bytes"] for leg in cell.leg_breakdown.values()
    ) == cell.modeled_wire_bytes


def test_quantized_cells_ride_qr_legs(group):
    """int8/int4 wire programs must be priced on the quantized-ring legs —
    mispricing them as flat f32 exchanges would silently misrank the
    precision trade-off BENCH_MODELED exists to expose."""
    cost_model = CostModel.from_samples([], intra_size=4)
    for wire, leg in (("int8", "qr8"), ("int4", "qr4")):
        ddp = _build(group, "gradient_allreduce", wire, overlap=False)
        try:
            state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
            cell = model_step_cell(ddp, state, _batch(), cost_model, wire=wire)
        finally:
            ddp.shutdown()
        assert leg in cell.legs_used, (wire, cell.legs_used)
        assert cell.leg_breakdown[leg]["wire_bytes"] > 0


def test_census_matches_committed_artifact(group):
    """A fresh trace reproduces the committed BENCH_MODELED.json byte
    census for the headline cell — the committed artifact is live evidence,
    not a snapshot that can silently rot."""
    art = json.load(open(os.path.join(REPO, "BENCH_MODELED.json")))
    ref = next(
        r for r in art["rows"]
        if r["algo"] == "gradient_allreduce" and r["wire"] == "f32"
        and r["overlap"] is False
    )
    ddp = _build(group, "gradient_allreduce", "f32", overlap=False)
    try:
        state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
        cell = model_step_cell(
            ddp, state, _batch(), CostModel.from_samples([], intra_size=4)
        )
    finally:
        ddp.shutdown()
    assert cell.census_wire_bytes == ref["census_wire_bytes"]
    assert cell.num_collectives == ref["num_collectives"]


# ---------------------------------------------------------------------------
# FLOP census
# ---------------------------------------------------------------------------


def test_flops_census_counts_dot_general_exactly():
    a = jnp.zeros((32, 64), jnp.float32)
    b = jnp.zeros((64, 128), jnp.float32)
    closed = jax.make_jaxpr(lambda x, y: x @ y)(a, b)
    census = flops_census(closed)
    assert census["n_dots"] == 1
    assert census["flops"] == 2.0 * 32 * 64 * 128


def test_flops_census_cond_takes_max_branch():
    x = jnp.zeros((16, 16), jnp.float32)

    def f(p, x):
        return jax.lax.cond(p, lambda v: v @ v @ v, lambda v: v @ v, x)

    census = flops_census(jax.make_jaxpr(f)(True, x))
    # max branch: two matmuls, not three (2+1) summed across branches
    assert census["flops"] == 2 * (2.0 * 16 * 16 * 16)


# ---------------------------------------------------------------------------
# Pallas evidence gating
# ---------------------------------------------------------------------------


def test_pallas_basis_fallback_without_chip_evidence(tmp_path):
    # the committed PALLAS_TPU.json is interpret-mode CPU → fallback basis
    basis = pallas_kernel_basis("gradient_allreduce", "int8")
    assert basis["basis"] == "modeled-jnp-fallback"
    assert "quantized_ring_hop_int8" in basis["gated_kernels"]
    # f32 monolithic programs gate on no Pallas kernel at all
    assert pallas_kernel_basis("gradient_allreduce", "f32")["basis"] == (
        "jnp-native"
    )
    # real-chip evidence for every gated kernel flips the basis
    ev = tmp_path / "pallas.json"
    ev.write_text(json.dumps({
        "backend": "tpu v5e", "interpret": False,
        "kernels": [
            {"kernel": "quantized_ring_hop_int8"},
            {"kernel": "decompress_reduce_requantize"},
        ],
    }))
    chip = pallas_kernel_basis("gradient_allreduce", "int8",
                               evidence_path=str(ev))
    assert chip["basis"] == "measured-chip"


def test_modeled_bench_rows_read_committed_artifact():
    rows = modeled_bench_rows("vgg16_img_per_sec_per_chip")
    assert rows and rows[0]["mode"] == "modeled"
    assert rows[0]["value"] > 0
    assert rows[0]["trend"], "modeled trend rows missing"
    eff = modeled_bench_rows("vgg16_dp_scaling_efficiency")
    assert eff and 0 < eff[0]["value"] <= 1.0
    assert modeled_bench_rows("no_such_metric") == []


# ---------------------------------------------------------------------------
# One topology model (scaling_projection unification)
# ---------------------------------------------------------------------------


def test_topology_is_shared_with_scaling_projection():
    """Both committed artifacts carry the same TopologyAssumptions block —
    the 'two diverging cost models' failure mode is structurally gone."""
    desc = DEFAULT_TOPOLOGY.describe()
    sp = json.load(open(os.path.join(REPO, "SCALING_PROJECTION.json")))
    for key, val in desc.items():
        assert sp["assumptions"][key] == val, key
    bm = json.load(open(os.path.join(REPO, "BENCH_MODELED.json")))
    assert bm["assumptions"]["topology"] == desc


def test_t_collective_ring_model():
    topo = DEFAULT_TOPOLOGY
    n, B = 8, 1 << 20
    dx, dy = torus_dims(n)
    lat = (dx / 2 + dy / 2) * topo.ici_lat_hop
    assert t_collective("allreduce", B, n) == pytest.approx(
        2 * (n - 1) / n * B / topo.ici_bw_chip + 2 * lat
    )
    assert t_collective("allgather", B, n) == pytest.approx(
        (n - 1) / n * B / topo.ici_bw_chip + lat
    )
    assert t_collective("permute", B, n) == pytest.approx(
        B / topo.ici_bw_chip + topo.ici_lat_hop
    )
    assert t_collective("allreduce", B, 1) == 0.0
    # DCN leg parameters are explicit model fields, not buried constants
    assert topo.dcn_bw_chip() == topo.dcn_bw_host / topo.chips_per_host
