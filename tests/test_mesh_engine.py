"""Named-mesh engine: MeshSpec construction/validation, axis-kwarg typo
fences on the engine and Trainer, dp x 1 bitwise parity with the legacy 1-D
engine, 2-D end-to-end training, and the per-axis static-verifier arms.

The tentpole's contract in one file: a ``MeshSpec`` threads named axes
through the group and the engine so the bucketed gradient exchange rides
the *data* axes only, while model axes (tp/fsdp-as-param-shard) keep their
own collectives — and every way to get that wiring wrong (typo'd axis
kwarg, role mismatch, hierarchical algorithm on a named mesh, an exchange
collective leaking onto a model axis) fails loudly at construction or
static-verify time instead of silently averaging across tensor-parallel
shards.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import bagua_tpu
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.analysis import (
    WireModelConfig,
    check_plan_conformance,
    collect_ir,
    verify_step_program,
)
from bagua_tpu.analysis.verify import _abstract
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.mesh import DATA_AXIS_NAMES, MODEL_AXIS_NAMES, MeshSpec
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.observability import FlightRecorder, Telemetry
from bagua_tpu.sharded.algorithm import ZeroAlgorithm
from bagua_tpu.trainer import Trainer

LAYERS = [12, 16, 16, 4]


def make_batch(seed=0, n=32):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, LAYERS[0]).astype(np.float32))
    y = jnp.asarray(rng.randn(n, LAYERS[-1]).astype(np.float32))
    return x, y


def make_ddp(group, algo=None, **kw):
    kw.setdefault("bucket_size_bytes", 1 << 9)
    return DistributedDataParallel(
        mse_loss, optax.sgd(0.1), algo or GradientAllReduceAlgorithm(),
        process_group=group, **kw,
    )


# -- MeshSpec construction and validation (satellite 1) -----------------------


def test_meshspec_roles_and_sizes():
    spec = MeshSpec({"dp": 4, "tp": 2})
    assert spec.names == ("dp", "tp")
    assert spec.size == 8 and spec.shape == (4, 2)
    assert spec.data_axes == ("dp",) and spec.model_axes == ("tp",)
    assert spec.exchange_size == 4
    assert "dp" in DATA_AXIS_NAMES and "tp" in MODEL_AXIS_NAMES

    spec = MeshSpec({"dp": 4, "fsdp": 2})
    assert spec.data_axes == ("dp", "fsdp")
    assert spec.exchange_size == 8  # fsdp rides the exchange too

    # explicit overrides beat name inference
    spec = MeshSpec({"rows": 4, "cols": 2}, dp_axis="rows", tp_axis="cols")
    assert spec.data_axes == ("rows",) and spec.model_axes == ("cols",)


def test_meshspec_equality_and_repr():
    a, b = MeshSpec({"dp": 4, "tp": 2}), MeshSpec({"dp": 4, "tp": 2})
    assert a == b and hash(a) == hash(b)
    assert a != MeshSpec({"dp": 2, "tp": 4})
    assert "dp=4" in repr(a) and "tp=2" in repr(a)


def test_meshspec_typo_axis_kwarg_raises():
    """A typo'd dp_axis/tp_axis/fsdp_axis names none of the declared axes —
    the construction-time fence for the silent-replication failure mode."""
    with pytest.raises(ValueError, match="none of the declared mesh axes"):
        MeshSpec({"dp": 4, "tp": 2}, dp_axis="dpp")
    with pytest.raises(ValueError, match="check the tp_axis spelling"):
        MeshSpec({"dp": 4, "tp": 2}, tp_axis="pt")


def test_meshspec_malformed_specs_raise():
    with pytest.raises(ValueError, match="at least one axis"):
        MeshSpec({})
    with pytest.raises(ValueError, match="duplicate mesh axis names"):
        MeshSpec([("dp", 4), ("dp", 2)])
    with pytest.raises(ValueError, match="non-positive size"):
        MeshSpec({"dp": 0})
    with pytest.raises(ValueError, match="exactly one role"):
        MeshSpec({"dp": 4, "tp": 2}, dp_axis="tp", tp_axis="tp")
    with pytest.raises(ValueError, match="no inferable role"):
        MeshSpec({"rows": 4, "cols": 2})
    with pytest.raises(ValueError, match="carry the data-parallel exchange"):
        MeshSpec({"tp": 8})


def test_group_needs_matching_device_count():
    with pytest.raises(ValueError, match="needs 16 devices"):
        bagua_tpu.new_group(mesh_spec=MeshSpec({"dp": 8, "tp": 2}))


def test_group_exposes_mesh_axes():
    g = bagua_tpu.new_group(mesh_spec=MeshSpec({"dp": 4, "tp": 2}))
    assert g.all_axes == ("dp", "tp")
    assert g.data_axes == ("dp",) and g.model_axes == ("tp",)
    assert g.size == 8 and g.exchange_size == 4
    assert dict(g.mesh.shape) == {"dp": 4, "tp": 2}


# -- engine / Trainer axis-kwarg fences (satellite 1) -------------------------


def test_ddp_typo_axis_kwarg_raises():
    g = bagua_tpu.new_group(mesh_spec=MeshSpec({"dp": 4, "tp": 2}))
    with pytest.raises(ValueError, match="none of the declared mesh axes"):
        make_ddp(g, dp_axis="ddp")
    with pytest.raises(ValueError, match="none of the declared mesh axes"):
        make_ddp(g, tp_axis="tpp")


def test_trainer_typo_axis_kwarg_raises():
    g = bagua_tpu.new_group(mesh_spec=MeshSpec({"dp": 4, "tp": 2}))
    with pytest.raises(ValueError, match="none of the declared mesh axes"):
        Trainer(
            mse_loss, optax.sgd(0.1), GradientAllReduceAlgorithm(),
            process_group=g, dp_axis="ddp",
        )


def test_ddp_axis_role_mismatch_raises():
    """Naming a declared-but-wrong-role axis is a different bug than a typo
    and gets a different message: the axis exists, its role doesn't fit."""
    g = bagua_tpu.new_group(mesh_spec=MeshSpec({"dp": 4, "tp": 2}))
    with pytest.raises(ValueError, match="must name one of its data axes"):
        make_ddp(g, dp_axis="tp")
    with pytest.raises(ValueError, match="must name one of its model axes"):
        make_ddp(g, tp_axis="dp")


def test_hierarchical_fenced_on_named_mesh():
    g = bagua_tpu.new_group(mesh_spec=MeshSpec({"dp": 4, "tp": 2}))
    with pytest.raises(ValueError, match="legacy \\(inter, intra\\) mesh"):
        make_ddp(g, algo=GradientAllReduceAlgorithm(hierarchical=True))


# -- dp x 1 bitwise parity with the 1-D engine (acceptance) -------------------


@pytest.mark.parametrize("algo_cls", [GradientAllReduceAlgorithm, ZeroAlgorithm])
def test_dp1_bitwise_parity_with_legacy_engine(algo_cls):
    """A pure-dp MeshSpec mesh is the SAME machine as the legacy 1-D group:
    3 overlapped steps + finalize land bitwise-identical params AND
    optimizer state.  The refactor moved the axis wiring, not the math."""
    params = init_mlp(jax.random.PRNGKey(0), LAYERS)
    batches = [make_batch(seed=s) for s in range(3)]
    finals = []
    for spec in (None, MeshSpec({"dp": 8})):
        if spec is None:
            g = bagua_tpu.new_group(intra_size=1)
        else:
            g = bagua_tpu.new_group(mesh_spec=spec)
        ddp = make_ddp(g, algo=algo_cls(), overlap=True)
        state = ddp.init(params)
        for b in batches:
            state, losses = ddp.train_step(state, b)
        state = ddp.finalize_pending_updates(state)
        jax.block_until_ready(state)
        ddp.shutdown()
        finals.append(state)
    la, lb = jax.tree.leaves(finals[0]), jax.tree.leaves(finals[1])
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- 2-D meshes end-to-end (acceptance) ---------------------------------------


@pytest.mark.parametrize(
    "axes,algo_cls",
    [
        ({"dp": 4, "tp": 2}, GradientAllReduceAlgorithm),
        ({"dp": 4, "tp": 2}, ZeroAlgorithm),
        ({"dp": 4, "fsdp": 2}, GradientAllReduceAlgorithm),
        ({"dp": 4, "fsdp": 2}, ZeroAlgorithm),
    ],
)
def test_2d_mesh_trains_and_replicates(axes, algo_cls):
    """Both 2-D shapes train under both exchange algorithms with overlap on,
    and the final params are identical on every rank row — the dp average
    covers dp rows, and tp/fsdp peers ran the same replicated computation."""
    g = bagua_tpu.new_group(mesh_spec=MeshSpec(axes))
    ddp = make_ddp(g, algo=algo_cls(), overlap=True)
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    losses_seen = []
    for s in range(3):
        state, losses = ddp.train_step(state, make_batch(seed=s))
        losses_seen.append(float(np.asarray(losses).ravel()[0]))
    state = ddp.finalize_pending_updates(state)
    jax.block_until_ready(state)
    ddp.shutdown()
    assert all(np.isfinite(l) for l in losses_seen)
    for leaf in jax.tree.leaves(state.params):
        arr = np.asarray(leaf)
        assert arr.shape[0] == g.size
        for r in range(1, g.size):
            np.testing.assert_array_equal(arr[r], arr[0])


# -- static verifier on 2-D programs (acceptance) -----------------------------


@pytest.mark.parametrize(
    "axes,algo_cls,want_axes",
    [
        ({"dp": 4, "tp": 2}, GradientAllReduceAlgorithm, ("dp",)),
        ({"dp": 4, "fsdp": 2}, ZeroAlgorithm, ("dp", "fsdp")),
    ],
)
def test_static_verify_2d_program(axes, algo_cls, want_axes):
    g = bagua_tpu.new_group(mesh_spec=MeshSpec(axes))
    ddp = make_ddp(g, algo=algo_cls())
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    batch = make_batch()
    cfg = WireModelConfig.from_engine(ddp)
    assert cfg.exchange_axes == want_axes
    assert cfg.mesh_axes == tuple(axes)
    assert cfg.n == g.exchange_size
    report = verify_step_program(
        ddp, state, batch, variant=ddp.impl.step_variant(0)
    )
    errors = [f for f in report.findings if f.severity == "error"]
    assert report.ok, errors
    ddp.shutdown()


def test_axis_conformance_flags_stray_exchange_axis():
    """The negative arm: the same traced 2-D program fails conformance when
    the config claims the exchange is confined to an axis the collectives
    don't actually ride — the checker names the stray axes."""
    g = bagua_tpu.new_group(mesh_spec=MeshSpec({"dp": 4, "tp": 2}))
    ddp = make_ddp(g)
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    batch = make_batch()
    variant = ddp.impl.step_variant(0)
    program, _ = collect_ir(
        ddp._build_sharded(variant),
        (_abstract(state), _abstract(batch)),
        dict(g.mesh.shape),
    )
    cfg = WireModelConfig.from_engine(ddp)
    assert not [
        f for f in check_plan_conformance(program, cfg)
        if f.severity == "error"
    ]
    lying = dataclasses.replace(cfg, exchange_axes=("tp",))
    findings = [
        f for f in check_plan_conformance(program, lying)
        if f.severity == "error" and "stray" in f.message
    ]
    assert findings, "exchange collectives on dp were not flagged vs tp-only"
    assert any("'dp'" in f.message for f in findings)
    ddp.shutdown()


# -- flight records carry the exchange axes -----------------------------------


def test_flight_records_carry_data_axes():
    g = bagua_tpu.new_group(mesh_spec=MeshSpec({"dp": 4, "tp": 2}))
    fr = FlightRecorder(capacity=128, rank=0, world_size=1)
    ddp = make_ddp(g, telemetry=Telemetry(flight=fr))
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    state, losses = ddp.train_step(state, make_batch())
    jax.block_until_ready(losses)
    ddp.shutdown()
    (program,) = ddp._flight_programs.values()
    exchange = [r for r in program if r["phase"] != "hop"]
    assert exchange, "no exchange records captured"
    for rec in exchange:
        assert rec["axes"] == ["dp"]

# -- per-axis budget partition over engine-traced programs --------------------


@pytest.mark.parametrize(
    "axes", [{"dp": 8}, {"dp": 4, "tp": 2}, {"dp": 4, "fsdp": 2}],
    ids=["dp8", "dp4xtp2", "dp4xfsdp2"],
)
@pytest.mark.parametrize("algo_cls", [GradientAllReduceAlgorithm, ZeroAlgorithm])
@pytest.mark.parametrize("precision", ["f32", "int8"])
def test_axis_budget_partition_exact_over_traced_program(
        axes, algo_cls, precision):
    """Property, over real traced programs (gar/zero x f32/int8 x three
    mesh shapes): the BudgetModel's per-axis wire ledger joined from the
    captured flight program covers exactly the mesh's data axes, its scalar
    wire promise is the ledger's sum, and the settled per-axis
    wire_slowdown split sums BITWISE to the scalar component on every
    pricing path — partition by construction, no tolerance."""
    from bagua_tpu.observability import BudgetModel
    from bagua_tpu.service.planner import AlphaBeta, CostModel

    g = bagua_tpu.new_group(mesh_spec=MeshSpec(axes))
    fr = FlightRecorder(capacity=256, rank=0, world_size=1)
    ddp = make_ddp(g, algo=algo_cls(wire_precision=precision),
                   telemetry=Telemetry(flight=fr))
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    state, losses = ddp.train_step(state, make_batch())
    jax.block_until_ready(losses)
    ddp.shutdown()
    (program,) = ddp._flight_programs.values()

    legs = {ax: AlphaBeta(0.0, 1e8 * (i + 1))
            for i, ax in enumerate(g.data_axes)}
    cm = CostModel(flat=AlphaBeta(0.0, 1e9), axis_legs=legs)
    model = BudgetModel(compute_ms=6.0, cost_model=cm, program=program)

    # the ledger joined from the program covers exactly the data axes the
    # exchange rides, and the scalar promise IS its sorted-key sum
    assert set(model.axis_wire_ms) == set(g.data_axes)
    assert all(v > 0 for v in model.axis_wire_ms.values())
    assert model.wire_ms == sum(
        model.axis_wire_ms[ax] for ax in sorted(model.axis_wire_ms))

    def assert_exact(budget):
        assert set(budget.wire_axis_ms) == set(g.data_axes)
        assert budget.components["wire_slowdown"] == sum(
            budget.wire_axis_ms[ax] for ax in sorted(budget.wire_axis_ms))
        assert budget.axis_partition_error_ms() == 0.0

    # path 1: per-axis measured wire (enqueue->retire deltas)
    model.note_wire(
        sum(model.axis_wire_ms.values()) * 2.0,
        by_axis={ax: ms * 2.0 for ax, ms in model.axis_wire_ms.items()})
    assert_exact(model.settle(0, 20.0))

    # path 2: scalar measured wire, split by the ledger's expected shares
    model.note_wire(model.wire_ms * 3.0)
    assert_exact(model.settle(1, 20.0))

    # path 3: per-axis byte census over the program's own traffic
    census = {ax: 0.0 for ax in g.data_axes}
    for rec in program:
        rec_axes = [a for a in (rec.get("axes") or ()) if a]
        if not rec_axes or not rec.get("nbytes"):
            continue
        for ax in rec_axes:
            census[ax] += float(rec["nbytes"]) / len(rec_axes)
    assert all(v > 0 for v in census.values())
    base = model.expected()  # clean steps must land inside the 25% band
    for step in range(2, 7):
        model.settle(step, base, wire_bytes_by_axis=dict(census))
    inflated = dict(census)
    worst = sorted(inflated)[-1]
    inflated[worst] *= 2.0
    assert_exact(model.settle(7, base + 4.0, wire_bytes_by_axis=inflated))
