"""Decentralized algorithms vs pure-numpy oracles.

TPU analog of the reference's oracle-style tests
(``tests/torch_api/test_decentralized.py``,
``test_low_precision_decentralized.py``): the algorithm is reimplemented in
plain numpy/jax on stacked per-rank weights and compared against the
framework's result after several steps.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms.decentralized import (
    DecentralizedAlgorithm,
    LowPrecisionDecentralizedAlgorithm,
    _shift_one_perm,
)
from bagua_tpu.bucket import BucketPlan
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

from tests.oracles import oracle_compress, oracle_decompress

N = 8
N_STEPS = 6
LR = 0.05
DIM_IN, DIM_OUT = 10, 3


def make_problem(seed=0):
    params = init_mlp(jax.random.PRNGKey(seed), [DIM_IN, 8, DIM_OUT])
    rng = np.random.RandomState(seed)
    xs = rng.randn(N_STEPS, N * 4, DIM_IN).astype(np.float32)
    ys = rng.randn(N_STEPS, N * 4, DIM_OUT).astype(np.float32)
    return params, xs, ys


def flat_grad_fn(plan, shapes_params):
    """Return f(flat_w, x, y) -> flat gradient, via the same bucket layout."""

    def fn(flat, x, y):
        params = plan.debucketize([flat])
        g = jax.grad(mse_loss)(params, (x, y))
        return plan.bucketize(g)[0]

    return jax.jit(fn)


def test_shift_one_perm_symmetric():
    for n in [2, 4, 8]:
        for s in range(8):
            perm = _shift_one_perm(s, n)
            peer = dict(perm)
            for r, p in perm:
                assert peer[p] == r, f"asymmetric pairing at n={n} s={s}"
                assert p != r


@pytest.mark.parametrize("mode", ["all", "shift_one"])
def test_decentralized_matches_oracle(group, mode):
    params, xs, ys = make_problem()
    ddp = DistributedDataParallel(
        mse_loss,
        optax.sgd(LR),
        DecentralizedAlgorithm(hierarchical=False, peer_selection_mode=mode),
        process_group=group,
    )
    state = ddp.init(params)
    for i in range(N_STEPS):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))

    # ---- numpy oracle over stacked flat weights ----
    plan = BucketPlan.from_tree(params, 1 << 62, align_elems=N)
    grad = flat_grad_fn(plan, params)
    w = np.tile(np.asarray(plan.bucketize(params)[0])[None], (N, 1))
    for step in range(N_STEPS):
        x = xs[step].reshape(N, -1, DIM_IN)
        y = ys[step].reshape(N, -1, DIM_OUT)
        g = np.stack([np.asarray(grad(jnp.asarray(w[r]), x[r], y[r])) for r in range(N)])
        if mode == "all":
            peer = np.tile(w.mean(axis=0, keepdims=True), (N, 1))
        else:
            perm = _shift_one_perm(step, N)
            recv = np.empty_like(w)
            for src, dst in perm:
                recv[dst] = w[src]
            peer = (w + recv) * 0.5
        w = peer - LR * g

    got = np.stack(
        [np.asarray(ddp.plan.bucketize(ddp.params_unstacked(state, r))[0]) for r in range(N)]
    )
    np.testing.assert_allclose(got, w, rtol=2e-4, atol=1e-5)


def test_decentralized_hierarchical_all_matches_oracle(group):
    """hierarchical all-mode: intra average then inter average == global
    average, so the run must match the flat-mode numpy oracle exactly."""
    params, xs, ys = make_problem(seed=3)
    ddp = DistributedDataParallel(
        mse_loss,
        optax.sgd(LR),
        DecentralizedAlgorithm(hierarchical=True, peer_selection_mode="all"),
        process_group=group,
    )
    state = ddp.init(params)
    for i in range(2):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))

    plan = BucketPlan.from_tree(params, 1 << 62, align_elems=N)
    grad = flat_grad_fn(plan, params)
    w = np.tile(np.asarray(plan.bucketize(params)[0])[None], (N, 1))
    for step in range(2):
        x = xs[step].reshape(N, -1, DIM_IN)
        y = ys[step].reshape(N, -1, DIM_OUT)
        g = np.stack([np.asarray(grad(jnp.asarray(w[r]), x[r], y[r])) for r in range(N)])
        w = np.tile(w.mean(axis=0, keepdims=True), (N, 1)) - LR * g
    got = np.stack(
        [np.asarray(ddp.plan.bucketize(ddp.params_unstacked(state, r))[0]) for r in range(N)]
    )
    np.testing.assert_allclose(got, w, rtol=2e-4, atol=1e-5)


def test_communication_interval_skips_steps(group):
    params, xs, ys = make_problem(seed=4)
    ddp = DistributedDataParallel(
        mse_loss,
        optax.sgd(LR),
        DecentralizedAlgorithm(
            hierarchical=False, peer_selection_mode="all", communication_interval=2
        ),
        process_group=group,
    )
    state = ddp.init(params)
    for i in range(2):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))

    # oracle: exchange at step 0 (0 % 2 == 0), skip at step 1
    plan = BucketPlan.from_tree(params, 1 << 62, align_elems=N)
    grad = flat_grad_fn(plan, params)
    w = np.tile(np.asarray(plan.bucketize(params)[0])[None], (N, 1))
    for step in range(2):
        x = xs[step].reshape(N, -1, DIM_IN)
        y = ys[step].reshape(N, -1, DIM_OUT)
        g = np.stack([np.asarray(grad(jnp.asarray(w[r]), x[r], y[r])) for r in range(N)])
        if step % 2 == 0:
            w = np.tile(w.mean(axis=0, keepdims=True), (N, 1)) - LR * g
        else:
            w = w - LR * g
    got = np.stack(
        [np.asarray(ddp.plan.bucketize(ddp.params_unstacked(state, r))[0]) for r in range(N)]
    )
    np.testing.assert_allclose(got, w, rtol=2e-4, atol=1e-5)


def test_low_precision_decentralized_matches_oracle(group):
    params, xs, ys = make_problem(seed=5)
    ddp = DistributedDataParallel(
        mse_loss,
        optax.sgd(LR),
        LowPrecisionDecentralizedAlgorithm(hierarchical=False),
        process_group=group,
    )
    state = ddp.init(params)
    for i in range(N_STEPS):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))

    # ---- numpy oracle ----
    plan = BucketPlan.from_tree(params, 1 << 62, align_elems=N)
    grad = flat_grad_fn(plan, params)
    w0 = np.asarray(plan.bucketize(params)[0])
    w = np.tile(w0[None], (N, 1))  # live weights
    wrep = w.copy()  # "weight" replica
    lrep = w.copy()
    rrep = w.copy()
    for step in range(N_STEPS):
        x = xs[step].reshape(N, -1, DIM_IN)
        y = ys[step].reshape(N, -1, DIM_OUT)
        g = np.stack([np.asarray(grad(jnp.asarray(w[r]), x[r], y[r])) for r in range(N)])
        t = w - LR * g  # post-optimizer weights
        diff = t + lrep / 3.0 + rrep / 3.0 - wrep * (5.0 / 3.0)
        qs, mms = zip(*[oracle_compress(diff[r][None]) for r in range(N)])
        own = np.stack([oracle_decompress(qs[r], mms[r])[0] for r in range(N)])
        lrecv = np.stack([own[(r - 1) % N] for r in range(N)])  # from left peer
        rrecv = np.stack([own[(r + 1) % N] for r in range(N)])
        lrep = lrep + lrecv
        rrep = rrep + rrecv
        t_new = own + wrep
        w = t_new
        wrep = t_new.copy()

    got = np.stack(
        [np.asarray(ddp.plan.bucketize(ddp.params_unstacked(state, r))[0]) for r in range(N)]
    )
    np.testing.assert_allclose(got, w, rtol=2e-4, atol=2e-4)


def test_shift_one_odd_world_construction_fence():
    """_shift_one_perm partitions ranks into halves, so an odd peer count
    silently mis-pairs — the impl constructor must reject it up front,
    naming the mesh, for both the flat and the hierarchical (inter-axis)
    worlds.  Even worlds construct fine."""
    from types import SimpleNamespace

    from bagua_tpu.algorithms.decentralized import DecentralizedAlgorithmImpl

    def fake_group(intra, inter):
        return SimpleNamespace(
            intra_size=intra, inter_size=inter,
            exchange_size=intra * inter,
        )

    # the fence must name the failing peer count AND suggest both remedies
    # (resize to an even world, or fall back to peer_selection_mode='all')
    with pytest.raises(ValueError, match="even number") as exc:
        DecentralizedAlgorithmImpl(
            fake_group(1, 3), hierarchical=False,
            peer_selection_mode="shift_one",
        )
    msg = str(exc.value)
    assert "3 peers" in msg
    assert "e.g. 2 or 4" in msg
    assert "peer_selection_mode='all'" in msg
    with pytest.raises(ValueError, match="even number") as exc:
        DecentralizedAlgorithmImpl(
            fake_group(4, 3), hierarchical=True,
            peer_selection_mode="shift_one",
        )
    assert "3 peers" in str(exc.value)
    # even peers (flat 8, and hierarchical inter=2) construct fine
    DecentralizedAlgorithmImpl(
        fake_group(1, 8), hierarchical=False, peer_selection_mode="shift_one"
    )
    DecentralizedAlgorithmImpl(
        fake_group(4, 2), hierarchical=True, peer_selection_mode="shift_one"
    )


def test_gossip_construction_fences():
    """The gossip staleness gate is defined on the full flat exchange with
    an exchange every round: hierarchical or interval-skipping
    constructions must be rejected, as must a negative bound."""
    from types import SimpleNamespace

    from bagua_tpu.algorithms.decentralized import DecentralizedAlgorithmImpl

    g = SimpleNamespace(intra_size=1, inter_size=8, exchange_size=8)
    with pytest.raises(ValueError, match="hierarchical=False"):
        DecentralizedAlgorithmImpl(g, hierarchical=True, staleness_tau=2)
    with pytest.raises(ValueError, match="communication_interval=1"):
        DecentralizedAlgorithmImpl(
            g, hierarchical=False, communication_interval=2, staleness_tau=2
        )
    with pytest.raises(ValueError, match=">= 0"):
        DecentralizedAlgorithmImpl(g, hierarchical=False, staleness_tau=-1)
    # τ switch knob only exists when the state was allocated at init
    plain = DecentralizedAlgorithmImpl(g, hierarchical=False)
    with pytest.raises(ValueError, match="staleness_tau"):
        plain.set_staleness_tau(2)


def test_gossip_tau0_bitwise_matches_plain_decentralized(group):
    """The gossip knob allocated-but-disabled (τ=0) must train bitwise
    identically to the plain flat decentralized exchange."""
    params, xs, ys = make_problem(seed=6)

    def run(algo):
        ddp = DistributedDataParallel(
            mse_loss, optax.sgd(LR), algo, process_group=group
        )
        state = ddp.init(params)
        for i in range(4):
            state, _ = ddp.train_step(
                state, (jnp.asarray(xs[i]), jnp.asarray(ys[i]))
            )
        return [np.asarray(l) for l in jax.tree.leaves(state.params)]

    got = run(DecentralizedAlgorithm(hierarchical=False, staleness_tau=0))
    ref = run(DecentralizedAlgorithm(hierarchical=False))
    for a, b in zip(got, ref):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)


def test_gossip_staleness_bound_forces_exchange(group):
    """Eager gossip: a rank under a directive skips adopting the average
    (ships its published replica, keeps its live weights) for at most τ
    consecutive rounds, then is forced back to the full exchange —
    counters cycle 1, 2, 0, … and healthy ranks never move off 0."""
    params, xs, ys = make_problem(seed=7)
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(LR),
        DecentralizedAlgorithm(hierarchical=False, staleness_tau=2),
        process_group=group,
    )
    state = ddp.init(params)
    state = ddp.apply_degradation_directive(state, (2,))
    seen = []
    for step in range(7):
        i = step % N_STEPS
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
        c = np.asarray(state.algo_state["staleness"])
        seen.append(int(c[2]))
        assert c[2] <= 2
        assert (np.delete(c, 2) == 0).all(), c
    assert seen == [1, 2, 0, 1, 2, 0, 1]


def test_gossip_stale_rank_keeps_local_weights(group):
    """During a replay round the degraded rank discards the received average
    (its weights evolve by pure local SGD) while still feeding its published
    replica into the others' average; on the forced round it re-joins."""
    params, xs, ys = make_problem(seed=8)
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(LR),
        DecentralizedAlgorithm(hierarchical=False, staleness_tau=1),
        process_group=group,
    )
    state = ddp.init(params)
    state = ddp.apply_degradation_directive(state, (2,))

    # step 0 is a replay round for rank 2 (counter 0 -> 1): pure local SGD
    # against the last-published (=init) weights shipped to the gang
    state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
    plan = BucketPlan.from_tree(params, 1 << 62, align_elems=N)
    grad = flat_grad_fn(plan, params)
    w0 = np.asarray(plan.bucketize(params)[0])
    x = xs[0].reshape(N, -1, DIM_IN)
    y = ys[0].reshape(N, -1, DIM_OUT)
    g2 = np.asarray(grad(jnp.asarray(w0), x[2], y[2]))
    local_only = w0 - LR * g2
    got2 = np.asarray(ddp.plan.bucketize(ddp.params_unstacked(state, 2))[0])
    np.testing.assert_allclose(got2, local_only, rtol=2e-4, atol=1e-5)

    # the healthy ranks averaged WITH rank 2's published (init) replica:
    # identical to what the τ=None all-mode exchange would have produced
    g = np.stack([np.asarray(grad(jnp.asarray(w0), x[r], y[r])) for r in range(N)])
    mean_w = np.tile(w0[None], (N, 1)).mean(axis=0)
    healthy = mean_w - LR * g[0]
    got0 = np.asarray(ddp.plan.bucketize(ddp.params_unstacked(state, 0))[0])
    np.testing.assert_allclose(got0, healthy, rtol=2e-4, atol=1e-5)

    # step 1: the bound (τ=1) forces rank 2 back into the exchange
    state, _ = ddp.train_step(state, (jnp.asarray(xs[1]), jnp.asarray(ys[1])))
    assert int(np.asarray(state.algo_state["staleness"])[2]) == 0


def test_flat_shift_one_hlo_has_no_all_gather(group):
    """The flat (combined-axes) shift_one exchange must lower to point-to-point
    collective-permutes, never an all-gather (VERDICT weak #4)."""
    import optax

    from bagua_tpu.algorithms.decentralized import DecentralizedAlgorithm
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.models.mlp import init_mlp, mse_loss

    params = init_mlp(jax.random.PRNGKey(0), [6, 8, 2])
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05),
        DecentralizedAlgorithm(hierarchical=False, peer_selection_mode="shift_one"),
        process_group=group,
    )
    state = ddp.init(params)
    fn = ddp._step_fns.get("default") or ddp._build_step("default")
    batch = (jnp.zeros((8, 6), jnp.float32), jnp.zeros((8, 2), jnp.float32))
    hlo = jax.jit(fn).lower(state, batch).compile().as_text()
    assert "collective-permute" in hlo
    assert "all-gather" not in hlo, "shift_one still lowers to an all-gather"
