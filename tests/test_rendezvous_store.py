"""Cross-host store backend over the rendezvous blob tier.

Covers the reference's redis-store behaviors (``redis_store.py:46-137``,
``store.py:56-143``) on our transport: binary round-trips, hashed routing
across multiple servers, LRU eviction at the byte cap (redis ``maxmemory``
+ ``allkeys-lru``), local-daemon bootstrap, and — the load-bearing case —
a CacheLoader cache genuinely shared across two OS processes.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from helpers import free_port

from bagua_tpu.contrib.cache_loader import CacheLoader
from bagua_tpu.contrib.rendezvous_store import (
    RendezvousStore,
    make_rendezvous_cluster_store,
)
from bagua_tpu.distributed.rendezvous import RendezvousState, start_rendezvous_server


@pytest.fixture()
def blob_server():
    port = free_port()
    state = RendezvousState(max_blob_bytes=1 << 20)
    server = start_rendezvous_server(state, port, host="127.0.0.1")
    yield f"127.0.0.1:{port}", state
    server.shutdown()


def test_blob_roundtrip_and_count(blob_server):
    endpoint, _ = blob_server
    store = RendezvousStore(endpoint)
    assert store.get("missing") is None
    arr = np.arange(1000, dtype=np.float32).reshape(10, 100)
    store.set("sample/0", (arr, {"label": 7}))
    got_arr, got_meta = store.get("sample/0")
    np.testing.assert_array_equal(got_arr, arr)
    assert got_meta == {"label": 7}
    store.set("sample/1", b"raw-bytes")
    assert store.num_keys() == 2
    assert store.status()
    store.clear()
    assert store.num_keys() == 0
    store.shutdown()


def test_keys_with_slashes_and_unicode(blob_server):
    endpoint, _ = blob_server
    store = RendezvousStore(endpoint)
    for key in ("a/b/c", "sp ace", "uni-ключ", "q?x=1&y=2"):
        store.set(key, key.upper())
        assert store.get(key) == key.upper()
    assert store.num_keys() == 4


def test_lru_eviction_at_byte_cap():
    port = free_port()
    state = RendezvousState(max_blob_bytes=4096)
    server = start_rendezvous_server(state, port, host="127.0.0.1")
    try:
        store = RendezvousStore(f"127.0.0.1:{port}")
        payload = os.urandom(1024)
        for i in range(3):
            store.set(f"k{i}", payload)
        _ = store.get("k0")       # LRU-touch k0 so k1 becomes the eviction victim
        store.set("k3", payload)  # pickled size pushes total past 4096
        assert store.get("k1") is None, "least-recently-used key survived the cap"
        assert store.get("k0") is not None
        assert store.get("k3") is not None
    finally:
        server.shutdown()


def test_cluster_store_routes_across_servers():
    ports = [free_port(), free_port()]
    states = [RendezvousState() for _ in ports]
    servers = [
        start_rendezvous_server(st, p, host="127.0.0.1")
        for st, p in zip(states, ports)
    ]
    try:
        cluster = make_rendezvous_cluster_store(
            [f"127.0.0.1:{p}" for p in ports]
        )
        items = {f"key-{i}": np.full((4,), i) for i in range(32)}
        cluster.mset(items)
        # Every key readable through the routing layer; the shards disjointly
        # partition the keyspace (no key written to both servers).
        for k, v in items.items():
            np.testing.assert_array_equal(cluster.get(k), v)
        per_server = [st.blob_count() for st in states]
        assert sum(per_server) == 32
        assert all(c > 0 for c in per_server), (
            f"xxhash routing sent every key to one shard: {per_server}"
        )
        assert cluster.num_keys() == 32
    finally:
        for s in servers:
            s.shutdown()


def test_blob_token_gates_blob_routes_only():
    port = free_port()
    state = RendezvousState(blob_token="s3cret")
    server = start_rendezvous_server(state, port, host="127.0.0.1")
    try:
        bad = RendezvousStore(f"127.0.0.1:{port}", token="wrong")
        with pytest.raises(RuntimeError, match="403"):
            bad.set("k", 1)
        with pytest.raises(RuntimeError, match="403"):
            bad.get("k")
        good = RendezvousStore(f"127.0.0.1:{port}", token="s3cret")
        good.set("k", 42)
        assert good.get("k") == 42
        # Membership routes stay open (no payloads): the rendezvous client
        # itself needs no token.
        from bagua_tpu.distributed.rendezvous import RendezvousClient

        client = RendezvousClient(f"127.0.0.1:{port}", node_rank=0)
        assert client.announce(nslots=1)["epoch"] == 0
    finally:
        server.shutdown()


def test_bootstrap_ambiguous_ports_raise():
    with pytest.raises(ValueError, match="bootstrap_port"):
        make_rendezvous_cluster_store(
            ["127.0.0.1:29400", "127.0.0.1:29500"], bootstrap=True
        )


def test_bootstrap_starts_local_server():
    port = free_port()
    cluster = make_rendezvous_cluster_store(
        [f"127.0.0.1:{port}"], bootstrap=True, max_blob_bytes=1 << 16
    )
    cluster.set("boot", [1, 2, 3])
    assert cluster.get("boot") == [1, 2, 3]
    # Second construction finds the server already serving (no double-start).
    again = make_rendezvous_cluster_store([f"127.0.0.1:{port}"], bootstrap=True)
    assert again.get("boot") == [1, 2, 3]


_CHILD_POPULATE = r"""
import sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {tests!r})
import numpy as np
from bagua_tpu.contrib.cache_loader import CacheLoader

loader = CacheLoader(
    backend="rendezvous", dataset_name="mnist", endpoints=[{endpoint!r}],
    writer_buffer_size=4,
)
loads = []
def load_fn(key):
    loads.append(key)
    return np.full((8,), int(key), dtype=np.int32)
for i in range(8):
    loader.get(str(i), load_fn)
loader.flush()
assert len(loads) == 8, loads
print("populated", loader.num_keys())
"""


@pytest.mark.slow
def test_cache_loader_shared_across_two_processes(blob_server):
    """The VERDICT r4 'missing #1' case: one OS process populates the cache,
    a different OS process gets pure hits through the same endpoints —
    the property the reference gets from redis
    (``tests/contrib/test_cached_dataset.py`` semantics, but cross-process)."""
    endpoint, state = blob_server
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = subprocess.run(
        [sys.executable, "-c", _CHILD_POPULATE.format(
            repo=repo, tests=os.path.join(repo, "tests"), endpoint=endpoint)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert child.returncode == 0, child.stdout + child.stderr
    assert state.blob_count() == 8  # the writes crossed the process boundary

    # This (parent) process: every key must be a hit — load_fn must never run.
    loader = CacheLoader(
        backend="rendezvous", dataset_name="mnist", endpoints=[endpoint]
    )

    def must_not_load(key):
        raise AssertionError(f"cache miss for {key} — cross-process hit failed")

    for i in range(8):
        value = loader.get(str(i), must_not_load)
        np.testing.assert_array_equal(value, np.full((8,), i, dtype=np.int32))
    assert loader.hit_rate == 1.0
