"""Unit tests for utils: flatten/unflatten, dtype map, speed meters."""

import time
from unittest import mock

import jax.numpy as jnp
import numpy as np

from bagua_tpu import utils
from bagua_tpu.defs import dtype_itemsize


def test_flatten_unflatten_roundtrip():
    arrays = [jnp.arange(6.0).reshape(2, 3), jnp.ones((4,)), jnp.zeros((1, 1, 2))]
    flat = utils.flatten(arrays)
    assert flat.shape == (12,)
    back = utils.unflatten(flat, [a.shape for a in arrays])
    for a, b in zip(arrays, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dtype_roundtrip():
    for d in [jnp.float32, jnp.float16, jnp.bfloat16, jnp.uint8, jnp.int32]:
        name = utils.to_bagua_datatype(d)
        assert utils.from_bagua_datatype(name) == d
        assert dtype_itemsize(name) == jnp.dtype(d).itemsize


def test_speed_meter_steady_rate():
    with mock.patch("time.time") as t:
        now = [1000.0]
        t.side_effect = lambda: now[0]
        m = utils.SpeedMeter()
        for _ in range(200):
            m.record(100.0)
            now[0] += 1.0
        assert abs(m.speed(60.0) - 100.0) < 5.0


def test_statistical_average_window_bounded():
    with mock.patch("time.time") as t:
        now = [1000.0]
        t.side_effect = lambda: now[0]
        avg = utils.StatisticalAverage()
        for _ in range(30):
            avg.record(5.0)
            now[0] += 1.0
        # Window must stay near actual history (~30 s), not blow up to 2**len.
        assert avg.total_recording_time() < 120.0
        assert abs(avg.get(8.0) - 5.0) < 1e-6


def test_align_size():
    assert utils.align_size(10, 8) == 16
    assert utils.align_size(16, 8) == 16
    assert utils.align_size(1, 32) == 32
