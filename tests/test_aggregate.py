"""Gang-scope aggregation: straggler attribution, KV push/collect, degradation.

Pins the acceptance criteria of the cross-rank observability layer:

* straggler attribution on a synthetic 4-rank summary set with one slowed
  rank — flagged rank, score = p50/median, slowest-phase attribution;
* push/collect through a *real* in-process rendezvous server, namespaced
  by the attempt nonce;
* clean degradation: a dead KV endpoint (or no client at all) trips the
  breaker and yields a local-only view — gauges flag it, training-path
  calls never raise;
* gang gauges ride the ordinary Prometheus export.
"""

import pytest

from helpers import free_port
from bagua_tpu.distributed.rendezvous import (
    RendezvousClient,
    RendezvousState,
    start_rendezvous_server,
)
from bagua_tpu.observability import (
    GangAggregator,
    GangView,
    MetricsRegistry,
    StepSummary,
    Telemetry,
    straggler_score,
    summarize_telemetry,
)
from bagua_tpu.observability.aggregate import gang_kv_key
from bagua_tpu.resilience.retry import CircuitBreaker


def four_rank_summaries(slow_rank=2, slow_factor=2.0):
    """Synthetic gang: three healthy ranks at 10 ms p50, one slowed one
    whose time went into the data phase."""
    out = []
    for r in range(4):
        slow = r == slow_rank
        out.append(StepSummary(
            rank=r, step=100, window=20,
            p50_ms=10.0 * (slow_factor if slow else 1.0),
            p99_ms=15.0,
            wire_bytes=1 << 20,
            mfu=0.4,
            samples_per_s=100.0,
            phase_ms={"dispatch": 4.0, "wait": 3.0,
                      "data": 11.0 if slow else 2.0},
        ))
    return out


# -- straggler attribution ----------------------------------------------------


def test_straggler_attribution_synthetic_four_ranks():
    s = straggler_score(four_rank_summaries(slow_rank=2, slow_factor=2.0))
    assert s is not None
    assert s["rank"] == 2
    assert s["score"] == pytest.approx(2.0)
    assert s["p50_ms"] == pytest.approx(20.0)
    assert s["gang_median_ms"] == pytest.approx(10.0)
    assert s["phase"] == "data"  # the slowed rank's largest phase bucket


def test_straggler_below_factor_or_underpopulated_is_none():
    assert straggler_score(four_rank_summaries(slow_factor=1.2)) is None
    assert straggler_score(four_rank_summaries()[:1]) is None
    assert straggler_score([]) is None
    # a custom factor can flag the mild skew
    assert straggler_score(four_rank_summaries(slow_factor=1.2), factor=1.1) is not None


def test_step_summary_payload_roundtrip_filters_unknown_fields():
    s = four_rank_summaries()[1]
    payload = s.payload()
    payload["from_the_future"] = {"x": 1}  # newer writer: ignored on read
    back = StepSummary.from_payload(payload)
    assert back == s


def test_gang_view_report_and_export():
    reg = MetricsRegistry()
    view = GangView(4, four_rank_summaries(slow_rank=3, slow_factor=3.0))
    rep = view.report()
    assert rep["ranks_reporting"] == 4 and not rep["local_only"]
    assert rep["p50_median_ms"] == pytest.approx(10.0)
    assert rep["p50_skew"] == pytest.approx(3.0)
    assert rep["mfu_mean"] == pytest.approx(0.4)
    assert rep["straggler"]["rank"] == 3
    view.export(reg)
    snap = reg.snapshot()
    assert snap["gang_ranks_reporting"] == 4
    assert snap["gang_straggler_rank"] == 3
    assert snap["gang_step_p50_skew"] == pytest.approx(3.0)
    prom = reg.to_prometheus()
    assert "bagua_gang_step_p50_ms_median" in prom
    # no straggler -> sentinel values, not a missing gauge
    GangView(4, four_rank_summaries(slow_factor=1.0)).export(reg)
    snap = reg.snapshot()
    assert snap["gang_straggler_rank"] == -1 and snap["gang_straggler_score"] == 0.0


def test_gang_view_per_rank_scores_report_and_export():
    """Per-rank straggler scores (each rank's p50 / gang median) ride the
    report AND the gauge export — the audit trail a per-rank degradation
    decision joins against, not just the worst rank's score."""
    reg = MetricsRegistry()
    view = GangView(4, four_rank_summaries(slow_rank=2, slow_factor=2.0))
    rep = view.report()
    assert rep["rank_scores"] == {
        "0": pytest.approx(1.0), "1": pytest.approx(1.0),
        "2": pytest.approx(2.0), "3": pytest.approx(1.0),
    }
    view.export(reg)
    snap = reg.snapshot()
    for r, score in ((0, 1.0), (1, 1.0), (2, 2.0), (3, 1.0)):
        assert snap[f"gang_straggler_score_rank{r}"] == pytest.approx(score)
    assert "bagua_gang_straggler_score_rank2" in reg.to_prometheus()
    # sub-threshold skew still exports per-rank scores (the whole point:
    # visibility below the indictment line)...
    view = GangView(4, four_rank_summaries(slow_rank=1, slow_factor=1.2))
    assert view.straggler is None
    assert view.rank_scores[1] == pytest.approx(1.2)
    # ...while an underpopulated or zero-median gang exports none
    assert GangView(4, four_rank_summaries()[:1]).rank_scores == {}


def test_gang_view_heartbeat_ages_report_and_export():
    reg = MetricsRegistry()
    # keys/values arrive as JSON strings from the coordinator; the view
    # normalizes them so a silent rank 1 is readable straight off the gauges
    view = GangView(4, four_rank_summaries(),
                    heartbeat_ages={"0": "0.1", 1: 7.25, 2: 0.2, 3: 0.15})
    rep = view.report()
    assert rep["heartbeat_ages_s"] == {"0": 0.1, "1": 7.25, "2": 0.2, "3": 0.15}
    view.export(reg)
    snap = reg.snapshot()
    assert snap["gang_heartbeat_age_s_rank0"] == pytest.approx(0.1)
    assert snap["gang_heartbeat_age_s_rank1"] == pytest.approx(7.25)
    assert "bagua_gang_heartbeat_age_s_rank1" in reg.to_prometheus()
    # no ages (old coordinator) -> empty map in the report, no per-rank gauges
    rep = GangView(4, four_rank_summaries()).report()
    assert rep["heartbeat_ages_s"] == {}


def test_summarize_telemetry_reads_registry(tmp_path):
    tel = Telemetry(metrics_jsonl=str(tmp_path / "m.jsonl"))
    for i in range(6):
        tel.on_step(step=i, wall_s=0.010, n_samples=32, wire_bytes=1000)
    tel.registry.gauge("mfu").set(0.33)
    tel.registry.gauge("health_loss").set(1.25)
    s = summarize_telemetry(tel, rank=3, step=6, window=6,
                            phase_ms={"dispatch": 5.0})
    assert s.rank == 3 and s.step == 6 and s.window == 6
    assert s.p50_ms == pytest.approx(10.0, rel=0.01)
    assert s.wire_bytes == 6000
    assert s.mfu == pytest.approx(0.33)
    assert s.phase_ms == {"dispatch": 5.0}
    assert s.health["health_loss"] == pytest.approx(1.25)
    tel.close()


# -- KV push/collect against a real server ------------------------------------


@pytest.fixture()
def kv_server():
    st = RendezvousState(min_nodes=1, settle_s=0.05)
    port = free_port()
    server = start_rendezvous_server(st, port, host="127.0.0.1")
    try:
        yield port
    finally:
        server.shutdown()


def test_push_collect_roundtrip_over_real_kv(kv_server):
    port = kv_server
    aggs = [
        GangAggregator(
            RendezvousClient(f"127.0.0.1:{port}", node_rank=r, timeout_s=10),
            rank=r, world_size=4, attempt="a7", window=20,
        )
        for r in range(4)
    ]
    summaries = four_rank_summaries(slow_rank=1, slow_factor=2.5)
    # non-zero ranks push and get no view back
    for r in (1, 2, 3):
        assert aggs[r].aggregate(summaries[r]) is None
    reg = MetricsRegistry()
    aggs[0].registry = reg
    view = aggs[0].aggregate(summaries[0])
    assert view is not None and view.ranks_reporting == 4
    assert not view.local_only
    assert view.straggler["rank"] == 1 and view.straggler["phase"] == "data"
    assert reg.snapshot()["gang_degraded"] == 0
    # attempt nonce namespaces the keys: a different attempt sees nothing
    other = GangAggregator(aggs[0].client, rank=0, world_size=4, attempt="b0")
    assert other.collect() == []
    assert aggs[0].client.kv_get(gang_kv_key("a7", 1))["rank"] == 1


def test_partial_gang_is_marked_local_only(kv_server):
    port = kv_server
    agg = GangAggregator(
        RendezvousClient(f"127.0.0.1:{port}", node_rank=0, timeout_s=10),
        rank=0, world_size=4, attempt="pp",
    )
    view = agg.aggregate(four_rank_summaries()[0])  # nobody else published
    assert view.ranks_reporting == 1 and view.local_only


def test_heartbeat_ages_ride_the_real_kv(kv_server):
    port = kv_server
    clients = [RendezvousClient(f"127.0.0.1:{port}", node_rank=r, timeout_s=10)
               for r in range(3)]
    for c in clients:
        c.announce(nslots=1)
    agg = GangAggregator(clients[0], rank=0, world_size=3, attempt="hb")
    ages = agg.heartbeat_ages()
    assert sorted(ages) == [0, 1, 2]
    assert all(isinstance(a, float) and 0.0 <= a < 60.0 for a in ages.values())
    # the client caches the latest map for anyone holding only the client
    assert sorted(clients[0].last_heartbeat_ages) == [0, 1, 2]
    # degradation: no client, or one without a heartbeat channel -> {}
    assert GangAggregator(None, rank=0, world_size=3).heartbeat_ages() == {}

    class NoHeartbeatKV:
        pass

    agg2 = GangAggregator(NoHeartbeatKV(), rank=0, world_size=3, attempt="hb")
    assert agg2.heartbeat_ages() == {}


def test_heartbeat_ages_degrade_on_dead_endpoint(monkeypatch):
    monkeypatch.setenv("BAGUA_RPC_RETRIES", "0")
    client = RendezvousClient(f"127.0.0.1:{free_port()}", node_rank=0, timeout_s=1)
    agg = GangAggregator(client, rank=0, world_size=4, attempt="hb")
    assert agg.heartbeat_ages() == {}  # transport failure degrades, never raises


# -- degradation --------------------------------------------------------------


def test_dead_endpoint_degrades_to_local_only(monkeypatch):
    reg = MetricsRegistry()
    # nothing listens on this port; client must fail fast, never raise
    monkeypatch.setenv("BAGUA_RPC_RETRIES", "0")
    client = RendezvousClient(f"127.0.0.1:{free_port()}", node_rank=0, timeout_s=1)
    breaker = CircuitBreaker(failure_threshold=2, cooldown_s=60.0, name="t")
    agg = GangAggregator(client, rank=0, world_size=4, attempt="x",
                         registry=reg, breaker=breaker)
    s = four_rank_summaries()[0]
    for _ in range(3):  # trips the breaker on the way
        view = agg.aggregate(s)
        assert view is not None and view.local_only
        assert view.ranks_reporting == 1 and view.summaries[0].rank == 0
    snap = reg.snapshot()
    assert snap["gang_degraded"] == 1 and snap["gang_local_only"] == 1
    assert snap["gang_push_failures_total"] == 3


def test_no_client_is_a_clean_local_only_view():
    reg = MetricsRegistry()
    agg = GangAggregator(None, rank=0, world_size=2, registry=reg)
    view = agg.aggregate(four_rank_summaries()[0])
    assert view.local_only and view.ranks_reporting == 1
    # deliberate local-only mode is configuration, not failure: no counter
    assert "gang_push_failures_total" not in reg.snapshot()
    assert reg.snapshot()["gang_degraded"] == 1


def test_trainer_gang_window_exports_local_view(group, tmp_path):
    """Trainer(gang_window=N) builds the aggregator lazily and ticks it on
    cadence; single-process (no KV endpoint) runs local-only end to end."""
    import jax
    import numpy as np
    import optax

    from bagua_tpu.algorithms import Algorithm
    from bagua_tpu.models.mlp import init_mlp, mse_loss
    from bagua_tpu.trainer import Trainer

    tel = Telemetry(metrics_jsonl=str(tmp_path / "m.jsonl"))
    rng = np.random.RandomState(0)

    def batches(n):
        for _ in range(n):
            yield (rng.randn(16, 8).astype(np.float32),
                   rng.randn(16, 4).astype(np.float32))

    with Trainer(
        mse_loss, optax.sgd(0.05), Algorithm.init("gradient_allreduce"),
        process_group=group, watchdog_timeout_s=0, telemetry=tel,
        gang_window=3,
    ) as t:
        state = t.init_state(init_mlp(jax.random.PRNGKey(0), [8, 16, 4]))
        assert t.gang is not None and t.gang.window == 3
        t.fit(state, batches(7))
    view = t.gang.last_view
    assert view is not None and view.ranks_reporting == 1
    assert view.summaries[0].phase_ms  # host-overhead attribution rode along
    snap = tel.registry.snapshot()
    assert snap["gang_ranks_reporting"] == 1
    tel.close()


def test_tick_is_window_cadenced(tmp_path):
    tel = Telemetry(metrics_jsonl=str(tmp_path / "m.jsonl"))
    tel.on_step(step=0, wall_s=0.01, n_samples=8, wire_bytes=10)
    agg = GangAggregator(None, rank=0, world_size=1, window=5)
    assert agg.tick(0, tel) is None     # step 0 never aggregates
    assert agg.tick(3, tel) is None     # off-cadence
    view = agg.tick(5, tel)
    assert view is not None and view.summaries[0].step == 5
    tel.close()
