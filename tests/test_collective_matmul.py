"""Collective-matmul ring kernels: bitwise oracle parity + dispatch policy.

The contract under test (``kernels/collective_matmul.py``): the pure-jnp ring
compositions ARE the semantics — ``ag_matmul`` reproduces
``all_gather(x) @ w`` and ``matmul_rs`` reproduces rank ``r``'s row block of
``psum(x @ w)`` — and the Pallas tile GEMM (interpret mode on this CPU tier)
slots in **bitwise**-identically across shard counts and tile shapes,
including non-divisible edge tiles.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bagua_tpu.kernels.collective_matmul import (
    ag_matmul,
    get_collective_matmul,
    matmul_rs,
    matmul_tile_pallas,
)


def ring_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


def run_ag(n, x, w, dot=None, ring="uni"):
    fn = jax.jit(
        jax.shard_map(
            lambda a, b: ag_matmul(a, b, "tp", dot=dot, ring=ring),
            mesh=ring_mesh(n),
            in_specs=(P("tp", None), P(None, None)),
            out_specs=P(None, None),
            check_vma=False,
        )
    )
    return np.asarray(fn(x, w))


def run_rs(n, x, w, dot=None, ring="uni"):
    fn = jax.jit(
        jax.shard_map(
            lambda a, b: matmul_rs(a, b, "tp", dot=dot, ring=ring),
            mesh=ring_mesh(n),
            in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None),
            check_vma=False,
        )
    )
    return np.asarray(fn(x, w))


@pytest.mark.parametrize("n", [2, 4, 8])
def test_ag_matmul_matches_gathered_dot(n):
    """Ring all-gather matmul == plain dot of the gathered input."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n * 6, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 24).astype(np.float32))
    got = run_ag(n, x, w)
    np.testing.assert_allclose(got, np.asarray(x @ w), rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_matmul_rs_matches_psum_dot(n):
    """Ring matmul reduce-scatter == the psum'd product, row-sharded."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(n * 4, n * 8).astype(np.float32))
    w = jnp.asarray(rng.randn(n * 8, 24).astype(np.float32))
    got = run_rs(n, x, w)
    np.testing.assert_allclose(got, np.asarray(x @ w), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize(
    "shape,tiles",
    [
        ((12, 16, 24), (None, None)),  # divisible everywhere
        ((9, 7, 10), (4, 4)),  # edge tiles on M and N, odd K
        ((5, 3, 2), (8, 8)),  # tiles larger than the operands (clamped)
    ],
)
def test_pallas_tile_ring_bitwise_matches_oracle(n, shape, tiles):
    """The acceptance gate: pallas-interpret tile GEMM inside both rings is
    BITWISE-identical to the jnp-dot oracle composition — shard counts x tile
    shapes x non-divisible edge tiles."""
    ms, k, nl = shape
    dot = functools.partial(
        matmul_tile_pallas, interpret=True, tile_m=tiles[0], tile_n=tiles[1]
    )
    rng = np.random.RandomState(2)
    xa = jnp.asarray(rng.randn(n * ms, k).astype(np.float32))
    wa = jnp.asarray(rng.randn(k, nl).astype(np.float32))
    np.testing.assert_array_equal(run_ag(n, xa, wa, dot=dot), run_ag(n, xa, wa))
    xr = jnp.asarray(rng.randn(n * ms, n * 4).astype(np.float32))
    wr = jnp.asarray(rng.randn(n * 4, nl).astype(np.float32))
    np.testing.assert_array_equal(run_rs(n, xr, wr, dot=dot), run_rs(n, xr, wr))


@pytest.mark.parametrize("shape", [(16, 32, 48), (9, 7, 10), (1, 1, 1)])
def test_matmul_tile_pallas_bitwise_matches_dot(shape):
    m, k, nn = shape
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(m, k).astype(np.float32))
    w = jnp.asarray(rng.randn(k, nn).astype(np.float32))
    got = matmul_tile_pallas(x, w, interpret=True, tile_m=4, tile_n=4)
    ref = jnp.dot(x, w, preferred_element_type=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_matmul_tile_pallas_grad_matches_dot():
    """custom_vjp: d/dx and d/dw through the tiled GEMM == jnp.dot grads
    (pallas_call has no transpose rule; the VJP reroutes through the same
    tiled kernel)."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(10, 7).astype(np.float32))
    w = jnp.asarray(rng.randn(7, 12).astype(np.float32))

    def loss(f):
        return lambda a, b: jnp.sum(jnp.sin(f(a, b)))

    g_p = jax.grad(
        loss(functools.partial(matmul_tile_pallas, interpret=True, tile_m=4, tile_n=4)),
        argnums=(0, 1),
    )(x, w)
    g_j = jax.grad(loss(jnp.dot), argnums=(0, 1))(x, w)
    for a, b in zip(g_p, g_j):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_ring_grads_match_oracle_composition():
    """Autodiff through the unrolled rings: pallas-dot grads == jnp-dot
    grads (the rings are plain traced loops, so this is the fused layers'
    backward path)."""
    n = 4
    dot = functools.partial(matmul_tile_pallas, interpret=True, tile_m=4, tile_n=4)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(n * 3, n * 2).astype(np.float32))
    w = jnp.asarray(rng.randn(n * 2, 6).astype(np.float32))

    def grads(d):
        fn = jax.jit(
            jax.shard_map(
                jax.grad(
                    lambda a, b: jnp.sum(matmul_rs(a, b, "tp", dot=d) ** 2),
                    argnums=(0, 1),
                ),
                mesh=ring_mesh(n),
                in_specs=(P(None, "tp"), P("tp", None)),
                out_specs=(P(None, "tp"), P("tp", None)),
                check_vma=False,
            )
        )
        return fn(x, w)

    for a, b in zip(grads(dot), grads(None)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


# -- bidirectional ring ("bidir": two counter-rotating half-arcs) ------------


@pytest.mark.parametrize("n", [2, 3, 4, 8])
def test_ag_matmul_bidir_bitwise_matches_uni(n):
    """The bidirectional all-gather ring only changes the transport — every
    source block is still multiplied whole by the same dot — so its output is
    BITWISE the unidirectional ring's, on arbitrary floats, even/odd ring
    sizes included."""
    rng = np.random.RandomState(10)
    x = jnp.asarray(rng.randn(n * 6, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 24).astype(np.float32))
    np.testing.assert_array_equal(
        run_ag(n, x, w, ring="bidir"), run_ag(n, x, w)
    )


@pytest.mark.parametrize("n", [2, 3, 4, 8])
def test_matmul_rs_bidir_matches_psum_dot(n):
    """The bidirectional reduce-scatter sums the same partial products over
    two arcs — correct to f32 rounding against the psum'd product."""
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(n * 4, n * 8).astype(np.float32))
    w = jnp.asarray(rng.randn(n * 8, 24).astype(np.float32))
    got = run_rs(n, x, w, ring="bidir")
    np.testing.assert_allclose(got, np.asarray(x @ w), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n", [2, 3, 4, 8])
def test_matmul_rs_bidir_bitwise_on_exact_sums(n):
    """Pinning the arc algebra against the unidirectional oracle: on
    integer-valued operands every partial product and serial sum is exact in
    f32, so any source double-counted, dropped, or misrouted by the two-arc
    schedule shows up as a bitwise mismatch."""
    rng = np.random.RandomState(12)
    x = jnp.asarray(rng.randint(-4, 5, size=(n * 4, n * 8)).astype(np.float32))
    w = jnp.asarray(rng.randint(-4, 5, size=(n * 8, 24)).astype(np.float32))
    np.testing.assert_array_equal(
        run_rs(n, x, w, ring="bidir"), run_rs(n, x, w)
    )


def test_bidir_pallas_tile_matches_bidir_oracle():
    """The pluggable tile GEMM composes with the bidirectional ring exactly
    as with the unidirectional one: pallas-interpret dots inside both arcs,
    bitwise vs the jnp-dot bidir composition."""
    n = 4
    dot = functools.partial(matmul_tile_pallas, interpret=True, tile_m=4, tile_n=4)
    rng = np.random.RandomState(13)
    xa = jnp.asarray(rng.randn(n * 5, 7).astype(np.float32))
    wa = jnp.asarray(rng.randn(7, 10).astype(np.float32))
    np.testing.assert_array_equal(
        run_ag(n, xa, wa, dot=dot, ring="bidir"), run_ag(n, xa, wa, ring="bidir")
    )
    xr = jnp.asarray(rng.randn(n * 3, n * 2).astype(np.float32))
    wr = jnp.asarray(rng.randn(n * 2, 6).astype(np.float32))
    np.testing.assert_array_equal(
        run_rs(n, xr, wr, dot=dot, ring="bidir"), run_rs(n, xr, wr, ring="bidir")
    )


def test_bidir_ring_grads_match_uni():
    """Autodiff through the two-arc rings (plain unrolled loops) lands on the
    unidirectional grads to f32 rounding."""
    n = 4
    rng = np.random.RandomState(14)
    x = jnp.asarray(rng.randn(n * 3, n * 2).astype(np.float32))
    w = jnp.asarray(rng.randn(n * 2, 6).astype(np.float32))

    def grads(ring):
        fn = jax.jit(
            jax.shard_map(
                jax.grad(
                    lambda a, b: jnp.sum(matmul_rs(a, b, "tp", ring=ring) ** 2),
                    argnums=(0, 1),
                ),
                mesh=ring_mesh(n),
                in_specs=(P(None, "tp"), P("tp", None)),
                out_specs=(P(None, "tp"), P("tp", None)),
                check_vma=False,
            )
        )
        return fn(x, w)

    for a, b in zip(grads("bidir"), grads("uni")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_unknown_ring_raises():
    with pytest.raises(ValueError, match="ring must be"):
        run_ag(2, jnp.zeros((4, 4)), jnp.zeros((4, 4)), ring="spiral")
    with pytest.raises(ValueError, match="ring must be"):
        run_rs(2, jnp.zeros((4, 4)), jnp.zeros((4, 4)), ring="spiral")


def test_matmul_rs_indivisible_raises():
    n = 4
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(n * 3 + 1, n * 2).astype(np.float32))
    w = jnp.asarray(rng.randn(n * 2, 6).astype(np.float32))
    with pytest.raises(ValueError, match="divide by the ring size"):
        jax.jit(
            jax.shard_map(
                lambda a, b: matmul_rs(a[: n * 3 + 1], b, "tp"),
                mesh=ring_mesh(n),
                in_specs=(P(None, "tp"), P("tp", None)),
                out_specs=P(None, "tp"),
                check_vma=False,
            )
        )(x, w)


def test_multi_axis_ring_raises():
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("a", "b"))
    x = jnp.zeros((4, 8), jnp.float32)
    w = jnp.zeros((8, 4), jnp.float32)
    with pytest.raises(ValueError, match="single mesh axis"):
        jax.jit(
            jax.shard_map(
                lambda a, b: ag_matmul(a, b, ("a", "b")),
                mesh=mesh,
                in_specs=(P(("a", "b"), None), P(None, None)),
                out_specs=P(None, None),
                check_vma=False,
            )
        )(x, w)


def test_single_rank_degenerates_to_dot():
    """n == 1: both primitives are just the local dot (no collectives)."""
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(6, 8).astype(np.float32))
    w = jnp.asarray(rng.randn(8, 4).astype(np.float32))
    np.testing.assert_array_equal(run_ag(1, x, w), np.asarray(x @ w))
    np.testing.assert_array_equal(run_rs(1, x, w), np.asarray(x @ w))


def test_dispatch_cpu_default_is_oracle():
    """No explicit arg, no env, CPU backend -> the bare jnp compositions."""
    ag, rs = get_collective_matmul()
    assert ag is ag_matmul and rs is matmul_rs


def test_dispatch_env_switch(monkeypatch):
    monkeypatch.setenv("BAGUA_PALLAS_COLLECTIVE_MATMUL", "1")
    ag, rs = get_collective_matmul(interpret=True)
    assert isinstance(ag, functools.partial) and ag.func is ag_matmul
    assert isinstance(rs, functools.partial) and rs.func is matmul_rs
    monkeypatch.setenv("BAGUA_PALLAS_COLLECTIVE_MATMUL", "0")
    ag, rs = get_collective_matmul()
    assert ag is ag_matmul and rs is matmul_rs


def test_dispatch_explicit_overrides_env(monkeypatch):
    monkeypatch.setenv("BAGUA_PALLAS_COLLECTIVE_MATMUL", "0")
    ag, rs = get_collective_matmul(use_pallas=True, interpret=True)
    assert isinstance(ag, functools.partial)
    # ... and the pallas-bound pair still bitwise-matches the oracle.
    rng = np.random.RandomState(8)
    n = 2
    x = jnp.asarray(rng.randn(n * 5, 6).astype(np.float32))
    w = jnp.asarray(rng.randn(6, 4).astype(np.float32))
    fn = jax.jit(
        jax.shard_map(
            lambda a, b: ag(a, b, "tp"),
            mesh=ring_mesh(n),
            in_specs=(P("tp", None), P(None, None)),
            out_specs=P(None, None),
            check_vma=False,
        )
    )
    np.testing.assert_array_equal(np.asarray(fn(x, w)), run_ag(n, x, w))


def test_non_f32_falls_back_to_dot():
    """The Pallas tile GEMM only claims f32; other dtypes take jnp.dot."""
    x = jnp.ones((4, 4), jnp.bfloat16)
    w = jnp.ones((4, 4), jnp.bfloat16)
    got = matmul_tile_pallas(x, w, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(got, np.float32), np.asarray(jnp.dot(x, w), np.float32)
    )
