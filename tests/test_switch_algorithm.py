"""Mid-training algorithm/precision switching (``ddp.switch_algorithm`` /
``apply_precision_plan``): bitwise continuation, static-verify gating,
and configuration carry-over through snapshots.

The continuation contract: after a switch at step K, the trajectory is
identical to a *fresh engine of the final configuration* warm-started from
the switch-point state — the value-preserving state remap leaves nothing
behind that the fresh engine wouldn't also have.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.sharded import ZeroAlgorithm

N = 8
LAYERS = [10, 16, 4]
STEPS = 8
SWITCH_AT = 3


def _batches(steps=STEPS, seed=0):
    rng = np.random.RandomState(seed)
    return [
        (jnp.asarray(rng.randn(16, LAYERS[0]), np.float32),
         jnp.asarray(rng.randn(16, LAYERS[-1]), np.float32))
        for _ in range(steps)
    ]


def _make(group, algo, overlap=True, **kwargs):
    return DistributedDataParallel(
        mse_loss, optax.adam(1e-2), algo, process_group=group,
        bucket_size_bytes=1 << 9, overlap=overlap, **kwargs,
    )


def _fork(state):
    """A deep on-device copy: train_step donates its input buffers, so two
    engines continuing from the same state each need their own."""
    return jax.tree.map(jnp.copy, state)


def _params_equal(a_state, b_state):
    for a, b in zip(jax.tree.leaves(jax.tree.map(np.asarray, a_state.params)),
                    jax.tree.leaves(jax.tree.map(np.asarray, b_state.params))):
        np.testing.assert_array_equal(a, b)


def _ranks_synchronized(state):
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, state.params)):
        for r in range(1, N):
            np.testing.assert_array_equal(leaf[0], leaf[r])


# -- gar -> zero -> gar under overlap ----------------------------------------


def test_switch_gar_zero_gar_losses_match_uninterrupted(group):
    """The round trip: gradient_allreduce -> zero -> gradient_allreduce
    mid-training with overlap on.  Each leg's loss curve is identical to an
    uninterrupted run of that leg's configuration warm-started from the
    switch-point state (the fresh-final-engine contract), and the ranks
    stay synchronized throughout."""
    batches = _batches()
    ddp = _make(group, GradientAllReduceAlgorithm())
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    losses = []
    for b in batches[:SWITCH_AT]:
        state, l = ddp.train_step(state, b)
        losses.append(float(np.asarray(l).mean()))

    state = ddp.switch_algorithm(state, "zero", reason="manual")
    assert ddp.impl.algo_name == "zero"
    assert ddp._plan_source == "manual"

    # fresh zero engine warm-started from the switch point: the reference
    # the continuation must be bitwise against
    ref = _make(group, ZeroAlgorithm())
    ref.init(init_mlp(jax.random.PRNGKey(0), LAYERS))  # binds the template
    ref.adopt_plan_payload(ddp.export_plan_payload())
    ref.clear_pending_reshard()
    ref_state = _fork(state)

    for b in batches[SWITCH_AT:6]:
        state, l = ddp.train_step(state, b)
        losses.append(float(np.asarray(l).mean()))
        ref_state, rl = ref.train_step(ref_state, b)
        np.testing.assert_array_equal(np.asarray(l), np.asarray(rl))
    _params_equal(ddp.finalize_pending_updates(state),
                  ref.finalize_pending_updates(ref_state))

    state = ddp.switch_algorithm(state, "gradient_allreduce", reason="manual")
    assert ddp.impl.algo_name == "gradient_allreduce"
    for b in batches[6:]:
        state, l = ddp.train_step(state, b)
        losses.append(float(np.asarray(l).mean()))
    assert len(losses) == STEPS and all(np.isfinite(losses))
    assert int(np.asarray(state.step)[0]) == STEPS
    _ranks_synchronized(state)
    ref.shutdown()
    ddp.shutdown()


def test_switch_to_zero_continuation_bitwise(group):
    """gar -> zero at step K: the continued trajectory is bitwise-identical,
    step by step, to a fresh zero engine fed the same post-switch state —
    the optimizer-state scatter and the pending-shard seeding are
    value-level no-ops."""
    batches = _batches(seed=3)
    ddp = _make(group, GradientAllReduceAlgorithm())
    state = ddp.init(init_mlp(jax.random.PRNGKey(1), LAYERS))
    for b in batches[:SWITCH_AT]:
        state, _ = ddp.train_step(state, b)
    state = ddp.switch_algorithm(state, "zero", reason="manual")

    fresh = _make(group, ZeroAlgorithm())
    fresh.init(init_mlp(jax.random.PRNGKey(1), LAYERS))
    assert fresh.adopt_plan_payload(ddp.export_plan_payload())
    fresh.clear_pending_reshard()
    fresh_state = _fork(state)
    for b in batches[SWITCH_AT:]:
        state, l = ddp.train_step(state, b)
        fresh_state, fl = fresh.train_step(fresh_state, b)
        np.testing.assert_array_equal(np.asarray(l), np.asarray(fl))
    _params_equal(ddp.finalize_pending_updates(state),
                  fresh.finalize_pending_updates(fresh_state))
    fresh.shutdown()
    ddp.shutdown()


def test_switch_from_zero_drains_pending(group):
    """zero -> gar: the deferred all-gather pending at the switch point is
    finalized into the params before the remap, so the gar engine starts
    from exactly the parameters the zero engine would have gathered."""
    batches = _batches(seed=4)
    ddp = _make(group, ZeroAlgorithm())
    state = ddp.init(init_mlp(jax.random.PRNGKey(2), LAYERS))
    for b in batches[:SWITCH_AT]:
        state, _ = ddp.train_step(state, b)
    expect = ddp.finalize_pending_updates(state)
    state = ddp.switch_algorithm(state, "gradient_allreduce", reason="manual")
    _params_equal(state, expect)
    for b in batches[SWITCH_AT:]:
        state, l = ddp.train_step(state, b)
    assert np.isfinite(np.asarray(l)).all()
    _ranks_synchronized(state)
    ddp.shutdown()


# -- precision round trip under overlap --------------------------------------


def test_precision_f32_int8_f32_continuation(group):
    """f32 -> int8 -> f32 mid-training (wire_precision="auto", overlap on):
    after the final switch back, the loss curve is bitwise-identical to a
    fresh auto engine warm-started from the switch-point state with the
    same adopted precision plan."""
    batches = _batches(seed=5)
    ddp = _make(group, GradientAllReduceAlgorithm(wire_precision="auto"), overlap="auto")
    state = ddp.init(init_mlp(jax.random.PRNGKey(3), LAYERS))
    for b in batches[:SWITCH_AT]:
        state, _ = ddp.train_step(state, b)
    nb = ddp.plan.num_buckets
    assert ddp.apply_precision_plan(["int8"] * nb, reason="manual")
    for b in batches[SWITCH_AT:6]:
        state, l = ddp.train_step(state, b)
    assert np.isfinite(np.asarray(l)).all()
    assert ddp.apply_precision_plan(["f32"] * nb, reason="manual")

    fresh = _make(group, GradientAllReduceAlgorithm(wire_precision="auto"), overlap="auto")
    fresh.init(init_mlp(jax.random.PRNGKey(3), LAYERS))
    assert fresh.adopt_plan_payload(ddp.export_plan_payload())
    fresh.clear_pending_reshard()
    fresh_state = _fork(state)
    for b in batches[6:]:
        state, l = ddp.train_step(state, b)
        fresh_state, fl = fresh.train_step(fresh_state, b)
        np.testing.assert_array_equal(np.asarray(l), np.asarray(fl))
    _params_equal(state, fresh_state)
    _ranks_synchronized(state)
    fresh.shutdown()
    ddp.shutdown()


# -- guard rails ---------------------------------------------------------------


def test_switch_algorithm_guards(group):
    ddp = _make(group, GradientAllReduceAlgorithm())
    state = ddp.init(init_mlp(jax.random.PRNGKey(4), LAYERS))
    state, _ = ddp.train_step(state, _batches(1)[0])

    with pytest.raises(ValueError, match="consensus"):
        ddp.switch_algorithm(state, "decentralized", reason="manual")
    with pytest.raises(ValueError, match="supported targets"):
        ddp.switch_algorithm(state, "nonexistent_algo", reason="manual")
    with pytest.raises(ValueError, match="reason"):
        ddp.switch_algorithm(state, "zero", reason="operator")

    # same-algorithm switch is a no-op: same state object, no version bump
    pv = ddp.plan_version
    out = ddp.switch_algorithm(state, "gradient_allreduce", reason="manual")
    assert out is state and ddp.plan_version == pv
    ddp.shutdown()


def test_switch_rejected_by_strict_verifier_rolls_back(group, monkeypatch):
    """A strict-verify rejection surfaces as an exception and leaves the
    engine on its previous configuration — plan version bumped (uniqueness)
    but the algorithm, plan and updater are the pre-switch ones, and the
    caller's state keeps stepping."""
    ddp = _make(group, GradientAllReduceAlgorithm())
    state = ddp.init(init_mlp(jax.random.PRNGKey(5), LAYERS))
    state, _ = ddp.train_step(state, _batches(1)[0])
    old_plan = ddp.plan

    def boom(reason):
        raise RuntimeError("static verifier rejected the switch program")

    monkeypatch.setattr(ddp, "_static_reverify", boom)
    with pytest.raises(RuntimeError, match="rejected"):
        ddp.switch_algorithm(state, "zero", reason="manual")
    monkeypatch.undo()
    assert ddp.impl.algo_name == "gradient_allreduce"
    assert ddp.plan is old_plan
    assert ddp._sharded_updater is None
    state, l = ddp.train_step(state, _batches(2, seed=9)[1])
    assert np.isfinite(np.asarray(l)).all()
    ddp.shutdown()


# -- snapshot / elastic-resume carry-over -------------------------------------


@pytest.fixture()
def _no_persistent_compile_cache():
    """The bitwise-continuation assertion compares two engines compiling the
    same step program in one process.  With the persistent compilation cache
    on, the second engine deserializes the entry the first one just wrote,
    and on the CPU backend that roundtrip is not execution-faithful (observed:
    1-ULP loss drift, and intermittent heap corruption inside dispatch).
    Compile both in-process instead."""
    from jax._src import compilation_cache as _cc

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()  # the used/not-used decision is latched in globals
    yield
    jax.config.update("jax_compilation_cache_dir", prev)
    _cc.reset_cache()


def test_snapshot_resume_carries_autopilot_config(
    group, tmp_path, _no_persistent_compile_cache
):
    """An autopilot-chosen configuration rides the snapshot manifest:
    resume re-adopts the plan, re-applies the adopted precision, reports
    ``plan_source="autopilot"``, and the restored trajectory continues
    bitwise."""
    from bagua_tpu.resilience import AsyncSnapshotter, ElasticResumeCoordinator

    batches = _batches(seed=6)
    ddp = _make(group, GradientAllReduceAlgorithm(wire_precision="auto"), overlap="auto")
    state = ddp.init(init_mlp(jax.random.PRNGKey(6), LAYERS))
    for b in batches[:SWITCH_AT]:
        state, _ = ddp.train_step(state, b)
    ddp.apply_precision_plan(
        ["int8"] * ddp.plan.num_buckets, reason="autopilot:wire_slowdown"
    )
    assert ddp._plan_source == "autopilot"
    payload = ddp.export_plan_payload()
    assert payload["config"]["source"] == "autopilot"
    assert payload["config"]["algorithm"] == "gradient_allreduce"
    assert list(payload["config"]["bucket_precisions"]) == (
        ["int8"] * ddp.plan.num_buckets
    )
    state, _ = ddp.train_step(state, batches[SWITCH_AT])

    snap_dir = str(tmp_path / "autopilot_snap")
    snap = AsyncSnapshotter(
        snap_dir, every=1, world_size=group.size,
        manifest_extra_fn=lambda: {"plan": ddp.export_plan_payload()},
    )
    snap.force_snapshot(state, SWITCH_AT + 1)
    snap.close()

    fresh = _make(group, GradientAllReduceAlgorithm(wire_precision="auto"), overlap="auto")
    init = fresh.init(init_mlp(jax.random.PRNGKey(9), LAYERS))
    res = ElasticResumeCoordinator(snap_dir).resume(fresh, init)
    assert res is not None and res.step == SWITCH_AT + 1
    assert res.plan_source == "autopilot"
    assert fresh._plan_source == "autopilot"
    assert list(fresh.impl.bucket_precisions(fresh.plan)) == (
        ["int8"] * fresh.plan.num_buckets
    )
    # Run the two trajectories sequentially (not interleaved) so only one
    # donating executable is live at a time, then compare the recorded losses.
    expect = []
    for b in batches[SWITCH_AT + 1:]:
        state, l = ddp.train_step(state, b)
        expect.append(np.asarray(l).copy())
    rs = res.state
    got = []
    for b in batches[SWITCH_AT + 1:]:
        rs, rl = fresh.train_step(rs, b)
        got.append(np.asarray(rl).copy())
    for l, rl in zip(expect, got):
        np.testing.assert_array_equal(l, rl)
    _params_equal(state, rs)
    fresh.shutdown()
    ddp.shutdown()


def test_adopt_plan_payload_algorithm_mismatch(group):
    """A snapshot taken under zero cannot be adopted by a gar engine — the
    carried configuration names its algorithm and adoption refuses, telling
    the operator to construct the engine to match."""
    ddp = _make(group, ZeroAlgorithm())
    state = ddp.init(init_mlp(jax.random.PRNGKey(7), LAYERS))
    state, _ = ddp.train_step(state, _batches(1)[0])
    payload = ddp.export_plan_payload()
    assert payload["config"]["algorithm"] == "zero"

    other = _make(group, GradientAllReduceAlgorithm())
    with pytest.raises(ValueError, match="algorithm"):
        other.adopt_plan_payload(payload)
    other.shutdown()
    ddp.shutdown()


def test_reapplied_identical_precision_plan_is_noop(group):
    """Satellite pin: re-applying the precision plan the engine is already
    on returns False and bumps nothing — resume's re-apply path must not
    recompile a gang that is already in the adopted configuration."""
    ddp = _make(group, GradientAllReduceAlgorithm(wire_precision="auto"), overlap="auto")
    state = ddp.init(init_mlp(jax.random.PRNGKey(8), LAYERS))
    state, _ = ddp.train_step(state, _batches(1)[0])
    nb = ddp.plan.num_buckets
    assert ddp.apply_precision_plan(["int8"] * nb, reason="manual")
    pv = ddp.plan_version
    assert not ddp.apply_precision_plan(["int8"] * nb, reason="manual")
    assert ddp.plan_version == pv
    ddp.shutdown()
