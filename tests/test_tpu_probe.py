"""The relay classifier in ci/tpu_probe.py is load-bearing: the bench
preflight, the session script, and the watcher all branch on it.  Pin its
verdicts against live sockets exhibiting each behavior."""

import pytest
import importlib.util
import os
import socket
import threading

from helpers import free_port

# Load ci/tpu_probe.py by path — a sys.path.insert of ci/ would shadow
# same-named modules for the rest of the pytest session.
_spec = importlib.util.spec_from_file_location(
    "tpu_probe",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "ci", "tpu_probe.py"),
)
tpu_probe = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(tpu_probe)


def _serve(handler):
    """One-connection TCP server on an ephemeral port; returns the port."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]

    def run():
        try:
            conn, _ = srv.accept()
            handler(conn)
        except OSError:
            pass
        finally:
            srv.close()

    threading.Thread(target=run, daemon=True).start()
    return port


def test_accepted_then_dropped_is_dead_upstream_signature():
    port = _serve(lambda conn: conn.close())  # accept, drop immediately
    assert tpu_probe.relay_diagnosis("127.0.0.1", port) == "accepted-then-dropped"


def test_accepted_held_is_healthy_signature():
    import time

    def hold(conn):
        time.sleep(6.0)
        conn.close()

    port = _serve(hold)
    assert tpu_probe.relay_diagnosis("127.0.0.1", port, hold_s=1.0) == "accepted-held"


def test_server_that_speaks_is_held():
    def greet(conn):
        conn.sendall(b"hello")
        import time

        time.sleep(3.0)
        conn.close()

    port = _serve(greet)
    assert tpu_probe.relay_diagnosis("127.0.0.1", port, hold_s=1.0) == "accepted-held"


def test_refused_when_nothing_listens():
    port = free_port()  # bound then released: next connect is refused
    assert tpu_probe.relay_diagnosis("127.0.0.1", port) in ("refused", "no-listener")


def test_failure_summary_names_phase_and_relay():
    result = {
        "ok": False,
        "attempts": [{"ok": False, "last_phase": "devices +0.0s", "elapsed": 50.0}],
        "relay": "accepted-then-dropped",
        "last_phase": "devices +0.0s",
    }
    s = tpu_probe.failure_summary(result)
    assert "devices" in s and "upstream tunnel dead" in s and "1x" in s


@pytest.mark.slow
def test_probe_once_caps_a_hung_child_and_names_the_phase(monkeypatch):
    """A child whose init hangs forever must come back within the cap with
    the stuck phase named — the exact dead-tunnel behavior.  The child body
    is swapped for one that prints its phases then blocks (ignoring
    SIGINT, like the PJRT client's retry loop), so this also exercises the
    SIGINT -> SIGKILL escalation."""
    import time

    monkeypatch.setattr(tpu_probe, "_CHILD", r"""
import signal, time
signal.signal(signal.SIGINT, signal.SIG_IGN)
print("phase:import +0.0s", flush=True)
print("phase:devices +0.1s", flush=True)
time.sleep(600)
""")
    t0 = time.monotonic()
    r = tpu_probe.probe_once(cap_s=3.0)
    elapsed = time.monotonic() - t0
    assert r["ok"] is False
    assert r["last_phase"].startswith("devices"), r
    # cap (3s) + SIGINT grace (10s) + SIGKILL communicate (5s) + slack
    assert elapsed < 25.0, elapsed


def test_probe_once_reports_success():
    """A child that completes all phases yields ok=True."""
    import unittest.mock as mock

    with mock.patch.object(tpu_probe, "_CHILD", r"""
print("phase:import +0.0s", flush=True)
print("phase:matmul-ok +0.1s", flush=True)
"""):
        r = tpu_probe.probe_once(cap_s=30.0)
    assert r["ok"] is True and r["last_phase"].startswith("matmul-ok")
