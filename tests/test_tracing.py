"""Distributed tracing: span model, W3C context propagation, sampling,
the Telemetry wiring, retry attribution, the Perfetto exporter — and the
acceptance criterion that turning it all on is bitwise-inert.

The subsystem's contracts, each pinned here:

* **span model** — ``bagua.span.v1`` dicts validate, parent/child links
  carry one trace_id, and the W3C ``traceparent`` header round-trips
  (malformed / all-zero / version-ff headers degrade to None, never
  raise);
* **context** — the thread-local stack parents RPC client spans under the
  step's active phase span; ``client_span`` is a verbatim no-op when no
  tracer is installed;
* **attribution** — a 429 raised inside a client span lands as
  ``status: 429`` plus a ``backpressure`` annotation with the server's
  Retry-After hint; ``retry_call`` backoffs annotate the in-flight span
  and feed the ``rpc_retry_total`` / ``rpc_backoff_s_total`` counters and
  the schema-validated ``rpc_retry`` event;
* **bitwise-inert** — BAGUA_TRACING on vs off trains *bit-identical*
  params + optimizer state, overlap on, for gradient_allreduce AND zero
  (every hook is host-side: phase transitions, RPC transports, step
  boundaries — never the traced computation).
"""

import hashlib
import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.observability import (
    SPAN_SCHEMA,
    Span,
    Telemetry,
    Tracer,
    client_span,
    format_traceparent,
    get_global_tracer,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    set_global_tracer,
    validate_metrics_file,
    validate_span,
)
from bagua_tpu.resilience.retry import (
    BackpressureError,
    RetryPolicy,
    get_retry_observer,
    retry_call,
)

LAYERS = [12, 16, 16, 4]


@pytest.fixture(autouse=True)
def _clean_globals():
    """Every test starts and ends with no ambient tracer / observer —
    these are process-wide and must never leak across tests."""
    set_global_tracer(None)
    yield
    set_global_tracer(None)
    from bagua_tpu.resilience.retry import set_retry_observer

    set_retry_observer(None)


# -- ids + traceparent --------------------------------------------------------


def test_ids_and_traceparent_roundtrip():
    tid, sid = new_trace_id(), new_span_id()
    assert len(tid) == 32 and len(sid) == 16 and tid != new_trace_id()
    header = format_traceparent(tid, sid)
    assert header == f"00-{tid}-{sid}-01"
    ctx = parse_traceparent(header)
    assert ctx == {"trace_id": tid, "span_id": sid, "sampled": True}
    assert parse_traceparent(format_traceparent(tid, sid, sampled=False))[
        "sampled"
    ] is False


@pytest.mark.parametrize("header", [
    None,
    "",
    "not-a-traceparent",
    "00-zz-zz-01",                                    # non-hex
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",        # all-zero trace id
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",        # all-zero span id
    "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",        # forbidden version
    "00-" + "1" * 31 + "-" + "2" * 16 + "-01",        # short trace id
    "00-" + "1" * 32 + "-" + "2" * 16,                # missing flags
])
def test_parse_traceparent_rejects_garbage(header):
    assert parse_traceparent(header) is None  # degrade, never raise


def test_span_serialization_validates():
    root = Span("train_step", attrs={"step": 3})
    child = Span("phase:dispatch", trace_id=root.trace_id,
                 parent_id=root.span_id)
    child.annotate("retry:backpressure", attempt=1, retry_after_s=0.5)
    child.dur_ms = 1.25
    for span in (root, child):
        d = span.to_dict()
        assert d["schema"] == SPAN_SCHEMA
        assert validate_span(d) == []
    d = child.to_dict()
    assert d["parent_id"] == root.span_id
    assert d["trace_id"] == root.trace_id
    assert d["annotations"][0]["name"] == "retry:backpressure"
    assert parse_traceparent(child.traceparent)["span_id"] == child.span_id
    # the validator actually rejects
    assert validate_span({"trace_id": "nope"})
    assert validate_span({**root.to_dict(), "kind": "weird"})
    assert validate_span({**root.to_dict(), "ts": "yesterday"})


# -- tracer context + sampling ------------------------------------------------


def test_step_phases_and_rpc_spans_share_one_trace():
    tracer = Tracer(sample_every=1, service="trainer", rank=0)
    root = tracer.begin_step(7, variant="full")
    tracer.on_phase("dispatch")
    with tracer.span("rpc /autotune/report", kind="client") as sp:
        assert tracer.current_span() is sp
    tracer.on_phase("wait")
    tracer.end_step(wall_ms=12.5)
    spans = {s["name"]: s for s in tracer.finished_spans()}
    assert set(spans) == {
        "train_step", "phase:dispatch", "rpc /autotune/report", "phase:wait",
    }
    assert spans["train_step"]["span_id"] == root.span_id
    assert all(s["trace_id"] == root.trace_id for s in spans.values())
    assert spans["phase:dispatch"]["parent_id"] == root.span_id
    assert spans["rpc /autotune/report"]["parent_id"] == (
        spans["phase:dispatch"]["span_id"]
    )
    assert spans["train_step"]["attrs"]["wall_ms"] == 12.5
    assert all(validate_span(s) == [] for s in spans.values())


def test_step_sampling_drops_whole_steps():
    tracer = Tracer(sample_every=2)
    for step in range(4):
        assert (tracer.begin_step(step) is not None) == (step % 2 == 0)
        tracer.on_phase("dispatch")
        tracer.end_step()
    names = [s["name"] for s in tracer.finished_spans()]
    # steps 1 and 3 left nothing at all — not even phase children
    assert names.count("train_step") == 2
    assert names.count("phase:dispatch") == 2
    assert tracer.n_dropped_unsampled == 2


def test_tracer_context_is_thread_local():
    tracer = Tracer()
    tracer.begin_step(0)
    seen = {}

    def worker():
        seen["current"] = tracer.current_span()
        with tracer.span("bg write") as sp:
            seen["own"] = tracer.current_span() is sp

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    tracer.end_step()
    # the background thread never saw the fit loop's context, and its own
    # span is a fresh root
    assert seen["current"] is None and seen["own"]
    bg = next(s for s in tracer.finished_spans() if s["name"] == "bg write")
    assert bg.get("parent_id") is None


def test_span_jsonl_file_is_line_valid(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    tracer = Tracer(path=path)
    tracer.begin_step(0)
    tracer.on_phase("dispatch")
    tracer.end_step()
    tracer.close()
    with open(path) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    assert len(lines) == 2
    assert all(validate_span(s) == [] for s in lines)


# -- client_span + 429 attribution --------------------------------------------


def test_client_span_is_noop_without_tracer():
    assert get_global_tracer() is None
    with client_span("rpc /x", component="fleet") as (sp, headers):
        assert sp is None and headers == {}


def test_client_span_injects_context_and_attributes_429():
    tracer = Tracer()
    set_global_tracer(tracer)
    tracer.begin_step(0)
    with client_span("rpc /ok", component="fleet", endpoint="/ok") as (sp, h):
        ctx = parse_traceparent(h["traceparent"])
        assert ctx["trace_id"] == sp.trace_id
        assert ctx["span_id"] == sp.span_id
    with pytest.raises(BackpressureError):
        with client_span("rpc /shed", component="fleet") as (sp, _h):
            raise BackpressureError("shed", retry_after_s=1.5)
    tracer.end_step()
    spans = {s["name"]: s for s in tracer.finished_spans()}
    assert spans["rpc /ok"]["kind"] == "client"
    assert spans["rpc /ok"]["attrs"]["component"] == "fleet"
    shed = spans["rpc /shed"]
    assert shed["attrs"]["status"] == 429
    (ann,) = shed["annotations"]
    assert ann["name"] == "backpressure" and ann["retry_after_s"] == 1.5
    # a non-429 failure is tagged, not mistaken for backpressure
    with pytest.raises(ValueError):
        with client_span("rpc /boom", component="fleet"):
            raise ValueError("nope")
    boom = next(s for s in tracer.finished_spans() if s["name"] == "rpc /boom")
    assert boom["attrs"]["error"] == "ValueError"
    assert not boom.get("annotations")


# -- telemetry wiring + retry integration -------------------------------------


def test_env_gate_builds_and_tears_down_the_tracer(monkeypatch, tmp_path):
    monkeypatch.setenv("BAGUA_TRACING", "1")
    monkeypatch.setenv("BAGUA_TRACE_SAMPLE", "3")
    monkeypatch.setenv("BAGUA_TRACE_PATH", str(tmp_path / "spans.jsonl"))
    tel = Telemetry()
    assert tel.tracer is not None and tel.tracer.sample_every == 3
    assert get_global_tracer() is tel.tracer
    assert get_retry_observer() == tel.on_rpc_retry
    tel.close()
    assert get_global_tracer() is None
    assert get_retry_observer() is None
    # and default-off: no env, no tracer, no global
    monkeypatch.delenv("BAGUA_TRACING")
    tel2 = Telemetry()
    assert tel2.tracer is None and get_global_tracer() is None
    tel2.close()


def test_retry_call_feeds_counters_events_and_span_annotations(tmp_path):
    events_path = str(tmp_path / "metrics.jsonl")
    tel = Telemetry(metrics_jsonl=events_path, tracing=Tracer())
    state = {"n": 0}

    def shedding():
        state["n"] += 1
        if state["n"] <= 2:
            raise BackpressureError("shed", retry_after_s=0.01)
        return "ok"

    tel.tracer.begin_step(0)
    tel.enter_phase("dispatch")
    assert retry_call(
        shedding, policy=RetryPolicy(retries=3, base_s=0.001, seed=0),
        sleep=lambda s: None, label="/rdzv/heartbeat",
    ) == "ok"
    tel.tracer.end_step()
    reg = tel.registry.snapshot()
    assert reg["rpc_retry_total"] == 2
    assert reg["rpc_backpressure_total"] == 2
    assert reg["rpc_backoff_s_total"] >= 0.02
    tel.close()
    assert validate_metrics_file(events_path) == []
    events = [json.loads(line) for line in open(events_path)]
    retries = [e for e in events if e["event"] == "rpc_retry"]
    assert len(retries) == 2
    for ev in retries:
        assert ev["endpoint"] == "/rdzv/heartbeat"
        assert ev["reason"] == "backpressure"
        assert ev["retry_after_s"] == 0.01
        assert len(ev["trace_id"]) == 32  # joins the timeline
    # the in-flight phase span carries the backoff annotations too
    phase = next(s for s in tel.tracer.finished_spans()
                 if s["name"] == "phase:dispatch")
    anns = [a for a in phase["annotations"]
            if a["name"] == "retry:backpressure"]
    assert [a["attempt"] for a in anns] == [0, 1]
    assert all(a["retry_after_s"] == 0.01 for a in anns)


def test_snapshot_and_events_carry_trace_context(tmp_path):
    events_path = str(tmp_path / "metrics.jsonl")
    tel = Telemetry(metrics_jsonl=events_path, tracing=Tracer())
    tel.on_step_start(4, variant="full")
    snap = tel.snapshot()
    assert snap["trace"]["trace_id"] == tel.tracer.current_span().trace_id
    tel.on_health_alert(step=4, kind="loss_spike", value=9.0, threshold=3.0)
    tel.close()
    assert validate_metrics_file(events_path) == []
    (alert,) = [json.loads(line) for line in open(events_path)
                if '"health_alert"' in line]
    assert alert["trace_id"] == snap["trace"]["trace_id"]


# -- exporter -----------------------------------------------------------------


def test_chrome_trace_export_validates_and_links():
    import sys as _sys
    _sys.path.insert(0, "ci")
    try:
        from export_timeline import build_chrome_trace, validate_chrome_trace
    finally:
        _sys.path.pop(0)
    tracer = Tracer()
    tracer.begin_step(0)
    tracer.on_phase("dispatch")
    with tracer.span("rpc /rdzv/kv/x", kind="client") as sp:
        sp.annotate("retry:backpressure", attempt=0, retry_after_s=0.2)
    tracer.end_step()
    trace = build_chrome_trace(tracer.finished_spans())
    assert validate_chrome_trace(trace) == []
    evs = trace["traceEvents"]
    names = {e["name"] for e in evs if e["ph"] == "X"}
    assert {"train_step", "phase:dispatch", "rpc /rdzv/kv/x"} <= names
    # 2 parent->child links -> 2 matched flow pairs, annotation -> instant
    assert sum(1 for e in evs if e["ph"] == "s") == 2
    assert sum(1 for e in evs if e["ph"] == "f") == 2
    assert any(e["ph"] == "i" and e["name"] == "retry:backpressure"
               for e in evs)
    # the validator rejects a dangling flow arrow
    broken = {"traceEvents": [e for e in evs if e["ph"] != "f"]}
    assert any("unmatched flow" in p for p in validate_chrome_trace(broken))


def test_chrome_trace_renders_plan_decision_instants(tmp_path):
    """Autopilot ``plan_decision`` rows (and the ``perf_regression``
    incidents they cite) load from a metrics JSONL and render as Perfetto
    annotation instants — decision kind in the name, verdict + citing
    trace_id in args — joinable on the shared trace_id."""
    import sys as _sys
    _sys.path.insert(0, "ci")
    try:
        from export_timeline import (
            build_chrome_trace, load_metrics_incidents, validate_chrome_trace,
        )
    finally:
        _sys.path.pop(0)
    path = str(tmp_path / "metrics.jsonl")
    tel = Telemetry(metrics_jsonl=path, flight=None)
    tel.jsonl.emit({
        "event": "perf_regression", "ts": 10.0, "step": 40,
        "stream": "step_wall", "dominant": "wire_slowdown",
        "components": {"wire_slowdown": 8.0}, "residual_ms": 8.0,
        "expected_ms": 10.0, "measured_ms": 18.0, "plan_version": 3,
        "trace_id": "lane-w3-s40",
    })
    tel.on_plan_decision(
        step=43, decision="demote_precision", reason="autopilot:wire_slowdown",
        trace_id="lane-w3-s40", plan_version=3,
        from_config={"algorithm": "gradient_allreduce", "precision": "f32"},
        to_config={"algorithm": "gradient_allreduce", "precision": "int8"},
        verdict="canary", modeled={"stay_ms": 18.0, "chosen_ms": 12.0},
    )
    tel.close()
    events = load_metrics_incidents(path)
    assert [e["event"] for e in events] == ["perf_regression", "plan_decision"]
    trace = build_chrome_trace([], events)
    assert validate_chrome_trace(trace) == []
    instants = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    named = {e["name"]: e for e in instants}
    assert set(named) == {
        "perf_regression:wire_slowdown", "plan_decision:demote_precision"}
    dec = named["plan_decision:demote_precision"]
    assert dec["cat"] == "decision"
    assert dec["args"]["verdict"] == "canary"
    assert dec["args"]["to_config"]["precision"] == "int8"
    # the join key: the decision cites the incident's trace_id
    inc = named["perf_regression:wire_slowdown"]
    assert dec["args"]["trace_id"] == inc["args"]["trace_id"]


# -- the acceptance criterion: bitwise inert ----------------------------------


def make_batch(seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(32, LAYERS[0]).astype(np.float32))
    y = jnp.asarray(rng.randn(32, LAYERS[-1]).astype(np.float32))
    return x, y


def run_steps(group, algo_name, tracer, steps=3):
    tel = Telemetry(tracing=tracer, flight=None)
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.1, momentum=0.9), build_algorithm(algo_name),
        process_group=group, bucket_size_bytes=1 << 9, overlap=True,
        telemetry=tel,
    )
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    batch = make_batch()
    losses = None
    for _ in range(steps):
        state, losses = ddp.train_step(state, batch)
    jax.block_until_ready(losses)
    ddp.shutdown()
    tel.close()
    return state


def state_sha(state):
    h = hashlib.sha256()
    for leaf in jax.tree.leaves((state.params, state.opt_state)):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


@pytest.mark.parametrize("algo_name", ["gradient_allreduce", "zero"])
def test_tracing_is_bitwise_inert(group, algo_name):
    """The acceptance criterion: tracing on (sampling every step, every
    phase instrumented) vs off trains bit-identical params + optimizer
    state, overlap on, for the all-reduce AND the sharded (zero) paths."""
    state_off = run_steps(group, algo_name, None, steps=3)
    tracer = Tracer(sample_every=1)
    state_on = run_steps(group, algo_name, tracer, steps=3)
    names = [s["name"] for s in tracer.finished_spans()]
    assert names.count("train_step") == 3  # it actually traced
    assert any(n.startswith("phase:") for n in names)
    assert state_sha(state_on) == state_sha(state_off)
