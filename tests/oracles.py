"""Shared numpy oracles for MinMaxUInt8 compression (reference semantics:
``tests/internal/compressor.py:4-33`` / ``bagua_kernels.cu:404-480``)."""

import numpy as np

EPS = 1e-7


def oracle_compress(chunks: np.ndarray):
    mn = chunks.min(axis=1, keepdims=True)
    mx = chunks.max(axis=1, keepdims=True)
    scale = 255.0 / (mx - mn + EPS)
    upper = np.rint(mx * scale)
    lower = upper - 255.0
    q = (np.minimum(np.rint(chunks * scale), upper) - lower).astype(np.uint8)
    return q, np.concatenate([mn, mx], axis=1)


def oracle_decompress(q: np.ndarray, minmax: np.ndarray):
    mn, mx = minmax[:, 0:1], minmax[:, 1:2]
    scale = 255.0 / (mx - mn + EPS)
    lower = np.rint(mx * scale) - 255.0
    return (q.astype(np.float32) + lower) / scale


def oracle_compressed_allreduce(per_rank: np.ndarray, average: bool = True):
    """Numpy simulation of compress→a2a→decompress→reduce→compress→allgather."""
    n, numel = per_rank.shape
    chunk = numel // n
    qs, mms = [], []
    for r in range(n):
        q, mm = oracle_compress(per_rank[r].reshape(n, chunk))
        qs.append(q)
        mms.append(mm)
    reduced = []
    for r in range(n):
        acc = np.zeros((chunk,), np.float32)
        for s in range(n):
            acc += oracle_decompress(qs[s][r : r + 1], mms[s][r : r + 1])[0]
        if average:
            acc /= n
        reduced.append(acc)
    out = []
    for r in range(n):
        q, mm = oracle_compress(reduced[r][None])
        out.append(oracle_decompress(q, mm)[0])
    return np.concatenate(out)
