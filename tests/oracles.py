"""Shared numpy oracles for MinMaxUInt8 compression (reference semantics:
``tests/internal/compressor.py:4-33`` / ``bagua_kernels.cu:404-480``)."""

import numpy as np

EPS = 1e-7
# Degenerate-range guard terms — mirror bagua_tpu.kernels.minmax_uint8.
REL_EPS = 1e-35
F32_MAX = 3.4028235e38


def oracle_scale(mn, mx, levels=255.0):
    """Bounded-denominator scale (mirrors ``minmax_uint8._safe_scale``):
    the relative term keeps ``rint(mx * scale)`` representable for
    near-constant chunks at extreme magnitude, the clamp keeps scale > 0
    when the range itself overflows f32; both vanish in f32 rounding for
    any sane chunk."""
    amax = np.maximum(np.abs(mn), np.abs(mx))
    return np.float32(levels) / np.minimum(
        mx - mn + np.float32(EPS) + np.float32(REL_EPS) * amax,
        np.float32(F32_MAX),
    )


def oracle_compress(chunks: np.ndarray):
    mn = chunks.min(axis=1, keepdims=True)
    mx = chunks.max(axis=1, keepdims=True)
    scale = oracle_scale(mn, mx)
    upper = np.rint(mx * scale)
    lower = upper - 255.0
    q = np.minimum(np.rint(chunks * scale), upper) - lower
    return q.astype(np.uint8), np.concatenate([mn, mx], axis=1)


def oracle_decompress(q: np.ndarray, minmax: np.ndarray):
    mn, mx = minmax[:, 0:1], minmax[:, 1:2]
    scale = oracle_scale(mn, mx)
    lower = np.rint(mx * scale) - 255.0
    return ((q.astype(np.float32) + lower) / scale).astype(np.float32)


def oracle_compressed_allreduce(per_rank: np.ndarray, average: bool = True):
    """Numpy simulation of compress→a2a→decompress→reduce→compress→allgather."""
    n, numel = per_rank.shape
    chunk = numel // n
    qs, mms = [], []
    for r in range(n):
        q, mm = oracle_compress(per_rank[r].reshape(n, chunk))
        qs.append(q)
        mms.append(mm)
    reduced = []
    for r in range(n):
        acc = np.zeros((chunk,), np.float32)
        for s in range(n):
            acc += oracle_decompress(qs[s][r : r + 1], mms[s][r : r + 1])[0]
        if average:
            acc /= n
        reduced.append(acc)
    out = []
    for r in range(n):
        q, mm = oracle_compress(reduced[r][None])
        out.append(oracle_decompress(q, mm)[0])
    return np.concatenate(out)
