"""Determinism: identical seeds => bitwise-identical final losses/weights.

The reference's CI gates on EXACT final loss equality per algorithm
(``benchmark_master.sh:81-83``); this is the single-host analog run on the
simulated mesh for every algorithm.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms import (
    WALL_CLOCK_ALGORITHMS,
    GlobalAlgorithmRegistry,
    build_algorithm,
)
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss


@pytest.mark.parametrize("name", sorted(GlobalAlgorithmRegistry.keys()))
def test_training_is_deterministic(group, name):
    if name in WALL_CLOCK_ALGORITHMS:
        pytest.skip("wall-clock-driven schedule: not bitwise-deterministic by design")

    def run():
        params = init_mlp(jax.random.PRNGKey(5), [12, 16, 4])
        algo = build_algorithm(name, lr=1e-3, qadam_warmup_steps=3)
        opt = None if name == "qadam" else optax.sgd(0.05)
        ddp = DistributedDataParallel(mse_loss, opt, algo, process_group=group)
        state = ddp.init(params)
        rng = np.random.RandomState(9)
        for _ in range(6):
            batch = (
                jnp.asarray(rng.randn(16, 12), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            )
            state, losses = ddp.train_step(state, batch)
        return np.asarray(losses), jax.tree.map(np.asarray, state.params)

    l1, p1 = run()
    l2, p2 = run()
    np.testing.assert_array_equal(l1, l2)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(a, b)
