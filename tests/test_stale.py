"""Stale-sync (bounded-staleness gradient exchange) vs a pure-numpy oracle.

The relaxation contract, pinned from four sides:

* ``τ=0`` is OFF: bitwise-identical to :class:`GradientAllReduceAlgorithm`
  with overlap on — the lane's bitwise gate, repeated at tier-1 scale.
* The replay algebra (stale payload + error-feedback residual) matches a
  plain-numpy reimplementation on stacked per-rank buckets, the same
  oracle style as ``test_decentralized.py``.
* The staleness bound is enforced by construction: a rank held under a
  directive replays at most τ consecutive rounds, then is *forced* back
  to a fresh full contribution — counters never exceed τ.
* The two host-side knobs do exactly what they claim: the directive flip
  is recompile-free (data, not code), the τ switch is the single-recompile
  arc, and ``reset_staleness_state`` re-primes counters/residual.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.algorithms.stale import StaleSyncAlgorithm
from bagua_tpu.bucket import BucketPlan
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

N = 8
N_STEPS = 6
LR = 0.05
DIM_IN, DIM_OUT = 10, 3
TAU = 2
STALE_RANK = 2


def make_problem(seed=0):
    params = init_mlp(jax.random.PRNGKey(seed), [DIM_IN, 8, DIM_OUT])
    rng = np.random.RandomState(seed)
    xs = rng.randn(N_STEPS, N * 4, DIM_IN).astype(np.float32)
    ys = rng.randn(N_STEPS, N * 4, DIM_OUT).astype(np.float32)
    return params, xs, ys


def make_ddp(group, tau=0, overlap=False, lr=LR, momentum=None, **kw):
    opt = optax.sgd(lr, momentum=momentum) if momentum else optax.sgd(lr)
    return DistributedDataParallel(
        mse_loss,
        opt,
        StaleSyncAlgorithm(staleness_tau=tau),
        process_group=group,
        overlap=overlap,
        **kw,
    )


def counters(state):
    return np.asarray(state.algo_state["staleness"])


def flat_grad_fn(plan):
    def fn(flat, x, y):
        params = plan.debucketize([flat])
        g = jax.grad(mse_loss)(params, (x, y))
        return plan.bucketize(g)[0]

    return jax.jit(fn)


def test_stale_tau0_bitwise_matches_gradient_allreduce(group):
    """The relaxation must be genuinely OFF at τ=0 — same compiled family as
    the synchronous engine, overlap on, params bitwise after 6 steps."""
    params, xs, ys = make_problem(seed=11)

    def run(algo):
        ddp = DistributedDataParallel(
            mse_loss, optax.sgd(0.01, momentum=0.9), algo,
            process_group=group, bucket_size_bytes=1 << 12, overlap="auto",
        )
        state = ddp.init(params)
        for i in range(N_STEPS):
            state, _ = ddp.train_step(
                state, (jnp.asarray(xs[i]), jnp.asarray(ys[i]))
            )
        assert ddp.overlap_enabled
        return [np.asarray(l) for l in jax.tree.leaves(state.params)]

    got = run(StaleSyncAlgorithm(staleness_tau=0))
    ref = run(build_algorithm("gradient_allreduce"))
    assert len(got) == len(ref)
    for a, b in zip(got, ref):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)


def test_stale_replay_matches_oracle(group):
    """τ=2 with rank 2 under a directive from step 0: the engine must match
    the replay algebra reimplemented in numpy —

        contrib = stale            while directive AND counter < τ
                = g + residual     otherwise (and the residual telescopes)

    including the init-zero replay payload on the very first stale round."""
    params, xs, ys = make_problem(seed=1)
    ddp = make_ddp(group, tau=TAU, bucket_size_bytes=1 << 62)
    state = ddp.init(params)
    state = ddp.apply_degradation_directive(state, (STALE_RANK,))
    for i in range(N_STEPS):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))

    # ---- numpy oracle on the flat bucket ----
    plan = BucketPlan.from_tree(params, 1 << 62, align_elems=N)
    grad = flat_grad_fn(plan)
    w = np.asarray(plan.bucketize(params)[0]).astype(np.float64)
    dim = w.shape[0]
    stale = np.zeros((N, dim))
    resid = np.zeros((N, dim))
    cnt = np.zeros(N, np.int64)
    for step in range(N_STEPS):
        x = xs[step].reshape(N, -1, DIM_IN)
        y = ys[step].reshape(N, -1, DIM_OUT)
        g = np.stack([
            np.asarray(grad(jnp.asarray(w.astype(np.float32)), x[r], y[r]))
            for r in range(N)
        ]).astype(np.float64)
        contrib = np.empty_like(g)
        for r in range(N):
            use = r == STALE_RANK and cnt[r] < TAU
            contrib[r] = stale[r] if use else g[r] + resid[r]
            # replay payload = last raw fresh gradient, held across replays
            if not use:
                stale[r] = g[r]
            cnt[r] = cnt[r] + 1 if use else 0
        resid = resid + g - contrib
        w = w - LR * contrib.mean(axis=0)

    got = np.asarray(ddp.plan.bucketize(ddp.params_unstacked(state, 0))[0])
    np.testing.assert_allclose(got, w, rtol=2e-4, atol=1e-5)
    # the counter walked the oracle's cycle too
    assert counters(state)[STALE_RANK] == cnt[STALE_RANK]


def test_staleness_bound_forces_fresh_exchange(group):
    """A rank held under a directive forever still exchanges every τ+1
    rounds: counters cycle 1, 2, 0, 1, 2, 0 … and never exceed τ; ranks
    without a directive never move off 0."""
    params, xs, ys = make_problem(seed=2)
    ddp = make_ddp(group, tau=TAU, bucket_size_bytes=1 << 62)
    state = ddp.init(params)
    state = ddp.apply_degradation_directive(state, (STALE_RANK,))
    seen = []
    for step in range(7):
        i = step % N_STEPS
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
        c = counters(state)
        seen.append(int(c[STALE_RANK]))
        assert c[STALE_RANK] <= TAU
        healthy = np.delete(c, STALE_RANK)
        assert (healthy == 0).all(), c
    # replay for τ rounds, then the forced fresh round resets the counter
    assert seen == [1, 2, 0, 1, 2, 0, 1]


def test_directive_flip_is_recompile_free(group):
    """The directive is a stacked int32 leaf — data, not code: flipping it
    must reuse the already-compiled step function verbatim."""
    params, xs, ys = make_problem(seed=3)
    ddp = make_ddp(group, tau=TAU)
    state = ddp.init(params)
    state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
    compiled_before = dict(ddp._step_fns)
    assert compiled_before, "step did not compile"
    state = ddp.apply_degradation_directive(state, (STALE_RANK,))
    state, _ = ddp.train_step(state, (jnp.asarray(xs[1]), jnp.asarray(ys[1])))
    state = ddp.apply_degradation_directive(state, ())
    state, _ = ddp.train_step(state, (jnp.asarray(xs[2]), jnp.asarray(ys[2])))
    for variant, fn in compiled_before.items():
        assert ddp._step_fns[variant] is fn, "directive flip re-traced the step"


def test_directive_validates_ranks_and_knob(group):
    params, xs, ys = make_problem(seed=4)
    ddp = make_ddp(group, tau=TAU)
    state = ddp.init(params)
    with pytest.raises(ValueError, match="out of range"):
        ddp.apply_degradation_directive(state, (N,))
    plain = DistributedDataParallel(
        mse_loss, optax.sgd(LR), build_algorithm("gradient_allreduce"),
        process_group=group,
    )
    pstate = plain.init(params)
    with pytest.raises(AttributeError, match="no staleness knob"):
        plain.apply_degradation_directive(pstate, (0,))
    with pytest.raises(AttributeError, match="no staleness knob"):
        plain.apply_staleness(2, reason="planner")


def test_apply_staleness_is_the_single_recompile_switch(group):
    """τ switch arc: clears the compiled step (τ shapes the gate), re-proves
    the program, emits no-op False when τ is unchanged, rejects τ<0."""
    params, xs, ys = make_problem(seed=5)
    ddp = make_ddp(group, tau=0)
    state = ddp.init(params)
    state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
    assert ddp._step_fns
    assert ddp.apply_staleness(TAU, reason="planner") is True
    assert ddp.impl.staleness_tau == TAU
    assert not ddp._step_fns, "τ switch must invalidate the compiled step"
    assert ddp.apply_staleness(TAU, reason="planner") is False  # no-op
    with pytest.raises(ValueError):
        ddp.apply_staleness(-1, reason="planner")
    # the re-bounded program still trains
    state, _ = ddp.train_step(state, (jnp.asarray(xs[1]), jnp.asarray(ys[1])))


def test_reset_staleness_state_reprimes_replay(group):
    """After a τ re-raise the replay state is ancient: reset must pin every
    counter to τ (first directive round is forced fresh, rewriting the
    payload before any replay) and zero the error-feedback residual."""
    params, xs, ys = make_problem(seed=6)
    ddp = make_ddp(group, tau=TAU, bucket_size_bytes=1 << 62)
    state = ddp.init(params)
    state = ddp.apply_degradation_directive(state, (STALE_RANK,))
    for i in range(2):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
    assert counters(state)[STALE_RANK] == 2
    resid = np.asarray(state.algo_state["residual"][0])
    assert np.abs(resid).max() > 0, "stale rounds must accrue residual"

    state = ddp.reset_staleness_state(state)
    assert (counters(state) == TAU).all()
    for leaf in state.algo_state["residual"]:
        assert np.abs(np.asarray(leaf)).max() == 0
    # counter at τ closes the gate: the very next round is fresh
    state, _ = ddp.train_step(state, (jnp.asarray(xs[2]), jnp.asarray(ys[2])))
    assert counters(state)[STALE_RANK] == 0


def test_stale_refuses_wire_quantization(group):
    """The replay algebra is defined on exact f32 buckets — stacking wire
    quantization's error feedback on top would compound two loops."""
    ddp = make_ddp(group, tau=TAU)
    with pytest.raises(ValueError, match="f32-only"):
        ddp.impl.set_bucket_precision(["int8"])
    with pytest.raises(ValueError):
        StaleSyncAlgorithm(staleness_tau=-1).reify(group)


def test_stale_convergence_tracks_bulk_sync(group):
    """Bounded staleness must stay a *relaxation*, not a different optimizer:
    on the fixed fixture, τ=2 with one degraded rank converges — loss
    strictly down an order of magnitude — and lands within a small factor
    of bulk sync's final loss."""
    params, _, _ = make_problem(seed=7)
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(N * 4, DIM_IN).astype(np.float32))
    w_true = rng.randn(DIM_IN, DIM_OUT).astype(np.float32)
    y = jnp.asarray(np.asarray(x) @ w_true)

    def run(tau, directive):
        ddp = make_ddp(group, tau=tau, lr=0.02)
        state = ddp.init(params)
        if directive:
            state = ddp.apply_degradation_directive(state, directive)
        losses = []
        for _ in range(40):
            state, loss = ddp.train_step(state, (x, y))
            losses.append(float(np.mean(np.asarray(loss))))
        return losses

    bulk = run(0, ())
    stale = run(TAU, (STALE_RANK,))
    assert stale[-1] < 0.5 * stale[0], "stale-sync did not converge"
    assert abs(stale[-1] - bulk[-1]) < 0.05 * bulk[-1], (stale[-1], bulk[-1])
