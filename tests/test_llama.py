"""Llama model family: RoPE/GQA unit oracles, TP and SP consistency against
the single-device model, and DDP training integration.

Oracles follow tests/test_parallel.py: single-device full computation on
assembled weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bagua_tpu.models.llama import (
    LlamaConfig,
    LlamaModel,
    apply_rope,
    llama_loss_fn,
    llama_test_config,
)


def test_config_validation():
    with pytest.raises(ValueError, match="num_kv_heads"):
        LlamaConfig(hidden_size=768, num_heads=6, num_kv_heads=4)
    with pytest.raises(ValueError, match="tp_size"):
        llama_test_config(num_heads=4, num_kv_heads=2, tp_size=4)  # kv % tp != 0
    with pytest.raises(ValueError, match="hidden_size"):
        LlamaConfig(hidden_size=100, num_heads=6, num_kv_heads=6)
    with pytest.raises(ValueError, match="head_dim"):  # 18/6 = 3, odd -> RoPE
        LlamaConfig(hidden_size=18, num_heads=6, num_kv_heads=6)


def test_rope_properties():
    """Position 0 is the identity; equal position offsets give equal relative
    attention scores (the defining RoPE property)."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1, 4, 2, 8).astype(np.float32))
    out0 = apply_rope(x, jnp.zeros((4,), jnp.int32), 10000.0)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(x), rtol=1e-6)

    q = jnp.asarray(rng.randn(1, 1, 1, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 1, 1, 8).astype(np.float32))

    def score(pq, pk):
        qr = apply_rope(q, jnp.asarray([pq]), 10000.0)
        kr = apply_rope(k, jnp.asarray([pk]), 10000.0)
        return float(jnp.sum(qr * kr))

    assert score(3, 1) == pytest.approx(score(7, 5), rel=1e-5)
    assert score(3, 1) != pytest.approx(score(3, 2), rel=1e-3)


@pytest.mark.slow
def test_gqa_matches_mha_with_repeated_kv():
    """num_kv_heads=1 with K/V weights replicated per head must equal the MHA
    model whose per-head K/V weights are identical."""
    rng = np.random.RandomState(1)
    ids = jnp.asarray(rng.randint(0, 64, (2, 8)).astype(np.int32))

    gqa_cfg = llama_test_config(num_heads=4, num_kv_heads=1)
    mha_cfg = llama_test_config(num_heads=4, num_kv_heads=4)
    gqa, mha = LlamaModel(gqa_cfg), LlamaModel(mha_cfg)
    p_gqa = gqa.init(jax.random.PRNGKey(0), ids)["params"]

    def widen(path, leaf):
        name = jax.tree_util.keystr(path)
        if "['k']['kernel']" in name or "['v']['kernel']" in name:
            return jnp.tile(leaf, (1, 4))  # replicate the single kv head x4
        return leaf

    p_mha = jax.tree_util.tree_map_with_path(widen, p_gqa)
    np.testing.assert_allclose(
        np.asarray(gqa.apply({"params": p_gqa}, ids)),
        np.asarray(mha.apply({"params": p_mha}, ids)),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.slow
def test_forward_and_loss_finite():
    cfg = llama_test_config()
    model = LlamaModel(cfg)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 64, (2, 16)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, 64)
    loss = llama_loss_fn(model)(params, ids)
    assert np.isfinite(float(loss))


def test_max_position_embeddings_enforced():
    cfg = llama_test_config(max_position_embeddings=8)
    model = LlamaModel(cfg)
    ids = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        model.init(jax.random.PRNGKey(0), ids)


def test_ring_attention_kv_groups_matches_repeat():
    """kv_groups expansion inside the ring == repeating K/V before it."""
    from bagua_tpu.parallel.ring_attention import ring_attention

    rng = np.random.RandomState(7)
    b, t, h, d, groups = 2, 8, 4, 8, 2
    q = jnp.asarray(rng.randn(b, 4 * t, h, d).astype(np.float32))
    kv = rng.randn(b, 4 * t, h // groups, d).astype(np.float32)
    k, v = jnp.asarray(kv), jnp.asarray(rng.randn(b, 4 * t, h // groups, d).astype(np.float32))

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))

    def run(use_groups):
        def body(qq, kk, vv):
            if use_groups:
                return ring_attention(qq, kk, vv, axis_name="sp", causal=True,
                                      kv_groups=groups)
            kk = jnp.repeat(kk, groups, axis=2)
            vv = jnp.repeat(vv, groups, axis=2)
            return ring_attention(qq, kk, vv, axis_name="sp", causal=True)

        fn = jax.jit(
            jax.shard_map(body, mesh=mesh,
                          in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
                          out_specs=P(None, "sp"), check_vma=False)
        )
        return np.asarray(fn(q, k, v))

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-5)


def _shard_llama_for_tp(params0, heads, kv_heads, tp):
    """tp-rank shards of a single-device param tree (column layers slice
    output columns; row layers slice input rows)."""

    def slice_leaf_for_rank(r):
        def go(path, leaf):
            name = jax.tree_util.keystr(path)
            arr = np.asarray(leaf)
            if any(f"['{p}']['kernel']" in name for p in ("q", "k", "v", "gate", "up")):
                cols = arr.shape[-1] // tp
                return jnp.asarray(arr[..., r * cols : (r + 1) * cols])
            if "['out']['kernel']" in name or "['down']['kernel']" in name:
                rows = arr.shape[0] // tp
                return jnp.asarray(arr[r * rows : (r + 1) * rows])
            return jnp.asarray(arr)

        return jax.tree_util.tree_map_with_path(go, params0)

    return [slice_leaf_for_rank(r) for r in range(tp)]


@pytest.mark.slow
def test_tp_sp_consistency():
    """tp=2 x sp=2 (zigzag) on a 2x2 submesh matches the single-device model
    with assembled weights — TP pairing, ring attention, RoPE global
    positions and GQA in one integration."""
    from bagua_tpu.parallel.ring_attention import zigzag_inverse, zigzag_order

    vocab, seq, tp, sp = 64, 16, 2, 2
    rng = np.random.RandomState(3)
    ids = rng.randint(0, vocab, size=(2, seq)).astype(np.int32)

    cfg0 = llama_test_config()
    model0 = LlamaModel(cfg0)
    params0 = model0.init(jax.random.PRNGKey(0), jnp.asarray(ids))["params"]
    ref = np.asarray(model0.apply({"params": params0}, jnp.asarray(ids)))

    cfg = llama_test_config(tp_size=tp, tp_axis="tp", sp_axis="sp", sp_layout="zigzag")
    model = LlamaModel(cfg)
    per_tp = _shard_llama_for_tp(params0, cfg.num_heads, cfg.num_kv_heads, tp)
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs), *[per_tp[r] for r in (0, 1) for _ in range(sp)]
    )

    order = zigzag_order(seq, sp)
    ids_z = jnp.asarray(ids)[:, order]

    devs = np.array(jax.devices()[:4]).reshape(tp, sp)
    mesh = Mesh(devs, ("tp", "sp"))
    fn = jax.jit(
        jax.shard_map(
            lambda p, ii: model.apply({"params": jax.tree.map(lambda q: q[0], p)}, ii),
            mesh=mesh,
            in_specs=(P(("tp", "sp")), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    got_z = np.asarray(fn(stacked, ids_z))
    # un-permute the zigzag token order to compare against the reference
    inv = zigzag_inverse(seq, sp)
    np.testing.assert_allclose(got_z[:, inv], ref, rtol=5e-3, atol=5e-3)


@pytest.mark.slow
def test_ddp_training_integration(group):
    """3 gradient_allreduce steps on the 8-device group: finite decreasing
    loss and bitwise replica equality."""
    import bagua_tpu
    from bagua_tpu.algorithms import build_algorithm
    from bagua_tpu.ddp import DistributedDataParallel

    cfg = llama_test_config()
    model = LlamaModel(cfg)
    rng = np.random.RandomState(4)
    ids = jnp.asarray(rng.randint(0, 64, (16, 16)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), ids[:2])["params"]
    ddp = DistributedDataParallel(
        llama_loss_fn(model), optax.adam(1e-3),
        build_algorithm("gradient_allreduce"), process_group=group,
    )
    state = ddp.init(params)
    losses = []
    for _ in range(3):
        state, loss = ddp.train_step(state, ids)
        losses.append(float(jnp.mean(loss)))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    for p in jax.tree.leaves(state.params):
        p = np.asarray(p)
        assert np.array_equal(p[0], p[-1])
