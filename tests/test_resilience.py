"""Resilience subsystem: async snapshots, preemption drain, elastic resume,
retry/breaker — the unit half of the tentpole's acceptance.

The fault-injection CI lane (``ci/fault_injection.py``, driven by
``tests/test_ci_lane.py``) proves the end-to-end story with real signals
against a live gang.  What it *cannot* exercise in this container — the
CPU backend refuses cross-process computations, so a genuine 2-process
gang never jits — is pinned here instead: the multi-process snapshot
layout (per-process files + stacked load), the cross-rank KV agreement
against a live rendezvous store (process count/index monkeypatched), and
every torn/partial/outage edge the filesystem and network can produce.
"""

import json
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.observability import Telemetry, validate_metrics_file
from bagua_tpu.resilience import (
    MANIFEST_FILENAME,
    AsyncSnapshotter,
    CircuitBreaker,
    CircuitOpenError,
    ElasticResumeCoordinator,
    PreemptionWatcher,
    RetryPolicy,
    SnapshotStore,
    clear_resumable_marker,
    read_resumable_marker,
    retry_call,
    seed_backoff,
    write_resumable_marker,
)

LAYERS = [12, 16, 16, 4]


def make_batch(seed=0, n=32):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, LAYERS[0]).astype(np.float32))
    y = jnp.asarray(rng.randn(n, LAYERS[-1]).astype(np.float32))
    return x, y


def make_ddp(group, bucket_size=1 << 9):
    return DistributedDataParallel(
        mse_loss, optax.sgd(0.1), GradientAllReduceAlgorithm(),
        process_group=group, bucket_size_bytes=bucket_size,
    )


def leaves_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- SnapshotStore: atomic completeness rules ---------------------------------


def test_store_completeness_skips_torn_and_partial(tmp_path):
    """A snapshot is complete iff its manifest exists AND every file it
    names exists — a killed writer leaves garbage that is skipped, never an
    error or a torn read."""
    store = SnapshotStore(str(tmp_path))
    arrays = [np.arange(8, dtype=np.float32).reshape(2, 4), np.ones(3)]

    # process file without a manifest: not yet committed
    store.write_process_arrays(5, 0, arrays)
    assert not store.is_complete(5)
    assert store.latest_complete() is None

    store.write_manifest(5, {"step": 5, "world_size": 2, "num_processes": 1,
                             "files": ["proc0.npz"]})
    assert store.is_complete(5) and store.latest_complete() == 5

    # a newer directory holding only a torn tmp file (writer killed mid-write)
    os.makedirs(store.step_dir(9), exist_ok=True)
    with open(os.path.join(store.step_dir(9), "proc0.npz.tmp.123"), "wb") as f:
        f.write(b"torn")
    # a newer manifest that names a file which never landed (rank died)
    store.write_manifest(12, {"step": 12, "world_size": 4, "num_processes": 2,
                              "files": ["proc0.npz", "proc1.npz"]})
    store.write_process_arrays(12, 0, arrays)
    assert not store.is_complete(9) and not store.is_complete(12)
    assert store.latest_complete() == 5  # only 5 may be trusted

    # atomic writes leave no tmp residue in the committed snapshot
    assert not [n for n in os.listdir(store.step_dir(5)) if ".tmp." in n]
    # non-step junk in the root is ignored
    os.makedirs(os.path.join(str(tmp_path), "step_garbage"), exist_ok=True)
    (tmp_path / "notes.txt").write_text("x")
    assert store.steps() == [5, 9, 12]


def test_store_multiprocess_layout_and_stacked_load(tmp_path):
    """The multi-process layout this container can't produce live: each
    process writes its leading-axis slice; load_stacked concatenates the
    manifest-named files in process order into full (world_size, ...) hosts."""
    store = SnapshotStore(str(tmp_path))
    full = [np.arange(4 * 3, dtype=np.float32).reshape(4, 3),
            np.arange(4, dtype=np.int32).reshape(4, 1)]
    store.write_process_arrays(3, 0, [a[:2] for a in full])
    store.write_process_arrays(3, 1, [a[2:] for a in full])
    manifest_in = {"step": 3, "world_size": 4, "num_processes": 2,
                   "files": ["proc0.npz", "proc1.npz"], "plan": {"v": 1}}
    store.write_manifest(3, manifest_in)

    manifest, leaves = store.load_stacked(3)
    assert manifest == manifest_in
    assert len(leaves) == 2
    for got, want in zip(leaves, full):
        np.testing.assert_array_equal(got, want)

    # process files that disagree on leaf count: torn gang, loud failure
    store.write_process_arrays(8, 0, [full[0][:2], full[1][:2]])
    store.write_process_arrays(8, 1, [full[0][2:]])
    store.write_manifest(8, {"step": 8, "world_size": 4, "num_processes": 2,
                             "files": ["proc0.npz", "proc1.npz"]})
    with pytest.raises(ValueError, match="disagree on leaf count"):
        store.load_stacked(8)
    # loading an incomplete snapshot is a loud FileNotFoundError
    with pytest.raises(FileNotFoundError):
        store.load_stacked(99)


def test_store_gc_keeps_newest_complete_and_inflight(tmp_path):
    """gc keeps the newest ``keep`` complete snapshots plus any incomplete
    directory *newer* than the newest complete one (may still be in flight);
    older incomplete garbage goes."""
    store = SnapshotStore(str(tmp_path))
    arrays = [np.ones(2)]
    for step in (2, 4, 6):
        store.write_process_arrays(step, 0, arrays)
        store.write_manifest(step, {"step": step, "world_size": 1,
                                    "num_processes": 1, "files": ["proc0.npz"]})
    os.makedirs(store.step_dir(1), exist_ok=True)  # old killed-writer garbage
    os.makedirs(store.step_dir(7), exist_ok=True)  # newer: may be in flight
    store.gc(keep=2)
    assert store.steps() == [4, 6, 7]
    assert store.latest_complete() == 6


# -- AsyncSnapshotter ---------------------------------------------------------


def test_snapshotter_cadence_dedupe_and_busy_skip(tmp_path):
    state = {"w": jnp.arange(16.0), "b": jnp.ones((4,))}
    tel = Telemetry()
    snap = AsyncSnapshotter(
        str(tmp_path), every=2, process_index=0, num_processes=1,
        world_size=1, telemetry=tel, keep=10,
        manifest_extra_fn=lambda: {"plan": {"buckets": [["w"]]}},
    )
    try:
        assert snap.maybe_snapshot(state, 1) is False  # off cadence
        assert snap.maybe_snapshot(state, 2) is True
        snap.drain()
        assert snap.store.latest_complete() == 2
        assert snap.maybe_snapshot(state, 2) is False  # same step: dedupe

        # writer busy at the cadence tick: skipped (counted), never queued
        snap._idle.clear()
        assert snap.maybe_snapshot(state, 4) is False
        snap._idle.set()
        assert snap.skipped == 1
        assert tel.registry.snapshot()["snapshot_skipped_total"] == 1

        # forced (drain-path) snapshot blocks until the manifest is on disk
        assert snap.force_snapshot(state, 6) is True
        manifest = snap.store.read_manifest(6)
        assert manifest["kind"] == "final"
        assert manifest["plan"] == {"buckets": [["w"]]}  # extras ride along
        assert snap.written == 2
        # the written snapshot round-trips bitwise
        _, leaves = snap.store.load_stacked(6)
        leaves_equal(leaves, [state["b"], state["w"]])  # flatten order: b, w
    finally:
        snap.close()
        snap.close()  # idempotent
    assert tel.registry.snapshot()["snapshots_total"] == 2


def test_snapshotter_disabled_and_error_surfacing(tmp_path):
    state = {"w": jnp.ones(3)}
    snap = AsyncSnapshotter(str(tmp_path / "off"), every=0, process_index=0,
                            num_processes=1, world_size=1)
    try:
        assert snap.maybe_snapshot(state, 10) is False  # every=0 disables
    finally:
        snap.close()

    snap2 = AsyncSnapshotter(str(tmp_path / "err"), every=1, process_index=0,
                             num_processes=1, world_size=1)

    def boom(*a, **k):
        raise OSError("disk full")

    snap2.store.write_process_arrays = boom
    try:
        with pytest.raises(OSError, match="disk full"):
            snap2.force_snapshot(state, 1)  # blocking: the error surfaces here
    finally:
        snap2.close()


# -- retry / backoff / circuit breaking ---------------------------------------


def test_retry_policy_env_knobs_and_backoff_bounds(monkeypatch):
    monkeypatch.setenv("BAGUA_RPC_RETRIES", "7")
    monkeypatch.setenv("BAGUA_RPC_BACKOFF_BASE_S", "0.5")
    monkeypatch.setenv("BAGUA_RPC_BACKOFF_MAX_S", "1.25")
    p = RetryPolicy()
    assert p.retries == 7 and p.base_s == 0.5 and p.max_s == 1.25

    p = RetryPolicy(retries=3, base_s=1.0, max_s=4.0, seed=0)
    for attempt in range(6):
        for _ in range(20):  # full jitter: uniform(0, min(max, base * 2^i))
            assert 0.0 <= p.backoff_s(attempt) <= min(4.0, 2.0 ** attempt)


def test_seed_backoff_pins_the_shared_jitter_stream():
    """Seedless policies draw from ONE module-level RNG: ``seed_backoff(n)``
    makes every subsequent backoff sequence reproducible across all of them
    (the repro knob for flaky-network lanes), while an explicit
    ``RetryPolicy(seed=...)`` keeps its own isolated stream that a later
    ``seed_backoff`` call cannot disturb."""
    seed_backoff(7)
    a = [RetryPolicy(retries=3, base_s=1.0, max_s=4.0).backoff_s(i)
         for i in range(5)]
    seed_backoff(7)
    b = [RetryPolicy(retries=3, base_s=1.0, max_s=4.0).backoff_s(i)
         for i in range(5)]
    assert a == b  # shared stream, bitwise-reproducible after re-seeding
    seed_backoff(8)
    c = [RetryPolicy(retries=3, base_s=1.0, max_s=4.0).backoff_s(i)
         for i in range(5)]
    assert a != c  # a different seed is a different schedule

    # two seedless policies interleave on the SAME stream: re-seeding and
    # drawing through either order reproduces the one global sequence
    seed_backoff(7)
    p1, p2 = RetryPolicy(base_s=1.0, max_s=4.0), RetryPolicy(base_s=1.0, max_s=4.0)
    interleaved = [p1.backoff_s(0), p2.backoff_s(0), p1.backoff_s(1)]
    seed_backoff(7)
    assert interleaved == [RetryPolicy(base_s=1.0, max_s=4.0).backoff_s(i)
                           for i in (0, 0, 1)]

    # an explicitly seeded policy is immune to the module knob
    iso1 = RetryPolicy(retries=3, base_s=1.0, max_s=4.0, seed=0)
    seed_backoff(12345)
    iso2 = RetryPolicy(retries=3, base_s=1.0, max_s=4.0, seed=0)
    assert [iso1.backoff_s(i) for i in range(5)] == [
        iso2.backoff_s(i) for i in range(5)
    ]


def test_retry_call_recovers_exhausts_and_passes_through():
    calls, sleeps, retried = [], [], []
    policy = RetryPolicy(retries=3, base_s=0.25, max_s=0.25, seed=1)

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    out = retry_call(flaky, policy=policy, sleep=sleeps.append,
                     on_retry=lambda i, e: retried.append(i))
    assert out == "ok" and len(calls) == 3
    assert len(sleeps) == 2 and all(0.0 <= s <= 0.25 for s in sleeps)
    assert retried == [0, 1]

    def dead():
        calls.append(1)
        raise OSError("persistent")

    calls.clear()
    with pytest.raises(OSError, match="persistent"):
        retry_call(dead, policy=policy, sleep=lambda s: None)
    assert len(calls) == 4  # 1 + retries attempts, then the last error raises

    def wrong():
        calls.append(1)
        raise ValueError("not transient")

    calls.clear()
    with pytest.raises(ValueError):  # outside retry_on: no retries burned
        retry_call(wrong, policy=policy, sleep=lambda s: None)
    assert len(calls) == 1


def test_circuit_breaker_open_halfopen_probe_lifecycle():
    now = [0.0]
    br = CircuitBreaker(failure_threshold=2, cooldown_s=10.0, clock=lambda: now[0])
    assert br.state == "closed"
    br.record_failure()
    br.before_call()  # one failure: still closed
    br.record_failure()
    assert br.state == "open" and br.times_opened == 1
    with pytest.raises(CircuitOpenError):
        br.before_call()  # fast-fail, no I/O

    now[0] = 11.0
    assert br.state == "half-open"
    br.before_call()  # admitted as THE probe
    with pytest.raises(CircuitOpenError):
        br.before_call()  # concurrent caller while the probe is in flight
    br.record_failure()  # probe failed: re-open for another cooldown
    with pytest.raises(CircuitOpenError):
        br.before_call()

    now[0] = 22.0
    br.before_call()
    br.record_success()  # probe succeeded: circuit closes
    assert br.state == "closed"
    br.before_call()

    off = CircuitBreaker(failure_threshold=0)
    for _ in range(10):
        off.record_failure()
    off.before_call()  # threshold <= 0 disables breaking entirely


def test_retry_call_fails_fast_while_circuit_open():
    """CircuitOpenError is never retried — the whole point is that a
    flapping service degrades the job instantly, not after stacked timeouts."""
    br = CircuitBreaker(failure_threshold=1, cooldown_s=1000.0, clock=lambda: 0.0)
    calls = []

    def dead():
        calls.append(1)
        raise ConnectionError("down")

    policy = RetryPolicy(retries=5, base_s=0.0, max_s=0.0)
    with pytest.raises(CircuitOpenError):
        retry_call(dead, policy=policy, breaker=br, sleep=lambda s: None)
    assert len(calls) == 1  # first failure opened the circuit; no more I/O


# -- preemption watcher + resumable marker ------------------------------------


def test_preemption_trigger_and_marker_roundtrip(tmp_path):
    w = PreemptionWatcher()
    assert not w.should_stop() and not w.preempted
    w.trigger()
    assert w.should_stop() and w.preempted

    d = str(tmp_path)
    assert read_resumable_marker(d) is None
    write_resumable_marker(d, 12, reason="preempted")
    marker = read_resumable_marker(d)
    assert marker["step"] == 12 and marker["reason"] == "preempted"
    assert not [n for n in os.listdir(d) if ".tmp." in n]  # atomic publish
    clear_resumable_marker(d)
    assert read_resumable_marker(d) is None
    clear_resumable_marker(d)  # idempotent


def test_preemption_sigterm_sets_flag_and_chains_prior_handler():
    """A real SIGTERM flips the flag (handler does nothing else — no I/O in
    signal context) and any previously installed Python handler still runs."""
    prior_calls = []
    original = signal.signal(signal.SIGTERM, lambda s, f: prior_calls.append(s))
    w = PreemptionWatcher()
    try:
        w.install().install()  # idempotent
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5.0
        while not w.preempted and time.time() < deadline:
            time.sleep(0.01)
        assert w.preempted and w.signum == signal.SIGTERM
        assert prior_calls == [signal.SIGTERM]
    finally:
        w.uninstall()
        restored = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, original)
    assert prior_calls and callable(restored)  # uninstall re-installed the prior


# -- cross-rank snapshot agreement (live rendezvous KV) -----------------------


def _complete_snapshot(store, step, world=2):
    store.write_process_arrays(step, 0, [np.full((world, 2), float(step))])
    store.write_manifest(step, {"step": step, "world_size": world,
                                "num_processes": 1, "files": ["proc0.npz"]})


@pytest.fixture()
def kv_store():
    """A live rendezvous store + two rank clients on localhost."""
    from bagua_tpu.distributed.rendezvous import (
        RendezvousClient, RendezvousState, start_rendezvous_server,
    )
    from tests.helpers import free_port

    port = free_port()
    server = start_rendezvous_server(RendezvousState(min_nodes=1), port)
    endpoint = f"http://127.0.0.1:{port}"
    try:
        yield RendezvousClient(endpoint, node_rank=0), RendezvousClient(endpoint, node_rank=1)
    finally:
        server.shutdown()


def test_agreed_resume_step_is_min_over_ranks(tmp_path, monkeypatch, kv_store):
    """Ranks publish their local view under the attempt nonce and take the
    minimum — a rank whose filesystem lags must not be resumed past what it
    can read.  (Process count/index are monkeypatched: this container's CPU
    backend cannot run a real multi-process gang.)"""
    client0, client1 = kv_store
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    store = SnapshotStore(str(tmp_path))
    _complete_snapshot(store, 3)
    _complete_snapshot(store, 6)

    coord = ElasticResumeCoordinator(store, rendezvous_client=client0,
                                     agreement_timeout_s=10.0)
    # rank 1's filesystem view lags at step 3: the gang agrees on 3, not 6
    client1.kv_set("resilience/resume/7/rank1", json.dumps(3))
    assert coord.agreed_resume_step(nonce="7") == 3
    # rank 0's own view landed in the KV under the same nonce
    assert json.loads(client0.kv_get("resilience/resume/7/rank0")) == 6

    # a different nonce namespaces a different round: rank 1 sees nothing
    # on disk, so the whole gang cold-starts
    client1.kv_set("resilience/resume/8/rank1", json.dumps(None))
    assert coord.agreed_resume_step(nonce="8") is None


def test_agreement_timeout_and_outage_fall_back_to_local(
    tmp_path, monkeypatch, kv_store
):
    client0, _ = kv_store
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setenv("BAGUA_RPC_RETRIES", "0")  # keep the outage path fast
    monkeypatch.setenv("BAGUA_RPC_BACKOFF_MAX_S", "0.01")
    store = SnapshotStore(str(tmp_path))
    _complete_snapshot(store, 4)

    # rank 1 never publishes: the agreement times out, local scan wins
    coord = ElasticResumeCoordinator(store, rendezvous_client=client0,
                                     agreement_timeout_s=0.5)
    assert coord.agreed_resume_step(nonce="t") == 4

    # store unreachable entirely: degrade to the local scan, never block
    from bagua_tpu.distributed.rendezvous import RendezvousClient
    from tests.helpers import free_port

    dead = RendezvousClient(f"http://127.0.0.1:{free_port()}", node_rank=0,
                            timeout_s=1.0)
    coord = ElasticResumeCoordinator(store, rendezvous_client=dead,
                                     agreement_timeout_s=0.5)
    assert coord.agreed_resume_step(nonce="u") == 4

    # single-process gang never consults the store at all
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    coord = ElasticResumeCoordinator(store, rendezvous_client=dead)
    assert coord.agreed_resume_step() == 4


# -- elastic resume into a live engine ----------------------------------------


def test_resume_bitwise_roundtrip_carries_plan_and_marker(group, tmp_path):
    """The core resume contract: the restored state is bitwise-identical to
    the snapshotted one, the manifest's bucket plan is re-adopted (no planner
    cold-start), the drain marker is consumed into ``lost_steps``, and the
    restart lands on every telemetry surface."""
    jsonl = str(tmp_path / "metrics.jsonl")
    tel = Telemetry(metrics_jsonl=jsonl)
    ddp = make_ddp(group, bucket_size=1 << 9)
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    batch = make_batch()
    for _ in range(2):
        state, _ = ddp.train_step(state, batch)
    assert ddp.plan.num_buckets > 1

    snap_dir = str(tmp_path / "snaps")
    snap = AsyncSnapshotter(
        snap_dir, every=1, world_size=group.size,
        manifest_extra_fn=lambda: {"plan": ddp.export_plan_payload()},
    )
    snap.force_snapshot(state, 2)
    snap.close()
    # the previous incarnation drained at step 5 before its final snapshot
    # failed: 3 steps of work are lost and the marker says so
    write_resumable_marker(snap_dir, 5)

    # the restarted engine cold-starts with a different (single-bucket) plan
    ddp2 = make_ddp(group, bucket_size=1 << 22)
    init2 = ddp2.init(init_mlp(jax.random.PRNGKey(9), LAYERS))
    assert ddp2.plan.num_buckets == 1
    coord = ElasticResumeCoordinator(snap_dir, telemetry=tel)
    res = coord.resume(ddp2, init2)

    assert res is not None and res.step == 2
    assert res.old_world_size == res.new_world_size == group.size
    assert res.plan_source == "carried"
    assert ddp2.plan.num_buckets == ddp.plan.num_buckets  # tuned plan adopted
    assert [[td.name for td in b] for b in ddp2.plan.declarations()] == [
        [td.name for td in b] for b in ddp.plan.declarations()
    ]
    leaves_equal(res.state, state)  # bitwise, params + opt state + step
    assert read_resumable_marker(snap_dir) is None  # resume consumed it

    # resumed state trains on the adopted plan
    state2, losses = ddp2.train_step(res.state, batch)
    assert np.isfinite(np.asarray(losses)).all()
    assert int(np.asarray(state2.step)[0]) == 3

    tel.close()
    assert validate_metrics_file(jsonl) == []
    events = [json.loads(l) for l in open(jsonl) if l.strip()]
    (restart,) = [e for e in events if e["event"] == "restart"]
    assert restart["step"] == 2 and restart["lost_steps"] == 3
    assert restart["plan_source"] == "carried"
    assert tel.registry.snapshot()["lost_steps_total"] == 3
    ddp.shutdown()
    ddp2.shutdown()


def test_resume_remaps_snapshot_into_resized_gang(group, tmp_path):
    """A snapshot taken at world size 4 resumes into this 8-way gang: the
    replicated leaves re-stack to the new size bitwise."""
    ddp = make_ddp(group)
    state = ddp.init(init_mlp(jax.random.PRNGKey(1), LAYERS))
    state, _ = ddp.train_step(state, make_batch(1))

    store = SnapshotStore(str(tmp_path))
    halves = [np.asarray(leaf)[:4] for leaf in jax.tree.leaves(state)]
    store.write_process_arrays(1, 0, halves)
    store.write_manifest(1, {"step": 1, "world_size": 4, "num_processes": 1,
                             "files": ["proc0.npz"]})

    init2 = ddp.init(init_mlp(jax.random.PRNGKey(2), LAYERS))
    res = ElasticResumeCoordinator(store).resume(ddp, init2)
    assert res.old_world_size == 4 and res.new_world_size == group.size
    assert res.plan_source == "fresh"  # no plan rode in this manifest
    # allreduce keeps every rank row bitwise equal, so the remapped state
    # must equal the original 8-stacked state exactly
    leaves_equal(res.state, state)
    ddp.shutdown()


def test_resume_refuses_mismatched_state_shape(group, tmp_path):
    """A snapshot from a different model/optimizer definition fails loud —
    leaf-count drift must never be silently zip-truncated into the state."""
    ddp = make_ddp(group)
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    snap_dir = str(tmp_path / "snaps")
    snap = AsyncSnapshotter(snap_dir, every=1, world_size=group.size)
    snap.force_snapshot(state, 1)
    snap.close()

    ddp2 = make_ddp(group)
    init_smaller = ddp2.init(init_mlp(jax.random.PRNGKey(0), [12, 16, 4]))
    with pytest.raises(ValueError, match="leaves"):
        ElasticResumeCoordinator(snap_dir).resume(ddp2, init_smaller)
    ddp.shutdown()
    ddp2.shutdown()

    # nothing on disk at all: resume is a clean None (cold start)
    empty = ElasticResumeCoordinator(str(tmp_path / "empty"))
    assert empty.resume(ddp2, init_smaller) is None


# -- Trainer integration ------------------------------------------------------

TR_LAYERS = [8, 12, 4]


def make_trainer(group, tmp_path, telemetry=None, **kw):
    from bagua_tpu.trainer import Trainer

    kw.setdefault("snapshot_dir", str(tmp_path / "snaps"))
    kw.setdefault("snapshot_every", 1000)  # cadence noise off; tests force
    kw.setdefault("watchdog_timeout_s", 0)
    return Trainer(
        mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(),
        process_group=group, telemetry=telemetry, **kw,
    )


def tr_batches(n, seed=3):
    rng = np.random.RandomState(seed)
    return [
        (jnp.asarray(rng.randn(16, TR_LAYERS[0]), np.float32),
         jnp.asarray(rng.randn(16, TR_LAYERS[-1]), np.float32))
        for _ in range(n)
    ]


def test_trainer_preemption_drain_then_elastic_resume(group, tmp_path):
    """In-process end-to-end: a triggered preemption drains the in-flight
    step, forces a final snapshot + resumable marker, and a second Trainer
    over the same directory resumes at that exact step with zero lost work,
    on the carried bucket plan."""
    batches = tr_batches(6)
    tr1 = make_trainer(group, tmp_path)
    state = tr1.init_state(init_mlp(jax.random.PRNGKey(0), TR_LAYERS))
    assert tr1.resume_result is None  # nothing to resume from yet
    state = tr1.fit(state, batches[:3], log_every=0)
    assert tr1._state_step(state) == 3 and not tr1.preempted

    tr1.preemption.trigger()  # the orchestrator-sidecar path; SIGTERM is
    # exercised with a real signal by ci/fault_injection.py
    state = tr1.fit(state, batches[3:], log_every=0)
    assert tr1.preempted  # drained after ONE more step, not the full epoch
    assert tr1._state_step(state) == 4
    snap_dir = str(tmp_path / "snaps")
    assert read_resumable_marker(snap_dir)["step"] == 4
    assert SnapshotStore(snap_dir).latest_complete() == 4
    tr1.close()

    tel = Telemetry(metrics_jsonl=str(tmp_path / "m.jsonl"))
    tr2 = make_trainer(group, tmp_path, telemetry=tel)
    state2 = tr2.init_state(init_mlp(jax.random.PRNGKey(7), TR_LAYERS))
    res = tr2.resume_result
    assert res is not None and res.step == 4
    assert res.plan_source == "carried"
    leaves_equal(state2, state)  # bitwise: params, opt state, step counter
    assert read_resumable_marker(snap_dir) is None  # marker consumed

    state2 = tr2.fit(state2, batches[4:], log_every=0)
    assert tr2._state_step(state2) == 6
    tr2.close()
    assert validate_metrics_file(str(tmp_path / "m.jsonl")) == []
    (restart,) = [
        json.loads(l) for l in open(tmp_path / "m.jsonl") if l.strip()
        and json.loads(l)["event"] == "restart"
    ]
    assert restart["step"] == 4 and restart["lost_steps"] == 0


def test_trainer_close_idempotent_and_exception_safe(group, tmp_path, monkeypatch):
    """close() tears everything down exactly once, keeps going past a
    failing teardown, and the context manager closes on the exception path."""
    monkeypatch.setenv("BAGUA_SNAPSHOT_EVERY", "5")  # env overrides the arg
    tel = Telemetry()
    tr = make_trainer(group, tmp_path, telemetry=tel, watchdog_timeout_s=60)
    assert tr.snapshotter.every == 5
    assert tr.preemption._installed  # SIGTERM handler live on the main thread
    watchdog = tr.watchdog
    assert watchdog is not None and watchdog._thread.is_alive()

    shutdowns = []
    monkeypatch.setattr(tr.ddp, "shutdown", lambda: shutdowns.append(1))

    def boom():
        raise RuntimeError("snapshotter teardown failed")

    real_close = tr.snapshotter.close
    tr.snapshotter.close = boom
    tr.close()  # must not raise, must not stop early
    assert tr._closed
    assert tr.watchdog is None and watchdog._stopped.is_set()
    watchdog._thread.join(timeout=10.0)
    assert not watchdog._thread.is_alive()
    assert not tr.preemption._installed  # signal handler restored
    assert shutdowns == [1]  # teardown ran past the failing snapshotter
    tr.close()  # second call is a no-op
    assert shutdowns == [1]
    real_close()  # don't leak the writer thread the test sabotaged

    with pytest.raises(ValueError, match="mid-fit"):
        with make_trainer(group, tmp_path / "ctx") as tr2:
            raise ValueError("died mid-fit")
    assert tr2._closed  # __exit__ closed on the exception path too


# -- elastic resume of the sharded (zero) engine ------------------------------
# The gang resize path above remaps replicated rank-stacked leaves; under the
# zero algorithm the optimizer state and the pending updated-parameter shards
# are SHARDED, so a resize must re-shard them through the layout recorded in
# the snapshot manifest's plan payload.

from bagua_tpu.communication import new_group  # noqa: E402
from bagua_tpu.sharded import ZeroAlgorithm  # noqa: E402


def make_zero_ddp(group, bucket_size=1 << 9):
    return DistributedDataParallel(
        mse_loss, optax.adam(1e-2), ZeroAlgorithm(),
        process_group=group, bucket_size_bytes=bucket_size, overlap=True,
    )


def zero_snapshot(ddp, state, world, tmp_path, name, step):
    snap_dir = str(tmp_path / name)
    snap = AsyncSnapshotter(
        snap_dir, every=1, world_size=world,
        manifest_extra_fn=lambda: {"plan": ddp.export_plan_payload()},
    )
    snap.force_snapshot(state, step)
    snap.close()
    return snap_dir


def test_zero_manifest_records_shard_layout(group, tmp_path):
    """Satellite contract: snapshot manifests under the zero algorithm carry
    the shard layout (world count + per-bucket shard geometry) so a resumer
    can rebuild the exact layout the optimizer shards were written under."""
    ddp = make_zero_ddp(group)
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    state, _ = ddp.train_step(state, make_batch(0, n=40))
    snap_dir = zero_snapshot(ddp, state, group.size, tmp_path, "m", 1)
    store = SnapshotStore(snap_dir)
    manifest = json.load(open(os.path.join(store.step_dir(1), MANIFEST_FILENAME)))
    shard = manifest["plan"]["shard"]
    assert shard["n_shards"] == group.size
    assert len(shard["buckets"]) == ddp.plan.num_buckets
    for b in shard["buckets"]:
        assert b["numel"] == b["shard_numel"] * group.size
    ddp.shutdown()


def test_zero_resume_grows_gang(group, tmp_path):
    """Odd -> even grow: a snapshot from a 5-way sharded gang resumes into
    this 8-way one.  Params replicate bitwise, and the migrated pending
    updated-parameter shards finalize to exactly the full parameters the old
    gang would have finalized."""
    small = new_group(list(range(5)), intra_size=1)
    ddp5 = make_zero_ddp(small)
    st5 = ddp5.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    for i in range(2):
        st5, _ = ddp5.train_step(st5, make_batch(i, n=40))
    snap_dir = zero_snapshot(ddp5, st5, 5, tmp_path, "w5", 2)

    ddp8 = make_zero_ddp(group)
    init8 = ddp8.init(init_mlp(jax.random.PRNGKey(3), LAYERS))
    res = ElasticResumeCoordinator(snap_dir).resume(ddp8, init8)
    assert res is not None and res.step == 2
    assert res.old_world_size == 5 and res.new_world_size == group.size
    for a, b in zip(jax.tree.leaves(res.state.params), jax.tree.leaves(st5.params)):
        np.testing.assert_array_equal(np.asarray(a)[0], np.asarray(b)[0])

    fin5 = ddp5.finalize_pending_updates(st5)
    fin8 = ddp8.finalize_pending_updates(res.state)
    for a, b in zip(jax.tree.leaves(fin8.params), jax.tree.leaves(fin5.params)):
        np.testing.assert_array_equal(np.asarray(a)[0], np.asarray(b)[0])

    state, loss = ddp8.train_step(res.state, make_batch(9, n=40))
    assert np.isfinite(np.asarray(loss)).all()
    assert int(np.asarray(state.step)[0]) == 3
    ddp5.shutdown()
    ddp8.shutdown()


def test_zero_resume_shrink_roundtrip_bitwise(group, tmp_path):
    """Even -> odd shrink, then grow back: 8 -> 5 -> 8.  Re-sharding is
    element-value-preserving by slot name and alignment padding carries
    exact zeros on both sides (zero grads keep zero moments; zero params get
    zero updates), so the round-tripped TrainState — params, sharded
    optimizer moments, pending shards, step — is bitwise-identical to the
    original 8-way state, leaf for leaf."""
    ddp8 = make_zero_ddp(group)
    st8 = ddp8.init(init_mlp(jax.random.PRNGKey(1), LAYERS))
    for i in range(2):
        st8, _ = ddp8.train_step(st8, make_batch(i, n=40))
    d8 = zero_snapshot(ddp8, st8, group.size, tmp_path, "w8", 2)

    small = new_group(list(range(5)), intra_size=1)
    # the shrunken engine cold-starts on a different (single-bucket) plan;
    # the manifest's carried plan must win before any resharding happens
    ddp5 = make_zero_ddp(small, bucket_size=1 << 22)
    init5 = ddp5.init(init_mlp(jax.random.PRNGKey(4), LAYERS))
    res5 = ElasticResumeCoordinator(d8).resume(ddp5, init5)
    assert res5.old_world_size == group.size and res5.new_world_size == 5
    assert res5.plan_source == "carried"
    assert ddp5.plan.num_buckets == ddp8.plan.num_buckets
    d5 = zero_snapshot(ddp5, res5.state, 5, tmp_path, "w5", 2)

    ddp8b = make_zero_ddp(group)
    init8b = ddp8b.init(init_mlp(jax.random.PRNGKey(5), LAYERS))
    res8 = ElasticResumeCoordinator(d5).resume(ddp8b, init8b)
    assert res8.old_world_size == 5 and res8.new_world_size == group.size
    leaves_equal(res8.state, st8)
    ddp8.shutdown()
    ddp5.shutdown()
    ddp8b.shutdown()


# -- named-mesh reshapes of the sharded engine ---------------------------------
# On a data-only MeshSpec mesh the exchange ring spans every axis, so shard
# rows map 1:1 to mesh-rank rows and the reshard path must carry values
# exactly across a mesh *reshape* (same gang, different factorization).

from bagua_tpu.mesh import MeshSpec  # noqa: E402


def test_zero_resume_mesh_reshape_roundtrip_bitwise(tmp_path):
    """dp8 -> dp4×fsdp2 -> dp8: reshaping a data-only named mesh preserves
    every leaf — params, the SHARDED adam moments, the pending
    updated-parameter shards, step — bitwise through the round trip, and
    the intermediate 2-D engine both trains and finalizes to the same full
    parameters the original dp8 gang would."""
    g_a = new_group(mesh_spec=MeshSpec({"dp": 8}))
    ddp_a = make_zero_ddp(g_a)
    st_a = ddp_a.init(init_mlp(jax.random.PRNGKey(1), LAYERS))
    for i in range(2):
        st_a, _ = ddp_a.train_step(st_a, make_batch(i, n=40))
    d_a = zero_snapshot(ddp_a, st_a, g_a.size, tmp_path, "dp8", 2)

    g_b = new_group(mesh_spec=MeshSpec({"dp": 4, "fsdp": 2}))
    assert g_b.exchange_size == g_b.size == 8  # fsdp joins the ring
    ddp_b = make_zero_ddp(g_b)
    init_b = ddp_b.init(init_mlp(jax.random.PRNGKey(4), LAYERS))
    res_b = ElasticResumeCoordinator(d_a).resume(ddp_b, init_b)
    assert res_b is not None and res_b.step == 2
    # element-value-preserving across the reshape, sharded opt state included
    leaves_equal(res_b.state, st_a)
    d_b = zero_snapshot(ddp_b, res_b.state, g_b.size, tmp_path, "dp4xfsdp2", 2)
    # the resumed 2-D engine actually trains on its mesh
    st_b, loss = ddp_b.train_step(res_b.state, make_batch(7, n=40))
    assert np.isfinite(np.asarray(loss)).all()

    g_c = new_group(mesh_spec=MeshSpec({"dp": 8}))
    ddp_c = make_zero_ddp(g_c)
    init_c = ddp_c.init(init_mlp(jax.random.PRNGKey(5), LAYERS))
    res_c = ElasticResumeCoordinator(d_b).resume(ddp_c, init_c)
    leaves_equal(res_c.state, st_a)
    fin_a = ddp_a.finalize_pending_updates(st_a)
    fin_c = ddp_c.finalize_pending_updates(res_c.state)
    for a, b in zip(jax.tree.leaves(fin_a.params), jax.tree.leaves(fin_c.params)):
        np.testing.assert_array_equal(np.asarray(a)[0], np.asarray(b)[0])
    ddp_a.shutdown()
    ddp_b.shutdown()
    ddp_c.shutdown()


def test_zero_reshard_fenced_on_model_axes():
    """Host-side shard migration is undefined when a model axis is present
    (state rows are per mesh rank, shard rows per exchange-ring slot); the
    engine must refuse loudly rather than scramble shards."""
    g = new_group(mesh_spec=MeshSpec({"dp": 4, "tp": 2}))
    ddp = make_zero_ddp(g)
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    host = jax.tree.map(np.asarray, state)
    payload = ddp.export_plan_payload()
    with pytest.raises(ValueError, match="model axes"):
        ddp.reshard_host_state(host, payload, old_world=8)
    ddp.shutdown()
