"""Flax TrainState integration — exact-parity regression.

The analog of the reference's Lightning-strategy tests
(``tests/pytorch_lightning/test_bagua_strategy.py:30-60``), which train the
same model through the strategy and through a manual loop and compare
weights.  Here: a genuine ``flax.training.train_state.TrainState`` driven
through :class:`FlaxBaguaStrategy` must match a plain single-device
flax/optax loop on the full batch (gradient_allreduce is mathematically a
full-batch step), and the ``to_flax`` boundary must hand back a state the
flax ecosystem accepts (step/opt_state synced, apply_gradients works).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training import train_state

import bagua_tpu
from bagua_tpu.integrations.flax import FlaxBaguaStrategy


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        return nn.Dense(4)(x)


DIM_IN = 8
GLOBAL_BATCH = 32  # 4 per rank on the 8-device sim


def make_flax_state(seed=0, lr=0.05):
    model = MLP()
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, DIM_IN)))["params"]
    return model, train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(lr)
    )


def make_loss(model):
    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        return jnp.mean((logits - y) ** 2)

    return loss_fn


def make_batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [
        (
            jnp.asarray(rng.randn(GLOBAL_BATCH, DIM_IN).astype(np.float32)),
            jnp.asarray(rng.randn(GLOBAL_BATCH, 4).astype(np.float32)),
        )
        for _ in range(n)
    ]


def test_matches_plain_flax_loop(group):
    """Strategy-trained params == plain flax full-batch loop, step by step."""
    model, fstate = make_flax_state()
    loss_fn = make_loss(model)
    batches = make_batches(4)

    # Reference: the user's original single-device flax loop.
    @jax.jit
    def plain_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        return state.apply_gradients(grads=grads), loss

    ref_state = fstate
    ref_losses = []
    for b in batches:
        ref_state, loss = plain_step(ref_state, b)
        ref_losses.append(float(loss))

    # Same model through the strategy over the 8-rank group.
    strategy = FlaxBaguaStrategy(loss_fn, "gradient_allreduce", process_group=group)
    bstate = strategy.init_from_flax(fstate)
    try:
        strat_losses = []
        for b in batches:
            bstate, losses = strategy.train_step(bstate, b)
            # per-rank local losses; their mean is the full-batch loss
            strat_losses.append(float(jnp.mean(losses)))
        out = strategy.to_flax(bstate, fstate)
    finally:
        strategy.shutdown()

    np.testing.assert_allclose(strat_losses, ref_losses, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(out.params), jax.tree.leaves(ref_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
    assert int(out.step) == int(ref_state.step) == len(batches)


def test_to_flax_state_is_ecosystem_usable(group):
    """The returned state is a real flax TrainState: apply_gradients and
    apply_fn work, opt_state is the synced adam state (not the init)."""
    model, fstate = make_flax_state()
    loss_fn = make_loss(model)
    strategy = FlaxBaguaStrategy(loss_fn, "gradient_allreduce", process_group=group)
    bstate = strategy.init_from_flax(fstate)
    try:
        for b in make_batches(2, seed=1):
            bstate, _ = strategy.train_step(bstate, b)
        out = strategy.to_flax(bstate, fstate)
    finally:
        strategy.shutdown()
    # adam's mu must have moved off its all-zeros init
    mu_leaves = jax.tree.leaves(out.opt_state[0].mu)
    assert any(np.abs(np.asarray(l)).max() > 0 for l in mu_leaves)
    # the flax ecosystem path keeps working on the returned state
    x, y = make_batches(1, seed=2)[0]
    grads = jax.grad(loss_fn)(out.params, (x, y))
    out2 = out.apply_gradients(grads=grads)
    assert int(out2.step) == int(out.step) + 1
    preds = out2.apply_fn({"params": out2.params}, x)
    assert preds.shape == (GLOBAL_BATCH, 4)


def test_resume_preserves_step_schedule(group):
    """A non-zero flax step survives the round-trip (warmup schedules on
    resumed runs depend on it)."""
    model, fstate = make_flax_state()
    loss_fn = make_loss(model)
    fstate = fstate.replace(step=7)
    strategy = FlaxBaguaStrategy(
        loss_fn, "async", process_group=group, warmup_steps=2
    )
    bstate = strategy.init_from_flax(fstate)
    try:
        assert int(jax.device_get(bstate.step)[0]) == 7
        bstate, _ = strategy.train_step(bstate, make_batches(1)[0])
        out = strategy.to_flax(bstate, fstate)
        assert int(out.step) == 8
    finally:
        strategy.shutdown()


def test_algorithm_kwargs_and_bad_usage():
    with pytest.raises(ValueError, match="algorithm_kwargs"):
        FlaxBaguaStrategy(lambda p, b: 0.0, bagua_tpu.algorithms.build_algorithm(
            "gradient_allreduce"), warmup_steps=2)
    strategy = FlaxBaguaStrategy(lambda p, b: 0.0)
    with pytest.raises(RuntimeError, match="init_from_flax"):
        strategy.train_step(None, None)


def test_bundled_optimizer_algorithms_are_rejected(group):
    """QAdam's gradient transform IS the Adam update direction; running the
    flax tx on top would be silently wrong — must refuse loudly."""
    model, fstate = make_flax_state()
    strategy = FlaxBaguaStrategy(make_loss(model), "qadam", process_group=group)
    with pytest.raises(ValueError, match="bundles its own optimizer"):
        strategy.init_from_flax(fstate)
    assert strategy.ddp is None  # no leaked engine


def test_reinit_shuts_down_previous_engine(group):
    """Re-entering with a new flax state must not leak the previous engine's
    background machinery (async averager thread)."""
    model, fstate = make_flax_state()
    loss_fn = make_loss(model)
    strategy = FlaxBaguaStrategy(loss_fn, "async", process_group=group)
    strategy.init_from_flax(fstate)
    first = strategy.ddp
    try:
        strategy.init_from_flax(fstate)  # re-enter
        assert strategy.ddp is not first
        assert first.impl._shutdown, "previous engine's averager not stopped"
    finally:
        strategy.shutdown()
