"""Async model average: warmup allreduce, time-armed sync, abort/resume."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bagua_tpu.algorithms.async_model_average import AsyncModelAverageAlgorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

N = 8
DIM_IN, DIM_OUT = 10, 3


def make_data(n_steps, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n_steps, N * 4, DIM_IN).astype(np.float32)
    ys = rng.randn(n_steps, N * 4, DIM_OUT).astype(np.float32)
    return xs, ys


def ranks_equal(state):
    return all(
        all(np.array_equal(np.asarray(l)[0], np.asarray(l)[r]) for r in range(1, N))
        for l in jax.tree.leaves(state.params)
    )


def max_spread(state):
    leaves = jax.tree.leaves(jax.tree.map(np.asarray, state.params))
    return max(np.abs(l.max(axis=0) - l.min(axis=0)).max() for l in leaves)


def test_sync_every_step_keeps_ranks_close(group):
    params = init_mlp(jax.random.PRNGKey(0), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(6, seed=1)

    def run(sync: bool):
        ddp = DistributedDataParallel(
            mse_loss,
            optax.sgd(0.05),
            AsyncModelAverageAlgorithm(sync_interval_ms=0),  # arm sync every step
            process_group=group,
        )
        state = ddp.init(params)
        if not sync:
            ddp.abort()
        for i in range(6):
            state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
        return state

    # With averaging armed every step, ranks differ by a single local update;
    # without it, the divergence accumulates and must be clearly larger.
    assert max_spread(run(sync=True)) < 0.5 * max_spread(run(sync=False))


def test_no_sync_when_aborted(group):
    params = init_mlp(jax.random.PRNGKey(1), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(3, seed=2)
    algo = AsyncModelAverageAlgorithm(sync_interval_ms=0)
    ddp = DistributedDataParallel(mse_loss, optax.sgd(0.05), algo, process_group=group)
    state = ddp.init(params)
    ddp.abort()
    for i in range(3):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
    assert not ranks_equal(state)  # ranks diverged: no averaging happened
    spread_before = max_spread(state)

    # resume: next step syncs again, collapsing the divergence to one local update
    ddp.resume()
    state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
    assert max_spread(state) < spread_before


def test_warmup_gradient_allreduce(group):
    """During warmup the grads are averaged, so ranks stay bitwise equal."""
    params = init_mlp(jax.random.PRNGKey(2), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(3, seed=3)
    ddp = DistributedDataParallel(
        mse_loss,
        optax.sgd(0.05),
        AsyncModelAverageAlgorithm(sync_interval_ms=10 ** 9, warmup_steps=100),
        process_group=group,
    )
    state = ddp.init(params)
    for i in range(3):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
    assert ranks_equal(state)
