"""Async model average: background averaging, non-blocking cadence,
warmup allreduce, negotiated abort/resume.

The averager is a real background thread (see the module docstring of
``bagua_tpu/algorithms/async_model_average.py``).  Deterministic tests drive
one averaging cycle by hand (``_cycle``) with the timer parked; a separate
timed test lets the thread run for real.
"""

import pytest
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bagua_tpu.algorithms.async_model_average import AsyncModelAverageAlgorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

N = 8
DIM_IN, DIM_OUT = 10, 3
PARKED = 10 ** 9  # sync_interval_ms large enough that the thread never fires


def make_data(n_steps, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n_steps, N * 4, DIM_IN).astype(np.float32)
    ys = rng.randn(n_steps, N * 4, DIM_OUT).astype(np.float32)
    return xs, ys


def make_ddp(params, lr=0.05, sync_interval_ms=PARKED, warmup_steps=0, group=None):
    ddp = DistributedDataParallel(
        mse_loss,
        optax.sgd(lr),
        AsyncModelAverageAlgorithm(
            sync_interval_ms=sync_interval_ms, warmup_steps=warmup_steps
        ),
        process_group=group,
    )
    return ddp


def spread_params(base):
    """Rank-stacked params where rank r's copy is ``base + r`` (maximally
    divergent start, so averaging effects are unmistakable)."""
    return jax.tree.map(
        lambda x: jnp.stack([x + float(r) for r in range(N)]), base
    )


def ranks_equal(state):
    return all(
        all(np.array_equal(np.asarray(l)[0], np.asarray(l)[r]) for r in range(1, N))
        for l in jax.tree.leaves(state.params)
    )


def ranks_close(state, atol=1e-5):
    """The delta-fold ``p + (avg - snap)`` is exact in value but not bitwise
    across ranks (fp non-associativity), so converged ranks agree to ~1e-7."""
    return max_spread(state) < atol


def max_spread(state):
    leaves = jax.tree.leaves(jax.tree.map(np.asarray, state.params))
    return max(np.abs(l.max(axis=0) - l.min(axis=0)).max() for l in leaves)


@pytest.mark.slow
def test_one_cycle_converges_ranks_to_mean(group):
    """One averaging cycle + fold collapses divergent ranks to their mean
    (lr=0 isolates the averaging path from training updates)."""
    base = init_mlp(jax.random.PRNGKey(0), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(3, seed=1)
    ddp = make_ddp(base, lr=0.0, group=group)
    state = ddp.init(stacked_params=spread_params(base))
    try:
        state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
        assert not ranks_equal(state)
        ddp.impl._cycle()  # one averaging cycle, timer parked
        state, _ = ddp.train_step(state, (jnp.asarray(xs[1]), jnp.asarray(ys[1])))
        assert ddp.impl.folds_applied == 1
        assert ranks_close(state)
        # with lr=0 the fold lands on the rank mean: base + (N-1)/2
        w0 = np.asarray(jax.tree.leaves(state.params)[0])
        e0 = np.asarray(jax.tree.leaves(spread_params(base))[0]).mean(axis=0)
        np.testing.assert_allclose(w0[0], e0, rtol=1e-6)
    finally:
        ddp.shutdown()


def test_background_thread_folds_while_training(group):
    """The real thread averages while steps run; ranks converge without any
    host-side coordination from the training loop."""
    base = init_mlp(jax.random.PRNGKey(1), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(2, seed=2)
    ddp = make_ddp(base, lr=0.0, sync_interval_ms=1, group=group)
    state = ddp.init(stacked_params=spread_params(base))
    try:
        deadline = time.monotonic() + 30.0
        i = 0
        while ddp.impl.folds_applied < 1 and time.monotonic() < deadline:
            state, _ = ddp.train_step(
                state, (jnp.asarray(xs[i % 2]), jnp.asarray(ys[i % 2]))
            )
            i += 1
        assert ddp.impl.folds_applied >= 1, "background averager never folded"
        assert ranks_close(state)
    finally:
        ddp.shutdown()


def test_step_cadence_independent_of_averaging(group):
    """The steady-state step has zero collectives; averaging runs on the side,
    so throughput with the averager hot stays within a generous factor of
    throughput with it aborted (the reference's defining property)."""
    base = init_mlp(jax.random.PRNGKey(2), [DIM_IN, 16, DIM_OUT])
    xs, ys = make_data(2, seed=3)
    batch = (jnp.asarray(xs[0]), jnp.asarray(ys[0]))

    def time_steps(ddp, n=30):
        state = ddp.init(base)
        state, _ = ddp.train_step(state, batch)  # compile
        jax.block_until_ready(state.params)
        t0 = time.perf_counter()
        for _ in range(n):
            state, _ = ddp.train_step(state, batch)
        jax.block_until_ready(state.params)
        return time.perf_counter() - t0

    hot = make_ddp(base, sync_interval_ms=1, group=group)
    cold = make_ddp(base, sync_interval_ms=1, group=group)
    cold.abort()
    try:
        # Wall-clock comparison on a shared CI box is inherently noisy
        # (VERDICT r2 weak #6): re-measure up to 3 times before declaring
        # the cadence serialized — a real serialization bug fails every
        # attempt, scheduler noise doesn't.
        for attempt in range(3):
            t_cold = time_steps(cold)
            t_hot = time_steps(hot)
            if t_hot < t_cold * 3 + 0.5:
                break
        # generous bound: averaging must not serialize the step cadence.
        # (Fold delivery itself is owned by
        # test_background_thread_folds_while_training — the averager now
        # compiles off the dispatch path, so a short timing window may
        # legitimately end before the first cycle lands.)
        assert t_hot < t_cold * 3 + 0.5, (t_hot, t_cold)
    finally:
        hot.shutdown()
        cold.shutdown()


def test_stale_generation_delta_is_dropped(group):
    """Double-fold guard: a delta whose snapshot predates an intervening fold
    must be dropped, not re-applied.  (Re-applying it re-adds the previous
    fold's correction: at lr=0 the rank spread re-inverts to its full initial
    magnitude instead of staying collapsed — the race the background thread
    can hit when a cycle snapshot overlaps a fold.)"""
    base = init_mlp(jax.random.PRNGKey(4), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(3, seed=5)
    ddp = make_ddp(base, lr=0.0, group=group)
    state = ddp.init(stacked_params=spread_params(base))
    try:
        state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
        ddp.impl._cycle()
        gen, delta = ddp.impl._pending
        stale = (gen, jax.tree.map(lambda x: x + 0, delta))  # pre-donation copy
        state, _ = ddp.train_step(state, (jnp.asarray(xs[1]), jnp.asarray(ys[1])))
        assert ddp.impl.folds_applied == 1 and ranks_close(state)
        # inject the stale-generation delta as if a racing cycle published it
        # (ready flag too — the step path only looks at landed deltas)
        ddp.impl._pending = stale
        ddp.impl._pending_ready = True
        state, _ = ddp.train_step(state, (jnp.asarray(xs[2]), jnp.asarray(ys[2])))
        assert ddp.impl.folds_applied == 1, "stale delta was folded"
        assert ddp.impl._pending is None, "stale delta was not dropped"
        assert ranks_close(state), "stale fold re-inverted the rank spread"
    finally:
        ddp.shutdown()


def test_step_path_makes_no_backend_queries(group):
    """The fold path must read only the plain ``_pending_ready`` flag — a
    per-leaf ``is_ready()`` probe on the step path cost ~130 ms/step over
    the tunneled PJRT client (r4 chip session: async 183 img/s vs 764 for
    gradient_allreduce on the same model)."""

    class ExplodingLeaf:
        def is_ready(self):
            raise AssertionError("step path queried the backend")

    base = init_mlp(jax.random.PRNGKey(5), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(1, seed=6)
    ddp = make_ddp(base, lr=0.0, group=group)
    state = ddp.init(base)
    try:
        state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
        # An in-flight (not-ready) delta must be left pending without a probe.
        ddp.impl._pending = (ddp.impl._fold_generation, ExplodingLeaf())
        ddp.impl._pending_ready = False
        state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
        assert ddp.impl._pending is not None  # still pending, never probed
        ddp.impl._pending = None
    finally:
        ddp.shutdown()


def test_abort_drains_and_resume_rearms(group):
    base = init_mlp(jax.random.PRNGKey(3), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(3, seed=4)
    ddp = make_ddp(base, lr=0.0, group=group)
    state = ddp.init(stacked_params=spread_params(base))
    try:
        state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
        ddp.abort()
        # a cycle while aborted must not produce a pending result
        ddp.impl._cycle()
        assert ddp.impl._pending is None
        state, _ = ddp.train_step(state, (jnp.asarray(xs[1]), jnp.asarray(ys[1])))
        assert ddp.impl.folds_applied == 0
        assert not ranks_equal(state)
        # resume re-arms: the next cycle folds
        ddp.resume()
        ddp.impl._cycle()
        state, _ = ddp.train_step(state, (jnp.asarray(xs[2]), jnp.asarray(ys[2])))
        assert ddp.impl.folds_applied == 1
        assert ranks_close(state)
    finally:
        ddp.shutdown()


def test_warmup_gradient_allreduce(group):
    """During warmup the grads are averaged, so ranks stay bitwise equal."""
    params = init_mlp(jax.random.PRNGKey(2), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(3, seed=3)
    ddp = make_ddp(params, sync_interval_ms=PARKED, warmup_steps=100, group=group)
    state = ddp.init(params)
    try:
        for i in range(3):
            state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
        assert ranks_equal(state)
    finally:
        ddp.shutdown()
