"""``wire_precision``: the in-collective quantized-ring exchange wired into
the gradient-allreduce and zero engines — int8/int4 training behavior, int4
error-feedback state, the "auto" + per-bucket precision plan path, and the
modelled per-precision wire-byte accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.kernels.quantized_ring import ring_wire_bytes
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.sharded import ZeroAlgorithm

N = 8
LAYERS = [10, 16, 4]  # 244 params; 1<<9 bucket bytes -> 3 buckets, last padded
STEPS = 5


def _batches(steps=STEPS, seed=1):
    rng = np.random.RandomState(seed)
    return [
        (jnp.asarray(rng.randn(16, LAYERS[0]), np.float32),
         jnp.asarray(rng.randn(16, LAYERS[-1]), np.float32))
        for _ in range(steps)
    ]


def _run(group, algo, overlap=False, steps=STEPS, precision_plan=None):
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(5e-2), algo, process_group=group,
        bucket_size_bytes=1 << 9, overlap=overlap,
    )
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    if precision_plan is not None:
        assert ddp.apply_precision_plan(precision_plan)
    losses = []
    for b in _batches(steps):
        state, loss = ddp.train_step(state, b)
        losses.append(float(np.asarray(loss)[0]))
    return ddp, state, losses


def _params0(state):
    return jax.tree.map(lambda l: np.asarray(l)[0], state.params)


def _assert_ranks_synced(state):
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, state.params)):
        for r in range(1, N):
            np.testing.assert_array_equal(leaf[0], leaf[r])


# -- gradient_allreduce ------------------------------------------------------


@pytest.mark.parametrize("precision", ["int8", "int4"])
def test_allreduce_quantized_trains_and_syncs(group, precision):
    """Quantized-wire training converges on the fixture model, keeps every
    rank bitwise-synchronized (the ring output is identical everywhere), and
    stays close to the exact-f32 trajectory."""
    _, ref_state, ref_losses = _run(group, GradientAllReduceAlgorithm())
    _, state, losses = _run(
        group, GradientAllReduceAlgorithm(wire_precision=precision)
    )
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(l) for l in losses)
    _assert_ranks_synced(state)
    # few-step drift vs f32 is bounded by the quantization granularity
    atol = 5e-3 if precision == "int8" else 5e-2
    for a, b in zip(jax.tree.leaves(_params0(state)), jax.tree.leaves(_params0(ref_state))):
        np.testing.assert_allclose(a, b, rtol=0, atol=atol)


def test_allreduce_int8_deterministic(group):
    """Two identical int8 runs are bitwise-identical — the quantized ring is
    a deterministic program, not a stochastic compressor."""
    _, s1, _ = _run(group, GradientAllReduceAlgorithm(wire_precision="int8"))
    _, s2, _ = _run(group, GradientAllReduceAlgorithm(wire_precision="int8"))
    for a, b in zip(jax.tree.leaves(_params0(s1)), jax.tree.leaves(_params0(s2))):
        np.testing.assert_array_equal(a, b)


def test_allreduce_int8_overlap_bitwise_matches_mono(group):
    """int8 is stateless, so the per-bucket overlap exchange runs the exact
    same ring program as the monolithic path — bitwise."""
    _, mono, _ = _run(group, GradientAllReduceAlgorithm(wire_precision="int8"),
                      overlap=False)
    _, over, _ = _run(group, GradientAllReduceAlgorithm(wire_precision="int8"),
                      overlap=True)
    for a, b in zip(jax.tree.leaves(_params0(mono)), jax.tree.leaves(_params0(over))):
        np.testing.assert_array_equal(a, b)


def test_allreduce_int4_carries_error_feedback_state(group):
    """int4 allocates one f32 residual per bucket, and after a step the
    residuals are non-zero (16 levels always leave requantization error on a
    real gradient)."""
    ddp, state, _ = _run(group, GradientAllReduceAlgorithm(wire_precision="int4"),
                         steps=2)
    resid = state.algo_state["qr_residual"]
    assert len(resid) == ddp.plan.num_buckets
    for r, spec in zip(resid, ddp.plan.specs):
        assert r.shape == (N, spec.numel) and r.dtype == jnp.float32
    assert any(float(jnp.max(jnp.abs(r))) > 0 for r in resid)


def test_allreduce_int4_error_feedback_beats_plain_requant(group):
    """The EF residual re-enters the next step's gradient: over a longer run
    the int4 trajectory tracks f32 more closely than the worst-case one-shot
    quantization error would suggest — concretely, the final loss lands
    within 10% of the exact run's."""
    _, _, ref_losses = _run(group, GradientAllReduceAlgorithm(), steps=12)
    _, _, q_losses = _run(
        group, GradientAllReduceAlgorithm(wire_precision="int4"), steps=12
    )
    assert q_losses[-1] < q_losses[0]
    assert q_losses[-1] <= ref_losses[-1] * 1.10, (q_losses[-1], ref_losses[-1])


def test_allreduce_int4_fences_overlap_and_rebucket(group):
    from bagua_tpu.bucket import BucketPlan

    algo = GradientAllReduceAlgorithm(wire_precision="int4")
    with pytest.raises(ValueError, match="per-bucket state"):
        DistributedDataParallel(
            mse_loss, optax.sgd(5e-2), algo, process_group=group, overlap=True
        )
    ddp, _, _ = _run(group, GradientAllReduceAlgorithm(wire_precision="int4"),
                     steps=1)
    with pytest.raises(ValueError, match="per-bucket state"):
        ddp.rebucket(BucketPlan.from_tree(
            init_mlp(jax.random.PRNGKey(0), LAYERS),
            bucket_size_bytes=1 << 22, align_elems=group.size,
        ))


def test_allreduce_hierarchical_int8_trains(group):
    """hierarchical + quantized: exact f32 sum intra-node, quantized ring on
    the inter leg only — still converges and stays rank-synchronized."""
    _, state, losses = _run(
        group, GradientAllReduceAlgorithm(hierarchical=True, wire_precision="int8")
    )
    assert losses[-1] < losses[0], losses
    _assert_ranks_synced(state)


def test_auto_without_plan_is_bitwise_f32(group):
    """wire_precision="auto" never quantizes until a plan is adopted — the
    trajectory is bitwise the plain engine's."""
    _, ref, _ = _run(group, GradientAllReduceAlgorithm())
    _, auto, _ = _run(group, GradientAllReduceAlgorithm(wire_precision="auto"))
    for a, b in zip(jax.tree.leaves(_params0(auto)), jax.tree.leaves(_params0(ref))):
        np.testing.assert_array_equal(a, b)


def test_auto_mixed_precision_plan(group):
    """A planner-style mixed plan (one bucket per precision) trains, keeps
    ranks synced, and resolves exactly as adopted."""
    ddp, state, losses = _run(
        group, GradientAllReduceAlgorithm(wire_precision="auto"),
        precision_plan=["int8", "f32", "int4"],
    )
    assert ddp.impl.bucket_precisions(ddp.plan) == ["int8", "f32", "int4"]
    assert losses[-1] < losses[0], losses
    _assert_ranks_synced(state)
    # re-applying the same plan is a no-op (keeps the compiled step)
    fns = dict(ddp._step_fns)
    assert not ddp.apply_precision_plan(["int8", "f32", "int4"])
    assert ddp._step_fns == fns


def test_precision_plan_validation(group):
    impl = GradientAllReduceAlgorithm(wire_precision="int8").reify(group)
    with pytest.raises(ValueError, match="auto"):
        impl.set_bucket_precision(["int8"])
    impl = GradientAllReduceAlgorithm(wire_precision="auto").reify(group)
    with pytest.raises(ValueError, match="unknown wire precisions"):
        impl.set_bucket_precision(["bf16"])
    with pytest.raises(ValueError, match="wire_precision must be one of"):
        GradientAllReduceAlgorithm(wire_precision="fp8").reify(group)


# -- zero --------------------------------------------------------------------


@pytest.mark.parametrize("precision", ["int8", "int4"])
def test_zero_quantized_trains_and_syncs(group, precision):
    """The zero engine's gradient leg rides the quantized reduce-scatter;
    the deferred parameter all-gather stays f32, so ranks remain bitwise in
    sync after the swap-in."""
    _, state, losses = _run(
        group, ZeroAlgorithm(wire_precision=precision),
        overlap=(precision == "int8"),
    )
    assert losses[-1] < losses[0], losses
    _assert_ranks_synced(state)


def test_zero_int4_error_feedback_state(group):
    ddp, state, _ = _run(group, ZeroAlgorithm(wire_precision="int4"), steps=2)
    assert "qr_residual" in state.algo_state
    resid = state.algo_state["qr_residual"]
    assert len(resid) == ddp.plan.num_buckets
    assert any(float(jnp.max(jnp.abs(r))) > 0 for r in resid)


def test_zero_compression_exclusive_with_precision(group):
    with pytest.raises(ValueError, match="mutually exclusive"):
        ZeroAlgorithm(compression="bytegrad", wire_precision="int8").reify(group)


# -- wire-byte accounting ----------------------------------------------------


def test_wire_bytes_by_precision_accounting(group):
    """The modelled counters split by resolved precision and price quantized
    buckets from ring_wire_bytes (compressed payload + sidecar per hop)."""
    ddp, _, _ = _run(
        group, GradientAllReduceAlgorithm(wire_precision="auto"), steps=1,
        precision_plan=["int8", "f32", "int4"],
    )
    by_prec = ddp.impl.wire_bytes_by_precision(ddp.plan)
    specs = ddp.plan.specs
    assert by_prec["int8"] == ring_wire_bytes(specs[0].numel, N, 8)
    assert by_prec["f32"] == 2 * specs[1].nbytes * (N - 1) // N
    assert by_prec["int4"] == ring_wire_bytes(specs[2].numel, N, 4)


def test_quantized_step_compiles_once(group):
    """The quantized path keeps the recompile-free contract: one jit-cache
    miss for the whole run."""
    from bagua_tpu.observability.telemetry import Telemetry

    tel = Telemetry()
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(5e-2),
        GradientAllReduceAlgorithm(wire_precision="int8"),
        process_group=group, bucket_size_bytes=1 << 9, telemetry=tel,
    )
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    for b in _batches(4):
        state, _ = ddp.train_step(state, b)
    assert sum(tel.recompile.compiles_by_variant.values()) == 1
