"""Gang-autopilot policy unit tests (``bagua_tpu/autopilot/``).

The controller's contract is exercised against small fakes of the engine,
sentinel and health monitor — the heavy integration (real engine, real
recompiles, closed loop) lives in ``tests/test_switch_algorithm.py`` and
the ``autopilot`` lane of ``ci/perf_audit.py``.  What is pinned here:

* hysteresis — one wire-dominant incident is not evidence, two are;
* the canary protocol — probation, loss-parity commit, rollback;
* the safety rung — a health reset while quantized re-promotes to f32
  immediately, no canary;
* stability re-promotion — at nominal bandwidth the α-dominated gang
  moves back to f32 and the health monitor is re-armed;
* cooldown — a knob just acted on holds, and the hold is *recorded*;
* strict-verifier rejections — counted, recorded, never dispatched;
* evidence plumbing — incidents are consumed non-destructively, every
  decision cites the triggering trace_id, rows validate against the
  ``plan_decision`` schema.
"""

from types import SimpleNamespace

import pytest

from bagua_tpu.autopilot import (
    AutopilotConfig,
    Configuration,
    GangAutopilot,
    candidate_configurations,
    degraded_cost_model,
    price_configurations,
)
from bagua_tpu.observability.metrics import validate_metrics_event
from bagua_tpu.service.planner import AlphaBeta, CostModel

# A regime where the ranking genuinely flips (see pricing.py): one 16 MiB
# bucket over 8 ranks — at nominal bandwidth the flat f32 allreduce is
# cheapest (the quantized ring pays 2(n-1) sequential hop latencies); under
# a bandwidth collapse the β term dominates and the compressed wire wins.
COST_MODEL = CostModel(flat=AlphaBeta(50e-6, 40e9), qr8=AlphaBeta(60e-6, 90e9))
PLAN = SimpleNamespace(num_buckets=1, specs=[SimpleNamespace(numel=4 << 20, nbytes=16 << 20)])


class FakeImpl:
    def __init__(self, precisions=None):
        self.algo_name = "gradient_allreduce"
        self.wire_precision = "auto"
        self.hierarchical = False
        self._precs = precisions

    def bucket_precisions(self, plan):
        return list(self._precs or ["f32"] * plan.num_buckets)

    def set_bucket_precision(self, *a, **kw):  # existence gates the knob
        raise AssertionError("the controller goes through apply_precision_plan")


class FakeDdp:
    def __init__(self, precisions=None):
        self.impl = FakeImpl(precisions)
        self.plan = PLAN
        self.plan_version = 0
        self.group = SimpleNamespace(exchange_size=8)
        self.switches = []
        self.precision_applies = []
        self.fail_next = False

    def switch_algorithm(self, state, name, reason=None, **kw):
        if self.fail_next:
            self.fail_next = False
            raise ValueError("static verify rejected the program")
        self.impl.algo_name = name
        self.plan_version += 1
        self.switches.append((name, reason))
        return state

    def apply_precision_plan(self, precisions, reason=None):
        if self.fail_next:
            self.fail_next = False
            raise ValueError("static verify rejected the program")
        if list(precisions) == self.impl.bucket_precisions(self.plan):
            return False
        self.impl._precs = list(precisions)
        self.plan_version += 1
        self.precision_applies.append((tuple(precisions), reason))
        return True


class FakeHealth:
    def __init__(self, clean_streak=10**6):
        self.clean_streak = clean_streak
        self.rearmed = 0

    def stabilized(self, n_windows):
        return self.clean_streak >= max(1, int(n_windows))

    def rearm(self):
        self.rearmed += 1


def _incident(trace="tr-1", measured=50.0, expected=5.0, dominant="wire_slowdown"):
    return {
        "dominant": dominant, "measured_ms": measured, "expected_ms": expected,
        "trace_id": trace, "step": 0, "plan_version": 0,
    }


def _pilot(ddp=None, health=None, sentinel=None, **cfg):
    cfg.setdefault("compute_ms", 1.0)
    cfg.setdefault("algorithms", ("gradient_allreduce",))
    sentinel = sentinel or SimpleNamespace(incidents=[], plan_version=0, budget=None)
    return GangAutopilot(
        ddp or FakeDdp(), COST_MODEL, AutopilotConfig(**cfg),
        sentinel=sentinel, health=health or FakeHealth(),
    ), sentinel


# -- pricing ------------------------------------------------------------------


def test_bandwidth_factor_degrades_beta_not_alpha():
    d = degraded_cost_model(COST_MODEL, 10.0)
    assert d.flat.alpha == COST_MODEL.flat.alpha
    assert d.flat.beta == pytest.approx(COST_MODEL.flat.beta / 10.0)
    assert degraded_cost_model(COST_MODEL, 1.0) is COST_MODEL


def test_pricing_ranking_flips_with_bandwidth():
    cands = candidate_configurations(("gradient_allreduce",), ("f32", "int8"))
    nominal = price_configurations(COST_MODEL, PLAN, 8, cands, 1.0)
    collapsed = price_configurations(
        COST_MODEL, PLAN, 8, cands, 1.0, bandwidth_factor=10.0
    )
    assert nominal[0][0].precision == "f32"
    assert collapsed[0][0].precision == "int8"


# -- hysteresis + demotion ----------------------------------------------------


def test_single_incident_is_held_by_hysteresis():
    pilot, sentinel = _pilot()
    sentinel.incidents.append(_incident())
    pilot.tick(None, step=10, loss=1.0)
    assert pilot.decisions == []
    assert pilot.ddp.precision_applies == []


def test_demotes_after_hysteresis_with_canary_and_trace():
    pilot, sentinel = _pilot()
    sentinel.incidents.extend([_incident("tr-a"), _incident("tr-b")])
    pilot.tick(None, step=10, loss=1.0)
    assert pilot.ddp.impl.bucket_precisions(PLAN) == ["int8"]
    (row,) = pilot.decisions
    assert row["decision"] == "demote_precision"
    assert row["verdict"] == "canary"
    assert row["reason"] == "autopilot:wire_slowdown"
    assert row["trace_id"] == "tr-b"  # the adjudicated incident
    assert row["modeled"]["chosen_ms"] < row["modeled"]["stay_ms"]
    assert pilot.report()["canary_active"]
    assert validate_metrics_event(row) == []


def test_demotion_requires_current_health():
    pilot, sentinel = _pilot(health=FakeHealth(clean_streak=0))
    # gang still on f32: the safety rung is idle, but demotion must not
    # chase goodput while the loss is misbehaving
    sentinel.incidents.extend([_incident(), _incident()])
    pilot.tick(None, step=10, loss=1.0)
    assert pilot.ddp.precision_applies == []


# -- canary adjudication ------------------------------------------------------


def _demoted_pilot(**cfg):
    pilot, sentinel = _pilot(**cfg)
    sentinel.incidents.extend([_incident(), _incident()])
    pilot.tick(None, step=10, loss=1.0)
    assert pilot.report()["canary_active"]
    return pilot


def test_canary_commits_on_loss_parity():
    pilot = _demoted_pilot(canary_steps=3)
    for s in range(11, 14):
        pilot.tick(None, step=s, loss=1.0)
    assert not pilot.report()["canary_active"]
    assert pilot.decisions[-1]["verdict"] == "committed"
    assert pilot.decisions[-1]["decision"] == "demote_precision"
    assert pilot.ddp.impl.bucket_precisions(PLAN) == ["int8"]


def test_canary_rolls_back_on_loss_regression():
    pilot = _demoted_pilot(canary_steps=3)
    for s in range(11, 14):
        pilot.tick(None, step=s, loss=50.0)  # blows past canary_loss_factor
    assert not pilot.report()["canary_active"]
    assert pilot.decisions[-1]["verdict"] == "rolled_back"
    assert pilot.decisions[-1]["decision"] == "rollback"
    assert pilot.ddp.impl.bucket_precisions(PLAN) == ["f32"]


def test_no_new_moves_during_probation():
    pilot = _demoted_pilot(canary_steps=100)
    pilot.sentinel.incidents.extend([_incident(), _incident()])
    pilot.tick(None, step=11, loss=1.0)
    assert len(pilot.decisions) == 1  # still just the canary entry


# -- safety + stability re-promotion ------------------------------------------


def test_health_reset_repromotes_immediately_without_canary():
    health = FakeHealth(clean_streak=0)
    pilot, _ = _pilot(ddp=FakeDdp(precisions=["int8"]), health=health)
    pilot.tick(None, step=10, loss=1.0)
    assert pilot.ddp.impl.bucket_precisions(PLAN) == ["f32"]
    (row,) = pilot.decisions
    assert row["decision"] == "repromote_precision"
    assert row["reason"] == "autopilot:loss_spike"
    assert row["verdict"] == "committed"  # safety moves skip probation
    assert not pilot.report()["canary_active"]


def test_stabilized_repromotes_at_nominal_bandwidth_and_rearms():
    health = FakeHealth(clean_streak=10**6)
    pilot, _ = _pilot(ddp=FakeDdp(precisions=["int8"]), health=health)
    pilot.tick(None, step=10, loss=1.0)
    assert pilot.ddp.impl.bucket_precisions(PLAN) == ["f32"]
    (row,) = pilot.decisions
    assert row["decision"] == "repromote_precision"
    assert row["reason"] == "autopilot:stabilized"
    assert row["verdict"] == "canary"  # economic moves still ride probation
    assert health.rearmed == 1


def test_stabilized_is_quiet_when_already_cheapest():
    pilot, _ = _pilot()  # already on gradient_allreduce/f32
    pilot.tick(None, step=10, loss=1.0)
    assert pilot.decisions == []


# -- cooldown -----------------------------------------------------------------


def test_cooldown_holds_and_records_the_hold():
    pilot = _demoted_pilot(canary_steps=3, cooldown_steps=100)
    for s in range(11, 14):
        pilot.tick(None, step=s, loss=1.0)  # commit the canary
    pilot.sentinel.incidents.extend([_incident("tr-c"), _incident("tr-d")])
    pilot.tick(None, step=20, loss=1.0)  # precision knob still cooling down
    row = pilot.decisions[-1]
    assert row["decision"] == "hold"
    assert row["verdict"] == "held"
    assert row["trace_id"] == "tr-d"
    assert len(pilot.ddp.precision_applies) == 1  # no second dispatch


def test_repromotion_respects_cooldown():
    pilot, _ = _pilot(ddp=FakeDdp(precisions=["int8"]), cooldown_steps=100)
    pilot._start_cooldown(0, ("precision",))
    pilot.tick(None, step=10, loss=1.0)
    assert pilot.decisions == []
    assert pilot.ddp.impl.bucket_precisions(PLAN) == ["int8"]


# -- verifier rejection -------------------------------------------------------


def test_verifier_rejection_is_counted_recorded_not_dispatched():
    pilot, sentinel = _pilot()
    sentinel.incidents.extend([_incident(), _incident()])
    pilot.ddp.fail_next = True
    pilot.tick(None, step=10, loss=1.0)
    assert pilot.verifier_rejections == 1
    row = pilot.decisions[-1]
    assert row["verdict"] == "rejected"
    assert pilot.ddp.impl.bucket_precisions(PLAN) == ["f32"]
    assert not pilot.report()["canary_active"]
    assert validate_metrics_event(row) == []


# -- evidence plumbing --------------------------------------------------------


def test_incident_consumption_is_nondestructive():
    pilot, sentinel = _pilot()
    sentinel.incidents.extend([_incident(), _incident()])
    pilot.tick(None, step=10, loss=1.0)
    # the fleet push's drain_incidents() still sees every incident
    assert len(sentinel.incidents) == 2
    pilot.tick(None, step=11, loss=1.0)
    assert len(pilot._wire_evidence) == 0  # but nothing is double-counted


def test_drain_decisions_is_incremental():
    pilot = _demoted_pilot()
    first = pilot.drain_decisions()
    assert [r["decision"] for r in first] == ["demote_precision"]
    assert pilot.drain_decisions() == []
    assert len(pilot.decisions) == 1  # the full history stays queryable


def test_every_decision_row_validates_and_cites():
    pilot = _demoted_pilot(canary_steps=3)
    for s in range(11, 14):
        pilot.tick(None, step=s, loss=1.0)
    assert len(pilot.decisions) == 2
    for row in pilot.decisions:
        assert validate_metrics_event(row) == []
        assert row["event"] == "plan_decision"
        assert row["trace_id"]  # incident-driven: the citation is mandatory
        assert row["plan_version"] == pilot.ddp.plan_version


def test_sentinel_plan_version_follows_the_engine():
    pilot = _demoted_pilot()
    assert pilot.sentinel.plan_version == pilot.ddp.plan_version == 1


def test_repromotion_quarantined_after_recent_wire_incident():
    pilot, sentinel = _pilot(
        ddp=FakeDdp(precisions=["int8"]), repromote_windows=30
    )
    pilot._last_wire_step = 90
    pilot.tick(None, step=100, loss=1.0)
    assert pilot.decisions == []  # only 10 steps since the incident
    pilot.tick(None, step=120, loss=1.0)
    assert pilot.decisions[-1]["decision"] == "repromote_precision"


def test_applied_switch_rebaselines_the_sentinel():
    calls = []
    pilot, sentinel = _pilot()
    sentinel.rebaseline = lambda wire_ms=None: calls.append(wire_ms)
    sentinel.incidents.extend([_incident(), _incident()])
    pilot.tick(None, step=10, loss=1.0)
    # the budget's wire expectation is re-priced to the adopted (int8)
    # configuration's modeled wire at nominal bandwidth
    from bagua_tpu.autopilot import wire_ms as model_wire
    (priced,) = calls
    assert priced == pytest.approx(model_wire(
        COST_MODEL, PLAN, 8, Configuration(precision="int8")
    ))


# -- axis-scoped pricing ------------------------------------------------------

# the same flip regime, on a dp4xtp2-shaped mesh: the gradient exchange
# rides dp (the flat/qr legs), while tp keeps its own fitted leg
AXIS_COST_MODEL = CostModel(
    flat=AlphaBeta(50e-6, 40e9), qr8=AlphaBeta(60e-6, 90e9),
    axis_legs={"dp": AlphaBeta(50e-6, 40e9), "tp": AlphaBeta(10e-6, 100e9)},
)


def test_degraded_cost_model_is_axis_scoped():
    # a model-axis (tp) incident leaves every exchange leg untouched and
    # degrades only the indicted axis's own leg
    d = degraded_cost_model(AXIS_COST_MODEL, 10.0, axis="tp",
                            exchange_axes=("dp",))
    assert d.flat.beta == AXIS_COST_MODEL.flat.beta
    assert d.qr8.beta == AXIS_COST_MODEL.qr8.beta
    assert d.axis_legs["dp"].beta == AXIS_COST_MODEL.axis_legs["dp"].beta
    assert d.axis_legs["tp"].beta == pytest.approx(
        AXIS_COST_MODEL.axis_legs["tp"].beta / 10.0)
    assert d.axis_legs["tp"].alpha == AXIS_COST_MODEL.axis_legs["tp"].alpha
    # a data-axis (dp) incident degrades the exchange legs (that IS the
    # exchange's bandwidth) plus dp's leg, and still spares tp's
    d = degraded_cost_model(AXIS_COST_MODEL, 10.0, axis="dp",
                            exchange_axes=("dp",))
    assert d.flat.beta == pytest.approx(AXIS_COST_MODEL.flat.beta / 10.0)
    assert d.qr8.beta == pytest.approx(AXIS_COST_MODEL.qr8.beta / 10.0)
    assert d.axis_legs["dp"].beta == pytest.approx(
        AXIS_COST_MODEL.axis_legs["dp"].beta / 10.0)
    assert d.axis_legs["tp"].beta == AXIS_COST_MODEL.axis_legs["tp"].beta
    # unscoped (legacy) keeps degrading everything
    d = degraded_cost_model(AXIS_COST_MODEL, 10.0)
    assert d.flat.beta == pytest.approx(AXIS_COST_MODEL.flat.beta / 10.0)
    assert d.axis_legs["tp"].beta == pytest.approx(
        AXIS_COST_MODEL.axis_legs["tp"].beta / 10.0)


def test_pricing_ranking_frozen_under_model_axis_collapse():
    """The ranking flips only when the indicted axis carries the gradient
    exchange: a tp/ICI brownout cannot be fixed by demoting the dp wire."""
    cands = candidate_configurations(("gradient_allreduce",), ("f32", "int8"))
    tp = price_configurations(AXIS_COST_MODEL, PLAN, 8, cands, 1.0,
                              bandwidth_factor=10.0, axis="tp",
                              exchange_axes=("dp",))
    assert tp[0][0].precision == "f32"
    dp = price_configurations(AXIS_COST_MODEL, PLAN, 8, cands, 1.0,
                              bandwidth_factor=10.0, axis="dp",
                              exchange_axes=("dp",))
    assert dp[0][0].precision == "int8"


def test_axis_scoped_incidents_hold_on_tp_demote_on_dp():
    ddp = FakeDdp()
    ddp.group = SimpleNamespace(exchange_size=8, data_axes=("dp",))
    sentinel = SimpleNamespace(incidents=[], plan_version=0, budget=None)
    pilot = GangAutopilot(
        ddp, AXIS_COST_MODEL,
        AutopilotConfig(compute_ms=1.0, algorithms=("gradient_allreduce",),
                        canary_steps=3),
        sentinel=sentinel, health=FakeHealth(),
    )
    # tp collapse: past hysteresis, but the exchange's economics are
    # untouched -> an explicit hold citing the indicted axis
    sentinel.incidents.extend(
        [dict(_incident("tr-a"), axis="tp"), dict(_incident("tr-b"), axis="tp")]
    )
    pilot.tick(None, step=10, loss=1.0)
    row = pilot.decisions[-1]
    assert row["decision"] == "hold" and row["verdict"] == "held"
    assert row["axis"] == "tp"
    assert ddp.precision_applies == []
    assert validate_metrics_event(row) == []
    # dp collapse: the exchange IS the indicted traffic -> demote; the
    # canary row and its commit both carry the axis
    sentinel.incidents.extend(
        [dict(_incident("tr-c"), axis="dp"), dict(_incident("tr-d"), axis="dp")]
    )
    pilot.tick(None, step=11, loss=1.0)
    row = pilot.decisions[-1]
    assert row["decision"] == "demote_precision" and row["verdict"] == "canary"
    assert row["axis"] == "dp"
    assert ddp.impl.bucket_precisions(PLAN) == ["int8"]
    for s in range(12, 16):
        pilot.tick(None, step=s, loss=1.0)
    assert pilot.decisions[-1]["verdict"] == "committed"
    assert pilot.decisions[-1]["axis"] == "dp"
    for r in pilot.decisions:
        assert validate_metrics_event(r) == []


def test_axis_blind_incident_keeps_legacy_demotion():
    """No axis on the incident (legacy 1-D gang): the whole-model
    degradation still flips the ranking and demotes."""
    pilot, sentinel = _pilot()
    sentinel.incidents.extend([_incident("tr-a"), _incident("tr-b")])
    pilot.tick(None, step=10, loss=1.0)
    row = pilot.decisions[-1]
    assert row["decision"] == "demote_precision"
    assert "axis" not in row


# -- the staleness director ---------------------------------------------------


from bagua_tpu.autopilot import (  # noqa: E402
    StalenessConfig,
    StalenessDirector,
    StalenessTightenAction,
    modeled_step_ms,
)
from bagua_tpu.observability.attribution import BudgetModel  # noqa: E402


class FakeStaleImpl:
    algo_name = "stale"
    hierarchical = False

    def __init__(self, tau=0):
        self.staleness_tau = tau

    def set_staleness_tau(self, tau):
        self.staleness_tau = int(tau)


class FakeStaleDdp:
    def __init__(self, tau=0):
        self.impl = FakeStaleImpl(tau)
        self.plan = PLAN
        self.plan_version = 0
        self.group = SimpleNamespace(exchange_size=8)
        self.staleness_switches = []
        self.directives = []
        self.resets = 0

    def apply_staleness(self, tau, reason=None):
        old = self.impl.staleness_tau
        self.impl.set_staleness_tau(tau)
        if old == int(tau):
            return False
        self.plan_version += 1
        self.staleness_switches.append((int(tau), reason))
        return True

    def apply_degradation_directive(self, state, ranks):
        self.directives.append(tuple(int(r) for r in ranks))
        return state

    def reset_staleness_state(self, state):
        self.resets += 1
        return state


class FakeStaleSentinel:
    def __init__(self):
        self.incidents = []
        self.degraded = None
        self.budget = SimpleNamespace(compute_ms=8.0)

    def mark_degraded(self, ranks):
        self.degraded = tuple(ranks)


def _straggler_incident(trace="tr-s", rank=2, excess=4.0, step=0):
    return {
        "dominant": "straggler", "straggler_rank": rank, "trace_id": trace,
        "step": step, "plan_version": 0,
        "components": {"straggler": excess},
        "measured_ms": 14.0, "expected_ms": 10.0,
    }


def _director(tau=2, health=None, **cfg):
    cfg.setdefault("hysteresis_incidents", 2)
    cfg.setdefault("cooldown_steps", 0)
    cfg.setdefault("heal_patience", 10**6)
    ddp = FakeStaleDdp()
    sent = FakeStaleSentinel()
    health = health or FakeHealth()
    d = StalenessDirector(
        ddp, StalenessConfig(tau=tau, **cfg), sentinel=sent, health=health,
    )
    return d, ddp, sent, health


def test_director_single_incident_held_by_hysteresis():
    d, ddp, sent, _ = _director()
    sent.incidents.append(_straggler_incident())
    d.tick(None, step=10)
    assert d.decisions == [] and ddp.staleness_switches == []
    assert d.degraded_ranks == ()


def test_director_degrades_with_trace_rank_and_reprime():
    d, ddp, sent, _ = _director()
    sent.incidents.extend([
        _straggler_incident("tr-a"), _straggler_incident("tr-b"),
    ])
    d.tick(None, step=10)
    # one τ switch + one state re-prime (fresh first round) + the directive
    assert ddp.staleness_switches == [(2, "autopilot:straggler")]
    assert ddp.resets == 1
    assert ddp.directives == [(2,)]
    assert d.degraded_ranks == (2,)
    assert sent.degraded == (2,)  # budget paces the gang at its median
    (row,) = d.decisions
    assert row["decision"] == "degrade_staleness"
    assert row["verdict"] == "committed"
    assert row["reason"] == "autopilot:straggler"
    assert row["trace_id"] == "tr-b"  # cites the adjudicated incident
    assert row["ranks"] == [2]
    assert row["to_config"]["staleness"] == 2
    assert validate_metrics_event(row) == []


def test_director_wire_incidents_are_not_straggler_evidence():
    d, ddp, sent, _ = _director()
    sent.incidents.extend([_incident("tr-1"), _incident("tr-2")])
    d.tick(None, step=10)
    assert d.decisions == [] and ddp.directives == []


def test_director_degrade_requires_current_health():
    d, ddp, sent, _ = _director(health=FakeHealth(clean_streak=0))
    sent.incidents.extend([
        _straggler_incident("tr-a"), _straggler_incident("tr-b"),
    ])
    d.tick(None, step=10)
    (row,) = d.decisions
    assert row["decision"] == "hold" and row["verdict"] == "held"
    assert ddp.staleness_switches == [] and ddp.directives == []


def test_director_cooldown_blocks_further_moves():
    d, ddp, sent, _ = _director(cooldown_steps=100)
    sent.incidents.extend([
        _straggler_incident("tr-a"), _straggler_incident("tr-b"),
    ])
    d.tick(None, step=10)
    assert d.degraded_ranks == (2,)
    sent.incidents.extend([
        _straggler_incident("tr-c", rank=3), _straggler_incident("tr-d", rank=3),
    ])
    d.tick(None, step=20)  # inside the cooldown: rank 3 must wait
    assert d.degraded_ranks == (2,)
    sent.incidents.extend([
        _straggler_incident("tr-e", rank=3), _straggler_incident("tr-f", rank=3),
    ])
    d.tick(None, step=120)
    assert d.degraded_ranks == (2, 3)
    assert ddp.directives[-1] == (2, 3)


def test_tighten_action_snaps_to_zero_and_noops_at_zero():
    ddp = FakeStaleDdp(tau=2)
    action = StalenessTightenAction(ddp)
    assert action({"kind": "loss_spike"}) is True
    assert ddp.impl.staleness_tau == 0
    assert ddp.staleness_switches == [(0, "health:loss_spike")]
    # already bulk-sync: the guardrail has nothing to tighten
    assert action({"kind": "loss_spike"}) is False
    assert len(ddp.staleness_switches) == 1
    # no staleness knob at all: clean False, no throw
    assert StalenessTightenAction(FakeDdp())({"kind": "loss_spike"}) is False


def test_director_adopts_external_tighten_then_repromotes():
    d, ddp, sent, health = _director(repromote_windows=5)
    sent.incidents.extend([
        _straggler_incident("tr-a"), _straggler_incident("tr-b"),
    ])
    d.tick(None, step=10)
    assert d.current_tau() == 2

    # the registered guardrail action fires outside the ladder...
    StalenessTightenAction(ddp)({"kind": "loss_spike"})
    health.clean_streak = 0
    d.tick(None, step=20)
    assert d.report()["tightened"] is True  # adopted, no decision forged
    assert ddp.staleness_switches[-1] == (0, "health:loss_spike")

    # ...and after the stabilization arc the degradation gets τ back
    health.clean_streak = 10**6
    d.tick(None, step=30)
    assert ddp.staleness_switches[-1] == (2, "autopilot:stabilized")
    assert ddp.resets == 2  # replay state re-primed on the re-raise too
    assert health.rearmed == 1
    row = d.decisions[-1]
    assert row["decision"] == "repromote_staleness"
    assert row["verdict"] == "committed"
    assert d.report()["tightened"] is False
    assert d.degraded_ranks == (2,)  # the directive never lapsed


def test_director_tightens_on_anomaly_without_registered_action():
    d, ddp, sent, health = _director()
    sent.incidents.extend([
        _straggler_incident("tr-a"), _straggler_incident("tr-b"),
    ])
    d.tick(None, step=10)
    health.clean_streak = 0
    d.tick(None, step=11)
    assert ddp.staleness_switches[-1] == (0, "health:anomaly")
    row = d.decisions[-1]
    assert row["decision"] == "tighten_staleness"
    assert row["verdict"] == "committed"


def test_director_heals_after_patience():
    d, ddp, sent, _ = _director(heal_patience=50)
    sent.incidents.extend([
        _straggler_incident("tr-a", step=8), _straggler_incident("tr-b", step=10),
    ])
    d.tick(None, step=10)
    assert d.degraded_ranks == (2,)
    d.tick(None, step=40)  # patience not yet elapsed
    assert d.degraded_ranks == (2,)
    d.tick(None, step=70)
    assert d.degraded_ranks == ()
    assert ddp.staleness_switches[-1] == (0, "autopilot:straggler_healed")
    assert ddp.directives[-1] == ()
    assert sent.degraded == ()  # budget back to worst-rank pacing
    row = d.decisions[-1]
    assert row["decision"] == "restore_bulk_sync"
    assert row["verdict"] == "committed"
    assert row["ranks"] == [2]


def test_director_modeled_block_prices_staleness():
    d, ddp, sent, _ = _director()
    d.cost_model = COST_MODEL
    sent.incidents.extend([
        _straggler_incident("tr-a", excess=6.0),
        _straggler_incident("tr-b", excess=6.0),
    ])
    d.tick(None, step=10)
    modeled = d.decisions[-1]["modeled"]
    assert modeled["straggler_excess_ms"] == pytest.approx(6.0)
    # τ=2 amortizes the excess to a third: strictly cheaper than staying
    assert modeled["chosen_ms"] < modeled["stay_ms"]


def test_director_drain_decisions_is_incremental():
    d, ddp, sent, _ = _director()
    sent.incidents.extend([
        _straggler_incident("tr-a"), _straggler_incident("tr-b"),
    ])
    d.tick(None, step=10)
    first = d.drain_decisions()
    assert [r["decision"] for r in first] == ["degrade_staleness"]
    assert d.drain_decisions() == []


# -- staleness pricing + budget pacing ----------------------------------------


def test_modeled_step_ms_amortizes_straggler_excess():
    def price(tau):
        return modeled_step_ms(
            COST_MODEL, PLAN, 8,
            Configuration(algorithm="stale", precision="f32", staleness=tau),
            1.0, straggler_excess_ms=6.0,
        )

    assert price(2) == pytest.approx(price(0) - 4.0)  # 6 -> 6/(τ+1)
    assert price(1) == pytest.approx(price(0) - 3.0)
    # no excess, no discount: staleness is never a win on a healthy gang
    healthy = modeled_step_ms(
        COST_MODEL, PLAN, 8,
        Configuration(algorithm="stale", precision="f32", staleness=2), 1.0,
    )
    assert healthy == pytest.approx(modeled_step_ms(
        COST_MODEL, PLAN, 8,
        Configuration(algorithm="stale", precision="f32", staleness=0), 1.0,
    ))


def test_candidate_configurations_staleness_composes_only_with_the_knob():
    cands = candidate_configurations(
        ("gradient_allreduce", "stale"), ("f32", "int8"), staleness_taus=(0, 2)
    )
    labels = {c.label() for c in cands}
    assert "stale/f32/tau=2" in labels
    assert "gradient_allreduce/int8" in labels
    # no τ>0 on algorithms without the knob, no quantized staleness
    assert not any(
        c.staleness and c.algorithm == "gradient_allreduce" for c in cands
    )
    assert all(c.precision == "f32" for c in cands if c.algorithm == "stale")
    # the tie-break: equal price prefers lower τ (no free convergence tax)
    priced = price_configurations(
        COST_MODEL, PLAN, 8,
        candidate_configurations(("stale",), ("f32",), staleness_taus=(2, 0)),
        1.0,
    )
    assert priced[0][0].staleness == 0


def test_budget_drops_straggler_evidence_for_degraded_ranks():
    bm = BudgetModel(compute_ms=8.0, wire_ms=2.0)
    bm.note_straggler(5.0, rank=2)
    row = bm.settle(0, 15.0)
    assert row.components["straggler"] == pytest.approx(5.0)
    assert row.straggler_rank == 2
    # under a degradation directive the gang paces at its median: the
    # indicted rank's excess is expected, not budgetable evidence
    bm.mark_degraded((2,))
    bm.note_straggler(5.0, rank=2)
    row = bm.settle(1, 10.0)
    assert row.components["straggler"] == 0.0
    assert row.straggler_rank == -1
    # other ranks still charge; clearing the directive restores rank 2
    bm.note_straggler(4.0, rank=1)
    assert bm.settle(2, 14.0).components["straggler"] == pytest.approx(4.0)
    bm.mark_degraded(())
    bm.note_straggler(5.0, rank=2)
    assert bm.settle(3, 15.0).components["straggler"] == pytest.approx(5.0)
