"""BucketPlan unit tests: round-trip, dtype grouping, size splitting, alignment."""

import jax
import jax.numpy as jnp
import numpy as np

from bagua_tpu.bucket import BucketPlan, tree_leaf_names
from bagua_tpu.defs import TensorDeclaration


def sample_tree():
    return {
        "a": jnp.arange(6.0).reshape(2, 3),
        "b": {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))},
        "c": jnp.full((5,), 2.0),
    }


def test_roundtrip_identity():
    tree = sample_tree()
    plan = BucketPlan.from_tree(tree, bucket_size_bytes=1 << 20)
    flats = plan.bucketize(tree)
    back = plan.debucketize(flats)
    assert jax.tree.structure(back) == jax.tree.structure(tree)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bucket_size_splitting():
    tree = {"x": jnp.zeros((100,)), "y": jnp.zeros((100,)), "z": jnp.zeros((100,))}
    # 100 floats = 400 bytes; budget 500 bytes -> one tensor per bucket
    plan = BucketPlan.from_tree(tree, bucket_size_bytes=500)
    assert plan.num_buckets == 3
    # huge budget -> single bucket
    plan = BucketPlan.from_tree(tree, bucket_size_bytes=1 << 20)
    assert plan.num_buckets == 1
    assert plan.specs[0].numel == 300


def test_dtype_grouping():
    tree = {"f": jnp.zeros((10,), jnp.float32), "i": jnp.zeros((10,), jnp.int32),
            "g": jnp.ones((10,), jnp.float32)}
    plan = BucketPlan.from_tree(tree, bucket_size_bytes=1 << 20)
    dtypes = sorted(s.dtype for s in plan.specs)
    assert dtypes == ["f32", "i32"]
    flats = plan.bucketize(tree)
    back = plan.debucketize(flats)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_alignment_padding():
    tree = {"x": jnp.arange(10.0)}
    plan = BucketPlan.from_tree(tree, bucket_size_bytes=1 << 20, align_elems=8)
    assert plan.specs[0].numel == 16
    flats = plan.bucketize(tree)
    assert flats[0].shape == (16,)
    np.testing.assert_array_equal(np.asarray(flats[0][10:]), np.zeros(6))
    back = plan.debucketize(flats)
    np.testing.assert_array_equal(np.asarray(back["x"]), np.arange(10.0))


def test_from_declarations_matches_autotune_format():
    tree = sample_tree()
    names = tree_leaf_names(tree)
    # Autotune proposes: every tensor alone in its own bucket.
    ref = BucketPlan.from_tree(tree, bucket_size_bytes=1)
    decls = [[td for td in bucket] for bucket in ref.declarations()]
    plan = BucketPlan.from_declarations(decls, tree)
    assert plan.num_buckets == len(names)
    back = plan.debucketize(plan.bucketize(tree))
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bucketize_traceable():
    tree = sample_tree()
    plan = BucketPlan.from_tree(tree, bucket_size_bytes=1 << 20)

    @jax.jit
    def roundtrip(t):
        return plan.debucketize(plan.bucketize(t))

    back = roundtrip(tree)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
