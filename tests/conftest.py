"""Test fixture: simulate an 8-device TPU pod slice with CPU devices.

Mirrors the reference's multi-worker-on-one-host simulation strategy
(reference ``tests/internal/multi_process.py:9-52`` spawns N processes, one
per CUDA device).  On TPU/JAX the analog is a single process with N virtual
devices: we force the host platform to expose 8 CPU devices and run every
sharded computation over a real ``jax.sharding.Mesh``, so collectives execute
with genuine SPMD semantics.
"""

import os

os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

# The axon TPU plugin (single real chip) registers itself via sitecustomize and
# overrides JAX_PLATFORMS; tests want the 8-device CPU simulation instead.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

# Persistent compilation cache: the suite's wall time is dominated by XLA
# compiles, most of which are identical run to run.  Keyed per backend by
# JAX itself; shared with the bench scripts' cache dir.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
)
jax.config.update("jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture()
def group():
    import bagua_tpu

    return bagua_tpu.init_process_group(intra_size=4)
