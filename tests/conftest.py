"""Test fixture: simulate an 8-device TPU pod slice with CPU devices.

Mirrors the reference's multi-worker-on-one-host simulation strategy
(reference ``tests/internal/multi_process.py:9-52`` spawns N processes, one
per CUDA device).  On TPU/JAX the analog is a single process with N virtual
devices: we force the host platform to expose 8 CPU devices and run every
sharded computation over a real ``jax.sharding.Mesh``, so collectives execute
with genuine SPMD semantics.
"""

import os

os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

# The axon TPU plugin (single real chip) registers itself via sitecustomize and
# overrides JAX_PLATFORMS; tests want the 8-device CPU simulation instead.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture()
def group():
    import bagua_tpu

    return bagua_tpu.init_process_group(intra_size=4)
