"""ByteGrad + MinMaxUInt8 correctness.

The numpy oracle reimplements the published MinMaxUInt8 semantics (the
reference ships a pure-torch oracle at ``tests/internal/compressor.py:4-33``
for the same purpose); the compressed-allreduce pipeline is checked against a
full numpy simulation, and DDP training asserts cross-rank bitwise equality.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms.bytegrad import ByteGradAlgorithm, compressed_allreduce
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.communication import ALL_AXES
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.kernels.minmax_uint8 import (
    compress_minmax_uint8,
    decompress_minmax_uint8,
    compress_minmax_uint8_pallas,
    decompress_minmax_uint8_pallas,
    decompress_reduce_requantize,
    decompress_reduce_requantize_pallas,
)
from bagua_tpu.models.mlp import init_mlp, mse_loss
from jax.sharding import PartitionSpec as P

from tests.oracles import oracle_compress, oracle_decompress


def test_compress_matches_oracle():
    rng = np.random.RandomState(0)
    chunks = rng.randn(4, 256).astype(np.float32) * 5.0
    q, mm = compress_minmax_uint8(jnp.asarray(chunks))
    oq, omm = oracle_compress(chunks)
    np.testing.assert_array_equal(np.asarray(q), oq)
    np.testing.assert_allclose(np.asarray(mm), omm, rtol=1e-6)
    x = decompress_minmax_uint8(q, mm)
    np.testing.assert_allclose(np.asarray(x), oracle_decompress(oq, omm), rtol=1e-5)


def test_compression_error_bound():
    rng = np.random.RandomState(1)
    chunks = rng.randn(2, 1024).astype(np.float32)
    q, mm = compress_minmax_uint8(jnp.asarray(chunks))
    x = np.asarray(decompress_minmax_uint8(q, mm))
    # max error is about one quantization level
    level = (chunks.max(1) - chunks.min(1)) / 255.0
    assert np.abs(x - chunks).max() <= level.max() * 1.01


def test_pallas_matches_xla_interpret():
    rng = np.random.RandomState(2)
    chunks = rng.randn(4, 128).astype(np.float32)
    q_ref, mm_ref = compress_minmax_uint8(jnp.asarray(chunks))
    q, mm = compress_minmax_uint8_pallas(jnp.asarray(chunks), interpret=True)
    np.testing.assert_array_equal(np.asarray(q), np.asarray(q_ref))
    np.testing.assert_allclose(np.asarray(mm), np.asarray(mm_ref), rtol=1e-6)
    x_ref = decompress_minmax_uint8(q_ref, mm_ref)
    x = decompress_minmax_uint8_pallas(q, mm, interpret=True)
    np.testing.assert_allclose(np.asarray(x), np.asarray(x_ref), rtol=1e-6)


@pytest.mark.parametrize(
    "shape", [(3, 100), (2, 4095), (1, 7), (5, 129)],
    ids=["3x100", "2x4095", "1x7", "5x129"],
)
def test_pallas_parity_unaligned_chunks(shape):
    """Chunk sizes that are NOT multiples of the Pallas row alignment (128
    lanes × 32 rows) must still agree bitwise with the jnp compressor: the
    Pallas wrappers fall back to the jnp path for unsupported shapes, and
    that fallback must be invisible at the byte level."""
    rng = np.random.RandomState(5)
    chunks = (rng.randn(*shape).astype(np.float32) * 3.0)
    q_ref, mm_ref = compress_minmax_uint8(jnp.asarray(chunks))
    q, mm = compress_minmax_uint8_pallas(jnp.asarray(chunks), interpret=True)
    np.testing.assert_array_equal(np.asarray(q), np.asarray(q_ref))
    np.testing.assert_allclose(np.asarray(mm), np.asarray(mm_ref), rtol=1e-6)
    x_ref = np.asarray(decompress_minmax_uint8(q_ref, mm_ref))
    x = np.asarray(decompress_minmax_uint8_pallas(q, mm, interpret=True))
    assert not np.isnan(x).any()
    np.testing.assert_allclose(x, x_ref, rtol=1e-6)


@pytest.mark.parametrize("value", [0.0, 2.5, -7.0], ids=["zero", "pos", "neg"])
def test_constant_chunk_roundtrip(value):
    """A constant chunk hits the mn == mx degenerate branch: the EPS guard
    keeps the scale finite, both backends emit identical uint8, and the
    round-trip reproduces the constant without NaNs."""
    chunks = np.full((2, 4096), value, np.float32)
    q_ref, mm_ref = compress_minmax_uint8(jnp.asarray(chunks))
    q, mm = compress_minmax_uint8_pallas(jnp.asarray(chunks), interpret=True)
    np.testing.assert_array_equal(np.asarray(q), np.asarray(q_ref))
    np.testing.assert_allclose(np.asarray(mm), np.asarray(mm_ref), rtol=1e-6)
    x = np.asarray(decompress_minmax_uint8(q_ref, mm_ref))
    assert not np.isnan(x).any()
    np.testing.assert_allclose(x, chunks, atol=1e-4)


@pytest.mark.parametrize(
    "value", [1e32, -1e35, 3.4e38], ids=["1e32", "-1e35", "f32max"]
)
def test_constant_chunk_huge_magnitude(value):
    """Constant blocks at huge magnitude: the EPS term alone leaves
    ``mx * scale`` overflowing f32 to inf, and ``upper - lower`` becomes NaN.
    The bounded-denominator scale keeps everything finite: q degenerates to 0
    (the 255-level offset is absorbed by the huge bounds) and the round-trip
    reconstructs the constant to f32 rounding, on both backends and in the
    numpy oracle — bitwise-identical q between all three."""
    chunks = np.full((2, 4096), value, np.float32)
    q_ref, mm_ref = compress_minmax_uint8(jnp.asarray(chunks))
    q_pl, mm_pl = compress_minmax_uint8_pallas(jnp.asarray(chunks), interpret=True)
    oq, omm = oracle_compress(chunks)
    np.testing.assert_array_equal(np.asarray(q_ref), oq)
    np.testing.assert_array_equal(np.asarray(q_pl), oq)
    assert (np.asarray(q_ref) == 0).all()
    for dec in (
        np.asarray(decompress_minmax_uint8(q_ref, mm_ref)),
        np.asarray(decompress_minmax_uint8_pallas(q_pl, mm_pl, interpret=True)),
        oracle_decompress(oq, omm),
    ):
        assert np.isfinite(dec).all()
        np.testing.assert_allclose(dec, chunks, rtol=1e-6)


def test_mixed_constant_and_varying_chunks():
    """The scale bound is per-chunk: a batch mixing in-range chunks with an
    overflow-prone constant one must quantize the former bitwise as the
    unguarded scheme would (the guard terms vanish in f32 rounding) while
    keeping the latter finite, bitwise across backends and vs the numpy
    oracle.  The all-zero chunk round-trips exactly (its scale is the plain
    255 / EPS, and q + lower is exactly zero)."""
    rng = np.random.RandomState(8)
    chunks = rng.randn(4, 4096).astype(np.float32)
    chunks[1] = 0.0
    chunks[3] = -2.5e33  # degenerate AND overflow-prone
    q_ref, mm_ref = compress_minmax_uint8(jnp.asarray(chunks))
    q_pl, mm_pl = compress_minmax_uint8_pallas(jnp.asarray(chunks), interpret=True)
    oq, omm = oracle_compress(chunks)
    np.testing.assert_array_equal(np.asarray(q_ref), oq)
    np.testing.assert_array_equal(np.asarray(q_pl), oq)
    dec = np.asarray(decompress_minmax_uint8(q_ref, mm_ref))
    assert not np.isnan(dec).any()
    np.testing.assert_array_equal(dec[1], chunks[1])
    np.testing.assert_allclose(dec[3], chunks[3], rtol=1e-6)
    level = (chunks[0].max() - chunks[0].min()) / 255.0
    assert np.abs(dec[0] - chunks[0]).max() <= level * 1.01


@pytest.mark.parametrize(
    "shape", [(3, 100), (1, 7), (5, 129)], ids=["3x100", "1x7", "5x129"]
)
def test_constant_unaligned_last_block_shapes(shape):
    """Degenerate blocks at shapes the Pallas kernels can't tile (unaligned
    last-block sizes): the jnp fallback must apply the same bounded scale,
    bitwise vs the oracle, with no NaNs."""
    chunks = np.full(shape, 1.7e33, np.float32)
    q_ref, mm_ref = compress_minmax_uint8(jnp.asarray(chunks))
    q_pl, mm_pl = compress_minmax_uint8_pallas(jnp.asarray(chunks), interpret=True)
    oq, omm = oracle_compress(chunks)
    np.testing.assert_array_equal(np.asarray(q_ref), oq)
    np.testing.assert_array_equal(np.asarray(q_pl), oq)
    dec = np.asarray(decompress_minmax_uint8_pallas(q_pl, mm_pl, interpret=True))
    assert np.isfinite(dec).all()
    np.testing.assert_allclose(dec, chunks, rtol=1e-6)


def test_fused_reducer_huge_constant_no_nan():
    """The fused dequant-reduce-requant hits the degenerate regime twice —
    on the incoming per-peer minmax and on the reduced chunk's requantize.
    Huge-magnitude constants must survive both without NaN, bitwise between
    the jnp composition and the Pallas kernel."""
    const = jnp.full((4, 4096), 8.8e33, jnp.float32)
    qc, mmc = compress_minmax_uint8(const)
    q_j, mm_j = decompress_reduce_requantize(qc, mmc, average=True)
    q_p, mm_p = decompress_reduce_requantize_pallas(
        qc, mmc, average=True, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(q_p), np.asarray(q_j))
    np.testing.assert_allclose(np.asarray(mm_p), np.asarray(mm_j), rtol=1e-6)
    out = np.asarray(decompress_minmax_uint8(q_j, mm_j))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out, np.full((1, 4096), 8.8e33, np.float32),
                               rtol=1e-6)


@pytest.mark.parametrize("average", [True, False], ids=["avg", "sum"])
def test_fused_reducer_matches_staged_composition(average):
    """``decompress_reduce_requantize`` fuses ByteGrad's middle three stages.
    Its jnp oracle IS the staged composition (same ops, same order), and the
    Pallas kernel must match it bitwise on the requantized payload — a
    single differing byte would desync the subsequent all-gather."""
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(4, 4096).astype(np.float32))
    q, mm = compress_minmax_uint8(x)
    # staged: decompress → tree-sum → (÷n) → compress
    dec = decompress_minmax_uint8(q, mm)
    red = jnp.sum(dec, axis=0, keepdims=True)
    if average:
        red = red / q.shape[0]
    q_staged, mm_staged = compress_minmax_uint8(red)
    q_fused, mm_fused = decompress_reduce_requantize(q, mm, average=average)
    np.testing.assert_array_equal(np.asarray(q_fused), np.asarray(q_staged))
    np.testing.assert_allclose(
        np.asarray(mm_fused), np.asarray(mm_staged), rtol=1e-6
    )
    q_pl, mm_pl = decompress_reduce_requantize_pallas(
        q, mm, average=average, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(q_pl), np.asarray(q_staged))
    np.testing.assert_allclose(
        np.asarray(mm_pl), np.asarray(mm_staged), rtol=1e-6
    )


def test_fused_reducer_unaligned_and_constant():
    """Fallback + degenerate coverage for the fused reducer: unaligned chunk
    sizes route to the jnp path bitwise-transparently, and all-constant
    inputs (mn == mx after reduction) survive requantization without NaNs."""
    rng = np.random.RandomState(7)
    q, mm = compress_minmax_uint8(jnp.asarray(rng.randn(3, 100), jnp.float32))
    q_j, mm_j = decompress_reduce_requantize(q, mm, average=True)
    q_p, mm_p = decompress_reduce_requantize_pallas(q, mm, average=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(q_p), np.asarray(q_j))
    np.testing.assert_allclose(np.asarray(mm_p), np.asarray(mm_j), rtol=1e-6)

    const = jnp.full((4, 4096), 1.5, jnp.float32)
    qc, mmc = compress_minmax_uint8(const)
    q2, mm2 = decompress_reduce_requantize_pallas(
        qc, mmc, average=True, interpret=True
    )
    out = np.asarray(decompress_minmax_uint8(q2, mm2))
    assert not np.isnan(out).any()
    np.testing.assert_allclose(out, 1.5, atol=1e-2)


def oracle_compressed_allreduce(per_rank: np.ndarray, average=True):
    """Numpy simulation of compress→a2a→decompress→reduce→compress→allgather."""
    n, numel = per_rank.shape
    chunk = numel // n
    # every rank compresses its own data per destination chunk
    qs, mms = [], []
    for r in range(n):
        q, mm = oracle_compress(per_rank[r].reshape(n, chunk))
        qs.append(q)
        mms.append(mm)
    # rank r receives chunk r from everyone, decompresses, reduces
    reduced = []
    for r in range(n):
        acc = np.zeros((chunk,), np.float32)
        for s in range(n):
            acc += oracle_decompress(qs[s][r : r + 1], mms[s][r : r + 1])[0]
        if average:
            acc /= n
        reduced.append(acc)
    # each rank compresses its reduced chunk; allgather; decompress
    out = []
    for r in range(n):
        q, mm = oracle_compress(reduced[r][None])
        out.append(oracle_decompress(q, mm)[0])
    return np.tile(np.concatenate(out)[None], (n, 1))


def test_compressed_allreduce_matches_oracle(group):
    rng = np.random.RandomState(3)
    n = group.size
    per_rank = rng.randn(n, n * 32).astype(np.float32)

    fn = jax.jit(
        group.shard_map(
            lambda x: compressed_allreduce(x[0], ALL_AXES, average=True)[None],
            in_specs=P(ALL_AXES),
            out_specs=P(ALL_AXES),
        )
    )
    got = np.asarray(fn(jnp.asarray(per_rank)))
    expect = oracle_compressed_allreduce(per_rank, average=True)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("hierarchical", [False, True])
def test_bytegrad_training(group, hierarchical):
    params = init_mlp(jax.random.PRNGKey(11), [12, 16, 4])
    rng = np.random.RandomState(4)
    ddp = DistributedDataParallel(
        mse_loss,
        optax.sgd(0.05),
        ByteGradAlgorithm(hierarchical=hierarchical),
        process_group=group,
    )
    ref = DistributedDataParallel(
        mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(), process_group=group
    )
    state = ddp.init(params)
    ref_state = ref.init(params)
    for i in range(10):
        batch = (
            jnp.asarray(rng.randn(32, 12), np.float32),
            jnp.asarray(rng.randn(32, 4), np.float32),
        )
        state, losses = ddp.train_step(state, batch)
        ref_state, ref_losses = ref.train_step(ref_state, batch)

    # weights bitwise-identical across ranks
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, state.params)):
        for r in range(1, group.size):
            np.testing.assert_array_equal(leaf[0], leaf[r])

    # and close to the uncompressed run (quantization noise only)
    for a, b in zip(
        jax.tree.leaves(ddp.params_unstacked(state)),
        jax.tree.leaves(ref.params_unstacked(ref_state)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0.05)
