"""Pipeline parallelism: forward equals sequential stage application; grads
flow through the pipeline schedule correctly; 1F1B matches GPipe's values
with bounded memory."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bagua_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_loss,
    pipeline_train_1f1b,
)

STAGES = 4
MICRO = 6
MB, DIM = 3, 8


def stage_fn(params, x):
    return jax.nn.tanh(x @ params["w"] + params["b"])


def make_stage_params(seed):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(DIM, DIM).astype(np.float32) * 0.5),
        "b": jnp.asarray(rng.randn(DIM).astype(np.float32) * 0.1),
    }


def sequential_oracle(stages, microbatches):
    out = []
    for m in range(microbatches.shape[0]):
        x = microbatches[m]
        for p in stages:
            x = stage_fn(p, x)
        out.append(x)
    return jnp.stack(out)


@pytest.fixture()
def pp_mesh():
    return Mesh(np.array(jax.devices()[:STAGES]), ("pp",))


def test_pipeline_matches_sequential(pp_mesh):
    stages = [make_stage_params(s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    rng = np.random.RandomState(42)
    micro = jnp.asarray(rng.randn(MICRO, MB, DIM).astype(np.float32))

    expect = np.asarray(sequential_oracle(stages, micro))

    fn = jax.jit(
        jax.shard_map(
            lambda p, mb: pipeline_apply(
                stage_fn, jax.tree.map(lambda q: q[0], p), mb, axis_name="pp"
            ),
            mesh=pp_mesh,
            in_specs=(P("pp"), P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    got = np.asarray(fn(stacked, micro))
    np.testing.assert_allclose(got, expect, rtol=2e-4, atol=1e-5)


def test_pipeline_gradients(pp_mesh):
    """Gradient of a loss on pipeline outputs matches the sequential oracle's
    gradient for each stage's parameters."""
    stages = [make_stage_params(10 + s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    rng = np.random.RandomState(7)
    micro = jnp.asarray(rng.randn(MICRO, MB, DIM).astype(np.float32))
    target = jnp.asarray(rng.randn(MICRO, MB, DIM).astype(np.float32))

    def oracle_loss(stages_list):
        out = sequential_oracle(stages_list, micro)
        return jnp.mean((out - target) ** 2)

    expect_grads = jax.grad(lambda s: oracle_loss(s))(stages)

    def local_loss(stacked_params, mb):
        p_local = jax.tree.map(lambda q: q[0], stacked_params)
        out = pipeline_apply(stage_fn, p_local, mb, axis_name="pp")
        return jnp.mean((out - target) ** 2)

    grad_fn = jax.jit(
        jax.shard_map(
            lambda p, mb: jax.grad(local_loss)(p, mb),
            mesh=pp_mesh,
            in_specs=(P("pp"), P()),
            out_specs=P("pp"),
            check_vma=False,
        )
    )
    got = grad_fn(stacked, micro)
    for s in range(STAGES):
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(got[key][s]),
                np.asarray(expect_grads[s][key]),
                rtol=2e-3, atol=1e-4,
                err_msg=f"stage {s} {key}",
            )


def test_pipeline_single_stage_fallback():
    stages = make_stage_params(0)
    micro = jnp.asarray(np.random.RandomState(0).randn(4, MB, DIM).astype(np.float32))
    out = pipeline_apply(stage_fn, stages, micro, axis_name="pp")
    expect = jax.vmap(lambda x: stage_fn(stages, x))(micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=1e-6)


def mse(y, t):
    return jnp.mean((y - t) ** 2)


def _data(seed, n_micro=MICRO):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.randn(n_micro, MB, DIM).astype(np.float32)),
        jnp.asarray(rng.randn(n_micro, MB, DIM).astype(np.float32)),
    )


def _oracle_loss_and_grads(stages, micro, target):
    def total(stages_list):
        out = sequential_oracle(stages_list, micro)
        return jnp.mean(jax.vmap(mse)(out, target))

    return jax.value_and_grad(total)(stages)


def test_pipeline_loss_scalar_only(pp_mesh):
    """pipeline_loss equals the loss on pipeline_apply outputs, and its HLO
    carries no (n_micro, mb, dim) broadcast — only the scalar psum."""
    stages = [make_stage_params(20 + s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    micro, target = _data(21)

    def local(p, mb):
        p_local = jax.tree.map(lambda q: q[0], p)
        return pipeline_loss(stage_fn, p_local, mb, target, mse, axis_name="pp")

    fn = jax.jit(
        jax.shard_map(
            local, mesh=pp_mesh, in_specs=(P("pp"), P()), out_specs=P(),
            check_vma=False,
        )
    )
    expect, _ = _oracle_loss_and_grads(stages, micro, target)
    np.testing.assert_allclose(float(fn(stacked, micro)), float(expect), rtol=2e-4)
    # grads through pipeline_loss match the oracle too
    grad_fn = jax.jit(
        jax.shard_map(
            lambda p, mb: jax.grad(local)(p, mb), mesh=pp_mesh,
            in_specs=(P("pp"), P()), out_specs=P("pp"), check_vma=False,
        )
    )
    got = grad_fn(stacked, micro)
    _, expect_grads = _oracle_loss_and_grads(stages, micro, target)
    for s in range(STAGES):
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(got[key][s]), np.asarray(expect_grads[s][key]),
                rtol=2e-3, atol=1e-4, err_msg=f"stage {s} {key}",
            )


def test_1f1b_matches_sequential_oracle(pp_mesh):
    """1F1B loss and per-stage grads equal the sequential program's."""
    stages = [make_stage_params(30 + s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    micro, target = _data(31)

    def local(p, mb):
        p_local = jax.tree.map(lambda q: q[0], p)
        loss, grads = pipeline_train_1f1b(
            stage_fn, p_local, mb, target, mse, axis_name="pp"
        )
        return loss, jax.tree.map(lambda g: g[None], grads)

    fn = jax.jit(
        jax.shard_map(
            local, mesh=pp_mesh, in_specs=(P("pp"), P()),
            out_specs=(P(), P("pp")), check_vma=False,
        )
    )
    loss, grads = fn(stacked, micro)
    expect_loss, expect_grads = _oracle_loss_and_grads(stages, micro, target)
    np.testing.assert_allclose(float(loss), float(expect_loss), rtol=2e-4)
    for s in range(STAGES):
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(grads[key][s]), np.asarray(expect_grads[s][key]),
                rtol=2e-3, atol=1e-4, err_msg=f"stage {s} {key}",
            )


@pytest.mark.slow
def test_1f1b_memory_bounded_vs_gpipe(pp_mesh):
    """The point of 1F1B+remat: peak temp memory stays flat as n_micro grows,
    while GPipe-autodiff's residual stack grows with it."""
    stages = [make_stage_params(40 + s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)

    def temp_bytes(build, n_micro):
        micro, target = _data(41, n_micro)

        def local(p, mb):
            p_local = jax.tree.map(lambda q: q[0], p)
            return build(p_local, mb, target)

        lowered = jax.jit(
            jax.shard_map(
                local, mesh=pp_mesh, in_specs=(P("pp"), P()),
                out_specs=P("pp"), check_vma=False,
            )
        ).lower(stacked, micro)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    def gpipe_grads(p_local, mb, target):
        return jax.grad(
            lambda p: pipeline_loss(stage_fn, p, mb, target, mse, axis_name="pp")
        )(p_local)

    def f1b_grads(p_local, mb, target):
        return pipeline_train_1f1b(stage_fn, p_local, mb, target, mse, "pp")[1]

    small, large = 8, 64
    gpipe_growth = temp_bytes(gpipe_grads, large) - temp_bytes(gpipe_grads, small)
    f1b_small, f1b_large = temp_bytes(f1b_grads, small), temp_bytes(f1b_grads, large)
    f1b_growth = f1b_large - f1b_small
    # GPipe residuals grow ~ (n_micro * mb * dim * stages...); 1F1B's stash is
    # fixed at (2S-1) slots -- its growth must be an order smaller.
    assert f1b_growth * 4 < gpipe_growth, (f1b_growth, gpipe_growth)


def test_1f1b_single_stage_fallback():
    stages = make_stage_params(50)
    micro, target = _data(51, 4)
    loss, grads = pipeline_train_1f1b(stage_fn, stages, micro, target, mse, "pp")
    expect_loss, expect_grads = jax.value_and_grad(
        lambda p: jnp.mean(
            jax.vmap(lambda x, t: mse(stage_fn(p, x), t))(micro, target)
        )
    )(stages)
    np.testing.assert_allclose(float(loss), float(expect_loss), rtol=1e-5)
    for key in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[key]), np.asarray(expect_grads[key]), rtol=1e-4
        )


def test_1f1b_extended_head_and_input_grads(pp_mesh):
    """The extended surface for real models: loss_params (an LM-head analog
    inside loss_fn) and input cotangents (for an embedding outside the
    pipeline).  Both come back psum-recoverable over pp and match the
    sequential oracle."""
    stages = [make_stage_params(70 + s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    micro, _ = _data(71)
    rng = np.random.RandomState(72)
    head = {"v": jnp.asarray(rng.randn(DIM, 2).astype(np.float32))}
    target = jnp.asarray(rng.randn(MICRO, MB, 2).astype(np.float32))

    def head_loss(hp, y, t):
        return jnp.mean((y @ hp["v"] - t) ** 2)

    def oracle(stages_list, hp, mbs):
        out = sequential_oracle(stages_list, mbs)
        return jnp.mean(jax.vmap(lambda y, t: head_loss(hp, y, t))(out, target))

    expect_loss, (eg_stages, eg_head, eg_micro) = jax.value_and_grad(
        oracle, argnums=(0, 1, 2)
    )(stages, head, micro)

    def local(p, hp, mb):
        p_local = jax.tree.map(lambda q: q[0], p)
        loss, grads = pipeline_train_1f1b(
            stage_fn, p_local, mb, target, head_loss, axis_name="pp",
            loss_params=hp, with_input_grads=True,
        )
        # loss_params/input grads live on one rank each: psum to recover
        g_head = jax.tree.map(lambda g: jax.lax.psum(g, "pp"), grads.loss_params)
        g_micro = jax.lax.psum(grads.inputs, "pp")
        return loss, jax.tree.map(lambda g: g[None], grads.stage), g_head, g_micro

    fn = jax.jit(
        jax.shard_map(
            local, mesh=pp_mesh, in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp"), P(), P()), check_vma=False,
        )
    )
    loss, g_stage, g_head, g_micro = fn(stacked, head, micro)
    np.testing.assert_allclose(float(loss), float(expect_loss), rtol=2e-4)
    np.testing.assert_allclose(
        np.asarray(g_head["v"]), np.asarray(eg_head["v"]), rtol=2e-3, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(g_micro), np.asarray(eg_micro), rtol=2e-3, atol=1e-4
    )
    for s in range(STAGES):
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(g_stage[key][s]), np.asarray(eg_stages[s][key]),
                rtol=2e-3, atol=1e-4, err_msg=f"stage {s} {key}",
            )


def test_gpipe_remat_same_values(pp_mesh):
    """remat=True changes memory, not values."""
    stages = [make_stage_params(60 + s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    micro, target = _data(61)

    def grads(remat):
        def local(p, mb):
            p_local = jax.tree.map(lambda q: q[0], p)
            return jax.grad(
                lambda q: pipeline_loss(
                    stage_fn, q, mb, target, mse, axis_name="pp", remat=remat
                )
            )(p_local)

        fn = jax.jit(
            jax.shard_map(
                local, mesh=pp_mesh, in_specs=(P("pp"), P()),
                out_specs=P("pp"), check_vma=False,
            )
        )
        return fn(stacked, micro)

    a, b = grads(False), grads(True)
    for key in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(a[key]), np.asarray(b[key]), rtol=1e-5, atol=1e-7
        )


def test_interleaved_matches_sequential_oracle(pp_mesh):
    """Interleaved V=2 over 4 ranks == sequential application of the 8
    global stages, for loss AND per-chunk gradients."""
    from bagua_tpu.parallel.pipeline import pipeline_loss_interleaved

    V = 2
    n_global = V * STAGES
    chunks = [make_stage_params(100 + j) for j in range(n_global)]
    rng = np.random.RandomState(3)
    micro = jnp.asarray(rng.randn(8, MB, DIM).astype(np.float32))  # 8 % 4 == 0
    target = jnp.asarray(rng.randn(8, MB, DIM).astype(np.float32))

    def mb_loss(y, t):
        return jnp.mean((y - t) ** 2)

    # oracle: global stage j = v * STAGES + r, applied in order j = 0..7
    def oracle(flat_chunks):
        out = []
        for m in range(micro.shape[0]):
            x = micro[m]
            for p in flat_chunks:
                x = stage_fn(p, x)
            out.append(mb_loss(x, target[m]))
        return jnp.mean(jnp.stack(out))

    expect_loss, expect_grads = jax.value_and_grad(oracle)(chunks)

    # rank r's stacked chunks: [chunk r, chunk STAGES + r, ...]
    per_rank = [
        jax.tree.map(lambda *xs: jnp.stack(xs), *[chunks[v * STAGES + r] for v in range(V)])
        for r in range(STAGES)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_rank)  # (S, V, ...)

    def local(p, mb, tg):
        mine = jax.tree.map(lambda q: q[0], p)  # (V, ...) per rank
        return pipeline_loss_interleaved(stage_fn, mine, mb, tg, mb_loss, axis_name="pp")

    fn = jax.jit(
        jax.shard_map(
            jax.value_and_grad(local),
            mesh=pp_mesh,
            in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp")),
            check_vma=False,
        )
    )
    loss, grads = fn(stacked, micro, target)
    np.testing.assert_allclose(float(loss), float(expect_loss), rtol=2e-4)
    got = np.asarray(grads["w"])  # (S, V, DIM, DIM)
    for r in range(STAGES):
        for v in range(V):
            np.testing.assert_allclose(
                got[r, v], np.asarray(expect_grads[v * STAGES + r]["w"]),
                rtol=2e-3, atol=2e-5,
            )


def test_interleaved_v1_equals_gpipe(pp_mesh):
    """V=1 interleaved degenerates to the GPipe schedule exactly."""
    from bagua_tpu.parallel.pipeline import pipeline_loss_interleaved

    stages = [make_stage_params(40 + s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    rng = np.random.RandomState(5)
    micro = jnp.asarray(rng.randn(8, MB, DIM).astype(np.float32))
    target = jnp.asarray(rng.randn(8, MB, DIM).astype(np.float32))

    def mb_loss(y, t):
        return jnp.mean((y - t) ** 2)

    def run(use_interleaved):
        def local(p, mb, tg):
            mine = jax.tree.map(lambda q: q[0], p)
            if use_interleaved:
                one = jax.tree.map(lambda q: q[None], mine)  # V=1 leading axis
                return pipeline_loss_interleaved(stage_fn, one, mb, tg, mb_loss, axis_name="pp")
            return pipeline_loss(stage_fn, mine, mb, tg, mb_loss, axis_name="pp")

        fn = jax.jit(
            jax.shard_map(local, mesh=pp_mesh, in_specs=(P("pp"), P(), P()),
                          out_specs=P(), check_vma=False)
        )
        return float(fn(stacked, micro, target))

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)


def test_interleaved_micro_divisibility(pp_mesh):
    from bagua_tpu.parallel.pipeline import pipeline_loss_interleaved

    stages = [make_stage_params(60 + s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    micro = jnp.zeros((6, MB, DIM), jnp.float32)  # 6 % 4 != 0
    target = jnp.zeros((6, MB, DIM), jnp.float32)

    def local(p, mb, tg):
        one = jax.tree.map(lambda q: q[0][None], p)  # this rank's chunk, V=1
        return pipeline_loss_interleaved(
            stage_fn, one, mb, tg, lambda y, t: jnp.mean((y - t) ** 2), axis_name="pp"
        )

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(
            jax.shard_map(local, mesh=pp_mesh, in_specs=(P("pp"), P(), P()),
                          out_specs=P(), check_vma=False)
        )(stacked, micro, target)
