"""Pipeline parallelism: forward equals sequential stage application; grads
flow through the pipeline schedule correctly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bagua_tpu.parallel.pipeline import pipeline_apply

STAGES = 4
MICRO = 6
MB, DIM = 3, 8


def stage_fn(params, x):
    return jax.nn.tanh(x @ params["w"] + params["b"])


def make_stage_params(seed):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(DIM, DIM).astype(np.float32) * 0.5),
        "b": jnp.asarray(rng.randn(DIM).astype(np.float32) * 0.1),
    }


def sequential_oracle(stages, microbatches):
    out = []
    for m in range(microbatches.shape[0]):
        x = microbatches[m]
        for p in stages:
            x = stage_fn(p, x)
        out.append(x)
    return jnp.stack(out)


@pytest.fixture()
def pp_mesh():
    return Mesh(np.array(jax.devices()[:STAGES]), ("pp",))


def test_pipeline_matches_sequential(pp_mesh):
    stages = [make_stage_params(s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    rng = np.random.RandomState(42)
    micro = jnp.asarray(rng.randn(MICRO, MB, DIM).astype(np.float32))

    expect = np.asarray(sequential_oracle(stages, micro))

    fn = jax.jit(
        jax.shard_map(
            lambda p, mb: pipeline_apply(
                stage_fn, jax.tree.map(lambda q: q[0], p), mb, axis_name="pp"
            ),
            mesh=pp_mesh,
            in_specs=(P("pp"), P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    got = np.asarray(fn(stacked, micro))
    np.testing.assert_allclose(got, expect, rtol=2e-4, atol=1e-5)


def test_pipeline_gradients(pp_mesh):
    """Gradient of a loss on pipeline outputs matches the sequential oracle's
    gradient for each stage's parameters."""
    stages = [make_stage_params(10 + s) for s in range(STAGES)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    rng = np.random.RandomState(7)
    micro = jnp.asarray(rng.randn(MICRO, MB, DIM).astype(np.float32))
    target = jnp.asarray(rng.randn(MICRO, MB, DIM).astype(np.float32))

    def oracle_loss(stages_list):
        out = sequential_oracle(stages_list, micro)
        return jnp.mean((out - target) ** 2)

    expect_grads = jax.grad(lambda s: oracle_loss(s))(stages)

    def local_loss(stacked_params, mb):
        p_local = jax.tree.map(lambda q: q[0], stacked_params)
        out = pipeline_apply(stage_fn, p_local, mb, axis_name="pp")
        return jnp.mean((out - target) ** 2)

    grad_fn = jax.jit(
        jax.shard_map(
            lambda p, mb: jax.grad(local_loss)(p, mb),
            mesh=pp_mesh,
            in_specs=(P("pp"), P()),
            out_specs=P("pp"),
            check_vma=False,
        )
    )
    got = grad_fn(stacked, micro)
    for s in range(STAGES):
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(got[key][s]),
                np.asarray(expect_grads[s][key]),
                rtol=2e-3, atol=1e-4,
                err_msg=f"stage {s} {key}",
            )


def test_pipeline_single_stage_fallback():
    stages = make_stage_params(0)
    micro = jnp.asarray(np.random.RandomState(0).randn(4, MB, DIM).astype(np.float32))
    out = pipeline_apply(stage_fn, stages, micro, axis_name="pp")
    expect = jax.vmap(lambda x: stage_fn(stages, x))(micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=1e-6)
