"""Telemetry hub: in-graph labels, trace analyzer, recompile detector, metrics.

Pins the observability contract end-to-end on the 8-device CPU sim:

* every bucket exchange in the compiled step carries a parseable
  ``bagua_ex/algo=<a>/bucket=<i>/phase=<p>`` scope (and the engine phases a
  ``bagua_step/phase=<p>`` scope) — for both the overlap and monolithic paths;
* the device-trace analyzer attributes the captured collective spans back to
  the bucket plan: one ``per_bucket`` row per plan bucket, labels matching;
* the recompile detector reports zero retraces across steady-state steps and
  at least one (plus a rate alert) when the jit cache churns;
* the metrics layer (registry, JSONL sink, Prometheus text export) and the
  StepTimer/Watchdog satellites behave as documented.
"""

import json
import os
import re
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.observability import (
    Counter,
    Histogram,
    JsonlSink,
    MetricsRegistry,
    ProfilerSession,
    RecompileDetector,
    StepTimer,
    Telemetry,
    Watchdog,
    analyze_trace,
    parse_exchange_label,
    parse_step_phase,
    rotated_metrics_files,
    validate_metrics_event,
    validate_metrics_file,
)

GLOBAL_BATCH = 32
LAYERS = [12, 16, 16, 4]


def make_batch(seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(GLOBAL_BATCH, LAYERS[0]).astype(np.float32))
    y = jnp.asarray(rng.randn(GLOBAL_BATCH, LAYERS[-1]).astype(np.float32))
    return x, y


def make_ddp(group, overlap, telemetry=None, bucket_size=1 << 9):
    return DistributedDataParallel(
        mse_loss,
        optax.sgd(0.1),
        GradientAllReduceAlgorithm(),
        process_group=group,
        bucket_size_bytes=bucket_size,  # small: forces several buckets
        overlap=overlap,
        telemetry=telemetry,
    )


def compiled_hlo(ddp, state, batch):
    """Compiled HLO text of the (single) cached step variant."""
    assert len(ddp._step_fns) == 1, ddp._step_fns.keys()
    (fn,) = ddp._step_fns.values()
    return fn.lower(state, batch).compile().as_text()


def op_name_labels(hlo):
    return re.findall(r'op_name="([^"]*)"', hlo)


# -- scope grammar round-trips ------------------------------------------------


def test_parse_exchange_label_roundtrip():
    lab = parse_exchange_label(
        "jit(step)/bagua_ex/algo=bytegrad/bucket=12/phase=mono/convert"
    )
    assert lab == {"algo": "bytegrad", "bucket": 12, "phase": "mono"}
    assert parse_exchange_label("jit(step)/transpose/all-reduce") is None
    assert parse_exchange_label("") is None and parse_exchange_label(None) is None


def test_parse_step_phase():
    assert parse_step_phase("jit(step)/bagua_step/phase=fwd_bwd/dot") == "fwd_bwd"
    assert parse_step_phase("jit(step)/dot") is None


# -- in-graph annotations in the compiled step --------------------------------


def test_overlap_step_hlo_carries_bucket_labels(group):
    """Every plan bucket's exchange is labeled phase=overlap in the compiled
    overlap step, and the engine phases are labeled too."""
    ddp = make_ddp(group, overlap=True)
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    batch = make_batch()
    state, _ = ddp.train_step(state, batch)
    assert ddp.plan.num_buckets > 1  # multi-bucket: labels are per-bucket facts

    labels = op_name_labels(compiled_hlo(ddp, state, batch))
    ex = [lab for lab in map(parse_exchange_label, labels) if lab]
    assert ex, "no bucket-exchange labels in compiled HLO"
    assert {e["algo"] for e in ex} == {"gradient_allreduce"}
    assert {e["phase"] for e in ex} == {"overlap"}
    assert {e["bucket"] for e in ex} == set(range(ddp.plan.num_buckets))

    phases = {p for p in map(parse_step_phase, labels) if p}
    assert "fwd_bwd" in phases and "optimizer" in phases


def test_monolithic_step_hlo_carries_mono_labels(group):
    ddp = make_ddp(group, overlap=False)
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    batch = make_batch()
    state, _ = ddp.train_step(state, batch)

    labels = op_name_labels(compiled_hlo(ddp, state, batch))
    ex = [lab for lab in map(parse_exchange_label, labels) if lab]
    assert {e["phase"] for e in ex} == {"mono"}
    assert {e["bucket"] for e in ex} == set(range(ddp.plan.num_buckets))


# -- trace analyzer on a CPU-captured profiler session ------------------------


def test_trace_analyzer_attributes_plan_buckets(group, tmp_path):
    """Acceptance: the analyzer's per-bucket collective spans match the
    bucket plan (count and labels) on a CPU ProfilerSession capture."""
    ddp = make_ddp(group, overlap=True)
    state = ddp.init(init_mlp(jax.random.PRNGKey(1), LAYERS))
    batch = make_batch(seed=1)
    state, _ = ddp.train_step(state, batch)  # warmup compile outside capture
    hlo = compiled_hlo(ddp, state, batch)

    prof_dir = str(tmp_path / "trace")
    prof = ProfilerSession(prof_dir)
    state, _ = prof.trace_steps(ddp.train_step, state, [batch, batch])

    report = analyze_trace(prof_dir, hlo_text=hlo)
    assert report["collective_spans"] > 0
    assert 0.0 <= report["measured_overlap_frac"] <= 1.0

    rows = report["per_bucket"]
    assert len(rows) == ddp.plan.num_buckets  # one row per plan bucket
    assert [r["bucket"] for r in rows] == list(range(ddp.plan.num_buckets))
    for r in rows:
        assert r["algo"] == "gradient_allreduce"
        assert r["phases"] == ["overlap"]
        assert r["spans"] > 0
        assert all(op.startswith("all-reduce") for op in r["hlo_ops"])
    # the step's only collectives are the labeled bucket exchanges
    assert report["unattributed"] is None
    ddp.shutdown()


def test_trace_analyzer_without_hlo_is_aggregate_only(group, tmp_path):
    ddp = make_ddp(group, overlap=True)
    state = ddp.init(init_mlp(jax.random.PRNGKey(2), LAYERS))
    batch = make_batch(seed=2)
    state, _ = ddp.train_step(state, batch)

    prof_dir = str(tmp_path / "trace")
    state, _ = ProfilerSession(prof_dir).trace_steps(ddp.train_step, state, [batch])

    report = analyze_trace(prof_dir)  # no hlo_text: no join table
    assert report["collective_spans"] > 0
    assert report["per_bucket"] == []
    assert report["unattributed"]["spans"] == report["collective_spans"]
    ddp.shutdown()


# -- recompile detector -------------------------------------------------------


def test_recompile_detector_steady_state_is_quiet():
    det = RecompileDetector()
    assert det.record_compile("default") is False  # warmup, not a retrace
    for _ in range(5):
        det.record_step()
    rep = det.report()
    assert rep == {
        "steps": 5, "retraces": 0, "alerts": 0,
        "compiles_by_variant": {"default": 1},
        "compile_ms_total": 0.0, "compile_ms_by_variant": {},
    }


def test_recompile_detector_counts_retraces_and_alerts():
    alerts = []
    on_alert = lambda msg, n: alerts.append((msg, n))  # noqa: E731
    det = RecompileDetector(window=10, max_retraces_per_window=1)
    det.record_compile("a", on_alert=on_alert)  # warmup
    assert det.record_compile("b", on_alert=on_alert) is True  # new variant = retrace
    assert det.record_compile("a", on_alert=on_alert) is True  # re-build = retrace
    det.record_compile("a", on_alert=on_alert)
    rep = det.report()
    assert rep["retraces"] == 3
    assert rep["alerts"] == 1 and len(alerts) == 1  # latched: one alarm
    assert "retraces in the last 10 steps" in alerts[0][0]


def test_recompile_detector_rearms_after_quiet_window():
    det = RecompileDetector(window=3, max_retraces_per_window=0)
    det.record_compile("v")
    det.record_compile("v")  # retrace -> alert #1
    assert det.report()["alerts"] == 1
    for _ in range(3):  # a full quiet window re-arms the alarm
        det.record_step()
    det.record_compile("v")  # retrace -> alert #2
    assert det.report() == {
        "steps": 3, "retraces": 2, "alerts": 2,
        "compiles_by_variant": {"v": 3},
        "compile_ms_total": 0.0, "compile_ms_by_variant": {},
    }


def test_ddp_telemetry_steady_state_then_forced_retrace(group, tmp_path):
    """Acceptance: 0 retraces across 5 steady-state MLP steps; clearing the
    jit cache (what need_reset/rebucket do) makes the next step a retrace."""
    jsonl = str(tmp_path / "metrics.jsonl")
    tel = Telemetry(metrics_jsonl=jsonl, max_retraces_per_window=0)
    ddp = make_ddp(group, overlap=True, telemetry=tel)
    state = ddp.init(init_mlp(jax.random.PRNGKey(3), LAYERS))
    batch = make_batch(seed=3)
    for _ in range(5):
        state, _ = ddp.train_step(state, batch)
    rep = tel.recompile.report()
    assert rep["steps"] == 5 and rep["retraces"] == 0 and rep["alerts"] == 0

    ddp._step_fns = {}  # forced cache churn: the step variant must rebuild
    state, _ = ddp.train_step(state, batch)
    rep = tel.recompile.report()
    assert rep["retraces"] == 1 and rep["alerts"] == 1

    snap = tel.snapshot()
    assert snap["phase"] == "wait" and snap["step"] == 5
    assert snap["metrics"]["steps_total"] == 6
    assert snap["metrics"]["retrace_alerts_total"] == 1
    assert snap["metrics"]["step_wall_ms"]["count"] == 6
    # engine satellite: step-wall percentiles surfaced host-side
    assert set(ddp.host_overhead_snapshot()["step_wall_ms"]) == {"p50", "p95", "p99"}

    tel.close()
    assert validate_metrics_file(jsonl) == []
    with open(jsonl) as f:
        events = [json.loads(line) for line in f if line.strip()]
    kinds = [e["event"] for e in events]
    assert kinds.count("step") == 6
    assert kinds.count("compile") == 2  # warmup + forced retrace
    assert kinds.count("retrace_alert") == 1
    retraced = [e["retrace"] for e in events if e["event"] == "compile"]
    assert retraced == [False, True]
    step_ev = next(e for e in events if e["event"] == "step")
    assert step_ev["wire_bytes"] == ddp.plan.total_bytes()
    assert "host_overhead_ms" in step_ev

    prom_path = str(tmp_path / "metrics.prom")
    tel.export_prometheus(prom_path)
    prom = open(prom_path).read()
    assert "bagua_steps_total 6" in prom
    assert "bagua_retraces_total 1" in prom
    assert "bagua_step_wall_ms_count 6" in prom
    ddp.shutdown()


# -- metrics layer ------------------------------------------------------------


def test_metrics_registry_instruments():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(2)
    with pytest.raises(ValueError):
        reg.counter("c").inc(-1)  # counters are monotonic
    with pytest.raises(TypeError):
        reg.gauge("c")  # kind mismatch under one name
    reg.gauge("g").set(1.5)
    for v in range(1, 101):
        reg.histogram("h").observe(float(v))
    snap = reg.snapshot()
    assert snap["c"] == 3 and snap["g"] == 1.5
    # nearest-rank: the p50 of 1..100 is the 50th smallest sample
    assert snap["h"]["count"] == 100 and snap["h"]["p50"] == 50.0

    prom = reg.to_prometheus()
    assert "# TYPE bagua_c counter" in prom and "bagua_c 3" in prom
    assert "# TYPE bagua_g gauge" in prom
    # histograms export as conformant summaries: quantile-labeled samples
    # (bare quantile values, "0.5" not "0.50") followed by _count/_sum
    assert 'bagua_h{quantile="0.5"} 50.0' in prom
    assert 'bagua_h{quantile="0.95"}' in prom and 'bagua_h{quantile="0.99"}' in prom
    assert "bagua_h_count 100" in prom
    assert f"bagua_h_sum {float(sum(range(1, 101)))}" in prom
    # quantile samples precede the _count/_sum pair within the family
    assert prom.index('bagua_h{quantile="0.5"}') < prom.index("bagua_h_count")


def test_histogram_window_is_recent_tail():
    h = Histogram("h", window=100)
    for v in range(1, 2001):
        h.observe(float(v))
    # percentiles over the last 100 observations (1901..2000), not the run
    assert h.percentiles()["p50"] == 1950.0
    assert h.count == 2000 and h.sum == sum(range(1, 2001))


def test_event_schema_validation(tmp_path):
    ok = {"ts": 1.0, "event": "step", "step": 3, "wall_ms": 1.0,
          "samples_per_s": 2.0, "wire_bytes": 8, "variant": "default"}
    assert validate_metrics_event(ok) == []
    assert validate_metrics_event({"event": "step"})  # missing envelope+payload
    assert validate_metrics_event({"ts": "now", "event": "x", "step": 0})

    path = str(tmp_path / "ev.jsonl")
    with JsonlSink(path) as sink:
        sink.emit(dict(ok))
        sink.emit({"event": "custom", "step": 0})  # unknown type: envelope only
        with pytest.raises(ValueError):
            sink.emit({"event": "compile", "step": 1})  # missing payload fields
    assert validate_metrics_file(path) == []
    with open(path, "a") as f:
        f.write("not json\n")
        f.write(json.dumps({"event": "step", "step": "three", "ts": 0}) + "\n")
    problems = validate_metrics_file(path)
    assert any("not JSON" in p for p in problems)
    assert any("'step'" in p for p in problems)


def test_jsonl_sink_rotation_and_rotated_validation(tmp_path, monkeypatch):
    path = str(tmp_path / "m.jsonl")
    ev = {"event": "custom", "step": 0, "ts": 1.0}
    line_len = len(json.dumps(ev, sort_keys=True)) + 1
    # room for ~2 lines per file: every 3rd emit rotates
    with JsonlSink(path, max_bytes=2 * line_len + 1) as sink:
        for i in range(7):
            sink.emit({"event": "custom", "step": i, "ts": 1.0})
    files = rotated_metrics_files(path)
    assert files[-1] == path and len(files) > 1
    assert all(os.path.exists(f) for f in files)
    # no event lost, order preserved oldest-file-first, no line split
    steps = []
    for f in files:
        with open(f) as fh:
            steps.extend(json.loads(ln)["step"] for ln in fh)
    assert steps == list(range(7))
    assert validate_metrics_file(path) == []
    # a bad line in a rotated segment is reported with the segment's name
    with open(files[0], "a") as fh:
        fh.write("not json\n")
    problems = validate_metrics_file(path)
    assert any(os.path.basename(files[0]) in p for p in problems)

    # default off: no rotation regardless of size
    monkeypatch.delenv("BAGUA_METRICS_MAX_MB", raising=False)
    path2 = str(tmp_path / "n.jsonl")
    with JsonlSink(path2) as sink:
        for i in range(50):
            sink.emit({"event": "custom", "step": i, "ts": 1.0})
    assert rotated_metrics_files(path2) == [path2]

    # BAGUA_METRICS_MAX_MB drives the default ceiling (fractional MiB ok)
    monkeypatch.setenv("BAGUA_METRICS_MAX_MB", str(2 * line_len / (1 << 20)))
    path3 = str(tmp_path / "o.jsonl")
    with JsonlSink(path3) as sink:
        for i in range(5):
            sink.emit({"event": "custom", "step": i, "ts": 1.0})
    assert len(rotated_metrics_files(path3)) > 1


# -- StepTimer and Watchdog satellites ----------------------------------------


def test_step_timer_percentiles_and_thread_safety():
    timer = StepTimer(window=64)
    assert timer.percentiles() == {}

    def worker():
        for _ in range(100):
            timer.tick(0.01)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert timer.n_steps == 400
    p = timer.percentiles()
    assert p["p50"] == p["p95"] == p["p99"] == 0.01


def test_step_timer_small_ring_quantiles_nearest_rank():
    """Nearest-rank indexing on tiny rings: the old ``int(p * n)`` bias made
    the p50 of a 2-sample ring return the MAX.  Pin the corrected values for
    1-, 2- and 3-sample rings (and the Histogram twin, same indexing)."""
    timer = StepTimer(window=8)
    timer.tick(0.5)
    assert timer.percentiles() == {"p50": 0.5, "p95": 0.5, "p99": 0.5}

    timer = StepTimer(window=8)
    timer.tick(0.010)
    timer.tick(0.020)
    p = timer.percentiles()
    assert p["p50"] == 0.010  # the LOWER sample, not the max
    assert p["p95"] == 0.020 and p["p99"] == 0.020

    timer = StepTimer(window=8)
    for v in (0.030, 0.010, 0.020):
        timer.tick(v)
    p = timer.percentiles()
    assert p["p50"] == 0.020 and p["p95"] == 0.030 and p["p99"] == 0.030

    h = Histogram("h", window=8)
    h.observe(1.0)
    h.observe(2.0)
    assert h.percentiles()["p50"] == 1.0


def test_watchdog_env_override(monkeypatch):
    monkeypatch.setenv("BAGUA_WATCHDOG_TIMEOUT_S", "7.5")
    assert Watchdog(timeout_s=300.0).timeout_s == 7.5
    monkeypatch.setenv("BAGUA_WATCHDOG_TIMEOUT_S", "not-a-number")
    assert Watchdog(timeout_s=300.0).timeout_s == 300.0  # ignored, not fatal
    monkeypatch.delenv("BAGUA_WATCHDOG_TIMEOUT_S")
    assert Watchdog(timeout_s=120.0).timeout_s == 120.0


def test_watchdog_timeout_context_carries_telemetry():
    tel = Telemetry()
    tel.current_step, tel.current_phase = 7, "dispatch"
    wd = Watchdog(timeout_s=60.0, snapshot_provider=tel.snapshot)
    wd.beat(phase="dispatch")
    ctx = wd._timeout_context()
    assert ctx["last_phase"] == "dispatch"
    assert ctx["telemetry"]["step"] == 7 and ctx["telemetry"]["phase"] == "dispatch"

    def bad():
        raise RuntimeError("boom")

    wd.snapshot_provider = bad
    ctx = wd._timeout_context()  # a broken hook must not lose the dump
    assert "telemetry" not in ctx and "boom" in ctx["telemetry_error"]


def test_watchdog_fires_with_phase_tag(tmp_path):
    fired = []
    wd = Watchdog(
        timeout_s=0.15, check_interval_s=0.05, on_timeout=lambda s: fired.append(s)
    )
    wd.dump_dir = str(tmp_path)  # the timeout path now leaves evidence files
    wd.start()
    wd.beat(phase="wait")
    deadline = time.time() + 3.0
    while not fired and time.time() < deadline:
        time.sleep(0.05)
    wd.stop()
    assert fired and wd.last_phase == "wait"


def test_telemetry_wires_watchdog_snapshot():
    wd = Watchdog(timeout_s=60.0)
    tel = Telemetry(watchdog=wd)
    assert wd.snapshot_provider == tel.snapshot  # bound to this hub
    tel.enter_phase("data")
    assert wd.last_phase == "data" and tel.current_phase == "data"


def test_on_rebucket_counter_gauges_and_event(tmp_path):
    """A plan swap shows up on every telemetry surface at once: the
    ``rebucket_total`` counter, the ``plan_version`` gauge, the optional
    predicted/measured exposed-comm gauges, a schema-valid ``rebucket`` JSONL
    event, and the Prometheus text export."""
    path = str(tmp_path / "m.jsonl")
    tel = Telemetry(metrics_jsonl=path)
    tel.on_rebucket(plan_version=1, n_buckets=4, step=7, predicted_exposed_ms=12.5)
    tel.on_rebucket(plan_version=2, n_buckets=2, step=9, measured_exposed_ms=3.25)
    tel.close()

    snap = tel.registry.snapshot()
    assert snap["rebucket_total"] == 2
    assert snap["plan_version"] == 2.0
    assert snap["predicted_exposed_comm_ms"] == 12.5
    assert snap["measured_exposed_comm_ms"] == 3.25

    from bagua_tpu.observability import validate_metrics_file

    assert validate_metrics_file(path) == []
    events = [json.loads(l) for l in open(path) if l.strip()]
    rb = [e for e in events if e["event"] == "rebucket"]
    assert [e["plan_version"] for e in rb] == [1, 2]
    assert rb[0]["n_buckets"] == 4 and rb[0]["step"] == 7
    assert rb[0]["predicted_exposed_ms"] == 12.5
    assert "predicted_exposed_ms" not in rb[1]  # optional field stays absent
    assert rb[1]["measured_exposed_ms"] == 3.25

    prom = tel.registry.to_prometheus()
    assert "bagua_rebucket_total 2" in prom
    assert "bagua_plan_version 2" in prom


def test_precision_switch_event_schema():
    """``precision_switch`` is a first-class schema-validated event type:
    the before/after per-bucket precision lists and the reason are required,
    typed payload fields."""
    ok = {"ts": 1.0, "event": "precision_switch", "step": 4, "plan_version": 0,
          "old_precisions": ["f32", "f32"], "new_precisions": ["int8", "f32"],
          "reason": "planner"}
    assert validate_metrics_event(ok) == []
    missing = dict(ok)
    del missing["new_precisions"]
    assert any("'new_precisions'" in p for p in validate_metrics_event(missing))
    badtype = dict(ok, old_precisions="f32")
    assert any("'old_precisions'" in p for p in validate_metrics_event(badtype))


def test_on_precision_switch_surfaces(tmp_path):
    """A wire-precision plan swap lands on every telemetry surface at once:
    the ``precision_switch_total`` counter, per-precision bucket-count
    gauges, a schema-valid JSONL event, and the Prometheus export."""
    path = str(tmp_path / "p.jsonl")
    tel = Telemetry(metrics_jsonl=path)
    tel.on_precision_switch(
        step=3, plan_version=0, old_precisions=["f32", "f32", "f32"],
        new_precisions=["int8", "f32", "int4"],
    )
    tel.on_precision_switch(
        step=9, plan_version=0, old_precisions=["int8", "f32", "int4"],
        new_precisions=["int8", "int8", "int4"], reason="manual",
    )
    tel.close()

    snap = tel.registry.snapshot()
    assert snap["precision_switch_total"] == 2
    assert snap["buckets_at_precision_int8"] == 2.0
    assert snap["buckets_at_precision_int4"] == 1.0

    assert validate_metrics_file(path) == []
    events = [json.loads(l) for l in open(path) if l.strip()]
    sw = [e for e in events if e["event"] == "precision_switch"]
    assert [e["reason"] for e in sw] == ["planner", "manual"]
    assert sw[0]["old_precisions"] == ["f32", "f32", "f32"]
    assert sw[0]["new_precisions"] == ["int8", "f32", "int4"]
    assert sw[1]["step"] == 9

    prom = tel.registry.to_prometheus()
    assert "bagua_precision_switch_total 2" in prom
    assert "bagua_buckets_at_precision_int8 2" in prom


def test_on_step_per_precision_wire_counters(tmp_path):
    """``wire_bytes_by_precision`` splits the census into per-precision
    counters (the flat-name labeled family) and rides the step JSONL event."""
    path = str(tmp_path / "w.jsonl")
    tel = Telemetry(metrics_jsonl=path)
    by_prec = {"f32": 1000, "int8": 300, "int4": 150}
    for step in range(3):
        tel.on_step(step=step, wall_s=0.01, n_samples=32, wire_bytes=1450,
                    wire_bytes_by_precision=by_prec)
    tel.close()

    snap = tel.registry.snapshot()
    assert snap["wire_bytes_precision_f32_total"] == 3000
    assert snap["wire_bytes_precision_int8_total"] == 900
    assert snap["wire_bytes_precision_int4_total"] == 450
    assert snap["wire_bytes_total"] == 3 * 1450

    assert validate_metrics_file(path) == []
    events = [json.loads(l) for l in open(path) if l.strip()]
    steps = [e for e in events if e["event"] == "step"]
    assert all(e["wire_bytes_by_precision"] == by_prec for e in steps)


def test_precision_plan_switch_emits_telemetry_from_engine(group, tmp_path):
    """End-to-end: ``apply_precision_plan`` on an ``auto`` engine emits the
    ``precision_switch`` event and subsequent steps feed the per-precision
    wire-byte counters with the modelled quantized-ring bytes."""
    from bagua_tpu.kernels.quantized_ring import ring_wire_bytes

    path = str(tmp_path / "pe.jsonl")
    tel = Telemetry(metrics_jsonl=path)
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05),
        GradientAllReduceAlgorithm(wire_precision="auto"),
        process_group=group, bucket_size_bytes=1 << 9, telemetry=tel,
    )
    state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    batch = make_batch()
    state, _ = ddp.train_step(state, batch)

    nb = ddp.plan.num_buckets
    assert nb >= 2
    plan = ["int8"] + ["f32"] * (nb - 1)
    assert ddp.apply_precision_plan(plan, reason="manual")
    state, _ = ddp.train_step(state, batch)
    tel.close()

    snap = tel.registry.snapshot()
    assert snap["precision_switch_total"] == 1
    assert snap["buckets_at_precision_int8"] == 1.0
    assert snap["buckets_at_precision_f32"] == float(nb - 1)
    # step 1 ran all-f32, step 2 ran the mixed plan: the int8 counter holds
    # exactly one step's modelled ring bytes for bucket 0
    n = group.size
    assert snap["wire_bytes_precision_int8_total"] == ring_wire_bytes(
        ddp.plan.specs[0].numel, n, 8
    )

    assert validate_metrics_file(path) == []
    events = [json.loads(l) for l in open(path) if l.strip()]
    (sw,) = [e for e in events if e["event"] == "precision_switch"]
    assert sw["old_precisions"] == ["f32"] * nb
    assert sw["new_precisions"] == plan and sw["reason"] == "manual"
    step_events = [e for e in events if e["event"] == "step"]
    assert "wire_bytes_by_precision" in step_events[-1]
    assert step_events[-1]["wire_bytes_by_precision"]["int8"] > 0
    ddp.shutdown()


def test_snapshot_and_restart_event_schemas(tmp_path):
    """The resilience subsystem's JSONL events are schema-validated like
    every other event type: required payload fields, typed, with torn or
    truncated records reported rather than crashing the validator."""
    snap_ok = {"ts": 1.0, "event": "snapshot", "step": 6,
               "wall_ms": 12.5, "bytes": 4096, "kind": "async"}
    restart_ok = {"ts": 2.0, "event": "restart", "step": 6,
                  "old_world_size": 8, "new_world_size": 4,
                  "plan_source": "carried", "lost_steps": 2}
    assert validate_metrics_event(snap_ok) == []
    assert validate_metrics_event(restart_ok) == []

    missing = dict(snap_ok)
    del missing["kind"]
    assert any("'kind'" in p for p in validate_metrics_event(missing))
    badtype = dict(restart_ok, lost_steps="two")
    assert any("'lost_steps'" in p for p in validate_metrics_event(badtype))

    path = str(tmp_path / "r.jsonl")
    with JsonlSink(path) as sink:
        sink.emit(dict(snap_ok))
        sink.emit(dict(restart_ok))
        with pytest.raises(ValueError):  # the sink refuses incomplete events
            sink.emit({"event": "restart", "step": 1})
    assert validate_metrics_file(path) == []


def test_on_snapshot_and_on_restart_surfaces(tmp_path):
    """A snapshot write and an elastic resume land on every telemetry surface
    at once: counters/gauges/histograms, schema-valid JSONL events, and the
    Prometheus text export."""
    path = str(tmp_path / "res.jsonl")
    tel = Telemetry(metrics_jsonl=path)
    tel.on_snapshot(step=3, wall_ms=7.25, n_bytes=1 << 20, kind="async")
    tel.on_snapshot(step=6, wall_ms=9.0, n_bytes=1 << 20, kind="final")
    tel.on_restart(step=6, old_world_size=8, new_world_size=4,
                   plan_source="carried", lost_steps=2)
    tel.close()

    snap = tel.registry.snapshot()
    assert snap["snapshots_total"] == 2
    assert snap["snapshot_last_step"] == 6.0
    assert snap["snapshot_wall_ms"]["count"] == 2
    assert snap["restarts_total"] == 1
    assert snap["lost_steps_total"] == 2
    assert snap["resumed_world_size"] == 4.0

    assert validate_metrics_file(path) == []
    events = [json.loads(l) for l in open(path) if l.strip()]
    snaps = [e for e in events if e["event"] == "snapshot"]
    assert [e["kind"] for e in snaps] == ["async", "final"]
    assert snaps[0]["bytes"] == 1 << 20 and snaps[0]["wall_ms"] == 7.25
    (restart,) = [e for e in events if e["event"] == "restart"]
    assert restart["step"] == 6 and restart["plan_source"] == "carried"
    assert restart["old_world_size"] == 8 and restart["new_world_size"] == 4

    prom = tel.registry.to_prometheus()
    assert "bagua_snapshots_total 2" in prom
    assert "bagua_restarts_total 1" in prom
    assert "bagua_lost_steps_total 2" in prom
    assert "bagua_snapshot_wall_ms_count 2" in prom


def test_rebucket_emits_telemetry_from_engine(group, tmp_path):
    """End-to-end: DistributedDataParallel.rebucket bumps plan_version and
    feeds the hub; training continues on the new plan."""
    from bagua_tpu.bucket import BucketPlan
    from bagua_tpu.models.mlp import init_mlp

    path = str(tmp_path / "e.jsonl")
    tel = Telemetry(metrics_jsonl=path)
    params = init_mlp(jax.random.PRNGKey(0), [16, 32, 4])
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(),
        process_group=group, bucket_size_bytes=1 << 10, telemetry=tel,
    )
    state = ddp.init(params)
    rng = np.random.RandomState(0)
    batch = (
        jnp.asarray(rng.randn(16, 16), np.float32),
        jnp.asarray(rng.randn(16, 4), np.float32),
    )
    state, _ = ddp.train_step(state, batch)
    assert ddp.plan_version == 0

    coarse = BucketPlan.from_declarations(
        [[td for b in ddp.plan.declarations() for td in b]],  # one mega-bucket
        ddp._tree_template, align_elems=group.size,
    )
    ddp.rebucket(coarse, predicted_exposed_ms=1.5)
    assert ddp.plan_version == 1
    snap = tel.registry.snapshot()
    assert snap["rebucket_total"] == 1 and snap["plan_version"] == 1.0
    assert snap["predicted_exposed_comm_ms"] == 1.5

    state, losses = ddp.train_step(state, batch)
    assert np.isfinite(np.asarray(losses)).all()
    tel.close()
    events = [json.loads(l) for l in open(path) if l.strip()]
    assert any(e["event"] == "rebucket" and e["plan_version"] == 1 for e in events)


# -- model-parallel scope grammar + per-scope trace attribution ---------------

from jax.sharding import PartitionSpec as P  # noqa: E402


def test_parse_mp_label_roundtrip():
    from bagua_tpu.observability import mp_scope, parse_mp_label

    lab = parse_mp_label("jit(f)/bagua_ex/axis=tp/phase=rs_ring/collective-permute")
    assert lab == {"axis": "tp", "phase": "rs_ring"}
    # the two grammars never cross-match: algo=/bucket= vs axis=
    assert parse_mp_label(
        "jit(step)/bagua_ex/algo=bytegrad/bucket=12/phase=mono/convert"
    ) is None
    assert parse_exchange_label("jit(f)/bagua_ex/axis=tp/phase=rs_ring/x") is None
    assert parse_mp_label("") is None and parse_mp_label(None) is None
    # the scope emits what the parser reads
    with mp_scope("ep", "dispatch"):
        pass


def test_fused_tp_hlo_carries_mp_labels():
    """The fused RowParallel ring's collectives carry axis=tp labels in the
    compiled HLO (rs_ring on the ppermutes, row_allgather on the gather)."""
    from jax.sharding import Mesh
    from bagua_tpu.observability import parse_mp_label
    from bagua_tpu.parallel.tensor_parallel import ParallelMLP

    tp = 8
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(8, 12).astype(np.float32))
    mlp = ParallelMLP(hidden_features=16, out_features=8, tp_size=tp, fused="auto")
    per_rank = [mlp.init(jax.random.PRNGKey(r), x)["params"] for r in range(tp)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_rank)
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))
    hlo = (
        jax.jit(
            jax.shard_map(
                lambda p, xx: mlp.apply(
                    {"params": jax.tree.map(lambda q: q[0], p)}, xx
                ),
                mesh=mesh, in_specs=(P("tp"), P()), out_specs=P(),
                check_vma=False,
            )
        )
        .lower(stacked, x)
        .compile()
        .as_text()
    )
    mp = [lab for lab in map(parse_mp_label, op_name_labels(hlo)) if lab]
    assert mp, "no model-parallel labels in compiled fused HLO"
    assert {m["axis"] for m in mp} == {"tp"}
    assert {"rs_ring", "row_allgather"} <= {m["phase"] for m in mp}


def test_trace_analyzer_per_scope_rows(tmp_path):
    """analyze_trace attributes mp-labeled collectives into per_scope rows
    with their own measured_overlap_frac (the tp/ep scope report)."""
    from jax.sharding import Mesh
    from bagua_tpu.parallel.tensor_parallel import ParallelMLP

    tp = 8
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(16, 16).astype(np.float32))
    mlp = ParallelMLP(hidden_features=32, out_features=16, tp_size=tp, fused="auto")
    per_rank = [mlp.init(jax.random.PRNGKey(r), x)["params"] for r in range(tp)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_rank)
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))
    step = jax.jit(
        jax.shard_map(
            lambda p, xx: mlp.apply({"params": jax.tree.map(lambda q: q[0], p)}, xx),
            mesh=mesh, in_specs=(P("tp"), P()), out_specs=P(), check_vma=False,
        )
    )
    compiled = step.lower(stacked, x).compile()
    compiled(stacked, x).block_until_ready()  # warm outside the capture

    prof_dir = str(tmp_path / "trace")
    with ProfilerSession(prof_dir):
        for _ in range(3):
            compiled(stacked, x).block_until_ready()

    report = analyze_trace(prof_dir, hlo_text=compiled.as_text())
    rows = {r["axis"]: r for r in report["per_scope"]}
    assert "tp" in rows, report
    row = rows["tp"]
    assert {"rs_ring", "row_allgather"} <= set(row["phases"])
    assert row["spans"] > 0 and row["collective_ms"] > 0
    assert 0.0 <= row["measured_overlap_frac"] <= 1.0
    assert any(op.startswith("collective-permute") for op in row["hlo_ops"])
    # the mp-labeled collectives are not double-counted as bucket exchanges
    assert report["per_bucket"] == []


# -- circuit-breaker transition telemetry -------------------------------------


def test_breaker_transition_event_schema():
    ok = {"ts": 1.0, "event": "breaker_transition", "step": 2,
          "breaker": "fleet-rpc", "old_state": "closed", "new_state": "open"}
    assert validate_metrics_event(ok) == []
    missing = dict(ok)
    del missing["new_state"]
    assert any("'new_state'" in p for p in validate_metrics_event(missing))
    badtype = dict(ok, old_state=1)
    assert any("'old_state'" in p for p in validate_metrics_event(badtype))


def test_breaker_transitions_land_on_telemetry(tmp_path):
    """A full breaker cycle (closed -> open -> half-open -> closed) lands on
    every telemetry surface: the shared + per-breaker state gauges, the
    transition counter, schema-valid JSONL events, and the Prometheus
    export.  ``bind_breaker`` is idempotent and never usurps a listener."""
    from bagua_tpu.resilience.retry import CircuitBreaker, CircuitOpenError

    path = str(tmp_path / "b.jsonl")
    tel = Telemetry(metrics_jsonl=path)
    tel.current_step = 12
    clk = [0.0]
    breaker = CircuitBreaker(failure_threshold=2, cooldown_s=5.0,
                             name="auto-rpc", clock=lambda: clk[0])
    tel.bind_breaker(breaker)
    assert breaker.listener == tel.on_breaker_transition
    tel.bind_breaker(breaker)  # idempotent
    assert breaker.listener == tel.on_breaker_transition
    taken = CircuitBreaker(name="other", listener=lambda *a: None)
    already = taken.listener
    tel.bind_breaker(taken)  # an explicit listener is left alone
    assert taken.listener is already

    breaker.record_failure()  # 1/2: still closed, no transition
    breaker.record_failure()  # 2/2: closed -> open
    assert tel.registry.snapshot()["breaker_state"] == 2.0
    with pytest.raises(CircuitOpenError):
        breaker.before_call()  # still cooling down: no transition
    clk[0] = 6.0
    breaker.before_call()  # cooldown over: open -> half-open (the probe)
    assert tel.registry.snapshot()["breaker_state"] == 1.0
    breaker.record_success()  # probe landed: half-open -> closed
    tel.close()

    snap = tel.registry.snapshot()
    assert snap["breaker_state"] == 0.0
    assert snap["breaker_state_auto_rpc"] == 0.0  # name sanitized for the gauge
    assert snap["breaker_transitions_total"] == 3

    assert validate_metrics_file(path) == []
    events = [json.loads(l) for l in open(path) if l.strip()]
    trans = [e for e in events if e["event"] == "breaker_transition"]
    assert [(e["old_state"], e["new_state"]) for e in trans] == [
        ("closed", "open"), ("open", "half-open"), ("half-open", "closed")]
    assert all(e["breaker"] == "auto-rpc" and e["step"] == 12 for e in trans)

    prom = tel.registry.to_prometheus()
    assert "bagua_breaker_state 0" in prom
    assert "bagua_breaker_transitions_total 3" in prom


# -- budget attribution / regression sentinel ---------------------------------


from bagua_tpu.observability import (  # noqa: E402
    BUDGET_COMPONENTS,
    BudgetModel,
    Cusum,
    RegressionSentinel,
)


def test_perf_regression_event_schema(tmp_path):
    sink = JsonlSink(str(tmp_path / "m.jsonl"))
    good = {
        "event": "perf_regression", "step": 7, "stream": "step_wall",
        "dominant": "compile",
        "components": {c: 0.0 for c in BUDGET_COMPONENTS},
        "residual_ms": 8.0, "expected_ms": 10.0, "measured_ms": 18.0,
        "plan_version": 2, "trace_id": "",
    }
    sink.emit(dict(good))
    # extra fields ride along (straggler_rank when the gang attributed one)
    sink.emit(dict(good, straggler_rank=3))
    # missing payload field and wrong types are rejected at the emit site
    bad = dict(good)
    del bad["dominant"]
    with pytest.raises(ValueError):
        sink.emit(bad)
    with pytest.raises(ValueError):
        sink.emit(dict(good, components="compile"))
    with pytest.raises(ValueError):
        sink.emit(dict(good, residual_ms="8"))
    sink.close()
    assert not validate_metrics_file(str(tmp_path / "m.jsonl"))


def test_budget_partition_sums_to_residual_with_all_components():
    model = BudgetModel(compute_ms=6.0, wire_ms=4.0)
    base_bytes = 1 << 20
    # feed the byte/host baselines with a few clean steps
    for step in range(5):
        model.settle(step, 10.0, host_ms=1.0, wire_bytes=base_bytes)
    model.note_compile(8.0)
    model.note_snapshot(6.0)
    model.note_backpressure(0.002)
    model.note_straggler(3.0, rank=2)
    budget = model.settle(5, 40.0, host_ms=2.5, wire_bytes=base_bytes * 2)
    assert set(budget.components) == set(BUDGET_COMPONENTS)
    assert budget.expected_ms == pytest.approx(10.0)
    assert budget.residual_ms == pytest.approx(30.0)
    assert budget.components["compile"] == pytest.approx(8.0)
    assert budget.components["snapshot"] == pytest.approx(6.0)
    assert budget.components["backpressure"] == pytest.approx(2.0)
    assert budget.components["straggler"] == pytest.approx(3.0)
    # 2x bytes = 1x excess over baseline, priced at wire_ms
    assert budget.components["wire_slowdown"] == pytest.approx(4.0)
    assert budget.components["host_data"] == pytest.approx(1.5)
    # the partition is exact by construction: unattributed is the remainder
    assert budget.partition_error_ms() == pytest.approx(0.0, abs=1e-9)
    assert sum(budget.components.values()) == pytest.approx(30.0)
    assert budget.dominant == "compile"
    assert budget.straggler_rank == 2
    # evidence hooks cleared: the next step settles clean
    nxt = model.settle(6, 10.0, host_ms=1.0, wire_bytes=base_bytes)
    assert nxt.components["compile"] == 0.0
    assert nxt.residual_ms == pytest.approx(0.0)


def test_budget_self_calibration_holds_fire_then_prices_the_median():
    model = BudgetModel(calibrate_steps=5)
    # while calibrating: expected = measured, residual 0, not calibrated
    early = model.settle(0, 50.0)
    assert early.residual_ms == 0.0 and not early.calibrated
    for step in range(1, 6):
        model.settle(step, 10.0 + step * 0.01)
    assert model.calibrated
    budget = model.settle(9, 20.0)
    assert budget.calibrated
    assert budget.expected_ms == pytest.approx(10.03, abs=0.5)
    assert budget.residual_ms == pytest.approx(10.0, abs=0.6)
    # a regressed step must NOT feed the baseline (no chasing)
    assert model.expected() == pytest.approx(10.03, abs=0.5)


def test_cusum_trips_on_sustained_shift_not_jitter():
    quiet = Cusum(k=1.0, h=8.0, warmup=10, alpha=0.05)
    rng = np.random.RandomState(0)
    assert not any(quiet.update(10.0 + rng.uniform(-0.1, 0.1))
                   for _ in range(300))
    shifted = Cusum(k=1.0, h=8.0, warmup=10, alpha=0.05)
    for _ in range(50):
        shifted.update(10.0 + rng.uniform(-0.1, 0.1))
    tripped = any(shifted.update(12.0 + rng.uniform(-0.1, 0.1))
                  for _ in range(50))
    assert tripped and shifted.trips == 1
    # goodput direction: a DOWNWARD shift trips the direction=-1 detector
    down = Cusum(k=1.0, h=8.0, warmup=10, alpha=0.05, direction=-1)
    for _ in range(50):
        down.update(0.9 + rng.uniform(-0.005, 0.005))
    assert any(down.update(0.7) for _ in range(50))


def test_sentinel_trips_attributes_and_drains(tmp_path):
    sink = JsonlSink(str(tmp_path / "m.jsonl"))
    registry = MetricsRegistry()
    sentinel = RegressionSentinel(
        budget=BudgetModel(compute_ms=6.0, wire_ms=4.0), sink=sink,
        registry=registry, warmup=10, threshold=8.0, cooldown=5, window=10,
    )
    sentinel.plan_version = 3
    rng = np.random.RandomState(0)
    step = 0
    for _ in range(20):
        sentinel.observe_step(step, 10.0 + float(rng.uniform(-0.05, 0.05)))
        step += 1
    assert not sentinel.incidents
    while not sentinel.incidents:
        sentinel.note_compile(8.0)
        sentinel.observe_step(step, 18.0 + float(rng.uniform(-0.05, 0.05)),
                              trace_id="00000000000000000000000000000abc")
        step += 1
        assert step < 100, "sentinel never tripped"
    inc = sentinel.incidents[0]
    assert inc["event"] == "perf_regression"
    assert inc["stream"] == "step_wall"
    assert inc["dominant"] == "compile"
    assert inc["plan_version"] == 3
    assert inc["trace_id"] == "00000000000000000000000000000abc"
    assert abs(sum(inc["components"].values()) - inc["residual_ms"]) <= (
        0.01 * max(1.0, abs(inc["residual_ms"]))
    )
    # the JSONL twin validated on emit; the counter ticked
    sink.close()
    assert not validate_metrics_file(str(tmp_path / "m.jsonl"))
    with open(str(tmp_path / "m.jsonl")) as f:
        events = [json.loads(line) for line in f if line.strip()]
    assert [e["event"] for e in events] == ["perf_regression"]
    assert registry.counter("perf_regressions_total").value == 1
    # drain hands over each incident exactly once
    assert sentinel.drain_incidents() == [inc]
    assert sentinel.drain_incidents() == []
    # cooldown re-arms: the sustained regression trips again eventually
    for _ in range(40):
        sentinel.note_compile(8.0)
        sentinel.observe_step(step, 18.0 + float(rng.uniform(-0.05, 0.05)))
        step += 1
    assert len(sentinel.incidents) >= 2
    assert sentinel.report()["wall_trips"] >= 2


def test_telemetry_regression_env_gate_and_budget_gauges(tmp_path, monkeypatch):
    # default off: the hub carries no sentinel
    assert Telemetry(flight=None).regression is None
    monkeypatch.setenv("BAGUA_REGRESSION_SENTINEL", "1")
    monkeypatch.setenv("BAGUA_REGRESSION_WARMUP", "5")
    path = str(tmp_path / "m.jsonl")
    tel = Telemetry(metrics_jsonl=path, flight=None)
    assert tel.regression is not None
    # the hub adopted its own sink + registry for the sentinel
    assert tel.regression.sink is tel.jsonl
    assert tel.regression.registry is tel.registry
    for step in range(8):
        tel.on_step(step, wall_s=0.010, n_samples=32, wire_bytes=1 << 16,
                    host_overhead={"pre": 0.001, "post": 0.001})
    snap = tel.snapshot()
    assert snap["regression"]["steps_seen"] == 8
    assert snap["regression"]["incidents"] == 0
    prom = tel.registry.to_prometheus()
    for comp in BUDGET_COMPONENTS:
        assert f"bagua_step_budget_{comp}_ms" in prom
    assert "bagua_step_budget_expected_ms" in prom
    assert "bagua_step_budget_residual_ms" in prom
    tel.close()
    assert not validate_metrics_file(path)
    # explicit instance wins over the env gate
    monkeypatch.delenv("BAGUA_REGRESSION_SENTINEL")
    sentinel = RegressionSentinel()
    tel2 = Telemetry(flight=None, regression=sentinel)
    assert tel2.regression is sentinel
    tel2.close()


def test_telemetry_feeds_sentinel_evidence_hooks(tmp_path):
    sentinel = RegressionSentinel(budget=BudgetModel(compute_ms=6.0))
    tel = Telemetry(flight=None, regression=sentinel)
    tel.on_compile_done("full", step=0, wall_ms=123.0)
    tel.on_snapshot(step=0, wall_ms=50.0, n_bytes=100, kind="final")
    tel.on_snapshot(step=0, wall_ms=999.0, n_bytes=100, kind="async")
    tel.on_rpc_retry("/rdzv/kv/x", attempt=1, delay_s=0.004,
                     reason="backpressure")
    budget = sentinel.budget
    assert budget._compile_ms == pytest.approx(123.0)
    # only BLOCKING snapshots stall the step; async writes cost nothing
    assert budget._snapshot_ms == pytest.approx(50.0)
    assert budget._backpressure_s == pytest.approx(0.004)
    tel.on_rebucket(plan_version=7, n_buckets=3)
    assert sentinel.plan_version == 7
    tel.close()

# -- per-axis wire attribution ------------------------------------------------


def test_perf_regression_axis_fields_ride_schema(tmp_path):
    """An axis-scoped incident (axis, link_class, wire_axis_ms) is the same
    schema event with extra fields — it must validate as-is so every
    downstream consumer (fleet push, diagnose_hang, perf_doctor) can read
    the axis without a schema bump."""
    sink = JsonlSink(str(tmp_path / "m.jsonl"))
    good = {
        "event": "perf_regression", "step": 7, "stream": "wire_axis:tp",
        "dominant": "wire_slowdown",
        "components": {c: 0.0 for c in BUDGET_COMPONENTS},
        "residual_ms": 8.0, "expected_ms": 10.0, "measured_ms": 18.0,
        "plan_version": 2, "trace_id": "",
    }
    sink.emit(dict(good, axis="tp", link_class="ici",
                   wire_axis_ms={"dp": 0.2, "tp": 7.8}))
    sink.close()
    assert not validate_metrics_file(str(tmp_path / "m.jsonl"))
    with open(str(tmp_path / "m.jsonl")) as f:
        (ev,) = [json.loads(line) for line in f if line.strip()]
    assert ev["axis"] == "tp" and ev["link_class"] == "ici"
    assert ev["wire_axis_ms"] == {"dp": 0.2, "tp": 7.8}


def test_budget_axis_partition_exact_on_every_pricing_path():
    """The per-axis wire split sums BITWISE to components["wire_slowdown"]
    on all three pricing paths (measured-by-axis, scalar-measured split by
    expected share, per-axis byte census) — partition by construction, not
    by tolerance."""
    axis_promise = {"dp": 3.0, "tp": 1.0}

    # path 1: per-axis measured wire — each axis's overshoot of its own
    # promise, the scalar defined as the sorted-key sum
    model = BudgetModel(compute_ms=6.0, axis_wire_ms=dict(axis_promise))
    assert model.wire_ms == 4.0  # the scalar promise IS the ledger's sum
    model.note_wire(9.2, by_axis={"dp": 7.3, "tp": 1.9})
    budget = model.settle(0, 16.0)
    assert budget.wire_axis_ms == pytest.approx({"dp": 4.3, "tp": 0.9})
    assert budget.components["wire_slowdown"] == (
        budget.wire_axis_ms["dp"] + budget.wire_axis_ms["tp"]
    )
    assert budget.axis_partition_error_ms() == 0.0
    assert budget.partition_error_ms() == pytest.approx(0.0, abs=1e-12)

    # path 2: scalar measured wire — proportional split by expected share,
    # the last (sorted) axis takes the exact remainder
    model.note_wire(9.0)
    budget = model.settle(1, 15.0)
    assert set(budget.wire_axis_ms) == {"dp", "tp"}
    assert budget.components["wire_slowdown"] == 5.0
    assert budget.wire_axis_ms["dp"] == pytest.approx(5.0 * 3.0 / 4.0)
    assert (budget.wire_axis_ms["dp"] + budget.wire_axis_ms["tp"]) == 5.0
    assert budget.axis_partition_error_ms() == 0.0

    # path 3: per-axis byte census — each axis's excess priced on its own
    # leg (here the ledger fallback), the scalar the sum of the parts
    census = BudgetModel(compute_ms=6.0, axis_wire_ms=dict(axis_promise))
    for step in range(5):
        census.settle(step, 10.0,
                      wire_bytes_by_axis={"dp": 1 << 20, "tp": 1 << 18})
    budget = census.settle(5, 14.0,
                           wire_bytes_by_axis={"dp": 1 << 21, "tp": 1 << 18})
    # dp doubled its bytes (1x excess over baseline, priced at its 3.0 ms
    # promise); tp stayed on baseline
    assert budget.wire_axis_ms["dp"] == pytest.approx(3.0)
    assert budget.wire_axis_ms["tp"] == 0.0
    assert budget.components["wire_slowdown"] == (
        budget.wire_axis_ms["tp"] + budget.wire_axis_ms["dp"]
    )
    assert budget.axis_partition_error_ms() == 0.0

    # axis-blind model: empty split, legacy scalar behavior unchanged
    legacy = BudgetModel(compute_ms=6.0, wire_ms=4.0)
    legacy.note_wire(9.0)
    budget = legacy.settle(0, 15.0)
    assert budget.wire_axis_ms == {}
    assert budget.components["wire_slowdown"] == 5.0
    assert budget.axis_partition_error_ms() == 0.0
    assert "wire_axis_ms" in budget.payload()


def test_budget_priced_axis_ledger_from_program_and_cost_model():
    """BudgetModel(program=...) joins the flight/IR records' ``axes``
    against the planner's per-axis α–β legs; a joint multi-axis record
    splits its bytes evenly across its axes, and axis-blind records are
    ignored."""
    from bagua_tpu.observability.attribution import priced_axis_wire_ms
    from bagua_tpu.service.planner import AlphaBeta, CostModel

    cm = CostModel(
        flat=AlphaBeta(0.0, 1e9),
        axis_legs={"dp": AlphaBeta(0.0, 1e8), "tp": AlphaBeta(0.0, 1e9)},
    )
    program = [
        {"algo": "gradient_allreduce", "bucket": 0, "nbytes": 1 << 20,
         "axes": ["dp"]},
        {"algo": "gradient_allreduce", "bucket": 1, "nbytes": 1 << 21,
         "axes": ["dp", "tp"]},  # joint exchange: bytes split evenly
        {"algo": "zero", "bucket": 0, "nbytes": 1 << 20},  # axis-blind
    ]
    ledger = priced_axis_wire_ms(cm, program)
    dp_bytes = (1 << 20) + (1 << 20)  # own record + half the joint one
    assert ledger["dp"] == pytest.approx(dp_bytes / 1e8 * 1e3)
    assert ledger["tp"] == pytest.approx((1 << 20) / 1e9 * 1e3)

    model = BudgetModel(compute_ms=6.0, cost_model=cm, program=program)
    assert model.axis_wire_ms == ledger
    # the scalar wire promise is the sorted-key sum of the ledger — bitwise
    assert model.wire_ms == ledger["dp"] + ledger["tp"]
    # no axes anywhere -> no ledger, wire stays unpriced
    blind = BudgetModel(compute_ms=6.0, cost_model=cm,
                        program=[{"algo": "zero", "bucket": 0,
                                  "nbytes": 1 << 20}])
    assert blind.axis_wire_ms == {} and blind.wire_ms is None


def test_sentinel_per_axis_stream_trips_and_names_link_class(tmp_path):
    """A sustained single-axis wire drift (wall flat: the collapse hides
    inside overlap slack) trips that axis's own CUSUM stream; the incident
    names the axis and resolves its physical link class (tp -> ici)."""
    sink = JsonlSink(str(tmp_path / "m.jsonl"))
    sentinel = RegressionSentinel(
        budget=BudgetModel(compute_ms=6.0,
                           axis_wire_ms={"dp": 3.0, "tp": 1.0}),
        sink=sink, warmup=10, threshold=8.0, cooldown=5, window=10,
    )
    step = 0
    for _ in range(20):
        sentinel.note_wire(4.0, by_axis={"dp": 3.0, "tp": 1.0})
        sentinel.observe_step(step, 10.0)
        step += 1
    assert not sentinel.incidents
    while not sentinel.incidents:
        # tp browns out; the wall stays flat so only the axis stream sees it
        sentinel.note_wire(10.0, by_axis={"dp": 3.0, "tp": 7.0})
        sentinel.observe_step(step, 10.0)
        step += 1
        assert step < 100, "axis stream never tripped"
    inc = sentinel.incidents[0]
    assert inc["stream"] == "wire_axis:tp"
    assert inc["axis"] == "tp" and inc["link_class"] == "ici"
    assert inc["wire_axis_ms"]["tp"] > inc["wire_axis_ms"]["dp"]
    assert sentinel.report()["axis_trips"]["tp"] >= 1
    sink.close()
    assert not validate_metrics_file(str(tmp_path / "m.jsonl"))

    # a committed config change resets the per-axis detectors and can
    # re-price the ledger alongside the scalar promise
    sentinel.rebaseline(wire_ms=2.0, axis_wire_ms={"dp": 1.5, "tp": 0.5})
    assert sentinel._axis_cusums == {}
    assert sentinel.budget.wire_ms == 2.0
    assert sentinel.budget.axis_wire_ms == {"dp": 1.5, "tp": 0.5}


def test_sentinel_wall_trip_indicts_dominant_axis():
    """A wall-stream trip whose verdict is wire-dominant picks the axis
    with the largest windowed slowdown (dp -> dcn link class)."""
    sentinel = RegressionSentinel(
        budget=BudgetModel(compute_ms=6.0,
                           axis_wire_ms={"dp": 3.0, "tp": 1.0}),
        warmup=10, threshold=8.0, cooldown=5, window=10,
    )
    step = 0
    for _ in range(20):
        sentinel.note_wire(4.0, by_axis={"dp": 3.0, "tp": 1.0})
        sentinel.observe_step(step, 10.0)
        step += 1
    while not sentinel.incidents:
        sentinel.note_wire(12.0, by_axis={"dp": 11.0, "tp": 1.0})
        sentinel.observe_step(step, 18.0)
        step += 1
        assert step < 100, "sentinel never tripped"
    inc = sentinel.incidents[0]
    assert inc["dominant"] == "wire_slowdown"
    assert inc["axis"] == "dp" and inc["link_class"] == "dcn"
    # incident-level partition: the axis split sums to the windowed
    # wire_slowdown component up to the payload rounding
    assert sum(inc["wire_axis_ms"].values()) == pytest.approx(
        inc["components"]["wire_slowdown"], abs=1e-2)


def test_telemetry_exports_per_axis_counters_and_gauges(tmp_path, monkeypatch):
    monkeypatch.setenv("BAGUA_REGRESSION_SENTINEL", "1")
    monkeypatch.setenv("BAGUA_REGRESSION_WARMUP", "5")
    path = str(tmp_path / "m.jsonl")
    tel = Telemetry(metrics_jsonl=path, flight=None)
    for step in range(6):
        tel.on_step(step, wall_s=0.010, n_samples=32, wire_bytes=3 << 16,
                    wire_bytes_by_axis={"dp": 1 << 17, "tp": 1 << 16})
    prom = tel.registry.to_prometheus()
    assert "bagua_wire_bytes_axis_dp_total" in prom
    assert "bagua_wire_bytes_axis_tp_total" in prom
    assert "bagua_step_budget_wire_dp_ms" in prom
    assert "bagua_step_budget_wire_tp_ms" in prom
    tel.close()
    assert not validate_metrics_file(path)
    with open(path) as f:
        events = [json.loads(line) for line in f if line.strip()]
    steps = [e for e in events if e.get("event") == "step"]
    assert steps and steps[-1]["wire_bytes_by_axis"] == {
        "dp": 1 << 17, "tp": 1 << 16,
    }
