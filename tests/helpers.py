"""Shared helpers for multi-process tests (worker spawning, ports, env)."""

import os
import socket
import subprocess

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker_env(**extra) -> dict:
    """Env for spawned workers: repo on PYTHONPATH, one device per process."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env.update(extra)
    return env


def spawn_and_collect(cmds, env, timeout=180):
    """Fan out worker commands and collect (rc, stdout, stderr) per worker.
    Always kills stragglers — a regression that deadlocks a worker must fail
    the test, not hang CI holding the rendezvous port."""
    procs = [
        subprocess.Popen(
            c, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True
        )
        for c in cmds
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs
