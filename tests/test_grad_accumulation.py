"""Gradient accumulation (no_sync analog): k accumulated microbatches equal
one step on their concatenation, no optimizer-state mutation off-boundary,
and centralized determinism is preserved."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bagua_tpu.algorithms import Algorithm, GradientAccumulation
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

N = 8


def _batches(rng, n, rows):
    return [
        (
            jnp.asarray(rng.randn(rows, 10), np.float32),
            jnp.asarray(rng.randn(rows, 4), np.float32),
        )
        for _ in range(n)
    ]


def test_accumulation_matches_concatenated_batches(group):
    """every=2 over half-batches == plain algorithm over the full batches
    (mean-reduction loss: the accumulated mean IS the full-batch gradient)."""
    params = init_mlp(jax.random.PRNGKey(0), [10, 16, 4])
    rng = np.random.RandomState(0)
    full = _batches(rng, 4, 32)
    halves = []
    for x, y in full:
        halves.append((x[:16], y[:16]))
        halves.append((x[16:], y[16:]))

    def run(algo, batches):
        ddp = DistributedDataParallel(
            mse_loss, optax.adam(1e-2), algo, process_group=group
        )
        state = ddp.init(params)
        for b in batches:
            state, _ = ddp.train_step(state, b)
        return ddp.params_unstacked(state)

    ref = run(Algorithm.init("gradient_allreduce"), full)
    acc = run(
        GradientAccumulation(Algorithm.init("gradient_allreduce"), every=2), halves
    )
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(acc)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_no_update_off_boundary(group):
    """Off-boundary steps leave params AND optimizer state untouched."""
    params = init_mlp(jax.random.PRNGKey(1), [10, 16, 4])
    ddp = DistributedDataParallel(
        mse_loss, optax.adam(1e-2),
        GradientAccumulation(Algorithm.init("bytegrad"), every=4),
        process_group=group,
    )
    state = ddp.init(params)
    rng = np.random.RandomState(1)
    b = _batches(rng, 1, 16)[0]
    before = jax.tree.map(np.asarray, (state.params, state.opt_state))
    for i in range(3):  # steps 0..2 of every=4: no boundary
        state, _ = ddp.train_step(state, b)
    after = jax.tree.map(np.asarray, (state.params, state.opt_state))
    for x, y in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(x, y)
    state, _ = ddp.train_step(state, b)  # step 3: boundary
    changed = any(
        not np.array_equal(x, np.asarray(y))
        for x, y in zip(jax.tree.leaves(before[0]), jax.tree.leaves(state.params))
    )
    assert changed, "boundary step applied no update"


def test_accumulated_bytegrad_keeps_ranks_equal(group):
    params = init_mlp(jax.random.PRNGKey(2), [10, 16, 4])
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05),
        GradientAccumulation(Algorithm.init("bytegrad"), every=2),
        process_group=group,
    )
    state = ddp.init(params)
    rng = np.random.RandomState(2)
    for b in _batches(rng, 6, 16):
        state, _ = ddp.train_step(state, b)
    for l in jax.tree.leaves(state.params):
        arr = np.asarray(l)
        for r in range(1, N):
            np.testing.assert_array_equal(arr[0], arr[r])
