"""MoE expert parallelism: gating invariants, EP all_to_all correctness,
end-to-end DDP training with experts excluded from DP sync."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from bagua_tpu.communication import ALL_AXES
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.parallel.moe import MoE, route_top1, route_top2
from bagua_tpu.parallel.moe.utils import split_moe_params

N = 8
MODEL_DIM = 8
NUM_EXPERTS = 8


def test_route_top1_invariants():
    rng = np.random.RandomState(0)
    S, E = 16, 4
    logits = jnp.asarray(rng.randn(S, E).astype(np.float32))
    l_aux, combine, dispatch, exp_counts = route_top1(logits, capacity_factor=1.0, min_capacity=2)
    C = combine.shape[-1]
    assert combine.shape == (S, E, C) and dispatch.shape == (S, E, C)
    # each token goes to at most one (expert, slot)
    assert int(jnp.sum(dispatch, axis=(1, 2)).max()) <= 1
    # each (expert, slot) holds at most one token
    assert int(jnp.sum(dispatch, axis=0).max()) <= 1
    # capacity respected
    assert int(jnp.sum(dispatch, axis=(0, 2)).max()) <= C
    # l_aux formula: sum(me*ce)*E
    gates = jax.nn.softmax(logits, axis=1)
    mask1 = jax.nn.one_hot(jnp.argmax(gates, axis=1), E)
    expect = jnp.sum(jnp.mean(gates, 0) * jnp.mean(mask1, 0)) * E
    np.testing.assert_allclose(float(l_aux), float(expect), rtol=1e-5)
    # exp_counts = tokens per expert pre-capacity
    np.testing.assert_array_equal(np.asarray(exp_counts), np.asarray(mask1.sum(0), np.int32))


def test_route_top2_invariants():
    rng = np.random.RandomState(1)
    S, E = 16, 4
    logits = jnp.asarray(rng.randn(S, E).astype(np.float32))
    l_aux, combine, dispatch, exp_counts = route_top2(logits, capacity_factor=1.0)
    # each token dispatched to at most 2 slots, combine weights sum to ~1
    per_token = jnp.sum(dispatch, axis=(1, 2))
    assert int(per_token.max()) <= 2
    sums = jnp.sum(combine, axis=(1, 2))
    kept = per_token > 0
    np.testing.assert_allclose(
        np.asarray(sums)[np.asarray(kept)], 1.0, rtol=1e-5
    )


def test_top1_capacity_truncation():
    # all tokens pick expert 0: capacity must cut the tail
    logits = jnp.tile(jnp.asarray([[10.0, 0.0]]), (8, 1))
    l_aux, combine, dispatch, exp_counts = route_top1(logits, capacity_factor=1.0, min_capacity=2)
    C = combine.shape[-1]
    assert int(jnp.sum(dispatch)) == min(8, C)
    assert int(exp_counts[0]) == 8  # pre-capacity count


def test_top1_used_token_masks_routing():
    """used_token=0 tokens are not routed at all and do not consume capacity
    (reference top1gating's used_token einsum, sharded_moe.py:122-123)."""
    rng = np.random.RandomState(2)
    S, E = 16, 4
    logits = jnp.asarray(rng.randn(S, E).astype(np.float32))
    used = jnp.asarray((np.arange(S) % 2 == 0).astype(np.float32))  # every other
    l_aux, combine, dispatch, exp_counts = route_top1(
        logits, capacity_factor=1.0, min_capacity=2, used_token=used
    )
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert (per_token[1::2] == 0).all()  # masked tokens never dispatched
    # demand histogram counts only used tokens
    mask1 = jax.nn.one_hot(jnp.argmax(jax.nn.softmax(logits, 1), axis=1), E)
    np.testing.assert_array_equal(
        np.asarray(exp_counts), np.asarray((used[:, None] * mask1).sum(0), np.int32)
    )


def test_top2_used_token_masks_routing():
    """used_token also masks top-2 routing (deliberate extension — the
    reference's top2gating drops the mask its TopKGate accepts)."""
    rng = np.random.RandomState(6)
    S, E = 16, 4
    logits = jnp.asarray(rng.randn(S, E).astype(np.float32))
    used = jnp.asarray((np.arange(S) < 8).astype(np.float32))
    _, combine, dispatch, exp_counts = route_top2(
        logits, capacity_factor=2.0, used_token=used
    )
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert (per_token[8:] == 0).all() and (per_token[:8] > 0).all()
    assert int(np.asarray(exp_counts).sum()) == 8  # only used first-choices


def test_top1_rsample_uses_noised_argmax_but_clean_weights():
    """RSample: argmax over gumbel-noised logits; combine weights and l_aux
    still come from the un-noised softmax (reference sharded_moe.py:101-117)."""
    rng = np.random.RandomState(3)
    S, E = 32, 4
    logits = jnp.asarray(rng.randn(S, E).astype(np.float32) * 0.1)  # near-uniform
    key = jax.random.PRNGKey(0)
    l_clean, c_clean, d_clean, _ = route_top1(logits, 2.0, min_capacity=2)
    l_noise, c_noise, d_noise, _ = route_top1(
        logits, 2.0, min_capacity=2, noisy_gate_policy="RSample", rng=key
    )
    # noise must change at least one token's expert choice on near-uniform logits
    assert not np.array_equal(np.asarray(d_clean), np.asarray(d_noise))
    # every dispatched token's combine weight equals its clean softmax prob
    probs = np.asarray(jax.nn.softmax(logits, axis=1))
    combine = np.asarray(c_noise)
    for s, e in zip(*np.nonzero(combine.sum(2))):
        np.testing.assert_allclose(combine[s, e].sum(), probs[s, e], rtol=1e-5)
    with pytest.raises(ValueError, match="requires an rng"):
        route_top1(logits, 2.0, noisy_gate_policy="RSample")


def test_min_capacity_floor_default():
    """Default min_capacity=4 (reference TopKGate default, sharded_moe.py:271):
    tiny batches still give each expert at least 4 slots."""
    logits = jnp.zeros((4, 8), jnp.float32)  # 4 tokens, 8 experts -> ceil=1
    _, combine, _, _ = route_top1(logits, capacity_factor=1.0)
    assert combine.shape[-1] == 4


def test_router_jitter_and_eval_capacity(group):
    """Jitter multiplies the gate input by uniform(1-1e-2, 1+1e-2) in training
    only; eval uses eval_capacity_factor (reference TopKGate.forward:282-303)."""
    from bagua_tpu.parallel.moe.layer import Router

    rng = np.random.RandomState(4)
    tokens = jnp.asarray(rng.randn(16, MODEL_DIM).astype(np.float32))
    router = Router(
        num_experts=4, k=1, capacity_factor=2.0, eval_capacity_factor=0.5,
        min_capacity=1, noisy_gate_policy="Jitter",
    )
    params = router.init(jax.random.PRNGKey(0), tokens)
    key = jax.random.PRNGKey(7)
    train_routing = router.apply(params, tokens, train=True, rng=key)
    eval_routing = router.apply(params, tokens, train=False)
    # capacity: train ceil(16/4*2)=8 vs eval max(ceil(16/4*0.5), 1)=2
    assert train_routing.combine_weights.shape[-1] == 8
    assert eval_routing.combine_weights.shape[-1] == 2
    # jitter is bounded: dispatch demand may shift but the weights stay within
    # the clean softmax's neighborhood; eval (no jitter) is deterministic
    eval2 = router.apply(params, tokens, train=False)
    np.testing.assert_array_equal(
        np.asarray(eval_routing.combine_weights), np.asarray(eval2.combine_weights)
    )
    # train=True without rng must fail loudly for Jitter
    with pytest.raises(ValueError, match="requires an rng"):
        router.apply(params, tokens, train=True)
    bad = Router(num_experts=4, noisy_gate_policy="Wiggle")
    with pytest.raises(ValueError, match="unknown noisy_gate_policy"):
        bad.init(jax.random.PRNGKey(0), tokens)


@pytest.mark.slow
def test_moe_used_token_end_to_end(group):
    """used_token flows MoE -> ExpertParallelFFN -> Router: masked tokens
    produce zero MoE output."""
    x = jnp.asarray(np.random.RandomState(5).randn(2, 8, MODEL_DIM), jnp.float32)
    used = jnp.ones((2, 8), jnp.float32).at[0, :4].set(0.0)
    moe = MoE(hidden_size=MODEL_DIM * 2, num_experts=4, capacity_factor=4.0,
              ep_size=1, ep_axis=None)
    params = moe.init(jax.random.PRNGKey(0), x)
    out, _ = moe.apply(params, x, used_token=used)
    out = np.asarray(out)
    assert np.all(out[0, :4] == 0.0)  # masked tokens: nothing routed back
    assert np.any(out[0, 4:] != 0.0)  # unmasked tokens flow through experts


class MoEModel(nn.Module):
    num_experts: int
    ep_size: int
    k: int = 1

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(MODEL_DIM)(x)
        h = jax.nn.relu(h)
        out, l_aux = MoE(
            hidden_size=MODEL_DIM * 2,
            num_experts=self.num_experts,
            k=self.k,
            capacity_factor=2.0,
            ep_size=self.ep_size,
            ep_axis=ALL_AXES,
        )(h)
        out = nn.Dense(4)(out)
        return out, l_aux


def moe_loss_fn(model):
    def loss_fn(params, batch):
        x, y = batch
        logits, l_aux = model.apply({"params": params}, x)
        mse = jnp.mean((logits - y) ** 2)
        return mse + 0.01 * l_aux

    return loss_fn


@pytest.mark.slow
def test_ep_matches_local_when_experts_tiled(group):
    """With identical (tiled) expert params, the distributed EP dispatch must
    produce the same per-rank output as running all experts locally."""
    rng = np.random.RandomState(2)
    x = rng.randn(N, 16, MODEL_DIM).astype(np.float32)  # per-rank tokens

    # local model: all experts on every rank
    local_model = MoEModel(num_experts=NUM_EXPERTS, ep_size=1)
    params = local_model.init(jax.random.PRNGKey(0), jnp.asarray(x[0]))["params"]

    local_out = np.stack(
        [np.asarray(local_model.apply({"params": params}, jnp.asarray(x[r]))[0]) for r in range(N)]
    )

    # EP model: same math, experts sharded over 8 ranks (1 expert each).
    ep_model = MoEModel(num_experts=NUM_EXPERTS, ep_size=N)
    ep_params = ep_model.init(jax.random.PRNGKey(0), jnp.asarray(x[0]))["params"]

    # Map the local model's expert e params to EP rank e's single local expert.
    def to_rank(r, tree_local, tree_ep):
        return jax.tree.map(
            lambda le, ee: le[r : r + 1] if le.shape[:1] == (NUM_EXPERTS,) and ee.shape[:1] == (1,) else le,
            tree_local, tree_ep,
        )

    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[to_rank(r, params, ep_params) for r in range(N)],
    )

    fn = jax.jit(
        group.shard_map(
            lambda p, xx: ep_model.apply({"params": jax.tree.map(lambda q: q[0], p)}, xx[0])[0][None],
            in_specs=(P(ALL_AXES), P(ALL_AXES)),
            out_specs=P(ALL_AXES),
        )
    )
    ep_out = np.asarray(fn(stacked, jnp.asarray(x)))
    np.testing.assert_allclose(ep_out, local_out, rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("k", [1, 2])
@pytest.mark.slow
def test_moe_ddp_training(group, k):
    """End-to-end: DDP + MoE with experts excluded from DP; expert params
    diverge across ranks, non-expert params stay bitwise equal
    (reference CI MoE benchmark, benchmark_master.sh:109-144)."""
    model = MoEModel(num_experts=NUM_EXPERTS, ep_size=N, k=k)
    rng = np.random.RandomState(3)
    x0 = jnp.asarray(rng.randn(16, MODEL_DIM).astype(np.float32))
    # per-rank independent expert init
    per_rank = [
        model.init(jax.random.PRNGKey(100 + r), x0)["params"] for r in range(N)
    ]
    # non-expert params must start equal: take rank 0's everywhere
    base = per_rank[0]

    def merge(r):
        def pick(path, b, pr):
            return pr if "experts" in jax.tree_util.keystr(path) else b

        return jax.tree_util.tree_map_with_path(pick, base, per_rank[r])

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *[merge(r) for r in range(N)])

    ddp = DistributedDataParallel(
        moe_loss_fn(model),
        optax.adam(1e-2),
        __import__("bagua_tpu.algorithms", fromlist=["x"]).GradientAllReduceAlgorithm(),
        process_group=group,
        dp_filter=lambda name: "experts" not in name,
    )
    state = ddp.init(stacked_params=stacked)

    losses_hist = []
    for i in range(8):
        batch = (
            jnp.asarray(rng.randn(N * 16, MODEL_DIM), np.float32),
            jnp.asarray(rng.randn(N * 16, 4), np.float32),
        )
        state, losses = ddp.train_step(state, batch)
        losses_hist.append(float(losses.mean()))

    assert all(np.isfinite(losses_hist))
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        arr = np.asarray(leaf)
        if "experts" in name:
            assert not all(
                np.array_equal(arr[0], arr[r]) for r in range(1, N)
            ), f"expert param {name} should differ across ranks"
        else:
            for r in range(1, N):
                np.testing.assert_array_equal(arr[0], arr[r], err_msg=name)


@pytest.mark.slow
def test_split_moe_params():
    model = MoEModel(num_experts=4, ep_size=1)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((4, MODEL_DIM)))["params"]
    non_expert, expert = split_moe_params(params)
    assert expert and non_expert
    assert all("experts" in k for k in expert)
    assert all("experts" not in k for k in non_expert)


# ---------------------------------------------------------------------------
# Chunked (overlapped) all-to-all schedule
# ---------------------------------------------------------------------------


def _ep_mesh(n=N):
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n]), ("ep",))


def _run_moe_sharded(moe, params, x):
    fn = jax.jit(
        jax.shard_map(
            lambda xx: moe.apply({"params": params}, xx)[0],
            mesh=_ep_mesh(),
            in_specs=P("ep", None),
            out_specs=P("ep", None),
            check_vma=False,
        )
    )
    return np.asarray(fn(x))


@pytest.mark.parametrize("chunks", [2, 4])
def test_a2a_chunks_bitwise_matches_unchunked(chunks):
    """The chunked dispatch->expert->combine schedule is EXACT: the expert
    FFN is position-wise, so splitting the capacity axis changes the overlap
    structure but not one bit of the result."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(N * 16, MODEL_DIM).astype(np.float32))

    def build(c):
        moe = MoE(
            hidden_size=MODEL_DIM * 2, num_experts=NUM_EXPERTS, ep_size=N,
            ep_axis="ep", capacity_factor=2.0, a2a_chunks=c,
        )
        params = moe.init(jax.random.PRNGKey(0), x[:16])["params"]
        return moe, params

    moe1, params1 = build(1)
    moec, paramsc = build(chunks)
    # shared Experts instance => identical parameter structure either way
    assert jax.tree.map(jnp.shape, params1) == jax.tree.map(jnp.shape, paramsc)
    ref = _run_moe_sharded(moe1, params1, x)
    got = _run_moe_sharded(moec, params1, x)
    np.testing.assert_array_equal(got, ref)


def test_a2a_chunks_clamps_to_capacity_divisor():
    from bagua_tpu.parallel.moe.layer import ExpertParallelFFN

    ffn = ExpertParallelFFN(num_experts=8, hidden_dim=16, a2a_chunks=5)
    assert ffn._resolve_chunks(8) == 4  # nearest divisor <= requested
    assert ffn._resolve_chunks(7) == 1
    big = ExpertParallelFFN(num_experts=8, hidden_dim=16, a2a_chunks=64)
    assert big._resolve_chunks(8) == 8  # never exceeds the capacity


def test_typod_ep_axis_raises_clear_error():
    """A misspelled ep_axis must fail loudly, not silently degrade to
    single-rank expert compute (the all-to-alls would just vanish)."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(N * 8, MODEL_DIM).astype(np.float32))
    moe = MoE(
        hidden_size=MODEL_DIM * 2, num_experts=NUM_EXPERTS, ep_size=N,
        ep_axis="exprt",  # typo: the mesh binds "ep"
        capacity_factor=2.0,
    )
    params = moe.init(jax.random.PRNGKey(0), x[:8])["params"]
    with pytest.raises(ValueError, match="none of the declared expert-parallel axes"):
        jax.jit(
            jax.shard_map(
                lambda xx: moe.apply({"params": params}, xx)[0],
                mesh=_ep_mesh(),
                in_specs=P("ep", None),
                out_specs=P("ep", None),
                check_vma=False,
            )
        )(x)
