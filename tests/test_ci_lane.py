"""Tier-1 lane for the CI perf gates: the overlap wire-pattern assertion.

Drives ``ci/perf_audit.py --quick --model=mlp --ddp-only`` as a subprocess —
the same entry point CI uses — so a regression in the overlap census (bucket
collectives merged back into a monolithic tail, or wire bytes drifting from
the monolithic path) fails the ``not slow`` suite, not just a nightly.  The
same invocation runs the telemetry smoke (a short instrumented lane whose
JSONL metrics stream is schema-validated and must be retrace-free).  The
mlp model keeps this at seconds scale; the VGG16 audit stays in the full
``ci/perf_audit.py`` run.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_perf_audit_quick_overlap_census(tmp_path):
    out = tmp_path / "audit"
    env = dict(os.environ)
    # the subprocess builds its own 8-device CPU sim; don't inherit a
    # conflicting device count from the test session
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "ci", "perf_audit.py"),
            "--quick", "--model=mlp", "--ddp-only", "--out", str(out),
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"perf_audit --quick failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    assert "overlap wire-pattern assertion passed" in proc.stderr
    assert "telemetry metrics schema check passed" in proc.stderr
    assert "autotune planner lane passed" in proc.stderr
    assert "fault-injection resilience lane passed" in proc.stderr
    assert "health guardrail lane passed" in proc.stderr
    assert "hang forensics lane passed" in proc.stderr
    assert "tracing lane passed" in proc.stderr
    assert "static verify lane passed" in proc.stderr
    assert "retrace-hazard lint passed" in proc.stderr
    assert "bench modeled lane passed" in proc.stderr
    assert "fleet sim lane passed" in proc.stderr
    assert "fleet load lane passed" in proc.stderr
    assert "fleet scale lane passed" in proc.stderr
    assert "regression attribution lane passed" in proc.stderr
    assert "autopilot lane passed" in proc.stderr
    assert "axis attribution lane passed" in proc.stderr

    # The telemetry smoke emits a JSONL metrics stream next to --out; hold it
    # to the event schema here too (belt and braces: the subprocess already
    # validated it, this catches a validator that silently stopped running).
    from bagua_tpu.observability import validate_metrics_file

    metrics_path = str(out) + "_metrics.jsonl"
    assert os.path.exists(metrics_path), "telemetry smoke did not emit metrics"
    assert validate_metrics_file(metrics_path) == []
    with open(metrics_path) as f:
        events = [json.loads(line) for line in f if line.strip()]
    kinds = [e["event"] for e in events]
    assert kinds.count("compile") == 1, kinds  # warmup only — no retraces
    assert kinds.count("step") >= 5  # steady-state steps
    assert all(not e.get("retrace") for e in events if e["event"] == "compile")
    # Prometheus textfile exported alongside, with the core families present
    prom = open(str(out) + "_metrics.prom").read()
    assert "bagua_steps_total" in prom and "bagua_step_wall_ms_count" in prom

    with open(str(out) + ".json") as f:
        audit = json.load(f)
    rows = audit["ddp"]
    for name in (
        "gradient_allreduce", "gradient_allreduce[flat]",
        "gradient_allreduce[overlap]", "gradient_allreduce[overlap,flat]",
    ):
        assert name in rows, f"missing audit row {name}"
    ov_flat = rows["gradient_allreduce[overlap,flat]"]
    assert ov_flat["overlap"] is True
    assert ov_flat["census"]["all-reduce"]["count"] == ov_flat["buckets"]
    assert ov_flat["buckets"] < ov_flat["slots"]  # multi-slot plan: the
    # per-bucket count is genuinely distinguishable from per-leaf

    # The recorded-span planner gate: DP plan must beat the greedy seed plan
    # on predicted exposed comm (the subprocess asserted it; re-check the
    # recorded numbers so a silently-skipped lane can't pass).
    planner = audit["autotune_planner"]
    assert (
        planner["planner_plan"]["predicted_exposed_ms"]
        < planner["greedy_plan"]["predicted_exposed_ms"]
    )
    assert planner["gain_ms"] > 0

    # The fault-injection lane's artifact: a killed-and-resumed gang landed
    # bitwise-identical to the uninterrupted reference run, on the carried
    # bucket plan, losing no more work than the snapshot cadence bounds.
    with open(str(out) + "_resilience.json") as f:
        resilience = json.load(f)
    fi = resilience["fault_injection"]
    assert fi["bitwise_identical"] is True
    assert fi["plan_source"] == "carried"
    assert fi["lost_steps"] <= 2 * fi["snapshot_every"]
    assert audit["resilience"]["fault_injection"] == fi
    assert resilience["overhead"]["p50_on_ms"] > 0

    # The health-guardrail lane's artifact: the detector fired on the
    # synthetic loss spike AND the forced NaN, the demotion action moved
    # the planner-chosen int8 wire to f32 (census-confirmed: zero uint8
    # collective bytes afterwards), and the NaN latch is set.
    health = audit["health"]
    kinds = {a["kind"] for a in health["alerts"]}
    assert {"loss_spike", "nonfinite"} <= kinds
    assert any("precision_demotion" in a["actions"] for a in health["alerts"])
    assert set(health["precisions_before"]) == {"int8"}
    assert set(health["precisions_after"]) == {"f32"}
    assert health["nan_latched"] is True
    assert health["census_u8_bytes"] == 0
    assert health["census_f32_allreduce"] >= 1  # f32 all-reduce count post-demotion
    # the lane's own JSONL stream validated (health_alert schema included)
    health_metrics = str(out) + "_health_metrics.jsonl"
    assert os.path.exists(health_metrics)
    assert validate_metrics_file(health_metrics) == []
    with open(health_metrics) as f:
        hev = [json.loads(line) for line in f if line.strip()]
    assert {e["kind"] for e in hev if e["event"] == "health_alert"} >= {
        "loss_spike", "nonfinite"}
    assert any(
        e["event"] == "precision_switch" and e["reason"].startswith("health:")
        for e in hev)

    # The hang-forensics lane's artifact: the recorder was bitwise-inert and
    # within noise on the hot path, and the analyzer attributed the injected
    # one-rank wedge to the exact collective (rank 2, the skipped bucket's
    # label/phase/plan_version) as a schema-valid hang_report.
    hang = audit["hang_forensics"]
    assert hang["verdict"] == "desync"
    assert hang["divergent_ranks"] == [2]
    assert hang["bitwise_identical"] is True
    assert hang["first_divergence_seq"] >= 0
    blocked = hang["blocked_on"]
    assert blocked["label"].startswith("bagua_ex/")
    assert blocked["bucket"] >= 0 and blocked["phase"]
    assert hang["p50_ms_recorder_on"] > 0 and hang["p50_ms_recorder_off"] > 0
    report_path = str(out) + "_hang_report.json"
    assert os.path.exists(report_path), "hang lane did not emit its report"
    from bagua_tpu.observability import validate_hang_report

    with open(report_path) as f:
        report = json.load(f)
    assert validate_hang_report(report) == []
    assert report["blocked_on"]["label"] == blocked["label"]

    # The tracing lane's artifact: tracing-on bitwise-identical to off and
    # within noise, the induced 429s attributed, the cross-process client->
    # server chain joined on /fleet/timeline, and the Perfetto export
    # re-validating against the Chrome trace-event schema.
    tr = audit["tracing"]
    assert tr["bitwise_identical"] is True
    assert tr["n_step_traces"] >= 2 and tr["n_spans"] > tr["n_step_traces"]
    assert tr["n_shed_429"] >= 1 and tr["n_retry_annotations"] >= 1
    assert tr["n_server_spans"] >= 1 and tr["n_flow_links"] >= 1
    assert tr["p50_ms_tracing_on"] > 0 and tr["p50_ms_tracing_off"] > 0
    trace_path = str(out) + "_trace.json"
    assert os.path.exists(trace_path), "tracing lane did not emit its export"
    sys.path.insert(0, os.path.join(REPO, "ci"))
    try:
        from export_timeline import validate_chrome_trace
    finally:
        sys.path.pop(0)
    with open(trace_path) as f:
        chrome = json.load(f)
    assert validate_chrome_trace(chrome) == []
    assert any(e["ph"] == "X" and e["name"] == "train_step"
               for e in chrome["traceEvents"])

    # The static-verify lane's artifact: strict four-checker verification of
    # the modeled wire programs, all trace-time (nothing dispatched), plus
    # the retrace-hazard lint holding the baseline allowlist.
    sv = audit["static_verify"]
    assert sv["mode"] == "strict"
    configs = {row["config"]: row for row in sv["configs"]}
    assert set(configs) == {
        "gradient_allreduce", "gradient_allreduce[int8]", "zero",
    }
    for row in configs.values():
        assert row["ok"] is True
        assert row["num_collectives"] > 0
        assert row["bucket_phases"] > 0
    assert audit["retrace_lint"]["ok"] is True

    # The perf-lab gates: the modeled step-time regression check held the
    # committed BENCH_MODELED.json (exact census bytes, step-ms tolerance),
    # and the fleet simulator drove the real aggregator/breaker paths against
    # a live loopback rendezvous with both injected faults surfaced.
    bm = audit["bench_modeled"]
    assert bm["ok"] is True and bm["checked_cells"] >= 10
    assert bm["artifact_summary"]["fail"] == 0
    fleet = audit["fleet_sim"]
    assert fleet["ok"] is True and fleet["deterministic"] is True
    assert fleet["n_gangs"] >= 4
    assert fleet["straggler_detections"]
    assert all(
        d["rank"] == 2 and d["phase"] == "wire"
        for d in fleet["straggler_detections"]
    )
    assert fleet["flap_breaker"]["times_opened"] >= 1
    assert fleet["flap_breaker"]["final_state"] == "closed"

    # The fleet control-plane load lane's artifact: ≥8 simulated gangs on one
    # WAL-backed multi-tenant server — zero cross-gang leakage under the
    # adversarial probe, raw 429s under the hammer while the paced client's
    # breaker never counts one, p99 RPC latency inside the gate, a mid-run
    # SIGKILL whose WAL replay lands the durable dump bitwise-identical with
    # rider clients observing the outage and recovering, and a second engine
    # adopting the pre-kill cached plan at step 0 with plan_source="fleet".
    with open(str(out) + "_fleet_load.json") as f:
        fl = json.load(f)
    assert fl["fleet_sim"]["n_gangs"] >= 8
    assert fl["fleet_sim"]["healthy"] == fl["fleet_sim"]["n_gangs"]
    assert fl["fleet_sim"]["churn_stale_ranks"] == [1]  # preempted rank surfaced
    assert fl["scheduler"]["straggler"]["rank"] == 2
    assert fl["scheduler"]["straggler"]["phase"] == "wire"
    assert fl["isolation"]["leaks"] == 0 and fl["isolation"]["probes"] >= 6
    assert fl["backpressure"]["denials_429"] >= 1
    assert fl["backpressure"]["retry_after_s_min"] >= 1
    assert fl["backpressure"]["paced_breaker_opened"] == 0
    assert fl["latency"]["p99_ms"] <= fl["latency"]["gate_ms"]
    assert fl["sigkill"]["dump_bitwise_identical"] is True
    assert fl["sigkill"]["rider_failures"] >= 1
    assert fl["sigkill"]["rider_breaker_opened"] >= 1
    assert fl["plan_adoption"]["plan_source"] == "fleet"
    assert fl["plan_adoption"]["published_before_kill"] is True
    assert audit["fleet_load"] == fl

    # The 1000-gang scale lane's quick variant: a sharded selector-loop
    # control plane absorbed the thundering herd with the canary gate
    # holding every non-cohort gang, held the p99 latency and scheduler
    # staleness gates under a preemption storm + KV flap (real 429s drawn),
    # closed all three remediation arcs — exact-correlation quarantine with
    # zero false positives and fleet-wide rollback, wedged-gang hang
    # diagnosis -> resize directive, canary graduation — and replayed every
    # per-shard WAL to the bitwise dump after a SIGKILL.
    with open(str(out) + "_fleet_scale.json") as f:
        fs = json.load(f)
    assert fs["n_gangs"] >= 100 and fs["server"]["shards"] == 4
    assert fs["herd"]["gangs"] == fs["n_gangs"]
    assert fs["herd"]["withheld_by_canary_gate"] >= fs["n_gangs"] - 2
    assert all(n > 0 for n in fs["herd"]["gangs_per_shard"])
    assert fs["churn"]["flap_429"] >= 1
    assert fs["latency"]["p99_ms"] <= fs["latency"]["gate_ms"]
    assert fs["staleness"]["observed_s"] <= fs["staleness"]["gate_s"]
    rem = fs["remediation"]
    assert rem["false_quarantines"] == 0
    assert len(rem["quarantined"]) == 1 and rem["quarantine_cites"]
    assert rem["rollback_gangs"] == ["b0", "b1"]
    assert rem["resize"]["verdict"] == "desync"
    assert rem["resize"]["to_world_size"] == 1
    assert rem["idempotent_resweep"] is True and rem["graduated"]
    assert fs["sigkill"]["dump_bitwise_identical"] is True
    assert fs["sigkill"]["remediation_state_survived"] is True
    assert all(
        0 < ms <= fs["sigkill"]["replay_gate_ms"]
        for ms in fs["sigkill"]["wal_replay_ms"]
    )
    assert audit["fleet_scale"] == fs

    # The regression-attribution lane's artifact: a clean 200-step sentinel-on
    # run emitted zero perf_regression incidents while exporting every
    # per-component budget gauge; sentinel on vs off was bitwise-identical for
    # gradient_allreduce AND zero; each of the four injected causes tripped
    # with the matching dominant component (partition summing to the residual
    # within 1%); and ingesting the incidents flipped the fleet scheduler
    # verdict to regressed.
    reg = audit["regression_attribution"]
    assert reg["ok"] is True
    assert reg["clean_steps"] >= 200 and reg["clean_incidents"] == 0
    assert reg["bitwise_identical"] is True
    causes = {"compile", "snapshot", "straggler", "wire_slowdown"}
    assert set(reg["injected"]) == causes
    for cause, inc in reg["injected"].items():
        assert inc["dominant"] == cause, reg["injected"]
        assert inc["stream"] in ("step_wall", "goodput")
        assert inc["partition_error_ms"] <= 0.01 * max(
            1.0, abs(inc["residual_ms"]))
    assert reg["straggler_rank"] == 2  # fleetsim's injected wire straggler
    assert reg["scheduler_verdict"] == "regressed"
    reg_metrics = str(out) + "_regression_metrics.jsonl"
    assert os.path.exists(reg_metrics), "regression lane did not emit metrics"
    assert validate_metrics_file(reg_metrics) == []
    with open(reg_metrics) as f:
        rev = [json.loads(line) for line in f if line.strip()]
    assert not [e for e in rev if e["event"] == "perf_regression"]
    reg_prom = open(reg_metrics + ".prom").read()
    assert "bagua_step_budget_compile_ms" in reg_prom
    assert "bagua_step_budget_wire_slowdown_ms" in reg_prom
    assert "bagua_step_budget_unattributed_ms" in reg_prom

    # The gang-autopilot lane's artifact: under the fleetsim bandwidth
    # collapse the controller demoted to the α–β-cheapest healthy config
    # (modeled strictly below stay-put), committed via canary loss-parity,
    # and re-promoted to f32 after recovery + quarantine — the closed loop,
    # both directions, with zero strict-verifier rejections dispatched.
    ap = audit["autopilot"]
    assert ap["ok"] is True
    assert ap["verifier_rejections"] == 0
    assert ap["demote_modeled"]["chosen_ms"] < ap["demote_modeled"]["stay_ms"]
    assert ap["demote_modeled"]["bandwidth_factor"] > 1.0
    assert ap["repromote_modeled"]["chosen_ms"] < ap["repromote_modeled"]["stay_ms"]
    assert ap["repromote_modeled"]["bandwidth_factor"] == 1.0
    # ordering: demote -> commit -> (recovery + quarantine) -> repromote -> commit
    assert (ap["demote_step"] < ap["demote_commit_step"]
            < ap["repromote_step"] < ap["repromote_commit_step"])
    assert ap["final_configuration"] == {
        "algorithm": "gradient_allreduce", "precision": "f32"}
    assert ap["wire_incidents"] >= 1 and ap["loss_spike_alerts"] >= 1
    assert ap["scheduler_autopilot"]["decision"] == "repromote_precision"
    assert ap["scheduler_autopilot"]["verdict"] == "committed"
    # the lane's own JSONL stream validated, with the decisions present
    ap_metrics = str(out) + "_autopilot_metrics.jsonl"
    assert os.path.exists(ap_metrics), "autopilot lane did not emit metrics"
    assert validate_metrics_file(ap_metrics) == []
    with open(ap_metrics) as f:
        apev = [json.loads(line) for line in f if line.strip()]
    decisions = [e for e in apev if e["event"] == "plan_decision"]
    assert len(decisions) == ap["decisions"]
    assert {d["decision"] for d in decisions} >= {
        "demote_precision", "repromote_precision"}
    inc_traces = {e["trace_id"] for e in apev if e["event"] == "perf_regression"}
    assert all(d["trace_id"] in inc_traces for d in decisions), decisions

    # Axis attribution lane: a dp4xtp2 mesh run where a tp (ici) brownout is
    # held — model-axis wire is not repriceable by exchange demotion — and a
    # dp (dcn) brownout demotes, with the budget's per-axis split exact and
    # the axis/link_class fields surviving the full fleet/scheduler join.
    ax = audit["axis_attribution"]
    assert ax["ok"] is True
    assert ax["mesh"] == {"dp": 4, "tp": 2}
    assert ax["bitwise_identical"] is True
    assert ax["axis_partition_max_error_ms"] == 0.0
    assert ax["tp_incidents"] >= 1 and ax["tp_link_class"] == "ici"
    assert ax["dp_incidents"] >= 1 and ax["dp_link_class"] == "dcn"
    assert ax["tp_holds"] >= 1  # every tp incident held, never demoted
    assert ax["demote_axis"] == "dp" and ax["demote_step"] > 0
    assert ax["scheduler_last_incident"]["axis"] == "dp"
    assert ax["scheduler_last_incident"]["link_class"] == "dcn"
    assert ax["scheduler_autopilot"]["decision"] == "demote_precision"
    assert ax["scheduler_autopilot"]["axis"] == "dp"


def test_perf_audit_quick_bytegrad_compressed_census(tmp_path):
    """Satellite lane: ``--quick --algo=bytegrad`` audits the compressed
    overlap pipeline — per-bucket uint8 all-to-all/all-gather counts and
    exact wire-byte parity against the monolithic row — at mlp scale."""
    out = tmp_path / "audit_bytegrad"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "ci", "perf_audit.py"),
            "--quick", "--algo=bytegrad", "--model=mlp", "--ddp-only",
            "--out", str(out),
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"perf_audit --quick --algo=bytegrad failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    assert "compressed overlap wire-pattern assertion passed" in proc.stderr

    with open(str(out) + ".json") as f:
        audit = json.load(f)
    rows = audit["ddp"]
    assert "bytegrad" in rows and "bytegrad[overlap]" in rows
    mono, ov = rows["bytegrad"], rows["bytegrad[overlap]"]
    assert ov["overlap"] is True and mono["overlap"] is False
    assert ov["buckets"] > 1
    for op in ("all-to-all", "all-gather"):
        # one u8 payload collective per bucket, byte-identical to monolithic
        assert ov["census"][op]["by_dtype"]["u8"]["count"] == ov["buckets"]
        assert (
            ov["census"][op]["by_dtype"]["u8"]["bytes"]
            == mono["census"][op]["by_dtype"]["u8"]["bytes"]
        )


def test_perf_audit_quick_stale_straggler_tolerance(tmp_path):
    """Satellite lane: ``--quick --algo=stale`` drives the full
    straggler-tolerance arc as a subprocess — a transient 1.5× compute
    straggler degrades rank 2 into bounded-staleness replay (decision citing
    the incident trace), an injected loss spike tightens τ→0 through the
    health guardrail, stabilized windows re-promote, and the healed
    straggler restores bulk sync — with modeled goodput under both
    relaxations strictly better than bulk sync and τ=0 bitwise gates held."""
    out = tmp_path / "audit_stale"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "ci", "perf_audit.py"),
            "--quick", "--algo=stale", "--model=mlp", "--ddp-only",
            "--out", str(out),
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"perf_audit --quick --algo=stale failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    assert "stale census assertion passed" in proc.stderr
    assert "straggler tolerance lane passed" in proc.stderr

    with open(str(out) + ".json") as f:
        audit = json.load(f)
    rows = audit["ddp"]
    assert "stale" in rows and "stale[overlap]" in rows
    # stale-sync materializes the flat contribution: ONE all-reduce per
    # bucket, byte-identical to the baseline's gradient exchange
    base = rows["gradient_allreduce"]
    for name in ("stale", "stale[overlap]"):
        row = rows[name]
        assert row["census"]["all-reduce"]["count"] == row["buckets"]
        assert (
            row["census"]["all-reduce"]["by_dtype"]["f32"]["bytes"]
            == base["census"]["all-reduce"]["by_dtype"]["f32"]["bytes"]
        )

    st = audit["straggler_tolerance"]
    assert st["ok"] is True
    assert st["verifier_rejections"] == 0
    # the degradation decision targeted the injected straggler...
    assert st["degrade_ranks"] == [2]
    assert st["degrade_modeled"]["chosen_ms"] < st["degrade_modeled"]["stay_ms"]
    assert st["degrade_modeled"]["straggler_excess_ms"] > 0
    # ...and the arc ran in order: degrade -> tighten -> repromote -> restore
    assert (st["degrade_step"] < st["tighten_step"]
            < st["repromote_step"] < st["restore_step"])
    assert st["switch_reasons"] == [
        "autopilot:straggler", "health:loss_spike",
        "autopilot:stabilized", "autopilot:straggler_healed",
    ]
    assert st["final_tau"] == 0
    assert st["scheduler_autopilot"]["decision"] == "restore_bulk_sync"
    assert st["scheduler_autopilot"]["verdict"] == "committed"
    # replay genuinely skipped exchanges, and the bound forced fresh rounds
    assert st["straggler_incidents"] >= 1
    assert st["skipped_rounds"] > 0 and st["fresh_rounds"] > 0
    # the wire ledger shows the degraded rank shipping fewer bytes than a
    # healthy rank over the degraded span
    assert st["accounting_bytes"]["2"] < st["accounting_bytes"]["0"]
    # modeled goodput: both relaxations strictly beat bulk sync under the
    # 1.5x transient straggler
    m = st["modeled_ms"]
    assert m["stale"] < m["bulk_sync"] and m["gossip"] < m["bulk_sync"]
    # τ=0 bitwise gates, both families
    assert set(st["bitwise_tau0"]) == {
        "stale[tau=0]==gradient_allreduce",
        "decentralized[gossip,tau=0]==decentralized",
    }


def test_perf_audit_quick_zero_sharded_census(tmp_path):
    """Satellite lane: ``--quick --algo=zero`` audits the sharded three-leg
    exchange — exactly one reduce-scatter and one all-gather per bucket, no
    gradient all-reduce, the RS ring bytes at ~0.5× (gated ≤0.55×) the
    all-reduce baseline's, and per-chip optimizer state at ~1/n."""
    out = tmp_path / "audit_zero"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "ci", "perf_audit.py"),
            "--quick", "--algo=zero", "--model=mlp", "--ddp-only",
            "--out", str(out),
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"perf_audit --quick --algo=zero failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    assert "zero sharded wire-pattern assertion passed" in proc.stderr

    with open(str(out) + ".json") as f:
        audit = json.load(f)
    rows = audit["ddp"]
    assert "zero" in rows and "zero[overlap]" in rows
    base = rows["gradient_allreduce"]
    n = 8  # the subprocess builds its own 8-device CPU sim

    def op_bytes(row, op):
        return sum(
            d["bytes"]
            for d in row["census"].get(op, {}).get("by_dtype", {}).values()
        )

    for name in ("zero", "zero[overlap]"):
        row = rows[name]
        assert row["buckets"] > 1
        # one RS (gradient leg) + one AG (parameter-update leg) per bucket,
        # and the all-reduce is gone entirely
        assert row["census"]["reduce-scatter"]["count"] == row["buckets"]
        assert row["census"]["all-gather"]["count"] == row["buckets"]
        assert row["census"].get("all-reduce", {"count": 0})["count"] == 0
        # ring traffic of the gradient exchange: RS result bytes are
        # payload/n, wire = result*(n-1); AR wire = result*2(n-1)/n
        rs_wire = op_bytes(row, "reduce-scatter") * (n - 1)
        ar_wire = op_bytes(base, "all-reduce") * 2 * (n - 1) // n
        assert rs_wire <= 0.55 * ar_wire, (rs_wire, ar_wire)
        # the memory claim: sharded Adam moments at ~1/n per chip
        ratio = row["opt_state_bytes_per_chip"] / base["opt_state_bytes_per_chip"]
        assert ratio <= 0.2, ratio


def test_perf_audit_quick_wire_int8_quantized_census(tmp_path):
    """Tier-1 lane for the quantized-ring wire gates: ``--quick --wire=int8``
    audits the in-collective blockwise quantization — zero all-reduces with
    u8-packed per-hop payloads at ≤0.3× the f32 ring bytes, the loss-parity
    guardrail certifying int8 AND int4(+EF), and that allow-list flowing
    into the planner's mixed per-bucket precision plan on the recorded VGG16
    operating point."""
    out = tmp_path / "audit_wire"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "ci", "perf_audit.py"),
            "--quick", "--wire=int8", "--model=mlp", "--ddp-only",
            "--out", str(out),
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"perf_audit --quick --wire=int8 failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    assert "wire quantized-ring census assertion passed" in proc.stderr
    assert "wire loss-parity lane passed" in proc.stderr
    assert "wire planner allow-list lane passed" in proc.stderr

    with open(str(out) + ".json") as f:
        audit = json.load(f)
    rows = audit["ddp"]
    assert "gradient_allreduce" in rows and "gradient_allreduce[int8]" in rows
    row = rows["gradient_allreduce[int8]"]
    assert row["buckets"] > 1
    # in-collective quantization: the full-precision exchange is GONE, the
    # inter-hop payload crosses u8-packed (n-1 hops per bucket), and the AG
    # tail ships compressed too
    n = 8  # the subprocess builds its own 8-device CPU sim
    assert row["census"].get("all-reduce", {"count": 0})["count"] == 0
    cp_u8 = row["census"]["collective-permute"]["by_dtype"]["u8"]
    assert cp_u8["count"] >= row["buckets"] * (n - 1)
    assert row["census"]["all-gather"]["by_dtype"]["u8"]["count"] > 0

    # The byte gate's recorded numbers: census == modeled ring_wire_bytes,
    # and ≤ 0.3× the f32 baseline's ring traffic (re-check so a
    # silently-skipped lane can't pass).
    wire = audit["wire"]
    assert wire["variant"] == "gradient_allreduce[int8]" and wire["bits"] == 8
    assert wire["wire_bytes"] == wire["modeled_wire_bytes"]
    assert 0 < wire["wire_bytes"] <= 0.3 * wire["f32_ring_bytes"]

    # The convergence guardrail certified both quantized precisions (int4
    # only survives through error feedback), and the planner turned that
    # allow-list into a genuinely mixed per-bucket plan: the 2(n-1)-hop
    # latency floor keeps small buckets f32 while bandwidth flips large
    # ones quantized.
    assert wire["loss_parity"]["allow_list"] == ["int8", "int4"]
    plan = wire["precision_plan"]
    assert plan["allow_list"] == ["f32", "int4", "int8"]
    chosen = set(plan["precisions"])
    assert "f32" in chosen and chosen & {"int8", "int4"}, plan["precisions"]
    assert plan["total_wire_ms"] < plan["total_wire_ms_f32"]
    assert 0.0 < plan["saved_frac"] < 1.0


def test_perf_audit_quick_tp_collective_matmul(tmp_path):
    """Tier-1 lane for the collective-matmul gates: fused-vs-oracle bitwise
    parity (interpret mode), the zero-all-reduce census of the fused
    RowParallel forward, and the per-scope measured_overlap_frac rows."""
    out = tmp_path / "audit_tp"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "ci", "perf_audit.py"),
            "--quick", "--model=tp", "--out", str(out),
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"perf_audit --quick --model=tp failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    assert "tp collective-matmul census assertion passed" in proc.stderr
    assert "tp fused-vs-oracle parity passed" in proc.stderr
    assert "measured_overlap_frac reported" in proc.stderr

    with open(str(out) + ".json") as f:
        audit = json.load(f)
    # fused RowParallel forward: ZERO standalone psum/all-reduce
    assert "all-reduce" not in audit["census"]["fused_fwd"]
    assert "all-reduce" not in audit["census"]["fused_fwd_bwd"]
    assert audit["census"]["fused_fwd"]["collective-permute"]["count"] == 7
    # unfused Megatron pair: exactly one fwd + one bwd all-reduce
    assert audit["census"]["unfused_fwd"]["all-reduce"]["count"] == 1
    assert audit["census"]["unfused_fwd_bwd"]["all-reduce"]["count"] == 2
    # bitwise parity held for every swept config (incl. edge tiles)
    assert audit["collective_matmul_parity"], "empty parity sweep"
    for row in audit["collective_matmul_parity"]:
        assert row["ag_bitwise"] and row["rs_bitwise"], row
    # per-scope overlap attribution for both parallelism scopes
    scopes = audit["trace"]["per_scope"]
    for axis in ("tp", "ep"):
        assert axis in scopes, scopes
        assert 0.0 <= scopes[axis]["measured_overlap_frac"] <= 1.0


def test_perf_audit_quick_llama_mesh(tmp_path):
    """Tier-1 lane for the named-mesh 2-D engine gates: the dp×tp census
    (bucketed exchange confined to the dp axis, model tp ring intact), the
    strict static-verifier pass on the 2-D step program (per-axis wire-byte
    exactness included), and dp×1-vs-1-D bitwise parity for both modeled
    algorithms with overlap on."""
    out = tmp_path / "audit_llama_mesh"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "ci", "perf_audit.py"),
            "--quick", "--model=llama-mesh", "--out", str(out),
        ],
        capture_output=True, text=True, timeout=600, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"perf_audit --quick --model=llama-mesh failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    assert "llama-mesh dp*tp census passed" in proc.stderr
    assert "llama-mesh static verify strict passed" in proc.stderr
    assert "llama-mesh dp*1 bitwise parity passed" in proc.stderr

    with open(str(out) + ".json") as f:
        audit = json.load(f)
    assert audit["mesh"] == {"dp": 4, "tp": 2}
    census = audit["census"]
    # every exchange collective rides exactly the dp axis...
    assert census["exchange_collectives"] > 0
    assert census["exchange_axes"] == ["dp"]
    for d in census["by_descriptor"]:
        if d["scope"] is not None:
            assert d["axes"] == ["dp"], d
    # ...while the Megatron block's tp ring survives untouched
    assert census["model_tp_collectives"] > 0
    # the strict four-checker pass held on the 2-D program
    assert audit["static_verify"]["ok"], audit["static_verify"]["findings"]
    # dp×1 == legacy 1-D, bitwise, params + opt state, overlap on
    algos = {row["algo"]: row for row in audit["dp1_parity"]}
    assert set(algos) == {"gradient_allreduce", "zero"}
    for row in algos.values():
        assert row["overlap"] and row["bitwise"], row
