"""Native IO prefetcher: ordered streaming, error handling, backpressure."""

import os

import numpy as np
import pytest

from bagua_tpu.contrib.io_prefetcher import IOPrefetcher


@pytest.fixture()
def files(tmp_path):
    paths = []
    rng = np.random.RandomState(0)
    for i in range(40):
        p = tmp_path / f"sample_{i}.bin"
        p.write_bytes(bytes([i % 256]) * (100 + int(rng.randint(0, 500))))
        paths.append(str(p))
    return paths


def test_read_ordered(files):
    pf = IOPrefetcher(n_threads=4, capacity=8)
    try:
        out = list(pf.read_ordered(files))
        assert [p for p, _ in out] == files
        for i, (p, payload) in enumerate(out):
            assert payload is not None
            assert payload == open(p, "rb").read()
    finally:
        pf.close()


def test_missing_file_yields_none(files, tmp_path):
    paths = files[:3] + [str(tmp_path / "does_not_exist.bin")] + files[3:6]
    pf = IOPrefetcher(n_threads=2, capacity=4)
    try:
        out = dict(pf.read_ordered(paths))
        assert out[paths[3]] is None
        assert all(out[p] is not None for p in paths if "does_not_exist" not in p)
    finally:
        pf.close()


def test_backpressure(files):
    pf = IOPrefetcher(n_threads=1, capacity=2)
    try:
        assert pf.submit(0, files[0])
        assert pf.submit(1, files[1])
        # budget of 2: a third submit may be refused until results are polled
        accepted_third = pf.submit(2, files[2])
        seen = set()
        for _ in range(3 if accepted_third else 2):
            rid, payload = pf.poll(timeout_ms=5000)
            assert payload is not None
            seen.add(rid)
        if not accepted_third:
            assert pf.submit(2, files[2])
            rid, payload = pf.poll(timeout_ms=5000)
            seen.add(rid)
        assert seen == {0, 1, 2}
    finally:
        pf.close()
