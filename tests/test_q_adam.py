"""QAdam correctness: warmup == Adam on the global batch; compression phase
matches a numpy oracle of the reference semantics (momentum from raw local
grads, MinMaxUInt8 scatter-gather exchange, frozen second moment)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.algorithms.q_adam import QAdamAlgorithm, QAdamOptimizer
from bagua_tpu.bucket import BucketPlan
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss
from tests.oracles import (
    oracle_compress,
    oracle_decompress,
    oracle_compressed_allreduce,
)

N = 8
DIM_IN, DIM_OUT = 10, 3
LR = 0.01
B1, B2 = 0.9, 0.999
EPS_ADAM = 1e-8
EPS_Q = 1e-7


def make_problem(n_steps, seed=0):
    params = init_mlp(jax.random.PRNGKey(seed), [DIM_IN, 8, DIM_OUT])
    rng = np.random.RandomState(seed)
    xs = rng.randn(n_steps, N * 4, DIM_IN).astype(np.float32)
    ys = rng.randn(n_steps, N * 4, DIM_OUT).astype(np.float32)
    return params, xs, ys


def test_invalid_hyperparams():
    with pytest.raises(ValueError):
        QAdamOptimizer(lr=-1.0)
    with pytest.raises(ValueError):
        QAdamOptimizer(warmup_steps=0)
    with pytest.raises(ValueError):
        QAdamOptimizer(betas=(1.0, 0.999))


def test_warmup_matches_adam_oracle(group):
    """During warmup QAdam == Adam (reference formulation) on the global batch."""
    n_steps = 5
    params, xs, ys = make_problem(n_steps, seed=1)
    qopt = QAdamOptimizer(lr=LR, warmup_steps=100, betas=(B1, B2), eps=EPS_ADAM)
    ddp = DistributedDataParallel(
        mse_loss, None, QAdamAlgorithm(qopt), process_group=group
    )
    state = ddp.init(params)
    for i in range(n_steps):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))

    # Oracle: reference Adam on global-batch gradients.
    w = {k: {kk: np.asarray(v) for kk, v in d.items()} for k, d in params.items()}
    flat_keys = [(k, kk) for k in sorted(w) for kk in sorted(w[k])]
    m = {key: np.zeros_like(w[key[0]][key[1]]) for key in flat_keys}
    v = {key: np.zeros_like(w[key[0]][key[1]]) for key in flat_keys}
    gradf = jax.jit(jax.grad(mse_loss))
    for t in range(n_steps):
        tree = {k: {kk: jnp.asarray(w[k][kk]) for kk in w[k]} for k in w}
        g = jax.tree.map(np.asarray, gradf(tree, (jnp.asarray(xs[t]), jnp.asarray(ys[t]))))
        step_id = t + 1
        for k, kk in flat_keys:
            gg = g[k][kk]
            if step_id < 100:
                m[(k, kk)] = B1 * m[(k, kk)] + (1 - B1) * gg
                v[(k, kk)] = B2 * v[(k, kk)] + (1 - B2) * gg * gg
            bc1 = 1 - B1 ** step_id
            bc2 = 1 - B2 ** step_id
            denom = np.sqrt(v[(k, kk)]) / np.sqrt(bc2) + EPS_ADAM
            w[k][kk] = w[k][kk] - (LR / bc1) * m[(k, kk)] / denom

    got = ddp.params_unstacked(state)
    for k in w:
        for kk in w[k]:
            np.testing.assert_allclose(
                np.asarray(got[k][kk]), w[k][kk], rtol=5e-4, atol=1e-5
            )


def test_compression_phase_matches_oracle(group):
    warmup = 2
    n_steps = 5
    params, xs, ys = make_problem(n_steps, seed=2)
    qopt = QAdamOptimizer(lr=LR, warmup_steps=warmup, betas=(B1, B2), eps=EPS_ADAM)
    ddp = DistributedDataParallel(
        mse_loss, None, QAdamAlgorithm(qopt, hierarchical=False), process_group=group
    )
    state = ddp.init(params)
    for i in range(n_steps):
        state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))

    # cross-rank bitwise equality (centralized algorithm)
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, state.params)):
        for r in range(1, N):
            np.testing.assert_array_equal(leaf[0], leaf[r])

    # ---- flat numpy oracle ----
    plan = BucketPlan.from_tree(params, ddp.bucket_size_bytes, align_elems=N)

    def flat_grad(flat, x, y):
        p = plan.debucketize([flat])
        return plan.bucketize(jax.grad(mse_loss)(p, (x, y)))[0]

    gradf = jax.jit(flat_grad)
    w = np.asarray(plan.bucketize(params)[0])  # identical across ranks
    m = np.zeros_like(w)
    vv = np.zeros_like(w)
    for t in range(n_steps):
        x = xs[t].reshape(N, -1, DIM_IN)
        y = ys[t].reshape(N, -1, DIM_OUT)
        g = np.stack(
            [np.asarray(gradf(jnp.asarray(w), x[r], y[r])) for r in range(N)]
        )
        step_id = t + 1
        if t < warmup:  # warmup comm phase: grads averaged
            gavg = g.mean(axis=0)
            if step_id < warmup:  # moments update one step shorter
                m = B1 * m + (1 - B1) * gavg
                vv = B2 * vv + (1 - B2) * gavg * gavg
        else:  # compression phase
            per_rank_m = np.stack([B1 * m + (1 - B1) * g[r] for r in range(N)])
            m = oracle_compressed_allreduce(per_rank_m, average=True)
        bc1 = 1 - B1 ** step_id
        bc2 = 1 - B2 ** step_id
        denom = np.sqrt(vv) / np.sqrt(bc2) + EPS_ADAM
        w = w - (LR / bc1) * m / denom

    got = np.asarray(ddp.plan.bucketize(ddp.params_unstacked(state))[0])
    np.testing.assert_allclose(got, w, rtol=5e-4, atol=1e-5)
