"""Trace-analyzer interval math and corrupt-capture degradation.

The overlap metric is only as trustworthy as ``_merge_intervals`` /
``_covered`` on the degenerate spans real traces contain — zero-length
events, identical timestamps, fully-nested intervals — and as the loader's
behavior on a capture the profiler never finished writing (job killed
mid-profile): salvage the parsed prefix, never raise.
"""

import gzip
import json

import pytest

from bagua_tpu.observability.trace_analysis import (
    _covered,
    _merge_intervals,
    analyze_trace,
    load_trace_events,
)


# -- interval math ------------------------------------------------------------


def test_merge_intervals_basic_and_empty():
    assert _merge_intervals([]) == []
    assert _merge_intervals([(1.0, 2.0)]) == [(1.0, 2.0)]
    assert _merge_intervals([(3.0, 4.0), (1.0, 2.0)]) == [(1.0, 2.0), (3.0, 4.0)]
    # touching intervals merge (closed-interval semantics)
    assert _merge_intervals([(1.0, 2.0), (2.0, 3.0)]) == [(1.0, 3.0)]


def test_merge_intervals_zero_length_spans():
    # a zero-length span inside another vanishes into it
    assert _merge_intervals([(0.0, 10.0), (5.0, 5.0)]) == [(0.0, 10.0)]
    # standing alone it survives as a degenerate interval
    assert _merge_intervals([(5.0, 5.0)]) == [(5.0, 5.0)]
    # and glues touching neighbours together
    assert _merge_intervals([(0.0, 5.0), (5.0, 5.0), (5.0, 8.0)]) == [(0.0, 8.0)]


def test_merge_intervals_identical_timestamps():
    assert _merge_intervals([(1.0, 3.0), (1.0, 3.0), (1.0, 3.0)]) == [(1.0, 3.0)]
    # same start, different ends: longest wins
    assert _merge_intervals([(1.0, 2.0), (1.0, 5.0)]) == [(1.0, 5.0)]


def test_merge_intervals_fully_nested():
    assert _merge_intervals([(0.0, 100.0), (10.0, 20.0), (30.0, 40.0)]) == [
        (0.0, 100.0)
    ]
    # nested chain presented inner-first
    assert _merge_intervals([(4.0, 6.0), (2.0, 8.0), (0.0, 10.0)]) == [(0.0, 10.0)]


def covered(start, end, intervals):
    merged = _merge_intervals(list(intervals))
    return _covered(start, end, merged, [s for s, _ in merged])


def test_covered_basic_clipping():
    ivs = [(0.0, 10.0), (20.0, 30.0)]
    assert covered(2.0, 8.0, ivs) == pytest.approx(6.0)       # inside
    assert covered(5.0, 25.0, ivs) == pytest.approx(10.0)     # straddles the gap
    assert covered(-5.0, 50.0, ivs) == pytest.approx(20.0)    # superset
    assert covered(10.0, 20.0, ivs) == pytest.approx(0.0)     # exactly the gap
    assert covered(40.0, 50.0, ivs) == pytest.approx(0.0)     # after everything
    assert covered(-9.0, -1.0, ivs) == pytest.approx(0.0)     # before everything


def test_covered_zero_length_query_and_spans():
    ivs = [(0.0, 10.0)]
    assert covered(5.0, 5.0, ivs) == 0.0        # zero-length query
    assert covered(8.0, 2.0, ivs) == 0.0        # inverted query
    assert covered(5.0, 6.0, []) == 0.0         # no compute at all
    # zero-length compute spans contribute zero coverage
    assert covered(0.0, 10.0, [(5.0, 5.0)]) == 0.0


def test_covered_identical_timestamps_not_double_counted():
    # duplicated compute spans (two lanes, same op) must not double-count
    assert covered(0.0, 4.0, [(1.0, 3.0), (1.0, 3.0)]) == pytest.approx(2.0)


# -- corrupt/truncated captures -----------------------------------------------


def trace_event(hlo_op, ts, dur, pid=1, tid=1, module="m"):
    return {"ph": "X", "pid": pid, "tid": tid, "ts": ts, "dur": dur,
            "name": hlo_op, "args": {"hlo_op": hlo_op, "hlo_module": module}}


def write_trace(path, events):
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_analyze_synthetic_trace_overlap_math(tmp_path):
    path = str(tmp_path / "t.trace.json.gz")
    # compute on lane 1 covers [0,100]; the collective [50,150] on lane 2
    # is half hidden
    write_trace(path, [
        trace_event("fusion.1", ts=0.0, dur=100.0, tid=1),
        trace_event("all-reduce.7", ts=50.0, dur=100.0, tid=2),
    ])
    rep = analyze_trace(path)
    assert rep["collective_spans"] == 1
    assert rep["measured_overlap_frac"] == pytest.approx(0.5)
    assert rep["per_bucket"] == []  # no HLO text: spans are unattributed
    assert rep["unattributed"]["spans"] == 1


def test_truncated_trace_degrades_to_salvaged_prefix(tmp_path, caplog, monkeypatch):
    import logging

    from bagua_tpu.observability import trace_analysis

    # small read chunks so the decompression error lands mid-stream, the way
    # it does on a multi-GB real capture (default chunk is 4 MiB)
    orig = trace_analysis._iter_trace_events
    monkeypatch.setattr(trace_analysis, "_iter_trace_events",
                        lambda f: orig(f, chunk=1024))

    path = str(tmp_path / "t.trace.json.gz")
    events = [trace_event(f"fusion.{i}", ts=10.0 * i, dur=5.0) for i in range(500)]
    events.append(trace_event("all-reduce.0", ts=0.0, dur=50.0, tid=2))
    write_trace(path, events)
    full = load_trace_events(path)
    assert len(full) == 501

    # chop the gzip stream mid-file: the common killed-mid-profile capture
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])
    with caplog.at_level(logging.WARNING,
                         logger="bagua_tpu.observability.trace_analysis"):
        salvaged = load_trace_events(path)
    assert 0 < len(salvaged) < len(full)
    assert any("truncated/corrupt" in r.message for r in caplog.records)
    # the analyzer runs on the salvaged prefix instead of raising
    rep = analyze_trace(path)
    assert rep["num_xla_events"] == len(salvaged)


def test_garbage_gzip_payload_degrades_empty(tmp_path):
    path = str(tmp_path / "t.trace.json.gz")
    with open(path, "wb") as f:
        f.write(b"\x1f\x8b\x08\x00garbage-not-a-gzip-body")
    assert load_trace_events(path) == []


def test_missing_trace_still_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_trace_events(str(tmp_path / "empty_dir"))
