"""Fleet control plane: multi-tenant namespaces, crash-safe WAL replay,
leases + backpressure, the cross-gang plan cache, and the scheduler view.

Pins the PR-13 contract end to end:

* per-gang isolation — rendezvous/KV/blob/autotune state scoped by gang id;
  an adversarial cross-gang probe reads nothing and the unprefixed
  single-tenant routes 404 on the fleet plane;
* crash safety — a control plane killed (including SIGKILL mid-run with
  live clients attached) and restarted on the same WAL dir replays to the
  bitwise-identical durable dump, while the clients ride the outage out on
  their retry/breaker machinery;
* leases + admission — an untouched gang lease expiring GCs the whole
  namespace (journaled, so a restart doesn't resurrect the dead); the
  per-gang token bucket answers 429 + Retry-After, which ``retry_call``
  paces on and the circuit breaker never counts as a failure;
* the cross-gang plan cache — a second engine with the same (model
  fingerprint, topology, algorithm, wire precision) adopts the first
  gang's published plan at step 0 with ``plan_source="fleet"``.
"""

import http.client
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from email.message import Message

import optax
import pytest

from helpers import free_port, worker_env
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.distributed.rendezvous import RendezvousClient
from bagua_tpu.env import get_rpc_timeout_s
from bagua_tpu.fleet import (
    FleetClient,
    FleetControlPlane,
    HashRing,
    RemediationEngine,
    ShardedControlPlane,
    TokenBucket,
    WriteAheadLog,
    adopt_fleet_plan,
    engine_plan_key,
    gang_endpoint,
    model_fingerprint,
    plan_cache_key,
    publish_engine_plan,
    start_async_fleet_server,
    start_fleet_server,
)
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.observability import (
    Telemetry,
    Tracer,
    set_global_tracer,
    validate_metrics_file,
)
from bagua_tpu.observability.aggregate import StepSummary, gang_kv_key
from bagua_tpu.observability.flight_recorder import flight_kv_key
from bagua_tpu.resilience.retry import (
    BackpressureError,
    CircuitBreaker,
    RetryPolicy,
    retry_after_hint,
    retry_call,
)

import jax  # noqa: E402  (after conftest pinned the CPU sim)

LAYERS = [12, 16, 16, 4]
RDZV_FAST = {"min_nodes": 1, "settle_s": 0.05}


def _serve(plane):
    server = start_fleet_server(plane, 0, host="127.0.0.1")
    return server, f"http://127.0.0.1:{server.server_address[1]}"


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def _canon(dump: dict) -> str:
    return json.dumps(dump, sort_keys=True)


def make_engine(group, bucket_size):
    ddp = DistributedDataParallel(
        mse_loss,
        optax.sgd(0.1),
        GradientAllReduceAlgorithm(),
        process_group=group,
        bucket_size_bytes=bucket_size,
        overlap=False,
    )
    ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
    return ddp


def plan_names(ddp):
    return [[td.name for td in b] for b in ddp.plan.declarations()]


# ---------------- primitives: cache key, token bucket, retry hints -----------


def test_plan_cache_key_is_injective_under_separators():
    a = plan_cache_key("fp/1", "ranks8", "Algo", "f32")
    b = plan_cache_key("fp", "1/ranks8", "Algo", "f32")
    assert a != b  # a "/" inside a field never collides with the separator
    assert plan_cache_key("fp", "ranks8", "Algo", "int8") != plan_cache_key(
        "fp", "ranks8", "Algo", "f32"
    )


def test_token_bucket_paces_and_refills():
    clk = [0.0]
    bucket = TokenBucket(rate=2.0, burst=3.0, clock=lambda: clk[0])
    assert [bucket.admit()[0] for _ in range(3)] == [True, True, True]
    ok, retry_after = bucket.admit()
    assert not ok and 0.0 < retry_after <= 0.5  # one token is 1/rate away
    clk[0] += retry_after
    assert bucket.admit()[0]
    # rate <= 0 disables admission control entirely
    off = TokenBucket(rate=0.0, burst=1.0, clock=lambda: clk[0])
    assert all(off.admit() == (True, 0.0) for _ in range(100))


def test_retry_after_hint_contract():
    assert retry_after_hint(BackpressureError("shed", 3.5)) == 3.5
    assert retry_after_hint(ValueError("nope")) is None

    def http_error(code, headers=None):
        hdrs = Message()
        for k, v in (headers or {}).items():
            hdrs[k] = v
        return urllib.error.HTTPError("http://x", code, "msg", hdrs, None)

    assert retry_after_hint(http_error(429, {"Retry-After": "2"})) == 2.0
    assert retry_after_hint(http_error(429, {"Retry-After": "soon"})) == 0.0
    assert retry_after_hint(http_error(429)) == 0.0  # still backpressure
    assert retry_after_hint(http_error(503, {"Retry-After": "9"})) is None


def test_rpc_timeout_env_knob(monkeypatch):
    from bagua_tpu.service.autotune_client import AutotuneClient

    monkeypatch.setenv("BAGUA_RPC_TIMEOUT_S", "3.5")
    assert get_rpc_timeout_s() == 3.5
    assert AutotuneClient(port=1).timeout == 3.5  # honors the shared knob
    assert FleetClient("127.0.0.1:1").timeout_s == 3.5
    assert AutotuneClient(port=1, timeout=2.0).timeout == 2.0  # explicit wins
    monkeypatch.delenv("BAGUA_RPC_TIMEOUT_S")
    assert get_rpc_timeout_s() == 10.0


def test_retry_call_paces_on_hint_and_429_never_trips_the_breaker():
    state = {"n": 0}

    def shedding():
        state["n"] += 1
        if state["n"] <= 2:
            raise BackpressureError("shed", retry_after_s=0.7)
        return "ok"

    sleeps = []
    breaker = CircuitBreaker(failure_threshold=1, cooldown_s=99.0, name="bp")
    policy = RetryPolicy(retries=3, base_s=0.001, max_s=2.0, seed=0)
    assert retry_call(shedding, policy=policy, breaker=breaker, sleep=sleeps.append) == "ok"
    # the server's hint floors the backoff (jitter would be ~1ms here)
    assert len(sleeps) == 2 and all(0.7 <= s <= 2.0 for s in sleeps)
    assert breaker.times_opened == 0 and breaker.state == "closed"

    # a hostile hint is capped at the policy ceiling
    def hostile():
        raise BackpressureError("shed", retry_after_s=1e9)

    sleeps2 = []
    with pytest.raises(BackpressureError):
        retry_call(
            hostile,
            policy=RetryPolicy(retries=2, base_s=0.001, max_s=0.25, seed=0),
            sleep=sleeps2.append,
        )
    assert sleeps2 == [0.25, 0.25]

    # a real connection failure still counts against the breaker
    def down():
        raise ConnectionRefusedError("down")

    b2 = CircuitBreaker(failure_threshold=1, cooldown_s=99.0, name="down")
    with pytest.raises(OSError):
        retry_call(down, policy=RetryPolicy(retries=0), breaker=b2, sleep=lambda s: None)
    assert b2.state == "open"


# ---------------- multi-tenant isolation -------------------------------------


def test_gang_isolation_and_unprefixed_probe_404():
    plane = FleetControlPlane(rdzv_kwargs=RDZV_FAST)
    server, base = _serve(plane)
    try:
        ep_a = gang_endpoint(base, "team-a/run1")  # "/" in the id round-trips
        a = RendezvousClient(ep_a, node_rank=0, timeout_s=15.0)
        b = RendezvousClient(gang_endpoint(base, "team-b"), node_rank=0, timeout_s=15.0)
        asn = a.wait_assignment(nslots=4, incarnation=1)
        assert asn["settled"] and asn["world_size"] == 4
        a.kv_set("secret", "a-only")
        req = urllib.request.Request(
            ep_a + "/rdzv/blob/ckpt", data=b"gang-a-weights", method="PUT"
        )
        assert _get_json_req(req)["ok"]

        # adversarial cross-gang probe: B sees none of A's state
        assert b.kv_get("secret") is None
        assert b._call("/rdzv/assignment")["settled"] is False
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                gang_endpoint(base, "team-b") + "/rdzv/blob/ckpt", timeout=10
            )
        assert ei.value.code == 404
        # while A reads its own blob back
        with urllib.request.urlopen(ep_a + "/rdzv/blob/ckpt", timeout=10) as resp:
            assert resp.read() == b"gang-a-weights"

        # the single-tenant route table is NOT mounted at the root
        for probe in ("/rdzv/assignment", "/rdzv/kv/secret", "/api/v1/health_check"):
            with pytest.raises(urllib.error.HTTPError) as e404:
                urllib.request.urlopen(base + probe, timeout=10)
            assert e404.value.code == 404

        # each gang tunes against its own AutotuneTaskManager pool
        from bagua_tpu.defs import TensorDeclaration

        fc = FleetClient(base)
        at_a = fc.autotune_client("team-a/run1")
        assert at_a.wait_until_ready(max_wait_s=10.0)
        at_a.register_tensors(
            "mlp", [TensorDeclaration(name="w0", num_elements=128, dtype="f32")]
        )
        assert plane.gang("team-a/run1").autotune_models == ["mlp"]
        assert plane.gang("team-b").autotune_models == []

        health = fc.health()
        assert health["status"] == "ok" and health["gangs"] == 2
        assert fc.gangs()["gangs"] == ["team-a/run1", "team-b"]
    finally:
        server.shutdown()


def _get_json_req(req, timeout=10.0):
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


# ---------------- WAL: replay, compaction, torn tails ------------------------


def _populate(plane):
    ns = plane.gang("alpha")
    ns.rendezvous.join(0, 2, 1)
    ns.rendezvous.join(1, 2, 1)
    time.sleep(0.08)
    assert ns.rendezvous.assignment()["settled"]
    for i in range(4):
        ns.rendezvous.kv_set(f"ck/{i}", i)
    ns.rendezvous.blob_set("weights", b"\x00\x01" * 64)
    plane.gang("beta").rendezvous.kv_set("other", "b")
    plane.plan_put(
        fingerprint="fp", topology="ranks4", algorithm="A", wire_precision="f32",
        plan={"buckets": [["w"]]}, meta={"gang": "alpha"},
    )


def test_wal_replay_restores_durable_state_bitwise(tmp_path):
    wal_dir = str(tmp_path / "wal")
    plane = FleetControlPlane(wal_dir=wal_dir, rdzv_kwargs=RDZV_FAST)
    _populate(plane)
    pre = plane.dump()
    # no close(), no compaction: the "crash" leaves only the appended log
    plane2 = FleetControlPlane(wal_dir=str(tmp_path / "wal"), rdzv_kwargs=RDZV_FAST)
    assert _canon(plane2.dump()) == _canon(pre)
    # the replayed store is live, not a husk: reads and writes both work
    st = plane2.gang("alpha").rendezvous
    assert st.kv_get("ck/3") == 3
    assert st.blob_get("weights") == b"\x00\x01" * 64
    asn = st.assignment()
    assert asn["settled"] and asn["world_size"] == 4
    plane2.gang("alpha").rendezvous.kv_set("post", "restart")
    plane3 = FleetControlPlane(wal_dir=wal_dir, rdzv_kwargs=RDZV_FAST)
    assert plane3.gang("alpha").rendezvous.kv_get("post") == "restart"


def test_wal_compaction_truncates_log_and_preserves_replay(tmp_path):
    wal_dir = str(tmp_path / "wal")
    plane = FleetControlPlane(wal_dir=wal_dir, compact_every=3, rdzv_kwargs=RDZV_FAST)
    _populate(plane)
    assert plane.wal.needs_compact()
    assert plane.maybe_compact()
    assert plane.wal.compactions == 1
    assert os.path.exists(plane.wal.snapshot_path)
    assert os.path.getsize(plane.wal.wal_path) == 0  # folded into the snapshot
    pre = plane.dump()

    # writes after compaction land in the (fresh) log and replay on top
    plane.gang("alpha").rendezvous.kv_set("late", "write")
    plane2 = FleetControlPlane(wal_dir=wal_dir, rdzv_kwargs=RDZV_FAST)
    assert plane2.gang("alpha").rendezvous.kv_get("late") == "write"

    # crash between snapshot replace and log truncate: stale records whose
    # seq <= the snapshot's last_seq are skipped on replay, not re-applied
    with open(plane.wal.wal_path, "a") as f:
        f.write(json.dumps({"op": "kv", "gang": "alpha", "key": "ck/0",
                            "value": "stale", "seq": 1}) + "\n")
    plane3 = FleetControlPlane(wal_dir=wal_dir, rdzv_kwargs=RDZV_FAST)
    assert plane3.gang("alpha").rendezvous.kv_get("ck/0") == 0  # not "stale"
    assert plane3.gang("alpha").rendezvous.kv_get("late") == "write"
    del pre


def test_compaction_keeps_records_acknowledged_during_dump(tmp_path):
    """The compaction race: a mutation acknowledged between the state dump
    and the snapshot's ``last_seq`` stamp must survive in the rewritten log
    — covering it with a stamp taken at compact time would silently drop a
    durable record on the next restart."""
    wal_dir = str(tmp_path / "wal")
    plane = FleetControlPlane(wal_dir=wal_dir, compact_every=1, rdzv_kwargs=RDZV_FAST)
    plane.gang("alpha").rendezvous.kv_set("early", 1)
    orig = plane._snapshot_state

    def racy_dump():
        state = orig()
        # simulates a handler thread acknowledging a write mid-compaction
        plane.gang("alpha").rendezvous.kv_set("late", "survives")
        return state

    plane._snapshot_state = racy_dump
    assert plane.maybe_compact()
    plane._snapshot_state = orig
    # the racing record is missing from the snapshot but preserved in the log
    snap = json.load(open(plane.wal.snapshot_path))
    assert "late" not in snap["state"]["gangs"]["alpha"]["kv"]
    kept = [json.loads(l) for l in open(plane.wal.wal_path)]
    assert [r["key"] for r in kept if r["op"] == "kv"] == ["late"]
    assert all(r["seq"] > snap["last_seq"] for r in kept)

    plane2 = FleetControlPlane(wal_dir=wal_dir, rdzv_kwargs=RDZV_FAST)
    assert plane2.gang("alpha").rendezvous.kv_get("late") == "survives"
    assert plane2.gang("alpha").rendezvous.kv_get("early") == 1
    assert _canon(plane2.dump()) == _canon(plane.dump())


def test_wal_torn_tail_is_dropped(tmp_path):
    wal_dir = str(tmp_path / "wal")
    plane = FleetControlPlane(wal_dir=wal_dir, rdzv_kwargs=RDZV_FAST)
    _populate(plane)
    pre = plane.dump()
    with open(plane.wal.wal_path, "a") as f:
        f.write('{"op": "kv", "gang": "alpha", "key": "torn", "va')  # mid-append kill
    plane2 = FleetControlPlane(wal_dir=wal_dir, rdzv_kwargs=RDZV_FAST)
    assert _canon(plane2.dump()) == _canon(pre)
    assert plane2.gang("alpha").rendezvous.kv_get("torn") is None
    # the torn-tail store still accepts appends and replays them
    plane2.gang("alpha").rendezvous.kv_set("after-torn", 1)
    plane3 = FleetControlPlane(wal_dir=wal_dir, rdzv_kwargs=RDZV_FAST)
    assert plane3.gang("alpha").rendezvous.kv_get("after-torn") == 1


def test_wal_object_roundtrip(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "w"), compact_every=100)
    assert wal.load() == (None, [])
    seqs = [wal.append({"op": "kv", "key": str(i)}) for i in range(5)]
    assert seqs == [1, 2, 3, 4, 5]
    wal.close()
    wal2 = WriteAheadLog(str(tmp_path / "w"))
    snapshot, records = wal2.load()
    assert snapshot is None and [r["key"] for r in records] == [str(i) for i in range(5)]
    assert wal2.append({"op": "kv", "key": "next"}) == 6  # seq continues
    wal2.compact({"folded": True})
    snapshot, records = WriteAheadLog(str(tmp_path / "w")).load()
    assert snapshot == {"folded": True} and records == []


# ---------------- leases + admission control ---------------------------------


def test_lease_expiry_gcs_namespace_and_survives_restart(tmp_path):
    clk = [0.0]
    wal_dir = str(tmp_path / "wal")
    kwargs = dict(wal_dir=wal_dir, lease_ttl_s=10.0, clock=lambda: clk[0],
                  rdzv_kwargs=RDZV_FAST)
    plane = FleetControlPlane(**kwargs)
    plane.gang("doomed").rendezvous.kv_set("k", "v")
    clk[0] = 5.0
    plane.gang("alive")  # touched at t=5: lease runs to t=15
    clk[0] = 12.0  # "doomed"'s lease (t=10) expired, "alive"'s has not
    assert plane.sweep_leases() == ["doomed"]
    assert plane.gang_ids() == ["alive"] and plane.gangs_gcd == 1
    # the GC is journaled: a restart must not resurrect the dead namespace
    plane2 = FleetControlPlane(**kwargs)
    assert plane2.gang_ids() == ["alive"]
    # ...and a gang re-created after GC starts from scratch
    assert plane2.gang("doomed").rendezvous.kv_get("k") is None


def test_gang_recreated_after_gc_survives_replay(tmp_path):
    """The GC journal record is appended inside the removal's critical
    section, so a recreation always journals *after* it — replay must end
    with the recreated gang alive, not GC a gang the pre-crash server
    considered living."""
    clk = [0.0]
    wal_dir = str(tmp_path / "wal")
    kwargs = dict(wal_dir=wal_dir, lease_ttl_s=10.0, clock=lambda: clk[0],
                  rdzv_kwargs=RDZV_FAST)
    plane = FleetControlPlane(**kwargs)
    plane.gang("g").rendezvous.kv_set("k", "old")
    clk[0] = 12.0
    assert plane.sweep_leases() == ["g"]
    plane.gang("g").rendezvous.kv_set("k", "new")  # recreated after the GC
    recs = [json.loads(l) for l in open(plane.wal.wal_path)]
    gc_seq = next(r["seq"] for r in recs if r["op"] == "gang_gc")
    assert gc_seq < max(r["seq"] for r in recs if r["op"] == "gang")

    plane2 = FleetControlPlane(**kwargs)
    assert plane2.gang_ids() == ["g"]
    assert plane2.gang("g").rendezvous.kv_get("k") == "new"


def test_blob_reads_do_not_perturb_replayed_eviction_order(tmp_path):
    """Fleet-tier blob eviction is FIFO by *set* (reads never LRU-touch):
    reads are not journaled, so eviction order must be a pure function of
    the journaled ops or a replayed server evicts a different key than the
    one it ran before the crash, breaking the bitwise dump witness."""
    wal_dir = str(tmp_path / "wal")
    kwargs = dict(wal_dir=wal_dir,
                  rdzv_kwargs=dict(RDZV_FAST, max_blob_bytes=3 * 8))
    plane = FleetControlPlane(**kwargs)
    st = plane.gang("g").rendezvous
    for k in ("b1", "b2", "b3"):
        st.blob_set(k, k.encode() * 4)  # 8 bytes each: the cap holds 3
    assert st.blob_get("b1") == b"b1b1b1b1"  # the read must not touch b1
    st.blob_set("b4", b"b4b4b4b4")  # evicts the oldest set — b1, not b2
    assert st.blob_get("b1") is None
    assert sorted(st._blobs) == ["b2", "b3", "b4"]

    pre = plane.dump()
    plane2 = FleetControlPlane(**kwargs)
    assert _canon(plane2.dump()) == _canon(pre)
    assert plane2.gang("g").rendezvous.blob_get("b2") == b"b2b2b2b2"


def test_backpressure_denials_keep_known_gang_lease_alive():
    clk = [0.0]
    plane = FleetControlPlane(lease_ttl_s=10.0, rate=0.001, burst=1.0,
                              clock=lambda: clk[0], rdzv_kwargs=RDZV_FAST)
    assert plane.admit("g")[0]  # burst token spent
    plane.gang("g").rendezvous.kv_set("k", "v")  # lease runs to t=10
    clk[0] = 8.0
    ok, retry_after = plane.admit("g")
    assert not ok and retry_after > 0
    # the denial touched the lease (now t=18): a live gang held in
    # backpressure past the TTL must not get its namespace reaped
    clk[0] = 16.0
    assert plane.sweep_leases() == []
    assert plane.gang_ids() == ["g"]
    assert plane.gang("g").rendezvous.kv_get("k") == "v"
    # ...but a denied request for an unknown gang never creates state
    assert plane.admit("ghost")[0]  # fresh bucket: the burst admits one
    assert not plane.admit("ghost")[0]
    assert plane.gang_ids() == ["g"] and "ghost" not in plane._leases


def test_backpressure_429_and_paced_ride_through():
    plane = FleetControlPlane(rate=50.0, burst=5.0, rdzv_kwargs=RDZV_FAST)
    server, base = _serve(plane)
    try:
        # raw hammer past the burst: the contract is 429 + Retry-After
        denied = None
        for _ in range(40):
            try:
                _get_json(base + "/g/hot/rdzv/kv/k")
            except urllib.error.HTTPError as e:
                assert e.code == 429
                assert int(e.headers["Retry-After"]) >= 1
                assert retry_after_hint(e) is not None
                denied = json.loads(e.read())
                break
        assert denied is not None and denied["error"] == "backpressure"
        assert denied["retry_after_s"] > 0
        assert plane.backpressure_denials >= 1

        # a paced client rides straight through: every write lands, and the
        # breaker never opens (429s are recorded as successes)
        client = RendezvousClient(gang_endpoint(base, "hot"), node_rank=0, timeout_s=10.0)
        breaker = CircuitBreaker(failure_threshold=2, cooldown_s=30.0, name="ride")
        policy = RetryPolicy(retries=8, base_s=0.001, max_s=0.5, seed=0)
        for i in range(25):
            retry_call(
                client._call_once, f"/rdzv/kv/w{i}", {"value": i},
                policy=policy, breaker=breaker,
            )
        assert breaker.times_opened == 0 and breaker.state == "closed"
        st = plane.gang("hot").rendezvous
        assert [st.kv_get(f"w{i}") for i in range(25)] == list(range(25))
    finally:
        server.shutdown()


# ---------------- scheduler view ---------------------------------------------


def test_scheduler_view_verdicts():
    clk = [100.0]
    plane = FleetControlPlane(lease_ttl_s=50.0, clock=lambda: clk[0],
                              rdzv_kwargs=RDZV_FAST)

    def push(gang, attempt, rank, step, p50, phase_ms=None):
        plane.gang(gang).rendezvous.kv_set(
            gang_kv_key(attempt, rank),
            StepSummary(rank=rank, step=step, p50_ms=p50,
                        phase_ms=phase_ms or {}).payload(),
        )

    # healthy: tight p50 spread on the NEWEST attempt; the dead incarnation's
    # wildly-skewed numbers (older attempt, lower step) must be ignored
    push("healthy", "0", 0, 5, 100.0)
    push("healthy", "0", 1, 5, 1.0)
    push("healthy", "1", 0, 100, 10.0)
    push("healthy", "1", 1, 100, 11.0)
    # straggler: rank 1's p50 is 1.6x the gang median, slowest phase tagged
    push("strag", "0", 0, 40, 10.0)
    push("strag", "0", 1, 40, 40.0, phase_ms={"h2d": 30.0, "compute": 5.0})
    # wedged: a flight digest landed (beats an otherwise-healthy summary set)
    push("wedged", "0", 0, 7, 10.0)
    plane.gang("wedged").rendezvous.kv_set(flight_kv_key("0", 1), {"hang": True})
    plane.gang("idle")

    view = plane.scheduler_view()
    assert view["n_gangs"] == 4
    gangs = view["gangs"]
    assert gangs["healthy"]["verdict"] == "healthy"
    assert gangs["healthy"]["max_step"] == 100
    assert gangs["healthy"]["ranks_reporting"] == 2
    assert gangs["healthy"]["straggler"] is None
    assert gangs["strag"]["verdict"] == "straggler"
    assert gangs["strag"]["straggler"]["rank"] == 1
    assert gangs["strag"]["straggler"]["phase"] == "h2d"
    assert gangs["wedged"]["verdict"] == "wedged"
    assert gangs["wedged"]["flight_ranks"] == ["rank1"]
    assert gangs["idle"]["verdict"] == "idle"
    assert gangs["idle"]["ranks_reporting"] == 0
    assert all(g["lease_remaining_s"] == 50.0 for g in gangs.values())


# ---------------- cross-gang plan cache --------------------------------------


def test_cross_gang_plan_adoption_at_step_zero(group, tmp_path):
    plane = FleetControlPlane(rdzv_kwargs=RDZV_FAST)
    server, base = _serve(plane)
    ddp_a = make_engine(group, bucket_size=1 << 9)   # many small buckets
    ddp_b = make_engine(group, bucket_size=1 << 20)  # one fat bucket
    try:
        assert plan_names(ddp_a) != plan_names(ddp_b)  # genuinely different plans
        fc = FleetClient(base)
        key = publish_engine_plan(fc, ddp_a, meta={"gang": "alpha", "step": 500})
        assert key is not None and plane.plan_count() == 1

        # same (fingerprint, topology, algorithm, wire precision) tuple: the
        # new gang adopts the proven plan before its first step
        assert engine_plan_key(ddp_b) == engine_plan_key(ddp_a)
        jsonl = str(tmp_path / "m.jsonl")
        tel = Telemetry(metrics_jsonl=jsonl)
        assert adopt_fleet_plan(fc, ddp_b, telemetry=tel) == "fleet"
        assert plan_names(ddp_b) == plan_names(ddp_a)
        tel.close()
        assert validate_metrics_file(jsonl) == []
        (restart,) = [
            json.loads(l) for l in open(jsonl) if '"restart"' in l
        ]
        assert restart["step"] == 0 and restart["plan_source"] == "fleet"
        assert restart["lost_steps"] == 0
        assert restart["old_world_size"] == restart["new_world_size"] == group.size

        # a lookup miss (different model) is advisory: None, plan untouched
        entry = fc.lookup_plan(
            fingerprint=model_fingerprint([]), topology=f"ranks{group.size}",
            algorithm="GradientAllReduceAlgorithm", wire_precision="f32",
        )
        assert entry is None

        # the cached entry carries its key + meta for the fleet operator
        hit = fc.lookup_plan(**engine_plan_key(ddp_a))
        assert hit["found"] and hit["meta"] == {"gang": "alpha", "step": 500}
        assert hit["key"]["topology"] == f"ranks{group.size}"
    finally:
        ddp_a.shutdown()
        ddp_b.shutdown()
        server.shutdown()


def test_fleet_warm_start_via_resume_coordinator(group, tmp_path):
    from bagua_tpu.resilience.resume import ElasticResumeCoordinator

    ddp_a = make_engine(group, bucket_size=1 << 9)
    ddp_b = make_engine(group, bucket_size=1 << 20)
    try:
        payload = ddp_a.export_plan_payload()
        jsonl = str(tmp_path / "m.jsonl")
        tel = Telemetry(metrics_jsonl=jsonl)
        coord = ElasticResumeCoordinator(
            str(tmp_path / "snaps"), telemetry=tel,
            fleet_plan_fn=lambda: payload,
        )
        assert coord.fleet_warm_start(ddp_b) == "fleet"
        assert plan_names(ddp_b) == plan_names(ddp_a)
        tel.close()
        assert validate_metrics_file(jsonl) == []
        (restart,) = [json.loads(l) for l in open(jsonl) if '"restart"' in l]
        assert restart["plan_source"] == "fleet" and restart["step"] == 0

        # no hook / a broken hook / a miss: all advisory, all None
        assert ElasticResumeCoordinator(
            str(tmp_path / "s2")
        ).fleet_warm_start(ddp_b) is None
        assert ElasticResumeCoordinator(
            str(tmp_path / "s3"), fleet_plan_fn=lambda: None
        ).fleet_warm_start(ddp_b) is None

        def boom():
            raise ConnectionRefusedError("fleet down")

        assert ElasticResumeCoordinator(
            str(tmp_path / "s4"), fleet_plan_fn=boom
        ).fleet_warm_start(ddp_b) is None
    finally:
        ddp_a.shutdown()
        ddp_b.shutdown()


# ---------------- SIGKILL + restart with live clients ------------------------


def _server_cmd(port, wal_dir):
    return [
        sys.executable, "-m", "bagua_tpu.fleet.server",
        "--port", str(port), "--host", "127.0.0.1",
        "--wal-dir", wal_dir, "--settle-s", "0.05", "--lease-ttl-s", "600",
    ]


def _wait_health(port, deadline_s=120.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            out = _get_json(f"http://127.0.0.1:{port}/fleet/health", timeout=2.0)
            if out.get("status") == "ok":
                return
        except (OSError, ValueError):
            time.sleep(0.2)
    raise TimeoutError(f"fleet server on port {port} never became healthy")


@pytest.mark.slow
def test_sigkill_restart_replays_wal_with_live_clients(tmp_path):
    port = free_port()
    wal_dir = str(tmp_path / "wal")
    env = worker_env(JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        _server_cmd(port, wal_dir), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    proc2 = None
    try:
        _wait_health(port)
        base = f"http://127.0.0.1:{port}"
        alpha = RendezvousClient(gang_endpoint(base, "alpha"), node_rank=0,
                                 timeout_s=30.0)
        alpha.wait_assignment(nslots=2, incarnation=1)
        for i in range(5):
            alpha.kv_set(f"ck/{i}", i)
        req = urllib.request.Request(
            gang_endpoint(base, "alpha") + "/rdzv/blob/weights",
            data=b"\x07" * 256, method="PUT",
        )
        _get_json_req(req)
        gamma = RendezvousClient(gang_endpoint(base, "gamma"), node_rank=0,
                                 timeout_s=30.0)
        gamma.kv_set("x", "y")
        pre = _get_json(base + "/fleet/dump")
        assert pre["n_gangs"] == 2

        # a live client keeps hammering across the outage: its breaker
        # absorbs the dead window, then it recovers on its own
        stop, restarted = threading.Event(), threading.Event()
        counts = {"fail": 0, "ok_after_restart": 0}
        breaker = CircuitBreaker(failure_threshold=2, cooldown_s=0.1, name="rider")
        policy = RetryPolicy(retries=1, base_s=0.01, max_s=0.05)

        def rider():
            while not stop.is_set():
                try:
                    retry_call(
                        alpha._call_once, "/rdzv/heartbeat", {"node_rank": 0},
                        policy=policy, breaker=breaker,
                    )
                    if restarted.is_set():
                        counts["ok_after_restart"] += 1
                except Exception:
                    counts["fail"] += 1
                time.sleep(0.02)

        t = threading.Thread(target=rider, daemon=True)
        t.start()
        time.sleep(0.3)  # let the rider see the healthy server first
        proc.kill()  # SIGKILL: no shutdown hook, no final compaction
        proc.wait(timeout=30)
        time.sleep(0.5)  # the rider must observe the outage
        assert counts["fail"] >= 1

        proc2 = subprocess.Popen(
            _server_cmd(port, wal_dir), env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        _wait_health(port)
        restarted.set()
        deadline = time.monotonic() + 30.0
        while counts["ok_after_restart"] < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        stop.set()
        t.join(timeout=10)
        assert counts["ok_after_restart"] >= 3  # the same client recovered
        assert breaker.times_opened >= 1  # the outage was breaker-absorbed

        # the WAL replay is exact: same durable dump, bit for bit
        post = _get_json(base + "/fleet/dump")
        assert _canon(post) == _canon(pre)
        # and the replayed state is live
        assert alpha.kv_get("ck/3") == 3
        asn = alpha._call("/rdzv/assignment")
        assert asn["settled"] and asn["world_size"] == 2
        assert gamma.kv_get("x") == "y"
    finally:
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=30)


# ---------------- scheduler verdict precedence + tracing tier ----------------


def test_scheduler_view_verdict_precedence_conflicting_signals():
    """The verdict ladder is wedged > straggler > regressed > healthy >
    idle: a gang carrying BOTH a flight digest and a straggler-grade p50
    spread must come back wedged, with the losing straggler signal still
    reported; a sentinel incident outranks healthy summaries but loses to
    a straggler spread (and the losing ``regressed`` fact survives)."""
    plane = FleetControlPlane(lease_ttl_s=50.0, clock=lambda: 10.0,
                              rdzv_kwargs=RDZV_FAST)

    def push(gang, rank, p50, phase_ms=None):
        plane.gang(gang).rendezvous.kv_set(
            gang_kv_key("0", rank),
            StepSummary(rank=rank, step=3, p50_ms=p50,
                        phase_ms=phase_ms or {}).payload(),
        )

    incident = {"step": 3, "dominant": "wire_slowdown", "stream": "step_wall"}

    # conflicting signals on one gang: a 4x p50 spread AND a flight digest
    push("conflict", 0, 10.0)
    push("conflict", 1, 40.0, phase_ms={"h2d": 30.0, "compute": 5.0})
    plane.gang("conflict").rendezvous.kv_set(flight_kv_key("0", 1), {"hang": True})
    # the same summaries without the digest sit one rung down — and an
    # incident on top must NOT outrank the straggler finding
    push("strag", 0, 10.0)
    push("strag", 1, 40.0, phase_ms={"h2d": 30.0, "compute": 5.0})
    plane.ingest_incidents("strag", [incident])
    # healthy summaries + an incident: the sentinel verdict wins
    push("regressed", 0, 10.0)
    push("regressed", 1, 11.0)
    plane.ingest_incidents("regressed", [incident])
    push("ok", 0, 10.0)
    push("ok", 1, 11.0)
    plane.gang("empty")

    gangs = plane.scheduler_view()["gangs"]
    assert gangs["conflict"]["verdict"] == "wedged"
    assert gangs["conflict"]["flight_ranks"] == ["rank1"]
    # the digest outranks — but does not erase — the straggler finding
    assert gangs["conflict"]["straggler"] is not None
    assert gangs["conflict"]["straggler"]["rank"] == 1
    assert gangs["strag"]["verdict"] == "straggler"
    # the straggler outranks — but does not erase — the regressed fact
    assert gangs["strag"]["regressed"] is True
    assert gangs["strag"]["incidents"] == 1
    assert gangs["regressed"]["verdict"] == "regressed"
    assert gangs["regressed"]["last_incident"] == {
        "step": 3, "dominant": "wire_slowdown", "stream": "step_wall",
    }
    assert gangs["ok"]["verdict"] == "healthy"
    assert gangs["ok"]["regressed"] is False
    assert gangs["empty"]["verdict"] == "idle"
    order = ("empty", "ok", "regressed", "strag", "conflict")
    assert [gangs[g]["verdict"] for g in order] == [
        "idle", "healthy", "regressed", "straggler", "wedged",
    ]


def test_fleet_tracing_timeline_join_and_metrics():
    """End to end over HTTP: a traced client RPC produces a server span
    that is a *child* of the client span (traceparent propagated), the
    pushed client spans join it on /fleet/timeline in parent-before-child
    order, /fleet/metrics exports the per-gang counters, and none of the
    volatile span state leaks into the durable dump."""
    plane = FleetControlPlane(rdzv_kwargs=RDZV_FAST)
    server, base = _serve(plane)
    tracer = Tracer(sample_every=1)
    set_global_tracer(tracer)
    try:
        fc = FleetClient(base)
        tracer.begin_step(0, variant="full")
        rc = fc.rendezvous_client("tr", 0)
        rc.kv_set("warm", 1)
        tracer.end_step()
        client_spans = tracer.finished_spans()
        rpc_span = next(
            s for s in client_spans if s["name"] == "rpc /rdzv/kv/warm"
        )
        root = next(s for s in client_spans if s["name"] == "train_step")
        assert rpc_span["trace_id"] == root["trace_id"]
        assert rpc_span["parent_id"] == root["span_id"]

        # push the finished spans (plus one junk span and one event); the
        # junk must be counted and dropped, never ingested
        pushed = fc.push_spans(
            "tr", client_spans + [{"trace_id": "nope"}],
            events=[{"event": "health_alert", "ts": time.time(), "rank": 0}],
        )
        assert pushed["accepted"] == len(client_spans)
        assert pushed["rejected"] == 1
        assert pushed["events"] == 1

        tl = fc.timeline("tr")
        assert tl["gang"] == "tr"
        assert tl["n_server_spans"] >= 1 and tl["n_events"] == 1
        server_spans = [i for i in tl["items"] if i["item"] == "server_span"]
        joined = [
            s for s in server_spans if s.get("parent_id") == rpc_span["span_id"]
        ]
        assert joined, server_spans
        assert joined[0]["trace_id"] == rpc_span["trace_id"]
        assert joined[0]["attrs"]["service"] == "fleet-server"
        assert joined[0]["attrs"]["status"] == 200
        # the causal index walks each trace parent-before-child
        chain = [s["span_id"] for s in tl["traces"][root["trace_id"]]]
        assert chain.index(root["span_id"]) < chain.index(rpc_span["span_id"])
        assert chain.index(rpc_span["span_id"]) < chain.index(joined[0]["span_id"])
        assert any(i["item"] == "event" and i["event"] == "health_alert"
                   for i in tl["items"])

        text = fc.metrics_text()
        assert "bagua_fleet_requests_total_tr" in text
        assert "bagua_fleet_lease_remaining_s_tr" in text
        assert "bagua_fleet_plan_cache_hits_total" in text
        assert "bagua_fleet_plan_cache_misses_total" in text

        # /fleet/timeline without a gang is a client error, not a crash
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(base + "/fleet/timeline")
        assert err.value.code == 400

        # the span rings are volatile: not a byte of them in the durable
        # dump, so the kill/restart bitwise witness is untouched
        dump = _get_json(base + "/fleet/dump")
        assert "span" not in json.dumps(dump)
        assert "trace_id" not in json.dumps(dump)
    finally:
        set_global_tracer(None)
        tracer.close()
        server.shutdown()


# ---------------- incident tier (regression sentinel) -------------------------


def test_fleet_incident_tier_routes_metrics_and_volatility(tmp_path):
    """End to end over HTTP: pushed perf_regression incidents land in the
    gang's volatile ring (malformed ones counted and dropped), surface on
    /fleet/incidents, /fleet/scheduler (the ``regressed`` verdict +
    ``last_incident`` fact), /fleet/timeline (``incident`` items) and the
    /fleet/metrics incident counters — and never touch the WAL: a restart
    on the same WAL dir comes back with an empty incident tier."""
    plane = FleetControlPlane(wal_dir=str(tmp_path / "wal"),
                              rdzv_kwargs=RDZV_FAST)
    server, base = _serve(plane)
    try:
        fc = FleetClient(base)
        incidents = [
            {"event": "perf_regression", "ts": time.time(), "step": 12,
             "stream": "step_wall", "dominant": "compile",
             "components": {"compile": 8.0, "unattributed": 0.1},
             "residual_ms": 8.1, "expected_ms": 10.0, "measured_ms": 18.1,
             "plan_version": 0, "trace_id": ""},
            {"event": "perf_regression", "ts": time.time(), "step": 40,
             "stream": "goodput", "dominant": "straggler",
             "straggler_rank": 2, "components": {"straggler": 120.0},
             "residual_ms": 120.0, "expected_ms": 10.0,
             "measured_ms": 130.0, "plan_version": 1, "trace_id": ""},
        ]
        out = fc.push_incidents("inc", incidents + ["junk", {"dominant": 3}])
        assert out["accepted"] == 2 and out["rejected"] == 2

        per_gang = fc.incidents("inc")
        assert per_gang["gang"] == "inc" and per_gang["n_incidents"] == 2
        assert [i["dominant"] for i in per_gang["incidents"]] == [
            "compile", "straggler",
        ]
        all_gangs = fc.incidents()
        assert all_gangs["n_incidents"] == 2
        assert set(all_gangs["gangs"]) == {"inc"}

        row = fc.scheduler_view()["gangs"]["inc"]
        assert row["verdict"] == "regressed" and row["regressed"] is True
        assert row["incidents"] == 2
        assert row["last_incident"] == {
            "step": 40, "dominant": "straggler", "stream": "goodput",
        }

        tl = fc.timeline("inc")
        tl_incidents = [i for i in tl["items"] if i["item"] == "incident"]
        assert len(tl_incidents) == 2 and tl["n_incidents"] == 2
        assert {i["dominant"] for i in tl_incidents} == {
            "compile", "straggler",
        }

        text = fc.metrics_text()
        assert "bagua_fleet_incidents_total 2" in text
        assert "bagua_fleet_incidents_total_inc 2" in text

        # volatile tier: not a byte of it in the durable dump ...
        dump = _get_json(base + "/fleet/dump")
        assert "perf_regression" not in json.dumps(dump)
        assert "incident" not in json.dumps(dump)
    finally:
        server.shutdown()

    # ... so a restart on the same WAL replays to an EMPTY incident tier
    plane2 = FleetControlPlane(wal_dir=str(tmp_path / "wal"),
                               rdzv_kwargs=RDZV_FAST)
    assert plane2.incidents()["n_incidents"] == 0
    assert plane2.scheduler_view()["gangs"]["inc"]["regressed"] is False

def test_fleet_axis_incident_and_decision_round_trip():
    """Axis-resolved incidents and axis-scoped autopilot decisions keep
    their axis/link_class through the HTTP round trip: the scheduler
    view's ``last_incident`` and ``autopilot`` columns and the timeline's
    incident/decision items carry the fields verbatim, and axis-blind
    payloads keep the exact legacy shape (no axis key materializes)."""
    plane = FleetControlPlane(rdzv_kwargs=RDZV_FAST)
    server, base = _serve(plane)
    try:
        fc = FleetClient(base)
        inc = {
            "event": "perf_regression", "ts": time.time(), "step": 60,
            "stream": "wire_axis:dp", "dominant": "wire_slowdown",
            "components": {"wire_slowdown": 40.0}, "residual_ms": 40.0,
            "expected_ms": 10.0, "measured_ms": 50.0, "plan_version": 1,
            "trace_id": "", "axis": "dp", "link_class": "dcn",
            "wire_axis_ms": {"dp": 39.0, "tp": 1.0},
        }
        assert fc.push_incidents("ax", [inc])["accepted"] == 1
        dec = {
            "event": "plan_decision", "ts": time.time(), "step": 61,
            "decision": "demote_precision",
            "reason": "autopilot:wire_slowdown", "trace_id": "",
            "plan_version": 2,
            "from_config": {"algorithm": "gradient_allreduce",
                            "precision": "f32"},
            "to_config": {"algorithm": "gradient_allreduce",
                          "precision": "int8"},
            "verdict": "canary", "axis": "dp",
        }
        assert fc.push_decisions("ax", [dec])["accepted"] == 1

        row = fc.scheduler_view()["gangs"]["ax"]
        assert row["verdict"] == "regressed"
        assert row["last_incident"] == {
            "step": 60, "dominant": "wire_slowdown",
            "stream": "wire_axis:dp", "axis": "dp", "link_class": "dcn",
        }
        assert row["autopilot"] == {
            "decision": "demote_precision", "verdict": "canary", "step": 61,
            "to_config": {"algorithm": "gradient_allreduce",
                          "precision": "int8"},
            "axis": "dp",
        }

        tl = fc.timeline("ax")
        (tl_inc,) = [i for i in tl["items"] if i["item"] == "incident"]
        assert tl_inc["axis"] == "dp" and tl_inc["link_class"] == "dcn"
        assert tl_inc["wire_axis_ms"] == {"dp": 39.0, "tp": 1.0}
        (tl_dec,) = [i for i in tl["items"] if i["item"] == "decision"]
        assert tl_dec["axis"] == "dp"

        # an axis-blind gang keeps the legacy column shapes exactly
        legacy_inc = {k: v for k, v in inc.items()
                      if k not in ("axis", "link_class", "wire_axis_ms")}
        legacy_dec = {k: v for k, v in dec.items() if k != "axis"}
        fc.push_incidents("old", [legacy_inc])
        fc.push_decisions("old", [legacy_dec])
        old = fc.scheduler_view()["gangs"]["old"]
        assert old["last_incident"] == {
            "step": 60, "dominant": "wire_slowdown",
            "stream": "wire_axis:dp",
        }
        assert "axis" not in old["autopilot"]
    finally:
        server.shutdown()


# ---------------- remediation engine: the verdict-driven fleet loop -----------


def _push_summary(plane, gang, rank, p50, step=5, attempt="0"):
    plane.gang(gang).rendezvous.kv_set(
        gang_kv_key(attempt, rank),
        StepSummary(rank=rank, step=step, p50_ms=p50).payload(),
    )


def _flight_digest(rank, label_at_2):
    tail = []
    for seq in range(3):
        label = label_at_2 if seq == 2 else f"allreduce:b{seq}"
        tail.append({
            "seq": seq, "step": seq, "label": label, "algo": "allreduce",
            "bucket": seq, "phase": "wire", "precision": "fp32",
            "nbytes": 1 << 20, "plan_version": 1, "variant": "sync",
            "t_enqueue": 1.0 + seq, "t_retire": 1.5 + seq,
        })
    return {"rank": rank, "last_seq": 2, "tail": tail, "mono": 120.0,
            "unretired": 0}


PLAN_DIMS = {"topology": "cpu:8", "algorithm": "gradient_allreduce",
             "wire_precision": "fp32"}


def test_remediation_quarantine_exact_correlation_and_wal_replay(tmp_path):
    """Arc 1 end to end: incidents citing the adopted plan_version quarantine
    the plan and roll back EVERY adopter; a regressed gang whose incidents
    name a different version indicts nothing (zero false quarantines); every
    action replays bitwise from the WAL, and the labeled remediation metric
    families count what the journal counted."""
    wal_dir = str(tmp_path / "wal")
    plane = FleetControlPlane(wal_dir=wal_dir, rdzv_kwargs=RDZV_FAST)
    bad_key = plane.plan_put("bad", plan={"buckets": [["w"]]},
                             meta={"plan_version": 2}, **PLAN_DIMS)
    good_key = plane.plan_put("good", plan={"buckets": [["w"]]},
                              meta={"plan_version": 1}, **PLAN_DIMS)
    for gang in ("b0", "b1"):
        assert plane.plan_get("bad", gang=gang, **PLAN_DIMS) is not None
        _push_summary(plane, gang, 0, 10.0)
    assert plane.plan_get("good", gang="h0", **PLAN_DIMS) is not None
    _push_summary(plane, "h0", 0, 10.0)

    # b0/b1 indict version 2 by trace; h0 regresses on an UNRELATED version
    for i, gang in enumerate(("b0", "b1")):
        plane.ingest_incidents(gang, [{
            "step": 5, "dominant": "wire_slowdown", "stream": "step_wall",
            "plan_version": 2, "trace_id": f"bad-trace-{i}",
        }])
    plane.ingest_incidents("h0", [{
        "step": 5, "dominant": "wire_slowdown", "stream": "step_wall",
        "plan_version": 999, "trace_id": "noise-trace",
    }])

    summary = RemediationEngine(plane).sweep()
    assert summary["quarantined"] == [bad_key]
    assert sorted(r["gang"] for r in summary["rollbacks"]) == ["b0", "b1"]
    statuses = plane.plan_statuses()
    assert statuses[bad_key]["status"] == "quarantined"
    assert statuses[bad_key]["cites"] == ["bad-trace-0", "bad-trace-1"]
    assert statuses[good_key]["status"] != "quarantined"  # no false positive
    # a quarantined plan is never served again — not even to a fresh gang
    assert plane.plan_get("bad", gang="b9", **PLAN_DIMS) is None
    # republication of the same version cannot launder the quarantine
    plane.plan_put("bad", plan={"buckets": [["w"]]},
                   meta={"plan_version": 2}, **PLAN_DIMS)
    assert plane.plan_get("bad", gang="b9", **PLAN_DIMS) is None

    (quarantine_ev,) = [e for e in summary["events"]
                        if e["event"] == "plan_quarantine"]
    assert quarantine_ev["cites"] == ["bad-trace-0", "bad-trace-1"]
    assert quarantine_ev["gangs"] == ["b0", "b1"]

    d = plane.directive("b0")
    assert d["action"] == "rollback_plan"
    assert d["reason"] == "plan_quarantine:v2"
    assert d["detail"]["cache_key"] == bad_key
    assert plane.ack_directive("b0", d["id"])
    # the unacked rollback surfaces as b1's remediation-pending marker
    gangs = plane.scheduler_view()["gangs"]
    assert gangs["b1"]["remediation"] == {
        "pending": 1, "action": "rollback_plan",
        "id": plane.directive("b1")["id"],
    }
    assert gangs["b0"]["remediation"] is None

    text = plane.metrics_text()
    assert "bagua_fleet_shard_count 1" in text
    assert 'bagua_wal_replay_ms{shard="0"}' in text
    assert 'bagua_remediations_total{action="quarantine"} 1' in text
    assert 'bagua_remediations_total{action="rollback_plan"} 2' in text

    # crash + replay: the whole remediation tier is bitwise-identical, live
    pre = plane.dump()
    plane2 = FleetControlPlane(wal_dir=wal_dir, rdzv_kwargs=RDZV_FAST)
    assert _canon(plane2.dump()) == _canon(pre)
    assert plane2.wal_replay_ms > 0
    assert plane2.plan_get("bad", gang="b9", **PLAN_DIMS) is None
    assert plane2.directive("b0") is None          # the ack survived
    assert plane2.directive("b1")["action"] == "rollback_plan"
    assert 'bagua_remediations_total{action="quarantine"} 1' in plane2.metrics_text()


def test_remediation_canary_gate_and_graduation():
    """Arc 3: a fresh plan_version serves only its first ``canary_n``
    adopters; once every cohort member reports a healthy window the plan
    graduates to default and the withheld gang is finally served."""
    plane = FleetControlPlane(rdzv_kwargs=RDZV_FAST, canary_n=2)
    key = plane.plan_put("cand", plan={"buckets": [["w"]]},
                         meta={"plan_version": 3}, **PLAN_DIMS)
    assert plane.plan_get("cand", gang="c0", **PLAN_DIMS) is not None
    assert plane.plan_get("cand", gang="c1", **PLAN_DIMS) is not None
    # cohort full: a third gang is withheld, but the legacy gang-less read
    # (no adoption, no canary exposure) still sees the cache entry
    assert plane.plan_get("cand", gang="c2", **PLAN_DIMS) is None
    assert plane.plan_get("cand", **PLAN_DIMS) is not None
    assert plane.plan_statuses()[key]["cohort"] == ["c0", "c1"]

    for gang in ("c0", "c1"):
        _push_summary(plane, gang, 0, 10.0)
        _push_summary(plane, gang, 1, 11.0)
    summary = RemediationEngine(plane).sweep()
    assert [c["gang"] for c in summary["clean"]] == ["c0", "c1"]
    assert summary["graduated"] == [key]
    assert summary["quarantined"] == [] and summary["resized"] == []
    rec = plane.plan_statuses()[key]
    assert rec["status"] == "default" and rec["clean"] == ["c0", "c1"]
    assert plane.plan_get("cand", gang="c2", **PLAN_DIMS) is not None
    verdicts = [e["verdict"] for e in summary["events"]
                if e["event"] == "canary_verdict"]
    assert verdicts == ["clean", "clean", "graduated"]
    # idempotent: a graduated plan produces no further canary traffic
    again = RemediationEngine(plane).sweep()
    assert again["clean"] == [] and again["graduated"] == []


def test_remediation_wedged_resize_directive_over_async_http():
    """Arc 2 over the selector-loop server: pushed flight digests whose
    tails first diverge at one seq join to a ``desync`` hang report; the
    sweep directs a resize shedding the divergent rank, re-sweeping while
    the directive is pending is a no-op, and the gang fetches + acks the
    directive over HTTP."""
    plane = FleetControlPlane(rdzv_kwargs=RDZV_FAST)
    server = start_async_fleet_server(plane, 0, host="127.0.0.1")
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        fc = FleetClient(base)
        rc = fc.rendezvous_client("w0", 0)
        rc.kv_set(flight_kv_key("0", 0), _flight_digest(0, "allreduce:b2"))
        rc.kv_set(flight_kv_key("0", 1), _flight_digest(1, "allgather:bX"))
        assert fc.scheduler_view()["gangs"]["w0"]["verdict"] == "wedged"

        sweep = fc.remediate()
        (resized,) = sweep["resized"]
        assert resized == {"gang": "w0", "verdict": "desync",
                           "to_world_size": 1}
        # pending directive -> the next sweep must not double-direct
        assert fc.remediate()["resized"] == []
        assert fc.scheduler_view()["gangs"]["w0"]["remediation"]["action"] == "resize"

        d = fc.gang_directive("w0")
        assert d["action"] == "resize" and d["reason"] == "hang:desync"
        assert d["detail"]["to_world_size"] == 1
        assert d["detail"]["implicated_ranks"] == [1]
        assert fc.ack_directive("w0", d["id"])
        assert fc.gang_directive("w0") is None
        rem = fc.remediation()
        assert rem["actions"]["resize"] == 1
    finally:
        server.shutdown()


def test_scheduler_view_verdict_races_with_remediation_marker():
    """The remediation-pending marker is a marker, not a verdict rung: it
    rides straggler/regressed/wedged/healthy rows without moving them on
    the ladder, always names the OLDEST pending directive, and clears only
    when the last directive is acked."""
    plane = FleetControlPlane(rdzv_kwargs=RDZV_FAST)
    incident = {"step": 3, "dominant": "wire_slowdown", "stream": "step_wall"}

    # race: straggler spread AND a regression incident AND a directive
    _push_summary(plane, "race", 0, 10.0, step=3)
    _push_summary(plane, "race", 1, 40.0, step=3)
    plane.ingest_incidents("race", [incident])
    first = plane.issue_directive("race", "rollback_plan", reason="q:v2")
    second = plane.issue_directive("race", "resize", reason="hang:desync")
    row = plane.scheduler_view()["gangs"]["race"]
    assert row["verdict"] == "straggler"      # the marker did not outrank
    assert row["regressed"] is True           # the losing fact survives
    assert row["remediation"] == {"pending": 2, "action": "rollback_plan",
                                  "id": first["id"]}
    # acking the oldest promotes the next-oldest into the marker
    assert plane.ack_directive("race", first["id"])
    row = plane.scheduler_view()["gangs"]["race"]
    assert row["verdict"] == "straggler"
    assert row["remediation"] == {"pending": 1, "action": "resize",
                                  "id": second["id"]}
    assert plane.ack_directive("race", second["id"])
    assert plane.scheduler_view()["gangs"]["race"]["remediation"] is None

    # a healthy gang under direction stays healthy; wedged stays wedged
    _push_summary(plane, "ok", 0, 10.0)
    _push_summary(plane, "ok", 1, 11.0)
    plane.issue_directive("ok", "rollback_plan", reason="q:v9")
    plane.gang("wedge").rendezvous.kv_set(flight_kv_key("0", 0),
                                          _flight_digest(0, "allreduce:b2"))
    plane.issue_directive("wedge", "resize", reason="hang:host_wedge")
    gangs = plane.scheduler_view()["gangs"]
    assert gangs["ok"]["verdict"] == "healthy"
    assert gangs["ok"]["remediation"]["action"] == "rollback_plan"
    assert gangs["wedge"]["verdict"] == "wedged"
    assert gangs["wedge"]["remediation"]["action"] == "resize"


# ---------------- sharded control plane ---------------------------------------


def test_sharded_plane_routing_fanout_merge_and_replay(tmp_path):
    """Consistent-hash sharding: routing is deterministic across ring
    rebuilds, every shard takes load, fleet-wide reads merge all shards,
    plan ops route by plan key (one authoritative shard), and a restart
    on the same WAL dirs replays every shard to the bitwise dump."""
    keys = [f"gang:g{i}" for i in range(200)]
    ring = HashRing(4)
    assert [ring.shard_for(k) for k in keys] == [
        HashRing(4).shard_for(k) for k in keys
    ]
    assert {ring.shard_for(k) for k in keys} == {0, 1, 2, 3}

    wal_dir = str(tmp_path / "wal")
    fleet = ShardedControlPlane(n_shards=4, wal_dir=wal_dir,
                                rdzv_kwargs=RDZV_FAST)
    gangs = [f"g{i}" for i in range(12)]
    for i, gang in enumerate(gangs):
        fleet.gang(gang).rendezvous.kv_set("warm", i)
    assert fleet.gang_ids() == sorted(gangs)
    info = fleet.shard_info()
    assert info["n_shards"] == 4
    assert sum(info["gangs_per_shard"]) == 12
    assert len(info["wal_replay_ms"]) == 4
    # isolation across the ring: one gang's key reads nothing elsewhere
    assert fleet.gang("g0").rendezvous.kv_get("warm") == 0
    assert fleet.gang("g1").rendezvous.kv_get("nope") is None

    key = fleet.plan_put("fp", plan={"buckets": [["w"]]},
                         meta={"plan_version": 1}, **PLAN_DIMS)
    owners = [s for s in fleet.shards if s.plan_count() == 1]
    assert len(owners) == 1                      # exactly one authoritative shard
    assert owners[0] is fleet.shard_for_plan_key(key)
    # a gang living on ANY shard adopts through the facade
    assert fleet.plan_get("fp", gang="g0", **PLAN_DIMS) is not None
    assert "g0" in fleet.plan_statuses()[key]["adopters"]

    fleet.issue_directive("g3", "resize", reason="hang:desync")
    assert fleet.directive("g3")["action"] == "resize"
    assert fleet.scheduler_view()["n_gangs"] == 12

    text = fleet.metrics_text()
    assert "bagua_fleet_shard_count 4" in text
    for shard in range(4):
        assert f'bagua_wal_replay_ms{{shard="{shard}"}}' in text

    pre = fleet.dump()
    assert pre["n_shards"] == 4 and len(pre["shards"]) == 4
    fleet2 = ShardedControlPlane(n_shards=4, wal_dir=wal_dir,
                                 rdzv_kwargs=RDZV_FAST)
    assert _canon(fleet2.dump()) == _canon(pre)
    assert fleet2.gang("g0").rendezvous.kv_get("warm") == 0
    assert fleet2.directive("g3")["action"] == "resize"
    assert all(ms > 0 for ms in fleet2.shard_info()["wal_replay_ms"])


def test_async_server_keepalive_pipelined_requests_and_404():
    """The selector-loop server speaks persistent HTTP/1.1: many requests
    ride one connection (GET and POST), an unknown route answers 404
    without killing the connection, and shutdown closes the listener."""
    plane = FleetControlPlane(rdzv_kwargs=RDZV_FAST)
    server = start_async_fleet_server(plane, 0, host="127.0.0.1")
    port = server.server_address[1]
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        for _ in range(3):
            conn.request("GET", "/fleet/health")
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 200 and body["status"] == "ok"
        payload = json.dumps({**PLAN_DIMS, "fingerprint": "fp",
                              "plan": {"buckets": [["w"]]},
                              "meta": {"plan_version": 1}}).encode()
        conn.request("POST", "/fleet/plan/publish", body=payload,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200 and json.loads(resp.read())["ok"]
        conn.request("GET", "/nope")
        resp = conn.getresponse()
        assert resp.status == 404
        resp.read()
        # the 404 was an answer, not a hangup: the connection still serves
        conn.request("GET", "/fleet/shards")
        resp = conn.getresponse()
        assert resp.status == 200 and json.loads(resp.read())["n_shards"] == 1
    finally:
        conn.close()
        server.shutdown()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        try:
            _get_json(f"http://127.0.0.1:{port}/fleet/health", timeout=0.5)
            time.sleep(0.05)
        except OSError:
            break
    else:
        raise AssertionError("async server still answering after shutdown")
