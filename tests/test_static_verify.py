"""Static collective-program verifier: the four checkers and the engine gate.

Adversarial half (the acceptance cases): programs with a rank-conditional
collective, a bucket whose wire bytes are off by one from the planner's
analytic model, and a stale exported plan version must each be **rejected at
trace time** by the right checker — named check, named source label — and,
when the strict gate is on, must never dispatch (the flight recorder stays
empty).

Positive half: real engines (gradient_allreduce, zero — every wire
precision the sweep covers lives in ``ci/static_verify.py``) pass strict
verification, and the statically predicted flight program equals the
recorder's capture record-for-record.
"""

import dataclasses
import json
import os
import subprocess
import sys
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import bagua_tpu  # noqa: F401  (grafts jax.shard_map on old jax)
from bagua_tpu.algorithms import build_algorithm
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.analysis import (
    StaticVerifyError,
    WireModelConfig,
    canonical_records,
    check_rank_invariance,
    check_wire_exactness,
    collect_ir,
    verify_step_program,
)
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss
from bagua_tpu.observability.flight_recorder import FlightRecorder
from bagua_tpu.observability.telemetry import Telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAYERS = [64, 128, 128, 64]


def make_batch():
    rng = np.random.RandomState(0)
    return (
        jnp.asarray(rng.randn(32, LAYERS[0]).astype(np.float32)),
        jnp.asarray(rng.randn(32, LAYERS[-1]).astype(np.float32)),
    )


def make_ddp(group, algo=None, overlap=False, telemetry=None, **kw):
    return DistributedDataParallel(
        mse_loss,
        optax.sgd(0.1, momentum=0.9),
        algo or build_algorithm("gradient_allreduce", lr=0.1),
        process_group=group,
        bucket_size_bytes=1 << 12,
        overlap=overlap,
        telemetry=telemetry,
        **kw,
    )


# ---------------------------------------------------------------------------
# Adversarial program 1: rank-conditional collective
# ---------------------------------------------------------------------------


def test_rank_conditional_psum_rejected_at_trace_time(group):
    """A psum under a ``lax.cond`` whose predicate derives from
    ``axis_index``: different ranks would take different branches around a
    collective — the first-desync class.  check_rank_invariance must reject
    it at trace time, attributing the enclosing branch."""

    def body(x):
        r = jax.lax.axis_index("intra")

        def exchange(v):
            return jax.lax.psum(v, "intra")

        def skip(v):
            return v * 4.0

        return jax.lax.cond(r == 0, exchange, skip, x)

    fn = group.shard_map(body, in_specs=(P("intra"),), out_specs=P("intra"))
    x = jnp.ones((8, 4), jnp.float32)
    program, _ = collect_ir(fn, (x,), dict(group.mesh.shape))

    assert program.collectives, "psum not extracted from the cond branch"
    flagged = [d for d in program.collectives if d.rank_conditional]
    assert flagged, "collective not marked rank-conditional"

    findings = check_rank_invariance(program)
    errors = [f for f in findings if f.severity == "error"]
    assert errors, "rank-conditional psum was not rejected"
    assert all(f.check == "rank_invariance" for f in errors)
    # the finding names the branch the collective sits under
    assert any("cond" in (f.label or f.message) for f in errors)


def test_uniform_cond_psum_is_clean(group):
    """Control: the same cond-around-psum shape with a *rank-uniform*
    predicate (a scalar every rank computes identically, e.g. a step-count
    schedule) must verify clean — the taint analysis has to distinguish
    rank-derived from rank-uniform predicates, not ban lax.cond."""

    def body(x, step):
        def exchange(v):
            return jax.lax.psum(v, "intra")

        def skip(v):
            return v * 4.0

        return jax.lax.cond(step % 2 == 0, exchange, skip, x)

    fn = group.shard_map(
        body, in_specs=(P("intra"), P()), out_specs=P("intra")
    )
    x = jnp.ones((8, 4), jnp.float32)
    step = jnp.zeros((), jnp.int32)
    program, _ = collect_ir(fn, (x, step), dict(group.mesh.shape))

    assert program.collectives
    assert not [d for d in program.collectives if d.rank_conditional]
    assert not [
        f for f in check_rank_invariance(program) if f.severity == "error"
    ]


def test_subaxis_psum_does_not_launder_taint(group):
    """A psum over a *sub*-axis does not uniformize along the others: a
    predicate derived from ``axis_index('inter')`` stays inter-varying
    after a psum over 'intra' only, so branching on it around a collective
    must still be rejected (the false-negative class of whole-set
    laundering)."""

    def body(x):
        r = jax.lax.axis_index("inter")
        # reduces over 'intra' only: still differs across 'inter' ranks
        half_uniform = jax.lax.psum(r, "intra")

        def exchange(v):
            return jax.lax.psum(v, "intra")

        def skip(v):
            return v * 4.0

        return jax.lax.cond(half_uniform > 0, exchange, skip, x)

    fn = group.shard_map(body, in_specs=(P("intra"),), out_specs=P("intra"))
    x = jnp.ones((8, 4), jnp.float32)
    program, _ = collect_ir(fn, (x,), dict(group.mesh.shape))

    flagged = [d for d in program.collectives if d.rank_conditional]
    assert flagged, "sub-axis psum laundered taint it must not launder"
    assert [f for f in check_rank_invariance(program) if f.severity == "error"]

    # control: laundering over BOTH axes is rank-uniform again
    def body_full(x):
        r = jax.lax.axis_index("inter")
        uniform = jax.lax.psum(jax.lax.psum(r, "intra"), "inter")

        def exchange(v):
            return jax.lax.psum(v, "intra")

        def skip(v):
            return v * 4.0

        return jax.lax.cond(uniform > 0, exchange, skip, x)

    fn = group.shard_map(
        body_full, in_specs=(P("intra"),), out_specs=P("intra")
    )
    program, _ = collect_ir(fn, (x,), dict(group.mesh.shape))
    assert not [d for d in program.collectives if d.rank_conditional]


def test_while_cond_collective_recorded_and_flagged(group):
    """Collectives in a while loop's *predicate* jaxpr must enter the IR
    (wire census) and, under a rank-tainted predicate, the rank-invariance
    check — they used to be invisible to all four checkers."""

    def body(x):
        def cond_fn(c):
            i, v = c
            # a psum'd convergence residual in the loop predicate
            return jax.lax.psum(jnp.sum(v), "intra") > i

        def body_fn(c):
            i, v = c
            return i + 1, v * 0.5

        _, out = jax.lax.while_loop(cond_fn, body_fn, (jnp.float32(0.0), x))
        return out

    fn = group.shard_map(body, in_specs=(P("intra"),), out_specs=P("intra"))
    x = jnp.ones((8, 4), jnp.float32)
    program, _ = collect_ir(fn, (x,), dict(group.mesh.shape))
    in_while = [d for d in program.collectives if "while" in d.path]
    assert in_while, "predicate psum missing from the IR"
    # uniform predicate (psum'd residual): legal, not rank-conditional
    assert not [d for d in program.collectives if d.rank_conditional]

    def body_tainted(x):
        def cond_fn(c):
            i, v = c
            return i < jax.lax.axis_index("intra")  # rank-varying trip count

        def body_fn(c):
            i, v = c
            return i + 1, jax.lax.psum(v, "intra")

        _, out = jax.lax.while_loop(cond_fn, body_fn, (jnp.int32(0), x))
        return out

    fn = group.shard_map(
        body_tainted, in_specs=(P("intra"),), out_specs=P("intra")
    )
    program, _ = collect_ir(fn, (x,), dict(group.mesh.shape))
    flagged = [d for d in program.collectives if d.rank_conditional]
    assert flagged, "collective under a rank-varying trip count not flagged"
    assert [f for f in check_rank_invariance(program) if f.severity == "error"]


def test_psum_laundering_clears_taint(group):
    """A predicate *derived from* axis_index but passed through psum is
    rank-uniform again (every rank holds the identical sum) — branching on
    it is legal and must not be flagged."""

    def body(x):
        r = jax.lax.axis_index("intra")
        uniform = jax.lax.psum(r, "intra")  # identical on every rank

        def exchange(v):
            return jax.lax.psum(v, "intra")

        def skip(v):
            return v * 4.0

        return jax.lax.cond(uniform > 0, exchange, skip, x)

    fn = group.shard_map(body, in_specs=(P("intra"),), out_specs=P("intra"))
    x = jnp.ones((8, 4), jnp.float32)
    program, _ = collect_ir(fn, (x,), dict(group.mesh.shape))
    assert not [
        f for f in check_rank_invariance(program) if f.severity == "error"
    ]


# ---------------------------------------------------------------------------
# Adversarial program 2: bucket wire bytes off by one from the planner model
# ---------------------------------------------------------------------------


def test_bucket_bytes_off_by_one_rejected(group):
    """Tamper the planner's view of bucket 0 by a single element: the IR's
    observed ring bytes no longer equal the analytic model and
    check_wire_exactness must reject, naming the bucket's exchange label.
    (flat fuse, so the payload model reads ``spec.numel`` directly.)"""
    ddp = make_ddp(group, GradientAllReduceAlgorithm(fuse="flat"))
    try:
        state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
        cfg = WireModelConfig.from_engine(ddp)
        program, _ = collect_ir(
            ddp._build_sharded("default"),
            (
                jax.eval_shape(lambda s: s, state),
                jax.eval_shape(lambda b: b, make_batch()),
            ),
            dict(group.mesh.shape),
        )
        # control: the honest plan verifies byte-exact
        clean, _ = check_wire_exactness(program, cfg)
        assert not [f for f in clean if f.severity == "error"]

        specs = list(cfg.plan.specs)
        specs[0] = dataclasses.replace(specs[0], numel=specs[0].numel + 1)
        tampered = dataclasses.replace(
            cfg, plan=SimpleNamespace(specs=tuple(specs))
        )
        findings, _ = check_wire_exactness(program, tampered)
        errors = [f for f in findings if f.severity == "error"]
        assert errors, "off-by-one bucket bytes were not rejected"
        assert all(f.check == "wire_exactness" for f in errors)
        assert any(f.bucket == 0 for f in errors)
        assert any("bucket=0" in f.label for f in errors if f.label)
    finally:
        ddp.shutdown()


def test_cond_sibling_branches_not_double_counted(group):
    """The walker records every branch of a cond but only one executes:
    the wire census must charge sibling branches of the same cond the max,
    not the sum (a scope duplicated across both branches used to produce a
    false wire_exactness error)."""
    from types import SimpleNamespace as NS

    from bagua_tpu.analysis.collective_ir import (
        CollectiveDescriptor, CollectiveProgram,
    )

    def desc(i, path, wire):
        return CollectiveDescriptor(
            index=i, primitive="psum", reduce_op="sum", axes=("intra",),
            ring_size=4, shapes=((8,),), dtypes=("float32",), nbytes=32,
            wire_bytes=wire, label=f"d{i}",
            scope={"algo": "toy", "bucket": 0, "phase": "mono"},
            mp=None, qr=None, path=path, rank_conditional=False,
            cond_label=None,
        )

    program = CollectiveProgram(
        collectives=[
            desc(0, (), 50),                 # outside any cond: always runs
            desc(1, ("cond#0@0",), 100),     # branch 0
            desc(2, ("cond#0@1",), 100),     # sibling branch: exclusive
            desc(3, ("cond#1@0",), 7),       # a second, independent cond
        ],
        axis_sizes={"intra": 4},
    )
    cfg = WireModelConfig(algo="other", plan=NS(specs=()), n=4)
    findings, table = check_wire_exactness(program, cfg)
    assert not [f for f in findings if f.severity == "error"]
    (row,) = table
    assert row["observed_bytes"] == 50 + 100 + 7, row

    # and a real trace assigns sibling branches of one cond distinct ids
    def body(x, step):
        def a(v):
            return jax.lax.psum(v, "intra")

        def b(v):
            return jax.lax.psum(v * 2.0, "intra")

        return jax.lax.cond(step % 2 == 0, a, b, x)

    fn = group.shard_map(
        body, in_specs=(P("intra"), P()), out_specs=P("intra")
    )
    traced, _ = collect_ir(
        fn, (jnp.ones((8, 4), jnp.float32), jnp.zeros((), jnp.int32)),
        dict(group.mesh.shape),
    )
    frames = [d.path[-1] for d in traced.collectives if d.path]
    cids = {f.partition("@")[0] for f in frames}
    branches = {f.partition("@")[2] for f in frames}
    assert len(cids) == 1, frames
    assert branches == {"0", "1"}, frames


# ---------------------------------------------------------------------------
# Adversarial program 3: stale exported plan version
# ---------------------------------------------------------------------------


def test_stale_plan_version_rejected(group):
    """A plan payload exported before the last rebucket (plan_version
    behind the engine's) must be rejected by check_plan_conformance."""
    ddp = make_ddp(group)
    try:
        state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
        stale = {"plan_version": ddp.plan_version + 1}
        report = verify_step_program(
            ddp, state, make_batch(), variant="default", payload=stale
        )
        assert not report.ok
        assert all(f.check == "plan_conformance" for f in report.errors)
        assert any("plan_version" in f.message for f in report.errors)
        with pytest.raises(StaticVerifyError, match="plan_conformance"):
            report.raise_if_failed()

        # control: the freshly exported version verifies clean
        ok = verify_step_program(
            ddp, state, make_batch(), variant="default",
            payload={"plan_version": ddp.plan_version},
        )
        assert ok.ok, ok.summary()
    finally:
        ddp.shutdown()


# ---------------------------------------------------------------------------
# The strict gate: rejected programs never dispatch
# ---------------------------------------------------------------------------


def test_strict_gate_blocks_dispatch(group, monkeypatch):
    """Under ``BAGUA_STATIC_VERIFY=strict`` a program failing verification
    raises before the jitted step ever runs: the flight recorder holds zero
    records and no flight program was finalized."""
    monkeypatch.setenv("BAGUA_STATIC_VERIFY", "strict")
    orig = WireModelConfig.from_engine.__func__

    def tampered(cls, ddp):
        cfg = orig(cls, ddp)
        specs = list(cfg.plan.specs)
        specs[0] = dataclasses.replace(specs[0], numel=specs[0].numel + 1)
        return dataclasses.replace(
            cfg, plan=SimpleNamespace(specs=tuple(specs))
        )

    monkeypatch.setattr(
        WireModelConfig, "from_engine", classmethod(tampered)
    )
    tel = Telemetry(flight=FlightRecorder(capacity=64, rank=0, world_size=1))
    ddp = make_ddp(group, GradientAllReduceAlgorithm(fuse="flat"),
                   telemetry=tel)
    try:
        state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
        with pytest.raises(StaticVerifyError, match="wire_exactness"):
            ddp.train_step(state, make_batch())
        assert tel.flight.records() == [], "collectives dispatched anyway"
        assert ddp._flight_programs == {}
        # the rejected step must not linger in any cache: a caller that
        # catches the error and retries re-verifies instead of dispatching
        assert ddp._step_fns == {}, "rejected step left in the jit cache"
        assert ddp._predicted_programs == {}
        with pytest.raises(StaticVerifyError, match="wire_exactness"):
            ddp.train_step(state, make_batch())
        assert tel.flight.records() == []
    finally:
        ddp.shutdown()


def test_strict_gate_passes_real_engines(group, monkeypatch):
    """Strict mode on honest engines: the gate verifies on the first
    train_step (trace time), dispatch proceeds, and the live capture equals
    the stored prediction record-for-record."""
    monkeypatch.setenv("BAGUA_STATIC_VERIFY", "strict")
    for name in ("gradient_allreduce", "zero"):
        tel = Telemetry(
            flight=FlightRecorder(capacity=128, rank=0, world_size=1)
        )
        ddp = make_ddp(group, build_algorithm(name, lr=0.1), telemetry=tel)
        try:
            state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
            state, losses = ddp.train_step(state, make_batch())
            jax.block_until_ready(losses)
            variant = ddp.impl.step_variant(0)
            predicted = ddp._predicted_programs.get(variant)
            captured = ddp._flight_programs.get(variant)
            assert predicted, f"{name}: gate stored no prediction"
            assert captured, f"{name}: no live flight program"
            assert canonical_records(predicted) == canonical_records(captured)
        finally:
            ddp.shutdown()


def test_warn_gate_logs_but_dispatches(group, monkeypatch, caplog):
    """``warn`` mode: same tampered engine as the strict test, but the step
    must run — findings land in the log instead of an exception."""
    import logging

    monkeypatch.setenv("BAGUA_STATIC_VERIFY", "warn")
    orig = WireModelConfig.from_engine.__func__

    def tampered(cls, ddp):
        cfg = orig(cls, ddp)
        specs = list(cfg.plan.specs)
        specs[0] = dataclasses.replace(specs[0], numel=specs[0].numel + 1)
        return dataclasses.replace(
            cfg, plan=SimpleNamespace(specs=tuple(specs))
        )

    monkeypatch.setattr(
        WireModelConfig, "from_engine", classmethod(tampered)
    )
    ddp = make_ddp(group, GradientAllReduceAlgorithm(fuse="flat"))
    try:
        state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
        with caplog.at_level(logging.WARNING, logger="bagua_tpu.ddp"):
            state, losses = ddp.train_step(state, make_batch())
        jax.block_until_ready(losses)
        assert any("wire_exactness" in r.message for r in caplog.records)
    finally:
        ddp.shutdown()


# ---------------------------------------------------------------------------
# The bounded-staleness sanction
# ---------------------------------------------------------------------------


def _stale_cond_program(group, mark=True, equal_bytes=True, both_exchange=True):
    """Hand-rolled bounded-staleness shape: a *rank-conditional* cond whose
    branches differ in payload.  Knobs degrade it into the rejectable
    variants: drop the scope marker, shrink one branch's wire bytes, or
    skip the exchange in one branch entirely."""
    from contextlib import nullcontext

    from bagua_tpu.observability.scope_grammar import format_stale_scope

    scope = (lambda: jax.named_scope(format_stale_scope(2))) if mark \
        else nullcontext

    def body(x):
        r = jax.lax.axis_index("intra")

        def fresh(v):
            with scope():
                return jax.lax.psum(v, "intra")

        def replay(v):
            if not both_exchange:
                return v * 2.0
            if not equal_bytes:
                with scope():
                    half = jax.lax.psum(v[:, :2], "intra")
                return jnp.concatenate([half, v[:, 2:]], axis=1)
            with scope():
                return jax.lax.psum(v * 0.5, "intra")

        return jax.lax.cond(r == 0, fresh, replay, x)

    fn = group.shard_map(body, in_specs=(P("intra"),), out_specs=P("intra"))
    x = jnp.ones((8, 4), jnp.float32)
    program, _ = collect_ir(fn, (x,), dict(group.mesh.shape))
    return program


def test_stale_marker_with_equal_bytes_is_sanctioned_info(group):
    """The sanctioned exception: rank-conditional cond, BOTH branches under
    the ``bagua_stale/tau=<k>`` marker moving identical wire bytes — the
    wire census is preserved either way the predicate falls, so the finding
    downgrades to info and strict verification would pass."""
    program = _stale_cond_program(group)
    flagged = [d for d in program.collectives if d.rank_conditional]
    assert flagged and all(d.stale == 2 for d in flagged)
    findings = check_rank_invariance(program)
    assert not [f for f in findings if f.severity == "error"], findings
    infos = [f for f in findings if f.severity == "info"]
    assert infos and all("sanctioned" in f.message for f in infos)
    assert any("tau=2" in f.message for f in infos)


def test_stale_marker_with_unequal_bytes_is_rejected(group):
    """Marker present but the branches move different wire bytes: the
    staleness sanction must NOT launder a genuine census divergence."""
    program = _stale_cond_program(group, equal_bytes=False)
    errors = [
        f for f in check_rank_invariance(program) if f.severity == "error"
    ]
    assert errors, "unequal-byte staleness cond was sanctioned"


def test_stale_marker_single_branch_exchange_is_rejected(group):
    """Marker present but only one branch exchanges at all: ranks could skip
    the collective outright — never sanctionable."""
    program = _stale_cond_program(group, both_exchange=False)
    errors = [
        f for f in check_rank_invariance(program) if f.severity == "error"
    ]
    assert errors, "single-branch staleness cond was sanctioned"


def test_unmarked_equal_bytes_cond_is_still_rejected(group):
    """Equal bytes alone don't earn the sanction — the descriptor must opt
    in with the scope marker, otherwise the program is presumed buggy."""
    program = _stale_cond_program(group, mark=False)
    assert all(d.stale is None for d in program.collectives)
    errors = [
        f for f in check_rank_invariance(program) if f.severity == "error"
    ]
    assert errors, "unmarked rank-conditional cond was sanctioned"


def test_strict_gate_passes_bounded_staleness_engines(group, monkeypatch):
    """The real relaxations under the strict gate: stale τ=2 (directive up)
    and gossip decentralized τ=2 verify and dispatch — their where-gated
    payloads never introduce rank-conditional control flow — and a τ
    switch re-verifies before the re-bounded step dispatches."""
    import optax

    from bagua_tpu.algorithms.decentralized import DecentralizedAlgorithm
    from bagua_tpu.algorithms.stale import StaleSyncAlgorithm

    monkeypatch.setenv("BAGUA_STATIC_VERIFY", "strict")
    for algo in (
        StaleSyncAlgorithm(staleness_tau=2),
        DecentralizedAlgorithm(hierarchical=False, staleness_tau=2),
    ):
        ddp = DistributedDataParallel(
            mse_loss, optax.sgd(0.1), algo,
            process_group=group, bucket_size_bytes=1 << 12,
        )
        try:
            state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
            state = ddp.apply_degradation_directive(state, (2,))
            state, losses = ddp.train_step(state, make_batch())
            jax.block_until_ready(losses)
            assert ddp.apply_staleness(1, reason="planner") is True
            state, losses = ddp.train_step(state, make_batch())
            jax.block_until_ready(losses)
        finally:
            ddp.shutdown()


# ---------------------------------------------------------------------------
# Re-verification on plan adoption
# ---------------------------------------------------------------------------


def test_rebucket_reverifies_and_rolls_back(group, monkeypatch):
    """After the gate has seen a batch, a rebucket re-verifies the new plan
    under strict mode; a verifier rejection rolls the old plan back."""
    monkeypatch.setenv("BAGUA_STATIC_VERIFY", "strict")
    ddp = make_ddp(group)
    try:
        state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
        state, _ = ddp.train_step(state, make_batch())
        old_plan, old_version = ddp.plan, ddp.plan_version
        plan2 = ddp.impl.tensors_to_buckets(
            ddp._tree_template, 1 << 14, filter_fn=None
        )
        ddp.rebucket(plan2)  # honest plan: re-verify passes
        assert ddp.plan_version > old_version

        # now make the verifier reject everything and attempt another
        # rebucket: the engine must roll back to the adopted plan
        adopted = ddp.plan
        from bagua_tpu import analysis

        def failing_verify(*a, **kw):
            raise StaticVerifyError([])

        monkeypatch.setattr(analysis, "verify_step_program", failing_verify)
        with pytest.raises(StaticVerifyError):
            ddp.rebucket(old_plan)
        assert ddp.plan is adopted, "rejected plan was not rolled back"
    finally:
        ddp.shutdown()


def test_gate_verifies_post_reshard_layout(group, monkeypatch):
    """With a sharded updater, the first cache-miss step after rebucket()
    carries a *pending host-side reshard*: the live state still has the old
    shard layout while the new program expects the new one.  The gate must
    trace over the post-reshard template — feeding the old-layout state
    into make_jaxpr verifies a program other than the one that dispatches
    (and crashes outright when the shapes disagree)."""
    from bagua_tpu import analysis

    monkeypatch.setenv("BAGUA_STATIC_VERIFY", "strict")
    verified_states = []
    orig = analysis.verify_step_program

    def spy(ddp_, state_, batch_, **kw):
        verified_states.append(state_)
        return orig(ddp_, state_, batch_, **kw)

    monkeypatch.setattr(analysis, "verify_step_program", spy)
    ddp = make_ddp(group, build_algorithm("zero", lr=0.1))
    try:
        state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
        assert ddp._sharded_updater is not None
        state, _ = ddp.train_step(state, make_batch())
        plan2 = ddp.impl.tensors_to_buckets(
            ddp._tree_template, 1 << 14, filter_fn=None
        )
        ddp.rebucket(plan2)
        assert ddp._pending_reshard is not None
        # cache-miss step under the pending reshard: gate + dispatch OK,
        # and the gate traced the CURRENT layout's template, not the
        # stale live state
        state, losses = ddp.train_step(state, make_batch())
        jax.block_until_ready(losses)
        shapes = lambda t: jax.tree.map(lambda l: tuple(l.shape), t)
        assert shapes(verified_states[-1]) == shapes(ddp.state_template())
        # the gate handed the verifier the abstract CURRENT-layout template,
        # not the stale live state (whose shard layout predates the plan —
        # shapes can coincide between layouts, identity cannot)
        assert all(
            isinstance(l, jax.ShapeDtypeStruct)
            for l in jax.tree_util.tree_leaves(verified_states[-1])
        ), "gate traced the stale pre-reshard state"
    finally:
        ddp.shutdown()


def test_warn_gate_survives_trace_failure(group, monkeypatch, caplog):
    """A raw exception out of the verifier's trace (not a checker Finding)
    must not crash train_step in warn mode — logged, gate skipped, step
    dispatched.  Strict still propagates it."""
    import logging

    from bagua_tpu import analysis

    def boom(*a, **kw):
        raise TypeError("synthetic trace failure")

    monkeypatch.setattr(analysis, "verify_step_program", boom)

    monkeypatch.setenv("BAGUA_STATIC_VERIFY", "warn")
    ddp = make_ddp(group)
    try:
        state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
        with caplog.at_level(logging.WARNING, logger="bagua_tpu.ddp"):
            state, losses = ddp.train_step(state, make_batch())
        jax.block_until_ready(losses)
        assert any("trace failed" in r.message for r in caplog.records)
    finally:
        ddp.shutdown()

    monkeypatch.setenv("BAGUA_STATIC_VERIFY", "strict")
    ddp = make_ddp(group)
    try:
        state = ddp.init(init_mlp(jax.random.PRNGKey(0), LAYERS))
        with pytest.raises(TypeError, match="synthetic trace failure"):
            ddp.train_step(state, make_batch())
        assert ddp._step_fns == {}
    finally:
        ddp.shutdown()


# ---------------------------------------------------------------------------
# CI surfaces: the sweep artifact, the lint, the hang analyzer's strict exit
# ---------------------------------------------------------------------------


def test_static_verify_json_committed_and_green():
    """The committed sweep artifact must exist, be green, and cover every
    registered algorithm x {f32,int8,int4} x {overlap off,on}."""
    path = os.path.join(REPO, "STATIC_VERIFY.json")
    assert os.path.exists(path), "STATIC_VERIFY.json not committed"
    with open(path) as f:
        report = json.load(f)
    assert report["summary"]["fail"] == 0
    assert report["summary"]["live_mismatch"] == 0
    assert report["summary"]["pass"] > 0
    from bagua_tpu.algorithms import GlobalAlgorithmRegistry

    cells = {(r["algo"], r["wire"], r["overlap"]) for r in report["rows"]}
    for name in GlobalAlgorithmRegistry.keys():
        for wire in ("f32", "int8", "int4"):
            for overlap in (False, True):
                assert (name, wire, overlap) in cells, (name, wire, overlap)
    live = {r["algo"]: r for r in report["live_capture"]}
    assert set(live) == {"gradient_allreduce", "zero"}
    assert all(r["match"] for r in live.values())


@pytest.mark.slow
def test_lint_traced_detects_planted_hazards(tmp_path):
    """The retrace lint flags all four hazard classes in a planted file and
    exits nonzero on non-baselined findings."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time, random\n"
        "import jax, jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    t = time.time()\n"
        "    r = random.random()\n"
        "    if jnp.any(x > 0):\n"
        "        x = x + 1\n"
        "    return x, t, r, int(jnp.sum(x))\n"
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "ci", "lint_traced.py"),
         "--root", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    for rule in ("wallclock-in-traced", "host-random-in-traced",
                 "python-if-on-traced-call", "concretize-traced"):
        assert rule in proc.stdout, f"{rule} not detected:\n{proc.stdout}"


@pytest.mark.slow
def test_lint_traced_repo_is_baselined():
    """The repo itself lints clean against the committed baseline."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "ci", "lint_traced.py")],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"


@pytest.mark.slow
def test_diagnose_hang_strict_exits_nonzero_on_desync(tmp_path):
    """``ci/diagnose_hang.py --strict`` returns 4 on a desync verdict and 0
    on a healthy gang."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_flight_recorder import rank_dump

    for r in range(4):
        rank_dump(tmp_path, r, 12, drop_idx=7 if r == 2 else None)
    script = os.path.join(REPO, "ci", "diagnose_hang.py")
    proc = subprocess.run(
        [sys.executable, script, "--dir", str(tmp_path), "--strict"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 4, proc.stderr
    assert "desync" in proc.stderr

    healthy = tmp_path / "healthy"
    healthy.mkdir()
    for r in range(4):
        rank_dump(healthy, r, 12)
    proc = subprocess.run(
        [sys.executable, script, "--dir", str(healthy), "--strict"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
