"""Quantized ring reduce-scatter / all-gather correctness.

The per-hop fused op is pinned bitwise between its jnp oracle and the Pallas
kernel (interpret mode), and the full ring is pinned bitwise against an
explicit per-package schedule simulation — the ring's arrival order is part
of the wire contract, so a single differing byte at any hop is a bug, not
noise.  Error feedback is checked as a convergence property: the residual
re-entering the input drives the time-averaged output to the true mean far
below the one-shot quantization error.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bagua_tpu.communication import ALL_AXES
from bagua_tpu.kernels.minmax_uint8 import (
    compress_minmax_uint8,
    decompress_minmax_uint8,
)
from bagua_tpu.kernels.quantized_ring import (
    compress_minmax_uint4,
    decompress_minmax_uint4,
    get_ring_hop,
    hop_dequant_add_requant,
    hop_dequant_add_requant_pallas,
    quantized_allgather,
    quantized_ring_allreduce,
    quantized_ring_reduce_scatter,
    resolve_block,
    ring_wire_bytes,
)


def _compressors(bits):
    if bits == 8:
        return compress_minmax_uint8, decompress_minmax_uint8
    return compress_minmax_uint4, decompress_minmax_uint4


# ---------------------------------------------------------------------------
# int4 blockwise codec
# ---------------------------------------------------------------------------


def test_uint4_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    blocks = rng.randn(4, 512).astype(np.float32) * 3.0
    packed, mm = compress_minmax_uint4(jnp.asarray(blocks))
    assert packed.shape == (4, 256) and packed.dtype == jnp.uint8
    x = np.asarray(decompress_minmax_uint4(packed, mm))
    level = (blocks.max(1) - blocks.min(1)) / 15.0
    assert np.abs(x - blocks).max() <= level.max() * 1.01


def test_uint4_packing_layout():
    """Element j rides the low nibble of byte j, element j + B/2 the high
    nibble — the wire format is part of the contract."""
    blocks = jnp.asarray(np.linspace(0.0, 15.0, 8, dtype=np.float32)[None])
    packed, mm = compress_minmax_uint4(blocks)
    p = np.asarray(packed)[0]
    lo, hi = p & 0xF, p >> 4
    q = np.concatenate([lo, hi]).astype(np.float32)
    # linspace over [0, 15] quantizes to its own rounded levels
    np.testing.assert_array_equal(q, np.rint(np.linspace(0, 15, 8)))


def test_uint4_constant_block_guard():
    """Constant blocks: the EPS-regularized scale is huge, so at extreme
    magnitude ``mx * scale`` would overflow — the bounded-denominator scale
    (``minmax_uint8._safe_scale``) keeps it finite with no branch: q
    degenerates to 0 (the 15-level offset is absorbed by the huge bounds)
    and the round-trip reconstructs the constant to f32 rounding.  In-range
    constants take the bitwise-unchanged normal path and round-trip to float
    tolerance with no NaN."""
    for v in (2.7e33, -8e31):  # overflow regime: near-exact reconstruction
        blocks = np.full((2, 64), v, np.float32)
        packed, mm = compress_minmax_uint4(jnp.asarray(blocks))
        assert (np.asarray(packed) == 0).all()
        x = np.asarray(decompress_minmax_uint4(packed, mm))
        assert np.isfinite(x).all()
        np.testing.assert_allclose(x, blocks, rtol=1e-6)
    for v in (0.0, -3.0):  # in-range constants: normal path, tiny error
        blocks = np.full((2, 64), v, np.float32)
        packed, mm = compress_minmax_uint4(jnp.asarray(blocks))
        x = np.asarray(decompress_minmax_uint4(packed, mm))
        assert not np.isnan(x).any()
        np.testing.assert_allclose(x, blocks, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# Per-hop fused op: jnp oracle vs Pallas (interpret)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "bits,block", [(8, 4096), (4, 8192)], ids=["int8", "int4"]
)
def test_hop_pallas_matches_oracle(bits, block):
    rng = np.random.RandomState(1)
    comp, _ = _compressors(bits)
    incoming = rng.randn(4, block).astype(np.float32)
    local = rng.randn(4, block).astype(np.float32) * 2.0
    q, mm = comp(jnp.asarray(incoming))
    q_j, mm_j, err_j = hop_dequant_add_requant(q, mm, jnp.asarray(local), bits=bits)
    q_p, mm_p, err_p = hop_dequant_add_requant_pallas(
        q, mm, jnp.asarray(local), bits=bits, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(q_p), np.asarray(q_j))
    np.testing.assert_allclose(np.asarray(mm_p), np.asarray(mm_j), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(err_p), np.asarray(err_j))


@pytest.mark.parametrize("bits", [8, 4], ids=["int8", "int4"])
def test_hop_pallas_fallback_unaligned(bits):
    """Off-tile block sizes route to the jnp oracle bitwise-transparently."""
    rng = np.random.RandomState(2)
    comp, _ = _compressors(bits)
    incoming = rng.randn(3, 100).astype(np.float32)
    local = rng.randn(3, 100).astype(np.float32)
    q, mm = comp(jnp.asarray(incoming))
    out_j = hop_dequant_add_requant(q, mm, jnp.asarray(local), bits=bits)
    out_p = hop_dequant_add_requant_pallas(
        q, mm, jnp.asarray(local), bits=bits, interpret=True
    )
    for a, b in zip(out_p, out_j):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_hop_constant_degenerate_no_nan():
    const = jnp.full((2, 4096), 5.5e33, jnp.float32)
    q, mm = compress_minmax_uint8(const)
    q2, mm2, err = hop_dequant_add_requant(q, mm, const, bits=8)
    out = np.asarray(decompress_minmax_uint8(q2, mm2))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out, np.full((2, 4096), 1.1e34, np.float32),
                               rtol=1e-6)
    # Constant blocks re-quantize near-losslessly: the residual is bounded
    # by f32 rounding of the sum, not by a quantization step.
    assert np.abs(np.asarray(err)).max() <= 1e-6 * 1.1e34


# ---------------------------------------------------------------------------
# Ring schedule simulation oracle
# ---------------------------------------------------------------------------


def sim_quantized_ring_rs(x_stack: np.ndarray, bits: int, block: int,
                          average: bool = True):
    """Explicit per-package simulation of the ring schedule: package for
    destination d starts at rank d+1 and moves forward one rank per step.
    Uses the same jnp block codecs, so agreement with the shard_map run must
    be bitwise."""
    comp, deco = _compressors(bits)
    n, L = x_stack.shape
    S = L // n
    nb = -(-S // block)
    Sp = nb * block
    xb = np.zeros((n, n, Sp), np.float32)
    xb[:, :, :S] = x_stack.reshape(n, n, S)
    shards = np.zeros((n, S), np.float32)
    errs = np.zeros((n, n, Sp), np.float32)
    for d in range(n):
        r0 = (d + 1) % n
        local0 = jnp.asarray(xb[r0, d].reshape(nb, block))
        q, mm = comp(local0)
        errs[r0, d] = np.asarray(local0 - deco(q, mm)).reshape(-1)
        for t in range(1, n):
            r = (r0 + t) % n
            local = jnp.asarray(xb[r, d].reshape(nb, block))
            if t < n - 1:
                q, mm, e = hop_dequant_add_requant(q, mm, local, bits=bits)
                errs[r, d] += np.asarray(e).reshape(-1)
            else:
                assert r == d
                red = np.asarray(deco(q, mm) + local).reshape(-1)
                shards[d] = (red / n if average else red)[:S]
    return shards, errs[:, :, :S].reshape(n, n * S)


@pytest.mark.parametrize("bits", [8, 4], ids=["int8", "int4"])
@pytest.mark.parametrize("average", [True, False], ids=["avg", "sum"])
def test_ring_rs_matches_schedule_sim(group, bits, average):
    rng = np.random.RandomState(3)
    n = group.size
    block = 64
    L = n * 96  # unaligned shard (96 % 64 != 0): pads to 2 blocks
    x = rng.randn(n, L).astype(np.float32)

    fn = jax.jit(
        group.shard_map(
            lambda v: tuple(
                o[None]
                for o in quantized_ring_reduce_scatter(
                    v[0], ALL_AXES, bits=bits, average=average, block=block
                )
            ),
            in_specs=P(ALL_AXES),
            out_specs=(P(ALL_AXES), P(ALL_AXES)),
        )
    )
    shards, errs = fn(jnp.asarray(x))
    sim_shards, sim_errs = sim_quantized_ring_rs(x, bits, block, average)
    np.testing.assert_array_equal(np.asarray(shards), sim_shards)
    np.testing.assert_array_equal(np.asarray(errs), sim_errs)


def test_ring_rs_pallas_hop_bitwise(group):
    """The ring with the Pallas hop (interpret) is bitwise-identical to the
    jnp-hop ring at an aligned block size."""
    rng = np.random.RandomState(4)
    n = group.size
    block = 4096
    L = n * block
    x = jnp.asarray(rng.randn(n, L).astype(np.float32))

    def run(hop):
        fn = jax.jit(
            group.shard_map(
                lambda v: quantized_ring_reduce_scatter(
                    v[0], ALL_AXES, bits=8, block=block, hop=hop
                )[0][None],
                in_specs=P(ALL_AXES),
                out_specs=P(ALL_AXES),
            )
        )
        return np.asarray(fn(x))

    import functools
    jnp_hop = functools.partial(hop_dequant_add_requant, bits=8)
    pl_hop = functools.partial(
        hop_dequant_add_requant_pallas, bits=8, interpret=True
    )
    np.testing.assert_array_equal(run(jnp_hop), run(pl_hop))


@pytest.mark.parametrize("bits", [8, 4], ids=["int8", "int4"])
def test_allreduce_identical_across_ranks_and_error_bound(group, bits):
    rng = np.random.RandomState(5)
    n = group.size
    L = n * 128
    x = rng.randn(n, L).astype(np.float32)

    fn = jax.jit(
        group.shard_map(
            lambda v: tuple(
                o[None]
                for o in quantized_ring_allreduce(
                    v[0], ALL_AXES, bits=bits, average=True, block=64
                )
            ),
            in_specs=P(ALL_AXES),
            out_specs=(P(ALL_AXES), P(ALL_AXES)),
        )
    )
    out, err = np.asarray(fn(jnp.asarray(x))[0]), np.asarray(fn(jnp.asarray(x))[1])
    # identical on every rank: the wire image is the single source of truth
    for r in range(1, n):
        np.testing.assert_array_equal(out[0], out[r])
    # and close to the true mean: per-hop quantization error compounds over
    # the ring, bounded by ~hops * level
    mean = x.mean(0)
    levels = 255.0 if bits == 8 else 15.0
    spread = np.abs(x).max() * n  # generous bound on any partial's range
    tol = (2 * n) * spread / levels
    assert np.abs(out[0] - mean).max() <= tol


def test_error_feedback_drives_mean_to_truth(group):
    """The EF contract: residuals re-entering the next step's input make the
    *time-averaged* output converge to the true mean — the bias of one-shot
    int4 quantization washes out instead of accumulating."""
    rng = np.random.RandomState(6)
    n = group.size
    L = n * 64
    g = rng.randn(n, L).astype(np.float32)  # fixed per-rank gradients

    fn = jax.jit(
        group.shard_map(
            lambda v: tuple(
                o[None]
                for o in quantized_ring_allreduce(
                    v[0], ALL_AXES, bits=4, average=True, block=64
                )
            ),
            in_specs=P(ALL_AXES),
            out_specs=(P(ALL_AXES), P(ALL_AXES)),
        )
    )
    resid = np.zeros_like(g)
    outs = []
    for _ in range(30):
        out, err = fn(jnp.asarray(g + resid))
        resid = np.asarray(err)
        outs.append(np.asarray(out)[0])
    mean = g.mean(0)
    one_shot = np.abs(outs[0] - mean).max()
    ef_avg = np.abs(np.mean(outs, axis=0) - mean).max()
    assert ef_avg < one_shot * 0.2
    assert ef_avg < 0.02


def test_quantized_allgather_matches_codec(group):
    rng = np.random.RandomState(7)
    n = group.size
    S = 96
    shards = rng.randn(n, S).astype(np.float32)

    fn = jax.jit(
        group.shard_map(
            lambda v: tuple(
                o[None]
                for o in quantized_allgather(v[0], ALL_AXES, bits=8, block=64)
            ),
            in_specs=P(ALL_AXES),
            out_specs=(P(ALL_AXES), P(ALL_AXES)),
        )
    )
    flat, err = fn(jnp.asarray(shards))
    flat, err = np.asarray(flat), np.asarray(err)
    # oracle: every shard independently compressed with the same block codec
    expect = []
    for r in range(n):
        padded = np.zeros((2, 64), np.float32)
        padded.reshape(-1)[:S] = shards[r]
        q, mm = compress_minmax_uint8(jnp.asarray(padded))
        dec = np.asarray(decompress_minmax_uint8(q, mm)).reshape(-1)[:S]
        expect.append(dec)
        np.testing.assert_array_equal(err[r], shards[r] - dec)
    expect = np.concatenate(expect)
    for r in range(n):
        np.testing.assert_array_equal(flat[r], expect)


def test_ring_wire_bytes_accounting():
    # 8 ranks, 64k elements, block 4096: shard 8192 elems = 2 blocks
    n, numel, B = 8, 8 * 8192, 4096
    per_hop8 = 8192 + 2 * 8
    assert ring_wire_bytes(numel, n, 8, block=B) == 2 * (n - 1) * per_hop8
    per_hop4 = 4096 + 2 * 8
    assert ring_wire_bytes(numel, n, 4, block=B) == 2 * (n - 1) * per_hop4
    assert ring_wire_bytes(numel, 1, 8) == 0
    # compressed hop bytes sit well under the 0.3x f32 gate
    f32_hop_bytes = 2 * (n - 1) * 8192 * 4
    assert ring_wire_bytes(numel, n, 8, block=B) <= 0.3 * f32_hop_bytes


def test_resolve_block_env(monkeypatch):
    assert resolve_block() == 4096
    monkeypatch.setenv("BAGUA_QR_BLOCK", "512")
    assert resolve_block() == 512
    assert resolve_block(128) == 128
    with pytest.raises(ValueError):
        resolve_block(7)


def test_get_ring_hop_dispatch(monkeypatch):
    import functools as ft

    h = get_ring_hop(8)
    assert isinstance(h, ft.partial) and h.func is hop_dequant_add_requant
    h = get_ring_hop(4, use_pallas=True)
    assert h.func is hop_dequant_add_requant_pallas
    monkeypatch.setenv("BAGUA_PALLAS_QUANTIZED_RING", "1")
    h = get_ring_hop(8)
    assert h.func is hop_dequant_add_requant_pallas
