"""Real-data example paths (reference: ``examples/`` are CI smoke targets,
``.buildkite/pipeline.yml``).  Each example's real loader runs end-to-end on
a generated on-disk fixture: IDX files (mnist), an ImageFolder tree
(imagenet), official-schema SQuAD JSON (squad)."""

import gzip
import json
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # real-data example runs + driver dryruns (subprocess, minutes)

from helpers import REPO_ROOT

EXAMPLES = os.path.join(REPO_ROOT, "examples")


def _run_example(script, args, timeout=300):
    """Run an example pinned to a 1-device CPU backend (examples have no
    platform override of their own, so drop the axon sitecustomize)."""
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT
    r = subprocess.run(
        [sys.executable, script, *args],
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    return r.stdout


def test_flax_strategy_example():
    """The three-call Flax adoption path trains and exits through to_flax."""
    out = _run_example(
        os.path.join(EXAMPLES, "flax_strategy", "main.py"),
        ["--algorithm", "gradient_allreduce", "--steps", "12", "--batch", "32"],
    )
    assert "final step 12" in out
    losses = [float(l.split("loss")[1]) for l in out.splitlines() if "loss" in l]
    assert losses[-1] < losses[0], out  # it actually learned


def test_mnist_real_idx(tmp_path):
    rng = np.random.RandomState(0)
    imgs = (rng.rand(256, 28, 28) * 255).astype(np.uint8)
    labels = rng.randint(0, 10, 256).astype(np.uint8)
    with gzip.open(tmp_path / "train-images-idx3-ubyte.gz", "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 3) + struct.pack(">III", 256, 28, 28)
                + imgs.tobytes())
    with open(tmp_path / "train-labels-idx1-ubyte", "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 1) + struct.pack(">I", 256)
                + labels.tobytes())
    out = _run_example(
        os.path.join(EXAMPLES, "mnist", "main.py"),
        ["--data-dir", str(tmp_path), "--epochs", "1", "--batch-size", "64"],
    )
    assert "256 samples (real)" in out


def test_imagenet_real_folder(tmp_path):
    PIL = pytest.importorskip("PIL.Image")
    rng = np.random.RandomState(0)
    for c in range(2):
        d = tmp_path / f"class_{c}"
        d.mkdir()
        for i in range(4):
            arr = (rng.rand(40 + 8 * c, 48, 3) * 255).astype(np.uint8)
            PIL.fromarray(arr).save(d / f"img_{i}.jpeg")
        (d / "README.txt").write_text("not an image")  # must be skipped
    out = _run_example(
        os.path.join(EXAMPLES, "imagenet", "main.py"),
        ["--data-dir", str(tmp_path), "--arch", "vgg16", "--image-size", "32",
         "--batch-size", "2", "--steps", "2"],
    )
    assert "8 images, 2 classes" in out


def test_squad_real_json(tmp_path):
    pytest.importorskip("tokenizers")
    ctx = "The quick brown fox jumps over the lazy dog near the river bank."
    data = {"data": [{"title": "t", "paragraphs": [{
        "context": ctx,
        "qas": [
            {"id": str(k), "question": f"What does the fox jump over ({k})?",
             "answers": [{"text": "the lazy dog", "answer_start": ctx.index("the lazy dog")}]}
            for k in range(24)
        ],
    }]}]}
    path = tmp_path / "train.json"
    path.write_text(json.dumps(data))
    out = _run_example(
        os.path.join(EXAMPLES, "squad", "main.py"),
        ["--data", str(path), "--batch-size", "2", "--steps", "2", "--seq", "64"],
    )
    assert "24 SQuAD features" in out


@pytest.mark.parametrize("n_devices", [16])
def test_dryrun_multichip_wider_than_test_mesh(n_devices):
    """The driver calls dryrun_multichip with arbitrary device counts; guard
    the path at a width larger than the suite's 8-device mesh (fresh
    subprocess: the simulated device count is fixed at jax init)."""
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO_ROOT
    r = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__; __graft_entry__.dryrun_multichip({n_devices})"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO_ROOT,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"


def test_llama_pretrain_real_text(tmp_path):
    """Char-LM on a real UTF-8 corpus fixture through the dp x tp x sp
    example (8-device sim inside the subprocess)."""
    text = ("To be, or not to be, that is the question:\n" * 80)
    corpus = tmp_path / "corpus.txt"
    corpus.write_text(text, encoding="utf-8")
    env_extra = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO_ROOT, **env_extra)
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, "llama_pretrain", "main.py"),
         "--data", str(corpus), "--dp", "2", "--tp", "2", "--sp", "2",
         "--steps", "8", "--seq", "32", "--batch", "8"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    lines = [l for l in r.stdout.splitlines() if l.startswith("final:")]
    assert lines, r.stdout
    # loss must improve on real text over a few steps
    parts = lines[0].split("loss")[1].split("->")
    assert float(parts[1]) < float(parts[0]), lines[0]
