"""Pin ci/tpu_session.sh's guard logic: freshness skips, budget admission,
marker semantics, and artifact-write hygiene — with the probe functions
stubbed so no chip is involved.

The guard is what decides how a scarce chip session spends its budget;
regressions here silently burn sessions (r4 lost ~45 minutes re-running
landed artifacts).
"""

import os
import subprocess
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Extract guard/run/fresh/remaining from the real script by sourcing it with
# the step section stripped: everything between the function definitions and
# the first `guard` invocation is driven by the test harness instead.
HARNESS = textwrap.dedent("""
    set -u
    cd "$WORK"
    SESSION_BUDGET_S=${SESSION_BUDGET_S:-300}
    FRESH_S=${FRESH_S:-3600}
    T0=$(date +%s)
    # functions lifted verbatim from ci/tpu_session.sh by the test
    {FUNCS}
    LAST_RC=0
    TUNNEL_DOWN=0
    probe_fast() { true; }
    probe_full() { true; }
    {BODY}
""")


def _funcs_from_script():
    """The function definitions (remaining/run/fresh/guard) from the real
    script, so the test exercises the shipped code, not a copy."""
    src = open(os.path.join(REPO, "ci", "tpu_session.sh")).read()
    start = src.index("remaining()")
    end = src.index("# Step order")
    funcs = src[start:end]
    # neutralize the real probes (the harness stubs them after sourcing)
    return funcs


def _run(body, env=None, work=None, tmp_path=None):
    import tempfile

    work = work or (str(tmp_path) if tmp_path is not None
                    else tempfile.mkdtemp(prefix="tpu_session_test_"))
    script = HARNESS.replace("{FUNCS}", _funcs_from_script()).replace("{BODY}", body)
    proc = subprocess.run(
        ["bash", "-c", script],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, **(env or {}), "WORK": work},
    )
    return proc, work


def test_redirect_marker_fresh_and_budget_paths(tmp_path):
    body = """
    guard step1 60 out.json echo '{"metric":"x","value":1}'
    guard step1b 60 out.json echo '{"metric":"x","value":2}'     # fresh skip
    guard step2 60 @M.ok true                                    # marker
    guard step2b 60 @M.ok true                                   # fresh skip
    guard step3 9999 - echo never                                # budget skip
    cat out.json
    """
    proc, work = _run(body, tmp_path=tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert '"value":1' in proc.stdout                      # first write won
    assert proc.stdout.count("SKIPPED") == 3, proc.stdout  # 1b, 2b, 3
    assert os.path.exists(os.path.join(work, "M.ok"))


def test_error_lines_never_clobber_artifacts(tmp_path):
    body = """
    echo '{"metric":"x","value":42}' > out.json
    touch -d '8 hours ago' out.json                      # stale -> re-run
    guard step 60 out.json sh -c 'echo "{\\"error\\":\\"tunnel died\\",\\"value\\":0}"; exit 3'
    cat out.json
    """
    proc, _ = _run(body, tmp_path=tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert '"value":42' in proc.stdout  # healthy artifact preserved


def test_fail_verdict_marks_fresh_but_crash_does_not(tmp_path):
    body = """
    guard gate 60 @G.ok sh -c 'echo "vgg16/async throughput=1 floor(190)=FAIL"; exit 1'
    [ -f G.ok ] && echo "verdict-marked"
    rm -f G.ok
    guard gate2 60 @G.ok sh -c 'echo "Traceback (most recent call last): boom"; exit 1'
    [ -f G.ok ] || echo "crash-not-marked"
    """
    proc, _ = _run(body, tmp_path=tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "verdict-marked" in proc.stdout
    assert "crash-not-marked" in proc.stdout


def test_tunnel_down_cached_after_double_probe_failure(tmp_path):
    body = """
    probe_fast() { false; }
    probe_full() { false; }
    LAST_RC=1
    guard a 60 - echo ran-a
    guard b 60 - echo ran-b
    """
    proc, _ = _run(body, tmp_path=tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "ran-a" not in proc.stdout and "ran-b" not in proc.stdout
    assert proc.stdout.count("tunnel down") == 2
