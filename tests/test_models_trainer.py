"""Model zoo smoke/correctness + Trainer + functional collectives +
alltoall_v."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import bagua_tpu
from bagua_tpu import communication as C


@pytest.mark.slow
def test_resnet50_forward_and_train_step(group):
    from bagua_tpu.algorithms import GradientAllReduceAlgorithm
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.models.resnet import init_resnet50, resnet_loss_fn

    model, variables = init_resnet50(jax.random.PRNGKey(0), image_size=32, num_classes=10)
    full = {"params": variables["params"], "batch_stats": variables["batch_stats"]}
    ddp = DistributedDataParallel(
        resnet_loss_fn(model), optax.sgd(0.01), GradientAllReduceAlgorithm(),
        process_group=group,
        dp_filter=lambda name: "batch_stats" not in name,
    )
    state = ddp.init(full)
    rng = np.random.RandomState(0)
    batch = (
        jnp.asarray(rng.rand(16, 32, 32, 3).astype(np.float32)),
        jnp.asarray(rng.randint(0, 10, 16).astype(np.int32)),
    )
    state, losses = ddp.train_step(state, batch)
    assert np.isfinite(np.asarray(losses)).all()


@pytest.mark.slow
def test_gpt_causal_sp_matches_local():
    """GPT with sp=4 ring attention == the same model run locally on the full
    sequence (identical params), including tied-LM-head logits."""
    from bagua_tpu.models.gpt import GPTConfig, GPTModel

    sp, t_local = 4, 4
    vocab, hidden, heads, layers = 32, 16, 4, 2
    ids = np.random.RandomState(0).randint(0, vocab, (2, sp * t_local)).astype(np.int32)

    cfg_local = GPTConfig(
        vocab_size=vocab, hidden_size=hidden, num_heads=heads, num_layers=layers,
        max_position_embeddings=sp * t_local,
    )
    model_local = GPTModel(cfg_local)
    params = model_local.init(jax.random.PRNGKey(0), jnp.asarray(ids))["params"]
    ref = np.asarray(model_local.apply({"params": params}, jnp.asarray(ids)))

    cfg_sp = GPTConfig(
        vocab_size=vocab, hidden_size=hidden, num_heads=heads, num_layers=layers,
        max_position_embeddings=sp * t_local, sp_axis="sp",
    )
    model_sp = GPTModel(cfg_sp)
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    fn = jax.jit(
        jax.shard_map(
            lambda ii: model_sp.apply({"params": params}, ii),
            mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    got = np.asarray(fn(jnp.asarray(ids)))
    np.testing.assert_allclose(got, ref, rtol=5e-3, atol=5e-3)


@pytest.mark.slow
def test_trainer_fit_with_checkpointing(group, tmp_path):
    from bagua_tpu.algorithms import Algorithm
    from bagua_tpu.models.mlp import init_mlp, mse_loss
    from bagua_tpu.trainer import Trainer

    def make():
        return Trainer(
            mse_loss, optax.adam(1e-3), Algorithm.init("gradient_allreduce"),
            process_group=group, ckpt_dir=str(tmp_path), ckpt_interval=5,
            watchdog_timeout_s=120.0,
        )

    rng = np.random.RandomState(0)

    def batches(n):
        for _ in range(n):
            yield (
                jnp.asarray(rng.randn(16, 8), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            )

    with make() as t1:
        params = init_mlp(jax.random.PRNGKey(0), [8, 16, 4])
        state = t1.init_state(params)
        state = t1.fit(state, batches(10), log_every=0)
        assert int(state.step[0]) == 10

    # new trainer resumes from the step-10 checkpoint
    with make() as t2:
        state2 = t2.init_state(params)
        assert int(state2.step[0]) == 10


def test_functional_allreduce_differentiable(group):
    from bagua_tpu.functional import all_reduce

    x = jnp.asarray(np.random.RandomState(0).randn(8, 4).astype(np.float32))

    def f(x):
        return jnp.sum(all_reduce(x, op=bagua_tpu.ReduceOp.AVG, group=group) ** 2)

    g = jax.grad(f)(x)
    # d/dx_r sum_r' (mean_x)^2 = 2*mean * (1/n) summed over all outputs -> 2*mean
    mean = np.asarray(x).mean(0)
    expect = np.tile((2 * mean)[None], (8, 1))
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-4, atol=1e-6)


def test_alltoall_v(group):
    n = group.size
    cap = 4
    rng = np.random.RandomState(1)
    # every rank sends j+1 rows to rank j (same pattern per rank for clarity)
    send_counts = np.minimum(np.arange(n) + 1, cap).astype(np.int32)
    data = rng.randn(n, n, cap, 2).astype(np.float32)  # per-rank (n, cap, 2)

    def local(x, counts):
        recv, rc = C.alltoall_v_inplace(x[0], counts[0])
        return recv[None], rc[None]

    fn = jax.jit(
        group.shard_map(
            local,
            in_specs=(P(C.ALL_AXES), P(C.ALL_AXES)),
            out_specs=(P(C.ALL_AXES), P(C.ALL_AXES)),
        )
    )
    counts = jnp.asarray(np.tile(send_counts[None], (n, 1)))
    recv, rc = fn(jnp.asarray(data), counts)
    recv, rc = np.asarray(recv), np.asarray(rc)
    for r in range(n):
        # rank r receives from rank s the chunk s destined to r
        for s in range(n):
            np.testing.assert_allclose(recv[r, s], data[s, r])
        # counts received: what each rank s sends to r = send_counts[r]
        np.testing.assert_array_equal(rc[r], np.full(n, send_counts[r]))


@pytest.mark.slow
def test_pinned_weight_norm_regression(group):
    """Exact weight-norm pins per algorithm (seed 13, 8 steps) — the analog
    of the reference's Lightning-strategy regression values
    (``tests/pytorch_lightning/test_bagua_strategy.py:46-60``,
    BASELINE.md rows).  Any numerical drift in an algorithm's math, the
    bucketing layout, or the engine's step composition trips this."""
    from bagua_tpu.algorithms import build_algorithm
    from bagua_tpu.ddp import DistributedDataParallel
    from bagua_tpu.models.mlp import init_mlp, mse_loss

    PINS = {
        "gradient_allreduce": 6.278911590576172,
        "bytegrad": 6.278995990753174,
        "decentralized": 6.269926071166992,
        "low_precision_decentralized": 6.272532939910889,
        "qadam": 6.088754653930664,
    }

    for name, expected in PINS.items():
        algo = build_algorithm(name, lr=1e-2, qadam_warmup_steps=3)
        opt = None if name == "qadam" else optax.sgd(0.05)
        ddp = DistributedDataParallel(mse_loss, opt, algo, process_group=group)
        params = init_mlp(jax.random.PRNGKey(13), [8, 16, 4])
        state = ddp.init(params)
        rng = np.random.RandomState(13)
        for _ in range(8):
            b = (
                jnp.asarray(rng.randn(16, 8), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            )
            state, _ = ddp.train_step(state, b)
        one_copy = ddp.params_unstacked(state)
        norm = float(
            jnp.sqrt(sum(jnp.sum(l ** 2) for l in jax.tree.leaves(one_copy)))
        )
        # tight tolerance (not bitwise): survives last-ulp reassociation from
        # jaxlib/CPU-kernel changes while catching real numerical drift
        np.testing.assert_allclose(
            norm, expected, rtol=1e-6, err_msg=f"{name} drifted from pin"
        )


def test_trainer_profile_window(group, tmp_path):
    """Trainer(profile_dir=...) captures an xprof trace of the configured
    step window and closes it cleanly even when fit() ends mid-window."""
    import glob

    from bagua_tpu.algorithms import Algorithm
    from bagua_tpu.models.mlp import init_mlp, mse_loss
    from bagua_tpu.trainer import Trainer

    rng = np.random.RandomState(0)

    def batches(n):
        for _ in range(n):
            yield (
                jnp.asarray(rng.randn(16, 8), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            )

    with Trainer(
        mse_loss, optax.sgd(0.05), Algorithm.init("gradient_allreduce"),
        process_group=group, watchdog_timeout_s=0,
        profile_dir=str(tmp_path / "full"), profile_steps=(2, 4),
    ) as t:
        state = t.init_state(init_mlp(jax.random.PRNGKey(0), [8, 16, 4]))
        t.fit(state, batches(6), log_every=0)
    assert glob.glob(str(tmp_path / "full") + "/**/*.xplane.pb", recursive=True)

    # window extends past the last step: close() must stop the trace
    with Trainer(
        mse_loss, optax.sgd(0.05), Algorithm.init("gradient_allreduce"),
        process_group=group, watchdog_timeout_s=0,
        profile_dir=str(tmp_path / "cut"), profile_steps=(1, 99),
    ) as t:
        state = t.init_state(init_mlp(jax.random.PRNGKey(1), [8, 16, 4]))
        t.fit(state, batches(3), log_every=0)
    assert glob.glob(str(tmp_path / "cut") + "/**/*.xplane.pb", recursive=True)


def test_trainer_profile_once_across_epochs(group, tmp_path):
    """A mid-window epoch end must not re-trigger capture on the next fit()
    (jax.profiler raises on double-start)."""
    from bagua_tpu.algorithms import Algorithm
    from bagua_tpu.models.mlp import init_mlp, mse_loss
    from bagua_tpu.trainer import Trainer

    rng = np.random.RandomState(0)

    def batches(n):
        for _ in range(n):
            yield (
                jnp.asarray(rng.randn(16, 8), np.float32),
                jnp.asarray(rng.randn(16, 4), np.float32),
            )

    with Trainer(
        mse_loss, optax.sgd(0.05), Algorithm.init("gradient_allreduce"),
        process_group=group, watchdog_timeout_s=0,
        profile_dir=str(tmp_path), profile_steps=(1, 99),
    ) as t:
        state = t.init_state(init_mlp(jax.random.PRNGKey(0), [8, 16, 4]))
        state = t.fit(state, batches(3), log_every=0)   # epoch 1: window opens
        # window still open at epoch boundary; epoch 2 hits i==1 again
        state = t.fit(state, batches(3), log_every=0)
        assert int(state.step[0]) == 6


@pytest.mark.slow
def test_gpt_causal_sp_zigzag_matches_local():
    """GPT with the zigzag SP layout == the local model on the full sequence:
    feed zigzag-permuted ids, invert the output permutation."""
    from bagua_tpu.models.gpt import GPTConfig, GPTModel
    from bagua_tpu.parallel.ring_attention import zigzag_inverse, zigzag_order

    sp, t_local = 4, 4
    vocab, hidden, heads, layers = 32, 16, 4, 2
    Tg = sp * t_local
    ids = np.random.RandomState(1).randint(0, vocab, (2, Tg)).astype(np.int32)

    cfg_local = GPTConfig(
        vocab_size=vocab, hidden_size=hidden, num_heads=heads, num_layers=layers,
        max_position_embeddings=Tg,
    )
    model_local = GPTModel(cfg_local)
    params = model_local.init(jax.random.PRNGKey(0), jnp.asarray(ids))["params"]
    ref = np.asarray(model_local.apply({"params": params}, jnp.asarray(ids)))

    cfg_sp = GPTConfig(
        vocab_size=vocab, hidden_size=hidden, num_heads=heads, num_layers=layers,
        max_position_embeddings=Tg, sp_axis="sp", sp_layout="zigzag",
    )
    model_sp = GPTModel(cfg_sp)
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    fn = jax.jit(
        jax.shard_map(
            lambda ii: model_sp.apply({"params": params}, ii),
            mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    order = zigzag_order(Tg, sp)
    inv = zigzag_inverse(Tg, sp)
    got = np.asarray(fn(jnp.asarray(ids[:, order])))[:, inv]
    np.testing.assert_allclose(got, ref, rtol=5e-3, atol=5e-3)


@pytest.mark.slow
def test_gpt_zigzag_lm_loss_masks_seam():
    """Under the zigzag SP layout the mid-block seam pair is excluded from
    the LM loss; per-rank losses must match the oracle computed from the
    local model's logits with the same positions dropped."""
    from bagua_tpu.models.gpt import GPTConfig, GPTModel, lm_loss_fn
    from bagua_tpu.parallel.ring_attention import zigzag_order

    sp, t_local = 4, 4
    vocab, Tg = 32, sp * t_local
    ids = np.random.RandomState(2).randint(0, vocab, (2, Tg)).astype(np.int32)

    cfg_sp = GPTConfig(
        vocab_size=vocab, hidden_size=16, num_heads=4, num_layers=1,
        max_position_embeddings=Tg, sp_axis="sp", sp_layout="zigzag",
    )
    model_sp = GPTModel(cfg_sp)
    from dataclasses import replace as dc_replace

    cfg_local = dc_replace(cfg_sp, sp_axis=None, sp_layout="contiguous")
    model_local = GPTModel(cfg_local)
    params = model_local.init(jax.random.PRNGKey(0), jnp.asarray(ids))["params"]

    order = zigzag_order(Tg, sp)
    zids = ids[:, order]
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    loss_fn = lm_loss_fn(model_sp)
    fn = jax.jit(
        jax.shard_map(
            lambda ii: loss_fn(params, ii)[None],
            mesh=mesh, in_specs=P(None, "sp"), out_specs=P("sp"),
            check_vma=False,
        )
    )
    per_rank = np.asarray(fn(jnp.asarray(zids)))

    # oracle: local logits on the permuted ids per shard, seam pair dropped
    ref_logits = np.asarray(model_local.apply({"params": params}, jnp.asarray(ids)))
    ref_logits_z = ref_logits[:, order]
    for r in range(sp):
        lo, hi = r * t_local, (r + 1) * t_local
        lg, tg = ref_logits_z[:, lo:hi], zids[:, lo:hi]
        logp = jax.nn.log_softmax(jnp.asarray(lg[:, :-1]))
        nll = -np.asarray(jnp.take_along_axis(logp, jnp.asarray(tg[:, 1:, None]), axis=-1))[..., 0]
        keep = np.arange(t_local - 1) != (t_local // 2 - 1)
        expect = (nll * keep[None]).sum() / (nll.shape[0] * (t_local - 2))
        np.testing.assert_allclose(per_rank[r], expect, rtol=5e-3, atol=5e-3)
