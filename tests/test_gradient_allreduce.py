"""Gradient allreduce end-to-end correctness.

TPU analog of reference ``tests/torch_api/test_gradient_allreduce.py:37-131``:
train a small MLP for 10 steps with per-rank data, then assert (a) weights are
bitwise-identical across ranks and (b) they match a single-device oracle run
on the full global batch (allreduce-mean of per-rank grads == grad of the
global-batch mean loss).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import bagua_tpu
from bagua_tpu.algorithms import GlobalAlgorithmRegistry, Algorithm
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.ddp import DistributedDataParallel
from bagua_tpu.models.mlp import init_mlp, mse_loss

N_STEPS = 10
GLOBAL_BATCH = 32
DIM_IN, DIM_OUT = 12, 4


def make_data(seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(N_STEPS, GLOBAL_BATCH, DIM_IN).astype(np.float32)
    ys = rng.randn(N_STEPS, GLOBAL_BATCH, DIM_OUT).astype(np.float32)
    return xs, ys


def oracle_run(params, xs, ys, lr):
    """Single-device SGD on the full global batch — the pure-python oracle
    (reference test style: ``test_decentralized.py`` implements the algorithm
    in plain torch and compares)."""
    opt = optax.sgd(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        grads = jax.grad(mse_loss)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    for i in range(N_STEPS):
        params, opt_state = step(params, opt_state, (xs[i], ys[i]))
    return params


@pytest.mark.parametrize("hierarchical", [False, True])
def test_weights_equal_across_ranks_and_match_oracle(group, hierarchical):
    params = init_mlp(jax.random.PRNGKey(42), [DIM_IN, 16, DIM_OUT])
    xs, ys = make_data()
    lr = 0.1

    ddp = DistributedDataParallel(
        mse_loss,
        optax.sgd(lr),
        GradientAllReduceAlgorithm(hierarchical=hierarchical),
        process_group=group,
    )
    state = ddp.init(params)
    for i in range(N_STEPS):
        state, losses = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))

    stacked = jax.tree.map(np.asarray, state.params)
    for leaf in jax.tree.leaves(stacked):
        for r in range(1, group.size):
            np.testing.assert_array_equal(leaf[0], leaf[r])

    expect = oracle_run(params, xs, ys, lr)
    got = ddp.params_unstacked(state)
    for e, g in zip(jax.tree.leaves(expect), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(e), np.asarray(g), rtol=2e-4, atol=2e-5)


def test_losses_shape_and_step_counter(group):
    params = init_mlp(jax.random.PRNGKey(0), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(seed=1)
    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(0.05), GradientAllReduceAlgorithm(), process_group=group
    )
    state = ddp.init(params)
    state, losses = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
    assert losses.shape == (group.size,)
    assert int(state.step[0]) == 1 and int(state.step[-1]) == 1


def test_registry():
    algo_cls = GlobalAlgorithmRegistry.get("gradient_allreduce")
    assert isinstance(algo_cls(), Algorithm)
    assert isinstance(Algorithm.init("gradient_allreduce"), Algorithm)
    with pytest.raises(KeyError):
        GlobalAlgorithmRegistry.get("nope")


def test_sum_not_average(group):
    """average=False sums gradients across ranks (reference
    ``gradient_allreduce.py`` average flag)."""
    params = init_mlp(jax.random.PRNGKey(7), [DIM_IN, 8, DIM_OUT])
    xs, ys = make_data(seed=2)
    lr = 0.01

    ddp = DistributedDataParallel(
        mse_loss, optax.sgd(lr), GradientAllReduceAlgorithm(average=False), process_group=group
    )
    state = ddp.init(params)
    state, _ = ddp.train_step(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))

    # Oracle: one step where the gradient is the SUM over per-rank local grads.
    n = group.size

    def summed_grad(params, batch):
        x, y = batch
        per_rank_x = x.reshape(n, -1, DIM_IN)
        per_rank_y = y.reshape(n, -1, DIM_OUT)
        g = jax.tree.map(
            lambda *ts: sum(ts),
            *[
                jax.grad(mse_loss)(params, (per_rank_x[i], per_rank_y[i]))
                for i in range(n)
            ],
        )
        return g

    g = summed_grad(params, (jnp.asarray(xs[0]), jnp.asarray(ys[0])))
    expect = jax.tree.map(lambda p, gg: p - lr * gg, params, g)
    got = ddp.params_unstacked(state)
    for e, o in zip(jax.tree.leaves(expect), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(e), np.asarray(o), rtol=2e-4, atol=2e-5)


def test_tuple_fusion_bitwise_matches_flat(group):
    """fuse='tuple' (variadic psum per bucket, zero-copy) must be bitwise
    identical to fuse='flat' (materialized bucket buffers): psum is
    elementwise, so fusion layout cannot change numerics."""
    params = init_mlp(jax.random.PRNGKey(3), [DIM_IN, 16, 16, DIM_OUT])
    xs, ys = make_data(seed=3)
    states = {}
    for fuse in ("tuple", "flat"):
        ddp = DistributedDataParallel(
            mse_loss, optax.sgd(0.1), GradientAllReduceAlgorithm(fuse=fuse),
            process_group=group, bucket_size_bytes=1 << 9,  # force several buckets
        )
        state = ddp.init(params)
        assert ddp.plan.num_buckets > 1
        for i in range(3):
            state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
        states[fuse] = jax.tree.map(np.asarray, state.params)
    for a, b in zip(jax.tree.leaves(states["tuple"]), jax.tree.leaves(states["flat"])):
        np.testing.assert_array_equal(a, b)


def test_tuple_fusion_compiled_structure(group):
    """Compiled-HLO structure of the tuple path: every bucket lowers to ONE
    variadic all-reduce whose operands keep the original (unflattened,
    unconcatenated) gradient shapes, and its copy bytes never exceed the flat
    path's.  (On tiny models XLA:CPU can elide the flat path's concats too —
    equality is allowed; the >3x gap shows up at VGG scale, see
    PERF_AUDIT.md.)"""
    import re

    params = init_mlp(jax.random.PRNGKey(4), [64, 256, 256, 64])
    x = jnp.zeros((group.size * 4, 64), jnp.float32)
    y = jnp.zeros((group.size * 4, 64), jnp.float32)

    def compile_text(fuse):
        ddp = DistributedDataParallel(
            mse_loss, optax.sgd(0.1), GradientAllReduceAlgorithm(fuse=fuse),
            process_group=group, bucket_size_bytes=1 << 16,
        )
        state = ddp.init(params)
        fn = ddp._build_step(ddp.impl.step_variant(0))
        return fn.lower(state, (x, y)).compile().as_text()

    def copy_bytes(text):
        total = 0
        for line in text.splitlines():
            m = re.search(r"=\s+f32\[([0-9,]*)\][^ ]*\s+copy\(", line)
            if m:
                n = 1
                for d in m.group(1).split(","):
                    if d:
                        n *= int(d)
                total += 4 * n
        return total

    tup_text = compile_text("tuple")
    # The weight-matrix gradients ride the all-reduce in their natural 2D
    # shapes — proof there was no flatten/concat into a bucket buffer.
    ar_lines = [l for l in tup_text.splitlines() if re.search(r"\ball-reduce\(", l)]
    assert ar_lines, "no all-reduce in the compiled tuple-path step"
    assert any("f32[256,256]" in l or "f32[64,256]" in l for l in ar_lines), (
        "tuple-path all-reduce lost the original leaf shapes:\n" + "\n".join(ar_lines)
    )
    assert copy_bytes(tup_text) <= copy_bytes(compile_text("flat"))


def test_bf16_wire_dtype(group):
    """wire_dtype=bfloat16 halves the exchange bytes: the compiled all-reduce
    must run on bf16 operands, and training must track the f32-wire run
    within bf16 tolerance."""
    import re

    params = init_mlp(jax.random.PRNGKey(5), [DIM_IN, 16, DIM_OUT])
    xs, ys = make_data(seed=5)

    finals = {}
    for wire in (None, jnp.bfloat16):
        ddp = DistributedDataParallel(
            mse_loss, optax.sgd(0.05),
            GradientAllReduceAlgorithm(wire_dtype=wire), process_group=group,
        )
        state = ddp.init(params)
        if wire is not None:
            fn = ddp._build_step(ddp.impl.step_variant(0))
            text = fn.lower(state, (jnp.asarray(xs[0]), jnp.asarray(ys[0]))).compile().as_text()
            ar = [l for l in text.splitlines() if re.search(r"\ball-reduce\(", l)]
            # XLA:CPU legalizes bf16 all-reduce by promoting the reduction
            # region to f32 (operands arrive through convert fusions); on TPU
            # the collective stays bf16 on the wire.  Accept either form —
            # what matters is that the bf16 round-trip entered the program.
            assert ar and all(("bf16[" in l) or ("promoted" in l) for l in ar), (
                "bf16 wire dtype not reflected in the all-reduce:\n" + "\n".join(ar)
            )
        for i in range(N_STEPS):
            state, _ = ddp.train_step(state, (jnp.asarray(xs[i]), jnp.asarray(ys[i])))
        finals[wire] = ddp.params_unstacked(state)

    for a, b in zip(jax.tree.leaves(finals[None]), jax.tree.leaves(finals[jnp.bfloat16])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0.05, atol=0.02)
