"""TP layers, ring attention, and the parallel BERT model.

Oracles: single-device full computation on the gathered inputs/weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bagua_tpu.parallel.ring_attention import ring_attention, _block_attention_local
from bagua_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    ParallelMLP,
    RowParallelDense,
)

B, T, H, D = 2, 4, 4, 8  # batch, local seq, heads, head_dim
SP = 8


def sp_mesh(n=8, axis="sp"):
    devs = jax.devices()[:n]
    return Mesh(np.array(devs), (axis,))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    rng = np.random.RandomState(0)
    q = rng.randn(B, SP * T, H, D).astype(np.float32)
    k = rng.randn(B, SP * T, H, D).astype(np.float32)
    v = rng.randn(B, SP * T, H, D).astype(np.float32)

    full = np.asarray(
        _block_attention_local(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal)
    )

    mesh = sp_mesh()
    fn = jax.jit(
        jax.shard_map(
            lambda qq, kk, vv: ring_attention(qq, kk, vv, axis_name="sp", causal=causal),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    got = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(got, full, rtol=2e-4, atol=2e-5)


def test_ring_attention_kv_mask():
    """Padding mask rotates with the K/V blocks and matches the full oracle."""
    rng = np.random.RandomState(5)
    q = rng.randn(B, SP * T, H, D).astype(np.float32)
    k = rng.randn(B, SP * T, H, D).astype(np.float32)
    v = rng.randn(B, SP * T, H, D).astype(np.float32)
    mask = rng.rand(B, SP * T) > 0.3  # ~70% attendable

    full = np.asarray(
        _block_attention_local(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), kv_mask=jnp.asarray(mask)
        )
    )
    mesh = sp_mesh()
    fn = jax.jit(
        jax.shard_map(
            lambda qq, kk, vv, mm: ring_attention(qq, kk, vv, axis_name="sp", kv_mask=mm),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    got = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(mask)))
    np.testing.assert_allclose(got, full, rtol=2e-4, atol=2e-5)


def test_ring_attention_single_rank_fallback():
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    out = ring_attention(q, q, q, axis_name="sp")  # no bound axis -> local
    ref = _block_attention_local(q, q, q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_column_row_parallel_matches_dense():
    """Column->gelu->Row over a 4-way tp axis == single-device dense MLP."""
    tp = 4
    rng = np.random.RandomState(2)
    x = rng.randn(6, 16).astype(np.float32)

    mlp = ParallelMLP(hidden_features=32, out_features=16, tp_size=tp, axis_name="tp")
    params = mlp.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]

    # oracle: assemble the full weight matrices from per-rank slices.
    # Per-rank params are identical after init (shapes are local); emulate
    # rank r holding columns [r*local:(r+1)*local] by initializing per rank.
    per_rank = [
        mlp.init(jax.random.PRNGKey(r), jnp.asarray(x))["params"] for r in range(tp)
    ]
    w1 = np.concatenate(
        [np.asarray(p["ColumnParallelDense_0"]["kernel"]) for p in per_rank], axis=1
    )
    b1 = np.concatenate(
        [np.asarray(p["ColumnParallelDense_0"]["bias"]) for p in per_rank]
    )
    w2 = np.concatenate(
        [np.asarray(p["RowParallelDense_0"]["kernel"]) for p in per_rank], axis=0
    )
    b2 = sum(np.asarray(p["RowParallelDense_0"]["bias"]) for p in per_rank)

    expect = jax.nn.gelu(x @ w1 + b1) @ w2 + b2

    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_rank)
    fn = jax.jit(
        jax.shard_map(
            lambda p, xx: mlp.apply({"params": jax.tree.map(lambda q: q[0], p)}, xx),
            mesh=mesh,
            in_specs=(P("tp"), P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    got = np.asarray(fn(stacked, jnp.asarray(x)))
    np.testing.assert_allclose(got, np.asarray(expect), rtol=2e-3, atol=2e-4)


def test_tp_axis_mismatch_raises():
    mlp = ParallelMLP(hidden_features=32, out_features=16, tp_size=4, axis_name="tp")
    x = jnp.zeros((2, 16))
    params = mlp.init(jax.random.PRNGKey(0), x)["params"]
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    with pytest.raises(ValueError, match="tp_size=4"):
        jax.jit(
            jax.shard_map(
                lambda xx: mlp.apply({"params": params}, xx),
                mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False,
            )
        )(x)


@pytest.mark.slow
def test_bert_forward_shapes_and_parallel_consistency():
    """BERT with tp=2 x sp=2 on a 2x2 submesh matches the single-device
    model with assembled weights — end-to-end integration of TP + SP."""
    from bagua_tpu.models.bert import BertConfig, BertModel

    vocab, hidden, heads, layers = 64, 16, 4, 2
    seq = 8
    rng = np.random.RandomState(3)
    ids = rng.randint(0, vocab, size=(2, seq)).astype(np.int32)

    # single-device reference
    cfg0 = BertConfig(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers, num_heads=heads,
        intermediate_size=32, max_position_embeddings=seq,
    )
    model0 = BertModel(cfg0)
    params0 = model0.init(jax.random.PRNGKey(0), jnp.asarray(ids))["params"]
    ref = np.asarray(model0.apply({"params": params0}, jnp.asarray(ids)))

    # tp=2, sp=2 model: slice params0 into per-(tp,sp)-rank shards
    tp, sp = 2, 2
    cfg = BertConfig(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers, num_heads=heads,
        intermediate_size=32, max_position_embeddings=seq, tp_size=tp, tp_axis="tp",
        sp_axis="sp",
    )
    model = BertModel(cfg)

    def shard_for_tp(r):
        """Take tp-rank r's slice of every TP param; heads are contiguous."""

        def slice_leaf(path, leaf):
            name = jax.tree_util.keystr(path)
            arr = np.asarray(leaf)
            if "qkv" in name:
                if name.endswith("['kernel']"):
                    # (in, 3*hidden) -> 3 x heads x head_dim; take local heads
                    k3 = arr.reshape(arr.shape[0], 3, heads, hidden // heads)
                    loc = k3[:, :, r * (heads // tp) : (r + 1) * (heads // tp)]
                    return jnp.asarray(loc.reshape(arr.shape[0], -1))
                loc = arr.reshape(3, heads, hidden // heads)[
                    :, r * (heads // tp) : (r + 1) * (heads // tp)
                ]
                return jnp.asarray(loc.reshape(-1))
            if "['out']['kernel']" in name:
                rows = arr.shape[0] // tp
                return jnp.asarray(arr[r * rows : (r + 1) * rows])
            if "['out']['bias']" in name:
                # RowParallelDense adds the bias AFTER the psum on every
                # rank, so the per-rank shard is the full bias.
                return jnp.asarray(arr)
            if "ColumnParallelDense_0" in name:
                cols = arr.shape[-1] // tp
                return jnp.asarray(arr[..., r * cols : (r + 1) * cols])
            if "RowParallelDense_0" in name and name.endswith("['kernel']"):
                rows = arr.shape[0] // tp
                return jnp.asarray(arr[r * rows : (r + 1) * rows])
            if "RowParallelDense_0" in name and name.endswith("['bias']"):
                return jnp.asarray(arr)
            return jnp.asarray(arr)

        return jax.tree_util.tree_map_with_path(slice_leaf, params0)

    per_tp = [shard_for_tp(r) for r in range(tp)]
    # build (tp*sp) rank-stacked params: same tp shard for both sp ranks
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs), *[per_tp[r] for r in (0, 1) for _ in range(sp)]
    )

    devs = np.array(jax.devices()[:4]).reshape(tp, sp)
    mesh = Mesh(devs, ("tp", "sp"))
    fn = jax.jit(
        jax.shard_map(
            lambda p, ii: model.apply({"params": jax.tree.map(lambda q: q[0], p)}, ii),
            mesh=mesh,
            in_specs=(P(("tp", "sp")), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    got = np.asarray(fn(stacked, jnp.asarray(ids)))
    np.testing.assert_allclose(got, ref, rtol=5e-3, atol=5e-3)


def test_flash_block_pallas_matches_jnp():
    """The Pallas block kernel (interpret mode on CPU) reproduces the jnp
    reference contribution exactly up to float tolerance, incl. padding of
    t_q/t_k/d to TPU tiles and fully-masked columns."""
    from bagua_tpu.kernels.flash_attention import (
        block_attention,
        block_attention_pallas,
    )

    rng = np.random.RandomState(0)
    b, tq, tk, h, d = 2, 12, 20, 3, 24  # deliberately non-tile-aligned
    qf = jnp.asarray(rng.randn(b, tq, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, tk, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, tk, h, d).astype(np.float32))
    mask = jnp.asarray(rng.rand(b, tq, tk) > 0.3)
    mask = mask.at[0, 3, :].set(False)  # one fully-masked query row

    o_ref, l_ref, m_ref = block_attention(qf, k, v, mask)
    o_p, l_p, m_p = block_attention_pallas(qf, k, v, mask, interpret=True)
    np.testing.assert_allclose(np.asarray(o_p), np.asarray(o_ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(l_p), np.asarray(l_ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(m_p), np.asarray(m_ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "tq,tk,d,bq,bk,masktype",
    [
        (384, 640, 64, 128, 128, "causal"),    # 3x5 k-accumulating tiles
        (256, 512, 128, 128, 256, "full"),     # 2x2 tiles
        (200, 300, 64, 128, 128, "causal"),    # unaligned seqs: pad + tile
        (256, 256, 128, 512, 512, "firstcol"), # blocks > seq: single tile
    ],
)
@pytest.mark.slow
def test_flash_tiled_multi_block_matches_jnp(tq, tk, d, bq, bk, masktype):
    """The TILED kernel's online-softmax accumulation across the sequential
    k-grid must reproduce the jnp reference for every tiling regime —
    multi-tile causal, full, unaligned-with-padding, and rows where only the
    first key survives (running-max rescale correctness)."""
    from bagua_tpu.kernels.flash_attention import (
        block_attention,
        block_attention_pallas,
    )

    rng = np.random.RandomState(0)
    b, h = 1, 2
    qf = jnp.asarray(rng.randn(b, tq, h, d).astype(np.float32)) / np.sqrt(d)
    k = jnp.asarray(rng.randn(b, tk, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, tk, h, d).astype(np.float32))
    if masktype == "causal":
        mask = jnp.broadcast_to(
            jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq), (b, tq, tk)
        )
    elif masktype == "firstcol":
        mask = jnp.zeros((b, tq, tk), bool).at[:, :, 0].set(True)
    else:
        mask = jnp.ones((b, tq, tk), bool)
    o_p, l_p, m_p = block_attention_pallas(
        qf, k, v, mask, interpret=True, block_q=bq, block_k=bk
    )
    o_j, l_j, m_j = block_attention(qf, k, v, mask)
    np.testing.assert_allclose(np.asarray(o_p), np.asarray(o_j), atol=2e-4)
    np.testing.assert_allclose(np.asarray(l_p), np.asarray(l_j), atol=2e-4)
    np.testing.assert_allclose(np.asarray(m_p), np.asarray(m_j), atol=2e-5)


def test_ring_attention_pallas_matches_oracle():
    """Full ring attention with the Pallas block kernel (interpret mode)
    equals full attention on the gathered sequence."""
    rng = np.random.RandomState(1)
    b, t, h, d, sp = 2, 16, 2, 8, 4
    q = rng.randn(b, t, h, d).astype(np.float32)
    k = rng.randn(b, t, h, d).astype(np.float32)
    v = rng.randn(b, t, h, d).astype(np.float32)
    ref = np.asarray(
        _block_attention_local(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True)
    )

    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    fn = jax.jit(
        jax.shard_map(
            lambda qq, kk, vv: ring_attention(
                qq, kk, vv, axis_name="sp", causal=True,
                use_pallas=True, interpret=True,
            ),
            mesh=mesh, in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"), check_vma=False,
        )
    )
    got = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_ring_attention_pallas_trains():
    """jax.grad through ring attention with the Pallas kernel must work
    (pallas_call has no autodiff rule — block_attention_fused carries a
    custom VJP) and match the jnp path's gradients.  Guards the training
    path that flips on the moment PALLAS_TPU.json validates the kernel."""
    rng = np.random.RandomState(3)
    b, t, h, d, sp = 1, 16, 2, 8, 4
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))

    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))

    def make_loss(use_pallas):
        def loss(q, k, v):
            y = jax.shard_map(
                lambda qq, kk, vv: ring_attention(
                    qq, kk, vv, axis_name="sp", causal=True,
                    use_pallas=use_pallas, interpret=use_pallas,
                ),
                mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                out_specs=P(None, "sp"), check_vma=False,
            )(q, k, v)
            return jnp.sum(y ** 2)

        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    g_pallas = make_loss(True)(q, k, v)
    g_jnp = make_loss(False)(q, k, v)
    for gp, gj in zip(g_pallas, g_jnp):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gj),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_ring_attention_gqa_native_fused_matches_jnp(layout, monkeypatch):
    """GQA through the fused kernel WITHOUT jnp.repeat (K/V BlockSpecs index
    the shared head tiles; dk/dv accumulate over the query-head group axis):
    composed forward+backward gradients must match the jnp repeat path."""
    monkeypatch.setenv("BAGUA_PALLAS_FLASH_BWD", "1")
    rng = np.random.RandomState(5)
    b, t, h, hkv, d, sp = 1, 32, 4, 2, 8, 4
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, hkv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, hkv, d).astype(np.float32))
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))

    def make_grad(use_pallas):
        def loss(q, k, v):
            y = jax.shard_map(
                lambda qq, kk, vv: ring_attention(
                    qq, kk, vv, axis_name="sp", causal=True,
                    kv_groups=h // hkv, layout=layout,
                    use_pallas=use_pallas, interpret=use_pallas,
                ),
                mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                out_specs=P(None, "sp"), check_vma=False,
            )(q, k, v)
            return jnp.sum(jnp.sin(y))

        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    for gp, gj in zip(make_grad(True)(q, k, v), make_grad(False)(q, k, v)):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gj),
                                   rtol=3e-4, atol=3e-4)


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_ring_attention_fused_backward_matches_jnp(layout, monkeypatch):
    """The FUSED flash backward (tile-recomputed probabilities, stop-grad-m
    semantics) must produce the same composed ring-attention gradients as
    the jnp path — the max-shift terms cancel under the merge+normalize
    composition, which is exactly what this pins."""
    monkeypatch.setenv("BAGUA_PALLAS_FLASH_BWD", "1")
    rng = np.random.RandomState(7)
    b, t, h, d, sp = 1, 32, 2, 8, 4
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))

    def make_grad(use_pallas):
        def loss(q, k, v):
            y = jax.shard_map(
                lambda qq, kk, vv: ring_attention(
                    qq, kk, vv, axis_name="sp", causal=True, layout=layout,
                    use_pallas=use_pallas, interpret=use_pallas,
                ),
                mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                out_specs=P(None, "sp"), check_vma=False,
            )(q, k, v)
            return jnp.sum(jnp.sin(y))  # nontrivial downstream cotangent

        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    g_fused = make_grad(True)(q, k, v)
    g_jnp = make_grad(False)(q, k, v)
    for gp, gj in zip(g_fused, g_jnp):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gj),
                                   rtol=3e-4, atol=3e-4)


@pytest.mark.slow
def test_gpt_4d_parallel_example():
    """The dp x pp x tp x sp composition example trains: one jitted step over
    a 4-axis mesh (pipeline stages, tensor-parallel blocks, ring attention,
    data parallel) with finite decreasing loss."""
    import subprocess
    import sys as _sys

    r = subprocess.run(
        [_sys.executable, "-c", (
            "import jax; jax.config.update('jax_platforms', 'cpu');"
            "import sys; sys.path.insert(0, '/root/repo');"
            "sys.path.insert(0, '/root/repo/examples/gpt_pretrain');"
            "from main import main;"
            "losses = main(['--steps', '5']);"
            "assert all(l == l for l in losses), losses;"
            "import numpy as np;"
            "assert np.mean(losses[-2:]) < losses[0], losses;"
            "print('4D OK', losses[0], '->', losses[-1])"
        )],
        env={**__import__('os').environ, "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
        capture_output=True, text=True, timeout=420,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "4D OK" in r.stdout


def test_ring_attention_zigzag_matches_full():
    """Zigzag layout (balanced causal schedule): permute the sequence with
    zigzag_order, run the ring, invert — must equal full attention."""
    from bagua_tpu.parallel.ring_attention import zigzag_inverse, zigzag_order

    rng = np.random.RandomState(1)
    Tg = SP * T
    q = rng.randn(B, Tg, H, D).astype(np.float32)
    k = rng.randn(B, Tg, H, D).astype(np.float32)
    v = rng.randn(B, Tg, H, D).astype(np.float32)

    full = np.asarray(
        _block_attention_local(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True)
    )

    order = zigzag_order(Tg, SP)
    inv = zigzag_inverse(Tg, SP)
    mesh = sp_mesh()
    fn = jax.jit(
        jax.shard_map(
            lambda qq, kk, vv: ring_attention(
                qq, kk, vv, axis_name="sp", causal=True, layout="zigzag"
            ),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    got_z = np.asarray(fn(jnp.asarray(q[:, order]), jnp.asarray(k[:, order]),
                          jnp.asarray(v[:, order])))
    np.testing.assert_allclose(got_z[:, inv], full, rtol=2e-4, atol=2e-5)


def test_ring_attention_zigzag_kv_mask():
    """Zigzag with a key-padding mask (mask permutes with the sequence)."""
    from bagua_tpu.parallel.ring_attention import zigzag_inverse, zigzag_order

    rng = np.random.RandomState(2)
    Tg = SP * T
    q = rng.randn(B, Tg, H, D).astype(np.float32)
    k = rng.randn(B, Tg, H, D).astype(np.float32)
    v = rng.randn(B, Tg, H, D).astype(np.float32)
    mask = rng.rand(B, Tg) > 0.3

    full = np.asarray(
        _block_attention_local(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True,
            kv_mask=jnp.asarray(mask),
        )
    )

    order = zigzag_order(Tg, SP)
    inv = zigzag_inverse(Tg, SP)
    mesh = sp_mesh()
    fn = jax.jit(
        jax.shard_map(
            lambda qq, kk, vv, mm: ring_attention(
                qq, kk, vv, axis_name="sp", causal=True, kv_mask=mm, layout="zigzag"
            ),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    got_z = np.asarray(fn(
        jnp.asarray(q[:, order]), jnp.asarray(k[:, order]),
        jnp.asarray(v[:, order]), jnp.asarray(mask[:, order]),
    ))
    # rows whose every key is masked are implementation-defined; compare the rest
    valid = np.isfinite(full).all(axis=(2, 3))
    np.testing.assert_allclose(got_z[:, inv][valid], full[valid], rtol=2e-4, atol=2e-5)


def test_zigzag_order_roundtrip():
    from bagua_tpu.parallel.ring_attention import zigzag_inverse, zigzag_order

    order = zigzag_order(32, 4)
    inv = zigzag_inverse(32, 4)
    assert (order[inv] == np.arange(32)).all()
    assert (np.sort(order) == np.arange(32)).all()
    # rank 0's shard = half-blocks 0 and 7
    assert list(order[:8]) == list(range(4)) + list(range(28, 32))


# ---------------------------------------------------------------------------
# Fused collective matmul in the TP layers
# ---------------------------------------------------------------------------


def _mlp_per_rank(tp, fused, hidden=32, out=16):
    """ParallelMLP + per-rank-initialized stacked params (rank r holds its
    weight slice) — the suite's standard TP harness."""
    rng = np.random.RandomState(10)
    x = rng.randn(8, 16).astype(np.float32)
    mlp = ParallelMLP(
        hidden_features=hidden, out_features=out, tp_size=tp, axis_name="tp",
        fused=fused,
    )
    per_rank = [
        mlp.init(jax.random.PRNGKey(r), jnp.asarray(x))["params"] for r in range(tp)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_rank)
    return mlp, stacked, x


def _mlp_apply(mlp, tp):
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))
    return jax.jit(
        jax.shard_map(
            lambda p, xx: mlp.apply({"params": jax.tree.map(lambda q: q[0], p)}, xx),
            mesh=mesh,
            in_specs=(P("tp"), P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def _census(lowerable, *args):
    """HLO collective census via the perf-audit helper (the same counter the
    CI lane gates on)."""
    import os
    import sys

    ci = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "ci")
    if ci not in sys.path:
        sys.path.insert(0, ci)
    from perf_audit import census

    hlo = jax.jit(lowerable).lower(*args).compile().as_text()
    return {op: entry["count"] for op, entry in census(hlo).items() if op != "copy"}


@pytest.mark.parametrize("fused", [True, "auto"])
def test_fused_mlp_matches_unfused(fused):
    """fused ParallelMLP == unfused on the same per-rank params."""
    tp = 4
    mlp_u, stacked, x = _mlp_per_rank(tp, False)
    mlp_f, _, _ = _mlp_per_rank(tp, fused)
    ref = np.asarray(_mlp_apply(mlp_u, tp)(stacked, jnp.asarray(x)))
    got = np.asarray(_mlp_apply(mlp_f, tp)(stacked, jnp.asarray(x)))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_tp_wire_census_fused_vs_unfused():
    """The autodiff wire contract of the Column->Row pair under shard_map.

    Unfused: the Megatron conjugate pair — EXACTLY one forward all-reduce
    plus one backward (psum's transpose on the input gradient), so 1 in the
    forward census and 2 in forward+backward.  Fused: ZERO standalone
    psum/all-reduce anywhere; the matmul_rs ring's tp_size-1 collective
    permutes (mirrored under autodiff) plus the row-block all-gather (whose
    transpose is a reduce-scatter) carry the exchange instead.
    """
    tp = 8
    mlp_u, stacked, x = _mlp_per_rank(tp, False)
    mlp_f, _, _ = _mlp_per_rank(tp, "auto")
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))
    xj = jnp.asarray(x)

    def wire(mlp, grad):
        def fwd(p, xx):
            return mlp.apply({"params": jax.tree.map(lambda q: q[0], p)}, xx)

        if grad:
            # grad wrt params AND input, nonlinear loss: the input cotangent
            # is what forces the backward collective onto the wire.
            inner = jax.grad(lambda p, xx: jnp.sum(fwd(p, xx) ** 2), argnums=(0, 1))
            out_specs = (P("tp"), P())
        else:
            inner, out_specs = fwd, P()
        return _census(
            jax.shard_map(
                inner, mesh=mesh, in_specs=(P("tp"), P()), out_specs=out_specs,
                check_vma=False,
            ),
            stacked, xj,
        )

    assert wire(mlp_u, grad=False).get("all-reduce") == 1
    assert wire(mlp_u, grad=True).get("all-reduce") == 2

    fwd_f = wire(mlp_f, grad=False)
    bwd_f = wire(mlp_f, grad=True)
    for c in (fwd_f, bwd_f):
        assert "all-reduce" not in c, c
    assert fwd_f["collective-permute"] == tp - 1, fwd_f
    assert fwd_f["all-gather"] == 1, fwd_f
    assert bwd_f["collective-permute"] == 2 * (tp - 1), bwd_f
    assert bwd_f["all-gather"] == 1 and bwd_f["reduce-scatter"] == 1, bwd_f


def test_fused_indivisible_tokens():
    """fused=True demands ring divisibility; 'auto' silently falls back."""
    tp = 4
    rng = np.random.RandomState(11)
    x = rng.randn(6, 16).astype(np.float32)  # 6 tokens % 4 != 0
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))

    def apply_with(fused):
        layer = RowParallelDense(12, tp, "tp", fused=fused)
        per_rank = [
            layer.init(jax.random.PRNGKey(r), jnp.asarray(x))["params"]
            for r in range(tp)
        ]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_rank)
        return jax.jit(
            jax.shard_map(
                lambda p, xx: layer.apply(
                    {"params": jax.tree.map(lambda q: q[0], p)}, xx
                ),
                mesh=mesh, in_specs=(P("tp"), P()), out_specs=P(),
                check_vma=False,
            )
        )(stacked, jnp.asarray(x))

    with pytest.raises(ValueError, match="divide by tp_size"):
        apply_with(True)
    got = np.asarray(apply_with("auto"))
    ref = np.asarray(apply_with(False))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("fused", [False, "auto"])
def test_sequence_parallel_roundtrip(fused):
    """Row(scatter_output) -> Column(gather_input): the sequence-parallel
    layout round-trips, fused and unfused agreeing with each other."""
    import flax.linen as nn

    tp = 4

    class Pair(nn.Module):
        fused: object

        @nn.compact
        def __call__(self, x):
            y = RowParallelDense(
                12, tp, "tp", fused=self.fused, scatter_output=True
            )(x)
            return ColumnParallelDense(
                8, tp, "tp", fused=self.fused, gather_input=True
            )(y)

    rng = np.random.RandomState(12)
    x = rng.randn(8, 20).astype(np.float32)  # (tokens, k_local) per rank
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))

    def run(fused_val):
        pair = Pair(fused=fused_val)
        # init with the LOCAL shard shape: RowParallelDense consumes the
        # k-sliced hidden, so its kernel is sized off x's local last dim
        x_local = jnp.asarray(x[:, : x.shape[1] // tp])
        per_rank = [
            pair.init(jax.random.PRNGKey(r), x_local)["params"] for r in range(tp)
        ]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_rank)
        return np.asarray(
            jax.jit(
                jax.shard_map(
                    lambda p, xx: pair.apply(
                        {"params": jax.tree.map(lambda q: q[0], p)}, xx
                    ),
                    mesh=mesh,
                    in_specs=(P("tp"), P(None, "tp")),
                    out_specs=P(None, "tp"),
                    check_vma=False,
                )
            )(stacked, jnp.asarray(x))
        )

    got = run(fused)
    assert got.shape == (8, 8)
    if fused != False:  # noqa: E712 — tri-state knob
        np.testing.assert_allclose(got, run(False), rtol=2e-5, atol=2e-5)
