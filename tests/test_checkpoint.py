"""Checkpoint round-trip: tracker file, MoE split layout, training resume
(reference MoE checkpoint CI test, ``benchmark_master.sh:146-160``)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu.checkpoint import get_latest_iteration, load_checkpoint, save_checkpoint
from bagua_tpu.ddp import DistributedDataParallel, TrainState
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.models.mlp import init_mlp, mse_loss


def tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_tracker_and_roundtrip(tmp_path):
    tree = {
        "layer": {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones((3,))},
        "experts": {"w": jnp.full((2, 4), 7.0)},
    }
    assert get_latest_iteration(str(tmp_path)) is None
    save_checkpoint(100, str(tmp_path), tree)
    save_checkpoint(200, str(tmp_path), tree)
    assert get_latest_iteration(str(tmp_path)) == 200
    # expert/model split layout on disk
    assert os.path.exists(tmp_path / "iter_0000200" / "model_states")
    assert os.path.exists(tmp_path / "iter_0000200" / "expert_states")

    restored, it = load_checkpoint(str(tmp_path))
    assert it == 200
    tree_equal(tree, restored)

    restored100, it100 = load_checkpoint(str(tmp_path), iteration=100)
    assert it100 == 100
    tree_equal(tree, restored100)


@pytest.mark.slow
def test_resume_training_identical(group, tmp_path):
    """Save mid-training, reload into a fresh engine, and check the next step
    is bitwise-identical to the uninterrupted run."""
    params = init_mlp(jax.random.PRNGKey(0), [8, 16, 4])
    rng = np.random.RandomState(0)
    batches = [
        (
            jnp.asarray(rng.randn(16, 8), np.float32),
            jnp.asarray(rng.randn(16, 4), np.float32),
        )
        for _ in range(6)
    ]

    def make_ddp():
        return DistributedDataParallel(
            mse_loss, optax.adam(1e-2), GradientAllReduceAlgorithm(), process_group=group
        )

    ddp = make_ddp()
    state = ddp.init(params)
    for i in range(3):
        state, _ = ddp.train_step(state, batches[i])
    save_checkpoint(3, str(tmp_path), state, moe_split=False)
    for i in range(3, 6):
        state, _ = ddp.train_step(state, batches[i])
    uninterrupted = state

    ddp2 = make_ddp()
    template = ddp2.init(params)  # build plan/template + a state template
    state2, it = load_checkpoint(str(tmp_path), target=template)
    assert it == 3
    for i in range(3, 6):
        state2, _ = ddp2.train_step(state2, batches[i])

    for a, b in zip(jax.tree.leaves(uninterrupted.params), jax.tree.leaves(state2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(state2.step[0]) == 6


def test_tracker_torn_write_race_falls_back_to_scan(tmp_path):
    """The save path publishes the completion marker *then* the tracker, both
    via write-temp + atomic rename — so every torn-write interleaving a
    restarting rank can observe heals to the newest checkpoint that actually
    landed, never a garbage iteration."""
    from bagua_tpu.checkpoint.checkpointing import (
        COMPLETE_FILENAME, TRACKER_FILENAME, _atomic_write,
    )

    root = str(tmp_path)

    def fake_ckpt(iteration, complete=True):
        d = tmp_path / f"iter_{iteration:07d}"
        d.mkdir()
        (d / "model_states").mkdir()
        if complete:
            _atomic_write(str(d / COMPLETE_FILENAME), str(iteration))

    # nothing on disk at all
    assert get_latest_iteration(root) is None

    fake_ckpt(3)
    fake_ckpt(5, complete=False)  # writer killed before the marker landed
    # the crash window: states of iter 5 half-written, tracker still says 3
    (tmp_path / TRACKER_FILENAME).write_text("3")
    assert get_latest_iteration(root) == 3
    # ...or the tracker itself was advanced to the incomplete checkpoint by a
    # buggy/older writer: the marker check rejects it, the scan heals to 3
    (tmp_path / TRACKER_FILENAME).write_text("5")
    assert get_latest_iteration(root) == 3
    # a torn tracker (reader caught a half-flushed in-place write) is not fatal
    (tmp_path / TRACKER_FILENAME).write_text("5\x00garbage")
    assert get_latest_iteration(root) == 3
    # tracker deleted entirely: pure scan
    (tmp_path / TRACKER_FILENAME).unlink()
    assert get_latest_iteration(root) == 3
    # tracker pointing past every directory (NFS lag): scan fallback again
    (tmp_path / TRACKER_FILENAME).write_text("9000")
    assert get_latest_iteration(root) == 3

    # no checkpoint ever completed: None, not a crash
    (tmp_path / f"iter_{3:07d}" / COMPLETE_FILENAME).unlink()
    assert get_latest_iteration(root) is None
    # junk directory names are skipped by the scan
    (tmp_path / "iter_notanumber").mkdir()
    assert get_latest_iteration(root) is None


@pytest.mark.slow
def test_save_checkpoint_publishes_marker_before_tracker(tmp_path):
    """After a real save: marker inside the checkpoint, tracker at the root,
    and no .tmp residue anywhere (every publish was an atomic rename)."""
    from bagua_tpu.checkpoint.checkpointing import COMPLETE_FILENAME, TRACKER_FILENAME

    tree = {"w": jnp.arange(4.0)}
    save_checkpoint(7, str(tmp_path), tree, moe_split=False)
    assert (tmp_path / "iter_0000007" / COMPLETE_FILENAME).read_text() == "7"
    assert (tmp_path / TRACKER_FILENAME).read_text() == "7"
    residue = [
        os.path.join(r, n)
        for r, _, names in os.walk(tmp_path) for n in names if ".tmp." in n
    ]
    assert residue == []
    assert get_latest_iteration(str(tmp_path)) == 7


def test_remap_world_size_replicated_and_expert():
    """Elastic restart remap: replicated leaves re-stack to the new size;
    expert leaves redistribute the global expert pool (total preserved)."""
    import jax.numpy as jnp
    import numpy as np

    from bagua_tpu.checkpoint import remap_world_size

    state = {
        "dense": {"w": jnp.broadcast_to(jnp.arange(6.0)[None], (8, 6))},
        "moe": {"experts": jnp.arange(8 * 2 * 3.0).reshape(8, 2, 3)},
    }
    is_expert = lambda path: "experts" in path

    down = remap_world_size(state, 4, expert_filter=is_expert)
    assert down["dense"]["w"].shape == (4, 6)
    np.testing.assert_array_equal(down["dense"]["w"][3], state["dense"]["w"][0])
    assert down["moe"]["experts"].shape == (4, 4, 3)  # 16 experts preserved
    np.testing.assert_array_equal(
        np.asarray(down["moe"]["experts"]).reshape(16, 3),
        np.asarray(state["moe"]["experts"]).reshape(16, 3),
    )

    up = remap_world_size(down, 16, expert_filter=is_expert)
    assert up["moe"]["experts"].shape == (16, 1, 3)
    assert up["dense"]["w"].shape == (16, 6)

    import pytest

    with pytest.raises(ValueError):
        remap_world_size(state, 5, expert_filter=is_expert)  # 16 % 5 != 0


def test_remap_world_size_edge_cases():
    """Elastic-resume remap corners: odd→even shrink, growing past the
    original size, and the expert pool surviving a down-up round trip
    bitwise."""
    from bagua_tpu.checkpoint import remap_world_size

    is_expert = lambda path: "experts" in path
    state = {
        "dense": {"w": jnp.broadcast_to(jnp.arange(5.0)[None], (6, 5))},
        "moe": {"experts": jnp.arange(6 * 2 * 3.0).reshape(6, 2, 3)},
    }

    # odd world size shrinking to an even one: 6 ranks x 2 experts = 12
    # experts redistribute as 4 x 3; the flattened pool is order-preserved
    down = remap_world_size(state, 4, expert_filter=is_expert)
    assert down["dense"]["w"].shape == (4, 5)
    assert down["moe"]["experts"].shape == (4, 3, 3)
    np.testing.assert_array_equal(
        np.asarray(down["moe"]["experts"]).reshape(12, 3),
        np.asarray(state["moe"]["experts"]).reshape(12, 3),
    )

    # growing PAST the original size: 12 experts over 12 ranks, one each
    up = remap_world_size(state, 12, expert_filter=is_expert)
    assert up["moe"]["experts"].shape == (12, 1, 3)
    assert up["dense"]["w"].shape == (12, 5)
    np.testing.assert_array_equal(up["dense"]["w"][11], state["dense"]["w"][0])
    # ...but a growth the pool cannot fill (12 % 24 != 0) fails loud
    with pytest.raises(ValueError):
        remap_world_size(state, 24, expert_filter=is_expert)

    # MoE down-up round trip is bitwise: shrink 6 -> 2, grow back 2 -> 6
    shrunk = remap_world_size(state, 2, expert_filter=is_expert)
    assert shrunk["moe"]["experts"].shape == (2, 6, 3)
    back = remap_world_size(shrunk, 6, expert_filter=is_expert)
    for key in ("dense", "moe"):
        for leaf, orig in zip(
            jax.tree.leaves(back[key]), jax.tree.leaves(state[key])
        ):
            np.testing.assert_array_equal(np.asarray(leaf), np.asarray(orig))

    # None leaves (the expert/model split placeholders) pass through
    holey = {"dense": None, "moe": {"experts": state["moe"]["experts"]}}
    remapped = remap_world_size(holey, 3, expert_filter=is_expert)
    assert remapped["dense"] is None
    assert remapped["moe"]["experts"].shape == (3, 4, 3)


def test_parse_nnodes():
    from bagua_tpu.distributed.run import parse_nnodes

    assert parse_nnodes("3") == (3, 3)
    assert parse_nnodes("1:4") == (1, 4)
    import pytest

    with pytest.raises(ValueError):
        parse_nnodes("4:2")
