"""Shared harness for the driver-facing benchmark scripts (bench.py,
bench_bert.py, bench_moe.py, bench_scaling.py, bench_llama.py): tunnel
preflight, deadline watchdog, JSON-line emission protocol, stderr progress
notes, persistent compilation cache.

Contract (what the driver parses): every script prints JSON lines to stdout;
the LAST line is authoritative.  A provisional line lands as soon as the
first timed step completes; if nothing has been emitted by the deadline
(``BENCH_DEADLINE_SEC`` + 60s slack), the watchdog prints an error line with
``value: 0`` and exits 3 — so the artifact is parseable even when the device
backend init hangs (round 1's failure mode).
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "ci"))
import tpu_probe  # noqa: E402  — bounded backend-init probe (ci/tpu_probe.py)


class BenchHarness:
    def __init__(self, metric: str, unit: str, recorded_artifact: str = None):
        self.metric = metric
        self.unit = unit
        #: optional repo-relative path of a committed artifact holding this
        #: metric's last real-hardware measurement — attached to watchdog /
        #: error lines so a dead tunnel doesn't read as "no evidence exists"
        self.recorded_artifact = recorded_artifact
        self.t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._emitted = False
        threading.Thread(target=self._watchdog, daemon=True).start()
        import jax

        if os.environ.get("BENCH_FORCE_CPU"):
            # CPU smoke of the bench scripts themselves: the axon
            # sitecustomize force-selects its platform via config.update,
            # which overrides JAX_PLATFORMS (see tests/conftest.py).
            jax.config.update("jax_platforms", "cpu")
        # Persistent compilation cache: a cold re-run skips the compile.
        # BAGUA_COMPILE_CACHE_DIR overrides; default is the repo-local dir.
        from bagua_tpu.env import setup_compile_cache

        setup_compile_cache(
            default_dir=os.path.join(
                os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
            )
        )

    def _error_line(self, error: str) -> str:
        line = {
            "metric": self.metric,
            "value": 0.0,
            "unit": self.unit,
            "vs_baseline": None,
            "error": error,
        }
        if self.recorded_artifact:
            line["recorded_artifact"] = self.recorded_artifact
        return json.dumps(line)

    def _watchdog(self):
        # one minute after the measurement loop's soft deadline
        deadline = float(os.environ.get("BENCH_DEADLINE_SEC", "420")) + 60.0
        time.sleep(deadline)
        if self._emitted:
            os._exit(0)  # provisional line already out; let it stand
        # Diagnose BEFORE taking the lock: relay_diagnosis holds sockets for
        # up to ~6s, and a measurement completing in that window must not
        # block in emit() only to be discarded by our os._exit.
        try:
            relay = tpu_probe.relay_diagnosis()
        except Exception:  # noqa: BLE001 — diagnosis must not mask the line
            relay = "diagnosis-failed"
        with self._lock:
            if self._emitted:
                os._exit(0)
            print(
                self._error_line(
                    f"no measurement within {deadline:.0f}s "
                    f"(backend init or compile hang; relay: {relay})"
                ),
                flush=True,
            )
        os._exit(3)

    def note(self, msg: str) -> None:
        print(
            f"[{self.metric.split('_')[0]} +{time.perf_counter() - self.t0:5.1f}s] {msg}",
            file=sys.stderr,
            flush=True,
        )

    def preflight(self) -> None:
        """Prove the TPU tunnel healthy BEFORE the main process touches the
        backend (rounds 1-4 recorded 0.0 because ``jax.devices()`` blocks
        forever when the axon tunnel's upstream is dead — the PJRT client
        retries its claim with no timeout; see ci/tpu_probe.py).

        Strategy: classify the relay socket (<5s).  If it holds the
        connection (healthy signature) proceed straight to in-process init
        — no throwaway chip claim on the happy path.  If it drops the
        connection (dead-upstream signature), run bounded child-process
        init probes while budget remains — a fresh process re-dials the
        handshake, so a tunnel that recovers mid-window is caught.  If
        nothing succeeds, emit an error line that names the stuck phase
        and the relay state, then exit 3 well before the outer watchdog.
        """
        if os.environ.get("BENCH_FORCE_CPU") or os.environ.get("BENCH_SKIP_PREFLIGHT"):
            return
        relay = tpu_probe.relay_diagnosis()
        self.note(f"preflight: relay {tpu_probe.RELAY_HOST}:{tpu_probe.RELAY_PORT} -> {relay}")
        if relay == "accepted-held":
            return  # healthy signature — init directly, watchdog still guards
        # Dead/ambiguous relay: bounded probes are ground truth (the relay
        # classification is heuristic — wait_healthy always runs at least
        # one real init attempt regardless of remaining budget).
        #
        # Fail-fast on the accepted-then-dropped signature: five rounds of
        # history say a relay that accepts then drops has a dead upstream
        # tunnel and never recovers mid-window, so burn ONE bounded probe as
        # ground truth instead of four, emit the structured error record
        # immediately, and salvage the session with the CPU-sim scaling
        # bench rather than spending the whole deadline on retries.
        deadline = self.t0 + float(os.environ.get("BENCH_DEADLINE_SEC", "420"))
        fail_fast = relay == "accepted-then-dropped"
        result = tpu_probe.wait_healthy(
            attempts=1 if fail_fast else 4, cap_s=50.0, note=self.note,
            deadline=deadline - 90.0, relay=relay,
        )
        if result["ok"]:
            # Settle before claiming: in the r4 session the step launched 3s
            # after a clean client exit hung at init — if the pool needs a
            # beat to free the previous lease, 5s is cheap insurance (the
            # watchdog still guards the main init either way).
            time.sleep(5.0)
            self.note("preflight: probe healthy — proceeding to backend init")
            return
        err = None
        with self._lock:
            if not self._emitted:
                err = self._error_line(tpu_probe.failure_summary(result))
                print(err, flush=True)
                self._emitted = True
        if fail_fast and err is not None:
            self._modeled_rows()
            self._cpu_sim_fallback(err)
        os._exit(3)

    def _modeled_rows(self) -> None:
        """Dead tunnel salvage, part 1: emit this metric's *modeled* value
        from the committed BENCH_MODELED.json (the perf lab's census-proved
        wire bytes priced through the fitted α–β model).  A pure JSON read —
        no subprocess, no tracing — so it cannot hang the salvage path.
        Rows are tagged ``"mode": "modeled"`` with explicit provenance; the
        structured error record still lands LAST, so the driver's last-line
        parse sees the abort, never a model masquerading as a measurement."""
        try:
            from bagua_tpu.perflab.engine import modeled_bench_rows

            rows = modeled_bench_rows(self.metric)
        except Exception as e:  # noqa: BLE001 — salvage must not mask the abort
            self.note(f"modeled fallback unavailable: {type(e).__name__}: {e}")
            return
        for row in rows:
            print(json.dumps(row), flush=True)
        if rows:
            self.note(
                f"fail-fast: emitted {len(rows)} modeled row(s) from "
                "BENCH_MODELED.json (mode=modeled; not a measurement)"
            )

    def _cpu_sim_fallback(self, error_line: str) -> None:
        """Dead tunnel salvage: run the scaling bench on the 8-device CPU sim
        so the session still yields a real (if simulated) measurement.  The
        fallback's JSON lines are forwarded tagged ``"fallback": "cpu-sim"``,
        and the structured error record is re-printed LAST so the driver's
        last-line parse still sees this metric's abort, not a foreign one."""
        import subprocess

        script = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "bench_scaling.py"
        )
        # Budget from the wall clock the fail-fast just saved: the child gets
        # a deadline short enough that ITS watchdog emits (provisional width
        # lines land as they complete) before our kill, and everything ends
        # before this harness's own watchdog thread can os._exit mid-forward.
        watchdog_wall = (
            self.t0 + float(os.environ.get("BENCH_DEADLINE_SEC", "420")) + 60.0
        )
        remaining = watchdog_wall - time.perf_counter() - 30.0
        child_deadline = max(120.0, remaining - 90.0)
        env = dict(os.environ)
        env.update(
            BENCH_FORCE_CPU="1",  # fallback preflight short-circuits: no recursion
            BENCH_BATCH_PER_CHIP="4",
            BENCH_IMAGE_SIZE="64",
            XLA_FLAGS="--xla_force_host_platform_device_count=8",
            BENCH_DEADLINE_SEC=str(int(child_deadline)),
        )
        self.note(
            "fail-fast: tunnel dead — falling back to CPU-sim scaling bench "
            f"({child_deadline:.0f}s budget)"
        )
        try:
            proc = subprocess.run(
                [sys.executable, script], env=env, capture_output=True,
                text=True, timeout=child_deadline + 80.0,
            )
            for line in proc.stdout.splitlines():
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                rec["fallback"] = "cpu-sim"
                print(json.dumps(rec), flush=True)
        except Exception as e:  # noqa: BLE001 — salvage must not mask the abort
            self.note(f"cpu-sim fallback failed: {type(e).__name__}: {e}")
        print(error_line, flush=True)

    def guard(self, main_fn) -> None:
        """Run the benchmark body; on ANY exception emit a parseable error
        line first (the tunneled TPU backend has been seen raising
        UNAVAILABLE after minutes of init), then re-raise."""
        try:
            self.preflight()
            main_fn()
        except BaseException as e:  # noqa: BLE001 — always leave a JSON line
            with self._lock:
                if not self._emitted:
                    print(
                        self._error_line(f"{type(e).__name__}: {e}"[:500]),
                        flush=True,
                    )
                    self._emitted = True
            raise

    def emit(self, value: float, provisional: bool = False, extra: dict = None) -> None:
        line = {
            "metric": self.metric,
            "value": round(value, 2),
            "unit": self.unit,
        }
        if extra:
            line.update(extra)
        if provisional:
            line["provisional"] = True
        with self._lock:
            self._emitted = True
            print(json.dumps(line), flush=True)
