"""Static analysis of traced collective programs.

The BAGUA configuration space — {algorithm × wire precision × overlap mode
× bucket plan}, mutable mid-training since PRs 8–10 — multiplies the
distinct collective programs a gang can run; this package proves a program
is gang-consistent **before dispatch** instead of diagnosing the hang
afterwards.  :mod:`~bagua_tpu.analysis.collective_ir` extracts a canonical
IR from the traced step's jaxpr, :mod:`~bagua_tpu.analysis.checks` runs
the four checkers (rank invariance, wire-byte exactness, plan conformance,
static/dynamic agreement with the flight recorder), and
:mod:`~bagua_tpu.analysis.verify` wires them into the engine's
``BAGUA_STATIC_VERIFY`` pre-dispatch gate and ``ci/static_verify.py``.
See ``docs/static_analysis.md``.
"""

from bagua_tpu.analysis.checks import (
    CHECK_NAMES,
    MODELED_ALGOS,
    Finding,
    StaticVerifyError,
    WireModelConfig,
    canonical_records,
    check_plan_conformance,
    check_rank_invariance,
    check_static_dynamic,
    check_wire_exactness,
)
from bagua_tpu.analysis.collective_ir import (
    COLLECTIVE_PRIMITIVES,
    CollectiveDescriptor,
    CollectiveProgram,
    extract_collective_ir,
    primitive_wire_bytes,
)
from bagua_tpu.analysis.verify import (
    VerifyReport,
    collect_ir,
    predict_flight_program,
    verify_collective_program,
    verify_step_program,
)

__all__ = [
    "CHECK_NAMES",
    "COLLECTIVE_PRIMITIVES",
    "MODELED_ALGOS",
    "CollectiveDescriptor",
    "CollectiveProgram",
    "Finding",
    "StaticVerifyError",
    "VerifyReport",
    "WireModelConfig",
    "canonical_records",
    "check_plan_conformance",
    "check_rank_invariance",
    "check_static_dynamic",
    "check_wire_exactness",
    "collect_ir",
    "extract_collective_ir",
    "predict_flight_program",
    "primitive_wire_bytes",
    "verify_collective_program",
    "verify_step_program",
]
