"""CollectiveIR: a canonical description of every collective in a jaxpr.

The static verifier's substrate.  :func:`extract_collective_ir` walks a
traced step program (a ``ClosedJaxpr`` from ``jax.make_jaxpr``, descending
through ``pjit``/``shard_map``/``cond``/``while``/``scan``/``custom_vjp``
sub-jaxprs) and emits one :class:`CollectiveDescriptor` per collective
primitive — ``psum``/``pmax``/``pmin``/``reduce_scatter``/``all_gather``/
``ppermute``/``all_to_all`` — carrying:

* the mesh axes it reduces over and the resulting ring size;
* the local operand shape/dtype and **exact** operand bytes (variadic
  ``psum`` sums its operands);
* the per-rank ring-model wire bytes for the primitive (an N-byte operand's
  all-reduce moves ``2N(n-1)/n``, a reduce-scatter/all-to-all ``N(n-1)/n``,
  an all-gather ``N(n-1)`` and a ppermute ``N`` — the same α–β legs the
  service planner prices);
* the enclosing named-scope label (the jaxpr ``name_stack``), parsed with
  the shared grammar (:mod:`bagua_tpu.observability.scope_grammar`) into
  the bucket-exchange / model-parallel / quantized-ring frames;
* its control-flow nesting path and a **rank-conditional** flag.

The rank-conditional flag comes from a taint analysis run during the same
walk.  Taint is tracked **per mesh axis**: ``axis_index('dp')`` taints its
result (and anything computed from it) with ``{'dp'}`` — the set of axes
along which the value can differ between ranks.  The rank-uniformizing
collectives (``psum``/``pmax``/``pmin``/``all_gather``) launder only the
axes they actually span: a ``psum`` over ``'dp'`` of a ``'dp'``-tainted
value is identical on every rank and clears the taint, but a ``psum`` over
a *sub*-axis (say ``'tp'``) of that same value still differs across
``'dp'`` ranks, so the residual ``{'dp'}`` taint survives.  A ``cond``/
``while`` whose predicate carries any residual axis taint executes
*different branch programs on different ranks* — any collective inside
such a branch (including the ``while``'s own predicate jaxpr) is the exact
desync class the flight recorder (PR 10) can only diagnose post-mortem, so
the walker marks it for ``check_rank_invariance`` to reject at trace time.
The analysis is deliberately scoped to ``axis_index``-derived taint:
per-rank *data* (batch shards) is rank-varying too, but branching on
reduced data is the normal ``is_update_step`` pattern and must stay clean.
"""

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
from jax._src import core as jcore

from bagua_tpu.observability.scope_grammar import (
    parse_exchange_label,
    parse_mp_label,
    parse_qr_scope,
    parse_stale_scope,
)

__all__ = [
    "COLLECTIVE_PRIMITIVES",
    "UNIFORMIZING_PRIMITIVES",
    "CollectiveDescriptor",
    "CollectiveProgram",
    "extract_collective_ir",
    "primitive_wire_bytes",
]

#: jaxpr primitive name -> reduction op (None = data movement only)
COLLECTIVE_PRIMITIVES = {
    "psum": "sum",
    "pmax": "max",
    "pmin": "min",
    "reduce_scatter": "sum",  # lax.psum_scatter
    "all_gather": None,
    "ppermute": None,
    "all_to_all": None,
}

#: collectives whose outputs are identical on every rank of the axes they
#: span — they launder those axes' axis_index taint away (a branch on a
#: fully-reduced value is gang-safe; taint along unreduced axes survives)
UNIFORMIZING_PRIMITIVES = frozenset({"psum", "pmax", "pmin", "all_gather"})

#: control-flow primitives whose predicate picks the executed program
_BRANCHING_PRIMITIVES = frozenset({"cond", "while"})


def primitive_wire_bytes(primitive: str, operand_bytes: int, n: int) -> int:
    """Per-rank ring-model wire bytes for one collective primitive over an
    ``n``-rank axis with ``operand_bytes`` of local input.  These are the
    planner's α–β payload legs: ring all-reduce ``2N(n-1)/n``, rs/a2a one
    scatter leg ``N(n-1)/n``, all-gather ``N(n-1)`` (the operand IS the
    local shard), ppermute one send of ``N``."""
    if n <= 1:
        return 0
    if primitive in ("psum", "pmax", "pmin"):
        return 2 * operand_bytes * (n - 1) // n
    if primitive in ("reduce_scatter", "all_to_all"):
        return operand_bytes * (n - 1) // n
    if primitive == "all_gather":
        return operand_bytes * (n - 1)
    if primitive == "ppermute":
        return operand_bytes
    raise ValueError(f"not a collective primitive: {primitive!r}")


@dataclasses.dataclass
class CollectiveDescriptor:
    """One collective primitive of the traced step program."""

    index: int                      #: position in jaxpr walk order
    primitive: str                  #: jaxpr primitive name
    reduce_op: Optional[str]        #: "sum"/"max"/"min" or None
    axes: Tuple[str, ...]           #: mesh axis names it spans
    ring_size: int                  #: product of those axes' sizes
    shapes: Tuple[Tuple[int, ...], ...]  #: local operand shapes
    dtypes: Tuple[str, ...]         #: local operand dtypes
    nbytes: int                     #: exact local operand bytes (summed)
    wire_bytes: int                 #: per-rank ring-model wire bytes
    label: str                      #: full name_stack string
    scope: Optional[Dict]           #: parsed bucket-exchange frame
    mp: Optional[Dict]              #: parsed model-parallel frame
    qr: Optional[Dict]              #: parsed quantized-ring sub-scope
    path: Tuple[str, ...]           #: enclosing control-flow frames —
                                    #: ``"while"`` or ``"cond#<eqn>@<branch>"``
                                    #: (the ids let checkers tell sibling
                                    #: branches of one cond apart)
    rank_conditional: bool          #: under a rank-tainted predicate
    cond_label: Optional[str]       #: label of that tainted control-flow eqn
    #: the bound τ of an enclosing ``bagua_stale/tau=<k>`` frame, or None —
    #: the sanctioned bounded-staleness marker ``check_rank_invariance``
    #: accepts (with structural conditions) instead of blanket-rejecting
    stale: Optional[int] = None

    @property
    def bucket(self) -> Optional[int]:
        return self.scope["bucket"] if self.scope else None

    @property
    def phase(self) -> Optional[str]:
        return self.scope["phase"] if self.scope else None

    @property
    def algo(self) -> Optional[str]:
        return self.scope["algo"] if self.scope else None


@dataclasses.dataclass
class CollectiveProgram:
    """The CollectiveIR of one traced step: descriptors in walk order plus
    the mesh geometry they were extracted under."""

    collectives: List[CollectiveDescriptor]
    axis_sizes: Dict[str, int]

    @property
    def world_size(self) -> int:
        n = 1
        for s in self.axis_sizes.values():
            n *= int(s)
        return n

    def labeled(self) -> List[CollectiveDescriptor]:
        """Descriptors carrying a bucket-exchange frame."""
        return [d for d in self.collectives if d.scope is not None]

    def by_bucket_phase(self) -> Dict[Tuple[str, int, str], List[CollectiveDescriptor]]:
        """Group the labeled descriptors by ``(algo, bucket, phase)``,
        preserving walk order inside each group (and insertion order of the
        groups themselves)."""
        out: Dict[Tuple[str, int, str], List[CollectiveDescriptor]] = {}
        for d in self.labeled():
            out.setdefault((d.scope["algo"], d.scope["bucket"], d.scope["phase"]), []).append(d)
        return out


def _aval_bytes(aval) -> int:
    shape = tuple(getattr(aval, "shape", ()) or ())
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def _axis_names(eqn) -> Tuple[str, ...]:
    a = eqn.params.get("axes")
    if a is None:
        a = eqn.params.get("axis_name")
    if a is None:
        return ()
    if not isinstance(a, (tuple, list)):
        a = (a,)
    # psum's axes param may mix positional ints with named axes; only the
    # names define the ring
    return tuple(str(x) for x in a if not isinstance(x, int))


def _sub_jaxprs(params) -> List[jcore.Jaxpr]:
    """Every sub-jaxpr reachable from an eqn's params — pjit/shard_map carry
    one (shard_map's is an *open* ``core.Jaxpr``, pjit's a ``ClosedJaxpr``),
    cond carries a tuple of branches, custom_vjp a call_jaxpr plus the fwd/
    bwd thunks."""
    subs = []
    for v in params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for w in vs:
            if isinstance(w, jcore.ClosedJaxpr):
                subs.append(w.jaxpr)
            elif isinstance(w, jcore.Jaxpr):
                subs.append(w)
    return subs


_NO_AXES: frozenset = frozenset()


class _Walk:
    def __init__(self, axis_sizes: Dict[str, int]):
        self.axis_sizes = {str(k): int(v) for k, v in axis_sizes.items()}
        self.out: List[CollectiveDescriptor] = []
        # stack of (frame, label, predicate_tainted); frame is "while" or
        # "cond#<eqn-id>@<branch>" so sibling branches are distinguishable
        self.ctrl: List[Tuple[str, str, bool]] = []
        self._cond_ids = 0  # unique id per visited cond eqn

    # -- recording -----------------------------------------------------------

    def record(self, eqn, label: str) -> None:
        name = eqn.primitive.name
        axes = tuple(a for a in _axis_names(eqn) if a in self.axis_sizes)
        n = 1
        for a in axes:
            n *= self.axis_sizes[a]
        avals = [v.aval for v in eqn.invars]
        nbytes = sum(_aval_bytes(a) for a in avals)
        self.out.append(
            CollectiveDescriptor(
                index=len(self.out),
                primitive=name,
                reduce_op=COLLECTIVE_PRIMITIVES[name],
                axes=axes,
                ring_size=n,
                shapes=tuple(tuple(getattr(a, "shape", ()) or ()) for a in avals),
                dtypes=tuple(str(getattr(a, "dtype", "")) for a in avals),
                nbytes=nbytes,
                wire_bytes=primitive_wire_bytes(name, nbytes, n),
                label=label,
                scope=parse_exchange_label(label),
                mp=parse_mp_label(label),
                qr=parse_qr_scope(label),
                path=tuple(p for p, _, _ in self.ctrl),
                rank_conditional=any(t for _, _, t in self.ctrl),
                cond_label=next(
                    (lab for _, lab, t in reversed(self.ctrl) if t), None
                ),
                stale=parse_stale_scope(label),
            )
        )

    # -- taint helpers -------------------------------------------------------
    #
    # ``taint`` maps Var -> frozenset of mesh-axis names the value can vary
    # along between ranks.  An empty mapping means rank-uniform.

    @staticmethod
    def _taint_of(v, taint: Dict) -> frozenset:
        if isinstance(v, jcore.Var):
            return taint.get(v, _NO_AXES)
        return _NO_AXES

    def _in_axes(self, eqn, taint: Dict) -> frozenset:
        axes = _NO_AXES
        for v in eqn.invars:
            axes |= self._taint_of(v, taint)
        return axes

    def _seed(self, sub_invars, call_invars, taint: Dict) -> Dict:
        sub: Dict[Any, frozenset] = {}
        for sv, av in zip(sub_invars, call_invars):
            ax = self._taint_of(av, taint)
            if ax:
                sub[sv] = ax
        return sub

    def _known_axes(self, eqn) -> frozenset:
        return frozenset(
            a for a in _axis_names(eqn) if a in self.axis_sizes
        )

    # -- the walk ------------------------------------------------------------

    def walk(self, jaxpr: jcore.Jaxpr, taint: Dict, record: bool = True) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            label = str(eqn.source_info.name_stack)
            in_axes = self._in_axes(eqn, taint)

            if name == "axis_index":
                # Varies exactly along the indexed axis; if the axis name is
                # unrecognized, conservatively assume every mesh axis.
                axes = self._known_axes(eqn) or frozenset(self.axis_sizes)
                for v in eqn.outvars:
                    taint[v] = axes
                continue

            if name in COLLECTIVE_PRIMITIVES:
                if record:
                    self.record(eqn, label)
                if name in UNIFORMIZING_PRIMITIVES:
                    # Identical on every rank of the axes it spans — launder
                    # exactly those; taint along unreduced axes survives (a
                    # psum over 'tp' of a 'dp'-varying value still differs
                    # across 'dp' ranks).
                    in_axes -= self._known_axes(eqn)
                if in_axes:
                    for v in eqn.outvars:
                        taint[v] = in_axes
                continue

            if name == "cond":
                pred = eqn.invars[0]
                pred_axes = self._taint_of(pred, taint)
                out_axes = pred_axes
                cid = self._cond_ids
                self._cond_ids += 1
                for bi, br in enumerate(eqn.params["branches"]):
                    brj = br.jaxpr if isinstance(br, jcore.ClosedJaxpr) else br
                    sub = self._seed(brj.invars, eqn.invars[1:], taint)
                    self.ctrl.append((f"cond#{cid}@{bi}", label, bool(pred_axes)))
                    self.walk(brj, sub, record)
                    self.ctrl.pop()
                    for v in brj.outvars:
                        out_axes |= self._taint_of(v, sub)
                if out_axes:
                    for v in eqn.outvars:
                        taint[v] = out_axes
                continue

            if name == "while":
                self._walk_while(eqn, taint, record, label)
                continue

            subs = _sub_jaxprs(eqn.params)
            if subs:
                out_axes = in_axes
                for sj in subs:
                    # pjit/shard_map invars align 1:1 with the call's; for
                    # scan/custom_vjp the positional zip is a conservative
                    # best-effort seed (zip truncates on mismatch)
                    sub = self._seed(sj.invars, eqn.invars, taint)
                    self.walk(sj, sub, record)
                    for v in sj.outvars:
                        out_axes |= self._taint_of(v, sub)
                if out_axes:
                    for v in eqn.outvars:
                        taint[v] = out_axes
                continue

            if in_axes:
                for v in eqn.outvars:
                    taint[v] = in_axes

    def _walk_while(self, eqn, taint: Dict, record: bool, label: str) -> None:
        p = eqn.params
        cond_j = p["cond_jaxpr"].jaxpr
        body_j = p["body_jaxpr"].jaxpr
        cn, bn = p.get("cond_nconsts", 0), p.get("body_nconsts", 0)
        cond_consts = list(eqn.invars[:cn])
        body_consts = list(eqn.invars[cn:cn + bn])
        carry = list(eqn.invars[cn + bn:])
        carry_taint = [self._taint_of(v, taint) for v in carry]

        def seed_from(consts, sub_invars):
            sub: Dict[Any, frozenset] = {}
            for sv, av in zip(sub_invars, consts + carry):
                ax = self._taint_of(av, taint)
                if ax:
                    sub[sv] = ax
            # carry slots tainted by a previous body pass
            for sv, ax in zip(sub_invars[len(consts):], carry_taint):
                if ax:
                    sub[sv] = sub.get(sv, _NO_AXES) | ax
            return sub

        # Fixpoint approximation on the carried taint: two silent body
        # passes (one propagation step each) before the recording passes.
        pred_axes: frozenset = _NO_AXES
        for _ in range(2):
            csub = seed_from(cond_consts, cond_j.invars)
            self.walk(cond_j, csub, record=False)
            pred_axes = _NO_AXES
            for v in cond_j.outvars:
                pred_axes |= self._taint_of(v, csub)
            bsub = seed_from(body_consts, body_j.invars)
            self.ctrl.append(("while", label, bool(pred_axes)))
            self.walk(body_j, bsub, record=False)
            self.ctrl.pop()
            new_carry = [self._taint_of(v, bsub) for v in body_j.outvars]
            if new_carry == carry_taint[: len(new_carry)]:
                break
            for i, ax in enumerate(new_carry):
                if i < len(carry_taint):
                    carry_taint[i] = carry_taint[i] | ax
        # Recording passes with converged taint — cond first (it evaluates
        # before the body), so a collective in the loop *predicate* (e.g. a
        # psum'd convergence residual) enters the wire census and the
        # rank-invariance check like any body collective: whether iteration
        # k's predicate even evaluates depends on iteration k-1's result,
        # so it inherits the same rank-conditional marking.
        pred_t = bool(pred_axes)
        csub = seed_from(cond_consts, cond_j.invars)
        self.ctrl.append(("while", label, pred_t))
        self.walk(cond_j, csub, record=record)
        self.ctrl.pop()
        bsub = seed_from(body_consts, body_j.invars)
        self.ctrl.append(("while", label, pred_t))
        self.walk(body_j, bsub, record=record)
        self.ctrl.pop()
        if pred_axes or any(carry_taint):
            out_axes = pred_axes
            for ax in carry_taint:
                out_axes |= ax
            for v in eqn.outvars:
                taint[v] = out_axes


def extract_collective_ir(closed_jaxpr, axis_sizes: Dict[str, int]) -> CollectiveProgram:
    """Walk a traced program into its CollectiveIR.

    ``closed_jaxpr`` is what ``jax.make_jaxpr(step_fn)(*abstract_args)``
    returns (an unjitted top-level works too); ``axis_sizes`` names the mesh
    axes collectives may span (e.g. ``dict(group.mesh.shape)``) — the walker
    sizes each descriptor's ring from it."""
    jaxpr = (
        closed_jaxpr.jaxpr
        if isinstance(closed_jaxpr, jcore.ClosedJaxpr)
        else closed_jaxpr
    )
    w = _Walk(axis_sizes)
    w.walk(jaxpr, {})
    return CollectiveProgram(collectives=w.out, axis_sizes=dict(w.axis_sizes))
