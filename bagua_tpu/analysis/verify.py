"""Entry points: verify a step program before it ever dispatches.

:func:`verify_step_program` is the pre-dispatch gate.  It traces the
engine's un-jitted sharded step (``ddp._build_sharded``) with
``jax.make_jaxpr`` over abstract ``ShapeDtypeStruct`` arguments — tracing
runs the step's Python, so the flight recorder's
:class:`~bagua_tpu.observability.flight_recorder.capture_program` context
captures the *dynamic* collective program in the same pass that yields the
jaxpr for the *static* one, and nothing executes on any device.  Over the
extracted :class:`~bagua_tpu.analysis.collective_ir.CollectiveProgram` it
runs the four checkers (:mod:`bagua_tpu.analysis.checks`) and returns a
:class:`VerifyReport`; ``report.raise_if_failed()`` is what
``BAGUA_STATIC_VERIFY=strict`` calls.

:func:`predict_flight_program` renders the IR into the exact record
templates ``ddp._flight_finalize`` produces from a live capture — same
label grammar, same enrichment fields — which is what lets check 4 compare
the two subsystems record-for-record.
"""

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import jax

from bagua_tpu.analysis.checks import (
    MODELED_ALGOS,
    Finding,
    StaticVerifyError,
    WireModelConfig,
    check_plan_conformance,
    check_rank_invariance,
    check_static_dynamic,
    check_wire_exactness,
)
from bagua_tpu.analysis.collective_ir import (
    CollectiveProgram,
    extract_collective_ir,
)
from bagua_tpu.observability.flight_recorder import capture_program
from bagua_tpu.observability.scope_grammar import format_exchange_label

__all__ = [
    "VerifyReport",
    "collect_ir",
    "predict_flight_program",
    "verify_collective_program",
    "verify_step_program",
]


@dataclasses.dataclass
class VerifyReport:
    """One verification run: every finding plus the evidence tables."""

    algo: str
    variant: str
    findings: List[Finding]
    wire_table: List[Dict]
    predicted: List[Dict]
    captured: List[Dict]
    num_collectives: int

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def raise_if_failed(self) -> "VerifyReport":
        if self.errors:
            raise StaticVerifyError(self.findings)
        return self

    def summary(self) -> str:
        if self.ok:
            return (
                f"static verify ok: algo={self.algo} variant={self.variant} "
                f"{self.num_collectives} collectives, "
                f"{len(self.wire_table)} bucket-phases"
            )
        return "; ".join(str(f) for f in self.errors)

    def to_json(self) -> Dict:
        return {
            "algo": self.algo,
            "variant": self.variant,
            "ok": self.ok,
            "num_collectives": self.num_collectives,
            "findings": [f.to_json() for f in self.findings],
            "wire_table": self.wire_table,
            "predicted_records": len(self.predicted),
            "captured_records": len(self.captured),
        }


def _abstract(tree):
    def conv(l):
        if isinstance(l, jax.ShapeDtypeStruct):
            return l
        return jax.ShapeDtypeStruct(
            jax.numpy.shape(l), jax.numpy.result_type(l)
        )

    return jax.tree.map(conv, tree)


def collect_ir(fn, args: Sequence, axis_sizes: Dict[str, int]):
    """Trace ``fn(*args)`` (args may be concrete or ``ShapeDtypeStruct``
    trees) into ``(CollectiveProgram, captured_events)`` — the static IR and
    the flight recorder's trace-time capture from the same single trace."""
    with capture_program() as events:
        closed = jax.make_jaxpr(fn)(*args)
    return extract_collective_ir(closed, axis_sizes), list(events)


def predict_flight_program(
    program: CollectiveProgram, cfg: WireModelConfig, variant: str = "default"
) -> List[Dict]:
    """The flight program the IR implies, in ``_flight_finalize``'s record
    shape: one annotate record per ``(bucket, phase)`` exchange scope, plus
    one ``phase="hop"`` ring record per quantized reduce-scatter/all-gather
    leg (bytes = the leg's summed ring-model wire bytes)."""
    plan, pv = cfg.plan, cfg.plan_version
    records: List[Dict] = []
    for (algo, b, phase), descs in program.by_bucket_phase().items():
        spec = plan.specs[b] if 0 <= b < len(plan.specs) else None
        prec = (
            cfg.precisions[b]
            if b < len(cfg.precisions) and spec is not None else "f32"
        )
        if spec is not None and spec.dtype not in ("f32", "f16", "bf16"):
            prec = "f32"
        rec = {
            "algo": algo, "bucket": b, "phase": phase,
            "nbytes": int(spec.nbytes) if spec is not None else 0,
            "precision": prec,
            "plan_version": pv, "variant": str(variant),
            "label": format_exchange_label(algo, b, phase),
        }
        if cfg.exchange_axes:
            # annotate() stamps the mesh axes the exchange rides; the
            # prediction must carry the same field for the record-for-record
            # static/dynamic comparison.
            rec["axes"] = list(cfg.exchange_axes)
        records.append(rec)
        hop_descs = [d for d in descs if d.qr and d.qr["stage"] == "hop"]
        ag_descs = [d for d in descs if d.qr and d.qr["stage"] == "ag"]
        for ring_kind, leg in (("rs", hop_descs), ("ag", ag_descs)):
            if not leg:
                continue
            bits = leg[0].qr["bits"]
            records.append({
                "algo": algo, "bucket": b, "phase": "hop",
                "ring": ring_kind, "bits": bits,
                "hops": leg[0].ring_size - 1,
                "nbytes": sum(d.wire_bytes for d in leg),
                "precision": f"int{bits}",
                "plan_version": pv, "variant": str(variant),
                "label": format_exchange_label(algo, b, "hop"),
            })
    return records


def verify_collective_program(
    program: CollectiveProgram,
    cfg: WireModelConfig,
    payload: Optional[Dict] = None,
    captured: Optional[Sequence[Dict]] = None,
    variant: str = "default",
) -> VerifyReport:
    """Run the four checkers over an already-extracted IR.  ``captured`` is
    the flight recorder's (finalized) record list for the same trace; when
    omitted — or when the algorithm's record program is not modeled — check
    4 reports an info finding instead of comparing."""
    findings = list(check_rank_invariance(program))
    wire_findings, wire_table = check_wire_exactness(program, cfg)
    findings += wire_findings
    findings += check_plan_conformance(program, cfg, payload=payload)
    predicted = predict_flight_program(program, cfg, variant=variant)
    if captured is not None and cfg.algo in MODELED_ALGOS:
        findings += check_static_dynamic(predicted, captured)
    else:
        findings.append(
            Finding(
                check="static_dynamic",
                severity="info",
                message=(
                    "no flight capture supplied"
                    if captured is None
                    else f"record program for {cfg.algo!r} is not modeled; "
                         "comparison skipped"
                ),
            )
        )
    return VerifyReport(
        algo=cfg.algo,
        variant=str(variant),
        findings=findings,
        wire_table=wire_table,
        predicted=predicted,
        captured=list(captured or ()),
        num_collectives=len(program.collectives),
    )


def verify_step_program(
    ddp,
    state,
    batch,
    variant: str = "default",
    payload: Optional[Dict] = None,
) -> VerifyReport:
    """Statically verify one step variant of a live engine, pre-dispatch.

    Traces ``ddp._build_sharded(variant)`` over abstract shapes (no device
    execution, no donation), extracts the IR, captures the flight program
    from the same trace, finalizes it through the engine's own
    ``_flight_finalize`` (single source of truth for record enrichment) and
    runs all four checks."""
    cfg = WireModelConfig.from_engine(ddp)
    sharded = ddp._build_sharded(variant)
    program, events = collect_ir(
        sharded,
        (_abstract(state), _abstract(batch)),
        dict(ddp.group.mesh.shape),
    )
    captured = list(ddp._flight_finalize(variant, events))
    return verify_collective_program(
        program, cfg, payload=payload, captured=captured, variant=variant
    )
