"""The four static checkers over a :class:`~bagua_tpu.analysis.collective_ir.CollectiveProgram`.

Each checker returns a list of :class:`Finding`; a ``severity="error"``
finding is what ``BAGUA_STATIC_VERIFY=strict`` turns into a
:class:`StaticVerifyError` *before dispatch*:

1. :func:`check_rank_invariance` — no collective may sit under a ``cond``/
   ``while`` whose predicate can depend on ``axis_index``-derived values.
   Different ranks taking different branches around a collective is the
   first-desync class the flight recorder (PR 10) can only attribute
   post-mortem; here it is a trace-time error naming the branch label.
2. :func:`check_wire_exactness` — per ``(bucket, phase)`` the IR's summed
   ring-model bytes must equal the planner's analytic wire model
   **exactly** (``ring_wire_bytes`` for quantized buckets, the
   ``2N(n-1)/n`` / ``N(n-1)/n`` / ``N(n-1)`` α–β legs otherwise).  The
   perf-audit wire census measures this; the checker proves it.
3. :func:`check_plan_conformance` — the traced per-bucket precision and
   phase sequence must match the adopted plan: every bucket present, the
   quantized-ring bits per bucket equal to the planner's
   ``bucket_precisions``, the int4 error-feedback fence
   (``holds_bucketized_state`` ⇒ no overlap, no int4 ring in an
   ``overlap`` phase), zero's rs+ag-no-allreduce contract, and — when an
   exported plan payload is supplied — a matching ``plan_version``.
4. :func:`check_static_dynamic` — the verifier's *predicted* flight
   program must equal the flight recorder's *captured* one
   record-for-record (label, bytes, precision, plan version), so the two
   subsystems certify each other.

The wire models are driven by an explicit :class:`WireModelConfig` rather
than a live engine, so adversarial tests can describe a program that was
never constructed; :meth:`WireModelConfig.from_engine` derives one from a
running :class:`~bagua_tpu.ddp.DistributedDataParallel`.

Scope: the byte/conformance contracts cover the algorithms whose wire
programs the planner prices — ``gradient_allreduce`` and ``zero`` (any
``wire_precision``, fuse mode, hierarchy).  Other algorithms' buckets are
reported as ``modeled: false`` rows (checks 1 still covers them; 3/4 run
where their contracts apply) — a deliberate scope decision documented in
``docs/static_analysis.md``.
"""

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bagua_tpu.analysis.collective_ir import CollectiveProgram
from bagua_tpu.kernels.quantized_ring import ring_wire_bytes

__all__ = [
    "CHECK_NAMES",
    "MODELED_ALGOS",
    "Finding",
    "StaticVerifyError",
    "WireModelConfig",
    "check_rank_invariance",
    "check_wire_exactness",
    "check_plan_conformance",
    "check_static_dynamic",
    "canonical_records",
]

CHECK_NAMES = (
    "rank_invariance",
    "wire_exactness",
    "plan_conformance",
    "static_dynamic",
)

#: algorithms whose full per-bucket wire/conformance contract is modeled
MODELED_ALGOS = ("gradient_allreduce", "zero")

_FLOAT_DTYPES = ("f32", "f16", "bf16")
_PRECISION_BITS = {"int8": 8, "int4": 4}


@dataclasses.dataclass
class Finding:
    """One verifier result.  ``check`` is a :data:`CHECK_NAMES` entry,
    ``label`` the source named-scope label the failure attributes to."""

    check: str
    severity: str  # "error" | "info"
    message: str
    label: str = ""
    bucket: Optional[int] = None

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        at = f" [{self.label}]" if self.label else ""
        return f"{self.check}: {self.message}{at}"


class StaticVerifyError(RuntimeError):
    """Raised under ``BAGUA_STATIC_VERIFY=strict`` — the program never
    dispatches.  Carries the error findings with check name + source label."""

    def __init__(self, findings: Sequence[Finding]):
        self.findings = [f for f in findings if f.severity == "error"]
        lines = "\n".join(f"  - {f}" for f in self.findings)
        super().__init__(
            f"static collective-program verification failed "
            f"({len(self.findings)} error(s)):\n{lines}"
        )


# ---------------------------------------------------------------------------
# Wire model configuration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class WireModelConfig:
    """Everything the analytic wire models need, detached from any engine."""

    algo: str
    plan: Any                       #: BucketPlan (specs with numel/nbytes/slots)
    n: int                          #: exchange-ring size (== gang size on 1-D meshes)
    n_intra: int = 1                #: intra-axis size (hierarchical legs)
    n_inter: int = 1
    #: mesh axes the engine's exchange is allowed to ride (named meshes);
    #: empty = unconstrained (legacy 1-D config).  The axis-conformance arm
    #: of check_plan_conformance errors on any exchange-scope collective
    #: touching an axis outside this set — "dp collectives on the dp axis
    #: only".
    exchange_axes: Tuple[str, ...] = ()
    #: every axis of a NAMED mesh (empty on legacy (inter, intra) groups) —
    #: the perflab cost bridge routes single-axis collectives to per-axis
    #: ``axis:<name>`` cost legs when this is set.
    mesh_axes: Tuple[str, ...] = ()
    precisions: Sequence[str] = ()  #: resolved per-bucket wire precision
    fuse: str = "tuple"
    hierarchical: bool = False
    wire_itemsize: Optional[int] = None  #: wire_dtype itemsize for float buckets
    compression: Optional[str] = None    #: zero's "bytegrad" (unmodeled)
    plan_version: int = 0
    overlap_enabled: bool = False
    holds_bucketized_state: bool = False
    #: the algorithm's overlap execution mode ("gradient" | "weight" |
    #: "post_step") — the bucketized-state fence only applies to the
    #: stateless per-bucket backward hook ("gradient")
    overlap_mode: str = "gradient"

    @classmethod
    def from_engine(cls, ddp) -> "WireModelConfig":
        impl, plan, group = ddp.impl, ddp.plan, ddp.group
        if plan is None:
            raise ValueError("engine has no bucket plan yet; call init() first")
        if hasattr(impl, "bucket_precisions"):
            precisions = list(impl.bucket_precisions(plan))
        else:
            precisions = ["f32"] * len(plan.specs)
        wd = getattr(impl, "wire_dtype", None)
        mesh = dict(group.mesh.shape)
        # The ring the exchange rides: every axis on legacy meshes, the data
        # axes only on named meshes (tp/sp peers each keep a full ring).
        exchange_size = getattr(group, "exchange_size", group.size)
        exchange_axes = tuple(getattr(group, "data_axes", ()) or ())
        mesh_axes = (
            tuple(group.all_axes)
            if getattr(group, "mesh_spec", None) is not None else ()
        )
        return cls(
            algo=getattr(impl, "algo_name", type(impl).__name__),
            plan=plan,
            n=exchange_size,
            n_intra=int(mesh.get("intra", 1)),
            n_inter=int(mesh.get("inter", 1)),
            exchange_axes=exchange_axes,
            mesh_axes=mesh_axes,
            precisions=precisions,
            fuse=getattr(impl, "fuse", "tuple"),
            hierarchical=bool(getattr(impl, "hierarchical", False)),
            wire_itemsize=None if wd is None else int(np.dtype(wd).itemsize),
            compression=getattr(impl, "compression", None),
            plan_version=int(ddp.plan_version),
            overlap_enabled=bool(ddp.overlap_enabled),
            holds_bucketized_state=bool(
                getattr(impl, "holds_bucketized_state", False)
            ),
            overlap_mode=getattr(impl, "overlap_mode", "gradient"),
        )

    # -- per-bucket analytic models -----------------------------------------

    def _itemsize(self, spec) -> int:
        from bagua_tpu.defs import dtype_itemsize

        native = dtype_itemsize(spec.dtype)
        if self.wire_itemsize is not None and spec.dtype in _FLOAT_DTYPES:
            return self.wire_itemsize
        return native

    def _allreduce_legs(self, payload: int) -> int:
        if self.hierarchical:
            ni, ne = self.n_intra, self.n_inter
            return (
                2 * payload * (ni - 1) // ni + 2 * payload * (ne - 1) // ne
            )
        return 2 * payload * (self.n - 1) // self.n

    def expected_bucket_bytes(self, bucket: int, phase: str) -> Optional[int]:
        """The planner's analytic wire bytes for one ``(bucket, phase)`` of
        this config's algorithm — None when the phase is unmodeled."""
        spec = self.plan.specs[bucket]
        prec = (
            self.precisions[bucket]
            if bucket < len(self.precisions) else "f32"
        )
        if self.algo == "gradient_allreduce" and phase in ("mono", "overlap"):
            if prec in _PRECISION_BITS:
                bits = _PRECISION_BITS[prec]
                if self.hierarchical:
                    # exact f32 intra sum of the flat + quantized inter ring
                    intra = 2 * spec.numel * 4 * (self.n_intra - 1) // self.n_intra
                    return intra + ring_wire_bytes(spec.numel, self.n_inter, bits)
                return ring_wire_bytes(spec.numel, self.n, bits)
            itemsize = self._itemsize(spec)
            mixed = any(p in _PRECISION_BITS for p in self.precisions)
            # variadic (unpadded) payload unless the flat buffer is
            # materialized: flat fuse on the all-f32 paths
            variadic = self.fuse == "tuple" or (mixed and phase == "mono")
            payload = (
                sum(s.numel for s in spec.slots) * itemsize
                if variadic else spec.numel * itemsize
            )
            return self._allreduce_legs(payload)
        if self.algo == "zero":
            if self.compression is not None:
                return None  # bytegrad's alltoall program: unmodeled
            if phase == "ag":
                # tiled all_gather of the (numel/n,) pending shard
                return (spec.nbytes // self.n) * (self.n - 1)
            if phase == "rs":
                if prec in _PRECISION_BITS and spec.dtype in _FLOAT_DTYPES:
                    # the quantized ring's reduce-scatter leg only
                    return ring_wire_bytes(
                        spec.numel, self.n, _PRECISION_BITS[prec]
                    ) // 2
                return spec.nbytes * (self.n - 1) // self.n
        return None


# ---------------------------------------------------------------------------
# Check 1: rank invariance
# ---------------------------------------------------------------------------


def _stale_sanctioned_ids(program: CollectiveProgram) -> set:
    """Descriptor ids the bounded-staleness sanction clears.

    A rank-conditional collective is tolerated — downgraded from error to
    info — only when (a) it carries the ``bagua_stale/tau=<k>`` scope
    marker (:func:`~bagua_tpu.observability.scope_grammar.format_stale_scope`),
    and (b) **every** sibling branch of its innermost rank-conditional
    ``cond`` moves identical wire bytes.  Under those conditions the
    branches differ in *payload* (fresh vs last-published buckets), not in
    whether the exchange runs, so ranks stay in lockstep on the wire and
    the per-round byte census is preserved exactly.  Note the engine's own
    staleness modes never trip this path at all — they gate payloads with
    elementwise ``where`` selects, not ``cond`` — so the sanction exists
    for hand-rolled bounded-staleness programs the descriptor marks
    explicitly."""
    sanctioned: set = set()
    by_cond: Dict[str, Dict[str, List]] = {}
    for d in program.collectives:
        if not d.rank_conditional or d.stale is None:
            continue
        conds = [p for p in d.path if p.startswith("cond#")]
        if not conds:
            continue
        cid, _, branch = conds[-1].partition("@")
        by_cond.setdefault(cid, {}).setdefault(branch, []).append(d)
    for branches in by_cond.values():
        if len(branches) < 2:
            continue  # single-branch: ranks could skip the exchange outright
        signatures = {
            tuple(sorted((d.primitive, d.wire_bytes) for d in descs))
            for descs in branches.values()
        }
        if len(signatures) == 1:
            for descs in branches.values():
                sanctioned.update(id(d) for d in descs)
    return sanctioned


def check_rank_invariance(program: CollectiveProgram) -> List[Finding]:
    """No collective under a control-flow predicate that can depend on
    rank-varying (``axis_index``-derived) values.

    One sanctioned exception: a collective carrying the bounded-staleness
    scope marker whose innermost rank-conditional ``cond`` has ≥2 sibling
    branches moving identical wire bytes (see
    :func:`_stale_sanctioned_ids`) is reported as ``info`` instead —
    the wire program is byte-identical either way the predicate falls."""
    out = []
    sanctioned = _stale_sanctioned_ids(program)
    for d in program.collectives:
        if not d.rank_conditional:
            continue
        if id(d) in sanctioned:
            out.append(
                Finding(
                    check="rank_invariance",
                    severity="info",
                    message=(
                        f"{d.primitive} over axes {d.axes} is "
                        f"rank-conditional but sanctioned: bounded-staleness "
                        f"marker tau={d.stale} with byte-identical sibling "
                        "branches — wire census preserved per round"
                    ),
                    label=d.label,
                    bucket=d.bucket,
                )
            )
            continue
        out.append(
            Finding(
                check="rank_invariance",
                severity="error",
                message=(
                    f"{d.primitive} over axes {d.axes} executes under a "
                    f"rank-conditional predicate ({d.cond_label or 'cond'}): "
                    "ranks can disagree on whether this collective runs — "
                    "guaranteed desync"
                ),
                label=d.label,
                bucket=d.bucket,
            )
        )
    return out


# ---------------------------------------------------------------------------
# Check 2: wire-byte exactness
# ---------------------------------------------------------------------------


def _branch_deduped_bytes(items: List[Tuple[Tuple[str, ...], int]]) -> int:
    """Wire bytes one runtime execution of a descriptor group moves.

    The walker records *every* branch of a ``cond`` but only one executes,
    so summing naively double-counts an exchange scope whose collectives
    appear in sibling branches.  ``items`` pairs each descriptor's
    cond-frames path (the ``"cond#<eqn>@<branch>"`` entries of
    ``CollectiveDescriptor.path``) with its wire bytes; descriptors in
    sibling branches of the same cond contribute the **max** across
    branches — exact when the branches move equal bytes (the only layout
    the exactness contract can hold for anyway), best-effort otherwise."""
    total = 0
    by_cond: Dict[str, Dict[str, List[Tuple[Tuple[str, ...], int]]]] = {}
    for path, nbytes in items:
        if not path:
            total += nbytes
            continue
        cid, _, branch = path[0].partition("@")
        by_cond.setdefault(cid, {}).setdefault(branch, []).append(
            (path[1:], nbytes)
        )
    for branches in by_cond.values():
        total += max(_branch_deduped_bytes(sub) for sub in branches.values())
    return total


def check_wire_exactness(
    program: CollectiveProgram, cfg: WireModelConfig
) -> Tuple[List[Finding], List[Dict]]:
    """Summed IR wire bytes per ``(bucket, phase)`` vs the analytic model
    (mutually-exclusive cond branches de-duplicated, see
    :func:`_branch_deduped_bytes`).

    Returns ``(findings, table)`` — the table has one row per labeled
    bucket-phase group with ``observed``/``expected``/``modeled`` fields
    (``STATIC_VERIFY.json`` commits it)."""
    findings: List[Finding] = []
    table: List[Dict] = []
    for (algo, bucket, phase), descs in program.by_bucket_phase().items():
        observed = _branch_deduped_bytes([
            (
                tuple(p for p in d.path if p.startswith("cond#")),
                d.wire_bytes,
            )
            for d in descs
        ])
        expected = (
            cfg.expected_bucket_bytes(bucket, phase)
            if algo == cfg.algo and bucket < len(cfg.plan.specs) else None
        )
        row = {
            "algo": algo,
            "bucket": bucket,
            "phase": phase,
            "collectives": len(descs),
            "observed_bytes": observed,
            "expected_bytes": expected,
            "modeled": expected is not None,
        }
        table.append(row)
        if expected is not None and observed != expected:
            findings.append(
                Finding(
                    check="wire_exactness",
                    severity="error",
                    message=(
                        f"bucket {bucket} phase {phase!r}: traced wire bytes "
                        f"{observed} != planner model {expected} "
                        f"(delta {observed - expected:+d})"
                    ),
                    label=descs[0].label,
                    bucket=bucket,
                )
            )
    return findings, table


# ---------------------------------------------------------------------------
# Check 3: plan conformance
# ---------------------------------------------------------------------------


def _observed_precisions(program: CollectiveProgram, cfg: WireModelConfig) -> Dict[int, str]:
    """Per-bucket precision the trace actually uses: the quantized-ring
    sub-scopes' bit width, f32 in their absence."""
    out: Dict[int, str] = {}
    for d in program.labeled():
        if d.algo != cfg.algo:
            continue
        b = d.bucket
        if d.qr is not None:
            out[b] = f"int{d.qr['bits']}"
        else:
            out.setdefault(b, "f32")
    return out


def check_plan_conformance(
    program: CollectiveProgram,
    cfg: WireModelConfig,
    payload: Optional[Dict] = None,
) -> List[Finding]:
    """Traced precision/phase sequence vs the adopted plan (+ optional
    exported plan payload for version conformance)."""
    findings: List[Finding] = []
    groups = program.by_bucket_phase()

    # stale / mismatched plan payload
    if payload is not None:
        pv = int(payload.get("plan_version", -1))
        if pv != cfg.plan_version:
            findings.append(
                Finding(
                    check="plan_conformance",
                    severity="error",
                    message=(
                        f"plan payload carries plan_version={pv} but the "
                        f"engine adopted plan_version={cfg.plan_version}: "
                        "stale plan — re-export before verifying against it"
                    ),
                )
            )
        buckets = payload.get("buckets")
        if buckets is not None and len(buckets) != len(cfg.plan.specs):
            findings.append(
                Finding(
                    check="plan_conformance",
                    severity="error",
                    message=(
                        f"plan payload declares {len(buckets)} buckets, "
                        f"engine plan has {len(cfg.plan.specs)}"
                    ),
                )
            )

    # the int4 error-feedback fence: bucketized residual state cannot ride
    # the stateless per-bucket backward hook.  Only the "gradient" overlap
    # mode uses that hook — "post_step"/"weight" algorithms keep their
    # bucketized state on the ordinary step path and overlap legitimately.
    if (
        cfg.holds_bucketized_state
        and cfg.overlap_enabled
        and cfg.overlap_mode == "gradient"
    ):
        findings.append(
            Finding(
                check="plan_conformance",
                severity="error",
                message=(
                    "algorithm holds bucketized state (int4 qr_residual) "
                    "with overlap enabled — the residual cannot thread "
                    "through the stateless backward hook"
                ),
            )
        )
    for (algo, bucket, phase), descs in groups.items():
        if algo != cfg.algo:
            continue
        if phase == "overlap" and any(
            d.qr is not None and d.qr["bits"] == 4 for d in descs
        ):
            findings.append(
                Finding(
                    check="plan_conformance",
                    severity="error",
                    message=(
                        f"bucket {bucket}: int4 quantized ring inside an "
                        "overlap phase — int4 error feedback is fenced to "
                        "the monolithic path"
                    ),
                    label=descs[0].label,
                    bucket=bucket,
                )
            )

    # axis conformance (named meshes): every collective inside one of this
    # algorithm's exchange scopes must ride the exchange axes only — a dp
    # collective leaking onto a model axis (tp/sp) would silently average
    # across tensor-parallel shards.
    if cfg.exchange_axes:
        allowed = set(cfg.exchange_axes)
        for (algo, bucket, phase), descs in groups.items():
            if algo != cfg.algo:
                continue
            for d in descs:
                stray = [a for a in d.axes if a not in allowed]
                if stray:
                    findings.append(
                        Finding(
                            check="plan_conformance",
                            severity="error",
                            message=(
                                f"bucket {bucket} phase {phase!r}: "
                                f"{d.primitive} rides mesh axes "
                                f"{tuple(d.axes)} but the exchange is "
                                f"confined to {cfg.exchange_axes} — stray "
                                f"axes {tuple(stray)}"
                            ),
                            label=d.label,
                            bucket=bucket,
                        )
                    )

    if cfg.algo not in MODELED_ALGOS:
        return findings

    # per-bucket precision vs the planner's resolution
    observed = _observed_precisions(program, cfg)
    for b, spec in enumerate(cfg.plan.specs):
        planned = cfg.precisions[b] if b < len(cfg.precisions) else "f32"
        if cfg.algo == "zero" and (
            spec.dtype not in _FLOAT_DTYPES or cfg.compression is not None
        ):
            planned = "f32"
        got = observed.get(b)
        if got is None:
            findings.append(
                Finding(
                    check="plan_conformance",
                    severity="error",
                    message=(
                        f"bucket {b} never appears in the traced exchange "
                        "program (missing collective)"
                    ),
                    bucket=b,
                )
            )
            continue
        if got != planned:
            findings.append(
                Finding(
                    check="plan_conformance",
                    severity="error",
                    message=(
                        f"bucket {b}: traced wire precision {got} != "
                        f"planned {planned}"
                    ),
                    bucket=b,
                )
            )

    # zero's contract: one rs + one ag per bucket, and never an all-reduce
    # inside an exchange scope (the whole point of sharding the update)
    if cfg.algo == "zero":
        for b in range(len(cfg.plan.specs)):
            for ph in ("rs", "ag"):
                if (cfg.algo, b, ph) not in groups:
                    findings.append(
                        Finding(
                            check="plan_conformance",
                            severity="error",
                            message=f"bucket {b}: zero is missing its "
                                    f"{ph!r} leg",
                            bucket=b,
                        )
                    )
        for (algo, bucket, phase), descs in groups.items():
            bad = [d for d in descs if d.primitive in ("psum", "pmax", "pmin")]
            if algo == cfg.algo and bad:
                findings.append(
                    Finding(
                        check="plan_conformance",
                        severity="error",
                        message=(
                            f"bucket {bucket} phase {phase!r}: {bad[0].primitive} "
                            "(all-reduce) inside a zero exchange scope — the "
                            "rs+ag contract forbids full-bucket reductions"
                        ),
                        label=bad[0].label,
                        bucket=bucket,
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# Check 4: static/dynamic agreement
# ---------------------------------------------------------------------------


def canonical_records(records: Sequence[Dict]) -> List[Dict]:
    """Order-insensitive canonical form of a flight program: jaxpr equation
    order and Python trace order legitimately differ (custom_vjp
    transposition reorders the backward), so both sides sort on the stable
    identity key before the record-for-record comparison."""
    return sorted(
        (dict(r) for r in records),
        key=lambda r: (
            int(r.get("bucket", -1)),
            str(r.get("phase", "")),
            str(r.get("ring", "")),
            int(r.get("bits", 0)),
            str(r.get("label", "")),
        ),
    )


def check_static_dynamic(
    predicted: Sequence[Dict], captured: Sequence[Dict]
) -> List[Finding]:
    """Predicted flight program (from the IR) vs the recorder's captured
    one — must agree label-for-label, byte-for-byte."""
    pred = canonical_records(predicted)
    capt = canonical_records(captured)
    findings: List[Finding] = []
    if len(pred) != len(capt):
        findings.append(
            Finding(
                check="static_dynamic",
                severity="error",
                message=(
                    f"predicted program has {len(pred)} records, flight "
                    f"recorder captured {len(capt)}"
                ),
            )
        )
    for p, c in zip(pred, capt):
        if p == c:
            continue
        keys = sorted(set(p) | set(c))
        diffs = [
            f"{k}: predicted={p.get(k)!r} captured={c.get(k)!r}"
            for k in keys
            if p.get(k) != c.get(k)
        ]
        findings.append(
            Finding(
                check="static_dynamic",
                severity="error",
                message=(
                    f"record mismatch ({'; '.join(diffs)})"
                ),
                label=str(c.get("label", p.get("label", ""))),
                bucket=c.get("bucket", p.get("bucket")),
            )
        )
    return findings
