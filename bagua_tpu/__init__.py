"""bagua_tpu: a TPU-native distributed training acceleration framework.

A from-scratch JAX/XLA/Pallas/pjit redesign with the capabilities of
BaguaSys/bagua (see SURVEY.md): pluggable data-parallel relaxation algorithms
(centralized/decentralized x full/low precision x sync/async + QAdam) over a
bucketed communication layer on a hierarchical ``(inter, intra)`` device mesh,
plus autotuning, fused optimizer, MoE expert parallelism, checkpointing, and
an elastic launcher.
"""

import bagua_tpu.compat  # noqa: F401  (must run first: grafts jax.shard_map/axis_size on old JAX)
from bagua_tpu.version import __version__  # noqa: F401
from bagua_tpu.defs import ReduceOp  # noqa: F401
from bagua_tpu.mesh import MeshSpec  # noqa: F401
from bagua_tpu.communication import (  # noqa: F401
    BaguaProcessGroup,
    init_process_group,
    is_initialized,
    get_default_group,
    new_group,
    allreduce,
    allgather,
    reducescatter,
    broadcast,
    alltoall,
    reduce,
    scatter,
    gather,
    barrier,
    broadcast_object,
    local_ranks,
)
from bagua_tpu.env import (  # noqa: F401
    get_rank,
    get_world_size,
    get_local_rank,
    get_local_size,
)
