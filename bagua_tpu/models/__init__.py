"""Model zoo used by the examples, tests and benchmarks."""

from bagua_tpu.models.mlp import init_mlp, mlp_apply  # noqa: F401
