"""Llama-style causal decoder: RMSNorm, rotary position embeddings (RoPE),
SwiGLU MLP, and grouped-query attention (GQA).

Beyond the reference (its model zoo stops at the VGG/BERT example tier) —
included to show the parallel substrate carries contemporary decoder
architectures unchanged: the blocks compose the same Megatron TP pairing
(`parallel/tensor_parallel.py`), ring-attention SP with contiguous or zigzag
layouts (`parallel/ring_attention.py`), and the GPT model's SP position /
seam-masked LM loss machinery (`models/gpt.py`) — one TP allreduce per
attention block and per MLP, RoPE applied to each rank's *global* token
positions before the ring exchange.
"""

import dataclasses
from typing import Any, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from bagua_tpu.models.gpt import _sp_positions, lm_loss_fn  # noqa: F401  (re-exported)
from bagua_tpu.parallel.ring_attention import _block_attention_local, ring_attention
from bagua_tpu.parallel.tensor_parallel import ColumnParallelDense, RowParallelDense


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    #: < num_heads enables grouped-query attention; K/V heads are shared by
    #: ``num_heads // num_kv_heads`` query heads each
    num_kv_heads: int = 32
    intermediate_size: int = 11008
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tp_size: int = 1
    tp_axis: Union[str, Tuple[str, ...]] = "tp"
    sp_axis: Union[str, Tuple[str, ...], None] = None
    #: "contiguous" or "zigzag" (see GPTConfig.sp_layout)
    sp_layout: str = "contiguous"
    compute_dtype: Any = jnp.float32

    def __post_init__(self):
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"hidden_size ({self.hidden_size}) must divide by num_heads "
                f"({self.num_heads})"
            )
        if (self.hidden_size // self.num_heads) % 2:
            raise ValueError(
                f"head_dim ({self.hidden_size // self.num_heads}) must be even "
                "(RoPE rotates half-dimension pairs)"
            )
        if self.num_heads % self.num_kv_heads:
            raise ValueError(
                f"num_heads ({self.num_heads}) must divide by num_kv_heads "
                f"({self.num_kv_heads})"
            )
        for field, n in (("num_heads", self.num_heads), ("num_kv_heads", self.num_kv_heads)):
            if n % self.tp_size:
                raise ValueError(
                    f"{field} ({n}) must divide by tp_size ({self.tp_size})"
                )


def llama_7b_config(**overrides) -> LlamaConfig:
    """The classic 7B shape (32 layers x 4096 hidden, MHA)."""
    return LlamaConfig(**overrides)


def llama_test_config(**overrides) -> LlamaConfig:
    kwargs = dict(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4, num_kv_heads=2,
        intermediate_size=48, max_position_embeddings=64,
    )
    kwargs.update(overrides)
    return LlamaConfig(**kwargs)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        dtype = x.dtype
        x = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        y = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + self.eps)
        return (y * scale).astype(dtype)


def apply_rope(x, positions, theta: float):
    """Rotate interleaved feature pairs of ``x`` (b, t, h, d) by the angles of
    ``positions`` (t,).  Computed in f32, cast back to ``x.dtype``."""
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {d}")
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]  # (t, d/2)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., ::2], xf[..., 1::2]
    rotated = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.reshape(x.shape).astype(x.dtype)


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, t, _ = x.shape
        head_dim = cfg.hidden_size // cfg.num_heads
        local_q = cfg.num_heads // cfg.tp_size
        local_kv = cfg.num_kv_heads // cfg.tp_size

        def proj(n_heads, name):
            return ColumnParallelDense(
                n_heads * head_dim, cfg.tp_size, cfg.tp_axis, use_bias=False,
                dtype=cfg.compute_dtype, name=name,
            )(x)

        q = proj(cfg.num_heads, "q").reshape(b, t, local_q, head_dim)
        k = proj(cfg.num_kv_heads, "k").reshape(b, t, local_kv, head_dim)
        v = proj(cfg.num_kv_heads, "v").reshape(b, t, local_kv, head_dim)

        # RoPE on the *global* positions of this rank's tokens — under SP the
        # K/V blocks carry their rotation with them around the ring.
        pos = _sp_positions(cfg, t)
        q = apply_rope(q, pos, cfg.rope_theta)
        k = apply_rope(k, pos, cfg.rope_theta)

        if cfg.sp_axis is not None:
            # GQA rides the ring unrepeated: kv_groups expands the shared
            # K/V heads inside the per-block compute, so the ring hops carry
            # 1/group of the K/V bytes.
            ctx = ring_attention(
                q, k, v, axis_name=cfg.sp_axis, causal=True, layout=cfg.sp_layout,
                kv_groups=local_q // local_kv,
            )
        else:
            if local_q != local_kv:  # local path: expand before the oracle
                k = jnp.repeat(k, local_q // local_kv, axis=2)
                v = jnp.repeat(v, local_q // local_kv, axis=2)
            ctx = _block_attention_local(q, k, v, causal=True)
        return RowParallelDense(
            cfg.hidden_size, cfg.tp_size, cfg.tp_axis, use_bias=False,
            dtype=cfg.compute_dtype, name="out",
        )(ctx.reshape(b, t, local_q * head_dim))


class LlamaMLP(nn.Module):
    """SwiGLU: down(silu(gate(x)) * up(x)) — two column projections, one row
    projection, one TP allreduce total."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        col = lambda name: ColumnParallelDense(
            cfg.intermediate_size, cfg.tp_size, cfg.tp_axis, use_bias=False,
            dtype=cfg.compute_dtype, name=name,
        )
        h = jax.nn.silu(col("gate")(x)) * col("up")(x)
        return RowParallelDense(
            cfg.hidden_size, cfg.tp_size, cfg.tp_axis, use_bias=False,
            dtype=cfg.compute_dtype, name="down",
        )(h)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        x = x + LlamaAttention(self.cfg, name="attn")(
            RMSNorm(self.cfg.norm_eps, name="attn_norm")(x)
        )
        return x + LlamaMLP(self.cfg, name="mlp")(
            RMSNorm(self.cfg.norm_eps, name="mlp_norm")(x)
        )


class LlamaModel(nn.Module):
    """Causal LM: embed -> pre-norm blocks -> RMSNorm -> untied f32 LM head.
    Output: (b, t, vocab) logits."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.cfg
        # RoPE itself is unbounded, but the config's trained context length
        # is still a real contract — enforce it against the *global* sequence
        # (sp axis size x local length, both static).
        try:
            from bagua_tpu.communication import axis_size

            axes = (cfg.sp_axis,) if isinstance(cfg.sp_axis, str) else cfg.sp_axis
            sp = axis_size(axes) if cfg.sp_axis is not None else 1
        except NameError:
            sp = 1
        t_global = sp * input_ids.shape[1]
        if t_global > cfg.max_position_embeddings:
            raise ValueError(
                f"global sequence length {t_global} exceeds the configured "
                f"max_position_embeddings ({cfg.max_position_embeddings})"
            )
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="embed")(input_ids)
        x = x.astype(cfg.compute_dtype)
        for i in range(cfg.num_layers):
            x = LlamaBlock(cfg, name=f"block_{i}")(x)
        x = RMSNorm(cfg.norm_eps, name="final_norm")(x.astype(jnp.float32))
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                        name="lm_head")(x)


# ``lm_loss_fn`` (imported from models.gpt) works unchanged: it reads only
# ``model.cfg.sp_axis`` / ``sp_layout`` and ``model.apply``, including the
# zigzag seam masking and its degenerate-layout fallback.
llama_loss_fn = lm_loss_fn
