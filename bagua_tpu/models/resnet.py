"""ResNet family (ResNet-50 is a BASELINE.json config: decentralized SGD).

Standard bottleneck ResNet in flax, NHWC, optional bfloat16 compute, and
optional cross-replica SyncBatchNorm (``bagua_tpu.contrib.sync_batchnorm``)
so statistics match large-batch multi-chip training.
"""

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from bagua_tpu.contrib.sync_batchnorm import SyncBatchNorm


class BottleneckBlock(nn.Module):
    features: int
    strides: int = 1
    compute_dtype: Any = jnp.float32
    sync_bn: bool = False

    def _norm(self, name):
        if self.sync_bn:
            return SyncBatchNorm(name=name)
        return nn.BatchNorm(use_running_average=False, momentum=0.9, name=name)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (1, 1), dtype=self.compute_dtype, use_bias=False)(x)
        y = jax.nn.relu(self._norm("bn1")(y))
        y = nn.Conv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            padding=1, dtype=self.compute_dtype, use_bias=False,
        )(y)
        y = jax.nn.relu(self._norm("bn2")(y))
        y = nn.Conv(self.features * 4, (1, 1), dtype=self.compute_dtype, use_bias=False)(y)
        y = self._norm("bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features * 4, (1, 1), strides=(self.strides, self.strides),
                dtype=self.compute_dtype, use_bias=False, name="proj",
            )(residual)
            residual = self._norm("bn_proj")(residual)
        return jax.nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    compute_dtype: Any = jnp.float32
    sync_bn: bool = False

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.compute_dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=3, use_bias=False,
                    dtype=self.compute_dtype)(x)
        if self.sync_bn:
            x = SyncBatchNorm(name="bn_init")(x)
        else:
            x = nn.BatchNorm(use_running_average=False, momentum=0.9, name="bn_init")(x)
        x = jax.nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(self.stage_sizes):
            for b in range(n_blocks):
                strides = 2 if i > 0 and b == 0 else 1
                x = BottleneckBlock(
                    64 * 2 ** i, strides=strides,
                    compute_dtype=self.compute_dtype, sync_bn=self.sync_bn,
                    name=f"stage{i}_block{b}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x.astype(jnp.float32))


def resnet50(num_classes: int = 1000, compute_dtype=jnp.float32, sync_bn: bool = False) -> ResNet:
    return ResNet([3, 4, 6, 3], num_classes, compute_dtype, sync_bn)


def init_resnet50(key, image_size: int = 224, num_classes: int = 1000, compute_dtype=jnp.float32, sync_bn=False):
    model = resnet50(num_classes, compute_dtype, sync_bn)
    variables = model.init(key, jnp.zeros((1, image_size, image_size, 3), jnp.float32))
    return model, variables


def resnet_loss_fn(model: ResNet):
    """Cross-entropy.  The DDP params tree holds both ``params`` and
    ``batch_stats``; pass ``dp_filter=lambda n: "batch_stats" not in n`` to
    the engine so the (gradient-free) BN statistics are neither bucketed nor
    allreduced.  Stats updates inside the loss are dropped (deterministic
    benchmark mode, matching the reference's synthetic benchmark)."""

    def loss_fn(params, batch):
        x, y = batch
        logits, _ = model.apply(
            {"params": params["params"], "batch_stats": params["batch_stats"]},
            x, mutable=["batch_stats"],
        )
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    return loss_fn
