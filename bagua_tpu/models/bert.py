"""BERT encoder family (BERT-Large is the reference's second headline
benchmark: 128-GPU finetune, ``README.md:50-53``).

TPU-native flax implementation with composable parallelism:

* **TP**: attention QKV is column-parallel (heads sharded over ``tp``), the
  output projection row-parallel; the FFN is a Column→Row pair — two forward
  allreduces per layer, Megatron-style.
* **SP (long context)**: the sequence dimension is sharded over ``sp`` and
  attention runs as ring attention (``bagua_tpu.parallel.ring_attention``);
  position embeddings are offset by the rank's global block start.
* **DP**: comes from the engine (batch sharded over the group axes).

``tp_size`` is static so parameter shapes are rank-local; axes are checked
at apply time.
"""

import dataclasses
from typing import Any, Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from bagua_tpu.parallel.ring_attention import ring_attention, _block_attention_local
from bagua_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    ParallelMLP,
    RowParallelDense,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    # parallelism
    tp_size: int = 1
    tp_axis: Union[str, Tuple[str, ...]] = "tp"
    sp_axis: Union[str, Tuple[str, ...], None] = None  # ring attention when set
    compute_dtype: Any = jnp.float32
    #: rematerialize each layer's activations in the backward pass
    #: (jax.checkpoint) — trades FLOPs for HBM, the standard TPU memory lever
    remat: bool = False


def bert_large_config(**overrides) -> BertConfig:
    return BertConfig(**overrides)


def bert_base_config(**overrides) -> BertConfig:
    return BertConfig(
        hidden_size=768, num_layers=12, num_heads=12, intermediate_size=3072, **overrides
    )


def _sp_offset(cfg: BertConfig, t_local: int):
    """Global position offset of this rank's sequence block under SP."""
    if cfg.sp_axis is None:
        return 0
    try:
        from bagua_tpu.communication import rank_id

        return rank_id(
            (cfg.sp_axis,) if isinstance(cfg.sp_axis, str) else cfg.sp_axis
        ) * t_local
    except NameError:
        return 0


class BertSelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask=None):
        cfg = self.cfg
        b, t, _ = x.shape
        if cfg.num_heads % cfg.tp_size != 0:
            raise ValueError("num_heads must divide by tp_size")
        local_heads = cfg.num_heads // cfg.tp_size
        head_dim = cfg.hidden_size // cfg.num_heads

        qkv = ColumnParallelDense(
            3 * cfg.hidden_size, cfg.tp_size, cfg.tp_axis, dtype=cfg.compute_dtype,
            name="qkv",
        )(x)
        qkv = qkv.reshape(b, t, 3, local_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

        if cfg.sp_axis is not None:
            ctx = ring_attention(q, k, v, axis_name=cfg.sp_axis, causal=False, kv_mask=mask)
        else:
            ctx = _block_attention_local(q, k, v, causal=False, kv_mask=mask)
        ctx = ctx.reshape(b, t, local_heads * head_dim)
        return RowParallelDense(
            cfg.hidden_size, cfg.tp_size, cfg.tp_axis, dtype=cfg.compute_dtype,
            name="out",
        )(ctx)


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask=None):
        cfg = self.cfg
        attn = BertSelfAttention(cfg, name="attention")(x, mask)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_attn")(x + attn)
        ffn = ParallelMLP(
            cfg.intermediate_size, cfg.hidden_size, cfg.tp_size, cfg.tp_axis,
            dtype=cfg.compute_dtype, name="mlp",
        )(x)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_ffn")(x + ffn)


class BertModel(nn.Module):
    """Encoder producing final hidden states ``(B, T_local, H)``."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        cfg = self.cfg
        b, t = input_ids.shape
        word = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="word_embeddings")(input_ids)
        pos_ids = jnp.arange(t)[None, :] + _sp_offset(cfg, t)
        pos = nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size, name="position_embeddings"
        )(pos_ids)
        x = word + pos
        if token_type_ids is not None:
            x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size, name="token_type_embeddings")(
                token_type_ids
            )
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_embed")(x)
        x = x.astype(cfg.compute_dtype)
        layer_cls = nn.remat(BertLayer) if cfg.remat else BertLayer
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, name=f"layer_{i}")(x, attention_mask)
        return x.astype(jnp.float32)


class BertForPreTraining(nn.Module):
    """Encoder + MLM head (untied decoder)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None):
        h = BertModel(self.cfg, name="bert")(input_ids, token_type_ids)
        h = nn.Dense(self.cfg.hidden_size, name="mlm_transform")(h)
        h = jax.nn.gelu(h)
        h = nn.LayerNorm(epsilon=self.cfg.layer_norm_eps, name="mlm_ln")(h)
        return nn.Dense(self.cfg.vocab_size, name="mlm_decoder")(h)


def mlm_loss_fn(model: BertForPreTraining):
    """Masked-LM cross entropy over all positions (synthetic-benchmark style)."""

    def loss_fn(params, batch):
        input_ids, labels = batch
        logits = model.apply({"params": params}, input_ids)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], axis=-1))

    return loss_fn
