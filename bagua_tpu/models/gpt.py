"""GPT-style causal decoder with optional ring-attention sequence parallelism
— the long-context demonstration model (causal ring attention over the ``sp``
axis lets context length scale with the number of chips)."""

import dataclasses
from typing import Any, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from bagua_tpu.parallel.ring_attention import ring_attention, _block_attention_local
from bagua_tpu.parallel.tensor_parallel import ColumnParallelDense, ParallelMLP, RowParallelDense


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 2048
    tp_size: int = 1
    tp_axis: Union[str, Tuple[str, ...]] = "tp"
    sp_axis: Union[str, Tuple[str, ...], None] = None
    #: "contiguous" or "zigzag" — the balanced causal ring layout; feed
    #: token ids permuted with ``ring_attention.zigzag_order`` and the model
    #: assigns the matching global positions (see docs/parallelism.md)
    sp_layout: str = "contiguous"
    compute_dtype: Any = jnp.float32


def _zigzag_active(cfg: GPTConfig) -> bool:
    """Is the zigzag layout actually in effect (axis bound, >1 rank)?  With
    the ``sp`` axis unbound (single-device eval/debug outside shard_map) or of
    size 1, zigzag degenerates to the identity layout — positions, attention,
    AND the loss seam mask must all take the contiguous path together."""
    if cfg.sp_axis is None or cfg.sp_layout != "zigzag":
        return False
    try:
        from bagua_tpu.communication import axis_size

        axes = (cfg.sp_axis,) if isinstance(cfg.sp_axis, str) else cfg.sp_axis
        return axis_size(axes) > 1
    except NameError:
        return False


def _sp_positions(cfg: GPTConfig, t_local: int):
    """Global position ids of this rank's local tokens, shape (t_local,)."""
    if cfg.sp_axis is None:
        return jnp.arange(t_local)
    try:
        from bagua_tpu.communication import axis_size, rank_id

        axes = (cfg.sp_axis,) if isinstance(cfg.sp_axis, str) else cfg.sp_axis
        r = rank_id(axes)
        if _zigzag_active(cfg):
            if t_local % 2:
                # fail here, with the real constraint, rather than as an
                # opaque broadcast error at the position-embedding add
                raise ValueError(
                    f"zigzag sp layout needs an even local sequence length, "
                    f"got {t_local}"
                )
            sp = axis_size(axes)
            t2 = t_local // 2
            return jnp.concatenate([
                r * t2 + jnp.arange(t2),
                (2 * sp - 1 - r) * t2 + jnp.arange(t2),
            ])
        return r * t_local + jnp.arange(t_local)
    except NameError:
        return jnp.arange(t_local)


class GPTBlock(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, t, _ = x.shape
        local_heads = cfg.num_heads // cfg.tp_size
        head_dim = cfg.hidden_size // cfg.num_heads

        h = nn.LayerNorm(name="ln1")(x)
        qkv = ColumnParallelDense(
            3 * cfg.hidden_size, cfg.tp_size, cfg.tp_axis, dtype=cfg.compute_dtype, name="qkv"
        )(h).reshape(b, t, 3, local_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.sp_axis is not None:
            ctx = ring_attention(
                q, k, v, axis_name=cfg.sp_axis, causal=True, layout=cfg.sp_layout
            )
        else:
            ctx = _block_attention_local(q, k, v, causal=True)
        attn = RowParallelDense(
            cfg.hidden_size, cfg.tp_size, cfg.tp_axis, dtype=cfg.compute_dtype, name="out"
        )(ctx.reshape(b, t, local_heads * head_dim))
        x = x + attn
        h = nn.LayerNorm(name="ln2")(x)
        return x + ParallelMLP(
            4 * cfg.hidden_size, cfg.hidden_size, cfg.tp_size, cfg.tp_axis,
            dtype=cfg.compute_dtype, name="mlp",
        )(h)


class GPTModel(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.cfg
        b, t = input_ids.shape
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="wte")(input_ids)
        pos = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size, name="wpe")(
            _sp_positions(cfg, t)[None, :]
        )
        x = (x + pos).astype(cfg.compute_dtype)
        for i in range(cfg.num_layers):
            x = GPTBlock(cfg, name=f"block_{i}")(x)
        x = nn.LayerNorm(name="ln_f")(x.astype(jnp.float32))
        wte = self.variables["params"]["wte"]["embedding"]
        return x @ wte.T  # tied LM head


def lm_loss_fn(model: GPTModel):
    """Next-token cross entropy (within the local block under SP).  With
    ``sp_layout="zigzag"`` the two local half-blocks are globally
    non-adjacent, so the mid-block seam pair (local ``t2-1 -> t2``) is a
    wrong prediction target — it is masked out of the mean."""
    cfg = model.cfg

    def loss_fn(params, batch):
        ids = batch
        logits = model.apply({"params": params}, ids)
        logp = jax.nn.log_softmax(logits[:, :-1])
        nll = -jnp.take_along_axis(logp, ids[:, 1:, None], axis=-1)[..., 0]
        if _zigzag_active(cfg):
            t = ids.shape[1]
            if t < 4:
                # t == 2 would leave zero targets after the seam mask and
                # divide by zero (NaN loss) — fail with the real constraint.
                raise ValueError(
                    f"zigzag LM loss needs a local sequence length >= 4 "
                    f"(seam masking leaves no targets at {t})"
                )
            keep = jnp.arange(t - 1) != (t // 2 - 1)  # drop the seam pair
            return jnp.sum(nll * keep[None]) / (nll.shape[0] * (t - 2))
        return jnp.mean(nll)

    return loss_fn
