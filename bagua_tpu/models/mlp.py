"""Minimal MLP used by algorithm-correctness tests.

The analog of the small nets in the reference's algorithm tests
(``tests/torch_api/test_gradient_allreduce.py:21-35``): two hidden layers,
plain pytree params, pure functions — so tests don't depend on a module
framework and oracles are easy to write in numpy.
"""

from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp


def init_mlp(key, sizes: Sequence[int]) -> Dict[str, Dict[str, jnp.ndarray]]:
    """He-initialized MLP: ``sizes = [in, h1, ..., out]``."""
    params = {}
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (k, (fan_in, fan_out)) in enumerate(zip(keys, zip(sizes[:-1], sizes[1:]))):
        params[f"layer{i}"] = {
            "w": jax.random.normal(k, (fan_in, fan_out), jnp.float32)
            * jnp.sqrt(2.0 / fan_in),
            "b": jnp.zeros((fan_out,), jnp.float32),
        }
    return params


def mlp_apply(params, x: jnp.ndarray) -> jnp.ndarray:
    n_layers = len(params)
    for i in range(n_layers):
        layer = params[f"layer{i}"]
        x = x @ layer["w"] + layer["b"]
        if i < n_layers - 1:
            x = jax.nn.relu(x)
    return x


def mse_loss(params, batch) -> jnp.ndarray:
    x, y = batch
    pred = mlp_apply(params, x)
    return jnp.mean((pred - y) ** 2)


def softmax_loss(params, batch) -> jnp.ndarray:
    x, y = batch
    logits = mlp_apply(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
