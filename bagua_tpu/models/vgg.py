"""VGG16 — the reference's headline benchmark model.

The reference benchmarks VGG16 with ``examples/benchmark/synthetic_benchmark.py``
(batch 32/GPU, CI thresholds in ``.buildkite/scripts/benchmark_master.sh:81-83``).
Implemented in flax.linen, NHWC (TPU-native layout), with an option to run the
conv/matmul compute in bfloat16 (MXU-friendly) while keeping parameters and
the loss in float32.
"""

from typing import Any, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

# 'M' = 2x2 max pool; ints = conv output channels (VGG16 = config D)
VGG16_CFG: Sequence[Union[str, int]] = (
    64, 64, "M",
    128, 128, "M",
    256, 256, 256, "M",
    512, 512, 512, "M",
    512, 512, 512, "M",
)


class VGG(nn.Module):
    num_classes: int = 1000
    cfg: Sequence[Union[str, int]] = VGG16_CFG
    compute_dtype: Any = jnp.float32
    classifier_width: int = 4096

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.compute_dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), padding=1, dtype=self.compute_dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.classifier_width, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.classifier_width, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)


def vgg16(num_classes: int = 1000, compute_dtype=jnp.float32) -> VGG:
    return VGG(num_classes=num_classes, compute_dtype=compute_dtype)


def init_vgg16(key, image_size: int = 224, num_classes: int = 1000, compute_dtype=jnp.float32):
    model = vgg16(num_classes, compute_dtype)
    params = model.init(key, jnp.zeros((1, image_size, image_size, 3), jnp.float32))
    return model, params["params"]


def vgg_loss_fn(model: VGG):
    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply({"params": params}, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    return loss_fn
