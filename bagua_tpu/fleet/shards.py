"""Consistent-hash sharded control plane.

One :class:`~bagua_tpu.fleet.control_plane.FleetControlPlane` process was
validated at 8 gangs; "millions of users" is 2–3 orders of magnitude more
tenants.  The existing :class:`GangNamespace` isolation is the natural
cut: nothing crosses gang boundaries except the plan cache and the
remediation tier, both of which are keyed — so the fleet shards cleanly
by key.

* **Gang ops** (rendezvous, KV, blobs, spans, incidents, directives,
  admission, leases) route by ``hash("gang:<gang_id>")`` — a gang's whole
  namespace lives on exactly one shard, so every per-gang invariant the
  unsharded plane guarantees holds unchanged.
* **Plan ops** (the cross-gang cache + its quarantine/canary lifecycle)
  route by ``hash("plan:<cache_key>")`` — every gang looking up the same
  (fingerprint, topology, algorithm, wire_precision) tuple lands on the
  same shard, so adoption journaling, canary cohorts, and quarantine are
  exactly as coherent as on one plane.
* **``/fleet/*`` reads** (scheduler view, gang list, incidents, metrics,
  dump) fan out to every shard and merge — gang ids are disjoint across
  shards by construction, so the merge is a plain union.

Each shard owns a private WAL directory (``<wal_dir>/shard-<k>``) and
replays independently; :meth:`ShardedControlPlane.dump` nests the
per-shard dumps so SIGKILL+replay stays a bitwise comparison per shard.

The hash ring uses virtual nodes so shard loads stay within a few percent
of uniform at 1000 gangs, and the ring is a pure function of
``n_shards`` — no rebalancing state to persist.
"""

import bisect
import hashlib
import os
import threading
from typing import Dict, List, Optional

from bagua_tpu.fleet.control_plane import FleetControlPlane, plan_cache_key

__all__ = ["HashRing", "ShardedControlPlane"]


class HashRing:
    """Consistent-hash ring over ``n_shards`` with ``vnodes`` virtual
    points per shard (sha256-based, stable across processes and runs)."""

    def __init__(self, n_shards: int, vnodes: int = 64):
        self.n_shards = max(1, int(n_shards))
        self.vnodes = max(1, int(vnodes))
        points = []
        for shard in range(self.n_shards):
            for v in range(self.vnodes):
                points.append((self._hash(f"shard{shard}:vn{v}"), shard))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._shards = [s for _, s in points]

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.sha256(key.encode("utf-8")).digest()[:8], "big"
        )

    def shard_for(self, key: str) -> int:
        if self.n_shards == 1:
            return 0
        i = bisect.bisect(self._hashes, self._hash(key)) % len(self._hashes)
        return self._shards[i]


class ShardedControlPlane:
    """N independent control-plane shards behind the one-plane API.

    The facade exposes the exact surface :class:`FleetHandler` and the
    :class:`~bagua_tpu.fleet.remediation.RemediationEngine` speak, so the
    HTTP layer and the remediation sweep run unmodified against 1 shard
    or 64.  Per-key ops route through the ring; fleet-wide reads fan out
    and merge.
    """

    def __init__(
        self,
        n_shards: int = 4,
        wal_dir: Optional[str] = None,
        vnodes: int = 64,
        **plane_kwargs,
    ):
        self.ring = HashRing(n_shards, vnodes=vnodes)
        self.n_shards = self.ring.n_shards
        self._lock = threading.Lock()
        self.shards: List[FleetControlPlane] = []
        for k in range(self.n_shards):
            shard_wal = os.path.join(wal_dir, f"shard-{k}") if wal_dir else None
            self.shards.append(FleetControlPlane(wal_dir=shard_wal, **plane_kwargs))

    # -- routing ----------------------------------------------------------------

    def shard_for_gang(self, gang_id: str) -> FleetControlPlane:
        return self.shards[self.ring.shard_for(f"gang:{gang_id}")]

    def shard_for_plan_key(self, key: str) -> FleetControlPlane:
        return self.shards[self.ring.shard_for(f"plan:{key}")]

    # -- gang namespaces, leases, admission -------------------------------------

    def gang(self, gang_id: str):
        return self.shard_for_gang(gang_id).gang(gang_id)

    def admit(self, gang_id: str) -> "tuple[bool, float]":
        return self.shard_for_gang(gang_id).admit(gang_id)

    def sweep_leases(self, min_interval_s: float = 1.0) -> List[str]:
        reaped: List[str] = []
        for shard in self.shards:
            reaped.extend(shard.sweep_leases(min_interval_s))
        return reaped

    def gang_ids(self) -> List[str]:
        ids: List[str] = []
        for shard in self.shards:
            ids.extend(shard.gang_ids())
        return sorted(ids)

    @property
    def gangs_gcd(self) -> int:
        return sum(s.gangs_gcd for s in self.shards)

    @property
    def backpressure_denials(self) -> int:
        return sum(s.backpressure_denials for s in self.shards)

    @property
    def canary_n(self) -> int:
        return self.shards[0].canary_n

    @property
    def plan_hits(self) -> int:
        return sum(s.plan_hits for s in self.shards)

    @property
    def plan_misses(self) -> int:
        return sum(s.plan_misses for s in self.shards)

    # -- cross-gang plan cache ---------------------------------------------------

    def plan_put(self, fingerprint, topology, algorithm, wire_precision,
                 plan, meta=None) -> str:
        key = plan_cache_key(fingerprint, topology, algorithm, wire_precision)
        return self.shard_for_plan_key(key).plan_put(
            fingerprint, topology, algorithm, wire_precision, plan, meta
        )

    def plan_get(self, fingerprint, topology, algorithm, wire_precision,
                 gang: Optional[str] = None) -> Optional[dict]:
        key = plan_cache_key(fingerprint, topology, algorithm, wire_precision)
        return self.shard_for_plan_key(key).plan_get(
            fingerprint, topology, algorithm, wire_precision, gang=gang
        )

    def plan_count(self) -> int:
        return sum(s.plan_count() for s in self.shards)

    # -- remediation tier --------------------------------------------------------

    def plan_statuses(self) -> Dict[str, dict]:
        merged: Dict[str, dict] = {}
        for shard in self.shards:
            merged.update(shard.plan_statuses())
        return merged

    def mark_plan_quarantined(self, key: str, cites) -> bool:
        return self.shard_for_plan_key(key).mark_plan_quarantined(key, cites)

    def record_canary_clean(self, key: str, gang: str) -> Optional[str]:
        return self.shard_for_plan_key(key).record_canary_clean(key, gang)

    def issue_directive(self, gang_id: str, action: str, reason: str = "",
                        detail: Optional[dict] = None) -> dict:
        return self.shard_for_gang(gang_id).issue_directive(
            gang_id, action, reason=reason, detail=detail
        )

    def directive(self, gang_id: str) -> Optional[dict]:
        return self.shard_for_gang(gang_id).directive(gang_id)

    def ack_directive(self, gang_id: str, directive_id: int) -> bool:
        return self.shard_for_gang(gang_id).ack_directive(gang_id, directive_id)

    def pending_directives(self, gang_id: str) -> List[dict]:
        return self.shard_for_gang(gang_id).pending_directives(gang_id)

    def remediation_summary(self) -> dict:
        merged = {"plans": {}, "directives": {}, "actions": {}}
        for shard in self.shards:
            summary = shard.remediation_summary()
            merged["plans"].update(summary["plans"])
            merged["directives"].update(summary["directives"])
            for action, n in summary["actions"].items():
                merged["actions"][action] = merged["actions"].get(action, 0) + n
        merged["canary_n"] = self.canary_n
        return merged

    def flight_digests(self, gang_id: str) -> List[dict]:
        return self.shard_for_gang(gang_id).flight_digests(gang_id)

    def remediate(self, **knobs) -> dict:
        """One RemediationEngine sweep over the *whole* sharded fleet: the
        engine reads the merged views and its writes route back through
        the ring (quarantine to the plan's shard, directives to each
        gang's shard)."""
        from bagua_tpu.fleet.remediation import RemediationEngine

        return RemediationEngine(self, **knobs).sweep()

    def shard_info(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "gangs_per_shard": [len(s.gang_ids()) for s in self.shards],
            "wal_replay_ms": [s.wal_replay_ms for s in self.shards],
        }

    # -- fleet-wide reads (fan out + merge) --------------------------------------

    def scheduler_view(self) -> dict:
        view = {"gangs": {}, "n_gangs": 0}
        for shard in self.shards:
            sv = shard.scheduler_view()
            view["gangs"].update(sv["gangs"])
            view["n_gangs"] += sv["n_gangs"]
        view["gangs"] = dict(sorted(view["gangs"].items()))
        return view

    def incidents(self, gang_id: Optional[str] = None) -> dict:
        if gang_id is not None:
            return self.shard_for_gang(gang_id).incidents(gang_id)
        gangs: Dict[str, list] = {}
        for shard in self.shards:
            gangs.update(shard.incidents()["gangs"])
        gangs = dict(sorted(gangs.items()))
        return {"gangs": gangs,
                "n_incidents": sum(len(v) for v in gangs.values())}

    def decisions(self, gang_id: Optional[str] = None) -> dict:
        if gang_id is not None:
            return self.shard_for_gang(gang_id).decisions(gang_id)
        gangs: Dict[str, list] = {}
        for shard in self.shards:
            gangs.update(shard.decisions()["gangs"])
        gangs = dict(sorted(gangs.items()))
        return {"gangs": gangs,
                "n_decisions": sum(len(v) for v in gangs.values())}

    def timeline(self, gang_id: str) -> dict:
        return self.shard_for_gang(gang_id).timeline(gang_id)

    # -- tracing (routed) --------------------------------------------------------

    def record_server_span(self, gang_id: str, route: str, status: int,
                           dur_ms: float, traceparent=None,
                           retry_after_s=None) -> dict:
        return self.shard_for_gang(gang_id).record_server_span(
            gang_id, route, status, dur_ms,
            traceparent=traceparent, retry_after_s=retry_after_s,
        )

    def ingest_spans(self, gang_id: str, spans, events=None) -> dict:
        return self.shard_for_gang(gang_id).ingest_spans(gang_id, spans, events)

    def ingest_incidents(self, gang_id: str, incidents) -> dict:
        return self.shard_for_gang(gang_id).ingest_incidents(gang_id, incidents)

    def ingest_decisions(self, gang_id: str, decisions) -> dict:
        return self.shard_for_gang(gang_id).ingest_decisions(gang_id, decisions)

    # -- metrics -----------------------------------------------------------------

    def metrics_text(self) -> str:
        """Merged ``/fleet/metrics`` exposition.  Per-shard registries
        cannot be concatenated (duplicate family names), so the aggregate
        families are composed by hand, plus the shard-labeled gauges only
        the sharded facade can know."""
        with self._lock:
            n_gangs = sum(len(s.gang_ids()) for s in self.shards)
            n_plans = self.plan_count()
            hits, misses = self.plan_hits, self.plan_misses
            denials = self.backpressure_denials
            actions = self.remediation_summary()["actions"]
            replay_ms = [s.wal_replay_ms for s in self.shards]
            has_wal = any(s.wal is not None for s in self.shards)
        lines = [
            "# HELP bagua_fleet_gangs live gang namespaces (all shards)",
            "# TYPE bagua_fleet_gangs gauge",
            f"bagua_fleet_gangs {n_gangs}",
            "# HELP bagua_fleet_plans_cached entries in the cross-gang plan cache (all shards)",
            "# TYPE bagua_fleet_plans_cached gauge",
            f"bagua_fleet_plans_cached {n_plans}",
            "# HELP bagua_fleet_plan_cache_hits_total plan-cache lookup hits (all shards)",
            "# TYPE bagua_fleet_plan_cache_hits_total counter",
            f"bagua_fleet_plan_cache_hits_total {hits}",
            "# HELP bagua_fleet_plan_cache_misses_total plan-cache lookup misses (all shards)",
            "# TYPE bagua_fleet_plan_cache_misses_total counter",
            f"bagua_fleet_plan_cache_misses_total {misses}",
            "# HELP bagua_fleet_backpressure_denials_total requests denied 429 (all shards)",
            "# TYPE bagua_fleet_backpressure_denials_total counter",
            f"bagua_fleet_backpressure_denials_total {denials}",
            "# HELP bagua_fleet_shard_count control-plane shards serving this fleet",
            "# TYPE bagua_fleet_shard_count gauge",
            f"bagua_fleet_shard_count {self.n_shards}",
        ]
        if has_wal:
            lines += [
                "# HELP bagua_wal_replay_ms wall time of the last WAL replay per shard",
                "# TYPE bagua_wal_replay_ms gauge",
            ]
            for k, ms in enumerate(replay_ms):
                lines.append(f'bagua_wal_replay_ms{{shard="{k}"}} {ms}')
        if actions:
            lines += [
                "# HELP bagua_remediations_total remediation actions journaled, by action",
                "# TYPE bagua_remediations_total counter",
            ]
            for action, n in sorted(actions.items()):
                lines.append(f'bagua_remediations_total{{action="{action}"}} {n}')
        return "\n".join(lines) + "\n"

    # -- durable-state witness ---------------------------------------------------

    def dump(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "shards": [s.dump() for s in self.shards],
        }

    def maybe_compact(self) -> bool:
        return any([s.maybe_compact() for s in self.shards])

    def close(self) -> None:
        for shard in self.shards:
            shard.close()
